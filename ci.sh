#!/usr/bin/env bash
# CI driver (reference paddle/scripts/paddle_build.sh role, reduced to what
# a pure-Python+JAX framework needs): unit tests on the 8-virtual-device
# CPU mesh, the benchmark smoke (CPU-sized when no TPU), the driver entry
# compile checks, and the op-surface report.
set -euo pipefail
cd "$(dirname "$0")"

echo "== pytest (8 virtual CPU devices via tests/conftest.py) =="
python -m pytest tests/ -q

echo "== bench smoke =="
python bench.py

echo "== driver entry points =="
python __graft_entry__.py

echo "== op surface =="
python tools/check_op_surface.py || true
