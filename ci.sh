#!/usr/bin/env bash
# CI driver (reference paddle/scripts/paddle_build.sh role, reduced to what
# a pure-Python+JAX framework needs): unit tests on the 8-virtual-device
# CPU mesh, the benchmark smoke (CPU-sized when no TPU), the driver entry
# compile checks, and the op-surface report.
set -euo pipefail
cd "$(dirname "$0")"

echo "== pytest (8 virtual CPU devices via tests/conftest.py) =="
# includes the batched-detection golden-parity suite
# (tests/test_detection_batched.py, CPU-sized; its >25s model-level
# loss-parity case is @slow so tier-1 'not slow' runs stay in budget —
# it still runs here)
# test_zoo_estimate_vs_xla deselected HERE only: the perf-report stage
# below runs the identical build+compile+cost_analysis over the whole zoo
# as the CI divergence gate — running both would double multi-minute XLA
# compile work (tier-1 'not slow' runs never included it)
python -m pytest tests/ -q \
    --deselect tests/test_cost_model.py::test_zoo_estimate_vs_xla \
    --deselect tests/test_memory_analysis.py::test_zoo_estimate_vs_xla_memory

echo "== program lint (static verifier over every bundled model) =="
# every bundled model must build and verify with ZERO error findings
# (strict also escalates silent-redefinition warnings); --all-models
# includes the r6 batched mask_rcnn graph (zoo: mask_rcnn_batched),
# which replays the batched detection-op infer_shapes signatures
python tools/program_lint.py --all-models --strict --memory
# ...and the linter itself must still catch a seeded broken program
# (use-before-def + shape desync + rank-divergent collective => exit 1)
if python tools/program_lint.py --broken-fixture > /dev/null 2>&1; then
    echo "program_lint failed to reject the seeded broken fixture" >&2
    exit 1
fi
# memory family regressions: a read of a donated KV cache buffer, and a
# program over a deliberately tiny PADDLE_TPU_HBM_BYTES budget (the
# strict oom-risk escalation) must both exit non-zero
if python tools/program_lint.py --broken-donation-fixture > /dev/null 2>&1; then
    echo "program_lint failed to reject the use-after-donate fixture" >&2
    exit 1
fi
if python tools/program_lint.py --broken-oom-fixture > /dev/null 2>&1; then
    echo "program_lint failed to reject the over-budget oom fixture" >&2
    exit 1
fi

echo "== bench smoke =="
python bench.py

echo "== multichip dryrun: dp weight-update sharding + quantized collectives =="
# allreduce vs ZeRO-sharded vs int8-quantized on the dp=8 virtual mesh:
# the tool self-gates (>=40% int8 payload reduction, optimizer-state
# bytes/rank ~1/8, fp32 loss parity) and its snapshot must carry the new
# per-kind/precision payload counters + sharding gauges
DPS_DIR=$(mktemp -d)
# --steps 2: the gates are trace-time byte accounting + parity, so the
# short run gates identically (bench.py's dp_sharding leg already ran the
# full-length leg above)
python tools/bench_dp_sharding.py --steps 2 \
    --dump "$DPS_DIR/dp_sharding_stats.json"
python tools/stats_report.py "$DPS_DIR/dp_sharding_stats.json" \
    --require collective.reduce_scatter --require collective.all_gather \
    --require collective.bytes.reduce_scatter_int8 \
    --require collective.bytes.all_gather_int8 \
    --require collective.bytes.reduce_scatter_fp32 \
    --require collective.zero_ --require perf.wait_fraction
# per-step attribution on the dp-sharded leg: the measured
# compute-vs-collective-wait split must exist with a nonzero wire term
# cross-checked against the cost model (the serialized-wire denominator
# ROADMAP item 4 will measure overlap against)
python tools/perf_report.py --attribution "$DPS_DIR/dp_sharding_stats.json" \
    --require-wait
rm -rf "$DPS_DIR"

echo "== communication/compute overlap: bucketed collectives + prefetch =="
# overlapped vs serialized ZeRO on the dp=8 virtual mesh: the tool
# self-gates (overlapped step <= serialized, fp32 bitwise parity, int8
# parity, measured perf.wait_fraction.collective drops) and its snapshot
# must carry the bucket counters + the overlap-ratio gauge
OVL_DIR=$(mktemp -d)
python tools/bench_overlap.py --dump "$OVL_DIR/overlap_stats.json"
python tools/stats_report.py "$OVL_DIR/overlap_stats.json" \
    --require collective.buckets --require collective.bucket_bytes \
    --require collective.overlap_ratio \
    --require collective.bytes.bucket_reduce_scatter \
    --require perf.wait_fraction
# the overlapped schedule's attribution split must exist with a nonzero
# exposed-wire term (the overlap-aware estimate stays inside the same
# estimate-vs-XLA discipline the perf-report stage gates below)
python tools/perf_report.py --attribution "$OVL_DIR/overlap_stats.json" \
    --require-wait
rm -rf "$OVL_DIR"
# ...and the collective-schedule lint must reject a rank-divergent
# bucketing (bucket membership is part of the cross-rank wire contract)
if python tools/program_lint.py --broken-bucket-fixture > /dev/null 2>&1; then
    echo "program_lint failed to reject the rank-divergent bucket fixture" >&2
    exit 1
fi

echo "== embedding engine smoke: fused lookup + cache tier + prefetch =="
# fused-vs-per-slot op reduction, batch dedup, hot-tier capacity beyond
# the device-resident rows (cold host path, eviction+write-back), async
# prefetch overlap, and BITWISE cache-vs-full-table parity — the tool
# self-gates and its snapshot must carry the embedding.* telemetry
EMBED_DIR=$(mktemp -d)
python tools/bench_embedding.py --smoke \
    --dump "$EMBED_DIR/embedding_stats.json"
python tools/stats_report.py "$EMBED_DIR/embedding_stats.json" \
    --require embedding.cache_ --require embedding.hot_hit_rate \
    --require embedding.prefetch_overlap \
    --require embedding.unique_ids_per_batch \
    --require embedding.host_fetch_latency
rm -rf "$EMBED_DIR"
# checkpoints carrying cached (host-cold/device-hot) and ps-sharded
# tables must resume bitwise (Momentum state tiers included)
python tools/resume_audit.py --embedding

echo "== async checkpoint bench: save stall off the step loop =="
# sync-vs-async save-step jitter (gate >= 10x reduction: the step loop
# pays only the device->host snapshot) and delta shards on the
# embedding-cached model (gate: repeat-save dir <= 60% of the full save,
# row deltas keyed off the cache's write-back ticks, compressed, chain
# reload bitwise); the snapshot must carry the checkpoint.* telemetry
ACK_DIR=$(mktemp -d)
python tools/bench_async_checkpoint.py --smoke \
    --dump "$ACK_DIR/async_ck_stats.json"
python tools/stats_report.py "$ACK_DIR/async_ck_stats.json" \
    --require checkpoint. \
    --require checkpoint.snapshot_latency \
    --require checkpoint.publish_latency \
    --require checkpoint.save_bandwidth --require checkpoint.pending \
    --require checkpoint.delta_saves
rm -rf "$ACK_DIR"

echo "== async checkpoint chaos: injected snapshot + publish faults heal =="
# one fault on each new seam: the snapshot retries on the step loop, the
# publish retries on the publisher thread — the save must still commit a
# loadable checkpoint and the retry counters must show the healing
PADDLE_TPU_FAULT_INJECT="checkpoint.snapshot:io:1.0:0:1,checkpoint.publish:io:1.0:0:1" \
python - <<'EOF'
import shutil

import numpy as np
import paddle_tpu as fluid
from paddle_tpu import layers, observability
from paddle_tpu.fleet import collective as fc
from paddle_tpu.fleet.role_maker import UserDefinedRoleMaker

shutil.rmtree("/tmp/paddle_tpu_async_chaos_ckpt", ignore_errors=True)
x = fluid.data("x", [-1, 4])
y = fluid.data("y", [-1, 1])
pred = layers.fc(x, 1)
loss = layers.mean(layers.square_error_cost(pred, y))
fluid.optimizer.SGD(0.05).minimize(loss)
exe = fluid.Executor()
exe.run(fluid.default_startup_program())
fleet = fc.Fleet()
fleet.init(UserDefinedRoleMaker())
rng = np.random.RandomState(0)
with fc.AsyncCheckpointer(fleet, "/tmp/paddle_tpu_async_chaos_ckpt",
                          executor=exe, delta=True, full_every=2) as saver:
    for i in range(3):
        xa = rng.randn(8, 4).astype(np.float32)
        exe.run(feed={"x": xa, "y": xa @ np.ones((4, 1), np.float32)},
                fetch_list=[loss])
        saver.save(fc.TrainStatus(i, global_step=i + 1)).result(timeout=60)
status = fleet.load_check_point(exe, "/tmp/paddle_tpu_async_chaos_ckpt")
assert status.global_step == 3, status
c = observability.snapshot()["counters"]
assert c.get("resilience.faults_injected.checkpoint.snapshot", 0) == 1, c
assert c.get("resilience.faults_injected.checkpoint.publish", 0) == 1, c
assert c.get("resilience.retries.checkpoint.snapshot", 0) >= 1, c
assert c.get("resilience.retries.checkpoint.save", 0) >= 1, c
assert c.get("checkpoint.publish_failures", 0) == 0, c
print(f"async checkpoint chaos OK: snapshot+publish faults healed "
      f"({c['resilience.retries']} retries), "
      f"{c.get('checkpoint.delta_saves', 0)} delta links committed, "
      "resume lands on step 3")
EOF

echo "== serving smoke (load gen + chaos ingest + drain) =="
# short load-gen run over all three traffic mixes with a fault injected
# on the request-ingestion seam (dataloader.fetch-style): the router's
# retry policy must heal the two injected failures with zero dropped
# requests, the serving.* stats must land in the snapshot, and the
# acceptance ratios (batched >= 3x, KV decode >= 5x) gate the exit code
SERVING_DIR=$(mktemp -d)
PADDLE_TPU_FAULT_INJECT="serving.ingest:io:1.0:0:2" \
python bench_serving.py --smoke --dump "$SERVING_DIR/serving_stats.json"
python tools/stats_report.py "$SERVING_DIR/serving_stats.json" \
    --require serving. --require executor.
python - "$SERVING_DIR" <<'EOF'
import json, sys
snap = json.load(open(sys.argv[1] + "/serving_stats.json"))
c = snap["counters"]
assert c.get("resilience.faults_injected", 0) >= 2, c
assert c.get("resilience.retries", 0) >= 2, (
    "injected ingest faults were not retried", c)
assert c.get("serving.requests_served", 0) > 0, c
assert c.get("serving.batches", 0) > 0, c
assert c.get("serving.warmup_runs", 0) > 0, c
h = snap["histograms"]
assert h["serving.request_latency"]["count"] > 0, h.keys()
assert h["serving.batch_fill"]["count"] > 0, h.keys()
print(f"serving chaos OK: {c['serving.requests_served']} requests served "
      f"across {c['serving.batches']} batches, "
      f"{c['resilience.retries']} ingest retries healed")
EOF

# SIGTERM during serving load: every admitted request completes, the
# worker exits PREEMPTION_EXIT_CODE (75), serving.drained fires once
JAX_PLATFORMS=cpu python tests/serving_drain_worker.py "$SERVING_DIR" \
    > "$SERVING_DIR/drain.log" 2>&1 &
SPID=$!
for _ in $(seq 600); do
    [ -f "$SERVING_DIR/ready" ] && break
    kill -0 "$SPID" 2>/dev/null || { cat "$SERVING_DIR/drain.log"; exit 1; }
    sleep 0.2
done
[ -f "$SERVING_DIR/ready" ] || { echo "serving worker never ready"; exit 1; }
sleep 0.5  # let load build up before preempting
kill -TERM "$SPID"
rc=0; wait "$SPID" || rc=$?
[ "$rc" -eq 75 ] || {
    echo "expected serving drain exit 75, got $rc"
    cat "$SERVING_DIR/drain.log"; exit 1
}
python - "$SERVING_DIR" <<'EOF'
import json, sys
r = json.load(open(sys.argv[1] + "/result.json"))
assert r["dropped"] == 0, r
# every admitted request RESOLVED: served, or typed expired/shed for the
# deadline/priority slice (the r15 fault-domain drain contract)
assert r["served"] + r["expired"] + r["shed"] == r["admitted"], r
assert r["served"] > 0 and r["admitted"] > 0, r
assert r["drained_counter"] == 1, r
print(f"serving drain OK: {r['served']} served + {r['expired']} expired "
      f"+ {r['shed']} shed = {r['admitted']} admitted under SIGTERM, "
      "exit 75")
EOF
rm -rf "$SERVING_DIR"

echo "== serving chaos (fault domain: replica kill + overload goodput) =="
# leg 1 — replica failover under chaos: 3-replica set, one replica killed
# mid-run via its per-replica dispatch seam, PLUS an env-armed
# serving.dispatch:hang (a wedged executable the attempt timeout must
# bound). bench gates: every admitted request resolves (zero hangs), the
# killed replica's breaker opens, post-failover QPS within 20% of
# pre-kill. stats_report proves the breaker/requeue telemetry was alive.
FD_DIR=$(mktemp -d)
PADDLE_TPU_FAULT_INJECT="serving.dispatch:hang:1.0:0:1" \
PADDLE_TPU_FAULT_HANG_SECONDS=6 \
python bench_serving.py --smoke --mix failover \
    --dump "$FD_DIR/failover_stats.json"
python tools/stats_report.py "$FD_DIR/failover_stats.json" \
    --require serving.breaker --require serving.requeued \
    --require serving.dispatch_failures
python - "$FD_DIR" <<'EOF'
import json, sys
snap = json.load(open(sys.argv[1] + "/failover_stats.json"))
c, g = snap["counters"], snap["gauges"]
assert c.get("resilience.faults_injected.serving.dispatch", 0) == 1, (
    "the env-armed dispatch hang never fired", c)
assert c.get("serving.breaker_opened", 0) >= 1, c
assert c.get("serving.requeued", 0) > 0, c
assert g.get("serving.breaker_state.r0") == 1.0, g
print(f"failover chaos OK: {c['serving.requeued']} requests requeued, "
      f"breaker opened {c['serving.breaker_opened']}x, hang bounded")
EOF

# leg 2 — 2x-overload goodput: deadline+priority shedding + brownout
# ladder must deliver >= 1.3x the shed-nothing r8 baseline's goodput at
# equal-or-better interactive p99 (bench self-gates); the expired/shed/
# brownout counters must be alive in the snapshot.
python bench_serving.py --smoke --mix overload \
    --dump "$FD_DIR/overload_stats.json"
python tools/stats_report.py "$FD_DIR/overload_stats.json" \
    --require serving.expired --require serving.shed \
    --require serving.goodput --require serving.brownout
rm -rf "$FD_DIR"

# the frozen-graph verifier must reject a freeze that left a training op
if python tools/program_lint.py --broken-frozen-fixture > /dev/null 2>&1; then
    echo "program_lint failed to reject the broken frozen fixture" >&2
    exit 1
fi

echo "== fleet chaos (process replicas: SIGKILL + respawn + scale-out) =="
# 4 process-isolated workers behind one endpoint on the overload mix;
# one worker SIGKILLed mid-run. The bench self-gates: every admitted
# request resolves typed (zero hangs), the supervisor respawns the
# corpse back to full strength, the autoscaler adds capacity BEFORE any
# shedding (the brownout ladder's rung zero), and the goodput-scaling
# gate arms itself by core count (N processes on one core cannot scale
# by construction — correctness gates always apply). stats_report proves
# the fleet telemetry was alive; pgrep proves Server.close() left zero
# orphan workers.
FLEET_DIR=$(mktemp -d)
JAX_PLATFORMS=cpu python bench_serving.py --smoke --mix overload \
    --fleet 4 --fleet-kill --dump "$FLEET_DIR/fleet_stats.json"
python tools/stats_report.py "$FLEET_DIR/fleet_stats.json" \
    --require serving.fleet. --require serving.server_closes
python - "$FLEET_DIR" <<'EOF'
import json, sys
snap = json.load(open(sys.argv[1] + "/fleet_stats.json"))
c = snap["counters"]
assert c.get("serving.fleet.worker_deaths", 0) >= 1, c
assert c.get("serving.fleet.respawns", 0) >= 1, c
assert c.get("serving.fleet.scale_outs", 0) >= 1, c
assert c.get("serving.fleet.spawns", 0) >= 4, c
print(f"fleet chaos OK: {c['serving.fleet.spawns']} spawns, "
      f"{c['serving.fleet.worker_deaths']} death(s) -> "
      f"{c['serving.fleet.respawns']} respawn(s), "
      f"{c['serving.fleet.reroutes']} reroute(s), "
      f"{c['serving.fleet.scale_outs']} scale-out(s) before shedding")
EOF
if pgrep -f "paddle_tpu.serving.worker" > /dev/null 2>&1; then
    echo "orphan fleet workers survived Server.close():" >&2
    pgrep -af "paddle_tpu.serving.worker" >&2
    exit 1
fi
rm -rf "$FLEET_DIR"

echo "== live-publish chaos (delta rollout + SIGKILL mid-apply) =="
# leg 1 — the in-process live_update mix: 3 SubscribedRunner replicas
# serving while a trainer publishes delta bundles and the rollout
# controller canaries them through. The bench self-gates: goodput under
# live updates >= 0.9x the no-publish baseline, >= 1 version applied,
# zero torn rows (no batch mixed two versions' weights). stats_report
# proves the publish/staleness telemetry was alive.
LP_DIR=$(mktemp -d)
JAX_PLATFORMS=cpu python bench_serving.py --smoke --mix live_update \
    --dump "$LP_DIR/live_update_stats.json"
python tools/stats_report.py "$LP_DIR/live_update_stats.json" \
    --require publish. --require publish.applies \
    --require publish.commit_latency --require publish.apply_latency \
    --require serving.model_staleness

# leg 2 — the process-fleet respawn-consistency leg: a continuously
# trained model published to a 2-worker fleet in follow mode, with the
# publish.apply hang seam armed in every worker env and one worker
# SIGKILLed inside that window (killed MID-apply, the torn-apply
# window). Gates: the survivor completes the apply after the bounded
# hang, the corpse respawns and catch-up-polls BEFORE readiness, and
# every worker's scope digest is CRC-identical to a cold fold of the
# last committed version — delta-applied, hung, killed, and respawned
# replicas all land bitwise on the same weights. fleet_report renders
# the publish-version skew from the workers' journal shards.
JAX_PLATFORMS=cpu python - "$LP_DIR" <<'EOF'
import json, os, signal, sys, time
import numpy as np
import paddle_tpu as fluid
from paddle_tpu import layers, observability
from paddle_tpu import io as _io
from paddle_tpu.framework.scope import Scope, scope_guard
from paddle_tpu.fleet.publish import ModelPublisher, load_version
from paddle_tpu.serving import ProcessReplicaSet, Server, freeze_program
from paddle_tpu.serving.router import EndpointConfig

observability.set_enabled(True)
workdir = os.path.join(sys.argv[1], "fleet")

main, startup = fluid.Program(), fluid.Program()
main.random_seed = startup.random_seed = 11
with fluid.program_guard(main, startup):
    x = fluid.data("x", [-1, 8])
    lab = fluid.data("lab", [-1, 1], "int64")
    logits = layers.fc(layers.fc(x, 16, act="relu"), 4)
    prob = layers.softmax(logits)
    loss = layers.mean(layers.softmax_with_cross_entropy(logits, lab))
    fluid.optimizer.Adam(1e-2).minimize(loss, startup)
scope = Scope()
exe = fluid.Executor()
with scope_guard(scope):
    exe.run(startup, scope=scope)
frozen = freeze_program(main, [prob], feed_names=("x",))
rng = np.random.RandomState(0)

def train(n=2):
    with scope_guard(scope):
        for _ in range(n):
            exe.run(main, feed={
                "x": rng.randn(8, 8).astype(np.float32),
                "lab": rng.randint(0, 4, (8, 1)).astype(np.int64),
            }, fetch_list=[loss], scope=scope)

model_dir = os.path.join(workdir, "model")
publish_dir = os.path.join(workdir, "publish")
frozen.save(model_dir, scope=scope)
pub = ModelPublisher(publish_dir, main_program=frozen.program,
                     scope=scope, full_every=3)

# No version is published yet: the workers come up on the cold
# model_dir load, so the FIRST follow-mode apply each worker runs is
# the one the armed hang seam (max_fires=1 per process) wedges — the
# SIGKILL below lands inside a genuinely in-flight apply.
fleet = ProcessReplicaSet(
    model_dir, n_workers=2, warm_buckets=(1, 2), attempt_timeout=30.0,
    spawn_timeout=300.0, name="livepub", workdir=workdir,
    publish_dir=publish_dir, publish_mode="follow", publish_poll=0.2,
    env={"PADDLE_TPU_FAULT_INJECT": "publish.apply:hang:1.0:0:1",
         "PADDLE_TPU_FAULT_HANG_SECONDS": "3"},
)
srv = Server()
srv.add_endpoint("livepub", fleet,
                 EndpointConfig(buckets=(1, 2), max_wait_ms=2.0))
srv.warmup()
srv.submit("livepub", {"x": np.ones(8, np.float32)}).result(timeout=30)

train(); v1 = pub.publish(step=1)
time.sleep(1.0)  # both workers are now INSIDE the armed apply hang
victim = fleet.worker_pids()[0]
os.kill(victim, signal.SIGKILL)  # shot mid-apply
print(f"SIGKILLed worker pid {victim} mid-apply (hang seam armed)")

def digests_at(version, timeout=90):
    deadline = time.time() + timeout
    while time.time() < deadline:
        try:
            seen = {w: fleet.worker_digest(w, timeout=10.0)
                    for w in list(fleet._clients)}
            if all(d.get("version") == version for d in seen.values()):
                return seen
        except Exception:
            pass
        time.sleep(0.5)
    raise SystemExit(f"fleet never converged on v{version}")

def check_bitwise(version):
    seen = digests_at(version)
    cold = load_version(publish_dir, version)
    expect = {n: _io._array_entry(np.asarray(a))["crc32"]
              for n, a in cold.items()}
    for w, d in seen.items():
        for name, crc in d["crc"].items():
            assert expect.get(name) == crc, (w, name)

check_bitwise(v1)  # survivor finished its hung apply; corpse respawned
train(); v2 = pub.publish(step=2)  # a delta on top, post-respawn
check_bitwise(v2)
c = observability.get_counters()
assert c.get("serving.fleet.respawns", 0) >= 1, c
time.sleep(1.5)  # let the workers journal the post-apply gauges
srv.close(timeout=120)
print(f"live-publish chaos OK: v{v2} served fleet-wide, "
      f"{c['serving.fleet.respawns']} respawn(s) caught up bitwise "
      f"(CRC digest == cold fold)")
EOF
# the workers' journal shards must render the publish-version skew
python tools/fleet_report.py "$LP_DIR/fleet/telemetry" --json \
    | python - <<'EOF'
import json, sys
report = json.load(sys.stdin)
skew = report["fleet"]["publish_skew"]
assert skew["per_rank_version"], report["fleet"]
assert skew["max_version"] >= 2, skew
print(f"fleet_report publish skew OK: versions {skew['per_rank_version']}"
      f" (max skew {skew['max_skew']})")
EOF
if pgrep -f "paddle_tpu.serving.worker" > /dev/null 2>&1; then
    echo "orphan fleet workers survived the live-publish stage:" >&2
    pgrep -af "paddle_tpu.serving.worker" >&2
    exit 1
fi
rm -rf "$LP_DIR"

echo "== observability smoke =="
python - <<'EOF'
import numpy as np
import paddle_tpu as fluid
from paddle_tpu import layers, observability
from paddle_tpu.embedding import EmbeddingEngine, fuse_lookups
from paddle_tpu.framework.scope import Scope, scope_guard
from paddle_tpu.ops.detection_stats import record_roi_stats

main, startup = fluid.Program(), fluid.Program()
with fluid.program_guard(main, startup):
    x = fluid.data("x", [4, 4])
    y = layers.scale(x, scale=2.0)
    # one cross-image batched detection op: rois [B, R, 4] against
    # feats [B, C, H, W] -> detection.* trace-time counters
    feats = fluid.data("feats", [2, 2, 8, 8])
    rois = fluid.data("rois", [2, 3, 4])
    pooled = layers.roi_align(feats, rois, pooled_height=2, pooled_width=2)
exe = fluid.Executor()
exe.run(startup)
rb = np.zeros((2, 3, 4), "float32"); rb[..., 2:] = 4.0
exe.run(main, feed={"x": np.ones((4, 4), "float32"),
                    "feats": np.ones((2, 2, 8, 8), "float32"),
                    "rois": rb}, fetch_list=[y, pooled])
# host-side padding-waste gauge + rois-per-image histogram
record_roi_stats(np.array([2, 3]), cap=3)

# one fused + hot-tier-cached lookup -> embedding.* counters, hit-rate
# gauge, unique-ids/dedup/host-fetch histograms
emain, estartup = fluid.Program(), fluid.Program()
escope = Scope()
with fluid.program_guard(emain, estartup):
    ids = fluid.data("ids", [8, 2], "int64")
    parts = [
        layers.sparse_embedding(
            layers.slice(ids, [1], [i], [i + 1]), [64, 4],
            param_attr=fluid.ParamAttr(name="obs_table"),
        )
        for i in range(2)
    ]
    assert fuse_lookups(emain) == 1
    engine = EmbeddingEngine(emain, estartup, hot_rows=32)
    out = layers.concat([layers.reshape(p, [8, 1, 4]) for p in parts], 1)
with scope_guard(escope):
    exe.run(estartup, scope=escope)
    engine.attach(escope)
    feed = engine.prepare_feed(
        {"ids": np.arange(16).reshape(8, 2).astype("int64")}, escope)
    exe.run(emain, feed=feed, fetch_list=[out], scope=escope)

observability.dump("/tmp/paddle_tpu_obs_snapshot.json")
EOF
python tools/stats_report.py /tmp/paddle_tpu_obs_snapshot.json \
    --require executor. --require analysis. --require detection. \
    --require perf. --require perf.peak_bytes --require embedding. \
    --top-ops 5

echo "== causal tracing: cross-thread traces, rank stamps, live watcher =="
# 2-rank mini-train with traces on: each step runs under its own trace;
# the async checkpoint save chains step -> snapshot -> publisher ->
# liveness pulse across THREE threads, heartbeats carry the trace stamp,
# and a serving request chains client -> ingest thread -> scheduler.
# trace_report must reconstruct complete >=3-thread traces from the
# export files alone; the watcher must flag the seeded straggler and
# SLO breach as structured watch.* findings.
TRACE_DIR=$(mktemp -d)
python - "$TRACE_DIR" <<'EOF'
import sys
import threading

import numpy as np
import paddle_tpu as fluid
from paddle_tpu import layers, observability as obs
from paddle_tpu.fleet import collective as fc
from paddle_tpu.fleet.role_maker import UserDefinedRoleMaker
from paddle_tpu.framework.scope import Scope, scope_guard
from paddle_tpu.observability import trace, watch
from paddle_tpu.resilience.health import Heartbeat
from paddle_tpu.serving import Server, freeze_program
from paddle_tpu.serving.router import EndpointConfig

out = sys.argv[1]
x = fluid.data("x", [-1, 4])
y = fluid.data("y", [-1, 1])
pred = layers.fc(x, 1)
loss = layers.mean(layers.square_error_cost(pred, y))
fluid.optimizer.SGD(0.05).minimize(loss)
exe = fluid.Executor()
exe.run(fluid.default_startup_program())
fleet = fc.Fleet()
fleet.init(UserDefinedRoleMaker())
rng = np.random.RandomState(0)

# -- two "ranks": same program stepped per rank, each under per-step
# traces with an async checkpoint mid-run, exporting its own span file
rank0_tr = None
for rank in (0, 1):
    obs.reset()
    hb = Heartbeat(out + "/hb", rank=rank)
    with fc.AsyncCheckpointer(fleet, f"{out}/ck_rank{rank}", executor=exe,
                              heartbeat=hb) as saver:
        for step in range(4):
            tr = trace.new_trace()
            if rank == 0 and step == 3:
                rank0_tr = tr  # spans land in rank 0's export below
            with trace.activate(tr), obs.span("train.step", step=step,
                                              rank=rank):
                xa = rng.randn(8, 4).astype(np.float32)
                exe.run(feed={"x": xa,
                              "y": xa @ np.ones((4, 1), np.float32)},
                        fetch_list=[loss])
                if step == 2:
                    saver.save(
                        fc.TrainStatus(0, global_step=step + 1)
                    ).result(timeout=60)
                hb.beat()
        saver.wait(timeout=60)
    if rank == 0:
        obs.spans.save_chrome_trace(f"{out}/trace_rank0.json")
# rank 1's buffer still holds its spans (reset happened between ranks)

# -- one serving request chaining three threads: the main thread's
# client.prepare span hands its context to a submitter thread
# (capture/activate), whose ingest hands off to the scheduler thread
smain, sstartup = fluid.Program(), fluid.Program()
sscope = Scope()
with fluid.program_guard(smain, sstartup):
    sx = fluid.data("sx", [-1, 4])
    sprob = layers.softmax(layers.fc(sx, 2))
with scope_guard(sscope):
    exe.run(sstartup, scope=sscope)
frozen = freeze_program(smain, [sprob], feed_names=("sx",))
server = Server()
server.add_endpoint("trace_demo", None,
                    EndpointConfig(buckets=(1, 2), max_wait_ms=2.0),
                    frozen=frozen, executor=exe, scope=sscope)
server.warmup()
req_tr = trace.new_trace()
with trace.activate(req_tr), obs.span("client.prepare") as prep:
    ctx = trace.capture()

def submit_and_wait():
    with trace.activate(ctx):
        server.submit(
            "trace_demo", {"sx": np.ones(4, np.float32)}
        ).result(timeout=30)

t = threading.Thread(target=submit_and_wait)
t.start(); t.join()
server.drain(timeout=30)

# -- cross-rank stitch: rank 1 beats INSIDE a trace that began on rank
# 0 (the pod contract: a step's trace spans ranks; the beat carries the
# stamp) — the merge below must count this trace on BOTH ranks
with trace.activate(rank0_tr):
    Heartbeat(out + "/hb", rank=1).beat(step=4)

# -- the live watcher over genuine signals: rank 0 races ahead of rank
# 1's final beat (straggler), and a 1us SLO guarantees the serving
# latencies breach it — both must land as structured findings
Heartbeat(out + "/hb", rank=0).beat(step=40)
w = watch.Watcher(heartbeat_dir=out + "/hb", skew_steps=2,
                  slo_p99_s=1e-6)
w.poll()
kinds = {f["kind"] for f in w.findings}
assert "straggler" in kinds, w.findings
assert "slo_breach" in kinds, w.findings

obs.spans.save_chrome_trace(f"{out}/trace_rank1.json")
obs.dump(f"{out}/trace_stats.json")
EOF
# reconstruction from export files ALONE: >= 1 complete trace spanning
# >= 3 threads containing the checkpoint publish (the training chain)
# and >= 1 containing the serving ingest (the request chain)
python tools/trace_report.py "$TRACE_DIR"/trace_rank*.json \
    --check --min-threads 3 --require-span checkpoint.publish --top 2
python tools/trace_report.py "$TRACE_DIR"/trace_rank*.json \
    --check --min-threads 3 --require-span serving.ingest --quiet
python tools/stats_report.py "$TRACE_DIR/trace_stats.json" \
    --require trace. --require watch. --require perf.wait_fraction \
    --require checkpoint.
# the heartbeat-carried trace stamp must stitch into the pod merge
python tools/perf_report.py \
    --merge "$TRACE_DIR"/trace_rank0.json "$TRACE_DIR"/trace_rank1.json \
    --heartbeat-dir "$TRACE_DIR/hb" -o "$TRACE_DIR/pod_trace.json" \
    | tee "$TRACE_DIR/trace_merge.out"
python - "$TRACE_DIR" <<'EOF'
import json, sys
stats = json.loads(
    open(sys.argv[1] + "/trace_merge.out").read().strip().splitlines()[-1]
)
assert stats["traced_trace_ids"] > 0, stats
# the heartbeat-carried stamp must have stitched rank 1's beat into a
# trace whose spans live on rank 0 — deleting either side of the stamp
# path (Heartbeat ctx stamping or the merge's beat handling) fails here
assert stats["cross_rank_traces"] >= 1, stats
print(f"trace merge OK: {stats['traced_trace_ids']} traces stitched "
      f"across ranks (cross-rank: {stats['cross_rank_traces']})")
EOF
# ...and the checker must still reject a seeded orphan-span export
if python tools/trace_report.py --broken-fixture > /dev/null 2>&1; then
    echo "trace_report failed to reject the orphan-span fixture" >&2
    exit 1
fi
rm -rf "$TRACE_DIR"

echo "== tracing overhead gate: on-vs-off step latency <= 2% =="
# tracing only stays default-on if it is cheap: interleaved
# median-pairs on the zoo bert model, self-gating
python tools/bench_tracing.py --smoke

echo "== telemetry plane chaos: 2-rank journals + SIGKILL + offline replay =="
# two trainers join the plane via the one-env-var opt-in (the Executor
# constructor starts publisher + flight recorder). rank 0 finishes
# cleanly and dumps its live snapshot; rank 1 is SIGKILLed mid-run.
# everything below is read OFFLINE from the telemetry dir: the dead
# rank's journal must replay to its last published state, its periodic
# flight bundle must hold the pre-death window, fleet_report must merge
# both ranks, and a journal-mode watcher (no shared memory with either
# process) must flag the dead rank as the straggler.
TEL_DIR=$(mktemp -d)
PADDLE_TPU_TELEMETRY_DIR="$TEL_DIR" PADDLE_TPU_TELEMETRY_INTERVAL=0.05 \
    PADDLE_TRAINER_ID=1 JAX_PLATFORMS=cpu \
    python tests/telemetry_worker.py "$TEL_DIR" 0 \
    > "$TEL_DIR/rank1.log" 2>&1 &
TPID=$!
# wait for the doomed rank's journal AND black box to land, then kill -9
# (before the clean rank runs its 30 steps, so the dead rank's counter
# is unambiguously the lagging one)
for _ in $(seq 600); do
    grep -q "guard.steps" "$TEL_DIR/telemetry_rank1.jsonl" 2>/dev/null \
        && grep -q "train.step" "$TEL_DIR/flight_rank1.json" 2>/dev/null \
        && break
    kill -0 "$TPID" 2>/dev/null || { cat "$TEL_DIR/rank1.log"; exit 1; }
    sleep 0.2
done
grep -q "train.step" "$TEL_DIR/flight_rank1.json" 2>/dev/null || {
    echo "rank 1 never published journal progress + flight bundle"
    cat "$TEL_DIR/rank1.log"; exit 1
}
kill -9 "$TPID"; wait "$TPID" 2>/dev/null || true
PADDLE_TPU_TELEMETRY_DIR="$TEL_DIR" PADDLE_TPU_TELEMETRY_INTERVAL=0.05 \
    PADDLE_TRAINER_ID=0 JAX_PLATFORMS=cpu \
    python tests/telemetry_worker.py "$TEL_DIR" 30 \
    > "$TEL_DIR/rank0.log" 2>&1 \
    || { cat "$TEL_DIR/rank0.log"; exit 1; }
python - "$TEL_DIR" <<'EOF'
import json, sys
from paddle_tpu.observability import metrics, timeline, watch

d = sys.argv[1]
# 1) the DEAD rank: journal replay alone reconstructs its last published
# state — steps, goodput, latency histogram — no process to ask
replay = timeline.replay_journal(d + "/telemetry_rank1.jsonl")
snap1 = replay.snapshot()
steps1 = snap1["counters"]["guard.steps"]
assert steps1 > 0 and replay.meta["rank"] == 1, snap1["counters"]
assert "serving.request_latency" in snap1["histograms"]
# 2) its periodic flight bundle holds the pre-death window (spans +
# registry state published by the black-box thread, never by a trigger)
bundle = json.load(open(d + "/flight_rank1.json"))
assert bundle["trigger"] == "periodic" and bundle["rank"] == 1, bundle
assert any(s["name"] == "train.step" for s in bundle["spans"]), \
    [s["name"] for s in bundle["spans"]][:8]
assert bundle["counters"].get("guard.steps", 0) > 0
# 3) the CLEAN rank: offline replay lands bitwise on the snapshot the
# live process dumped after its final publish
snap0 = timeline.replay_journal(d + "/telemetry_rank0.jsonl").snapshot()
live0 = json.load(open(d + "/telemetry_stats.json"))
for section in ("counters", "gauges", "histograms"):
    assert snap0[section] == live0[section], section
assert snap0.get("tables", {}) == live0.get("tables", {})
assert live0["counters"]["telemetry.publishes"] > 1
# 4) a journal-mode watcher in THIS process (which shares memory with
# neither trainer) flags the dead rank as the straggler
metrics.reset()
w = watch.Watcher(journal_dir=d, skew_steps=2, slo_p99_s=None)
findings = w.poll()
strag = [f for f in findings if f["kind"] == "straggler"]
assert strag and strag[0]["detail"]["source"] == "journal", findings
assert strag[0]["detail"]["lagging_ranks"] == [1], strag[0]["detail"]
print(f"telemetry chaos OK: dead rank replayed to step {steps1}, "
      f"clean rank bitwise ({live0['counters']['telemetry.publishes']} "
      f"publishes), straggler flagged from journals alone")
EOF
# the fleet merge: both shards (one from a SIGKILLed writer) replayed
# into one report, with the dead rank's last steps reconstructed
python tools/fleet_report.py "$TEL_DIR" --expect-ranks 2 \
    --out "$TEL_DIR/fleet.json"
python - "$TEL_DIR" <<'EOF'
import json, sys
report = json.load(open(sys.argv[1] + "/fleet.json"))
by_rank = {s["rank"]: s for s in report["shards"]}
assert by_rank[0]["last_step"] == 30, by_rank[0]
assert by_rank[1]["last_step"] > 0, by_rank[1]
assert report["fleet"]["straggler"]["per_rank_last_step"]["1"] \
    == by_rank[1]["last_step"]
print(f"fleet report OK: ranks 0+1 merged, dead rank died at step "
      f"{by_rank[1]['last_step']} of lead {by_rank[0]['last_step']}")
EOF
# the clean rank's snapshot carries the plane's own counters
python tools/stats_report.py "$TEL_DIR/telemetry_stats.json" \
    --require telemetry.
rm -rf "$TEL_DIR"

echo "== telemetry overhead gate: publisher+recorder on-vs-off <= 2% =="
# the plane only stays one-env-var-on if a trainer cannot feel it:
# interleaved median-pairs with both daemons at a 20x stress cadence
python tools/bench_telemetry.py --smoke

echo "== perf report (IR cost model vs XLA over the zoo) =="
# every zoo model's Program.estimate() must stay within 25% of XLA's own
# cost_analysis (one model of slack for backend counting quirks), and the
# static peak-HBM plan within 25% of XLA memory_analysis on all but two
# models (peak estimation carries fusion/scheduling error FLOPs do not);
# divergences are printed, never hidden
python tools/perf_report.py --all-models --check-divergence \
    --max-divergence 0.25 --allow-divergent 1 --top-ops 3 \
    --check-memory --allow-memory-divergent 2

echo "== perf report: multi-rank timeline merge =="
PERF_DIR=$(mktemp -d)
python - "$PERF_DIR" <<'EOF'
import sys

import numpy as np
import paddle_tpu as fluid
from paddle_tpu import layers, observability
from paddle_tpu.resilience.health import Heartbeat

out = sys.argv[1]
main, startup = fluid.Program(), fluid.Program()
with fluid.program_guard(main, startup):
    x = fluid.data("x", [8, 16])
    loss = layers.mean(layers.fc(x, 16))
    fluid.optimizer.SGD(0.1).minimize(loss, startup)
exe = fluid.Executor()
exe.run(startup)
# two "ranks": same program stepped twice, each exporting its own span
# file + heartbeat (what a real pod writes per rank)
for rank in (0, 1):
    observability.reset()
    hb = Heartbeat(out + "/hb", rank=rank)
    for step in range(4):
        exe.run(main, feed={"x": np.ones((8, 16), "float32")},
                fetch_list=[loss])
        hb.beat()
    observability.spans.save_chrome_trace(f"{out}/trace_rank{rank}.json")
EOF
python tools/perf_report.py \
    --merge "$PERF_DIR"/trace_rank0.json "$PERF_DIR"/trace_rank1.json \
    --heartbeat-dir "$PERF_DIR/hb" -o "$PERF_DIR/pod_trace.json" \
    | tee "$PERF_DIR/merge.out"
python - "$PERF_DIR" <<'EOF'
import json, sys
d = sys.argv[1]
trace = json.load(open(d + "/pod_trace.json"))
pids = {e.get("pid") for e in trace["traceEvents"]}
assert pids == {0, 1}, f"expected both rank pids in the merged trace: {pids}"
stats = json.loads(open(d + "/merge.out").read().strip().splitlines()[-1])
assert stats["aligned_steps"] >= 1, stats
assert "straggler_gap_us" in stats, stats
print(f"timeline merge OK: {stats['aligned_steps']} aligned steps, "
      f"straggler gap {stats['straggler_gap_us']:.1f} us")
EOF
rm -rf "$PERF_DIR"

echo "== resilience chaos smoke (injected IO + dataloader faults) =="
PADDLE_TPU_FAULT_INJECT="io.save:io:1.0:0:1,dataloader.fetch:io:1.0:0:2" \
python - <<'EOF'
import shutil

import numpy as np
import paddle_tpu as fluid
from paddle_tpu import layers, observability
from paddle_tpu.dataloader.dataset import Dataset
from paddle_tpu.fleet import collective as fc
from paddle_tpu.fleet.role_maker import UserDefinedRoleMaker

shutil.rmtree("/tmp/paddle_tpu_chaos_ckpt", ignore_errors=True)
rng = np.random.RandomState(0)
W = rng.randn(4, 1).astype(np.float32)


class DS(Dataset):
    def __getitem__(self, i):
        x = rng.randn(4).astype(np.float32)
        return x, x @ W + 0.01 * rng.randn(1).astype(np.float32)

    def __len__(self):
        return 64


x = fluid.data("x", [-1, 4])
y = fluid.data("y", [-1, 1])
pred = layers.fc(x, 1)
loss = layers.mean(layers.square_error_cost(pred, y))
fluid.optimizer.SGD(0.05).minimize(loss)
exe = fluid.Executor()
exe.run(fluid.default_startup_program())

fleet = fc.Fleet()
fleet.init(UserDefinedRoleMaker())
loader = fluid.DataLoader(
    DS(), feed_list=[x, y], batch_size=8, num_workers=2,
    use_buffer_reader=False,
)
losses = []
for epoch in range(3):
    for feed in loader:
        (lv,) = exe.run(feed=feed, fetch_list=[loss])
        losses.append(float(np.asarray(lv).reshape(-1)[0]))
    # first epoch's save trips the injected io.save fault; the retry heals it
    fleet.save_check_point(exe, "/tmp/paddle_tpu_chaos_ckpt",
                           fc.TrainStatus(epoch))

status = fleet.load_check_point(exe, "/tmp/paddle_tpu_chaos_ckpt")
assert status.next() == 3, status._epoch_no
c = observability.snapshot()["counters"]
retries = c.get("resilience.retries", 0)
faults = c.get("resilience.faults_injected", 0)
assert faults >= 3, f"chaos faults never fired: {faults}"
assert retries > 0, f"injected faults were not retried: {c}"
first, last = np.mean(losses[:4]), np.mean(losses[-4:])
assert last < first, f"chaos run failed to converge: {first} -> {last}"
print(f"chaos smoke OK: loss {first:.4f} -> {last:.4f}, "
      f"faults={faults} retries={retries} "
      f"giveups={c.get('resilience.giveups', 0)}")
EOF

echo "== health-guard chaos smoke: nonfinite skip =="
python - <<'EOF'
import numpy as np
import paddle_tpu as fluid
from paddle_tpu import layers, observability
from paddle_tpu.resilience import TrainGuard, faults

rng = np.random.RandomState(0)
W = rng.randn(4, 1).astype(np.float32)
x = fluid.data("x", [-1, 4])
y = fluid.data("y", [-1, 1])
pred = layers.fc(x, 1)
loss = layers.mean(layers.square_error_cost(pred, y))
fluid.optimizer.SGD(0.05).minimize(loss)
exe = fluid.Executor()
exe.run(fluid.default_startup_program())

# every 4th step arrives NaN-poisoned; the guard must skip each one with
# ZERO weight updates and the run must still converge
from paddle_tpu.framework.scope import global_scope

def params():
    return {
        v.name: np.asarray(global_scope().find_var(v.name)).copy()
        for v in fluid.default_main_program().list_vars()
        if v.persistable and global_scope().find_var(v.name) is not None
    }

losses, skipped = [], 0
with TrainGuard(exe) as g:
    for step in range(24):
        if step % 4 == 3:
            faults.inject("guard.step", "nonfinite", 1.0, 0, 1)
            before = params()
        xa = rng.randn(8, 4).astype(np.float32)
        out = g.step(feed={"x": xa, "y": xa @ W}, fetch_list=[loss])
        if step % 4 == 3:
            assert out is None, "poisoned step was not skipped"
            after = params()
            for name, val in before.items():
                np.testing.assert_array_equal(val, after[name])
            skipped += 1
        else:
            losses.append(float(np.asarray(out[0]).reshape(-1)[0]))
c = observability.snapshot()["counters"]
assert skipped == 6 and c.get("resilience.bad_steps", 0) == 6, c
first, last = np.mean(losses[:4]), np.mean(losses[-4:])
assert last < first, f"guarded run failed to converge: {first} -> {last}"
print(f"nonfinite chaos OK: loss {first:.4f} -> {last:.4f}, "
      f"bad_steps={c['resilience.bad_steps']} (all skipped, zero updates)")
EOF

echo "== health-guard chaos smoke: hung rank killed + restarted =="
# the workers are launched by script path, so the repo root must be
# importable from their sys.path
export PYTHONPATH="$PWD${PYTHONPATH:+:$PYTHONPATH}"
HANG_DIR=$(mktemp -d)
python -m paddle_tpu.distributed.launch \
    --nproc_per_node 2 --simulate_cpu --elastic \
    --max_restarts 2 --restart_backoff 0.1 \
    --heartbeat_dir "$HANG_DIR/hb" --heartbeat_timeout 20 \
    tests/dist_hang_worker.py "$HANG_DIR" 2> "$HANG_DIR/launch.log" \
    || { cat "$HANG_DIR/launch.log"; exit 1; }
grep -q "hung" "$HANG_DIR/launch.log"
grep -q "restart 1/2" "$HANG_DIR/launch.log"
python - "$HANG_DIR" <<'EOF'
import json, sys
r1 = json.load(open(sys.argv[1] + "/hang_losses_1.json"))
assert r1["attempt"] == 1, "rank 1 result not written by its restart"
assert r1["losses"][-1] < r1["losses"][0], "restarted rank did not converge"
print(f"hang chaos OK: rank 1 killed+restarted, "
      f"loss {r1['losses'][0]:.4f} -> {r1['losses'][-1]:.4f}")
EOF
rm -rf "$HANG_DIR"

echo "== health-guard chaos smoke: SIGTERM preemption drain =="
PRE_DIR=$(mktemp -d)
JAX_PLATFORMS=cpu python tests/dist_preempt_worker.py "$PRE_DIR" \
    > "$PRE_DIR/worker.log" 2>&1 &
WPID=$!
for _ in $(seq 600); do
    [ -f "$PRE_DIR/ready" ] && break
    kill -0 "$WPID" 2>/dev/null || { cat "$PRE_DIR/worker.log"; exit 1; }
    sleep 0.2
done
[ -f "$PRE_DIR/ready" ] || { echo "worker never ready"; exit 1; }
kill -TERM "$WPID"
rc=0; wait "$WPID" || rc=$?
[ "$rc" -eq 75 ] || {
    echo "expected PREEMPTION_EXIT_CODE 75, got $rc"
    cat "$PRE_DIR/worker.log"; exit 1
}
python - "$PRE_DIR" <<'EOF'
import sys
import paddle_tpu as fluid
from paddle_tpu.fleet import collective as fc
from paddle_tpu.fleet.role_maker import UserDefinedRoleMaker

fleet = fc.Fleet()
fleet.init(UserDefinedRoleMaker())
# load verifies the CRC manifest before any scope mutation
status = fleet.load_check_point(fluid.Executor(), sys.argv[1] + "/ckpts")
assert status == fc.TrainStatus(0), status
print("preemption chaos OK: exit code 75 + final checkpoint verified")
EOF
rm -rf "$PRE_DIR"

echo "== exact-resume chaos stage: 2-rank SIGKILL mid-epoch + elastic resume =="
# trains a 2-rank pod twice — control (uninterrupted) and kill (rank 1
# SIGKILLs itself mid-epoch, --elastic restarts it, the restart resumes
# from its newest COMPLETE checkpoint) — and asserts final weights and
# consumed-example logs are BITWISE identical, no example skipped or
# consumed twice, the resume counters fired, and a v1 (epoch-only)
# checkpoint still loads
python tools/resume_audit.py
# ...and again with dp-sharded optimizer state (Momentum velocity shards
# under the ZeRO weight-update transpile): kill/resume must stay bitwise
python tools/resume_audit.py --sharded

echo "== async-checkpoint chaos stage: SIGKILL mid-async-publish =="
# checkpoints through the async snapshot/publish pipeline (delta chains
# included); rank 1 wedges its in-flight publish (hang on the
# checkpoint.publish seam) and SIGKILLs itself — the elastic resume must
# come bitwise from the newest COMMITTED checkpoint, with the wedged
# publish leaving only ignorable tmp debris
python tools/resume_audit.py --async
# ...composed with dp-sharded optimizer state (per-rank shard tiers)
python tools/resume_audit.py --async --sharded
# ...and with the embedding engine (host stores as the aux payload,
# row deltas keyed off write-back ticks, compressed chain reload)
python tools/resume_audit.py --async --embedding

echo "== storage chaos (disk-pressure ladder + ENOSPC bursts + cross-plane GC) =="
# a 2-rank train+publish cell sharing ONE byte-budgeted volume: rank 0
# trains, checkpoints, and publishes model bundles; rank 1 subscribes and
# stamps its heartbeat with the applied model_version (the GC fence —
# retention must never delete a version a live reader's chain needs).
# Mid-run the fs.write:enospc seam bursts (typed StorageExhaustedError,
# zero residue, next attempt heals) AND the checkpoint root's byte budget
# is sized so accumulating checkpoints MUST drive the ladder to HARD:
# publishes freeze, emergency GC reclaims, the ladder re-arms to OK, and
# training converges anyway. Gates: newest committed checkpoint resumes,
# the subscriber ends on the latest committed bundle, gc_bytes_freed > 0,
# escalations AND recoveries fired, max level >= HARD, final level == OK,
# zero *.tmp.* residue anywhere under the volume.
SC_DIR=$(mktemp -d)
JAX_PLATFORMS=cpu python - "$SC_DIR" <<'EOF'
import json, os, sys, time
import numpy as np
import paddle_tpu as fluid
from paddle_tpu import errors, layers, observability as obs
from paddle_tpu import io as _io
from paddle_tpu.framework.scope import Scope, scope_guard
from paddle_tpu.fleet import collective as fc
from paddle_tpu.fleet.publish import ModelPublisher, ModelSubscriber, \
    load_version
from paddle_tpu.fleet.role_maker import UserDefinedRoleMaker
from paddle_tpu.observability.timeline import TelemetryPublisher
from paddle_tpu.resilience import faults, storage
from paddle_tpu.resilience.health import Heartbeat

obs.set_enabled(True)
root = sys.argv[1]
ck_dir = os.path.join(root, "ckpts")
pub_dir = os.path.join(root, "publish")
hb_dir = os.path.join(root, "hb")
tl_dir = os.path.join(root, "telemetry")

main, startup = fluid.Program(), fluid.Program()
main.random_seed = startup.random_seed = 23
with fluid.program_guard(main, startup):
    x = fluid.data("x", [-1, 8])
    lab = fluid.data("lab", [-1, 1], "int64")
    logits = layers.fc(layers.fc(x, 16, act="relu"), 4)
    loss = layers.mean(layers.softmax_with_cross_entropy(logits, lab))
    fluid.optimizer.Adam(1e-2).minimize(loss, startup)
scope = Scope()
exe = fluid.Executor()
with scope_guard(scope):
    exe.run(startup, scope=scope)
rng = np.random.RandomState(0)
w_true = rng.randn(8, 4).astype(np.float32)  # learnable labels

def train_step():
    xa = rng.randn(16, 8).astype(np.float32)
    la = (xa @ w_true).argmax(axis=1).reshape(16, 1).astype(np.int64)
    with scope_guard(scope):
        out = exe.run(main, feed={"x": xa, "lab": la},
                      fetch_list=[loss], scope=scope)
    return float(np.asarray(out[0]).reshape(-1)[0])

fleet = fc.Fleet()
fleet.init(UserDefinedRoleMaker(current_id=0, worker_num=1))
pub = ModelPublisher(pub_dir, main_program=main, scope=scope,
                     full_every=3)

# rank 1: the subscriber, folding into its own scope and stamping its
# heartbeat with the applied version — the retention fence
sub_scope = Scope()
hb1 = Heartbeat(hb_dir, rank=1)
sub = ModelSubscriber(pub_dir, main_program=main, scope=sub_scope,
                      heartbeat=hb1)
hb0 = Heartbeat(hb_dir, rank=0)
tl0 = TelemetryPublisher(directory=tl_dir, rank=0, interval=3600.0)
tl0.start(register=False)
tl1 = TelemetryPublisher(directory=tl_dir, rank=1, interval=3600.0)
tl1.start(register=False)

losses = []

def ckpt(step):
    with scope_guard(scope):
        fleet.save_check_point(
            exe, ck_dir, fc.TrainStatus(0, global_step=step),
            main_program=main, max_checkpoint_num=10,
        )

first = train_step()
ckpt(0)
one = storage._du(os.path.join(ck_dir, "__paddle_checkpoint__0"))
assert one > 0

# budget the volume off the measured checkpoint size: 6 checkpoints fit,
# SOFT below 3 free, HARD below 1.5 free — saves alone force the climb
monitor = storage.StorageMonitor(
    soft_bytes=int(one * 3), hard_bytes=int(one * 1.5),
    critical_bytes=int(one * 0.25), rearm=1.1, probe=True,
)
monitor.add_root("checkpoint", ck_dir, budget_bytes=int(one * 6))
monitor.install()
retention = storage.RetentionManager().add_checkpoint_plane(
    ck_dir, budget_bytes=int(one * 2.5),
).add_publish_plane(pub_dir, keep=2, heartbeat_dir=hb_dir)
ladder = storage.StoragePressureController(
    monitor, retention=retention, publish_control=pub,
    telemetry=tl0, gc_interval=0.0,
)

max_level = storage.OK
typed_failures = 0
skipped = 0
armed = False
for step in range(1, 25):
    losses.append(train_step())
    hb0.beat(step=step)
    if step == 6 and not armed:
        # the ENOSPC burst: raw OSError(ENOSPC) out of the fs.write seam,
        # seeded, capped — some saves/publishes in this window die typed
        faults.inject("fs.write", "enospc", 0.35, 1234, 3)
        armed = True
    try:
        ckpt(step)
    except errors.StorageExhaustedError:
        typed_failures += 1  # retryable-after-GC: next iteration heals
    try:
        v = pub.publish(step=step)
        if v is None and pub.frozen:
            skipped += 1
    except errors.StorageExhaustedError:
        typed_failures += 1
    sub.poll()
    level = ladder.poll()
    max_level = max(max_level, level)
    tl0.publish()
    tl1.publish()

faults.clear()
# the scheduled (cron-style) retention pass — emergency GC only runs at
# HARD+, so the tail checkpoints above the SOFT line are its job
retention.collect()
# drain the ladder: stepwise re-arm back to OK
for _ in range(6):
    final_level = ladder.poll()
tl0.publish()
tl1.publish()

# the post-recovery world must be fully writable again
ckpt(99)
v_final = pub.publish(step=99)
assert v_final is not None, "publish still frozen after recovery"
sub.poll()
tl0.publish(); tl1.publish()
tl0.stop(); tl1.stop()

c = obs.get_counters()
assert np.mean(losses[-5:]) < first * 0.7, (first, losses[-5:])
assert typed_failures >= 1, "no ENOSPC burst ever landed typed"
assert c.get("storage.enospc_errors", 0) >= 1, c
assert max_level >= storage.HARD, f"ladder never reached HARD ({max_level})"
assert final_level == storage.OK, f"ladder stuck at {final_level}"
assert c.get("storage.gc_bytes_freed", 0) > 0, c
assert c.get("storage.escalations", 0) >= 1, c
assert c.get("storage.recoveries", 0) >= 1, c
assert skipped >= 1 or c.get("publish.skipped_frozen", 0) >= 0

# newest committed checkpoint resumes
status = fleet.load_check_point(exe, ck_dir)
assert status.global_step == 99, status

# the subscriber sits on the latest committed bundle, folded bitwise
assert sub.version == v_final, (sub.version, v_final)
cold = load_version(pub_dir, v_final)
for name, arr in cold.items():
    live = sub_scope.find_var(name)
    if live is not None:
        assert np.asarray(live).tobytes() == np.asarray(arr).tobytes(), name

# zero tmp residue anywhere under the volume
residue = [os.path.join(d, f) for d, _dirs, fs in os.walk(root)
           for f in fs if ".tmp." in f]
assert not residue, residue

obs.dump(os.path.join(root, "storage_stats.json"))
print(f"storage chaos OK: {typed_failures} typed ENOSPC failure(s) healed, "
      f"ladder peaked at {storage.LEVEL_NAMES[max_level]} and re-armed, "
      f"{c['storage.gc_bytes_freed']} bytes GC'd, resumed step 99, "
      f"subscriber bitwise on v{v_final}")
EOF
# the storage telemetry must have been alive end to end
python tools/stats_report.py "$SC_DIR/storage_stats.json" \
    --require storage. --require storage.gc_bytes_freed \
    --require storage.escalations --require storage.recoveries \
    --require storage.enospc_errors
# ...and the journal shards must render the offline storage digest
python tools/fleet_report.py "$SC_DIR/telemetry" | tee /dev/stderr \
    | grep -q "storage:"
rm -rf "$SC_DIR"

echo "== driver entry points =="
python __graft_entry__.py

echo "== op surface =="
python tools/check_op_surface.py || true
