#!/usr/bin/env bash
# CI driver (reference paddle/scripts/paddle_build.sh role, reduced to what
# a pure-Python+JAX framework needs): unit tests on the 8-virtual-device
# CPU mesh, the benchmark smoke (CPU-sized when no TPU), the driver entry
# compile checks, and the op-surface report.
set -euo pipefail
cd "$(dirname "$0")"

echo "== pytest (8 virtual CPU devices via tests/conftest.py) =="
python -m pytest tests/ -q

echo "== bench smoke =="
python bench.py

echo "== observability smoke =="
python - <<'EOF'
import numpy as np
import paddle_tpu as fluid
from paddle_tpu import layers, observability

main, startup = fluid.Program(), fluid.Program()
with fluid.program_guard(main, startup):
    x = fluid.data("x", [4, 4])
    y = layers.scale(x, scale=2.0)
exe = fluid.Executor()
exe.run(startup)
exe.run(main, feed={"x": np.ones((4, 4), "float32")}, fetch_list=[y])
observability.dump("/tmp/paddle_tpu_obs_snapshot.json")
EOF
python tools/stats_report.py /tmp/paddle_tpu_obs_snapshot.json \
    --require executor.

echo "== resilience chaos smoke (injected IO + dataloader faults) =="
PADDLE_TPU_FAULT_INJECT="io.save:io:1.0:0:1,dataloader.fetch:io:1.0:0:2" \
python - <<'EOF'
import shutil

import numpy as np
import paddle_tpu as fluid
from paddle_tpu import layers, observability
from paddle_tpu.dataloader.dataset import Dataset
from paddle_tpu.fleet import collective as fc
from paddle_tpu.fleet.role_maker import UserDefinedRoleMaker

shutil.rmtree("/tmp/paddle_tpu_chaos_ckpt", ignore_errors=True)
rng = np.random.RandomState(0)
W = rng.randn(4, 1).astype(np.float32)


class DS(Dataset):
    def __getitem__(self, i):
        x = rng.randn(4).astype(np.float32)
        return x, x @ W + 0.01 * rng.randn(1).astype(np.float32)

    def __len__(self):
        return 64


x = fluid.data("x", [-1, 4])
y = fluid.data("y", [-1, 1])
pred = layers.fc(x, 1)
loss = layers.mean(layers.square_error_cost(pred, y))
fluid.optimizer.SGD(0.05).minimize(loss)
exe = fluid.Executor()
exe.run(fluid.default_startup_program())

fleet = fc.Fleet()
fleet.init(UserDefinedRoleMaker())
loader = fluid.DataLoader(
    DS(), feed_list=[x, y], batch_size=8, num_workers=2,
    use_buffer_reader=False,
)
losses = []
for epoch in range(3):
    for feed in loader:
        (lv,) = exe.run(feed=feed, fetch_list=[loss])
        losses.append(float(np.asarray(lv).reshape(-1)[0]))
    # first epoch's save trips the injected io.save fault; the retry heals it
    fleet.save_check_point(exe, "/tmp/paddle_tpu_chaos_ckpt",
                           fc.TrainStatus(epoch))

status = fleet.load_check_point(exe, "/tmp/paddle_tpu_chaos_ckpt")
assert status.next() == 3, status._epoch_no
c = observability.snapshot()["counters"]
retries = c.get("resilience.retries", 0)
faults = c.get("resilience.faults_injected", 0)
assert faults >= 3, f"chaos faults never fired: {faults}"
assert retries > 0, f"injected faults were not retried: {c}"
first, last = np.mean(losses[:4]), np.mean(losses[-4:])
assert last < first, f"chaos run failed to converge: {first} -> {last}"
print(f"chaos smoke OK: loss {first:.4f} -> {last:.4f}, "
      f"faults={faults} retries={retries} "
      f"giveups={c.get('resilience.giveups', 0)}")
EOF

echo "== driver entry points =="
python __graft_entry__.py

echo "== op surface =="
python tools/check_op_surface.py || true
