#!/usr/bin/env bash
# CI driver (reference paddle/scripts/paddle_build.sh role, reduced to what
# a pure-Python+JAX framework needs): unit tests on the 8-virtual-device
# CPU mesh, the benchmark smoke (CPU-sized when no TPU), the driver entry
# compile checks, and the op-surface report.
set -euo pipefail
cd "$(dirname "$0")"

echo "== pytest (8 virtual CPU devices via tests/conftest.py) =="
python -m pytest tests/ -q

echo "== bench smoke =="
python bench.py

echo "== observability smoke =="
python - <<'EOF'
import numpy as np
import paddle_tpu as fluid
from paddle_tpu import layers, observability

main, startup = fluid.Program(), fluid.Program()
with fluid.program_guard(main, startup):
    x = fluid.data("x", [4, 4])
    y = layers.scale(x, scale=2.0)
exe = fluid.Executor()
exe.run(startup)
exe.run(main, feed={"x": np.ones((4, 4), "float32")}, fetch_list=[y])
observability.dump("/tmp/paddle_tpu_obs_snapshot.json")
EOF
python tools/stats_report.py /tmp/paddle_tpu_obs_snapshot.json \
    --require executor.

echo "== driver entry points =="
python __graft_entry__.py

echo "== op surface =="
python tools/check_op_surface.py || true
