#!/usr/bin/env python
"""Op-surface checker (reference tools/check_op_desc.py +
print_signatures.py role): compares this framework's registered op set
against the reference operator library and reports coverage, grouped by
the reference's operator directories.

Usage:
    python tools/check_op_surface.py [--reference /root/reference] [--missing]

The reference registers ops in C++ via REGISTER_OPERATOR/REGISTER_OP_*
macros; this scans those macro invocations. Ops our design subsumes by
construction (device/memory/scaffolding ops that exist only because the
reference interprets graphs op-by-op on CUDA) are listed in SUBSUMED with
the mechanism that replaces them.
"""

from __future__ import annotations

import argparse
import os
import re
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# reference ops that have no emitter HERE by design — each entry names the
# mechanism that delivers the capability instead
SUBSUMED = {
    # memory/scheduling scaffolding: whole-block XLA compilation
    "memcpy": "XLA buffer assignment",
    "fetch": "Executor fetch_list",
    "feed": "Executor feed dict",
    "share_data": "XLA aliasing/donation",
    # reader ops: the DataLoader/Dataset host pipeline (reader.py)
    "create_py_reader": "DataLoader.from_generator",
    "read": "DataLoader iteration",
    "create_double_buffer_reader": "dataloader device double-buffering",
    # PS RPC graph ops: sharded in-HBM tables + ICI (ops/sparse.py)
    "listen_and_serv": "fleet/parameter_server.py (sync over ICI)",
    "send": "XLA collectives",
    "recv": "XLA collectives",
    "send_barrier": "jax.distributed barrier",
    "fetch_barrier": "jax.distributed barrier",
    "gen_nccl_id": "jax.distributed coordination service",
    "c_gen_nccl_id": "jax.distributed coordination service",
    "c_comm_init": "parallel/mesh.py Mesh construction",
    "c_comm_init_all": "parallel/mesh.py Mesh construction",
    "c_sync_calc_stream": "XLA stream scheduling",
    "c_sync_comm_stream": "XLA stream scheduling",
    "c_wait_comm": "XLA stream scheduling",
    "c_wait_compute": "XLA stream scheduling",
    # hand-fused CUDA kernels: XLA fuses the unfused graph (plus Pallas
    # attention in kernels/flash_attention.py); fc = mul+elementwise_add
    "fc": "XLA fusion of mul + elementwise_add",
    "coalesce_tensor": "XLA buffer assignment",
    # LoD machinery: sequences are padded [B,T,...] + lengths here
    # (layers/sequence_lod.py); tensor arrays become lax.scan state
    "lod_reset": "padded+lengths design",
    "lod_rank_table": "padded+lengths design",
    "lod_array_length": "lax.scan carries",
    "lod_tensor_to_array": "lax.scan carries",
    "array_to_lod_tensor": "lax.scan carries",
    "merge_lod_tensor": "lax.cond/select on dense tensors",
    "split_lod_tensor": "lax.cond/select on dense tensors",
    "max_sequence_len": "padded+lengths design",
    "im2sequence": "padded+lengths design",
    # persistence ops: io.py save/load execute host-side
    "load": "io.load_persistables",
    "load_combine": "io.load_persistables",
    "save": "io.save_persistables",
    "save_combine": "io.save_persistables",
    # cudnn/xpu-specific kernels with generic equivalents here
    "cudnn_lstm": "ops/rnn.py lstm (lax.scan)",
    # PS-RPC graph ops: the whole parameter-server RPC plane is replaced
    # by sharded in-HBM tables + ICI collectives (fleet/parameter_server.py)
    "broadcast": "c_broadcast (ops/collective.py)",
    "checkpoint_notify": "fleet checkpoint rotation",
    "fake_init": "sharded-table init (parallel/sparse.py)",
    "fl_listen_and_serv": "PS plane subsumed (sync over ICI)",
    "merge_ids": "PS plane subsumed",
    "split_ids": "PS plane subsumed",
    "split_byref": "PS plane subsumed",
    "prefetch": "PS plane subsumed",
    "recv_save": "PS plane subsumed",
    "ref_by_trainer_id": "PS plane subsumed",
    # DGC: real implementation — one fused op does compress + sparse
    # exchange + momentum correction (ops/optimizer_ops.py
    # dgc_momentum_step; the reference splits it into three ops)
    "dgc": "dgc_momentum_step (fused compress+exchange+update)",
    "dgc_clip_by_norm": "dgc_momentum_step + clip_by_norm emitter",
    "dgc_momentum": "dgc_momentum_step",
    # host data-queue plumbing: the native DataLoader/Dataset pipeline
    # (dataloader/, dataset/) owns queues; no in-graph queue ops exist
    "enqueue": "dataloader host queues",
    "dequeue": "dataloader host queues",
    "queue_generator": "dataloader host queues",
    # BoxPS / PS fetch-push plane: capability delivered by the sharded
    # in-HBM tables + async PS engine (ops/sparse.py,
    # fleet/parameter_server.py, distributed_lookup_table 18/18 covered)
    "pull_box_sparse": "sharded tables (ops/sparse.py)",
    "pull_box_extended_sparse": "sharded tables (ops/sparse.py)",
    "push_box_sparse": "sharded tables (ops/sparse.py)",
    "push_box_extended_sparse": "sharded tables (ops/sparse.py)",
    "pull_sparse": "sharded tables (ops/sparse.py)",
    "pull_sparse_v2": "sharded tables (ops/sparse.py)",
    "push_sparse": "sharded tables (ops/sparse.py)",
    "push_sparse_v2": "sharded tables (ops/sparse.py)",
    "push_dense": "sharded tables (ops/sparse.py)",
    # RNN-era scaffolding replaced by scan_block (ops/control_flow.py)
    "recurrent": "scan_block (StaticRNN -> lax.scan)",
    "rnn_memory_helper": "scan_block carries",
    "shrink_rnn_memory": "padded+lengths design (masked carries)",
    "reorder_lod_tensor_by_rank": "padded+lengths design",
    "merge_lod_tensor_infer": "lax.cond/select on dense tensors",
    # dygraph-to-static execution: @declarative jit capture
    "run_program": "dygraph/dygraph_to_static.py jit capture",
    # grad kernel registered as a standalone op name in the reference;
    # grads here are synthesized by the generic __vjp__ machinery
    "cross_entropy_grad2": "generic __vjp__ grad synthesis",
}

# operators/fused/: CUDA hand-fusions that exist because the reference
# interprets graphs op-by-op — here XLA fuses the unfused composition
# inside the whole-block jit, except attention and the residual tail,
# which have real Pallas kernels. Per-op rationale (VERDICT r3 item 8:
# no directory blankets):
SUBSUMED.update({
    "conv2d_fusion": "XLA conv epilogue fusion (conv+bias+act)",
    "conv2d_inception_fusion": "XLA fuses the inception branch concat",
    "fused_batch_norm_act": "XLA fuses batch_norm + activation emitters",
    "fused_batch_norm_act_grad": "generic __vjp__ of the fused pair",
    "fused_elemwise_activation": "XLA elementwise fusion",
    "fused_elemwise_activation_grad": "generic __vjp__ grad synthesis",
    "fused_embedding_eltwise_layernorm":
        "XLA fuses embedding-sum + LN; residual tail analog is "
        "kernels/fused_residual.py",
    "fused_embedding_fc_lstm":
        "lookup + ops/rnn.py lax.scan LSTM (gates fused by XLA)",
    "fused_embedding_seq_pool":
        "lookup_table + sequence_pool over padded+lengths; XLA fuses",
    "fused_embedding_seq_pool_grad": "generic __vjp__ grad synthesis",
    "fused_fc_elementwise_layernorm":
        "matmul epilogue fusion + fused_dropout_add_ln Pallas kernel",
    "fusion_group": "runtime elementwise-codegen JIT -> XLA IS the codegen",
    "fusion_gru": "ops/rnn.py lax.scan GRU step (XLA fuses the gates)",
    "fusion_lstm": "ops/rnn.py lax.scan LSTM step",
    "fusion_repeated_fc_relu": "XLA fuses fc+relu chains",
    "fusion_seqconv_eltadd_relu":
        "sequence_conv + add + relu composition (padded+lengths); XLA fuses",
    "fusion_seqexpand_concat_fc":
        "sequence_expand + concat + fc composition; XLA fuses",
    "fusion_seqpool_concat": "sequence_pool + concat composition; XLA fuses",
    "fusion_seqpool_cvm_concat":
        "sequence_pool + cvm (ops/ctr_ops.py) + concat; XLA fuses",
    "fusion_squared_mat_sub":
        "the FM (sum^2 - sum-of-squares) trick, written directly "
        "(models/deepfm.py); XLA fuses",
    "fusion_transpose_flatten_concat": "XLA layout assignment",
    "multihead_matmul": "kernels/flash_attention.py Pallas flash kernel",
    # engine-delegation ops: one op wrapping an external compiler's engine;
    # XLA is this framework's (only) compiler, with AOT serialization
    # (Executor.serialize_executable) covering the engine-cache role
    "tensorrt_engine": "XLA + AOT executable serialization (inference.py)",
    "lite_engine": "XLA + AOT executable serialization (inference.py)",
    # raw NCCL op: collectives are first-class emitters over ICI
    "nccl": "ops/collective.py ICI collectives",
})

# directory-wide subsumption where ONE design decision replaces the whole
# directory (documented in COVERAGE.md; per-op listing would restate the
# same sentence): LoD sequences are padded+lengths, readers are the host
# DataLoader pipeline, mkldnn is a CPU-backend concern XLA owns
SUBSUMED_DIRS = {
    "sequence_ops": "layers/sequence_lod.py masked-dense compositions",
    "reader": "DataLoader/Dataset host pipeline",
    "mkldnn": "XLA CPU backend",
}


def reference_ops(ref_root):
    """op name -> first file registering it, from REGISTER_* macros."""
    pat = re.compile(
        r"REGISTER_(?:OPERATOR|OP_WITHOUT_GRADIENT|OP_CPU_KERNEL_FUNCTOR)"
        r"\(\s*([a-z0-9_]+)"
    )
    ops = {}
    base = os.path.join(ref_root, "paddle", "fluid", "operators")
    for dirpath, _, files in os.walk(base):
        for fn in files:
            if not fn.endswith((".cc", ".cu")):
                continue
            path = os.path.join(dirpath, fn)
            try:
                text = open(path, errors="ignore").read()
            except OSError:
                continue
            for m in pat.finditer(text):
                ops.setdefault(m.group(1), os.path.relpath(path, base))
    return ops


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--reference", default="/root/reference")
    ap.add_argument("--missing", action="store_true",
                    help="list every uncovered op")
    args = ap.parse_args()

    import paddle_tpu  # noqa: F401  (registers all emitters)
    from paddle_tpu.framework.registry import registered_ops

    ours = set(registered_ops())
    # grad ops are synthesized generically here; count fwd names only
    ref = {
        name: where
        for name, where in reference_ops(args.reference).items()
        if not name.endswith("_grad")
    }

    by_dir = {}
    n_emitter = n_subsumed = 0
    for name, where in ref.items():
        d = os.path.dirname(where) or "."
        row = by_dir.setdefault(d, {"total": 0, "covered": 0, "missing": []})
        row["total"] += 1
        if name in ours:
            row["covered"] += 1
            n_emitter += 1
        elif name in SUBSUMED or d in SUBSUMED_DIRS:
            row["covered"] += 1
            n_subsumed += 1
        else:
            row["missing"].append(name)

    total = sum(r["total"] for r in by_dir.values())
    covered = sum(r["covered"] for r in by_dir.values())
    # headline splits real emitters from documented subsumptions (VERDICT
    # r3 item 8: no inflated 100% without the split)
    print(f"reference fwd ops: {total}; {n_emitter} with real emitters "
          f"({n_emitter / total:.0%}) + {n_subsumed} documented "
          f"subsumptions = {covered} covered; our registry: "
          f"{len(ours)} ops")
    print(f"{'directory':32s} {'covered':>9s}")
    for d in sorted(by_dir, key=lambda k: -by_dir[k]["total"]):
        row = by_dir[d]
        print(f"{d:32s} {row['covered']:4d}/{row['total']:<4d}")
        if args.missing and row["missing"]:
            for name in sorted(row["missing"]):
                print(f"    - {name}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
