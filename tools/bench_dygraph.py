#!/usr/bin/env python
"""Dygraph per-op dispatch overhead vs the static executor (VERDICT r3
item 9; reference motivation: pybind/op_function_generator.cc — the
reference generated C++ bindings because Python per-op dispatch dominated
eager mode).

Times one BERT-layer-shaped block (fc 768->3072 gelu, fc 3072->768,
layer_norm, residual) fwd+bwd three ways on the CPU backend:
  * static   — Program + Executor (whole-block jit; one dispatch/step)
  * eager    — dygraph tracer (per-op jit-cache-hit dispatch)
  * to_static— the same dygraph forward under @declarative (jit capture)
Prints one JSON line with ms/step and the eager/static ratio.
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")


def _time(fn, steps=20, warmup=3):
    for _ in range(warmup):
        fn()
    t0 = time.perf_counter()
    for _ in range(steps):
        fn()
    return (time.perf_counter() - t0) / steps * 1000.0


def main():
    import jax

    jax.config.update("jax_platforms", "cpu")

    import numpy as np

    import paddle_tpu as fluid
    from paddle_tpu import dygraph, layers
    from paddle_tpu.framework import unique_name

    b, s, h, ffn = 8, 128, 768, 3072
    rng = np.random.RandomState(0)
    x_np = rng.randn(b * s, h).astype(np.float32) * 0.1

    results = {}

    # ---- static ----
    main_prog, startup = fluid.Program(), fluid.Program()
    main_prog.random_seed = startup.random_seed = 1
    scope = fluid.framework.scope.Scope()
    with fluid.program_guard(main_prog, startup), fluid.scope_guard(scope), \
            unique_name.guard():
        x = fluid.data("x", [b * s, h])
        y = layers.fc(x, ffn, act="gelu")
        y = layers.fc(y, h)
        y = layers.layer_norm(x + y, begin_norm_axis=1)
        loss = layers.reduce_mean(layers.square(y))
        fluid.optimizer.SGD(0.1).minimize(loss)
        exe = fluid.Executor()
        exe.run(startup, scope=scope)

        def static_step():
            (lv,) = exe.run(main_prog, feed={"x": x_np},
                            fetch_list=[loss], scope=scope,
                            return_numpy=False)
            jax.block_until_ready(lv)

        results["static_ms"] = round(_time(static_step), 3)

    # ---- dygraph eager / to_static ----
    from paddle_tpu.dygraph.tracer import trace_op, trace_op_multi

    class Block(dygraph.Layer):
        def __init__(self):
            super().__init__()
            from paddle_tpu.dygraph.nn import Linear

            self.fc1 = Linear(h, ffn, act="gelu")
            self.fc2 = Linear(ffn, h)
            self.scale = self.create_parameter([h], "float32")
            self.shift = self.create_parameter([h], "float32",
                                               is_bias=True)

        def forward(self, x):
            y = self.fc2(self.fc1(x))
            y = trace_op("elementwise_add", {"X": [x], "Y": [y]}, {})
            y = trace_op_multi(
                "layer_norm",
                {"X": [y], "Scale": [self.scale], "Bias": [self.shift]},
                {"begin_norm_axis": 1, "epsilon": 1e-5},
            )["Y"][0]
            y = trace_op("square", {"X": [y]}, {})
            return trace_op("reduce_mean", {"X": [y]},
                            {"dim": None, "keep_dim": False})

    with dygraph.guard():
        blk = Block()
        opt = fluid.optimizer.SGD(0.1)
        xv = dygraph.to_variable(x_np)

        def eager_step():
            loss = blk(xv)
            loss.backward()
            opt.minimize(loss, parameter_list=blk.parameters())
            blk.clear_gradients()
            loss.numpy()

        results["eager_ms"] = round(_time(eager_step), 3)

        traced = dygraph.declarative(blk.forward)

        def to_static_step():
            loss = traced(xv)
            loss.backward()
            opt.minimize(loss, parameter_list=blk.parameters())
            blk.clear_gradients()
            loss.numpy()

        try:
            results["to_static_ms"] = round(_time(to_static_step), 3)
        except Exception as e:  # declarative capture limits are informative
            results["to_static_ms"] = f"n/a ({type(e).__name__}: {e})"

    results["eager_over_static"] = round(
        results["eager_ms"] / results["static_ms"], 2
    )
    print(json.dumps(results))


if __name__ == "__main__":
    sys.exit(main())
