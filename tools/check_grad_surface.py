#!/usr/bin/env python
"""Gradient-audit checker (reference op_test.py:170 +
white_list/op_accuracy_white_list.py role): machine-checked accounting of
which registered emitters have numeric-Jacobian gradient coverage, which
are non-differentiable, and which are exempt with a recorded reason —
the reference enforces exactly this discipline through its check_grad
whitelists; here the checker IS the whitelist and CI fails on drift.

Buckets (every registered op must land in exactly one):
  swept      — in tests/test_grad_checks.py CASES (analytic vs
               central-difference Jacobian per op)
  nondiff    — registered differentiable=False (optimizer updates,
               comparisons, samplers, metrics, target assigners, ...);
               the registration flag is the machine-checked record
  dedicated  — gradient behavior covered by a named dedicated test
               (custom-vjp kernels, control flow, collectives)
  exempt     — differentiable but not numerically swept, with a reason
               (reference white_list counterpart)

Usage: python tools/check_grad_surface.py [--list BUCKET]
Exit nonzero if any op is unexplained, double-classified, or a curated
entry goes stale (names an op that no longer exists / whose flag flipped).
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "tests"))

# gradient behavior covered by a dedicated test (not the table sweep)
DEDICATED = {
    "ring_attention": "tests/test_longcontext.py::"
    "test_ring_backward_grads_match_dense_autodiff (custom_vjp ring bwd "
    "vs dense autodiff, both backends)",
    "ulysses_attention": "tests/test_longcontext.py (sharded==dense + "
    "training-step backward under sp)",
    "moe_ffn": "tests/test_longcontext.py::test_moe_dense_vs_expert_parallel"
    " + ep dryrun train step (__graft_entry__)",
    "fused_qkv_attention": "tests/test_flash_tiled.py + "
    "tests/test_flash_attention.py (Pallas bwd vs reference grads)",
    "fused_multihead_attention": "tests/test_flash_attention.py",
    "fused_dropout_add_ln": "tests/test_fused_residual.py (kernel grads "
    "vs unfused reference)",
    "dropout": "tests/test_dygraph.py + tests/test_ops.py (fixed-seed "
    "mask determinism; grad = mask-scaled passthrough)",
    "mp_allreduce_sum": "tests/test_dist_spmd.py (TP training matches "
    "replicated; identity fwd with psum-transposed bwd)",
    "c_identity": "tests/test_dist_spmd.py (TP: identity fwd, "
    "all-reduce bwd)",
    "cond": "tests/test_control_flow.py (training through cond branches)",
    "conditional_block": "tests/test_control_flow.py",
    "bounded_while": "tests/test_control_flow.py (differentiable While, "
    "bounded scan)",
    "scan_block": "tests/test_control_flow.py + tests/test_book_seq2seq.py"
    " (rnn training convergence)",
    "recompute_segment": "tests/test_amp_recompute_io.py (recompute == "
    "plain gradients)",
    "pipeline_block": "tests/test_pipeline.py (pipeline step-for-step == "
    "unpipelined training)",
    "pipeline_uniform": "tests/test_pipeline.py + 3d dryrun leg",
    "pipeline_gate_loss": "tests/test_pipeline.py",
    "select_input": "tests/test_control_flow.py (case/switch training)",
    "select_output": "tests/test_control_flow.py",
    "write_to_array": "tests/test_control_flow.py (array ops inside "
    "While grads)",
    "read_from_array": "tests/test_control_flow.py",
    "tensor_array_to_tensor": "tests/test_control_flow.py (concat grads "
    "through arrays)",
    "lookup_sparse_table": "tests/test_sparse.py (sharded-table DeepFM "
    "training; gradient-scale correction test)",
    "fused_lookup_table": "tests/test_embedding_engine.py (fused == "
    "per-slot training parity; dedup segment-sum golden; sharded/"
    "quantized grad-exchange parity)",
}

# differentiable-flagged but not numerically swept: reason recorded, the
# reference's op_accuracy_white_list counterpart
EXEMPT = {
    # zero-gradient a.e. (piecewise-constant): analytic grad is defined
    # as 0, nothing for a numeric Jacobian to resolve
    "ceil": "piecewise-constant: gradient 0 a.e.",
    "floor": "piecewise-constant: gradient 0 a.e.",
    "round": "piecewise-constant: gradient 0 a.e.",
    "sign": "piecewise-constant: gradient 0 a.e.",
    "elementwise_floordiv": "piecewise-constant: gradient 0 a.e.",
    "elementwise_mod": "grad wrt X is identity; wrt Y piecewise-constant "
    "-floor(x/y) — kink-dense, covered by identity-part algebra",
    # no float input to differentiate
    "one_hot": "integer input only",
    "gather_tree": "integer beam reconstruction (ids/parents)",
    "shard_index": "integer sharding arithmetic",
    "histogram": "count output: gradient 0",
    "allclose": "boolean output",
    "reduce_all": "boolean reduction",
    "reduce_any": "boolean reduction",
    "size": "integer metadata output",
    # constant / fill producers (no data inputs)
    "assign_value": "no inputs (constant producer)",
    "eye": "no inputs",
    "fill_constant": "no inputs",
    "linspace": "no inputs",
    "fill_constant_batch_size_like": "shape-only dependence on input",
    "fill_zeros_like": "constant-zero output: gradient 0",
    # trivial identities (grad = passthrough by construction)
    "assign": "identity passthrough",
    "cast": "dtype-cast passthrough (float-float cast grads are "
    "identity; int casts stop gradients)",
    "print": "identity passthrough with host-side print",
    "get_tensor_from_selected_rows": "selected-rows view: identity",
    "merge_selected_rows": "selected-rows row-merge: scatter-add of "
    "identity (scatter_nd_add swept)",
    "split_selected_rows": "selected-rows row-split: gather of identity "
    "(gather swept)",
    # composites of swept cells
    "attention_lstm": "lstm_unit cell (swept) + softmax attention "
    "(softmax/matmul swept); output checked in tests/test_op_surface_r3.py",
    "sync_batch_norm": "batch_norm math (swept) with psum'd batch stats; "
    "cross-device stats covered by dist tests",
    "box_decoder_and_assign": "box_coder decode (swept) + argmax "
    "assignment (non-differentiable selection)",
    "deformable_psroi_pooling": "deformable_conv bilinear sampling "
    "(swept) + psroi_pool pooling (swept)",
    "var_conv_2d": "ragged conv: conv2d kernel math (swept) under "
    "length masks; output checked in tests/test_detection_ext.py",
    "polygon_box_transform": "coordinate relabeling of offsets "
    "(scale/add algebra); inference-only op in the reference detection "
    "heads",
    "similarity_focus": "argmax-selection mask times identity: the mask "
    "is non-differentiable, the passthrough is",
    "roi_perspective_transform": "perspective resampling: kink-dense "
    "bilinear borders; inference-only in reference pipelines "
    "(output checked in tests/test_detection_ext.py)",
    "filter_by_instag": "tag-match row selection: data-dependent gather "
    "(gather swept); selection itself non-differentiable",
    # stochastic forward: numeric differencing would re-sample
    "nce": "stochastic negative sampling: loss surface is sample-"
    "dependent; output checked in tests/test_op_surface_r3.py",
    "sample_logits": "stochastic sampled-softmax helper (same reason)",
    "pyramid_hash": "hashed n-gram embedding: hash indexing is integer; "
    "table grads = lookup_table grads (swept)",
    # quantization family: straight-through estimator or integer codecs
    "quantize": "int8 codec (inference graph only)",
    "dequantize": "int8 codec (inference graph only)",
    "requantize": "int8 codec (inference graph only)",
    "dequantize_abs_max": "int8 codec (inference graph only)",
    "dequantize_log": "log-table codec (inference graph only)",
    "fake_quantize_abs_max": "QAT fake-quant: straight-through "
    "estimator — grad defined as identity; exactness tested in "
    "test_sequence_quant_static",
    "fake_quantize_dequantize_abs_max": "QAT STE (same)",
    "fake_quantize_moving_average_abs_max": "QAT STE (same)",
    "fake_quantize_dequantize_moving_average_abs_max": "QAT STE (same)",
    "fake_quantize_range_abs_max": "QAT STE (same)",
    "fake_channel_wise_quantize_abs_max": "QAT STE (same)",
    "fake_channel_wise_quantize_dequantize_abs_max": "QAT STE (same)",
    "fake_channel_wise_dequantize_max_abs": "QAT dequant codec",
    "fake_dequantize_max_abs": "QAT dequant codec",
    "conditional_block_infer": "inference-mode alias of "
    "conditional_block (dedicated control-flow tests); never on the "
    "training path",
    "moving_average_abs_max_scale": "scale-state tracker: passthrough "
    "output, state updates are non-differentiable",
    "lookup_table_dequant": "int8-dequant embedding: table is quantized "
    "storage (no float grads); float path = lookup_table (swept)",
}


def classify():
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax

    jax.config.update("jax_platforms", "cpu")
    import importlib

    import paddle_tpu  # noqa: F401  (registers all emitters)
    from paddle_tpu.framework.registry import _REGISTRY

    tg = importlib.import_module("test_grad_checks")
    swept = {c[1] for c in tg.CASES}

    buckets = {"swept": [], "nondiff": [], "dedicated": [], "exempt": []}
    problems = []
    for name in list(swept):
        if name not in _REGISTRY:
            problems.append(f"sweep case for unregistered op {name!r}")
    for name, entry in (("DEDICATED", DEDICATED), ("EXEMPT", EXEMPT)):
        for op in entry:
            if op not in _REGISTRY:
                problems.append(f"stale {name} entry: {op!r} not registered")

    for op, d in sorted(_REGISTRY.items()):
        marks = []
        if op in swept:
            marks.append("swept")
        if not d.differentiable:
            marks.append("nondiff")
        if op in DEDICATED:
            marks.append("dedicated")
        if op in EXEMPT:
            marks.append("exempt")
        if len(marks) == 0:
            problems.append(f"UNEXPLAINED differentiable op: {op!r}")
            continue
        if len(marks) > 1:
            # every double classification is a real defect: a swept op
            # flagged differentiable=False, or a curated entry that became
            # redundant/contradictory (in the sweep AND a whitelist, or in
            # both whitelists)
            problems.append(f"{op!r} double-classified: {marks}")
        buckets[marks[0]].append(op)
    return buckets, problems


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--list", choices=["swept", "nondiff", "dedicated",
                                       "exempt"])
    args = ap.parse_args()
    buckets, problems = classify()
    total = sum(len(v) for v in buckets.values())
    print(f"registered emitters: {total}")
    for k in ("swept", "nondiff", "dedicated", "exempt"):
        print(f"  {k:10s} {len(buckets[k]):4d}")
    if args.list:
        for op in buckets[args.list]:
            reason = DEDICATED.get(op) or EXEMPT.get(op) or ""
            print(f"  {op}: {reason}")
    if problems:
        print("\nPROBLEMS:")
        for p in problems:
            print(" ", p)
        return 1
    print("ok: every emitter is swept, non-differentiable, covered by a "
          "dedicated test, or exempt with a recorded reason")
    return 0


if __name__ == "__main__":
    sys.exit(main())
