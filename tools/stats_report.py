#!/usr/bin/env python
"""Pretty-print a paddle_tpu observability snapshot.

Usage:
    python tools/stats_report.py SNAPSHOT.json [--require PREFIX ...]
    python tools/stats_report.py SNAPSHOT.json --top-ops 15

SNAPSHOT.json is the file written by `paddle_tpu.observability.dump(path)`
(counters / gauges / histograms / span_count / tables). `--require PREFIX`
(repeatable) exits nonzero unless at least one metric name starts with
PREFIX — the CI guard that instrumentation did not silently go dead.
`--top-ops N` renders the top-N op sites of the "perf.cost_table" table
the executor publishes (per-op FLOPs/bytes/roofline from
`Program.estimate`); the default dump shows the table's totals.
"""

from __future__ import annotations

import argparse
import json
import sys

_BARS = " ▁▂▃▄▅▆▇█"


def _sparkline(hist):
    """Non-cumulative bucket counts as a unicode mini-bar chart."""
    cum = [c for _, c in hist["buckets"]]
    per = [c - p for c, p in zip(cum, [0] + cum[:-1])]
    peak = max(per) if per and max(per) > 0 else 1
    return "".join(_BARS[round(c / peak * (len(_BARS) - 1))] for c in per)


def _render_cost_table(table, top_ops, lines):
    lines.append(
        f"-- perf.cost_table: {table.get('total_flops', 0) / 1e9:.3f} "
        f"GFLOP/step, {table.get('total_bytes', 0) / 1e6:.3f} MB moved, "
        f"roofline >= {table.get('total_latency', 0) * 1e3:.3f} ms --"
    )
    fams = sorted(
        (table.get("by_family") or {}).items(),
        key=lambda kv: -kv[1].get("latency", 0),
    )
    for fam, agg in fams:
        lines.append(
            f"  {fam:<14} {agg.get('flops', 0) / 1e9:>10.3f} GFLOP "
            f"{agg.get('bytes', 0) / 1e6:>10.3f} MB  ({agg.get('ops', 0)} "
            "ops)"
        )
    if top_ops:
        lines.append(f"-- top {top_ops} op sites by roofline latency --")
        for e in (table.get("ops") or [])[:top_ops]:
            lines.append(
                f"  {e.get('op_type', '?'):<28} "
                f"{e.get('flops', 0) / 1e9:>10.3f} GFLOP "
                f"{e.get('bytes', 0) / 1e6:>9.3f} MB "
                f"{e.get('latency', 0) * 1e6:>9.1f} us"
                f"  b{e.get('block_idx', 0)}#{e.get('op_index', 0)}"
            )


def render(snap, top_ops=0):
    lines = []
    counters = snap.get("counters", {})
    gauges = snap.get("gauges", {})
    hists = snap.get("histograms", {})
    tables = snap.get("tables", {})
    lines.append("==== paddle_tpu observability snapshot ====")
    if counters:
        lines.append(f"-- counters ({len(counters)}) --")
        width = max(len(n) for n in counters)
        for name in sorted(counters):
            lines.append(f"  {name:<{width}}  {counters[name]:>14}")
    if gauges:
        lines.append(f"-- gauges ({len(gauges)}) --")
        width = max(len(n) for n in gauges)
        for name in sorted(gauges):
            lines.append(f"  {name:<{width}}  {gauges[name]:>14.6g}")
    if hists:
        lines.append(f"-- histograms ({len(hists)}) --")
        for name in sorted(hists):
            h = hists[name]
            n = h["count"]
            mean = h["sum"] / n if n else 0.0
            lines.append(
                f"  {name}: count={n} sum={h['sum']:.6g} mean={mean:.6g} "
                f"min={h['min']} max={h['max']}  |{_sparkline(h)}|"
            )
    # two byte-counter generations share the table: the sharded-update
    # kinds record estimated ring WIRE bytes under
    # collective.bytes.<kind>_<precision>; the classic emitters record
    # raw per-shard PAYLOAD bytes under collective.<kind>.bytes — both
    # belong in one view or an allreduce leg reads as zero traffic
    payload = {
        n[len("collective.bytes."):] + " (wire)": c
        for n, c in counters.items() if n.startswith("collective.bytes.")
    }
    payload.update({
        n[len("collective."):-len(".bytes")] + " (payload)": c
        for n, c in counters.items()
        if n.startswith("collective.") and n.endswith(".bytes")
    })
    if payload:
        lines.append("-- collective bytes by kind --")
        width = max(len(n) for n in payload)
        for name in sorted(payload):
            lines.append(
                f"  {name:<{width}}  {payload[name] / 1e6:>10.3f} MB"
            )
    # collective overlap digest (PR 14): bucketed grad collectives + the
    # cost model's hidden-wire estimate — the numbers bench_overlap gates
    n_buckets = counters.get("collective.buckets", 0)
    overlap_ratio = gauges.get("collective.overlap_ratio")
    if n_buckets or overlap_ratio is not None:
        lines.append("-- collective overlap --")
        if n_buckets:
            members = counters.get("collective.bucket_members", 0)
            lines.append(
                f"  {n_buckets} bucket(s), "
                f"{counters.get('collective.bucket_bytes', 0) / 1e6:.3f} "
                f"MB bucketed payload"
                + (f", {members} member grads" if members else "")
            )
        if overlap_ratio is not None:
            lines.append(
                f"  est overlap ratio {overlap_ratio:.1%} of wire "
                "seconds hidden behind compute"
            )
    # checkpoint pipeline digest: the stage split (snapshot = the step
    # loop's only cost; publish = background), bandwidth, and the tiered
    # save mix — the numbers the async-checkpoint bench gates on
    snap_h, pub_h = hists.get("checkpoint.snapshot_latency"), hists.get(
        "checkpoint.publish_latency"
    )
    if snap_h or pub_h:
        lines.append("-- checkpoint pipeline --")

        def _mean_ms(h):
            return (h["sum"] / h["count"] * 1e3) if h and h["count"] else 0.0

        snap_ms, pub_ms = _mean_ms(snap_h), _mean_ms(pub_h)
        lines.append(
            f"  snapshot (on-loop) mean {snap_ms:.2f} ms | publish "
            f"(background) mean {pub_ms:.2f} ms"
            + (f" | off-loop ratio {pub_ms / snap_ms:.1f}x"
               if snap_ms > 0 else "")
        )
        bw = hists.get("checkpoint.save_bandwidth")
        if bw and bw["count"]:
            lines.append(
                f"  save bandwidth mean "
                f"{bw['sum'] / bw['count'] / 1e6:.1f} MB/s over "
                f"{bw['count']} publishes"
            )
        mix = {
            k: counters.get(f"checkpoint.{k}", 0)
            for k in ("full_saves", "delta_saves", "coalesced",
                      "cancelled", "publish_failures")
        }
        dropped = counters.get("checkpoint.delta_bytes_dropped", 0)
        lines.append(
            "  saves: " + " ".join(f"{k}={v}" for k, v in mix.items())
            + (f" delta_bytes_dropped={dropped / 1e6:.2f}MB"
               if dropped else "")
        )
    if "perf.cost_table" in tables:
        _render_cost_table(tables["perf.cost_table"], top_ops, lines)
    # per-step attribution digest: the compute/collective-wait/host-stall
    # split the executor publishes (the serialized-wire denominator)
    attr = tables.get("perf.step_attribution")
    if attr:
        lines.append("-- step attribution --")
        lines.append(
            f"  step {attr.get('step_seconds', 0) * 1e3:.3f} ms = compute "
            f"{attr.get('compute_seconds', 0) * 1e3:.3f} + collective-wait "
            f"{attr.get('collective_wait_seconds', 0) * 1e3:.3f} + "
            f"host-stall {attr.get('host_stall_seconds', 0) * 1e3:.3f} ms"
        )
        lines.append(
            f"  wait fraction {attr.get('wait_fraction_collective', 0):.1%}"
            f" (cost-model wire estimate "
            f"{attr.get('est_wait_fraction', 0):.1%} of roofline)"
        )
        if attr.get("est_wire_hidden_seconds"):
            lines.append(
                f"  overlap: {attr['est_wire_hidden_seconds'] * 1e3:.3f} "
                f"ms wire hidden "
                f"({attr.get('est_overlap_ratio', 0):.0%} of the "
                "serialized wire)"
            )
    # serving fault-domain digest (r15): goodput vs shed/expired, the
    # brownout rung, and per-replica breaker states — the overload/
    # failover picture at a glance
    goodput = counters.get("serving.goodput", 0)
    shed = counters.get("serving.shed", 0)
    expired = counters.get("serving.expired", 0)
    breakers = {
        n[len("serving.breaker_state."):]: v
        for n, v in gauges.items()
        if n.startswith("serving.breaker_state.")
    }
    if goodput or shed or expired or breakers:
        lines.append("-- serving fault domain --")
        served = counters.get("serving.requests_served", 0)
        late = counters.get("serving.late_completions", 0)
        lines.append(
            f"  goodput {goodput} in-deadline of {served} served "
            f"({late} late) | expired {expired} | shed {shed} | "
            f"rejected {counters.get('serving.rejected', 0)}"
        )
        shed_by_class = {
            n[len("serving.shed_class."):]: c
            for n, c in counters.items()
            if n.startswith("serving.shed_class.")
        }
        if shed_by_class:
            lines.append(
                "  shed by class: " + " ".join(
                    f"{k}={v}" for k, v in sorted(shed_by_class.items())
                )
            )
        level = gauges.get("serving.brownout_level")
        if level is not None:
            lines.append(
                f"  brownout level {level:.0f} "
                f"(escalations={counters.get('serving.brownout_escalations', 0)}"
                f" recoveries={counters.get('serving.brownout_recoveries', 0)})"
            )
        if breakers:
            state_name = {0.0: "closed", 0.5: "half-open", 1.0: "open"}
            lines.append(
                "  breakers: " + " ".join(
                    f"{k}={state_name.get(v, v)}"
                    for k, v in sorted(breakers.items())
                )
                + f" | requeued {counters.get('serving.requeued', 0)}"
                + f" failovers {counters.get('serving.failovers', 0)}"
            )
    # live watcher digest: structured findings, newest last
    wf = (tables.get("watch.findings") or {}).get("findings") or []
    if wf:
        lines.append(f"-- watch findings ({len(wf)} recent) --")
        for f_ in wf[-8:]:
            detail = ", ".join(
                f"{k}={v}" for k, v in sorted(f_.get("detail", {}).items())
                if not isinstance(v, dict)
            )
            lines.append(
                f"  [{f_.get('severity', '?'):<7}] {f_.get('kind', '?')}: "
                f"{detail}"
            )
    # telemetry-plane digest (r16): journal liveness + flight dumps — a
    # frozen publishes counter in a fleet of live ranks IS the finding
    publishes = counters.get("telemetry.publishes", 0)
    dumps = counters.get("telemetry.flight_dumps", 0)
    if publishes or dumps:
        lines.append("-- telemetry plane --")
        lines.append(
            f"  {publishes} journal publishes, "
            f"{gauges.get('telemetry.journal_bytes', 0) / 1e3:.1f} KB "
            f"journaled, {counters.get('telemetry.rotations', 0)} "
            "rotation(s)"
        )
        triggers = {
            n[len("telemetry.flight_dumps."):]: c
            for n, c in counters.items()
            if n.startswith("telemetry.flight_dumps.")
        }
        if dumps:
            lines.append(
                f"  {dumps} flight-recorder dump(s): " + " ".join(
                    f"{k}={v}" for k, v in sorted(triggers.items())
                )
            )
    lines.append(f"span buffer: {snap.get('span_count', 0)} spans")
    if not (counters or gauges or hists):
        lines.append("(snapshot is empty — PADDLE_TPU_MONITOR=0, or nothing "
                     "instrumented ran)")
    return "\n".join(lines)


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("snapshot", help="JSON file from observability.dump()")
    ap.add_argument(
        "--require", action="append", default=[], metavar="PREFIX",
        help="fail unless some metric name starts with PREFIX (repeatable)",
    )
    ap.add_argument(
        "--top-ops", type=int, default=0, metavar="N",
        help="show the top-N op sites of the published perf.cost_table",
    )
    args = ap.parse_args(argv)
    with open(args.snapshot) as f:
        snap = json.load(f)
    print(render(snap, top_ops=args.top_ops))
    names = (
        list(snap.get("counters", {}))
        + list(snap.get("gauges", {}))
        + list(snap.get("histograms", {}))
        + list(snap.get("tables", {}))
    )
    missing = [
        p for p in args.require if not any(n.startswith(p) for n in names)
    ]
    if missing:
        print(f"MISSING required metric prefixes: {missing}", file=sys.stderr)
        return 2
    return 0


if __name__ == "__main__":
    sys.exit(main())
