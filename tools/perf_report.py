#!/usr/bin/env python
"""Per-op cost attribution + multi-rank timeline reports.

Three report modes over the analysis/cost.py IR cost model:

1. Cost tables — top-K op sites by roofline latency for any zoo model:
       python tools/perf_report.py --model bert --top-ops 15

2. Estimate-vs-XLA cross-check — `Program.estimate()` total FLOPs against
   the compiled executable's own `cost_analysis()` (Executor.flops; lower
   + compile only, never executes a step). The CI stage:
       python tools/perf_report.py --all-models --check-divergence \\
           --max-divergence 0.25 --allow-divergent 1
   exits non-zero when more than `--allow-divergent` models diverge past
   the threshold (divergences are always REPORTED, never hidden). Meshed
   models (bert_3d) are estimate-only: their shard_map executable wants
   the whole virtual pod stepping together. `--check-memory` runs the
   same cross-check for the static peak-HBM plan (analysis/memory.py)
   against XLA `memory_analysis` (arg+out+temp-alias), with its own
   `--allow-memory-divergent` budget: peak estimation carries fusion and
   scheduling error the FLOP count does not.

3. Merged pod timeline — fuse per-rank Chrome span exports
   (`observability.save_chrome_trace`, one file per rank) and optional
   heartbeat files (resilience/health.py `{dir}/hb_rank{K}`) into ONE
   chrome://tracing-loadable JSON, with per-rank step alignment stats:
       python tools/perf_report.py --merge r0.json r1.json \\
           --heartbeat-dir /ckpt/hb -o pod_trace.json
   Prints per-step skew (spread of "executor.step" end times across
   ranks, mean/max), the straggler gap (how far the last finisher trails
   the second-to-last), and which rank finishes last most often.
"""

from __future__ import annotations

import argparse
import json
import os
import re
import sys

# runnable as `python tools/perf_report.py` from anywhere
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


# ---------------------------------------------------------------------------
# cost tables + estimate-vs-XLA
# ---------------------------------------------------------------------------


def _synthetic_feed(bm, batch_hint=4):
    """Random arrays matching the model's declared feed specs. Safe even
    for structured inputs (boxes, ids): the XLA check only lowers and
    compiles — no step ever executes on this data."""
    import numpy as np

    from paddle_tpu.core.dtypes import to_numpy_dtype

    rng = np.random.RandomState(0)
    feed = {}
    blk = bm.main.global_block
    for n in bm.feed_names:
        v = blk.var(n)
        shape = tuple(
            int(d) if d not in (-1, None) else batch_hint for d in v.shape
        )
        dt = np.dtype(to_numpy_dtype(v.dtype or "float32"))
        if np.issubdtype(dt, np.integer):
            feed[n] = rng.randint(0, 3, shape).astype(dt)
        else:
            feed[n] = rng.rand(*shape).astype(dt)
    return feed


def report_model(name, top_ops, check_divergence, max_divergence,
                 check_memory=False):
    """Print the model's report; return ``(flops_div, mem_div)`` where
    each is the measured divergence past ``max_divergence`` or None when
    the check passed / was skipped / was not requested."""
    import paddle_tpu as fluid
    from paddle_tpu.framework.scope import Scope
    from paddle_tpu.models import build_model

    bm = build_model(name)
    feed = _synthetic_feed(bm)
    est = bm.main.estimate(
        feed_shapes={k: v.shape for k, v in feed.items()}
    )
    print(f"==== {name} ====")
    print(est.format(top=top_ops))
    if not (check_divergence or check_memory):
        return None, None
    if getattr(bm.main, "_mesh", None) is not None:
        print(f"  [skip] {name}: meshed program — estimate-only "
              "(shard_map executable needs the whole pod)")
        return None, None
    exe = fluid.Executor()
    scope = Scope()
    exe.run(bm.startup, scope=scope)
    flops_div = mem_div = None
    if check_divergence:
        xla = exe.flops(
            bm.main, feed=feed, fetch_list=list(bm.fetch_names), scope=scope
        )
        if not xla:
            print(f"  [skip] {name}: XLA cost_analysis reported no "
                  "FLOP data")
        else:
            div = abs(est.total_flops - xla) / xla
            verdict = "ok" if div <= max_divergence else "DIVERGENT"
            print(
                f"  estimate {est.total_flops / 1e6:.3f}M vs XLA "
                f"{xla / 1e6:.3f}M FLOPs -> divergence {div:.1%} "
                f"[{verdict}]"
            )
            if div > max_divergence:
                flops_div = div
    if check_memory:
        ma = exe.memory_analysis(
            bm.main, feed=feed, fetch_list=list(bm.fetch_names), scope=scope
        )
        if ma is None or est.peak_bytes is None:
            print(f"  [skip] {name}: XLA memory_analysis unavailable")
        else:
            xla_peak = ma["peak_bytes"]
            div = abs(est.peak_bytes - xla_peak) / max(xla_peak, 1.0)
            verdict = "ok" if div <= max_divergence else "DIVERGENT"
            print(
                f"  peak-HBM estimate {est.peak_bytes / 2**20:.2f} MiB "
                f"vs XLA {xla_peak / 2**20:.2f} MiB (arg+out+temp-alias) "
                f"-> divergence {div:.1%} [{verdict}]"
            )
            if div > max_divergence:
                mem_div = div
    return flops_div, mem_div


# ---------------------------------------------------------------------------
# multi-rank timeline merge
# ---------------------------------------------------------------------------

_RANK_RE = re.compile(r"rank[_-]?(\d+)")


def _rank_of(path, position):
    m = _RANK_RE.search(os.path.basename(path))
    return int(m.group(1)) if m else position


def _step_spans(events):
    """Per-rank "executor.step" spans ordered by start time."""
    steps = [
        e for e in events
        if e.get("ph") == "X" and e.get("name") == "executor.step"
    ]
    return sorted(steps, key=lambda e: e["ts"])


def merge_traces(paths, heartbeat_dir=None):
    """Merge per-rank Chrome span exports into one trace dict + skew stats.

    Each input is one rank's `observability.save_chrome_trace` output
    (wall-clock ts in epoch microseconds, so ranks on a shared clock
    align). Rank K's events move to pid K; heartbeat beats (if a dir is
    given) land as instant events on the matching rank row.
    """
    merged = []
    per_rank_steps = {}
    trace_ranks = {}  # trace_id -> set of ranks that recorded it
    # two passes over the rank ids: collisions (same basename copied into
    # per-host dirs) remap to ids NO input declares, so a duplicate never
    # steals a later file's genuine rank
    declared = [_rank_of(p, i) for i, p in enumerate(paths)]
    ranks_assigned, used = [], set()
    for path, rank in zip(paths, declared):
        if rank in used:
            free = 0
            while free in used or free in declared:
                free += 1
            print(
                f"WARNING: {path} resolves to rank {rank}, already taken "
                f"— remapping to rank {free}",
                file=sys.stderr,
            )
            rank = free
        used.add(rank)
        ranks_assigned.append(rank)
    for rank, path in zip(ranks_assigned, paths):
        with open(path) as f:
            trace = json.load(f)
        events = trace.get("traceEvents", trace)
        merged.append({
            "name": "process_name", "ph": "M", "pid": rank,
            "args": {"name": f"rank {rank}"},
        })
        tid_seen = set()
        for e in events:
            if e.get("ph") == "M":
                if e.get("name") == "thread_name" \
                        and e.get("tid") not in tid_seen:
                    tid_seen.add(e.get("tid"))
                    merged.append({**e, "pid": rank})
                continue
            tr = (e.get("args") or {}).get("trace_id")
            if tr:
                trace_ranks.setdefault(tr, set()).add(rank)
            merged.append({**e, "pid": rank})
        per_rank_steps[rank] = _step_spans(
            [e for e in events if e.get("ph") == "X"]
        )
    if heartbeat_dir:
        for fn in sorted(os.listdir(heartbeat_dir)):
            if not fn.startswith("hb_rank") or ".tmp." in fn:
                continue
            # inlined resilience/health.py::read_beat (torn/missing beat
            # -> skip) so the merge path stays import-light: a login host
            # without jax must still merge copied rank artifacts
            try:
                with open(os.path.join(heartbeat_dir, fn)) as f:
                    beat = json.load(f)
            except (OSError, ValueError):
                continue
            if not isinstance(beat, dict):
                continue
            # beats carry the beating step's trace stamp (health.py):
            # the cross-RANK stitch — a trace whose spans live on one
            # rank and whose beat lands on another is one causal timeline
            if beat.get("trace_id"):
                trace_ranks.setdefault(beat["trace_id"], set()).add(
                    int(beat.get("rank", 0))
                )
            merged.append({
                "ph": "I", "s": "p", "pid": int(beat.get("rank", 0)),
                "tid": 0, "name": f"heartbeat step {beat.get('step')}",
                "ts": float(beat.get("time", 0.0)) * 1e6, "cat": "health",
                "args": dict(beat),
            })
    stats = _skew_stats(per_rank_steps)
    stats["traced_trace_ids"] = len(trace_ranks)
    stats["cross_rank_traces"] = sum(
        1 for ranks_ in trace_ranks.values() if len(ranks_) > 1
    )
    return {"traceEvents": merged}, stats


def _skew_stats(per_rank_steps):
    """Step-alignment stats across ranks: for step k, skew = spread of
    the ranks' step-END times (first vs last finisher), straggler gap =
    how far the LAST finisher trails the second-to-last (the pod-wide
    stall one slow rank alone causes — with 2 ranks the two coincide);
    the straggler is the rank that finishes last most often."""
    ranks = sorted(per_rank_steps)
    counts = {r: len(per_rank_steps[r]) for r in ranks}
    n_steps = min(counts.values()) if counts else 0
    # align the TRAILING n steps of every rank: the span ring buffer keeps
    # the most recent spans, so when counts differ it is the OLDEST steps a
    # longer rank dropped — leading-index pairing would compare unrelated
    # steps. A mismatch is still flagged: trailing alignment is a guess.
    tails = {r: per_rank_steps[r][-n_steps:] for r in ranks}
    skews, gaps, last_finisher = [], [], {}
    for k in range(n_steps):
        ends = {
            r: tails[r][k]["ts"] + tails[r][k]["dur"]
            for r in ranks
        }
        ordered = sorted(ends.values())
        skews.append(ordered[-1] - ordered[0])
        gaps.append(ordered[-1] - ordered[-2] if len(ordered) > 1 else 0.0)
        lag = max(ends, key=ends.get)
        last_finisher[lag] = last_finisher.get(lag, 0) + 1
    straggler = (
        max(last_finisher, key=last_finisher.get) if last_finisher else None
    )
    return {
        "ranks": ranks,
        "steps_per_rank": counts,
        "aligned_steps": n_steps,
        "count_mismatch": len(set(counts.values())) > 1,
        "step_skew_us": {
            "mean": sum(skews) / len(skews) if skews else 0.0,
            "max": max(skews) if skews else 0.0,
        },
        "straggler_gap_us": sum(gaps) / len(gaps) if gaps else 0.0,
        "straggler_rank": straggler,
        "straggler_last_finishes": last_finisher,
    }


def _print_merge_stats(stats):
    print(
        f"merged {len(stats['ranks'])} rank(s) "
        f"{stats['steps_per_rank']} -> {stats['aligned_steps']} aligned "
        "step(s)"
    )
    if stats.get("count_mismatch"):
        print(
            "WARNING: ranks recorded different step counts — stats pair "
            "the trailing steps of each rank and may misalign",
            file=sys.stderr,
        )
    sk = stats["step_skew_us"]
    print(
        f"step skew: mean {sk['mean']:.1f} us, max {sk['max']:.1f} us; "
        f"straggler gap {stats['straggler_gap_us']:.1f} us"
        + (
            f" (rank {stats['straggler_rank']} finishes last "
            f"{stats['straggler_last_finishes'][stats['straggler_rank']]}x)"
            if stats["straggler_rank"] is not None else ""
        )
    )


# ---------------------------------------------------------------------------
# per-step attribution: estimate vs measured compute / wait split
# ---------------------------------------------------------------------------


def report_attribution(snapshot_path, require_wait=False):
    """Render the executor's ``perf.step_attribution`` table (measured
    compute / collective-wait / host-stall split vs the cost model's
    wire-time estimate) from an observability snapshot. This is the
    serialized-wire denominator ROADMAP item 4 measures overlap against:
    ``wait_fraction_collective`` of a serialized step is the share an
    overlapped schedule can hide.

    ``require_wait=True`` additionally fails unless the leg actually
    exercised the wire (est_wire_seconds > 0) — the dp-sharded CI leg's
    guard that the split did not silently degrade to compute-only."""
    with open(snapshot_path) as f:
        snap = json.load(f)
    table = (snap.get("tables") or {}).get("perf.step_attribution")
    if not table:
        print(
            "no perf.step_attribution table in the snapshot — run at "
            "least 2 steps of one executable (the first carries the "
            "compile) with monitoring on",
            file=sys.stderr,
        )
        return 2
    ms = 1e3
    print("==== per-step attribution (steady-state window mean) ====")
    print(
        f"  measured step      {table['step_seconds'] * ms:9.3f} ms over "
        f"{table.get('window_steps', 0)} step(s)"
    )
    denom = table["step_seconds"] or 1.0
    for key, label in (
        ("compute_seconds", "compute"),
        ("collective_wait_seconds", "collective wait"),
        ("host_stall_seconds", "host stall"),
    ):
        v = table.get(key, 0.0)
        print(f"  {label:<18} {v * ms:9.3f} ms  ({v / denom:6.1%})")
    est_wire = table.get("est_wire_seconds", 0.0)
    est_comp = table.get("est_compute_seconds", 0.0)
    print(
        f"  cost-model roofline: compute {est_comp * ms:.3f} ms, wire "
        f"{est_wire * ms:.3f} ms -> est wait fraction "
        f"{table.get('est_wait_fraction', 0.0):.1%} "
        f"(measured {table.get('wait_fraction_collective', 0.0):.1%} of "
        "the step)"
    )
    if table.get("est_wire_total_seconds"):
        # overlap-aware split (PR 14): est_wire_seconds above is the
        # EXPOSED wire; the hidden share rides behind compute
        hidden = table.get("est_wire_hidden_seconds", 0.0)
        print(
            f"  overlap schedule: serialized wire "
            f"{table['est_wire_total_seconds'] * ms:.3f} ms, hidden "
            f"{hidden * ms:.3f} ms "
            f"({table.get('est_overlap_ratio', 0.0):.0%} of the wire "
            "behind the math)"
        )
    if table.get("traced_wire_bytes"):
        print(
            f"  traced collective sites move ~"
            f"{table['traced_wire_bytes'] / 1e6:.3f} MB wire/step "
            "(emitter-side cross-check)"
        )
    gauges = snap.get("gauges", {})
    waits = {k: v for k, v in gauges.items()
             if k.startswith("perf.wait_fraction.")}
    if waits:
        print("  live gauges: " + "  ".join(
            f"{k.split('.')[-1]}={v:.1%}" for k, v in sorted(waits.items())
        ))
    bad = []
    for key in ("wait_fraction_collective", "wait_fraction_host",
                "est_wait_fraction"):
        v = table.get(key)
        if v is None or not (0.0 <= v <= 1.0):
            bad.append(f"{key}={v!r}")
    # "the leg touched the wire" means the SERIALIZED wire roofline is
    # nonzero — a perfectly overlapped schedule may legitimately expose
    # zero wire (est_wire_seconds == 0 with overlap_ratio == 1), and that
    # must not read as a dead leg. Older snapshots without the overlap
    # fields fall back to the exposed term (there the two are equal).
    est_wire_total = table.get("est_wire_total_seconds", est_wire)
    if require_wait and est_wire_total <= 0:
        bad.append("est_wire_total_seconds=0 (leg never touched the wire)")
    if require_wait and est_wire > 0 \
            and table.get("collective_wait_seconds", 0) <= 0:
        # measured wait must exist whenever the estimate says wire is
        # still exposed; a fully hidden wire (est_wire == 0) makes a
        # zero measured wait the CORRECT answer, not a degraded split
        bad.append("collective_wait_seconds=0")
    if bad:
        print(f"attribution check FAILED: {bad}", file=sys.stderr)
        return 2
    print(json.dumps({"attribution": table}))
    return 0


# ---------------------------------------------------------------------------


def main(argv=None):
    ap = argparse.ArgumentParser(
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    ap.add_argument("--model", action="append", default=[],
                    help="zoo model to report on (repeatable)")
    ap.add_argument("--all-models", action="store_true",
                    help="report on every bundled model")
    ap.add_argument("--top-ops", type=int, default=10, metavar="N",
                    help="op sites to show per model (default 10)")
    ap.add_argument("--check-divergence", action="store_true",
                    help="cross-check estimate vs XLA cost_analysis")
    ap.add_argument("--max-divergence", type=float, default=0.25,
                    help="allowed |est-xla|/xla per model (default 0.25)")
    ap.add_argument("--allow-divergent", type=int, default=1,
                    help="models allowed past the threshold before the "
                         "exit status fails (default 1)")
    ap.add_argument("--check-memory", action="store_true",
                    help="cross-check the static peak-HBM estimate vs "
                         "XLA memory_analysis (arg+out+temp-alias)")
    ap.add_argument("--allow-memory-divergent", type=int, default=2,
                    help="models allowed past the memory threshold "
                         "before the exit status fails (default 2: the "
                         "planner does not model cross-op fusion or "
                         "XLA's scheduling freedom)")
    ap.add_argument("--merge", nargs="+", metavar="TRACE.json",
                    help="merge per-rank chrome span exports")
    ap.add_argument("--attribution", metavar="SNAPSHOT.json",
                    help="render the perf.step_attribution table "
                         "(measured compute/wait/host split vs the cost "
                         "model's wire estimate) from a snapshot")
    ap.add_argument("--require-wait", action="store_true",
                    help="with --attribution: fail unless the leg "
                         "exercised the wire (est_wire_seconds > 0)")
    ap.add_argument("--heartbeat-dir", metavar="DIR",
                    help="fold hb_rank* beats into the merged trace")
    ap.add_argument("-o", "--out", metavar="PATH",
                    help="write the merged trace JSON here")
    args = ap.parse_args(argv)

    if args.attribution:
        return report_attribution(
            args.attribution, require_wait=args.require_wait
        )
    if args.merge:
        trace, stats = merge_traces(args.merge, args.heartbeat_dir)
        _print_merge_stats(stats)
        if args.out:
            with open(args.out, "w") as f:
                json.dump(trace, f)
            print(f"merged trace -> {args.out}")
        print(json.dumps(stats))
        return 0

    # model reports need jax; the merge path above stays import-light so
    # it can run on a login host against copied rank artifacts
    _flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in _flags:
        os.environ["XLA_FLAGS"] = (
            _flags + " --xla_force_host_platform_device_count=8"
        ).strip()
    import jax

    jax.config.update("jax_platforms", "cpu")

    from paddle_tpu.models import MODEL_BUILDERS

    names = list(MODEL_BUILDERS) if args.all_models else args.model
    if not names:
        ap.error("pass --model NAME, --all-models, or --merge TRACES...")
    unknown = [n for n in names if n not in MODEL_BUILDERS]
    if unknown:
        ap.error(f"unknown models {unknown}; have {sorted(MODEL_BUILDERS)}")
    divergent, mem_divergent = [], []
    for n in names:
        flops_div, mem_div = report_model(
            n, args.top_ops, args.check_divergence, args.max_divergence,
            check_memory=args.check_memory,
        )
        if flops_div is not None:
            divergent.append((n, flops_div))
        if mem_div is not None:
            mem_divergent.append((n, mem_div))
    status = 0
    if args.check_divergence:
        print(
            f"divergence check: {len(names) - len(divergent)}/{len(names)} "
            f"within {args.max_divergence:.0%}"
            + (f"; divergent: {divergent}" if divergent else "")
        )
        if len(divergent) > args.allow_divergent:
            status = 2
    if args.check_memory:
        # a separate budget from the flops gate: peak estimation carries
        # fusion/scheduling error the FLOP count does not
        print(
            f"memory check: {len(names) - len(mem_divergent)}/{len(names)} "
            f"within {args.max_divergence:.0%}"
            + (f"; divergent: {mem_divergent}" if mem_divergent else "")
        )
        if len(mem_divergent) > args.allow_memory_divergent:
            status = 2
    return status


if __name__ == "__main__":
    sys.exit(main())
