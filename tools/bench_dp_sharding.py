#!/usr/bin/env python
"""dp-sharding bench leg: weight-update sharding + quantized collectives
on the virtual mesh (the multichip dryrun environment).

Trains one Adam MLP three ways on a dp=8 in-process mesh — per-grad
allreduce baseline, ZeRO sharded update (fp32 wire), sharded update with
int8 block-quantized collectives — and reports:

* collective payload (wire) bytes per step, from the ``collective.*``
  counters the emitters record at trace time;
* optimizer-state bytes per rank (sharded gauges) vs the replicated
  baseline layout;
* loss-trajectory parity across the three builds.

Gates (exit 1 on violation unless --no-gate):

* int8 collective payload <= 0.6x the allreduce baseline wire bytes
  (the ">=40% payload reduction" acceptance);
* optimizer-state bytes/rank <= 1.25x (full / dp) — "~1/N";
* sharded fp32 losses match the baseline (rtol 1e-5; the dp=8 reduction
  tree may legally reorder adds), int8 within 5e-2.

Usage:
    python tools/bench_dp_sharding.py [--steps N] [--dump SNAP.json]
                                      [--no-gate]

Prints ONE JSON line (the bench.py dp_sharding leg parses it). Always
re-executes itself in a child process pinned to an 8-device virtual CPU
platform, so it behaves identically from a TPU-attached driver and from
CPU CI (the __graft_entry__.dryrun_multichip pattern).
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys

DP = 8
_CHILD_ENV = "_PADDLE_TPU_DP_SHARDING_CHILD"
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)


def _respawn(argv):
    env = dict(os.environ)
    env[_CHILD_ENV] = "1"
    env["XLA_FLAGS"] = (
        env.get("XLA_FLAGS", "")
        + f" --xla_force_host_platform_device_count={DP}"
    ).strip()
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("PALLAS_AXON_POOL_IPS", None)  # never claim the driver's chip
    proc = subprocess.run(
        [sys.executable, os.path.abspath(__file__)] + argv,
        env=env, cwd=os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)
        )),
        capture_output=True, text=True, timeout=1200,
    )
    sys.stderr.write(proc.stderr)
    sys.stdout.write(proc.stdout)
    return proc.returncode


def _build_and_train(mode, steps, quant=None):
    import numpy as np

    import jax
    import paddle_tpu as fluid
    from paddle_tpu import layers, observability
    from paddle_tpu.framework import unique_name
    from paddle_tpu.framework.scope import Scope
    from paddle_tpu.parallel import make_mesh, shard_program
    from paddle_tpu.parallel.transpiler import (
        GradAllReduce,
        ShardedWeightUpdate,
    )

    b, d, h = 16, 512, 256
    before = dict(observability.snapshot()["counters"])
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 7
    scope = Scope()
    with fluid.program_guard(main, startup), fluid.scope_guard(scope), \
            unique_name.guard():
        x = fluid.data("x", [b, d])
        y = fluid.data("y", [b, 1])
        hid = layers.fc(x, h, act="relu")
        hid = layers.fc(hid, h, act="relu")
        pred = layers.fc(hid, 1)
        loss = layers.mean(layers.square_error_cost(pred, y))
        _, pg = fluid.optimizer.Adam(0.001).minimize(loss, startup)
        blk = main.global_block
        if mode == "allreduce":
            GradAllReduce(DP).transpile(main, pg)
        else:
            ShardedWeightUpdate(DP, quant=quant).transpile(main, startup, pg)
        blk.append_op("scale", {"X": [loss.name]}, {"Out": [loss.name]},
                      {"scale": 1.0 / DP, "bias": 0.0})
        blk.append_op("c_allreduce_sum", {"X": [loss.name]},
                      {"Out": [loss.name]}, {"axis_name": "dp"})
        shard_program(main, make_mesh({"dp": DP}, jax.devices()[:DP]),
                      {"x": ("dp",), "y": ("dp",)})
        exe = fluid.Executor()
        exe.run(startup, scope=scope)
        losses = []
        for i in range(steps):
            rng = np.random.RandomState(100 + i)
            feed = {"x": rng.randn(b, d).astype(np.float32),
                    "y": rng.randn(b, 1).astype(np.float32)}
            # return_numpy=True: this loop materializes the loss every
            # step anyway (no pipelining to preserve), and the numpy
            # path is the one that publishes the perf.step_attribution
            # sample the CI attribution gate reads
            (lv,) = exe.run(main, feed=feed, fetch_list=[loss],
                            scope=scope)
            losses.append(float(np.asarray(lv).reshape(-1)[0]))
        # baseline optimizer-state bytes: the replicated accumulators
        state_bytes = 0
        for v in main.list_vars():
            if getattr(v, "_accum_of", None) is not None:
                n = 1
                for dim in v.shape or ():
                    n *= int(dim)
                state_bytes += n * 4
        shard_gauges = {
            k: v for k, v in observability.snapshot()["gauges"].items()
            if k.startswith("collective.zero_")
        }
    after = observability.snapshot()["counters"]
    delta = {
        k: after[k] - before.get(k, 0)
        for k in after
        if k.startswith("collective.") and after[k] != before.get(k, 0)
    }
    return {
        "losses": losses,
        "counters": delta,
        "replicated_state_bytes": state_bytes,
        "gauges": shard_gauges,
    }


def run(steps, dump, gate):
    import numpy as np

    from paddle_tpu import observability

    base = _build_and_train("allreduce", steps)
    shard = _build_and_train("sharded", steps)
    quant = _build_and_train("sharded", steps, quant="int8")

    # wire bytes: the zero counters already carry the (n-1)/n ring factor;
    # the allreduce counter records raw payload, x 2(n-1)/n on the wire
    ring = 2.0 * (DP - 1) / DP
    base_wire = base["counters"].get(
        "collective.c_allreduce_sum.bytes", 0
    ) * ring
    fp_wire = (
        shard["counters"].get("collective.bytes.reduce_scatter_fp32", 0)
        + shard["counters"].get("collective.bytes.all_gather_fp32", 0)
    )
    q_wire = (
        quant["counters"].get("collective.bytes.reduce_scatter_int8", 0)
        + quant["counters"].get("collective.bytes.all_gather_int8", 0)
    )
    g = shard["gauges"]
    per_rank = g.get("collective.zero_optimizer_state_bytes_per_rank", 0)
    full = g.get("collective.zero_optimizer_state_bytes_full", 0)
    master = g.get("collective.zero_master_shard_bytes_per_rank", 0)
    # independent cross-check: the transpiler's "full" gauge must equal a
    # plain walk of the BASELINE build's accumulator vars
    base_full = base["replicated_state_bytes"]
    state_gauge_consistent = bool(
        full and abs(full - base_full) <= 0.02 * base_full
    )

    parity_fp = bool(np.allclose(base["losses"], shard["losses"],
                                 rtol=1e-5, atol=1e-6))
    parity_q = bool(np.allclose(base["losses"], quant["losses"],
                                rtol=5e-2, atol=5e-2))
    payload_reduction = 1.0 - (q_wire / base_wire) if base_wire else 0.0
    state_ratio = per_rank / full if full else 1.0

    result = {
        "metric": "dp_sharding",
        "dp": DP,
        "steps": steps,
        "baseline_allreduce_wire_bytes": int(base_wire),
        "sharded_fp32_wire_bytes": int(fp_wire),
        "sharded_int8_wire_bytes": int(q_wire),
        "int8_payload_reduction": round(payload_reduction, 4),
        "optimizer_state_bytes_replicated": int(full),
        "optimizer_state_bytes_replicated_recount": int(base_full),
        "optimizer_state_gauge_consistent": state_gauge_consistent,
        "optimizer_state_bytes_per_rank": int(per_rank),
        "optimizer_state_ratio": round(state_ratio, 4),
        "master_shard_bytes_per_rank": int(master),
        "loss_parity_fp32": parity_fp,
        "loss_parity_int8": parity_q,
        "final_loss": {
            "allreduce": base["losses"][-1],
            "sharded": shard["losses"][-1],
            "sharded_int8": quant["losses"][-1],
        },
    }
    failures = []
    if payload_reduction < 0.40:
        failures.append(
            f"int8 payload reduction {payload_reduction:.1%} < 40%"
        )
    if state_ratio > 1.25 / DP:
        failures.append(
            f"optimizer-state bytes/rank ratio {state_ratio:.4f} > "
            f"1.25/{DP}"
        )
    if not parity_fp:
        failures.append("sharded fp32 losses diverge from allreduce")
    if not parity_q:
        failures.append("sharded int8 losses out of tolerance")
    if not state_gauge_consistent:
        failures.append(
            f"transpiler state gauge {full} disagrees with the baseline "
            f"accumulator recount {base_full}"
        )
    result["gate_failures"] = failures
    if dump:
        observability.dump(dump)
    print(json.dumps(result))
    if failures and gate:
        print(f"dp-sharding gates FAILED: {failures}", file=sys.stderr)
        return 1
    return 0


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--steps", type=int, default=4)
    ap.add_argument("--dump", default=None,
                    help="write the observability snapshot here")
    ap.add_argument("--no-gate", action="store_true",
                    help="report only, never fail the exit code")
    args = ap.parse_args(argv)
    if os.environ.get(_CHILD_ENV) != "1":
        return _respawn(
            ["--steps", str(args.steps)]
            + (["--dump", args.dump] if args.dump else [])
            + (["--no-gate"] if args.no_gate else [])
        )
    return run(args.steps, args.dump, gate=not args.no_gate)


if __name__ == "__main__":
    sys.exit(main())
