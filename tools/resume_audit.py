"""Kill/resume equivalence audit: prove an elastic resume is
indistinguishable from never having died.

Runs tests/dist_resume_worker.py twice through the real launcher:

* **control** — a 2-rank run to completion, no interference;
* **kill** — the same run, but rank 1 SIGKILLs itself mid-epoch (one step
  past a checkpoint, off the checkpoint cadence) and the launcher's
  ``--elastic`` path restarts it; the restart resumes from its newest
  COMPLETE checkpoint via the TrainStatus-v2 / rank-shard machinery.

Then asserts, per rank:

1. final weights are BITWISE identical between the two runs;
2. the consumed-example logs are bitwise identical, and independently
   match the DistributedBatchSampler's planned schedule exactly — so no
   example was skipped or consumed twice on the resumed timeline;
3. the restarted rank really took the resume path (attempt 1 completed,
   ``resilience.resumes`` counter fired);
4. a v1 (epoch-only) checkpoint still loads through the same
   ``Fleet.load_check_point`` entry point.

Exit 0 on success; any violation raises. Used by the ci.sh chaos stage::

    python tools/resume_audit.py [--out DIR] [--keep]
"""

import argparse
import json
import os
import shutil
import subprocess
import sys
import tempfile

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(REPO, "tests", "dist_resume_worker.py")


def run_pod(out_dir, kill, started_port, sharded=False, async_=False):
    os.makedirs(out_dir, exist_ok=True)
    cmd = [
        sys.executable, "-m", "paddle_tpu.distributed.launch",
        "--nproc_per_node", "2", "--simulate_cpu",
        "--started_port", str(started_port),
        "--log_dir", os.path.join(out_dir, "logs"),
    ]
    if kill:
        cmd += ["--elastic", "--max_restarts", "2",
                "--restart_backoff", "0.1"]
    cmd += [WORKER, out_dir] + (["1"] if kill else [])
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    if sharded:
        env["PADDLE_TPU_RESUME_SHARDED"] = "1"
    if async_:
        env["PADDLE_TPU_RESUME_ASYNC"] = "1"
    proc = subprocess.run(cmd, env=env, capture_output=True, text=True)
    if proc.returncode != 0:
        for rank in (0, 1):
            log = os.path.join(out_dir, "logs", f"worker_{rank}.log")
            if os.path.exists(log):
                sys.stderr.write(f"---- worker_{rank}.log ----\n")
                sys.stderr.write(open(log).read())
        sys.stderr.write(proc.stderr)
        raise RuntimeError(
            f"{'kill' if kill else 'control'} pod failed "
            f"(rc={proc.returncode})"
        )


def parse_log(path):
    """[(step, epoch, [indices...]), ...]"""
    out = []
    for ln in open(path).read().splitlines():
        if not ln:
            continue
        step, epoch, idxs = ln.split()
        out.append((int(step), int(epoch),
                    [int(i) for i in idxs.split(",")]))
    return out


def planned_schedule(rank, nranks, epoch):
    """The batches DistributedBatchSampler(seed=13, shuffle=True) deals to
    `rank` in `epoch` — recomputed from first principles so the log check
    does not depend on the very code under audit."""
    from tests.dist_resume_worker import BS, N

    order = np.random.RandomState(13 + epoch).permutation(N)
    per_rank = (N + nranks - 1) // nranks
    mine = np.resize(order, per_rank * nranks)[rank::nranks]
    return [mine[i:i + BS].tolist() for i in range(0, len(mine), BS)]


def audit_logs(out_dir, nranks=2):
    """Every rank's log must equal its planned schedule exactly — each
    planned example consumed once, in order, none skipped or repeated."""
    from tests.dist_resume_worker import EPOCHS

    for rank in range(nranks):
        entries = parse_log(
            os.path.join(out_dir, f"consumed_rank{rank}.log")
        )
        got = {}
        for _step, epoch, idxs in entries:
            got.setdefault(epoch, []).append(idxs)
        for epoch in range(EPOCHS):
            plan = planned_schedule(rank, nranks, epoch)
            assert got.get(epoch) == plan, (
                f"rank {rank} epoch {epoch}: consumed batches deviate "
                f"from the sampler schedule\n got: {got.get(epoch)}\nplan: "
                f"{plan}"
            )
        steps = [e[0] for e in entries]
        assert steps == list(range(1, len(steps) + 1)), (
            f"rank {rank}: step sequence has gaps/repeats: {steps}"
        )


def assert_bitwise_equal(control_dir, kill_dir, nranks=2):
    for rank in range(nranks):
        a = np.load(os.path.join(control_dir, f"final_rank{rank}.npz"))
        b = np.load(os.path.join(kill_dir, f"final_rank{rank}.npz"))
        assert sorted(a.files) == sorted(b.files), (rank, a.files, b.files)
        for name in a.files:
            ab, bb = a[name], b[name]
            assert ab.dtype == bb.dtype and ab.shape == bb.shape and (
                ab.tobytes() == bb.tobytes()
            ), f"rank {rank} var {name!r}: weights differ after resume"
        la = open(os.path.join(control_dir, f"consumed_rank{rank}.log"),
                  "rb").read()
        lb = open(os.path.join(kill_dir, f"consumed_rank{rank}.log"),
                  "rb").read()
        assert la == lb, f"rank {rank}: consumed-example logs differ"


def assert_resume_fired(kill_dir):
    done = json.load(open(os.path.join(kill_dir, "done_rank1.json")))
    assert done["attempt"] >= 1, (
        f"rank 1 finished on attempt {done['attempt']} — it was never "
        "killed+restarted, the audit proved nothing"
    )
    obs = json.load(open(
        os.path.join(kill_dir, f"obs_rank1_attempt{done['attempt']}.json")
    ))
    counters = obs.get("counters", obs)
    assert counters.get("resilience.resumes", 0) >= 1, (
        f"resume path never fired on the restarted rank: {counters}"
    )


def assert_async_pipeline_audited(kill_dir):
    """The --async leg proved something only if the surviving rank's
    checkpoints really went through the async pipeline (snapshot/publish
    stage histograms, at least one delta link) and the killed rank's
    wedged publish left only tmp debris — every numbered checkpoint dir
    on disk is committed (load-candidate) state."""
    obs = json.load(open(os.path.join(kill_dir, "obs_rank0_attempt0.json")))
    c = obs.get("counters", {})
    h = obs.get("histograms", {})
    assert c.get("checkpoint.async_saves", 0) >= 3, c
    assert c.get("checkpoint.delta_saves", 0) >= 1, (
        "no delta checkpoint was published on the async leg", c)
    assert h["checkpoint.snapshot_latency"]["count"] >= 3, h.keys()
    assert h["checkpoint.publish_latency"]["count"] >= 1, h.keys()
    ckpt_root = os.path.join(kill_dir, "ckpts")
    bad = [d for d in os.listdir(ckpt_root) if d.endswith(".tmp")]
    # a wedged publish may leave a *.tmp shard dir INSIDE a checkpoint —
    # never a torn numbered checkpoint at the top level; committed dirs
    # must each carry a commit record
    for d in os.listdir(ckpt_root):
        full = os.path.join(ckpt_root, d)
        if d.startswith("__paddle_checkpoint__") and not d.endswith(".tmp"):
            assert os.path.exists(os.path.join(full, "commit.json")), d
    print(f"async pipeline audited: {c['checkpoint.async_saves']} async "
          f"saves, {c['checkpoint.delta_saves']} delta links, "
          f"{len(bad)} uncommitted tmp dirs (ignored by load)")


def audit_v1_compat(work_dir):
    """A v1 (epoch-only) checkpoint — the PR-2/3 on-disk format: payload +
    manifest + bare train_status.json, no commit record, no shards — must
    still load through Fleet.load_check_point."""
    import paddle_tpu as fluid
    from paddle_tpu import layers
    from paddle_tpu.fleet import collective as fc
    from paddle_tpu.fleet.role_maker import UserDefinedRoleMaker

    x = fluid.data("x", [-1, 4])
    pred = layers.fc(x, 1)
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    path = os.path.join(work_dir, "v1_ckpts")
    ckpt = os.path.join(path, "__paddle_checkpoint__0")
    fluid.io.save_persistables(exe, ckpt)
    with open(os.path.join(ckpt, "train_status.json"), "w") as f:
        json.dump({"epoch_no": 3}, f)
    fleet = fc.Fleet()
    fleet.init(UserDefinedRoleMaker())
    status = fleet.load_check_point(exe, path)
    assert status.next() == 4, status
    assert status.global_step == 0 and not status.cursor, status
    print("v1 compat OK: epoch-only checkpoint loads with defaulted "
          "v2 fields")


def assert_sharded_state_audited(out_dir, nranks=2):
    """The sharded leg proved something only if the checkpointed/final
    state really contains dp-sharded optimizer shards (Momentum velocity
    shards under the @ZERO_SHARD layout) and the full-size velocities are
    gone."""
    for rank in range(nranks):
        z = np.load(os.path.join(out_dir, f"final_rank{rank}.npz"))
        shard_vars = [n for n in z.files if n.endswith("@ZERO_SHARD")]
        assert any("velocity" in n for n in shard_vars), (
            f"rank {rank}: no sharded optimizer state in the audited "
            f"final weights ({z.files})"
        )
        full = [
            n for n in z.files
            if "velocity" in n and not n.endswith("@ZERO_SHARD")
        ]
        assert not full, (
            f"rank {rank}: full-size optimizer state survived the "
            f"sharded transpile: {full}"
        )


def _du(path):
    from paddle_tpu.fleet.collective import _dir_bytes

    return _dir_bytes(path)


def audit_embedding(work_dir, sharded=False, async_=False):
    """PR-11 leg: a checkpoint carrying CACHED (host-cold/device-hot) or
    ps-SHARDED embedding tables must resume bitwise. In-process: train the
    fused DeepFM 4 steps, checkpoint (persistables + engine host state +
    RNG), rebuild everything from scratch, restore, train 4 more — the
    continuation's losses and final flushed table state must be bitwise
    identical to an uninterrupted 8-step run.

    ``async_``: route the checkpoints through fleet.AsyncCheckpointer
    instead — a full save at step 2 and a DELTA link at step 4 (row
    oracles keyed off the embedding cache's write-back ticks, compressed
    payloads, engine host state as the aux payload) — and resume through
    ``Fleet.load_check_point(load_aux=True)``'s chain reconstruction."""
    import jax.numpy as jnp

    import paddle_tpu as fluid
    from paddle_tpu.embedding import EmbeddingEngine, fuse_lookups
    from paddle_tpu.framework import unique_name
    from paddle_tpu.framework.scope import Scope
    from paddle_tpu.models.deepfm import DeepFMConfig, deepfm

    # vocab >> hot tier (the capacity-beyond-device shape the cache
    # exists for): only the resident slice writes back between saves, so
    # the --async leg's row-delta payloads stay far below a full save
    cfg = DeepFMConfig(vocab_size=2048, num_fields=4, embed_dim=8,
                       mlp_sizes=(16,))
    b, total_steps, ckpt_step = 16, 8, 4
    rng = np.random.RandomState(5)
    feeds = []
    for _ in range(total_steps):
        idv = (cfg.vocab_size * rng.power(0.4, (b, cfg.num_fields)))
        idv = idv.astype(np.int64)
        feeds.append({"feat_ids": idv,
                      "label": (idv[:, :1] % 2 == 0).astype(np.float32)})

    def build():
        main, startup = fluid.Program(), fluid.Program()
        main.random_seed = startup.random_seed = 7
        scope = Scope()
        with fluid.program_guard(main, startup), unique_name.guard():
            ids = fluid.data("feat_ids", [b, cfg.num_fields], "int64")
            label = fluid.data("label", [b, 1], "float32")
            loss, _p = deepfm(ids, label, cfg, per_slot=True)
            fuse_lookups(main)
            engine = None
            if not sharded:
                engine = EmbeddingEngine(main, startup,
                                         hot_rows=cfg.vocab_size // 8)
            # Momentum: the checkpoint must carry hot-tier/sharded
            # accumulator state, not just the tables
            fluid.optimizer.Momentum(0.05, 0.9).minimize(loss)
            if sharded:
                from paddle_tpu.parallel import (
                    make_mesh,
                    shard_program,
                    shard_sparse_tables,
                )

                shard_sparse_tables(main)
                shard_program(main, make_mesh({"ps": 8}))
        exe = fluid.Executor()
        exe.run(startup, scope=scope)
        if engine:
            engine.attach(scope)
        return main, startup, scope, exe, loss, engine

    def step(main, scope, exe, loss, engine, feed):
        f = engine.prepare_feed(feed, scope) if engine else feed
        (lv,) = exe.run(main, feed=f, fetch_list=[loss], scope=scope)
        return float(np.asarray(lv).reshape(-1)[0])

    def final_state(main, scope, engine):
        out = {}
        if engine:
            for k, v in engine.state_dict(scope).items():
                out[k] = np.asarray(v)
        for v in main.list_vars():
            if v.persistable and scope.find_var(v.name) is not None:
                out[v.name] = np.asarray(scope.find_var(v.name))
        return out

    # control: uninterrupted
    main, startup, scope, exe, loss, engine = build()
    control_losses = [
        step(main, scope, exe, loss, engine, f) for f in feeds
    ]
    control_state = final_state(main, scope, engine)

    from paddle_tpu.framework.scope import scope_guard

    label = ("sharded" if sharded else "cached") + (
        " async" if async_ else ""
    )
    if async_:
        from paddle_tpu.fleet import collective as fc
        from paddle_tpu.fleet.role_maker import UserDefinedRoleMaker

        fleet = fc.Fleet()
        fleet.init(UserDefinedRoleMaker())
        ckpt = os.path.join(
            work_dir,
            f"embed_async_{'sharded' if sharded else 'cached'}",
        )
        # resume timeline: full save at step 2, delta link at step 4
        main, startup, scope, exe, loss, engine = build()
        losses = []
        with scope_guard(scope):
            saver = fc.AsyncCheckpointer(
                fleet, ckpt, executor=exe, main_program=main, scope=scope,
                delta=True, full_every=4, compress=True,
                queue_policy="block", remain_all_checkpoint=True,
                row_oracles=engine.delta_row_oracles() if engine else None,
            )
            for k, f in enumerate(feeds[:ckpt_step], 1):
                losses.append(step(main, scope, exe, loss, engine, f))
                if k % 2 == 0:
                    st = fc.TrainStatus.capture(
                        epoch_no=0, global_step=k, program=main
                    )
                    saver.save(
                        st,
                        aux=engine.state_dict(scope) if engine else None,
                    ).result(timeout=120)
            saver.close()
        dirs = sorted(
            d for d in os.listdir(ckpt)
            if d.startswith("__paddle_checkpoint__")
        )
        assert len(dirs) == 2 and os.path.exists(
            os.path.join(ckpt, dirs[1], "delta.json")
        ), dirs
        full_b, delta_b = _du(os.path.join(ckpt, dirs[0])), _du(
            os.path.join(ckpt, dirs[1])
        )
        if engine is not None:
            # the byte cut is a CACHED-model property: the write-back-tick
            # row oracles shrink the host stores to the resident slice.
            # (The sharded leg has no oracle — every table mutates every
            # step, so its delta only proves chain-resume correctness.)
            assert delta_b < full_b * 0.8, (
                f"delta link ({delta_b}B) did not cut repeat-save bytes "
                f"vs the full save ({full_b}B) on the cached model"
            )
        # rebuild from scratch; resume through the committed delta chain
        main, startup, scope, exe, loss, engine = build()
        with scope_guard(scope):
            status = fleet.load_check_point(
                exe, ckpt, main_program=main, load_aux=True
            )
            assert status.global_step == ckpt_step, status
            if engine:
                engine.load_state_dict(status.aux, scope)
            status.restore(program=main)
            losses += [
                step(main, scope, exe, loss, engine, f)
                for f in feeds[ckpt_step:]
            ]
            resumed_state = final_state(main, scope, engine)
        print(f"  delta chain: full {full_b}B -> delta {delta_b}B "
              f"({delta_b / full_b:.0%} of the full link, compressed)")
    else:
        # resume timeline: train to the checkpoint, persist, REBUILD,
        # restore
        main, startup, scope, exe, loss, engine = build()
        losses = [
            step(main, scope, exe, loss, engine, f)
            for f in feeds[:ckpt_step]
        ]
        ckpt = os.path.join(
            work_dir, f"embed_ckpt_{'sharded' if sharded else 'cached'}"
        )
        if engine:
            engine.flush(scope)
        with scope_guard(scope):
            fluid.io.save_persistables(exe, ckpt, main_program=main)
        if engine:
            np.savez(os.path.join(ckpt, "embedding_state.npz"),
                     **engine.state_dict(scope))
        rng_state = main.rng_state()

        main, startup, scope, exe, loss, engine = build()
        with scope_guard(scope):
            fluid.io.load_persistables(exe, ckpt, main_program=main)
        if engine:
            state = dict(np.load(os.path.join(ckpt, "embedding_state.npz")))
            engine.load_state_dict(state, scope)
            # the freshly-installed device tier is stale placeholder data;
            # residency restarts empty so first-touch refills from host
        main.set_rng_state(rng_state)
        losses += [
            step(main, scope, exe, loss, engine, f)
            for f in feeds[ckpt_step:]
        ]
        resumed_state = final_state(main, scope, engine)
    assert losses == control_losses, (
        f"embedding {label} resume: losses diverge\n control: "
        f"{control_losses}\n resumed: {losses}"
    )
    assert sorted(control_state) == sorted(resumed_state), (
        sorted(control_state), sorted(resumed_state))
    for name in control_state:
        a, barr = control_state[name], resumed_state[name]
        assert a.tobytes() == barr.tobytes(), (
            f"embedding {label} resume: var {name!r} differs bitwise"
        )
    if sharded:
        print(f"embedding resume OK ({label}): 8-step continuation bitwise "
              "with ps=8 row-sharded tables + Momentum velocity in the "
              "checkpoint")
    else:
        print(f"embedding resume OK ({label}): 8-step continuation bitwise "
              "with hot-tier cache (hot=vocab/8), host cold store + "
              "velocity tiers round-tripped")


def main(argv=None):
    ap = argparse.ArgumentParser("resume_audit")
    ap.add_argument("--out", default=None,
                    help="work dir (default: a fresh temp dir)")
    ap.add_argument("--keep", action="store_true",
                    help="keep the work dir for inspection")
    ap.add_argument("--sharded", action="store_true",
                    help="train with the ZeRO sharded weight update "
                         "(Momentum over a dp=2 virtual mesh) so the "
                         "audit covers dp-sharded optimizer state")
    ap.add_argument("--embedding", action="store_true",
                    help="audit checkpoints carrying the PR-11 embedding "
                         "engine state: hot-tier cached tables (host cold "
                         "store + velocity tiers) and ps-sharded tables "
                         "must both resume bitwise")
    ap.add_argument("--async", dest="async_", action="store_true",
                    help="route checkpoints through the async "
                         "snapshot/publish pipeline (delta chains "
                         "included) and SIGKILL the rank while a publish "
                         "is IN FLIGHT: resume must come bitwise from the "
                         "newest committed checkpoint. Composes with "
                         "--sharded and --embedding")
    args = ap.parse_args(argv)
    work = args.out or tempfile.mkdtemp(prefix="paddle_tpu_resume_audit_")
    os.makedirs(work, exist_ok=True)
    sys.path.insert(0, REPO)
    if args.embedding:
        os.environ.setdefault(
            "XLA_FLAGS", "--xla_force_host_platform_device_count=8"
        )
        alabel = "async " if args.async_ else ""
        try:
            print(f"== resume audit: embedding engine ({alabel}cached "
                  "tables) ==")
            audit_embedding(work, sharded=False, async_=args.async_)
            print(f"== resume audit: embedding engine ({alabel}ps-sharded "
                  "tables) ==")
            audit_embedding(work, sharded=True, async_=args.async_)
            return 0
        finally:
            if not args.keep and args.out is None:
                shutil.rmtree(work, ignore_errors=True)
    label = ("async " if args.async_ else "") + (
        "sharded " if args.sharded else ""
    )
    ports = (6470, 6490) if args.sharded else (6370, 6390)
    if args.async_:
        ports = (ports[0] + 200, ports[1] + 200)
    try:
        control, kill = os.path.join(work, "control"), os.path.join(work, "kill")
        print(f"== resume audit: {label}control run (uninterrupted) ==")
        run_pod(control, kill=False, started_port=ports[0],
                sharded=args.sharded, async_=args.async_)
        print(f"== resume audit: {label}kill run (SIGKILL rank 1 "
              f"{'mid-async-publish' if args.async_ else 'mid-epoch'}, "
              "elastic resume) ==")
        run_pod(kill, kill=True, started_port=ports[1],
                sharded=args.sharded, async_=args.async_)

        assert_resume_fired(kill)
        audit_logs(kill)
        audit_logs(control)
        assert_bitwise_equal(control, kill)
        if args.async_:
            assert_async_pipeline_audited(kill)
        if args.sharded:
            assert_sharded_state_audited(control)
            assert_sharded_state_audited(kill)
            print(f"resume audit OK ({label.strip()}): "
                  "SIGKILL+elastic-resume with dp-sharded optimizer state "
                  "is bitwise identical to the uninterrupted run — "
                  "velocity shards included")
        elif args.async_:
            print("resume audit OK (async): SIGKILL mid-async-publish + "
                  "elastic resume is bitwise identical to the "
                  "uninterrupted run; only committed checkpoints were "
                  "loadable, delta chain included")
        else:
            audit_v1_compat(work)
            print("resume audit OK: SIGKILL+elastic-resume run is bitwise "
                  "identical to the uninterrupted run (weights + "
                  "consumed-example logs), no example skipped or repeated, "
                  "resume counters fired, v1 checkpoint loads")
        return 0
    finally:
        if not args.keep and args.out is None:
            shutil.rmtree(work, ignore_errors=True)


if __name__ == "__main__":
    sys.exit(main())
