#!/usr/bin/env python
"""Build every bundled model and run the static program verifier over it.

Usage:
    python tools/program_lint.py --all-models [--strict] [--memory]
    python tools/program_lint.py --model bert --model gpt --json
    python tools/program_lint.py --broken-fixture   # must exit non-zero

Exit status: 0 when no model produced an ERROR finding (under --strict,
escalated WARNINGs — silent redefinition, oom-risk — also count),
non-zero otherwise. ``--broken-fixture`` builds a deliberately malformed
Program (use-before-def + shape desync + rank-divergent collective) and
lints it: CI asserts the exit status is NON-zero, the linter's own
regression test. ``--broken-donation-fixture`` (a read of a donated KV
cache buffer) and ``--broken-oom-fixture`` (a program over a deliberately
tiny ``PADDLE_TPU_HBM_BYTES``) are the memory family's equivalents.

``--memory`` prints the static peak-HBM plan (analysis/memory.py) per
model; ``--json`` swaps the human report for one machine-readable JSON
document on stdout (per-model findings with severity/category/op/loc,
plus the memory summary) for dashboards and diffing.

Models are built through ``paddle_tpu.models.zoo`` (CI-sized configs,
training programs with optimizer applied); meshed models (bert_3d) get a
virtual-device mesh so the collective-schedule lint has bound axes.
"""

from __future__ import annotations

import argparse
import os
import sys

# runnable as `python tools/program_lint.py` from anywhere
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# an 8-device virtual CPU mesh for the meshed models, before jax loads
# (mirrors tests/conftest.py)
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")


def _lint_one(name, strict, verbose, cost=False, memory=False,
              records=None):
    import time

    from paddle_tpu.analysis import Severity, verify_program
    from paddle_tpu.models import build_model

    t0 = time.time()
    bm = build_model(name)
    built = time.time() - t0
    report = verify_program(bm.main, bm.feed_names, bm.fetch_names)
    startup_report = verify_program(bm.startup, (), ())
    report.extend(startup_report.findings)
    verified = time.time() - t0 - built
    failing = report.strict_errors() if strict else report.errors
    status = "FAIL" if failing else "ok"
    mt = None
    if memory or records is not None:
        # the memory family's full table (the verifier only surfaces its
        # findings; the table carries the per-op liveness timeline)
        from paddle_tpu.analysis import plan_memory

        mt = plan_memory(bm.main, feed_names=bm.feed_names or None,
                         fetch_names=bm.fetch_names)
    if records is not None:
        records.append({
            "model": name,
            "status": status,
            "errors": len(report.errors),
            "warnings": len(report.warnings),
            "infos": len(report.infos),
            "findings": [f.to_dict() for f in report.findings],
            "memory": mt.to_dict() if mt is not None else None,
        })
        return not failing
    print(
        f"[{status}] {name:<10} build {built:5.1f}s verify {verified:5.1f}s"
        f"  errors={len(report.errors)} warnings={len(report.warnings)} "
        f"info={len(report.infos)}"
    )
    min_sev = Severity.INFO if verbose else Severity.WARNING
    shown = [f for f in report.findings if f.severity >= min_sev]
    for f in shown:
        print("    " + f.format())
    if memory:
        for line in mt.format(top=5).splitlines():
            print("    " + line)
    if cost:
        # the fourth analysis family: per-op FLOPs/bytes/roofline table
        # (analysis/cost.py) at the model's graph-build shapes
        for line in bm.main.estimate().format(top=10).splitlines():
            print("    " + line)
    return not failing


def _broken_fixture():
    """A deliberately malformed Program: the linter must reject it."""
    import paddle_tpu as fluid
    from paddle_tpu.parallel import make_mesh, shard_program
    from paddle_tpu.parallel.pipeline import slice_program_into_stages

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        from paddle_tpu import layers

        x = fluid.data("x", [8, 4])
        with fluid.device_guard("pipeline:0"):
            h = layers.fc(x, 4)
        with fluid.device_guard("pipeline:1"):
            loss = layers.mean(layers.fc(h, 4))
        main._pipeline = {"num_microbatches": 2, "axis_name": "pp"}
        _, pipe_op = slice_program_into_stages(main, loss)
        blk = main.global_block
        # use-before-def: a temp no op ever produces
        blk.create_var(name="never_written", shape=[8, 4], dtype="float32")
        blk.append_op("relu", {"X": ["never_written"]}, {"Out": ["r0"]})
        blk.create_var(name="r0", shape=[8, 4], dtype="float32")
        # shape desync: declaration disagrees with the emitter
        blk.create_var(name="desynced", shape=[3, 3], dtype="float32")
        blk.append_op("relu", {"X": ["r0"]}, {"Out": ["desynced"]})
    # rank-divergent collective: stage 0 allreduces, stage 1 does not
    stage0 = main.blocks[pipe_op.attr("stage_blocks")[0]]
    stage0.append_op(
        "c_allreduce_sum", {"X": [h.name]}, {"Out": [h.name]},
        {"axis_name": "dp"},
    )
    mesh = make_mesh({"dp": 4, "pp": 2})
    shard_program(main, mesh, {"x": ("dp",)})
    return main, ("x",), (loss.name,)


def _broken_frozen_fixture():
    """A "frozen" inference program with a surviving optimizer op: the
    ``training-op-in-inference`` structural finding must reject it (the
    serving freeze regression fixture)."""
    import paddle_tpu as fluid
    from paddle_tpu import layers

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.data("x", [-1, 4])
        pred = layers.fc(x, 2)
        prob = layers.softmax(pred)
    blk = main.global_block
    # a leftover sgd update (as if prune missed it): params mutate while
    # serving — the exact defect the finding exists to catch
    w = blk.all_parameters()[0]
    blk.create_var(name="lr0", shape=[1], dtype="float32")
    blk.append_op(
        "fill_constant", {}, {"Out": ["lr0"]},
        {"shape": [1], "dtype": "float32", "value": 0.1},
    )
    blk.append_op(
        "sgd",
        {"Param": [w.name], "Grad": [w.name], "LearningRate": ["lr0"]},
        {"ParamOut": [w.name]},
    )
    main._is_inference = True
    return main, ("x",), (prob.name,)


def _broken_bucket_fixture():
    """A program whose pipeline stages BUCKET the same grad exchange
    differently (two members on stage 0, one fused member on stage 1):
    bucket membership is part of the cross-rank wire contract, so the
    collective-schedule lint must reject this at build time — on a pod it
    would deadlock (or silently corrupt) the exchange."""
    import paddle_tpu as fluid
    from paddle_tpu import layers
    from paddle_tpu.parallel import make_mesh, shard_program
    from paddle_tpu.parallel.pipeline import slice_program_into_stages

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.data("x", [8, 4])
        with fluid.device_guard("pipeline:0"):
            h = layers.fc(x, 4)
        with fluid.device_guard("pipeline:1"):
            loss = layers.mean(layers.fc(h, 4))
        main._pipeline = {"num_microbatches": 2, "axis_name": "pp"}
        _, pipe_op = slice_program_into_stages(main, loss)
    for si, pads in ((0, [256, 256]), (1, [512])):
        stage = main.blocks[pipe_op.attr("stage_blocks")[si]]
        gname = f"bucket_grad_{si}"
        stage.create_var(name=gname, shape=[4, 4], dtype="float32")
        stage.append_op(
            "fill_constant", {}, {"Out": [gname]},
            {"shape": [4, 4], "dtype": "float32", "value": 0.0},
        )
        outs = []
        for j, p in enumerate(pads):
            oname = f"bucket_shard_{si}_{j}"
            stage.create_var(name=oname, shape=[p], dtype="float32")
            outs.append(oname)
        stage.append_op(
            "zero_bucket_reduce_scatter",
            {"X": [gname] * len(pads)}, {"Out": outs},
            {"axis_name": "dp", "pad_lens": pads, "quant": "none"},
        )
    shard_program(main, make_mesh({"dp": 4, "pp": 2}), {"x": ("dp",)})
    return main, ("x",), (loss.name,)


def _broken_donation_fixture():
    """A decode step whose ``kv_cache_write`` emits the updated cache
    under a NEW name — donating the old buffer (``mutates`` aliases Out
    onto Cache) — and then reads the stale donated handle. On device the
    read observes the overwritten pages; the donation verifier must
    reject it with ``use-after-donate``."""
    import paddle_tpu as fluid

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        rows = fluid.data("rows", [1, 4, 8])
        pos = fluid.data("pos", [1], dtype="int32")
    blk = main.global_block
    blk.create_var(name="cache", shape=[16, 4, 8], dtype="float32",
                   persistable=True)
    blk.create_var(name="cache_new", shape=[16, 4, 8], dtype="float32",
                   persistable=True)
    blk.append_op(
        "kv_cache_write",
        {"Cache": ["cache"], "X": [rows.name], "Pos": [pos.name]},
        {"Out": ["cache_new"]},
    )
    # the defect: 'cache' was donated to 'cache_new' one op ago
    blk.create_var(name="stale", shape=[16, 4, 8], dtype="float32")
    blk.append_op("scale", {"X": ["cache"]}, {"Out": ["stale"]},
                  {"scale": 2.0})
    return main, ("rows", "pos"), ("stale",)


def _broken_oom_fixture():
    """A program whose static peak cannot fit the deliberately tiny
    ``PADDLE_TPU_HBM_BYTES`` the CI stage pins: the memory planner must
    emit ``oom-risk``, which strict verify escalates to a refusal."""
    import paddle_tpu as fluid
    from paddle_tpu import layers

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.data("x", [64, 1024])
        h = layers.fc(x, 1024, act="relu")
        out = layers.fc(h, 1024)
    return main, ("x",), (out.name,)


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--all-models", action="store_true",
                    help="lint every bundled model")
    ap.add_argument("--model", action="append", default=[],
                    help="lint one model by name (repeatable)")
    ap.add_argument("--strict", action="store_true",
                    help="escalated warnings (redefinition) also fail")
    ap.add_argument("--verbose", action="store_true",
                    help="print INFO findings too")
    ap.add_argument("--broken-fixture", action="store_true",
                    help="lint the seeded broken program (must fail)")
    ap.add_argument("--broken-frozen-fixture", action="store_true",
                    help="lint a frozen program with a surviving "
                         "training op (must fail)")
    ap.add_argument("--broken-bucket-fixture", action="store_true",
                    help="lint a program whose ranks bucket the same "
                         "grad exchange differently (must fail)")
    ap.add_argument("--broken-donation-fixture", action="store_true",
                    help="lint a program that reads a donated KV cache "
                         "buffer (must fail)")
    ap.add_argument("--broken-oom-fixture", action="store_true",
                    help="lint a program over a tiny PADDLE_TPU_HBM_BYTES "
                         "budget (must fail under the strict escalation)")
    ap.add_argument("--cost", action="store_true",
                    help="print the Program.estimate() cost table per model")
    ap.add_argument("--memory", action="store_true",
                    help="print the static peak-HBM plan per model")
    ap.add_argument("--json", action="store_true",
                    help="emit one machine-readable JSON document instead "
                         "of the human report")
    args = ap.parse_args(argv)

    if (args.broken_fixture or args.broken_frozen_fixture
            or args.broken_bucket_fixture or args.broken_donation_fixture
            or args.broken_oom_fixture):
        from paddle_tpu.analysis import OOM_RISK, verify_program

        if args.broken_frozen_fixture:
            program, feeds, fetches = _broken_frozen_fixture()
        elif args.broken_bucket_fixture:
            program, feeds, fetches = _broken_bucket_fixture()
        elif args.broken_donation_fixture:
            program, feeds, fetches = _broken_donation_fixture()
        elif args.broken_oom_fixture:
            # the oom gate needs a budget to be over; CI pins a tiny one,
            # and a bare invocation gets the same default
            os.environ.setdefault("PADDLE_TPU_HBM_BYTES", "1m")
            program, feeds, fetches = _broken_oom_fixture()
        else:
            program, feeds, fetches = _broken_fixture()
        report = verify_program(program, feeds, fetches)
        if args.broken_oom_fixture:
            # oom-risk is a WARNING that strict escalates; require the
            # category itself so another escalation can't mask a regression
            failing = [f for f in report.strict_errors()
                       if f.category == OOM_RISK]
        else:
            failing = report.errors
        if args.json:
            import json

            print(json.dumps({
                "fixture": True,
                "failing": len(failing),
                "findings": [f.to_dict() for f in report.findings],
            }, indent=2, sort_keys=True))
        else:
            for f in report.findings:
                print("    " + f.format())
        if failing:
            if not args.json:
                print(f"broken fixture: {len(failing)} blocking "
                      "finding(s) found (exit 1, as CI expects)")
            return 1
        print("broken fixture: linter found NO blocking findings — the "
              "verifier regressed", file=sys.stderr)
        return 0

    from paddle_tpu.models import MODEL_BUILDERS

    names = list(MODEL_BUILDERS) if args.all_models else args.model
    if not names:
        ap.error("pass --all-models, --model NAME, or --broken-fixture")
    unknown = [n for n in names if n not in MODEL_BUILDERS]
    if unknown:
        ap.error(f"unknown models {unknown}; have {sorted(MODEL_BUILDERS)}")
    records = [] if args.json else None
    ok = True
    for n in names:
        ok = _lint_one(n, args.strict, args.verbose, cost=args.cost,
                       memory=args.memory, records=records) and ok
    if args.json:
        import json

        print(json.dumps(
            {"models": records, "strict": args.strict, "ok": ok},
            indent=2, sort_keys=True,
        ))
    else:
        print("lint:", "PASS" if ok else "FAIL",
              f"({len(names)} model(s), strict={args.strict})")
    return 0 if ok else 2


if __name__ == "__main__":
    sys.exit(main())
