#!/usr/bin/env python
"""Telemetry-plane overhead gate: publisher+recorder on vs off.

The journal publisher and flight recorder only earn default-on status
(the ``PADDLE_TPU_TELEMETRY_DIR`` one-env-var opt-in) if a trainer
cannot feel them: the per-step latency delta between the full telemetry
plane running (publisher journaling deltas + recorder re-publishing its
black box, both at an aggressive cadence) and the same process with both
paused must stay within ``--gate`` (default 2%) on a zoo model.

Methodology is bench_tracing's: the two modes run strictly INTERLEAVED
(on, off, on, off ...) against the same warm executable — one ON step
and one OFF step back to back per pair, alternating order — and the
estimator is the median pairwise delta over the median OFF latency.
Monitoring itself stays enabled in BOTH modes (its cost is
bench_tracing's gate); what this bench isolates is the background
publisher/recorder threads contending for the registry lock and the GIL.
Up to ``--rounds`` rounds; ANY round meeting the gate passes (re-measure
on miss filters scheduler noise on a shared CI host, not real overhead).

Prints one JSON line (bench.py convention); exits non-zero on gate miss.
"""

from __future__ import annotations

import argparse
import json
import os
import statistics
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _feed_for(bm, seed=0):
    import numpy as np

    from paddle_tpu.core.dtypes import to_numpy_dtype

    rng = np.random.RandomState(seed)
    feed = {}
    blk = bm.main.global_block
    for n in bm.feed_names:
        v = blk.var(n)
        shape = tuple(int(d) if d not in (-1, None) else 4 for d in v.shape)
        dt = np.dtype(to_numpy_dtype(v.dtype or "float32"))
        if np.issubdtype(dt, np.integer):
            feed[n] = rng.randint(0, 3, shape).astype(dt)
        else:
            feed[n] = rng.rand(*shape).astype(dt)
    return feed


def measure_round(exe, bm, feed, scope, steps, pub, rec):
    """One interleaved round; returns (median_on_s, median_off_s, median
    pairwise delta). ON = publisher + recorder live on their cadence
    threads; OFF = both paused (threads idle at the Event check — the
    kill-you-can-feel comparison, not a teardown/restart that would
    perturb the pair)."""
    on, off = [], []
    fetch = list(bm.fetch_names)

    def step_on(i):
        pub.resume()
        rec.resume()
        t0 = time.perf_counter()
        exe.run(bm.main, feed=feed, fetch_list=fetch, scope=scope)
        on.append(time.perf_counter() - t0)

    def step_off(i):
        pub.pause()
        rec.pause()
        t0 = time.perf_counter()
        exe.run(bm.main, feed=feed, fetch_list=fetch, scope=scope)
        off.append(time.perf_counter() - t0)

    for i in range(steps):
        # alternate which mode runs first within the pair (bench_tracing
        # rationale: a fixed order folds first-vs-second warmth into the
        # delta as fake overhead)
        first, second = (step_on, step_off) if i % 2 == 0 else (
            step_off, step_on)
        first(i)
        second(i)
    pub.resume()
    rec.resume()
    delta = statistics.median(a - b for a, b in zip(on, off))
    return statistics.median(on), statistics.median(off), delta


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--model", default="bert",
                    help="zoo model to step (default bert)")
    ap.add_argument("--steps", type=int, default=40,
                    help="interleaved step pairs per round (default 40)")
    ap.add_argument("--rounds", type=int, default=5,
                    help="measurement rounds; best round gates (default 5)")
    ap.add_argument("--gate", type=float, default=0.02,
                    help="max allowed relative overhead (default 0.02)")
    ap.add_argument("--cadence", type=float, default=0.05,
                    help="publisher/recorder interval while ON (default "
                         "0.05s — 20x the production default, a stress "
                         "cadence)")
    ap.add_argument("--smoke", action="store_true",
                    help="CI-sized run (fewer steps)")
    ap.add_argument("--dump", default=None,
                    help="write the observability snapshot JSON here")
    ap.add_argument("--no-gate", action="store_true",
                    help="report only, never fail the exit code")
    args = ap.parse_args(argv)
    steps = 32 if args.smoke else args.steps

    _flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in _flags:
        os.environ["XLA_FLAGS"] = (
            _flags + " --xla_force_host_platform_device_count=8"
        ).strip()
    import jax

    jax.config.update("jax_platforms", "cpu")

    import paddle_tpu as fluid
    from paddle_tpu import observability as obs
    from paddle_tpu.framework.scope import Scope
    from paddle_tpu.models import build_model
    from paddle_tpu.observability import FlightRecorder, TelemetryPublisher

    bm = build_model(args.model, with_mesh=False)
    exe = fluid.Executor()
    scope = Scope()
    exe.run(bm.startup, scope=scope)
    feed = _feed_for(bm)
    fetch = list(bm.fetch_names)
    for _ in range(3):  # warm the executable + estimate off the clock
        exe.run(bm.main, feed=feed, fetch_list=fetch, scope=scope)

    tdir = tempfile.mkdtemp(prefix="bench_telemetry_")
    pub = TelemetryPublisher(
        directory=tdir, rank=0, interval=args.cadence
    ).start(register=False)
    rec = FlightRecorder(
        directory=tdir, rank=0, interval=args.cadence
    ).start(register=False)

    rounds = []
    best = None
    try:
        for r in range(max(1, args.rounds)):
            med_on, med_off, delta = measure_round(
                exe, bm, feed, scope, steps, pub, rec
            )
            overhead = delta / med_off if med_off > 0 else 0.0
            rounds.append({
                "median_on_ms": round(med_on * 1e3, 4),
                "median_off_ms": round(med_off * 1e3, 4),
                "median_pair_delta_ms": round(delta * 1e3, 5),
                "overhead": round(overhead, 5),
            })
            if best is None or overhead < best:
                best = overhead
            if overhead <= args.gate:
                break
    finally:
        pub.stop()
        rec.stop()
    ok = best is not None and best <= args.gate
    if args.dump:
        obs.dump(args.dump)
    result = {
        "metric": "telemetry_overhead",
        "model": args.model,
        "steps_per_round": steps,
        "cadence_s": args.cadence,
        "journal_bytes": os.path.getsize(pub.path),
        "rounds": rounds,
        "overhead": round(best, 5),
        "gate": args.gate,
        "gate_ok": ok,
    }
    print(json.dumps(result))
    if not ok and not args.no_gate:
        print(
            f"telemetry overhead gate FAILED: best {best:.2%} > "
            f"{args.gate:.0%} across {len(rounds)} round(s)",
            file=sys.stderr,
        )
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
