#!/usr/bin/env python
"""Communication/compute overlap bench leg: bucketed grad collectives +
prefetched all-gathers vs serialized ZeRO on the dp=8 in-process mesh
(ROADMAP item 4; the denominator is PR 9's serialized reduce-scatter →
update → all-gather schedule, whose wait share PR 13's attribution
measures).

Trains one Adam MLP two ways — serialized ZeRO (per-grad
zero_reduce_scatter, updates + all-gathers at the program tail) and the
overlapped schedule (size-targeted zero_bucket_reduce_scatter buckets
fired at each bucket's last grad, shard updates + zero_all_gathers
hoisted to their dataflow frontier) — and reports:

* measured steady-state step time for both schedules (interleaved
  round-medians, so drift hits both alike) and the overlap speedup;
* ``perf.wait_fraction.collective`` before/after (the PR-13 attribution
  split) plus the cost model's exposed-wire estimate and
  ``collective.overlap_ratio``;
* loss parity: fp32 BITWISE overlapped == serialized, int8 overlapped
  BITWISE == per-grad int8 and within the PR-9 tolerance of fp32;
* ``collective.buckets`` / ``collective.bucket_bytes`` counters.

Gates (exit 1 on violation unless --no-gate):

* overlapped measured step time <= serialized (speedup >= 1.0);
* fp32 bitwise + int8 parity as above;
* measured ``perf.wait_fraction.collective`` drops vs serialized;
* the overlap-aware estimate actually hides wire (overlap_ratio > 0)
  and the snapshot carries the bucket counters.

Usage:
    python tools/bench_overlap.py [--steps N] [--dump SNAP.json]
                                  [--no-gate]

Prints ONE JSON line (the bench.py dp_overlap leg parses it). Always
re-executes itself in a child pinned to an 8-device virtual CPU platform
(the __graft_entry__.dryrun_multichip pattern), so it behaves identically
from a TPU-attached driver and from CPU CI.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

DP = 8
_CHILD_ENV = "_PADDLE_TPU_OVERLAP_CHILD"
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

# model shape: 12 fc layers x 256 wide — enough dense grads that the
# serialized schedule issues ~27 collectives per step while compute still
# dominates (the regime the overlap schedule is built for)
B, D, H, L = 16, 256, 256, 12
BUCKET_BYTES = 1 << 20


def _respawn(argv):
    env = dict(os.environ)
    env[_CHILD_ENV] = "1"
    env["XLA_FLAGS"] = (
        env.get("XLA_FLAGS", "")
        + f" --xla_force_host_platform_device_count={DP}"
    ).strip()
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("PALLAS_AXON_POOL_IPS", None)  # never claim the driver's chip
    proc = subprocess.run(
        [sys.executable, os.path.abspath(__file__)] + argv,
        env=env, cwd=REPO, capture_output=True, text=True, timeout=1200,
    )
    sys.stderr.write(proc.stderr)
    sys.stdout.write(proc.stdout)
    return proc.returncode


def _feed(i):
    import numpy as np

    rng = np.random.RandomState(100 + i)
    return {"x": rng.randn(B, D).astype(np.float32),
            "y": rng.randn(B, 1).astype(np.float32)}


def _build(overlapped, quant=None):
    import jax
    import paddle_tpu as fluid
    from paddle_tpu import layers
    from paddle_tpu.framework import unique_name
    from paddle_tpu.framework.scope import Scope
    from paddle_tpu.parallel import make_mesh, shard_program
    from paddle_tpu.parallel.transpiler import ShardedWeightUpdate

    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 7
    scope = Scope()
    with fluid.program_guard(main, startup), fluid.scope_guard(scope), \
            unique_name.guard():
        x = fluid.data("x", [B, D])
        y = fluid.data("y", [B, 1])
        h = x
        for _ in range(L):
            h = layers.fc(h, H, act="relu")
        pred = layers.fc(h, 1)
        loss = layers.mean(layers.square_error_cost(pred, y))
        _, pg = fluid.optimizer.Adam(0.001).minimize(loss, startup)
        blk = main.global_block
        ShardedWeightUpdate(
            DP, quant=quant,
            bucket_bytes=BUCKET_BYTES if overlapped else None,
            prefetch=overlapped,
        ).transpile(main, startup, pg)
        blk.append_op("scale", {"X": [loss.name]}, {"Out": [loss.name]},
                      {"scale": 1.0 / DP, "bias": 0.0})
        blk.append_op("c_allreduce_sum", {"X": [loss.name]},
                      {"Out": [loss.name]}, {"axis_name": "dp"})
        shard_program(main, make_mesh({"dp": DP}, jax.devices()[:DP]),
                      {"x": ("dp",), "y": ("dp",)})
    return main, startup, scope, loss


def _run_steps(exe, prog, steps, first_feed=0):
    """Run `steps` steps on the return_numpy path (the one that publishes
    the perf.step_attribution sample); returns the loss trajectory."""
    import numpy as np

    main, _startup, scope, loss = prog
    losses = []
    for i in range(steps):
        (lv,) = exe.run(main, feed=_feed(first_feed + i),
                        fetch_list=[loss], scope=scope)
        losses.append(float(np.asarray(lv).reshape(-1)[0]))
    return losses


def _attribution_phase(exe, prog, steps):
    """Reset metrics, run a steady-state window, and return (losses,
    snapshot) — the snapshot carries this schedule's wait fractions.
    The collective.* counters advance at TRACE time (once per compiled
    site), so one uncached step re-traces the program inside the window
    to land them in the snapshot."""
    from paddle_tpu import observability

    main, _startup, scope, loss = prog
    observability.reset()
    exe.run(main, feed=_feed(0), fetch_list=[loss], scope=scope,
            use_program_cache=False)
    losses = _run_steps(exe, prog, steps)
    return losses, observability.snapshot()


def run(steps, dump, gate):
    import numpy as np

    import paddle_tpu as fluid
    from paddle_tpu import observability

    exe = fluid.Executor()
    serial = _build(False)
    overlap = _build(True)
    for prog in (serial, overlap):
        exe.run(prog[1], scope=prog[2])
        _run_steps(exe, prog, 1)  # compile carry

    # -- timing: interleaved rounds, medians per round -------------------
    rounds, per_round = 6, 5
    t_serial, t_overlap = [], []
    fidx = 1
    for _ in range(rounds):
        for prog, sink in ((serial, t_serial), (overlap, t_overlap)):
            dts = []
            for _ in range(per_round):
                t0 = time.perf_counter()
                _run_steps(exe, prog, 1, first_feed=fidx)
                dts.append(time.perf_counter() - t0)
                fidx += 1
            sink.append(float(np.median(dts)))
    step_serial = float(np.median(t_serial))
    step_overlap = float(np.median(t_overlap))
    speedup = step_serial / step_overlap if step_overlap else 0.0

    # -- parity: fp32 bitwise, int8 bitwise vs per-grad int8 -------------
    # fresh builds (fresh scopes) so both schedules see identical initial
    # params and feeds; the pairs are then reused for the attribution
    # windows below (already compiled, steady state)
    par_steps = max(3, min(steps, 6))
    serial2, overlap2 = _build(False), _build(True)
    q_ser, q_over = _build(False, quant="int8"), _build(True, quant="int8")
    for prog in (serial2, overlap2, q_ser, q_over):
        exe.run(prog[1], scope=prog[2])
    loss_serial = _run_steps(exe, serial2, par_steps)
    loss_overlap = _run_steps(exe, overlap2, par_steps)
    q_serial = _run_steps(exe, q_ser, par_steps)
    q_overlap = _run_steps(exe, q_over, par_steps)
    parity_fp32 = bool(np.array_equal(loss_serial, loss_overlap))
    parity_int8 = bool(np.array_equal(q_serial, q_overlap))
    int8_tolerance = bool(np.allclose(loss_serial, q_overlap,
                                      rtol=5e-2, atol=5e-2))

    # -- attribution: wait fraction before (serialized) / after ----------
    _, snap_serial = _attribution_phase(exe, serial2, steps)
    _, snap_overlap = _attribution_phase(exe, overlap2, steps)
    if dump:
        observability.dump(dump)  # the overlapped schedule's snapshot

    def _wait(snap):
        return float(
            snap["gauges"].get("perf.wait_fraction.collective", 0.0)
        )

    def _attr(snap):
        return (snap.get("tables") or {}).get("perf.step_attribution") or {}

    wait_serial, wait_overlap = _wait(snap_serial), _wait(snap_overlap)
    attr_o = _attr(snap_overlap)
    counters = snap_overlap.get("counters", {})
    overlap_ratio = float(
        snap_overlap["gauges"].get("collective.overlap_ratio", 0.0)
    )

    result = {
        "metric": "dp_overlap",
        "dp": DP,
        "model": {"batch": B, "width": H, "layers": L,
                  "bucket_bytes": BUCKET_BYTES},
        "step_ms_serialized": round(step_serial * 1e3, 3),
        "step_ms_overlapped": round(step_overlap * 1e3, 3),
        "overlap_speedup": round(speedup, 4),
        "loss_parity_fp32_bitwise": parity_fp32,
        "loss_parity_int8_bitwise": parity_int8,
        "int8_within_tolerance": int8_tolerance,
        "wait_fraction_collective_serialized": round(wait_serial, 4),
        "wait_fraction_collective_overlapped": round(wait_overlap, 4),
        "est_wait_fraction_overlapped": round(
            float(attr_o.get("est_wait_fraction", 0.0)), 4
        ),
        "est_wire_hidden_seconds": float(
            attr_o.get("est_wire_hidden_seconds", 0.0)
        ),
        "est_overlap_ratio": overlap_ratio,
        "collective_buckets": int(counters.get("collective.buckets", 0)),
        "collective_bucket_bytes": int(
            counters.get("collective.bucket_bytes", 0)
        ),
        "final_loss": {"serialized": loss_serial[-1],
                       "overlapped": loss_overlap[-1]},
    }
    failures = []
    if speedup < 1.0:
        failures.append(
            f"overlapped step {step_overlap * 1e3:.2f} ms slower than "
            f"serialized {step_serial * 1e3:.2f} ms (speedup {speedup:.3f})"
        )
    if not parity_fp32:
        failures.append("overlapped fp32 losses diverge from serialized")
    if not parity_int8:
        failures.append("overlapped int8 losses diverge from per-grad int8")
    if not int8_tolerance:
        failures.append("int8 overlapped losses out of PR-9 tolerance")
    if not wait_overlap < wait_serial:
        failures.append(
            f"wait_fraction.collective did not drop "
            f"({wait_serial:.4f} -> {wait_overlap:.4f})"
        )
    if not 0.0 < overlap_ratio <= 1.0:
        failures.append(
            f"collective.overlap_ratio={overlap_ratio} (no wire hidden)"
        )
    if result["collective_buckets"] <= 0:
        failures.append("no collective.buckets recorded")
    result["gate_failures"] = failures
    print(json.dumps(result))
    if failures and gate:
        print(f"overlap gates FAILED: {failures}", file=sys.stderr)
        return 1
    return 0


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--steps", type=int, default=6,
                    help="steps per attribution window")
    ap.add_argument("--dump", default=None,
                    help="write the overlapped schedule's observability "
                         "snapshot here")
    ap.add_argument("--no-gate", action="store_true",
                    help="report only, never fail the exit code")
    args = ap.parse_args(argv)
    if os.environ.get(_CHILD_ENV) != "1":
        return _respawn(
            ["--steps", str(args.steps)]
            + (["--dump", args.dump] if args.dump else [])
            + (["--no-gate"] if args.no_gate else [])
        )
    return run(args.steps, args.dump, gate=not args.no_gate)


if __name__ == "__main__":
    sys.exit(main())
