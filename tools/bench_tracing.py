#!/usr/bin/env python
"""Tracing overhead gate: tracing-on vs tracing-off step latency.

Causal tracing only earns default-on status if it is cheap enough to
leave on in production — the contract this bench enforces: the per-step
latency delta between full tracing (monitoring on, a fresh trace + root
span wrapped around every step, attribution gauges publishing) and the
kill-switch path (``set_enabled(False)``) must stay within ``--gate``
(default 2%) on a zoo model.

Methodology: the two modes run strictly INTERLEAVED (on, off, on, off
...) against the same warm executable, and the comparison is
median-vs-median — interleaving cancels thermal/load drift that would
otherwise dominate a 2% bar on a shared CPU CI host. The measurement
repeats up to ``--rounds`` times and passes if ANY round meets the gate
(one round is one fair sample; re-measuring on miss filters scheduler
noise, not real overhead — a true >2% cost fails all rounds).

Prints one JSON line (bench.py convention); exits non-zero on gate miss.
"""

from __future__ import annotations

import argparse
import json
import os
import statistics
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _feed_for(bm, seed=0):
    import numpy as np

    from paddle_tpu.core.dtypes import to_numpy_dtype

    rng = np.random.RandomState(seed)
    feed = {}
    blk = bm.main.global_block
    for n in bm.feed_names:
        v = blk.var(n)
        shape = tuple(int(d) if d not in (-1, None) else 4 for d in v.shape)
        dt = np.dtype(to_numpy_dtype(v.dtype or "float32"))
        if np.issubdtype(dt, np.integer):
            feed[n] = rng.randint(0, 3, shape).astype(dt)
        else:
            feed[n] = rng.rand(*shape).astype(dt)
    return feed


def measure_round(exe, bm, feed, scope, steps):
    """One interleaved round; returns (median_on_s, median_off_s,
    median pairwise delta). Each iteration measures one ON step and one
    OFF step back to back, so the per-pair delta is drift-free; the
    median over pairs is the overhead estimator (a mean would let one
    scheduler preemption swing the whole round)."""
    from paddle_tpu import observability as obs

    on, off = [], []
    fetch = list(bm.fetch_names)

    def step_on(i):
        # ON: the full production tracing surface — fresh trace, root
        # span, span/metric writes inside the executor, attribution
        obs.set_enabled(True)
        t0 = time.perf_counter()
        with obs.activate(obs.new_trace()), \
                obs.span("bench.step", step=i):
            exe.run(bm.main, feed=feed, fetch_list=fetch, scope=scope)
        on.append(time.perf_counter() - t0)

    def step_off(i):
        # OFF: the kill-switch path
        obs.set_enabled(False)
        t0 = time.perf_counter()
        exe.run(bm.main, feed=feed, fetch_list=fetch, scope=scope)
        off.append(time.perf_counter() - t0)

    for i in range(steps):
        # alternate which mode runs first within the pair: a fixed order
        # would fold any systematic first-vs-second cost (allocator /
        # cache warmth) into the on-off delta as fake overhead
        first, second = (step_on, step_off) if i % 2 == 0 else (
            step_off, step_on)
        first(i)
        second(i)
    obs.set_enabled(True)
    delta = statistics.median(a - b for a, b in zip(on, off))
    return statistics.median(on), statistics.median(off), delta


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--model", default="bert",
                    help="zoo model to step (default bert)")
    ap.add_argument("--steps", type=int, default=40,
                    help="interleaved step pairs per round (default 40)")
    ap.add_argument("--rounds", type=int, default=5,
                    help="measurement rounds; best round gates (default 5)")
    ap.add_argument("--gate", type=float, default=0.02,
                    help="max allowed relative overhead (default 0.02)")
    ap.add_argument("--smoke", action="store_true",
                    help="CI-sized run (fewer steps)")
    ap.add_argument("--dump", default=None,
                    help="write the observability snapshot JSON here")
    ap.add_argument("--no-gate", action="store_true",
                    help="report only, never fail the exit code")
    args = ap.parse_args(argv)
    steps = 32 if args.smoke else args.steps

    _flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in _flags:
        os.environ["XLA_FLAGS"] = (
            _flags + " --xla_force_host_platform_device_count=8"
        ).strip()
    import jax

    jax.config.update("jax_platforms", "cpu")

    import paddle_tpu as fluid
    from paddle_tpu import observability as obs
    from paddle_tpu.framework.scope import Scope
    from paddle_tpu.models import build_model

    bm = build_model(args.model, with_mesh=False)
    exe = fluid.Executor()
    scope = Scope()
    exe.run(bm.startup, scope=scope)
    feed = _feed_for(bm)
    fetch = list(bm.fetch_names)
    for _ in range(3):  # warm the executable + estimate off the clock
        exe.run(bm.main, feed=feed, fetch_list=fetch, scope=scope)

    rounds = []
    best = None
    for r in range(max(1, args.rounds)):
        med_on, med_off, delta = measure_round(exe, bm, feed, scope, steps)
        overhead = delta / med_off if med_off > 0 else 0.0
        rounds.append({
            "median_on_ms": round(med_on * 1e3, 4),
            "median_off_ms": round(med_off * 1e3, 4),
            "median_pair_delta_ms": round(delta * 1e3, 5),
            "overhead": round(overhead, 5),
        })
        if best is None or overhead < best:
            best = overhead
        if overhead <= args.gate:
            break
    ok = best is not None and best <= args.gate
    if args.dump:
        obs.dump(args.dump)
    result = {
        "metric": "tracing_overhead",
        "model": args.model,
        "steps_per_round": steps,
        "rounds": rounds,
        "overhead": round(best, 5),
        "gate": args.gate,
        "gate_ok": ok,
    }
    print(json.dumps(result))
    if not ok and not args.no_gate:
        print(
            f"tracing overhead gate FAILED: best {best:.2%} > "
            f"{args.gate:.0%} across {len(rounds)} round(s)",
            file=sys.stderr,
        )
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
