#!/usr/bin/env python
"""Reconstruct causal traces from per-rank Chrome span exports.

The observability layer stamps every span recorded under an active
TraceContext with ``trace_id`` / ``span_id`` / ``parent_id`` (ride-along
in each "X" event's args — see paddle_tpu/observability/trace.py), so the
causal tree is reconstructible from export files ALONE: no live process,
no jax. Feed it one file per rank (``observability.save_chrome_trace``)
or a merged pod trace (``perf_report.py --merge`` output):

    python tools/trace_report.py trace_rank0.json trace_rank1.json

Per trace it prints the span tree (indent = causality, not wall order),
thread/rank fan-out, and a per-category time rollup; the last line is a
machine-readable JSON stats summary.

CI modes:

* ``--check`` — exit non-zero unless at least ``--min-traces`` COMPLETE
  traces exist that span at least ``--min-threads`` distinct threads
  (complete = has a root and every parent_id resolves inside the trace;
  an orphan span means a broken handoff or a parent lost to the ring
  buffer). ``--require-span NAME`` (repeatable) additionally demands a
  qualifying trace contain the named span.
* ``--broken-fixture`` — self-test: runs the checker over a seeded trace
  with an orphan span; the exit status MUST be non-zero (ci.sh asserts
  the checker still catches broken traces).
"""

from __future__ import annotations

import argparse
import json
import os
import re
import sys

_RANK_RE = re.compile(r"rank[_-]?(\d+)")


def _rank_of(path, position):
    m = _RANK_RE.search(os.path.basename(path))
    return int(m.group(1)) if m else position


def load_spans(paths):
    """Traced spans from chrome-trace export files.

    Returns a list of dicts with name/cat/ts/dur/trace_id/span_id/
    parent_id/thread, where ``thread`` is a (rank, pid, tid) triple —
    distinct triples are distinct execution threads. Untraced spans
    (no trace_id) are skipped: they are the flat legacy view."""
    spans = []
    for i, path in enumerate(paths):
        rank = _rank_of(path, i)
        with open(path) as f:
            trace = json.load(f)
        events = trace.get("traceEvents", trace)
        for e in events:
            if e.get("ph") != "X":
                continue
            args = e.get("args") or {}
            if "trace_id" not in args:
                continue
            spans.append({
                "name": e.get("name", "?"),
                "cat": e.get("cat", ""),
                "ts": float(e.get("ts", 0.0)),
                "dur": float(e.get("dur", 0.0)),
                "trace_id": args["trace_id"],
                "span_id": args.get("span_id"),
                "parent_id": args.get("parent_id"),
                # a merged pod trace carries rank as pid; per-rank export
                # files carry it in the filename
                "rank": e.get("pid", rank) if len(paths) == 1 else rank,
                "thread": (rank, e.get("pid", 0), e.get("tid", 0)),
            })
    return spans


def build_traces(spans):
    """Group spans into traces and judge completeness."""
    traces = {}
    for s in spans:
        traces.setdefault(s["trace_id"], []).append(s)
    out = []
    for tid, ss in traces.items():
        ids = {s["span_id"] for s in ss if s["span_id"]}
        roots = [s for s in ss if not s["parent_id"]]
        orphans = [
            s for s in ss
            if s["parent_id"] and s["parent_id"] not in ids
        ]
        threads = {s["thread"] for s in ss}
        ranks = {s["rank"] for s in ss}
        t0 = min(s["ts"] for s in ss)
        t1 = max(s["ts"] + s["dur"] for s in ss)
        out.append({
            "trace_id": tid,
            "spans": sorted(ss, key=lambda s: s["ts"]),
            "roots": roots,
            "orphans": orphans,
            "complete": bool(roots) and not orphans,
            "threads": threads,
            "ranks": ranks,
            "wall_us": t1 - t0,
        })
    # widest traces first: the interesting ones for a human
    out.sort(key=lambda t: (-len(t["threads"]), -len(t["spans"])))
    return out


def _print_tree(trace, max_spans=40):
    children = {}
    for s in trace["spans"]:
        children.setdefault(s["parent_id"], []).append(s)

    lines = []

    def walk(span, depth):
        if len(lines) >= max_spans:
            return
        lines.append(
            f"  {'  ' * depth}{span['name']:<28} "
            f"{span['dur'] / 1e3:>9.3f} ms  "
            f"[rank {span['rank']} tid {span['thread'][2]}]"
        )
        for c in sorted(children.get(span["span_id"], []),
                        key=lambda s: s["ts"]):
            walk(c, depth + 1)

    for root in sorted(trace["roots"], key=lambda s: s["ts"]):
        walk(root, 0)
    for line in lines:
        print(line)
    n = len(trace["spans"])
    if n > max_spans:
        print(f"  ... ({n - max_spans} more spans)")
    for o in trace["orphans"][:5]:
        print(f"  ORPHAN {o['name']} (parent {o['parent_id']} missing)")


def _category_rollup(trace):
    cats = {}
    for s in trace["spans"]:
        cats[s["cat"]] = cats.get(s["cat"], 0.0) + s["dur"]
    return {c: round(d / 1e3, 3) for c, d in
            sorted(cats.items(), key=lambda kv: -kv[1])}


def report(paths, check=False, min_threads=2, min_traces=1,
           require_spans=(), top=5, quiet=False):
    spans = load_spans(paths)
    traces = build_traces(spans)
    qualifying = []
    for t in traces:
        if not t["complete"] or len(t["threads"]) < min_threads:
            continue
        names = {s["name"] for s in t["spans"]}
        if any(r not in names for r in require_spans):
            continue
        qualifying.append(t)
    if not quiet:
        for t in traces[:top]:
            mark = "complete" if t["complete"] else (
                f"INCOMPLETE ({len(t['orphans'])} orphans)"
                if t["orphans"] else "INCOMPLETE (no root)"
            )
            print(
                f"== trace {t['trace_id']}: {len(t['spans'])} spans, "
                f"{len(t['threads'])} thread(s), {len(t['ranks'])} "
                f"rank(s), {t['wall_us'] / 1e3:.3f} ms [{mark}] =="
            )
            _print_tree(t)
            print(f"  by category (ms): {_category_rollup(t)}")
        if len(traces) > top:
            print(f"... ({len(traces) - top} more traces)")
    stats = {
        "files": len(paths),
        "traced_spans": len(spans),
        "traces": len(traces),
        "complete_traces": sum(1 for t in traces if t["complete"]),
        "orphan_spans": sum(len(t["orphans"]) for t in traces),
        "max_threads": max((len(t["threads"]) for t in traces), default=0),
        "cross_thread_traces": sum(
            1 for t in traces if len(t["threads"]) > 1
        ),
        "cross_rank_traces": sum(1 for t in traces if len(t["ranks"]) > 1),
        "qualifying_traces": len(qualifying),
        "min_threads": min_threads,
    }
    print(json.dumps(stats))
    if check and len(qualifying) < min_traces:
        print(
            f"CHECK FAILED: {len(qualifying)} complete trace(s) spanning "
            f">= {min_threads} threads"
            + (f" containing {list(require_spans)}" if require_spans
               else "")
            + f", need {min_traces}",
            file=sys.stderr,
        )
        return 3
    return 0


def _broken_fixture(tmpdir):
    """A seeded export whose only trace has an orphan span (its parent was
    never exported — the exact signature of a broken thread handoff)."""
    events = [
        {"ph": "X", "name": "train.step", "cat": "host", "ts": 1000.0,
         "dur": 5000.0, "pid": 0, "tid": 0,
         "args": {"trace_id": "t1", "span_id": "a"}},
        {"ph": "X", "name": "checkpoint.publish", "cat": "checkpoint",
         "ts": 2000.0, "dur": 1000.0, "pid": 0, "tid": 1,
         "args": {"trace_id": "t1", "span_id": "c",
                  "parent_id": "DEAD-NEVER-EXPORTED"}},
    ]
    path = os.path.join(tmpdir, "broken_trace_rank0.json")
    with open(path, "w") as f:
        json.dump({"traceEvents": events}, f)
    return path


def main(argv=None):
    ap = argparse.ArgumentParser(
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    ap.add_argument("traces", nargs="*", metavar="TRACE.json",
                    help="chrome span export files (one per rank, or one "
                         "merged pod trace)")
    ap.add_argument("--check", action="store_true",
                    help="exit non-zero unless the completeness bar holds")
    ap.add_argument("--min-threads", type=int, default=2,
                    help="threads a qualifying trace must span (default 2)")
    ap.add_argument("--min-traces", type=int, default=1,
                    help="qualifying traces --check requires (default 1)")
    ap.add_argument("--require-span", action="append", default=[],
                    metavar="NAME",
                    help="qualifying traces must contain this span "
                         "(repeatable)")
    ap.add_argument("--top", type=int, default=5,
                    help="traces to print trees for (default 5)")
    ap.add_argument("--quiet", action="store_true",
                    help="stats JSON only, no trees")
    ap.add_argument("--broken-fixture", action="store_true",
                    help="self-test: check a seeded orphan-span export "
                         "(must exit non-zero)")
    args = ap.parse_args(argv)

    if args.broken_fixture:
        import tempfile

        with tempfile.TemporaryDirectory() as td:
            return report(
                [_broken_fixture(td)], check=True,
                min_threads=2, min_traces=1, quiet=True,
            )
    if not args.traces:
        ap.error("pass trace export files (or --broken-fixture)")
    return report(
        args.traces, check=args.check, min_threads=args.min_threads,
        min_traces=args.min_traces, require_spans=args.require_span,
        top=args.top, quiet=args.quiet,
    )


if __name__ == "__main__":
    sys.exit(main())
