#!/usr/bin/env python
"""Async checkpoint bench: measure the save stall coming OFF the step
loop, and delta shards cutting repeat-save bytes on an embedding-cached
model. Self-gating (BASELINE.md r12 acceptance):

* **stall leg** — a model with checkpoint-heavy persistables trains
  while checkpointing synchronously vs through fleet.AsyncCheckpointer.
  Step-time jitter during a save (save-step wall minus the median plain
  step) must drop >= 10x async vs sync: the async step loop pays only
  the device→host snapshot, while serialize/CRC/fsync/publish/verify
  run on the publisher thread.
* **delta leg** — the fused DeepFM with the hot-tier cache checkpoints
  twice through the async pipeline (delta=True, compressed, row oracles
  keyed off the cache's write-back ticks): the second (delta) checkpoint
  dir must be <= 60% of the full save's bytes, and the delta-chain
  reload must be bitwise identical to the live state.

Usage: python tools/bench_async_checkpoint.py [--smoke] [--dump OUT.json]
Exit 0 only if every gate holds.
"""

import argparse
import os
import shutil
import sys
import tempfile
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def _du(path):
    from paddle_tpu.fleet.collective import _dir_bytes

    return _dir_bytes(path)


def bench_stall(work, ballast_mb, steps, save_every):
    """Sync-vs-async save stall on one model; returns the gate dict."""
    import paddle_tpu as fluid
    from paddle_tpu import layers
    from paddle_tpu.fleet import collective as fc
    from paddle_tpu.fleet.role_maker import UserDefinedRoleMaker
    from paddle_tpu.framework import unique_name
    from paddle_tpu.framework.scope import Scope, scope_guard

    rows = int(ballast_mb * 1024 * 1024 / (64 * 4))
    rng = np.random.RandomState(0)
    fleet = fc.Fleet()
    fleet.init(UserDefinedRoleMaker())

    def build():
        main, startup = fluid.Program(), fluid.Program()
        main.random_seed = startup.random_seed = 7
        scope = Scope()
        with fluid.program_guard(main, startup), unique_name.guard():
            x = fluid.data("x", [-1, 16])
            y = fluid.data("y", [-1, 1])
            pred = layers.fc(x, 1)
            loss = layers.mean(layers.square_error_cost(pred, y))
            fluid.optimizer.SGD(0.05).minimize(loss)
            # checkpoint-heavy state that is NOT touched every step —
            # embedding-table-shaped ballast the save must still move
            main.global_block.create_parameter(
                "ck_ballast", [rows, 64], "float32"
            )
        with scope_guard(scope):
            fluid.Executor().run(startup, scope=scope)
        scope.set_var(
            "ck_ballast",
            rng.randn(rows, 64).astype(np.float32),
        )
        return main, scope, loss

    def run(mode, path):
        main, scope, loss = build()
        exe = fluid.Executor()
        saver = None
        if mode == "async":
            saver = fc.AsyncCheckpointer(
                fleet, path, executor=exe, main_program=main, scope=scope,
                remain_all_checkpoint=True,
            )
        plain, stalls = [], []
        with scope_guard(scope):
            for i in range(steps):
                xa = rng.randn(64, 16).astype(np.float32)
                feed = {"x": xa, "y": xa @ np.ones((16, 1), np.float32)}
                t0 = time.perf_counter()
                exe.run(main, feed=feed, fetch_list=[loss], scope=scope)
                if (i + 1) % save_every == 0:
                    st = fc.TrainStatus(0, global_step=i + 1)
                    if saver is not None:
                        saver.save(st)
                    else:
                        fleet.save_check_point(
                            exe, path, st, main_program=main,
                            remain_all_checkpoint=True,
                        )
                    stalls.append(time.perf_counter() - t0)
                else:
                    plain.append(time.perf_counter() - t0)
        if saver is not None:
            saver.wait()
            saver.close()
        base = float(np.median(plain))
        jitter = [max(0.0, s - base) for s in stalls]
        return base, float(np.median(jitter))

    sync_base, sync_jitter = run("sync", os.path.join(work, "sync_ck"))
    async_base, async_jitter = run("async", os.path.join(work, "async_ck"))
    ratio = sync_jitter / max(async_jitter, 1e-9)
    print(f"stall leg: plain step ~{sync_base * 1e3:.1f} ms; save-step "
          f"jitter sync {sync_jitter * 1e3:.1f} ms vs async "
          f"{async_jitter * 1e3:.1f} ms -> {ratio:.1f}x reduction "
          f"({ballast_mb} MB checkpoint payload)")
    # the async leg's committed checkpoint must be loadable
    import paddle_tpu as fluid_mod  # noqa: F401

    status = fleet.load_check_point(
        fluid.Executor(), os.path.join(work, "async_ck")
    )
    assert status.global_step > 0, status
    return {
        "payload_mb": ballast_mb,
        "sync_jitter_ms": sync_jitter * 1e3,
        "async_jitter_ms": async_jitter * 1e3,
        "jitter_reduction": ratio,
        "gate": ratio >= 10.0,
    }


def bench_delta(work, vocab, steps):
    """Repeat-save bytes on an embedding-cached model: full vs delta."""
    import paddle_tpu as fluid
    from paddle_tpu.embedding import EmbeddingEngine, fuse_lookups
    from paddle_tpu.fleet import collective as fc
    from paddle_tpu.fleet.role_maker import UserDefinedRoleMaker
    from paddle_tpu.framework import unique_name
    from paddle_tpu.framework.scope import Scope, scope_guard
    from paddle_tpu.models.deepfm import DeepFMConfig, deepfm

    cfg = DeepFMConfig(vocab_size=vocab, num_fields=4, embed_dim=16,
                       mlp_sizes=(16,))
    b = 16
    rng = np.random.RandomState(5)
    fleet = fc.Fleet()
    fleet.init(UserDefinedRoleMaker())
    path = os.path.join(work, "delta_ck")

    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 7
    scope = Scope()
    with fluid.program_guard(main, startup), unique_name.guard():
        ids = fluid.data("feat_ids", [b, cfg.num_fields], "int64")
        label = fluid.data("label", [b, 1], "float32")
        loss, _p = deepfm(ids, label, cfg, per_slot=True)
        fuse_lookups(main)
        engine = EmbeddingEngine(main, startup, hot_rows=cfg.vocab_size // 16)
        fluid.optimizer.Momentum(0.05, 0.9).minimize(loss)
    exe = fluid.Executor()
    with scope_guard(scope):
        exe.run(startup, scope=scope)
        engine.attach(scope)

        saver = fc.AsyncCheckpointer(
            fleet, path, executor=exe, main_program=main, scope=scope,
            delta=True, full_every=8, compress=True, queue_policy="block",
            remain_all_checkpoint=True,
            row_oracles=engine.delta_row_oracles(),
        )

        def train(n):
            for _ in range(n):
                idv = (cfg.vocab_size * rng.power(0.4, (b, cfg.num_fields)))
                idv = idv.astype(np.int64)
                feed = engine.prepare_feed(
                    {"feat_ids": idv,
                     "label": (idv[:, :1] % 2 == 0).astype(np.float32)},
                    scope,
                )
                exe.run(main, feed=feed, fetch_list=[loss], scope=scope)

        train(steps)
        saver.save(fc.TrainStatus(0, global_step=steps),
                   aux=engine.state_dict(scope)).result(timeout=300)
        train(steps)
        saver.save(fc.TrainStatus(0, global_step=2 * steps),
                   aux=engine.state_dict(scope)).result(timeout=300)
        saver.close()
        live_aux = engine.state_dict(scope)
        live_scope = {
            v.name: np.asarray(scope.find_var(v.name)).copy()
            for v in main.list_vars()
            if v.persistable and scope.find_var(v.name) is not None
        }

    full_b = _du(os.path.join(path, "__paddle_checkpoint__0"))
    delta_b = _du(os.path.join(path, "__paddle_checkpoint__1"))
    ratio = delta_b / full_b
    print(f"delta leg: vocab {vocab} hot {cfg.vocab_size // 16}; full save "
          f"{full_b / 1e3:.1f} KB -> repeat (delta) save "
          f"{delta_b / 1e3:.1f} KB ({ratio:.0%}), compressed, row deltas "
          "keyed off cache write-back ticks")

    # chain reload must be bitwise identical to the live state
    scope2 = Scope()
    with scope_guard(scope2):
        exe.run(startup, scope=scope2)
        engine.attach(scope2)
        status = fleet.load_check_point(
            exe, path, main_program=main, load_aux=True
        )
        engine.load_state_dict(status.aux, scope2)
        for name, want in live_aux.items():
            got = status.aux[name]
            assert np.asarray(got).tobytes() == want.tobytes(), name
        for name, want in live_scope.items():
            got = np.asarray(scope2.find_var(name))
            assert got.tobytes() == want.tobytes(), name
    print("delta leg: chain reload (full + 1 delta) bitwise == live state")
    return {
        "full_bytes": full_b,
        "delta_bytes": delta_b,
        "delta_ratio": ratio,
        "gate": ratio <= 0.6,
    }


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--smoke", action="store_true",
                    help="CI-sized payloads (smaller ballast/vocab)")
    ap.add_argument("--dump", default=None,
                    help="write the observability snapshot JSON here")
    ap.add_argument("--keep", action="store_true")
    args = ap.parse_args(argv)

    ballast_mb = 24 if args.smoke else 96
    vocab = 8192 if args.smoke else 65536
    work = tempfile.mkdtemp(prefix="paddle_tpu_async_ck_bench_")
    try:
        stall = bench_stall(work, ballast_mb, steps=12, save_every=4)
        delta = bench_delta(work, vocab, steps=4)
        from paddle_tpu import observability

        if args.dump:
            observability.dump(args.dump)
        ok = stall["gate"] and delta["gate"]
        print(f"gates: jitter reduction {stall['jitter_reduction']:.1f}x "
              f"(need >= 10), repeat-save ratio {delta['delta_ratio']:.0%} "
              f"(need <= 60%) -> {'OK' if ok else 'FAIL'}")
        return 0 if ok else 1
    finally:
        if not args.keep:
            shutil.rmtree(work, ignore_errors=True)


if __name__ == "__main__":
    sys.exit(main())
