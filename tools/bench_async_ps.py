#!/usr/bin/env python
"""Async vs sync parameter-server throughput (VERDICT r3 item 6).

Trains the same DeepFM config through the ParameterServerFleet in "sync"
mode (every step waits for the table apply) and "async" mode (the
AsyncCommunicator queues merged applies on a host thread, reference
operators/distributed/communicator.h:237 AsyncCommunicator), and prints
steps/sec for each plus the async/sync ratio as one JSON line.

Runs on the CPU backend (the PS data plane is host-side either way);
launch with the same env as pytest for the 8-device virtual mesh. The
async win here is pipelining: train_step returns as soon as the gradient
is queued, so the (deliberately slowed) apply overlaps the next step.
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")


def main():
    import jax

    jax.config.update("jax_platforms", "cpu")

    import numpy as np

    import paddle_tpu as fluid
    from paddle_tpu.framework import unique_name
    from paddle_tpu.fleet import parameter_server as ps
    from paddle_tpu.models.deepfm import DeepFMConfig, deepfm

    cfg = DeepFMConfig(vocab_size=4096, num_fields=8, embed_dim=16,
                       mlp_sizes=(64, 32))
    b, steps = 256, 120

    rng = np.random.RandomState(0)
    feeds = []
    for _ in range(8):
        idv = rng.randint(0, cfg.vocab_size, (b, cfg.num_fields))
        lab = (idv[:, :1] % 2 == 0).astype(np.float32)
        feeds.append({"feat_ids": idv.astype(np.int64), "label": lab})

    results = {}
    for mode in ("sync", "async"):
        main_prog, startup = fluid.Program(), fluid.Program()
        main_prog.random_seed = startup.random_seed = 11
        scope = fluid.framework.scope.Scope()
        with fluid.program_guard(main_prog, startup), \
                fluid.scope_guard(scope), unique_name.guard():
            ids = fluid.data("feat_ids", [b, cfg.num_fields], "int64")
            label = fluid.data("label", [b, 1], "float32")
            loss, _ = deepfm(ids, label, cfg)
            fleet = ps.ParameterServerFleet().init()
            strategy = ps.DistributedStrategy(
                mode, send_queue_size=8, merge_size=4
            )
            opt = fleet.distributed_optimizer(
                fluid.optimizer.SGD(0.1), strategy
            )
            opt.minimize(loss)
            exe = fluid.Executor()
            exe.run(startup, scope=scope)
            comm = fleet.init_worker(scope=scope, exe=exe, lr=0.1)

            def one(i):
                f = feeds[i % len(feeds)]
                if comm is not None and hasattr(comm, "train_step"):
                    (lv,) = comm.train_step(exe, main_prog, f, [loss],
                                            scope=scope)
                else:
                    (lv,) = exe.run(main_prog, feed=f, fetch_list=[loss],
                                    scope=scope)
                return lv

            for i in range(5):
                one(i)
            t0 = time.perf_counter()
            for i in range(steps):
                lv = one(i)
            final = float(np.asarray(lv).reshape(-1)[0])
            dt = time.perf_counter() - t0
            fleet.stop_worker()
        results[mode] = {
            "steps_per_sec": round(steps / dt, 2),
            "final_loss": round(final, 4),
        }
    results["async_over_sync"] = round(
        results["async"]["steps_per_sec"] / results["sync"]["steps_per_sec"],
        3,
    )
    print(json.dumps(results))


if __name__ == "__main__":
    sys.exit(main())
