"""Measure per-device parameter/optimizer-state bytes across the composed
parallelism stack (the BASELINE.md bytes/device table).

Builds the SAME BERT pretrain step under each strategy stack on an
8-device virtual CPU mesh and sums the actual per-device shard bytes of
every persistable after one training step — measured, not estimated.
Usage: JAX_PLATFORMS=cpu python tools/bytes_per_device_3d.py
"""

import os

os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
os.environ["JAX_PLATFORMS"] = "cpu"

import numpy as np  # noqa: E402

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import paddle_tpu as fluid  # noqa: E402
from paddle_tpu.framework.scope import Scope  # noqa: E402
from paddle_tpu.models import BertConfig  # noqa: E402
from paddle_tpu.models.bert_3d import (bert_3d_shardings, build_bert_3d,  # noqa: E402
                                       example_feed_3d)
from paddle_tpu.parallel import make_mesh, shard_program  # noqa: E402


def bytes_per_device(scope):
    per = {}
    for name in scope.local_var_names():
        v = scope.find_var(name)
        if not hasattr(v, "addressable_shards"):
            continue
        for sh in v.addressable_shards:
            per[sh.device] = per.get(sh.device, 0) + sh.data.nbytes
    return per


def run(cfg, b, s, dp, mp, pp, label):
    main, startup, loss = build_bert_3d(
        cfg, b // dp, s, num_stages=pp, microbatches=2, dp=dp,
    )
    axes = {}
    if dp > 1:
        axes["dp"] = dp
    if mp > 1:
        axes["mp"] = mp
    if pp > 1:
        axes["pp"] = pp
    if not axes:
        axes = {"dp": 1}
    n = 1
    for v in axes.values():
        n *= v
    mesh = make_mesh(axes, jax.devices()[:n])
    sh = bert_3d_shardings(cfg, num_stages=pp if pp > 1 else None)
    sh = {
        k: tuple(a if (a is None or a in axes) else None for a in v)
        for k, v in sh.items()
    }
    shard_program(main, mesh, sh, mode="hybrid",
                  manual_axes=tuple(a for a in ("dp", "pp") if a in axes))
    exe = fluid.Executor()
    scope = Scope()
    exe.run(startup, scope=scope)
    feed = example_feed_3d(cfg, b, s)
    (lv,) = exe.run(main, feed=feed, fetch_list=[loss], scope=scope)
    assert np.isfinite(float(np.asarray(lv).reshape(-1)[0]))
    per = bytes_per_device(scope)
    mx = max(per.values())
    print(f"| {label} | {n} | {mx / 1e6:.1f} MB |")
    return mx


def main():
    cfg = BertConfig(
        vocab_size=8192, hidden_size=512, num_layers=8, num_heads=8,
        intermediate_size=2048, max_position=512,
        hidden_dropout=0.0, attention_dropout=0.0,
    )
    b, s = 16, 128
    n_params = (
        cfg.vocab_size * cfg.hidden_size * 2  # word emb + mlm head
        + cfg.max_position * cfg.hidden_size
        + cfg.num_layers * (
            4 * cfg.hidden_size * cfg.hidden_size
            + 2 * cfg.hidden_size * cfg.intermediate_size
        )
    )
    print(f"model ~{n_params / 1e6:.1f}M params; fp32 param+2×Adam moments "
          f"= {n_params * 12 / 1e6:.0f} MB unsharded")
    print("| strategy | devices | max persistable bytes/device |")
    print("|---|---|---|")
    base = run(cfg, b, s, dp=8, mp=1, pp=1, label="dp8 (replicated params)")
    m1 = run(cfg, b, s, dp=2, mp=4, pp=1, label="dp2 × mp4 (Megatron TP)")
    m2 = run(cfg, b, s, dp=2, mp=2, pp=2,
             label="dp2 × mp2 × pp2 (uniform pipeline, stacked stages)")
    m3 = run(cfg, b, s, dp=1, mp=4, pp=2, label="mp4 × pp2")
    print(f"shrink vs replicated: mp4 {base / m1:.2f}x, "
          f"2x2x2 {base / m2:.2f}x, mp4xpp2 {base / m3:.2f}x")


if __name__ == "__main__":
    main()
