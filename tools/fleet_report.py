#!/usr/bin/env python
"""Merge per-process telemetry journals into a fleet-wide report.

Usage:
    python tools/fleet_report.py TELEMETRY_DIR [--bin 1.0] [--json]
    python tools/fleet_report.py DIR --expect-ranks 2 --out fleet.json

Reads every ``telemetry_rank*.jsonl`` shard a
``observability.timeline.TelemetryPublisher`` wrote under TELEMETRY_DIR
(dead writers included — the whole point: a SIGKILLed rank's journal
replays offline) and reconstructs:

* per-rank final state: last step counter, last journal seq/time, total
  goodput — the "what was rank K doing when it died" answer;
* fleet time series, binned at ``--bin`` seconds: summed request/goodput
  QPS, per-rank step-time curves (mean step latency per journal window),
  and the cross-process p99 rebuilt by merging per-shard histogram
  bucket deltas (``metrics.window_p99`` over
  ``metrics.merge_cumulative_buckets`` — the same helpers the live
  watcher uses, so offline and online answers agree);
* straggler gaps: the per-rank last-step spread;
* the storage digest: per-root free bytes at last journal stamp, the
  pressure-level timeline (every ``storage.pressure`` gauge move), GC
  reclaim totals and the journaled ``storage.gc`` action table — the
  offline answer to "was the fleet running out of disk, and did GC keep
  up".

``--expect-ranks N`` exits non-zero unless at least N shards were found
and replayed (the CI guard that a dead rank's journal survived);
``--json`` prints the machine-readable report on stdout instead of the
human rendering (``--out`` writes it to a file either way).
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from paddle_tpu.observability import metrics, timeline  # noqa: E402

STEP_COUNTERS = ("guard.steps", "executor.run_steps")


def _noncum(h):
    """Raw replay-state histogram -> (bounds, per-bucket counts incl +Inf)."""
    return list(h["bounds"]), list(h["counts"])


def analyze_shard(path, step_metric="executor.step_latency",
                  latency_metric="serving.request_latency"):
    """Replay one shard into (summary, per-record points)."""
    st = timeline.ReplayState()
    points = []
    prev = {"served": 0, "goodput": 0, "sl_count": 0, "sl_sum": 0.0,
            "lat": None, "pressure": None}
    paths = ([path + ".1"] if os.path.exists(path + ".1") else []) + [path]
    n_records = 0
    for p in paths:
        for rec in timeline.read_records(p):
            st.apply(rec)
            n_records += 1
            c = st.state["counters"]
            point = {"t": rec.get("t")}
            served = c.get("serving.requests_served", 0)
            goodput = c.get("serving.goodput", 0)
            point["served"] = served - prev["served"]
            point["goodput"] = goodput - prev["goodput"]
            prev["served"], prev["goodput"] = served, goodput
            sl = st.state["hists"].get(step_metric)
            if sl is not None:
                d_count = sl["count"] - prev["sl_count"]
                d_sum = sl["sum"] - prev["sl_sum"]
                prev["sl_count"], prev["sl_sum"] = sl["count"], sl["sum"]
                if d_count > 0:
                    point["steps"] = d_count
                    point["step_mean_s"] = d_sum / d_count
            lat = st.state["hists"].get(latency_metric)
            if lat is not None:
                bounds, counts = _noncum(lat)
                pl = prev["lat"]
                if pl is not None and pl[0] == bounds:
                    deltas = [a - b for a, b in zip(counts, pl[1])]
                else:
                    deltas = counts
                prev["lat"] = (bounds, counts)
                if any(deltas):
                    point["lat_bounds"] = bounds
                    point["lat_deltas"] = deltas
            pressure = st.state["gauges"].get("storage.pressure")
            if pressure is not None and pressure != prev["pressure"]:
                # every gauge MOVE is one timeline event — the offline
                # reconstruction of the ladder's escalations/recoveries
                prev["pressure"] = pressure
                point["pressure"] = int(pressure)
            points.append(point)
    counters = st.state["counters"]
    last_step = None
    for name in STEP_COUNTERS:
        if name in counters:
            last_step = counters[name]
            break
    summary = {
        "path": path,
        "rank": st.meta.get("rank"),
        "pid": st.meta.get("pid"),
        "records": n_records,
        "last_seq": st.meta.get("seq"),
        "last_t": st.meta.get("t"),
        "last_step": last_step,
        "goodput": counters.get("serving.goodput", 0),
        "requests_served": counters.get("serving.requests_served", 0),
    }
    gauges = st.state.get("gauges", {})
    version = gauges.get("serving.model_version")
    if version is not None:
        # the live-publish plane stamps these gauges into every journal
        # record: the report can show which model version each process
        # serves — and how far behind the freshest one it runs
        summary["model_version"] = int(version)
        stale = gauges.get("serving.model_staleness_seconds")
        if stale is not None:
            summary["model_staleness_s"] = float(stale)
    free = {
        name[len("storage.free_bytes."):]: int(val)
        for name, val in gauges.items()
        if name.startswith("storage.free_bytes.")
    }
    if free or "storage.pressure" in gauges:
        storage = {"free_bytes": free}
        if "storage.pressure" in gauges:
            storage["pressure"] = int(gauges["storage.pressure"])
        for c in ("storage.gc_bytes_freed", "storage.escalations",
                  "storage.recoveries", "storage.writes_refused"):
            if c in counters:
                storage[c.split(".", 1)[1]] = counters[c]
        gc_table = (st.state.get("tables", {}).get("storage.gc") or {})
        if gc_table.get("actions"):
            storage["gc_actions"] = gc_table["actions"]
        summary["storage"] = storage
    return summary, points, st


def _binned(shards_points, bin_s):
    """Merge every shard's per-record points into time bins."""
    bins = {}
    for points in shards_points:
        for pt in points:
            if pt.get("t") is None:
                continue
            key = int(pt["t"] // bin_s)
            b = bins.setdefault(key, {
                "served": 0, "goodput": 0, "lat": {}, "inf": 0,
            })
            b["served"] += pt.get("served", 0)
            b["goodput"] += pt.get("goodput", 0)
            if "lat_deltas" in pt:
                bounds, deltas = pt["lat_bounds"], pt["lat_deltas"]
                for le, d in zip(bounds, deltas):
                    b["lat"][le] = b["lat"].get(le, 0) + d
                b["inf"] += deltas[-1]  # the +Inf bucket
    out = []
    for key in sorted(bins):
        b = bins[key]
        entry = {
            "t": key * bin_s,
            "qps": b["served"] / bin_s,
            "goodput_qps": b["goodput"] / bin_s,
        }
        if b["lat"] or b["inf"]:
            cum, buckets = 0, []
            for le in sorted(b["lat"]):
                cum += b["lat"][le]
                buckets.append([le, cum])
            buckets.append(["+Inf", cum + b["inf"]])
            p99 = metrics.window_p99(None, buckets)
            if p99 is not None:
                entry["p99_s"] = p99
        out.append(entry)
    return out


def build_report(directory, bin_s=1.0, step_metric="executor.step_latency",
                 latency_metric="serving.request_latency",
                 stale_after=None, now=None):
    shard_paths = sorted(
        p for p in glob.glob(os.path.join(directory, "telemetry_rank*.jsonl"))
    )
    shards, all_points, step_curves = [], [], {}
    for path in shard_paths:
        summary, points, _st = analyze_shard(
            path, step_metric=step_metric, latency_metric=latency_metric
        )
        if summary["last_seq"] is None:
            continue  # unreadable / empty shard
        if stale_after is not None and summary["last_t"] is not None:
            # the Watcher's dead_process verdict, offline: a live
            # publisher stamps its shard every interval, so a stale
            # last_t means the process stopped writing, not went idle
            ref = time.time() if now is None else now
            stale = ref - float(summary["last_t"])
            summary["stale_s"] = stale
            summary["dead"] = stale > float(stale_after)
        shards.append(summary)
        all_points.append(points)
        rank = summary["rank"]
        curve = [
            [pt["t"], pt["step_mean_s"]] for pt in points
            if "step_mean_s" in pt and pt.get("t") is not None
        ]
        if curve:
            step_curves[str(rank)] = curve
    steps = {
        str(s["rank"]): s["last_step"] for s in shards
        if s["last_step"] is not None
    }
    straggler = {}
    if len(steps) >= 2:
        lead = max(steps.values())
        straggler = {
            "lead_step": lead,
            "max_gap_steps": lead - min(steps.values()),
            "per_rank_last_step": steps,
        }
    dead = [s for s in shards if s.get("dead")]
    versions = {
        str(s["rank"]): s["model_version"] for s in shards
        if s.get("model_version") is not None
    }
    publish_skew = {}
    if versions:
        vmax, vmin = max(versions.values()), min(versions.values())
        publish_skew = {
            "per_rank_version": versions,
            "max_version": vmax,
            "min_version": vmin,
            "max_skew": vmax - vmin,
            "lagging_ranks": sorted(
                int(r) for r, v in versions.items() if v < vmax
            ),
        }
    storage = {}
    with_storage = [s for s in shards if s.get("storage")]
    if with_storage:
        pressure_tl = {}
        for s, points in zip(shards, all_points):
            curve = [
                [pt["t"], pt["pressure"]] for pt in points
                if "pressure" in pt and pt.get("t") is not None
            ]
            if curve:
                pressure_tl[str(s["rank"])] = curve
        storage = {
            "per_rank": {
                str(s["rank"]): s["storage"] for s in with_storage
            },
            "gc_bytes_freed_total": sum(
                s["storage"].get("gc_bytes_freed", 0) for s in with_storage
            ),
            "escalations_total": sum(
                s["storage"].get("escalations", 0) for s in with_storage
            ),
            "recoveries_total": sum(
                s["storage"].get("recoveries", 0) for s in with_storage
            ),
            "pressure_timeline": pressure_tl,
        }
    return {
        "dir": directory,
        "shards": shards,
        "fleet": {
            "ranks": len(shards),
            "dead_processes": [
                {"rank": s["rank"], "pid": s["pid"],
                 "stale_s": s["stale_s"]} for s in dead
            ],
            "goodput_total": sum(s["goodput"] for s in shards),
            "requests_served_total": sum(
                s["requests_served"] for s in shards
            ),
            "timeline": _binned(all_points, bin_s),
            "step_time": step_curves,
            "straggler": straggler,
            "publish_skew": publish_skew,
            "storage": storage,
        },
    }


def render(report):
    lines = [f"==== fleet telemetry report: {report['dir']} ===="]
    for s in report["shards"]:
        lines.append(
            f"  rank {s['rank']} (pid {s['pid']}): {s['records']} records, "
            f"last seq {s['last_seq']}, last step {s['last_step']}, "
            f"goodput {s['goodput']}"
        )
    fleet = report["fleet"]
    lines.append(
        f"-- fleet: {fleet['ranks']} rank(s), "
        f"{fleet['requests_served_total']} served "
        f"({fleet['goodput_total']} in-deadline) --"
    )
    for d in fleet.get("dead_processes", ()):
        lines.append(
            f"  DEAD: rank {d['rank']} (pid {d['pid']}) — journal stale "
            f"{d['stale_s']:.1f}s"
        )
    skew = fleet.get("publish_skew")
    if skew:
        lag = skew["lagging_ranks"]
        lines.append(
            f"  publish skew: versions "
            f"{skew['min_version']}..{skew['max_version']} "
            f"(max skew {skew['max_skew']})"
            + (f"; lagging rank(s) {lag}" if lag else "")
        )
    sto = fleet.get("storage")
    if sto:
        levels = {0: "ok", 1: "soft", 2: "hard", 3: "critical"}
        lines.append(
            f"  storage: {sto['gc_bytes_freed_total']} bytes GC'd, "
            f"{sto['escalations_total']} escalation(s), "
            f"{sto['recoveries_total']} recovery(ies)"
        )
        for rank, s in sorted(sto["per_rank"].items()):
            frees = ", ".join(
                f"{root}={b}" for root, b in sorted(
                    s.get("free_bytes", {}).items()
                )
            )
            lines.append(
                f"    rank {rank}: pressure "
                f"{levels.get(s.get('pressure'), '?')}"
                + (f"; free bytes {frees}" if frees else "")
                + (f"; {s['writes_refused']} write(s) refused"
                   if s.get("writes_refused") else "")
            )
        for rank, curve in sorted(sto["pressure_timeline"].items()):
            moves = " -> ".join(levels.get(lvl, "?") for _, lvl in curve)
            lines.append(f"    rank {rank} pressure timeline: {moves}")
    strag = fleet["straggler"]
    if strag:
        lines.append(
            f"  straggler gap: {strag['max_gap_steps']} steps behind "
            f"lead {strag['lead_step']} "
            f"({strag['per_rank_last_step']})"
        )
    tl = fleet["timeline"]
    if tl:
        p99s = [e["p99_s"] for e in tl if "p99_s" in e]
        lines.append(
            f"  {len(tl)} time bin(s); peak qps "
            f"{max(e['qps'] for e in tl):.1f}"
            + (f"; worst bin p99 {max(p99s):.4g}s" if p99s else "")
        )
    for rank, curve in sorted(fleet["step_time"].items()):
        means = [m for _, m in curve]
        lines.append(
            f"  rank {rank} step time: {len(curve)} window(s), mean "
            f"{sum(means) / len(means) * 1e3:.2f} ms, worst "
            f"{max(means) * 1e3:.2f} ms"
        )
    return "\n".join(lines)


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("dir", help="telemetry dir holding telemetry_rank*.jsonl")
    ap.add_argument("--bin", type=float, default=1.0, metavar="S",
                    help="time-bin width in seconds (default 1.0)")
    ap.add_argument("--json", action="store_true",
                    help="print the machine-readable report instead")
    ap.add_argument("--out", default=None,
                    help="also write the JSON report here")
    ap.add_argument("--expect-ranks", type=int, default=0, metavar="N",
                    help="fail unless >= N shards replayed")
    ap.add_argument("--step-metric", default="executor.step_latency")
    ap.add_argument("--latency-metric", default="serving.request_latency")
    ap.add_argument("--stale-after", type=float, default=None, metavar="S",
                    help="flag shards whose last journal stamp is older "
                         "than S seconds as dead processes (the offline "
                         "twin of the watcher's dead_process finding)")
    args = ap.parse_args(argv)
    report = build_report(
        args.dir, bin_s=args.bin, step_metric=args.step_metric,
        latency_metric=args.latency_metric, stale_after=args.stale_after,
    )
    if args.out:
        with open(args.out, "w") as f:
            json.dump(report, f, indent=2)
    print(json.dumps(report) if args.json else render(report))
    if args.expect_ranks and len(report["shards"]) < args.expect_ranks:
        print(
            f"EXPECTED >= {args.expect_ranks} shards, replayed "
            f"{len(report['shards'])}", file=sys.stderr,
        )
        return 2
    return 0


if __name__ == "__main__":
    sys.exit(main())
