#!/usr/bin/env python
"""Embedding-engine bench: fused lookup + hot-tier cache + async prefetch.

Self-gating (exit 1 when any gate fails), prints ONE JSON line:

  * ``ops_reduction``       — per-slot lookup dispatch sites before vs
    fused sites after ``embedding.fuse_lookups`` (DeepFM: 2F+ -> 2);
  * ``dedup_unique_ratio``  — mean unique/total ids per batch (< 1 means
    batch dedup is doing work on this id distribution);
  * ``capacity_ratio``      — cold-store rows / device hot-tier rows: the
    table capacity beyond one device's resident tier (the host cold path
    demonstrated structurally: device holds hot_rows, host holds vocab);
  * ``cache_parity``        — cached/evicting training run is BITWISE
    equal to the full-table run (SGD; eviction/refetch round trips
    included);
  * ``prefetch_overlap``    — mean fraction of host staging time hidden
    behind the previous step's compute;
  * ``hot_hit_rate``        — final hot-tier hit-rate gauge.

``--dump PATH`` writes the observability snapshot (stats_report
``--require embedding.``); ``--smoke`` shrinks the run for CI.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--smoke", action="store_true", help="CI-sized run")
    ap.add_argument("--steps", type=int, default=None)
    ap.add_argument("--dump", default=None,
                    help="write the observability snapshot JSON here")
    args = ap.parse_args(argv)

    import jax

    import paddle_tpu as fluid
    from paddle_tpu import observability
    from paddle_tpu.embedding import (
        EmbeddingEngine,
        Prefetcher,
        fuse_lookups,
    )
    from paddle_tpu.framework.scope import Scope
    from paddle_tpu.models.deepfm import DeepFMConfig, deepfm

    smoke = args.smoke
    cfg = DeepFMConfig(
        vocab_size=2048 if smoke else 8192, num_fields=6, embed_dim=8,
        mlp_sizes=(16,),
    )
    b = 32 if smoke else 128
    hot = cfg.vocab_size // 4
    steps = args.steps or (8 if smoke else 24)
    rng = np.random.RandomState(0)
    feeds = []
    for _ in range(steps):
        idv = (cfg.vocab_size * rng.power(0.35, (b, cfg.num_fields)))
        idv = idv.astype(np.int64)
        feeds.append({
            "feat_ids": idv,
            "label": (idv[:, :1] % 2 == 0).astype(np.float32),
        })

    def build(hot_rows=None):
        from paddle_tpu.framework import unique_name

        main, startup = fluid.Program(), fluid.Program()
        main.random_seed = startup.random_seed = 3
        scope = Scope()
        with fluid.program_guard(main, startup), unique_name.guard():
            ids = fluid.data("feat_ids", [b, cfg.num_fields], "int64")
            label = fluid.data("label", [b, 1], "float32")
            loss, _pred = deepfm(ids, label, cfg, per_slot=True)
            before = sum(
                1 for op in main.global_block.ops
                if op.type == "distributed_lookup_table"
            )
            fuse_lookups(main)
            after = sum(
                1 for op in main.global_block.ops
                if op.type in ("distributed_lookup_table",
                               "fused_lookup_table")
            )
            engine = None
            if hot_rows:
                engine = EmbeddingEngine(main, startup, hot_rows=hot_rows)
            fluid.optimizer.SGD(0.05).minimize(loss)
        exe = fluid.Executor()
        exe.run(startup, scope=scope)
        if engine:
            engine.attach(scope)
        return main, scope, exe, loss, engine, (before, after)

    # cached + prefetched run (the capacity path)
    main, scope, exe, loss, engine, (before, after) = build(hot_rows=hot)
    host_init = {
        t: g.host[t].copy() for g in engine.groups for t in g.table_names
    }
    losses_cached = []
    t0 = time.perf_counter()
    for f in Prefetcher(engine, feeds, scope):
        (lv,) = exe.run(main, feed=f, fetch_list=[loss], scope=scope)
        losses_cached.append(float(np.asarray(lv).reshape(-1)[0]))
    wall = time.perf_counter() - t0

    # full-table reference seeded with the SAME host-store init values
    fmain, fscope, fexe, floss, _eng, _sites = build(hot_rows=None)
    import jax.numpy as jnp

    for name, arr in host_init.items():
        fscope.set_var(name, jnp.asarray(arr))
    losses_full = []
    for f in feeds:
        (lv,) = fexe.run(fmain, feed=f, fetch_list=[floss], scope=fscope)
        losses_full.append(float(np.asarray(lv).reshape(-1)[0]))

    snap = observability.snapshot()
    gauges = snap["gauges"]
    hists = snap["histograms"]
    counters = snap["counters"]
    group = engine.groups[0].name
    overlap = hists.get("embedding.prefetch_overlap", {})
    dedup = hists.get("embedding.dedup_ratio", {})
    host_bytes = gauges.get(f"embedding.host_bytes.{group}", 0)
    device_bytes = gauges.get(f"embedding.device_bytes.{group}", 0)

    result = {
        "metric": "embedding_engine_capacity_smoke",
        "value": round(cfg.vocab_size / hot, 2),
        "unit": "cold_rows_over_hot_rows",
        "examples_per_sec": round(steps * b / wall, 1),
        "ops_reduction": {"lookup_sites_before": before,
                          "fused_sites_after": after},
        "dedup_unique_ratio": round(
            dedup["sum"] / dedup["count"], 4
        ) if dedup.get("count") else None,
        "capacity": {
            "vocab_rows": cfg.vocab_size,
            "hot_rows": hot,
            "capacity_ratio": round(cfg.vocab_size / hot, 2),
            "host_bytes": int(host_bytes),
            "device_bytes": int(device_bytes),
        },
        "cache_parity": losses_cached == losses_full,
        "hot_hit_rate": round(
            gauges.get(f"embedding.hot_hit_rate.{group}", 0.0), 4
        ),
        "evictions": counters.get("embedding.cache_evictions", 0),
        "writebacks": counters.get("embedding.cache_writebacks", 0),
        "prefetch_overlap": round(
            overlap["sum"] / overlap["count"], 3
        ) if overlap.get("count") else None,
        "final_loss": round(losses_cached[-1], 6),
        "platform": jax.devices()[0].platform,
    }
    if args.dump:
        observability.dump(args.dump)
    print(json.dumps(result), flush=True)

    ok = (
        after < before
        and after <= 2
        and (result["dedup_unique_ratio"] or 1.0) < 1.0
        and result["cache_parity"]
        and result["capacity"]["capacity_ratio"] > 1.0
        and result["capacity"]["host_bytes"]
        > result["capacity"]["device_bytes"]
        and result["evictions"] > 0
        and result["prefetch_overlap"] is not None
    )
    if not ok:
        print("embedding engine gates NOT met", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
