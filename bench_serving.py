"""Serving load generator: checkpoint -> frozen graph -> QPS.

Drives the paddle_tpu.serving router with traffic mixes and prints ONE
JSON line per mix (bench.py convention):

  * ``bert_classify``  — tiny-BERT sequence classifier, closed-loop
    concurrent clients over buckets (1, 2, 4, 8);
  * ``resnet_classify`` — CIFAR-sized ResNet-18 softmax head, open-loop
    Poisson arrivals (tests deadline-driven partial batches);
  * ``ctr_rank``       — fused-embedding DeepFM ranker (PR 11);
  * ``gpt_generate``   — KV-cache generation endpoint (prefill + decode);
  * ``overload``       — r15 fault-domain mix: open-loop Poisson at 2x
    the measured sustainable rate, 30% interactive / 70% background with
    per-class deadlines, run twice — the shed-nothing r8 baseline vs
    deadline+priority shedding with the watcher-driven brownout ladder —
    reporting GOODPUT (in-deadline completions/s) and shed/expired rate
    per priority class. Gates goodput(shed) >= 1.3x goodput(baseline) at
    equal-or-better interactive p99.
  * ``failover``       — r15 chaos mix: a 3-replica ``ReplicaSet``
    behind one endpoint under closed-loop load; one replica is KILLED
    mid-run (per-replica ``serving.dispatch.r0`` fault). Gates: every
    admitted request resolves (success or typed error, zero hangs), the
    killed replica's breaker opens, and post-failover QPS stays within
    20% of pre-kill. Run it under
    ``PADDLE_TPU_FAULT_INJECT=serving.dispatch:hang:...`` (ci.sh does)
    to add a wedged-executable dispatch the attempt timeout must bound.
  * ``live_update``    — r18 live-publish mix: a 3-replica
    ``SubscribedRunner`` set serving while a trainer thread publishes
    delta bundles and a ``RolloutController`` canaries them through.
    Every version's weights are version-constant, so each response row
    identifies the version that produced it. Gates: goodput under live
    updates >= 0.9x the no-publish baseline, >= 1 version applied
    fleet-wide, zero torn rows (no batch mixed two versions' weights).

Per mix: QPS, p50/p99 request latency (client-measured), batch-size
histogram from the ``serving.bucket_runs.*`` counters, and the frozen
graph's ``Program.estimate()`` roofline as the per-batch lower bound
(estimate vs measured — the PR-7 cross-check; on CPU the v5e peaks make
the ratio an overhead indicator, not a target).

Two acceptance ratios ride along:

  * ``batched_speedup``  — bucket-8 batch throughput vs 8 sequential
    single-request dispatches on the same executable set (>= 3x CPU CI:
    the arXiv:2301.13062 one-wide-program argument applied to serving);
  * ``kv_decode_speedup`` — KV-cache generation vs full-context recompute
    at context >= 256 (>= 5x: the O(1)-per-token decode path).

``--smoke`` shrinks the run for CI; ``--dump PATH`` writes the
observability snapshot for ``stats_report --require serving.``;
``--mix a,b`` runs a subset
(bert,resnet,ctr,gpt,overload,failover,live_update).
"""

from __future__ import annotations

import argparse
import json
import sys
import threading
import time

import numpy as np


def _percentiles(lat):
    lat = np.asarray(sorted(lat))
    if not len(lat):
        return {"p50_ms": None, "p99_ms": None}
    return {
        "p50_ms": round(float(np.percentile(lat, 50)) * 1e3, 3),
        "p99_ms": round(float(np.percentile(lat, 99)) * 1e3, 3),
    }


def _bucket_histogram(endpoint_name):
    from paddle_tpu import observability

    prefix = f"serving.bucket_runs.{endpoint_name}."
    return {
        k[len(prefix):]: v
        for k, v in observability.get_counters().items()
        if k.startswith(prefix)
    }


def _trace_latency_split(endpoint_name):
    """Queue-wait vs dispatch (compute) p50/p99 reconstructed from the
    request traces alone (serving.queue_wait / serving.dispatch spans the
    scheduler records under each request's TraceContext), cross-checked
    against the serving.* histograms: per request, queue_wait + dispatch
    must account for the request latency the endpoint histogram measured
    (mean-level check — the two are recorded by different clocks/sides,
    so the bar is agreement, not equality)."""
    from paddle_tpu import observability

    waits, disps = [], []
    for s in observability.get_spans():
        if (s.get("args") or {}).get("endpoint") != endpoint_name \
                or "trace_id" not in s:
            continue
        if s["name"] == "serving.queue_wait":
            waits.append(s["dur"] / 1e6)
        elif s["name"] == "serving.dispatch":
            disps.append(s["dur"] / 1e6)
    if not waits or not disps:
        return {"trace_spans": 0}
    hist = observability.get_histograms().get(
        f"serving.request_latency.{endpoint_name}"
    )
    consistent = None
    if hist and hist["count"]:
        hist_mean = hist["sum"] / hist["count"]
        trace_mean = (sum(waits) / len(waits)) + (sum(disps) / len(disps))
        # ingest/future-resolution overheads ride on the histogram side
        consistent = bool(
            trace_mean <= hist_mean * 1.25 + 2e-3
            and trace_mean >= hist_mean * 0.25
        )
    return {
        "trace_spans": len(waits) + len(disps),
        "trace_queue_wait_ms": _percentiles(waits),
        "trace_dispatch_ms": _percentiles(disps),
        "trace_vs_hist_consistent": consistent,
    }


def _roofline(frozen, bucket, feed_builder):
    """Program.estimate() at the largest bucket: analytic per-batch
    latency lower bound for the frozen graph."""
    try:
        feed = feed_builder(bucket)
        est = frozen.program.estimate(
            feed_shapes={k: tuple(v.shape) for k, v in feed.items()}
        )
        return {
            "est_batch_flops": float(est.total_flops),
            "est_batch_ms": round(est.total_latency * 1e3, 4),
        }
    except Exception as e:  # estimate failures must not kill the bench
        return {"est_error": str(e)[:120]}


def _closed_loop(server, endpoint, feed_builder, n_clients, duration):
    """N clients submit-wait-repeat; returns (latencies, n_done, wall)."""
    lats, lock = [], threading.Lock()
    stop = time.perf_counter() + duration

    def client(seed):
        rng = np.random.RandomState(seed)
        while time.perf_counter() < stop:
            t0 = time.perf_counter()
            fut = server.submit(endpoint, feed_builder(rng))
            fut.result(timeout=60)
            dt = time.perf_counter() - t0
            with lock:
                lats.append(dt)

    t_start = time.perf_counter()
    threads = [
        threading.Thread(target=client, args=(i,)) for i in range(n_clients)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    return lats, len(lats), time.perf_counter() - t_start


def _build_classifier_endpoint(kind, scope, seed=7):
    """Build + 2-step-train + freeze a tiny classifier; returns
    (frozen, sample_feed_builder, exe)."""
    import paddle_tpu as fluid
    from paddle_tpu import layers
    from paddle_tpu.framework.scope import scope_guard

    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = seed
    with fluid.program_guard(main, startup):
        if kind == "bert":
            from paddle_tpu.models.bert import BertConfig, bert_encoder

            cfg = BertConfig.tiny()
            s = 16
            ids = fluid.data("ids", [-1, s], "int64")
            types = fluid.data("types", [-1, s], "int64")
            mask = fluid.data("mask", [-1, s], "float32")
            seq = bert_encoder(ids, types, mask, cfg, is_test=False)
            # [CLS]-style pooled head: first token's hidden state
            pooled = layers.slice(seq, [1], [0], [1])
            logits = layers.fc(pooled, 4)
            prob = layers.softmax(logits)
            lab = fluid.data("lab", [-1, 1], "int64")
            loss = layers.mean(
                layers.softmax_with_cross_entropy(logits, lab)
            )
            feeds = ("ids", "types", "mask")

            def build(rng_or_b):
                if isinstance(rng_or_b, int):
                    b = rng_or_b
                    return {
                        "ids": np.zeros((b, s), np.int64),
                        "types": np.zeros((b, s), np.int64),
                        "mask": np.ones((b, s), np.float32),
                    }
                rng = rng_or_b
                return {
                    "ids": rng.randint(0, cfg.vocab_size, s).astype(
                        np.int64
                    ),
                    "types": np.zeros(s, np.int64),
                    "mask": np.ones(s, np.float32),
                }
        else:
            from paddle_tpu.models.resnet import resnet

            img = fluid.data("image", [-1, 3, 32, 32], "float32")
            logits = resnet(img, class_num=10, depth=18, is_test=False)
            prob = layers.softmax(logits)
            lab = fluid.data("lab", [-1, 1], "int64")
            loss = layers.mean(
                layers.softmax_with_cross_entropy(logits, lab)
            )
            feeds = ("image",)

            def build(rng_or_b):
                if isinstance(rng_or_b, int):
                    return {
                        "image": np.zeros(
                            (rng_or_b, 3, 32, 32), np.float32
                        ),
                    }
                return {
                    "image": rng_or_b.randn(3, 32, 32).astype(np.float32),
                }
        fluid.optimizer.Adam(1e-3).minimize(loss, startup)

    exe = fluid.Executor()
    with scope_guard(scope):
        exe.run(startup, scope=scope)
    from paddle_tpu.serving import freeze_program

    frozen = freeze_program(main, [prob], feed_names=feeds)
    return frozen, build, exe


def bench_classify_mix(name, kind, buckets, mode, load, duration,
                       results):
    from paddle_tpu.framework.scope import Scope
    from paddle_tpu.serving import Server
    from paddle_tpu.serving.router import EndpointConfig

    scope = Scope()
    frozen, build, exe = _build_classifier_endpoint(kind, scope)
    server = Server()
    server.add_endpoint(
        name, None,
        EndpointConfig(buckets=buckets, max_wait_ms=4.0, max_queue=4096),
        frozen=frozen, executor=exe, scope=scope,
    )
    t0 = time.perf_counter()
    server.warmup()
    warmup_s = time.perf_counter() - t0

    if mode == "closed":
        lats, n, wall = _closed_loop(server, name, build, load, duration)
    else:
        lats, n, wall = _poisson_loop(server, name, build, load, duration)
    server.drain(timeout=30)
    entry = {
        "mix": name,
        "mode": mode,
        "load": load,
        "requests": n,
        "qps": round(n / wall, 2) if wall > 0 else None,
        "warmup_s": round(warmup_s, 2),
        "buckets": _bucket_histogram(name),
        **_percentiles(lats),
        **_roofline(frozen, buckets[-1], build),
        **_trace_latency_split(name),
    }
    results[name] = entry
    return frozen, build, exe, scope, entry


def _poisson_loop(server, endpoint, feed_builder, rate_qps, duration):
    """Open-loop Poisson arrivals; latency = submit -> future resolve,
    stamped by a done-callback at RESOLVE time (waiting and then reading
    the wall clock would inflate early requests' latency to ~run
    length)."""
    rng = np.random.RandomState(1234)
    lats, lock = [], threading.Lock()
    futs = []
    t_start = time.perf_counter()
    stop = t_start + duration
    next_t = t_start
    while time.perf_counter() < stop:
        next_t += rng.exponential(1.0 / rate_qps)
        delay = next_t - time.perf_counter()
        if delay > 0:
            time.sleep(delay)
        t0 = time.perf_counter()
        fut = server.submit(endpoint, feed_builder(rng))

        def _done(f, t0=t0):
            dt = time.perf_counter() - t0
            with lock:
                lats.append(dt)

        fut.add_done_callback(_done)
        futs.append(fut)
    for f in futs:
        f.result(timeout=60)
    wall = time.perf_counter() - t_start
    return lats, len(futs), wall


def bench_batched_vs_sequential(frozen, build, exe, scope, bucket=8,
                                rounds=3, iters=10):
    """Throughput of ONE bucket-N batch vs N sequential single-request
    dispatches against the same warm executables."""
    from paddle_tpu.framework.scope import scope_guard

    fetch = list(frozen.fetch_names)
    feed_b = build(bucket)
    feed_1 = build(1)
    with scope_guard(scope):
        exe.run(frozen.program, feed=feed_b, fetch_list=fetch, scope=scope)
        exe.run(frozen.program, feed=feed_1, fetch_list=fetch, scope=scope)
        best_b = best_1 = float("inf")
        for _ in range(rounds):
            t0 = time.perf_counter()
            for _ in range(iters):
                exe.run(frozen.program, feed=feed_b, fetch_list=fetch,
                        scope=scope)
            best_b = min(best_b, (time.perf_counter() - t0) / iters)
            t0 = time.perf_counter()
            for _ in range(iters):
                for _ in range(bucket):
                    exe.run(frozen.program, feed=feed_1, fetch_list=fetch,
                            scope=scope)
            best_1 = min(best_1, (time.perf_counter() - t0) / iters)
    qps_batched = bucket / best_b
    qps_seq = bucket / best_1
    return {
        "bucket": bucket,
        "batched_qps": round(qps_batched, 1),
        "sequential_qps": round(qps_seq, 1),
        "batched_speedup": round(qps_batched / qps_seq, 2),
    }


def bench_ctr_rank(smoke, duration, results):
    """Recommendation traffic mix (PR 11): a DeepFM CTR ranker served
    through the continuous-batching router — per-slot sparse lookups fused
    into one ``fused_lookup_table`` per table width by the embedding
    engine, frozen, and dispatched per bucket. Records the FIRST
    served-embedding QPS baseline (no ratio gate yet: the number exists so
    the next round has a denominator)."""
    import paddle_tpu as fluid
    from paddle_tpu.embedding import fuse_lookups
    from paddle_tpu.framework.scope import Scope, scope_guard
    from paddle_tpu.models.deepfm import DeepFMConfig, deepfm
    from paddle_tpu.serving import Server, freeze_program
    from paddle_tpu.serving.router import EndpointConfig

    cfg = DeepFMConfig(
        vocab_size=4096, num_fields=13, embed_dim=16, mlp_sizes=(64, 32),
    )
    scope = Scope()
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 13
    with fluid.program_guard(main, startup):
        ids = fluid.data("feat_ids", [-1, cfg.num_fields], "int64")
        label = fluid.data("label", [-1, 1], "float32")
        loss, prob = deepfm(ids, label, cfg, per_slot=True)
        fused = fuse_lookups(main)
        fluid.optimizer.Adam(1e-3).minimize(loss, startup)
    assert fused == 2, f"expected 2 fused lookup sites, got {fused}"
    exe = fluid.Executor()
    with scope_guard(scope):
        exe.run(startup, scope=scope)
    frozen = freeze_program(main, [prob], feed_names=("feat_ids",))
    fused_frozen = sum(
        1 for op in frozen.program.global_block.ops
        if op.type == "fused_lookup_table"
    )

    server = Server()
    server.add_endpoint(
        "ctr_rank", None,
        EndpointConfig(buckets=(1, 2, 4, 8), max_wait_ms=4.0,
                       max_queue=4096),
        frozen=frozen, executor=exe, scope=scope,
    )
    server.warmup()

    def build(rng_or_b):
        if isinstance(rng_or_b, int):
            return {
                "feat_ids": np.zeros(
                    (rng_or_b, cfg.num_fields), np.int64
                ),
            }
        # power-law ids: the heavy-tailed CTR id distribution
        return {
            "feat_ids": (
                cfg.vocab_size * rng_or_b.power(0.35, cfg.num_fields)
            ).astype(np.int64),
        }

    lats, n, wall = _closed_loop(server, "ctr_rank", build, 8, duration)
    server.drain(timeout=30)
    entry = {
        "mix": "ctr_rank",
        "mode": "closed",
        "load": 8,
        "requests": n,
        "qps": round(n / wall, 2) if wall > 0 else None,
        "fused_lookup_sites_frozen": fused_frozen,
        "buckets": _bucket_histogram("ctr_rank"),
        **_percentiles(lats),
        **_roofline(frozen, 8, build),
        "baseline_note": "first served-embedding QPS baseline (r11)",
    }
    results["ctr_rank"] = entry
    return entry


def bench_gpt_generate(smoke, results):
    """KV-cache generation endpoint + the decode-vs-recompute ratio."""
    from paddle_tpu.models.gpt import GPTConfig
    from paddle_tpu.serving import GPTGenerator, Server
    from paddle_tpu.serving.generate import GPTGenerateRunner
    from paddle_tpu.serving.router import EndpointConfig

    # context >= 256 per the acceptance bar; 512 keeps the recompute
    # baseline's O(S) cost well clear of decode dispatch overhead on the
    # CPU CI leg (at 256 the ratio sits right at 5x and contention noise
    # can dip it under)
    context, new_tokens = (512, 32) if not smoke else (512, 24)
    cfg = GPTConfig(
        vocab_size=512, hidden_size=128, num_layers=2, num_heads=4,
        intermediate_size=256, max_position=context + new_tokens,
        use_fused_attention=False,
    )
    gen = GPTGenerator(
        cfg, batch=1, context_len=context, max_len=context + new_tokens
    )
    gen.init_params(seed=11)
    rng = np.random.RandomState(0)
    ctx = rng.randint(0, cfg.vocab_size, (1, context)).astype(np.int64)

    # decode vs full-recompute, best-of-3 (tunneled-chip convention)
    best_kv = best_full = float("inf")
    gen.generate(ctx, new_tokens)
    gen.generate_full_recompute(ctx, new_tokens)
    for _ in range(3):
        t0 = time.perf_counter()
        kv_tokens = gen.generate(ctx, new_tokens)
        best_kv = min(best_kv, time.perf_counter() - t0)
        t0 = time.perf_counter()
        full_tokens = gen.generate_full_recompute(ctx, new_tokens)
        best_full = min(best_full, time.perf_counter() - t0)
    parity = bool(np.array_equal(kv_tokens, full_tokens))

    # the generate endpoint through the router (closed-loop, 2 clients)
    server = Server()
    runner = GPTGenerateRunner(gen, max_new_tokens=new_tokens)
    server.add_endpoint(
        "gpt_generate", runner,
        EndpointConfig(buckets=(1,), max_wait_ms=1.0),
    )
    duration = 2.0 if smoke else 6.0

    def build(rng):
        return {
            "context_ids": rng.randint(0, cfg.vocab_size, context).astype(
                np.int64
            )
        }

    lats, n, wall = _closed_loop(server, "gpt_generate", build, 2,
                                 duration)
    server.drain(timeout=30)
    entry = {
        "mix": "gpt_generate",
        "mode": "closed",
        "load": 2,
        "context": context,
        "new_tokens": new_tokens,
        "requests": n,
        "qps": round(n / wall, 3) if wall > 0 else None,
        "decode_tok_s": round(new_tokens / best_kv, 1),
        "recompute_tok_s": round(new_tokens / best_full, 1),
        "kv_decode_speedup": round(best_full / best_kv, 2),
        "kv_parity": parity,
        **_percentiles(lats),
    }
    results["gpt_generate"] = entry
    return entry


def _overload_leg(server, ep_name, build, rate, duration, deadlines,
                  shed):
    """One open-loop Poisson leg at `rate` with a 30/70 interactive/
    background split; returns per-class outcome counts, latencies, and
    goodput (in-deadline completions/s — the baseline leg submits WITHOUT
    deadlines, so its completions are judged against the same budgets
    client-side: what the r8 router delivers when nobody sheds)."""
    from paddle_tpu.errors import (DeadlineExceededError,
                                   PreconditionNotMetError,
                                   RequestShedError)
    from paddle_tpu.serving import BACKGROUND, INTERACTIVE

    rng = np.random.RandomState(99)
    lock = threading.Lock()
    classes = ("interactive", "background")
    prio = {"interactive": INTERACTIVE, "background": BACKGROUND}
    # per-arrival accounting: a request either raises at SUBMIT time
    # (brownout/queue-full shed -> submit_shed) or becomes exactly one
    # future whose done-callback lands in exactly one outcome bucket
    # ("shed" there = evicted AFTER admission) — no arrival is counted
    # twice
    outcomes = {c: {"ok": 0, "late": 0, "expired": 0, "shed": 0,
                    "error": 0} for c in classes}
    submit_shed = {c: 0 for c in classes}
    lats = {c: [] for c in classes}
    resolved = [0]  # done-callback completions (result() can return
    # before callbacks have run; outcomes are read only once this
    # catches up to the admitted count)
    futs = []
    t_start = time.perf_counter()
    stop = t_start + duration
    next_t = t_start
    while time.perf_counter() < stop:
        next_t += rng.exponential(1.0 / rate)
        delay = next_t - time.perf_counter()
        if delay > 0:
            time.sleep(delay)
        cls = "interactive" if rng.random() < 0.3 else "background"
        dl_s = deadlines[cls]
        t0 = time.perf_counter()
        try:
            if shed:
                fut = server.submit(
                    ep_name, build(rng), deadline_ms=dl_s * 1e3,
                    priority=prio[cls],
                )
            else:
                fut = server.submit(ep_name, build(rng))
        except (RequestShedError, PreconditionNotMetError):
            with lock:
                submit_shed[cls] += 1
            continue

        def _done(f, t0=t0, cls=cls, dl=dl_s):
            dt = time.perf_counter() - t0
            with lock:
                try:
                    f.result()
                    lats[cls].append(dt)
                    outcomes[cls]["ok" if dt <= dl else "late"] += 1
                except DeadlineExceededError:
                    outcomes[cls]["expired"] += 1
                except RequestShedError:
                    outcomes[cls]["shed"] += 1
                except Exception:
                    outcomes[cls]["error"] += 1
                resolved[0] += 1

        fut.add_done_callback(_done)
        futs.append(fut)
    window = time.perf_counter() - t_start  # the arrival window
    unresolved = 0
    for f in futs:
        try:
            f.result(timeout=120)
        except Exception:
            if not f.done():
                unresolved += 1
    give_up = time.perf_counter() + 30.0
    while True:
        with lock:
            if resolved[0] >= len(futs) - unresolved:
                break
        if time.perf_counter() > give_up:
            break
        time.sleep(0.002)
    wall = time.perf_counter() - t_start
    in_deadline = sum(outcomes[c]["ok"] for c in classes)
    admitted = len(futs)
    arrived = admitted + sum(submit_shed.values())
    # goodput over the ARRIVAL window for BOTH legs: the baseline leg's
    # backlog keeps draining long after arrivals stop, and dividing by
    # that stretched wall would deflate its goodput by measurement
    # rather than by behavior (its late tail already contributes zero
    # to the numerator)
    return {
        "rate_qps": round(rate, 1),
        "arrived": arrived,
        "admitted": admitted,
        "unresolved": unresolved,
        "wall_s": round(wall, 2),
        "window_s": round(window, 2),
        "goodput_qps": (
            round(in_deadline / window, 2) if window > 0 else 0.0
        ),
        "outcomes": outcomes,
        "submit_shed": submit_shed,
        "shed_rate": {
            c: round(
                (submit_shed[c] + outcomes[c]["shed"])
                / max(1, submit_shed[c] + sum(outcomes[c].values())), 3
            )
            for c in classes
        },
        "interactive": _percentiles(lats["interactive"]),
        "background": _percentiles(lats["background"]),
    }


def bench_overload(smoke, duration, results):
    """The 2x-overload goodput mix: shed-nothing r8 baseline vs the r15
    fault domain (deadlines + priority shedding + brownout ladder), same
    arrival process. Self-gating: goodput >= 1.3x at equal-or-better
    interactive p99, and the expired/shed counters must be alive."""
    from paddle_tpu.framework.scope import Scope
    from paddle_tpu.observability.watch import Watcher
    from paddle_tpu.serving import BrownoutController, Server
    from paddle_tpu.serving.router import EndpointConfig

    scope = Scope()
    frozen, build, exe = _build_classifier_endpoint("bert", scope,
                                                    seed=17)

    # sustainable-capacity probe: a short closed-loop burst on a warm
    # endpoint; 2x this arrival rate is overload BY MEASUREMENT
    probe = Server()
    probe.add_endpoint(
        "overload_probe", None,
        EndpointConfig(buckets=(1, 2, 4, 8), max_wait_ms=4.0,
                       max_queue=4096),
        frozen=frozen, executor=exe, scope=scope,
    )
    probe.warmup()
    lats, n, wall = _closed_loop(probe, "overload_probe", build, 8,
                                 1.0 if smoke else 2.0)
    probe.drain(timeout=30)
    qps_cap = n / wall if wall > 0 else 100.0
    p50_cap = float(np.percentile(lats, 50)) if lats else 0.01
    rate = 2.0 * qps_cap
    # interactive budget 10x the uncontended p50 (floor 80ms): tight
    # enough that the baseline's growing queue blows it within a couple
    # hundred ms, loose enough that a shedding router serving near
    # capacity lands inside it rather than on the knife edge
    int_dl = max(10.0 * p50_cap, 0.08)
    deadlines = {"interactive": int_dl, "background": 4.0 * int_dl}

    def leg_server(name, shed):
        s = Server()
        s.add_endpoint(
            name, None,
            EndpointConfig(buckets=(1, 2, 4, 8), max_wait_ms=4.0,
                           max_queue=(256 if shed else 1_000_000)),
            frozen=frozen, executor=exe, scope=scope,
        )
        s.warmup()
        return s

    # leg 1 — the shed-nothing r8 baseline: no deadlines, no classes,
    # unbounded-ish queue; completions judged against the SAME budgets
    base_srv = leg_server("overload_base", shed=False)
    base = _overload_leg(base_srv, "overload_base", build, rate,
                         duration, deadlines, shed=False)
    base_srv.drain(timeout=60)

    # leg 2 — the fault domain: deadlines + priorities + the
    # watcher-driven brownout ladder on the interactive SLO
    shed_srv = leg_server("overload", shed=True)
    watcher = Watcher(latency_metric="serving.request_latency.overload",
                      slo_p99_s=deadlines["interactive"])
    ctl = BrownoutController(
        shed_srv, slo_p99_s=deadlines["interactive"], watcher=watcher,
        escalate_after=2, recover_after=2, interval=0.1,
    )
    ctl.start()
    shed = _overload_leg(shed_srv, "overload", build, rate, duration,
                         deadlines, shed=True)
    brownout_level_end = ctl.level
    ctl.stop()
    shed_srv.drain(timeout=60)

    from paddle_tpu import observability
    c = observability.get_counters()
    goodput_ratio = (
        shed["goodput_qps"] / base["goodput_qps"]
        if base["goodput_qps"] else float("inf")
    )
    p99_base = base["interactive"]["p99_ms"]
    p99_shed = shed["interactive"]["p99_ms"]
    entry = {
        "mix": "overload",
        "mode": "open-2x",
        "capacity_qps": round(qps_cap, 1),
        "deadline_ms": {k: round(v * 1e3, 1) for k, v in
                        deadlines.items()},
        "baseline": base,
        "shedding": shed,
        "goodput_ratio": round(goodput_ratio, 2),
        "interactive_p99_ms": {"baseline": p99_base, "shedding": p99_shed},
        "brownout_level_end": brownout_level_end,
        "brownout_escalations": c.get("serving.brownout_escalations", 0),
        "serving_expired": c.get("serving.expired", 0),
        "serving_shed": c.get("serving.shed", 0),
        "gates": {
            "goodput_ratio>=1.3": goodput_ratio >= 1.3,
            "interactive_p99<=baseline": bool(
                p99_shed is not None and p99_base is not None
                and p99_shed <= p99_base
            ),
            "expired_counter_alive": c.get("serving.expired", 0) > 0,
            "all_resolved": (base["unresolved"] == 0
                             and shed["unresolved"] == 0),
        },
    }
    entry["ok"] = all(entry["gates"].values())
    results["overload"] = entry
    return entry


def bench_failover(smoke, duration, results):
    """The replica-kill chaos mix: 3 FrozenRunner replicas behind one
    endpoint, closed-loop load, replica r0 killed mid-run via its
    per-replica dispatch fault. Self-gating: zero unresolved requests,
    breaker open on r0, post-failover QPS within 20% of pre-kill."""
    from paddle_tpu.framework.scope import Scope
    from paddle_tpu.resilience import faults
    from paddle_tpu.serving import ReplicaSet, Server
    from paddle_tpu.serving.router import EndpointConfig, FrozenRunner

    scope = Scope()
    frozen, build, exe = _build_classifier_endpoint("bert", scope,
                                                    seed=23)
    replicas = {
        f"r{i}": FrozenRunner(frozen, executor=exe, scope=scope)
        for i in range(3)
    }
    rs = ReplicaSet(replicas, breaker_threshold=2, cooldown_s=1.0,
                    attempt_timeout=1.0, name="failover")
    server = Server()
    server.add_endpoint(
        "failover", rs,
        EndpointConfig(buckets=(1, 2, 4), max_wait_ms=2.0,
                       max_queue=4096),
    )
    server.warmup()

    w = duration / 3.0
    done_times, lock = [], threading.Lock()
    unresolved = [0]
    typed_errors = [0]
    stop = time.perf_counter() + duration
    t_start = time.perf_counter()
    kill_at = t_start + 1.5 * w

    def client(seed):
        rng = np.random.RandomState(seed)
        while time.perf_counter() < stop:
            fut = server.submit("failover", build(rng))
            try:
                fut.result(timeout=30)
            except Exception:
                with lock:
                    if fut.done():
                        typed_errors[0] += 1  # resolved, typed: fine
                    else:
                        unresolved[0] += 1  # a hang: the gate-breaker
                continue
            with lock:
                done_times.append(time.perf_counter() - t_start)

    threads = [threading.Thread(target=client, args=(i,))
               for i in range(6)]
    for t in threads:
        t.start()
    # the mid-run kill: r0's dispatch seam raises from here on — the
    # same seam ci.sh's env-armed serving.dispatch:hang chaos rides
    while time.perf_counter() < kill_at:
        time.sleep(0.01)
    faults.inject("serving.dispatch.r0", "unavailable", prob=1.0, seed=0)
    for t in threads:
        t.join()
    faults.clear("serving.dispatch.r0")
    server.drain(timeout=30)

    pre = [t for t in done_times if 0.5 * w <= t < 1.5 * w]
    post = [t for t in done_times if 2.0 * w <= t < 3.0 * w]
    qps_pre = len(pre) / w
    qps_post = len(post) / w
    from paddle_tpu import observability
    c = observability.get_counters()
    g = observability.get_gauges()
    entry = {
        "mix": "failover",
        "mode": "closed",
        "load": 6,
        "requests": len(done_times),
        "kill_at_s": round(1.5 * w, 2),
        "qps_pre_kill": round(qps_pre, 1),
        "qps_post_failover": round(qps_post, 1),
        "qps_recovery": round(qps_post / qps_pre, 3) if qps_pre else None,
        "unresolved": unresolved[0],
        "typed_errors": typed_errors[0],
        "requeued": c.get("serving.requeued", 0),
        "breaker_opened": c.get("serving.breaker_opened", 0),
        "breaker_state": {
            r: g.get(f"serving.breaker_state.{r}") for r in replicas
        },
        "replica_states": rs.states(),
        "dispatch_hang_faults": c.get(
            "resilience.faults_injected.serving.dispatch", 0
        ),
        "gates": {
            "zero_hangs": unresolved[0] == 0,
            "breaker_open_on_r0": g.get(
                "serving.breaker_state.r0") == 1.0,
            "requeued>0": c.get("serving.requeued", 0) > 0,
            "qps_within_20pct": qps_pre > 0
            and qps_post >= 0.8 * qps_pre,
        },
    }
    entry["ok"] = all(entry["gates"].values())
    results["failover"] = entry
    return entry


def bench_live_update(smoke, duration, results):
    """The r18 live-publish mix: a 3-replica ``SubscribedRunner`` set
    serving while a trainer thread publishes delta bundles and a
    ``RolloutController`` canaries them through the fleet. The weights
    of every version are version-constant (a deterministic pattern of
    the version number), so each response row identifies exactly one
    committed version — a row matching NO version is a torn batch.

    Self-gating: goodput under live updates >= 0.9x the no-publish
    baseline (the apply stalls must cost < 10%), >= 1 version applied
    fleet-wide, zero torn rows."""
    import tempfile

    from paddle_tpu import observability
    from paddle_tpu.fleet.publish import (ModelPublisher, ModelSubscriber,
                                          committed_versions, load_version)
    from paddle_tpu.framework.scope import Scope, scope_guard
    from paddle_tpu.serving import ReplicaSet, Server, freeze_program
    from paddle_tpu.serving.rollout import (RolloutController,
                                            SubscribedRunner)
    from paddle_tpu.serving.router import EndpointConfig, FrozenRunner

    import paddle_tpu as fluid
    from paddle_tpu import layers

    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 11
    with fluid.program_guard(main, startup):
        x = fluid.data("x", [-1, 8])
        prob = layers.softmax(layers.fc(x, 6))
    trainer_scope = Scope()
    exe = fluid.Executor()
    with scope_guard(trainer_scope):
        exe.run(startup, scope=trainer_scope)
    frozen = freeze_program(main, [prob], feed_names=("x",))
    pnames = sorted(
        n for n in trainer_scope.local_var_names()
        if trainer_scope.find_var(n) is not None
        and frozen.program.global_block.var(n) is not None
    )

    def stamp(version):
        # version-constant weights: every persistable becomes a pattern
        # of the version number, so softmax(ones @ W + b) is a distinct,
        # recomputable fingerprint per version
        for i, name in enumerate(pnames):
            cur = np.asarray(trainer_scope.find_var(name))
            size = cur.size
            pat = (np.arange(size, dtype=np.float64) % 5 - 2.0) / 10.0
            arr = ((version % 7 + 1) * 0.1 * (i + 1) * pat).reshape(
                cur.shape
            ).astype(cur.dtype)
            trainer_scope.set_var(name, arr)

    publish_dir = tempfile.mkdtemp(prefix="bench-live-publish-")
    publisher = ModelPublisher(publish_dir, main_program=frozen.program,
                               scope=trainer_scope, full_every=4,
                               max_versions=64)
    stamp(1)
    publisher.publish(step=1)

    feed_one = {"x": np.ones(8, np.float32)}
    outputs, out_lock = [], threading.Lock()

    def serve_leg(live):
        runners = {}
        for i in range(3):
            scope = Scope()
            with scope_guard(scope):
                exe.run(startup, scope=scope)
            sub = ModelSubscriber(publish_dir,
                                  main_program=frozen.program,
                                  scope=scope, name=f"r{i}")
            sub.poll()  # catch-up before serving (the respawn path)
            runners[f"r{i}"] = SubscribedRunner(
                FrozenRunner(frozen, executor=exe, scope=scope), sub
            )
        rs = ReplicaSet(runners, name="live")
        server = Server()
        server.add_endpoint(
            "live", rs,
            EndpointConfig(buckets=(1, 2, 4), max_wait_ms=2.0,
                           max_queue=4096),
        )
        server.warmup()
        ctl = RolloutController(rs, publish_dir, watcher=None,
                                error_counters=(), canary_soak_ticks=1,
                                post_soak_ticks=0, interval=0.05)
        ctl.version = publisher._next - 1  # baseline: already rolled out
        stop_pub = threading.Event()

        def train_and_publish():
            v = publisher._next
            while not stop_pub.wait(duration / 6.0):
                stamp(v)
                publisher.publish(step=v)
                v += 1

        pub_thread = threading.Thread(target=train_and_publish,
                                      daemon=True)
        stop = time.perf_counter() + duration
        done = [0]

        def client(seed):
            while time.perf_counter() < stop:
                fut = server.submit("live", feed_one)
                out = fut.result(timeout=30)
                done[0] += 1
                if live:
                    with out_lock:
                        outputs.append(np.asarray(out[0]))

        if live:
            pub_thread.start()
            ctl.start()
        t0 = time.perf_counter()
        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        wall = time.perf_counter() - t0
        if live:
            stop_pub.set()
            pub_thread.join()
            ctl.stop()
        server.drain(timeout=30)
        return done[0] / wall if wall > 0 else 0.0, ctl

    qps_base, _ = serve_leg(live=False)
    qps_live, ctl = serve_leg(live=True)

    # every served row must reproduce as the output of exactly one
    # committed version's cold fold — a row matching none is a batch
    # that mixed weights from two versions across the apply fence
    expected = []
    ref = FrozenRunner(frozen, executor=exe, scope=Scope())
    for v in committed_versions(publish_dir):
        folded = load_version(publish_dir, v)
        for name, arr in folded.items():
            ref.scope.set_var(name, arr)
        (out,) = ref.run({"x": np.ones((1, 8), np.float32)})
        expected.append((v, np.asarray(out)[0]))
    torn = 0
    for row in outputs:
        errs = [float(np.max(np.abs(row - e))) for _v, e in expected]
        if min(errs) > 1e-4:
            torn += 1

    c = observability.get_counters()
    g = observability.get_gauges()
    versions_applied = int(ctl.version or 0)
    entry = {
        "mix": "live_update",
        "mode": "closed",
        "load": 4,
        "requests": len(outputs),
        "qps_baseline": round(qps_base, 1),
        "qps_live": round(qps_live, 1),
        "goodput_ratio": round(qps_live / qps_base, 3) if qps_base
        else None,
        "versions_published": c.get("publish.versions", 0),
        "versions_served_through": versions_applied,
        "rollouts": c.get("publish.rollouts", 0),
        "applies": c.get("publish.applies", 0),
        "rollbacks": c.get("publish.rollbacks", 0),
        "torn_rows": torn,
        "model_version_gauge": g.get("serving.model_version"),
        "staleness_s": g.get("serving.model_staleness_seconds"),
        "gates": {
            "goodput_dip<10pct": qps_base > 0
            and qps_live >= 0.9 * qps_base,
            "versions_applied>=1": c.get("publish.rollouts", 0) >= 1,
            "zero_torn_rows": torn == 0,
            "zero_rollbacks": c.get("publish.rollbacks", 0) == 0,
        },
    }
    entry["ok"] = all(entry["gates"].values())
    results["live_update"] = entry
    return entry


def _pid_alive(pid):
    import os

    try:
        os.kill(pid, 0)
    except OSError:
        return False
    return True


def bench_fleet(smoke, duration, results, n_workers=4, kill=False):
    """Process-fleet mix: the overload arrival process against a
    ``ProcessReplicaSet`` of real worker processes.

    Legs:

    1. **single** — a 1-worker fleet: capacity probe, then the overload
       arrival process (1.25x the N-worker aggregate rate) with
       deadlines + shedding. The per-process baseline.
    2. **fleet** — N workers, same arrival process. Gate: goodput >=
       2.5x the single-worker leg when >= 4 cores back the workers
       (min(N, cores) scales the bar below that; on a 1-core host the
       ratio is reported, not gated — N processes on one core cannot
       scale by construction).
    3. **chaos** (``kill=True``) — N-1 workers with ``max_replicas=N``,
       the journal-mode Watcher + BrownoutController + FleetAutoscaler
       closing the loop, and a REAL ``SIGKILL`` of one worker mid-run.
       Gates: every admitted request resolves typed (zero hangs), the
       worker death is detected and the corpse respawned, the
       autoscaler scaled out BEFORE anything was shed, the fleet is
       back to full strength afterwards, and ``Server.close()`` leaves
       zero orphan processes.
    """
    import os
    import signal
    import tempfile

    from paddle_tpu import observability
    from paddle_tpu.framework.scope import Scope
    from paddle_tpu.observability import timeline
    from paddle_tpu.observability.watch import Watcher
    from paddle_tpu.serving import (BrownoutController, FleetAutoscaler,
                                    ProcessReplicaSet, Server)
    from paddle_tpu.serving.router import EndpointConfig

    scope = Scope()
    frozen, build, exe = _build_classifier_endpoint("bert", scope,
                                                    seed=29)
    model_dir = tempfile.mkdtemp(prefix="bench-fleet-model-")
    frozen.save(model_dir, scope=scope)
    buckets = (1, 2, 4, 8)
    cores = os.cpu_count() or 1
    gates = {}

    def start_fleet(name, n, max_replicas=None, workdir=None, env=None):
        fleet = ProcessReplicaSet(
            model_dir, n_workers=n, max_replicas=max_replicas or n,
            warm_buckets=buckets, attempt_timeout=20.0,
            heartbeat_timeout=10.0, spawn_timeout=300.0, name=name,
            workdir=workdir, env=env,
        )
        srv = Server()
        srv.add_endpoint(
            name, fleet,
            EndpointConfig(buckets=buckets, max_wait_ms=4.0,
                           max_queue=4096),
        )
        srv.warmup()
        return srv, fleet

    # -- leg 1: single-worker baseline ---------------------------------
    srv1, fleet1 = start_fleet("fleet1", 1)
    lats, n_done, wall = _closed_loop(
        srv1, "fleet1", build, 4, 1.0 if smoke else 2.0
    )
    cap1 = n_done / wall if wall > 0 else 50.0
    p50_cap = float(np.percentile(lats, 50)) if lats else 0.01
    int_dl = max(10.0 * p50_cap, 0.1)
    deadlines = {"interactive": int_dl, "background": 4.0 * int_dl}
    # the shared arrival process: overload for ONE worker, ~1.25x
    # saturation for the full fleet — the single leg sheds/expires its
    # way through, the fleet leg serves it, and the goodput ratio is
    # the scaling number
    rate = 1.25 * n_workers * cap1
    single = _overload_leg(srv1, "fleet1", build, rate, duration,
                           deadlines, shed=True)
    pids1 = fleet1.worker_pids()
    srv1.close(timeout=120)

    # -- leg 2: the N-worker fleet, same arrivals ----------------------
    srvN, fleetN = start_fleet(f"fleet{n_workers}", n_workers)
    fleet_leg = _overload_leg(srvN, f"fleet{n_workers}", build, rate,
                              duration, deadlines, shed=True)
    pidsN = fleetN.worker_pids()
    srvN.close(timeout=120)

    ratio = (
        fleet_leg["goodput_qps"] / single["goodput_qps"]
        if single["goodput_qps"] else float("inf")
    )
    effective = min(n_workers, cores)
    if effective >= 4:
        required_ratio = 2.5
    elif effective >= 2:
        required_ratio = 0.625 * effective
    else:
        required_ratio = None  # 1 core: nothing to scale onto
    gates["fleet_goodput_scaling"] = (
        ratio >= required_ratio if required_ratio is not None else True
    )
    gates["legs_all_resolved"] = (
        single["unresolved"] == 0 and fleet_leg["unresolved"] == 0
    )
    gates["scaling_legs_zero_orphans"] = not any(
        _pid_alive(p) for p in pids1 + pidsN
    )

    entry = {
        "mix": "fleet",
        "mode": "open-fleet",
        "n_workers": n_workers,
        "cores": cores,
        "capacity_qps_1worker": round(cap1, 1),
        "rate_qps": round(rate, 1),
        "deadline_ms": {k: round(v * 1e3, 1)
                        for k, v in deadlines.items()},
        "single": single,
        "fleet": fleet_leg,
        "goodput_ratio": round(ratio, 2),
        "required_ratio": required_ratio,
    }

    # -- leg 3: chaos — SIGKILL under load, autoscale-first ------------
    if kill:
        chaos_dur = max(duration, 4.0)
        workdir = tempfile.mkdtemp(prefix="bench-fleet-chaos-")
        telemetry_dir = os.path.join(workdir, "telemetry")
        os.makedirs(telemetry_dir, exist_ok=True)
        # the parent joins the fleet's telemetry plane (rank 99, clear
        # of the workers' ranks) so the journal-mode watcher reads the
        # router's latency histograms from a shard like any other
        # process — no shared memory with the control loop
        os.environ["PADDLE_TPU_TELEMETRY_DIR"] = telemetry_dir
        os.environ["PADDLE_TRAINER_ID"] = "99"
        os.environ["PADDLE_TPU_TELEMETRY_INTERVAL"] = "0.25"
        timeline.ensure_publisher()
        c0 = observability.get_counters()
        srvC, fleetC = start_fleet(
            "fleet_chaos", n_workers - 1, max_replicas=n_workers,
            workdir=workdir,
            env={"PADDLE_TPU_TELEMETRY_INTERVAL": "0.25"},
        )
        watcher = Watcher(
            latency_metric="serving.request_latency.fleet_chaos",
            slo_p99_s=deadlines["interactive"],
            journal_dir=telemetry_dir,
            dead_process_timeout=3.0,
        )
        autoscaler = FleetAutoscaler(
            fleetC, breach_after=2, idle_after=10 ** 9, cooldown_s=5.0,
        )
        ctl = BrownoutController(
            srvC, slo_p99_s=deadlines["interactive"], watcher=watcher,
            escalate_after=2, recover_after=2, interval=0.25,
            autoscaler=autoscaler,
        )
        ctl.start()
        victim = fleetC.worker_pids()[0]

        def _assassin():
            time.sleep(chaos_dur / 3.0)
            os.kill(victim, signal.SIGKILL)

        killer = threading.Thread(target=_assassin, daemon=True)
        killer.start()
        chaos = _overload_leg(srvC, "fleet_chaos", build, rate,
                              chaos_dur, deadlines, shed=True)
        killer.join()
        # respawn-to-strength: the supervisor restores the corpse (and
        # the autoscaler's spare may land on top) while the backlog
        # drains; full strength = the n-1 the leg started with
        target = n_workers - 1
        wait_until = time.perf_counter() + 120.0
        while (time.perf_counter() < wait_until
               and fleetC.healthy_count() < target):
            time.sleep(0.5)
        healthy_end = fleetC.healthy_count()
        ctl.stop()
        c1 = observability.get_counters()
        first_scale = fleetC.first_scale_out_state
        pidsC = fleetC.worker_pids()
        srvC.close(timeout=120)

        def delta(name):
            return c1.get(name, 0) - c0.get(name, 0)

        gates["chaos_all_resolved"] = chaos["unresolved"] == 0
        gates["chaos_worker_death_detected"] = (
            delta("serving.fleet.worker_deaths") >= 1
        )
        gates["chaos_respawned"] = delta("serving.fleet.respawns") >= 1
        gates["chaos_scaled_out"] = delta("serving.fleet.scale_outs") >= 1
        # the brownout ladder's first rung is CAPACITY: the first
        # scale-out must precede any shed of this leg's traffic
        gates["chaos_scale_out_before_shed"] = (
            first_scale is not None
            and first_scale["shed"] - c0.get("serving.shed", 0) <= 0
        )
        gates["chaos_respawn_to_strength"] = healthy_end >= target
        gates["chaos_zero_orphans"] = not any(
            _pid_alive(p) for p in pidsC
        )
        entry["chaos"] = {
            **chaos,
            "victim_pid": victim,
            "healthy_end": healthy_end,
            "target_strength": target,
            "worker_deaths": delta("serving.fleet.worker_deaths"),
            "respawns": delta("serving.fleet.respawns"),
            "reroutes": delta("serving.fleet.reroutes"),
            "scale_outs": delta("serving.fleet.scale_outs"),
            "brownout_scale_outs": delta("serving.brownout_scale_outs"),
            "dead_process_findings": delta(
                "watch.findings.dead_process"
            ),
            "first_scale_out_shed_delta": (
                None if first_scale is None
                else first_scale["shed"] - c0.get("serving.shed", 0)
            ),
        }

    entry["gates"] = gates
    entry["ok"] = all(gates.values())
    results["fleet"] = entry
    return entry


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--smoke", action="store_true",
                    help="CI-sized run (short durations, small context)")
    ap.add_argument("--dump", default=None,
                    help="write the observability snapshot JSON here")
    ap.add_argument("--duration", type=float, default=None,
                    help="seconds of load per mix (default 2 smoke / 6)")
    ap.add_argument("--mix", default=None,
                    help="comma list of mixes to run "
                         "(bert,resnet,ctr,gpt,overload,failover,"
                         "live_update; default: all)")
    ap.add_argument("--fleet", type=int, default=0, metavar="N",
                    help="run the overload mix against an N-worker "
                         "process fleet (ProcessReplicaSet) instead of "
                         "the in-process servers")
    ap.add_argument("--fleet-kill", action="store_true",
                    help="with --fleet: add the chaos leg — SIGKILL a "
                         "worker mid-run and gate failover, respawn, "
                         "autoscale-before-shed, zero orphans")
    args = ap.parse_args(argv)
    duration = args.duration or (2.0 if args.smoke else 6.0)
    all_mixes = ("bert", "resnet", "ctr", "gpt", "overload", "failover",
                 "live_update")
    mixes = (
        tuple(m.strip() for m in args.mix.split(",") if m.strip())
        if args.mix else all_mixes
    )
    unknown = [m for m in mixes if m not in all_mixes]
    if unknown:
        print(f"unknown mixes {unknown} (want {all_mixes})",
              file=sys.stderr)
        return 2

    import jax

    on_accel = jax.devices()[0].platform in ("tpu", "gpu")
    results = {}
    gates = {}
    batched = ctr = gpt = None

    if "bert" in mixes:
        bert = bench_classify_mix(
            "bert_classify", "bert", (1, 2, 4, 8), "closed", 8, duration,
            results,
        )
        print(json.dumps(results["bert_classify"]), flush=True)
        # batched-vs-sequential acceptance ratio on the BERT frozen graph
        frozen, build, exe, scope, _ = bert
        batched = bench_batched_vs_sequential(frozen, build, exe, scope)
        print(json.dumps({"mix": "bert_classify", **batched}), flush=True)
        gates["batched_speedup>=3"] = batched["batched_speedup"] >= 3.0
        # the request traces must reconstruct the queue-wait/compute
        # split (tracing is the observability contract of this router)
        gates["bert_trace_reconstruction"] = (
            results["bert_classify"].get("trace_spans", 0) > 0
            and results["bert_classify"].get("trace_vs_hist_consistent")
            is not False
        )

    if "resnet" in mixes:
        # open-loop rate sized to ~60-70% of the CPU leg's service
        # capacity so latency reflects batching, not a saturated queue
        bench_classify_mix(
            "resnet_classify", "resnet", (1, 2, 4), "open",
            40 if not args.smoke else 10, duration, results,
        )
        print(json.dumps(results["resnet_classify"]), flush=True)

    if "ctr" in mixes:
        # recommendation mix: fused-embedding DeepFM ranker (PR 11)
        ctr = bench_ctr_rank(args.smoke, duration, results)
        print(json.dumps(ctr), flush=True)
        gates["ctr_qps>0"] = (ctr["qps"] or 0) > 0
        gates["ctr_fused_sites==2"] = (
            ctr["fused_lookup_sites_frozen"] == 2
        )

    if "gpt" in mixes:
        gpt = bench_gpt_generate(args.smoke, results)
        print(json.dumps(gpt), flush=True)
        gates["kv_decode_speedup>=5"] = gpt["kv_decode_speedup"] >= 5.0
        gates["kv_parity"] = bool(gpt["kv_parity"])

    if "overload" in mixes:
        if args.fleet:
            # process-fleet legs: the overload arrival process against
            # real worker processes (plus the SIGKILL chaos leg when
            # --fleet-kill is set)
            fl = bench_fleet(args.smoke, duration, results,
                             n_workers=args.fleet,
                             kill=args.fleet_kill)
            print(json.dumps(fl), flush=True)
            gates["fleet"] = fl["ok"]
        else:
            # r15 fault-domain goodput mix (2x sustainable arrival rate)
            ov = bench_overload(args.smoke, duration, results)
            print(json.dumps(ov), flush=True)
            gates["overload"] = ov["ok"]

    if "failover" in mixes:
        # r15 replica-kill chaos mix (3x window duration)
        fo = bench_failover(args.smoke, max(duration, 4.5), results)
        print(json.dumps(fo), flush=True)
        gates["failover"] = fo["ok"]

    if "live_update" in mixes:
        # r18 live-publish mix: delta rollout under load, goodput dip
        # < 10%, zero torn batches
        lu = bench_live_update(args.smoke, max(duration, 3.0), results)
        print(json.dumps(lu), flush=True)
        gates["live_update"] = lu["ok"]

    if args.dump:
        from paddle_tpu import observability

        observability.dump(args.dump)

    summary = {
        "metric": "serving_qps",
        "value": results.get("bert_classify", {}).get("qps"),
        "unit": "req/s (bert_classify closed-loop)",
        "on_accel": on_accel,
        "mixes": {
            k: {
                f: v.get(f)
                for f in ("qps", "p50_ms", "p99_ms", "requests")
            }
            for k, v in results.items()
        },
        "gates": gates,
    }
    if batched is not None:
        summary["batched_speedup"] = batched["batched_speedup"]
        summary["trace_queue_wait_ms"] = results["bert_classify"].get(
            "trace_queue_wait_ms"
        )
        summary["trace_dispatch_ms"] = results["bert_classify"].get(
            "trace_dispatch_ms"
        )
        summary["trace_vs_hist_consistent"] = results[
            "bert_classify"].get("trace_vs_hist_consistent")
    if gpt is not None:
        summary["kv_decode_speedup"] = gpt["kv_decode_speedup"]
        summary["kv_parity"] = gpt["kv_parity"]
    if ctr is not None:
        summary["served_embedding_qps"] = ctr["qps"]
    if "overload" in results:
        summary["goodput_ratio"] = results["overload"]["goodput_ratio"]
    if "failover" in results:
        summary["qps_recovery"] = results["failover"]["qps_recovery"]
    print(json.dumps(summary), flush=True)
    if not all(gates.values()):
        failed = [k for k, v in gates.items() if not v]
        print(f"serving acceptance ratios NOT met: {failed}",
              file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
