"""Serving load generator: checkpoint -> frozen graph -> QPS.

Drives the paddle_tpu.serving router with three traffic mixes and prints
ONE JSON line (bench.py convention):

  * ``bert_classify``  — tiny-BERT sequence classifier, closed-loop
    concurrent clients over buckets (1, 2, 4, 8);
  * ``resnet_classify`` — CIFAR-sized ResNet-18 softmax head, open-loop
    Poisson arrivals (tests deadline-driven partial batches);
  * ``gpt_generate``   — KV-cache generation endpoint (prefill + decode).

Per mix: QPS, p50/p99 request latency (client-measured), batch-size
histogram from the ``serving.bucket_runs.*`` counters, and the frozen
graph's ``Program.estimate()`` roofline as the per-batch lower bound
(estimate vs measured — the PR-7 cross-check; on CPU the v5e peaks make
the ratio an overhead indicator, not a target).

Two acceptance ratios ride along:

  * ``batched_speedup``  — bucket-8 batch throughput vs 8 sequential
    single-request dispatches on the same executable set (>= 3x CPU CI:
    the arXiv:2301.13062 one-wide-program argument applied to serving);
  * ``kv_decode_speedup`` — KV-cache generation vs full-context recompute
    at context >= 256 (>= 5x: the O(1)-per-token decode path).

``--smoke`` shrinks the run for CI; ``--dump PATH`` writes the
observability snapshot for ``stats_report --require serving.``.
"""

from __future__ import annotations

import argparse
import json
import sys
import threading
import time

import numpy as np


def _percentiles(lat):
    lat = np.asarray(sorted(lat))
    if not len(lat):
        return {"p50_ms": None, "p99_ms": None}
    return {
        "p50_ms": round(float(np.percentile(lat, 50)) * 1e3, 3),
        "p99_ms": round(float(np.percentile(lat, 99)) * 1e3, 3),
    }


def _bucket_histogram(endpoint_name):
    from paddle_tpu import observability

    prefix = f"serving.bucket_runs.{endpoint_name}."
    return {
        k[len(prefix):]: v
        for k, v in observability.get_counters().items()
        if k.startswith(prefix)
    }


def _trace_latency_split(endpoint_name):
    """Queue-wait vs dispatch (compute) p50/p99 reconstructed from the
    request traces alone (serving.queue_wait / serving.dispatch spans the
    scheduler records under each request's TraceContext), cross-checked
    against the serving.* histograms: per request, queue_wait + dispatch
    must account for the request latency the endpoint histogram measured
    (mean-level check — the two are recorded by different clocks/sides,
    so the bar is agreement, not equality)."""
    from paddle_tpu import observability

    waits, disps = [], []
    for s in observability.get_spans():
        if (s.get("args") or {}).get("endpoint") != endpoint_name \
                or "trace_id" not in s:
            continue
        if s["name"] == "serving.queue_wait":
            waits.append(s["dur"] / 1e6)
        elif s["name"] == "serving.dispatch":
            disps.append(s["dur"] / 1e6)
    if not waits or not disps:
        return {"trace_spans": 0}
    hist = observability.get_histograms().get(
        f"serving.request_latency.{endpoint_name}"
    )
    consistent = None
    if hist and hist["count"]:
        hist_mean = hist["sum"] / hist["count"]
        trace_mean = (sum(waits) / len(waits)) + (sum(disps) / len(disps))
        # ingest/future-resolution overheads ride on the histogram side
        consistent = bool(
            trace_mean <= hist_mean * 1.25 + 2e-3
            and trace_mean >= hist_mean * 0.25
        )
    return {
        "trace_spans": len(waits) + len(disps),
        "trace_queue_wait_ms": _percentiles(waits),
        "trace_dispatch_ms": _percentiles(disps),
        "trace_vs_hist_consistent": consistent,
    }


def _roofline(frozen, bucket, feed_builder):
    """Program.estimate() at the largest bucket: analytic per-batch
    latency lower bound for the frozen graph."""
    try:
        feed = feed_builder(bucket)
        est = frozen.program.estimate(
            feed_shapes={k: tuple(v.shape) for k, v in feed.items()}
        )
        return {
            "est_batch_flops": float(est.total_flops),
            "est_batch_ms": round(est.total_latency * 1e3, 4),
        }
    except Exception as e:  # estimate failures must not kill the bench
        return {"est_error": str(e)[:120]}


def _closed_loop(server, endpoint, feed_builder, n_clients, duration):
    """N clients submit-wait-repeat; returns (latencies, n_done, wall)."""
    lats, lock = [], threading.Lock()
    stop = time.perf_counter() + duration

    def client(seed):
        rng = np.random.RandomState(seed)
        while time.perf_counter() < stop:
            t0 = time.perf_counter()
            fut = server.submit(endpoint, feed_builder(rng))
            fut.result(timeout=60)
            dt = time.perf_counter() - t0
            with lock:
                lats.append(dt)

    t_start = time.perf_counter()
    threads = [
        threading.Thread(target=client, args=(i,)) for i in range(n_clients)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    return lats, len(lats), time.perf_counter() - t_start


def _build_classifier_endpoint(kind, scope, seed=7):
    """Build + 2-step-train + freeze a tiny classifier; returns
    (frozen, sample_feed_builder, exe)."""
    import paddle_tpu as fluid
    from paddle_tpu import layers
    from paddle_tpu.framework.scope import scope_guard

    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = seed
    with fluid.program_guard(main, startup):
        if kind == "bert":
            from paddle_tpu.models.bert import BertConfig, bert_encoder

            cfg = BertConfig.tiny()
            s = 16
            ids = fluid.data("ids", [-1, s], "int64")
            types = fluid.data("types", [-1, s], "int64")
            mask = fluid.data("mask", [-1, s], "float32")
            seq = bert_encoder(ids, types, mask, cfg, is_test=False)
            # [CLS]-style pooled head: first token's hidden state
            pooled = layers.slice(seq, [1], [0], [1])
            logits = layers.fc(pooled, 4)
            prob = layers.softmax(logits)
            lab = fluid.data("lab", [-1, 1], "int64")
            loss = layers.mean(
                layers.softmax_with_cross_entropy(logits, lab)
            )
            feeds = ("ids", "types", "mask")

            def build(rng_or_b):
                if isinstance(rng_or_b, int):
                    b = rng_or_b
                    return {
                        "ids": np.zeros((b, s), np.int64),
                        "types": np.zeros((b, s), np.int64),
                        "mask": np.ones((b, s), np.float32),
                    }
                rng = rng_or_b
                return {
                    "ids": rng.randint(0, cfg.vocab_size, s).astype(
                        np.int64
                    ),
                    "types": np.zeros(s, np.int64),
                    "mask": np.ones(s, np.float32),
                }
        else:
            from paddle_tpu.models.resnet import resnet

            img = fluid.data("image", [-1, 3, 32, 32], "float32")
            logits = resnet(img, class_num=10, depth=18, is_test=False)
            prob = layers.softmax(logits)
            lab = fluid.data("lab", [-1, 1], "int64")
            loss = layers.mean(
                layers.softmax_with_cross_entropy(logits, lab)
            )
            feeds = ("image",)

            def build(rng_or_b):
                if isinstance(rng_or_b, int):
                    return {
                        "image": np.zeros(
                            (rng_or_b, 3, 32, 32), np.float32
                        ),
                    }
                return {
                    "image": rng_or_b.randn(3, 32, 32).astype(np.float32),
                }
        fluid.optimizer.Adam(1e-3).minimize(loss, startup)

    exe = fluid.Executor()
    with scope_guard(scope):
        exe.run(startup, scope=scope)
    from paddle_tpu.serving import freeze_program

    frozen = freeze_program(main, [prob], feed_names=feeds)
    return frozen, build, exe


def bench_classify_mix(name, kind, buckets, mode, load, duration,
                       results):
    from paddle_tpu.framework.scope import Scope
    from paddle_tpu.serving import Server
    from paddle_tpu.serving.router import EndpointConfig

    scope = Scope()
    frozen, build, exe = _build_classifier_endpoint(kind, scope)
    server = Server()
    server.add_endpoint(
        name, None,
        EndpointConfig(buckets=buckets, max_wait_ms=4.0, max_queue=4096),
        frozen=frozen, executor=exe, scope=scope,
    )
    t0 = time.perf_counter()
    server.warmup()
    warmup_s = time.perf_counter() - t0

    if mode == "closed":
        lats, n, wall = _closed_loop(server, name, build, load, duration)
    else:
        lats, n, wall = _poisson_loop(server, name, build, load, duration)
    server.drain(timeout=30)
    entry = {
        "mix": name,
        "mode": mode,
        "load": load,
        "requests": n,
        "qps": round(n / wall, 2) if wall > 0 else None,
        "warmup_s": round(warmup_s, 2),
        "buckets": _bucket_histogram(name),
        **_percentiles(lats),
        **_roofline(frozen, buckets[-1], build),
        **_trace_latency_split(name),
    }
    results[name] = entry
    return frozen, build, exe, scope, entry


def _poisson_loop(server, endpoint, feed_builder, rate_qps, duration):
    """Open-loop Poisson arrivals; latency = submit -> future resolve,
    stamped by a done-callback at RESOLVE time (waiting and then reading
    the wall clock would inflate early requests' latency to ~run
    length)."""
    rng = np.random.RandomState(1234)
    lats, lock = [], threading.Lock()
    futs = []
    t_start = time.perf_counter()
    stop = t_start + duration
    next_t = t_start
    while time.perf_counter() < stop:
        next_t += rng.exponential(1.0 / rate_qps)
        delay = next_t - time.perf_counter()
        if delay > 0:
            time.sleep(delay)
        t0 = time.perf_counter()
        fut = server.submit(endpoint, feed_builder(rng))

        def _done(f, t0=t0):
            dt = time.perf_counter() - t0
            with lock:
                lats.append(dt)

        fut.add_done_callback(_done)
        futs.append(fut)
    for f in futs:
        f.result(timeout=60)
    wall = time.perf_counter() - t_start
    return lats, len(futs), wall


def bench_batched_vs_sequential(frozen, build, exe, scope, bucket=8,
                                rounds=3, iters=10):
    """Throughput of ONE bucket-N batch vs N sequential single-request
    dispatches against the same warm executables."""
    from paddle_tpu.framework.scope import scope_guard

    fetch = list(frozen.fetch_names)
    feed_b = build(bucket)
    feed_1 = build(1)
    with scope_guard(scope):
        exe.run(frozen.program, feed=feed_b, fetch_list=fetch, scope=scope)
        exe.run(frozen.program, feed=feed_1, fetch_list=fetch, scope=scope)
        best_b = best_1 = float("inf")
        for _ in range(rounds):
            t0 = time.perf_counter()
            for _ in range(iters):
                exe.run(frozen.program, feed=feed_b, fetch_list=fetch,
                        scope=scope)
            best_b = min(best_b, (time.perf_counter() - t0) / iters)
            t0 = time.perf_counter()
            for _ in range(iters):
                for _ in range(bucket):
                    exe.run(frozen.program, feed=feed_1, fetch_list=fetch,
                            scope=scope)
            best_1 = min(best_1, (time.perf_counter() - t0) / iters)
    qps_batched = bucket / best_b
    qps_seq = bucket / best_1
    return {
        "bucket": bucket,
        "batched_qps": round(qps_batched, 1),
        "sequential_qps": round(qps_seq, 1),
        "batched_speedup": round(qps_batched / qps_seq, 2),
    }


def bench_ctr_rank(smoke, duration, results):
    """Recommendation traffic mix (PR 11): a DeepFM CTR ranker served
    through the continuous-batching router — per-slot sparse lookups fused
    into one ``fused_lookup_table`` per table width by the embedding
    engine, frozen, and dispatched per bucket. Records the FIRST
    served-embedding QPS baseline (no ratio gate yet: the number exists so
    the next round has a denominator)."""
    import paddle_tpu as fluid
    from paddle_tpu.embedding import fuse_lookups
    from paddle_tpu.framework.scope import Scope, scope_guard
    from paddle_tpu.models.deepfm import DeepFMConfig, deepfm
    from paddle_tpu.serving import Server, freeze_program
    from paddle_tpu.serving.router import EndpointConfig

    cfg = DeepFMConfig(
        vocab_size=4096, num_fields=13, embed_dim=16, mlp_sizes=(64, 32),
    )
    scope = Scope()
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 13
    with fluid.program_guard(main, startup):
        ids = fluid.data("feat_ids", [-1, cfg.num_fields], "int64")
        label = fluid.data("label", [-1, 1], "float32")
        loss, prob = deepfm(ids, label, cfg, per_slot=True)
        fused = fuse_lookups(main)
        fluid.optimizer.Adam(1e-3).minimize(loss, startup)
    assert fused == 2, f"expected 2 fused lookup sites, got {fused}"
    exe = fluid.Executor()
    with scope_guard(scope):
        exe.run(startup, scope=scope)
    frozen = freeze_program(main, [prob], feed_names=("feat_ids",))
    fused_frozen = sum(
        1 for op in frozen.program.global_block.ops
        if op.type == "fused_lookup_table"
    )

    server = Server()
    server.add_endpoint(
        "ctr_rank", None,
        EndpointConfig(buckets=(1, 2, 4, 8), max_wait_ms=4.0,
                       max_queue=4096),
        frozen=frozen, executor=exe, scope=scope,
    )
    server.warmup()

    def build(rng_or_b):
        if isinstance(rng_or_b, int):
            return {
                "feat_ids": np.zeros(
                    (rng_or_b, cfg.num_fields), np.int64
                ),
            }
        # power-law ids: the heavy-tailed CTR id distribution
        return {
            "feat_ids": (
                cfg.vocab_size * rng_or_b.power(0.35, cfg.num_fields)
            ).astype(np.int64),
        }

    lats, n, wall = _closed_loop(server, "ctr_rank", build, 8, duration)
    server.drain(timeout=30)
    entry = {
        "mix": "ctr_rank",
        "mode": "closed",
        "load": 8,
        "requests": n,
        "qps": round(n / wall, 2) if wall > 0 else None,
        "fused_lookup_sites_frozen": fused_frozen,
        "buckets": _bucket_histogram("ctr_rank"),
        **_percentiles(lats),
        **_roofline(frozen, 8, build),
        "baseline_note": "first served-embedding QPS baseline (r11)",
    }
    results["ctr_rank"] = entry
    return entry


def bench_gpt_generate(smoke, results):
    """KV-cache generation endpoint + the decode-vs-recompute ratio."""
    from paddle_tpu.models.gpt import GPTConfig
    from paddle_tpu.serving import GPTGenerator, Server
    from paddle_tpu.serving.generate import GPTGenerateRunner
    from paddle_tpu.serving.router import EndpointConfig

    # context >= 256 per the acceptance bar; 512 keeps the recompute
    # baseline's O(S) cost well clear of decode dispatch overhead on the
    # CPU CI leg (at 256 the ratio sits right at 5x and contention noise
    # can dip it under)
    context, new_tokens = (512, 32) if not smoke else (512, 24)
    cfg = GPTConfig(
        vocab_size=512, hidden_size=128, num_layers=2, num_heads=4,
        intermediate_size=256, max_position=context + new_tokens,
        use_fused_attention=False,
    )
    gen = GPTGenerator(
        cfg, batch=1, context_len=context, max_len=context + new_tokens
    )
    gen.init_params(seed=11)
    rng = np.random.RandomState(0)
    ctx = rng.randint(0, cfg.vocab_size, (1, context)).astype(np.int64)

    # decode vs full-recompute, best-of-3 (tunneled-chip convention)
    best_kv = best_full = float("inf")
    gen.generate(ctx, new_tokens)
    gen.generate_full_recompute(ctx, new_tokens)
    for _ in range(3):
        t0 = time.perf_counter()
        kv_tokens = gen.generate(ctx, new_tokens)
        best_kv = min(best_kv, time.perf_counter() - t0)
        t0 = time.perf_counter()
        full_tokens = gen.generate_full_recompute(ctx, new_tokens)
        best_full = min(best_full, time.perf_counter() - t0)
    parity = bool(np.array_equal(kv_tokens, full_tokens))

    # the generate endpoint through the router (closed-loop, 2 clients)
    server = Server()
    runner = GPTGenerateRunner(gen, max_new_tokens=new_tokens)
    server.add_endpoint(
        "gpt_generate", runner,
        EndpointConfig(buckets=(1,), max_wait_ms=1.0),
    )
    duration = 2.0 if smoke else 6.0

    def build(rng):
        return {
            "context_ids": rng.randint(0, cfg.vocab_size, context).astype(
                np.int64
            )
        }

    lats, n, wall = _closed_loop(server, "gpt_generate", build, 2,
                                 duration)
    server.drain(timeout=30)
    entry = {
        "mix": "gpt_generate",
        "mode": "closed",
        "load": 2,
        "context": context,
        "new_tokens": new_tokens,
        "requests": n,
        "qps": round(n / wall, 3) if wall > 0 else None,
        "decode_tok_s": round(new_tokens / best_kv, 1),
        "recompute_tok_s": round(new_tokens / best_full, 1),
        "kv_decode_speedup": round(best_full / best_kv, 2),
        "kv_parity": parity,
        **_percentiles(lats),
    }
    results["gpt_generate"] = entry
    return entry


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--smoke", action="store_true",
                    help="CI-sized run (short durations, small context)")
    ap.add_argument("--dump", default=None,
                    help="write the observability snapshot JSON here")
    ap.add_argument("--duration", type=float, default=None,
                    help="seconds of load per mix (default 2 smoke / 6)")
    args = ap.parse_args(argv)
    duration = args.duration or (2.0 if args.smoke else 6.0)

    import jax

    on_accel = jax.devices()[0].platform in ("tpu", "gpu")
    results = {}

    bert = bench_classify_mix(
        "bert_classify", "bert", (1, 2, 4, 8), "closed", 8, duration,
        results,
    )
    print(json.dumps(results["bert_classify"]), flush=True)
    # batched-vs-sequential acceptance ratio on the BERT frozen graph
    frozen, build, exe, scope, _ = bert
    batched = bench_batched_vs_sequential(frozen, build, exe, scope)
    print(json.dumps({"mix": "bert_classify", **batched}), flush=True)

    # open-loop rate sized to ~60-70% of the CPU leg's service capacity so
    # the latency numbers reflect batching behavior, not a saturated queue
    bench_classify_mix(
        "resnet_classify", "resnet", (1, 2, 4), "open",
        40 if not args.smoke else 10, duration, results,
    )
    print(json.dumps(results["resnet_classify"]), flush=True)

    # recommendation mix: fused-embedding DeepFM ranker (PR 11) — records
    # the first served-embedding QPS baseline
    ctr = bench_ctr_rank(args.smoke, duration, results)
    print(json.dumps(ctr), flush=True)

    gpt = bench_gpt_generate(args.smoke, results)
    print(json.dumps(gpt), flush=True)

    if args.dump:
        from paddle_tpu import observability

        observability.dump(args.dump)

    summary = {
        "metric": "serving_qps",
        "value": results["bert_classify"]["qps"],
        "unit": "req/s (bert_classify closed-loop)",
        "on_accel": on_accel,
        "mixes": {
            k: {
                f: v.get(f)
                for f in ("qps", "p50_ms", "p99_ms", "requests")
            }
            for k, v in results.items()
        },
        "batched_speedup": batched["batched_speedup"],
        "kv_decode_speedup": gpt["kv_decode_speedup"],
        "kv_parity": gpt["kv_parity"],
        "served_embedding_qps": ctr["qps"],
        "trace_queue_wait_ms": results["bert_classify"].get(
            "trace_queue_wait_ms"
        ),
        "trace_dispatch_ms": results["bert_classify"].get(
            "trace_dispatch_ms"
        ),
        "trace_vs_hist_consistent": results["bert_classify"].get(
            "trace_vs_hist_consistent"
        ),
    }
    print(json.dumps(summary), flush=True)
    ok = (
        batched["batched_speedup"] >= 3.0
        and gpt["kv_decode_speedup"] >= 5.0
        and gpt["kv_parity"]
        and (ctr["qps"] or 0) > 0
        and ctr["fused_lookup_sites_frozen"] == 2
        # the request traces must reconstruct the queue-wait/compute
        # split (tracing is the observability contract of this router)
        and results["bert_classify"].get("trace_spans", 0) > 0
        and results["bert_classify"].get("trace_vs_hist_consistent")
        is not False
    )
    if not ok:
        print("serving acceptance ratios NOT met", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
