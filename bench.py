"""Benchmark driver: BERT-base MLM train step, tokens/sec on one chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
The reference publishes no numbers (BASELINE.md), so vs_baseline is reported
against the recorded target in BASELINE.json once filled; until then 1.0.
"""

from __future__ import annotations

import json
import sys
import time

import numpy as np


def main():
    import jax

    import paddle_tpu as fluid
    from paddle_tpu.framework.scope import Scope
    from paddle_tpu.models import BertConfig, bert_pretrain
    from paddle_tpu.optimizer import Adam

    on_accel = jax.devices()[0].platform != "cpu"
    b, s = (32, 128) if on_accel else (4, 64)
    cfg = BertConfig.base() if on_accel else BertConfig.tiny()

    main_prog, startup = fluid.Program(), fluid.Program()
    main_prog.random_seed = startup.random_seed = 1
    with fluid.program_guard(main_prog, startup):
        ids = fluid.data("ids", [b, s], "int64")
        types = fluid.data("types", [b, s], "int64")
        mask = fluid.data("mask", [b, s], "float32")
        labels = fluid.data("labels", [b, s], "int64")
        loss = bert_pretrain(ids, types, mask, labels, cfg)
        Adam(1e-4).minimize(loss, startup)

    scope = Scope()
    exe = fluid.Executor()
    exe.run(startup, scope=scope)

    rng = np.random.RandomState(0)
    feed = {
        "ids": rng.randint(0, cfg.vocab_size, (b, s)).astype("int32"),
        "types": rng.randint(0, cfg.type_vocab_size, (b, s)).astype("int32"),
        "mask": np.ones((b, s), "float32"),
        "labels": rng.randint(0, cfg.vocab_size, (b, s)).astype("int32"),
    }

    # warmup: compile + first dispatch
    for _ in range(2):
        exe.run(main_prog, feed=feed, fetch_list=[loss], scope=scope)

    n_steps = 20 if on_accel else 5
    t0 = time.perf_counter()
    for _ in range(n_steps):
        (lv,) = exe.run(main_prog, feed=feed, fetch_list=[loss], scope=scope)
    lv = float(np.asarray(lv).reshape(-1)[0])  # blocks on the last step
    dt = time.perf_counter() - t0

    tokens_per_sec = n_steps * b * s / dt
    assert np.isfinite(lv), "loss went non-finite during benchmark"
    print(
        json.dumps(
            {
                "metric": "bert_base_mlm_train_tokens_per_sec"
                if on_accel
                else "bert_tiny_mlm_train_tokens_per_sec_cpu",
                "value": round(tokens_per_sec, 1),
                "unit": "tokens/s",
                "vs_baseline": 1.0,
            }
        )
    )


if __name__ == "__main__":
    sys.exit(main())
