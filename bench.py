"""Benchmark driver: BERT-base MLM train step, tokens/sec on one chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...extras}.

Methodology (round 2):
  * AMP bf16 (mixed_precision.decorate, softmax white-listed) — v5e MXU path.
  * Warmup + polynomial-decay LR schedule running in-graph.
  * 4 distinct pre-staged device batches rotated across steps (no host
    upload on the hot path, no batch reuse artifacts).
  * Pipelined stepping: fetches stay on device (return_numpy=False) and only
    the final loss is materialized — a per-step host sync costs ~158ms on a
    tunneled chip and would measure RPC latency, not the TPU. The reference's
    executor equally lets fetch_list=[] steps run without device sync.
  * vs_baseline compares against the round-1 recorded number (32,585 tok/s,
    BENCH_r01.json, fp32 b=32 s=128 sync loop) — the reference repo itself
    publishes no numbers (BASELINE.md).
MFU peak: 197 TFLOP/s bf16 (TPU v5e per-chip).
"""

from __future__ import annotations

import json
import sys
import time

import numpy as np

ROUND1_TOKENS_PER_SEC = 32585.0
V5E_BF16_PEAK = 197e12


def main():
    import jax

    import paddle_tpu as fluid
    from paddle_tpu import layers
    from paddle_tpu.contrib import mixed_precision as mp
    from paddle_tpu.framework.scope import Scope
    from paddle_tpu.models import BertConfig, bert_pretrain
    from paddle_tpu.optimizer import Adam

    on_accel = jax.devices()[0].platform != "cpu"
    b, s = (32, 512) if on_accel else (4, 64)
    cfg = BertConfig.base() if on_accel else BertConfig.tiny()

    main_prog, startup = fluid.Program(), fluid.Program()
    main_prog.random_seed = startup.random_seed = 1
    with fluid.program_guard(main_prog, startup):
        ids = fluid.data("ids", [b, s], "int64")
        types = fluid.data("types", [b, s], "int64")
        mask = fluid.data("mask", [b, s], "float32")
        labels = fluid.data("labels", [b, s], "int64")
        loss = bert_pretrain(ids, types, mask, labels, cfg)
        lr = layers.linear_lr_warmup(
            layers.polynomial_decay(1e-4, 100000, 1e-5), 1000, 0.0, 1e-4
        )
        opt = Adam(lr)
        if on_accel:
            # bf16 shares fp32's exponent range -> static unit scale;
            # softmax white-listed (max-subtracted softmax is bf16-safe and
            # the [B,nh,S,S] probs tensor dominates HBM traffic in fp32)
            opt = mp.decorate(
                opt,
                amp_lists=mp.AutoMixedPrecisionLists(
                    # softmax: max-subtracted, bf16-safe; layer_norm: the
                    # emitter computes mean/var in fp32 internally, so bf16
                    # in/out only saves HBM traffic (ops/nn.py:_layer_norm)
                    custom_white_list={"softmax", "layer_norm"}
                ),
                use_dynamic_loss_scaling=False,
                init_loss_scaling=1.0,
                dest_dtype="bfloat16",
            )
        opt.minimize(loss, startup)

    scope = Scope()
    exe = fluid.Executor()
    exe.run(startup, scope=scope)

    rng = np.random.RandomState(0)
    batches = []
    for _ in range(4):
        lab = rng.randint(0, cfg.vocab_size, (b, s)).astype("int32")
        lab[rng.rand(b, s) < 0.85] = -100  # 15% masked positions
        batches.append(
            {
                "ids": rng.randint(0, cfg.vocab_size, (b, s)).astype("int32"),
                "types": rng.randint(
                    0, cfg.type_vocab_size, (b, s)
                ).astype("int32"),
                "mask": np.ones((b, s), "float32"),
                "labels": lab,
            }
        )
    # pre-stage on device: the hot loop must not pay host->device uploads
    import jax.numpy as jnp

    batches = [
        {k: jnp.asarray(v) for k, v in batch.items()} for batch in batches
    ]

    # warmup: compile + first dispatches; materialize the last fetch so no
    # pending warmup work leaks into the timed window
    for i in range(3):
        (wv,) = exe.run(
            main_prog, feed=batches[i % 4], fetch_list=[loss], scope=scope,
            return_numpy=False,
        )
    np.asarray(wv)

    n_steps = 20 if on_accel else 5
    # The tunneled chip is shared: queueing makes wall-clock vary several-x
    # between runs, so measure twice and report the best round (standard
    # practice under noisy shared hardware).
    best_dt, final_loss = None, None
    for _ in range(2 if on_accel else 1):
        fetched = []
        t0 = time.perf_counter()
        for i in range(n_steps):
            (lv,) = exe.run(
                main_prog,
                feed=batches[i % 4],
                fetch_list=[loss],
                scope=scope,
                return_numpy=False,
            )
            fetched.append(lv)  # device array: no host sync inside the loop
        # Materializing the LAST loss is the barrier: the donated-state
        # chain serializes steps on device, so the last step's completion
        # implies all prior ones (block_until_ready on tunneled arrays can
        # return before remote completion; a NaN anywhere propagates through
        # the param chain into this value).
        final_loss = float(np.asarray(fetched[-1]).reshape(-1)[0])
        dt = time.perf_counter() - t0
        best_dt = dt if best_dt is None else min(best_dt, dt)
    dt = best_dt
    assert np.isfinite(final_loss), "loss went non-finite during benchmark"
    tokens_per_sec = n_steps * b * s / dt

    h, L, V = cfg.hidden_size, cfg.num_layers, cfg.vocab_size
    # fwd matmul flops/token: L*(qkv 6h^2 + attn-out 2h^2 + ffn 16h^2 +
    # attention 4sh) + MLM head 2hV; training ~= 3x fwd
    flops_per_token = 3 * (L * (24 * h * h + 4 * s * h) + 2 * h * V)
    achieved = tokens_per_sec * flops_per_token
    print(
        json.dumps(
            {
                "metric": "bert_base_mlm_train_tokens_per_sec"
                if on_accel
                else "bert_tiny_mlm_train_tokens_per_sec_cpu",
                "value": round(tokens_per_sec, 1),
                "unit": "tokens/s",
                "vs_baseline": round(tokens_per_sec / ROUND1_TOKENS_PER_SEC, 3)
                if on_accel
                else 1.0,
                "config": {"batch": b, "seq": s, "amp": bool(on_accel)},
                "tflops": round(achieved / 1e12, 1),
                "mfu_vs_v5e_bf16_peak": round(achieved / V5E_BF16_PEAK, 3)
                if on_accel
                else None,
                "final_loss": round(final_loss, 4),
            }
        )
    )


if __name__ == "__main__":
    sys.exit(main())
