"""Benchmark driver: BERT-base MLM (primary metric) + ResNet-50 + YOLOv3
+ long-context GPT (S=2048/4096/8192 through the KV-tiled flash kernel)
+ DeepFM CTR + Mask R-CNN, all on one chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...extras}
— the BERT tokens/s stays the headline metric (comparable across rounds);
the other configs ride in "extra_metrics" so regressions are visible per
round (VERDICT r2 item 4).

Methodology (round 4):
  * AMP bf16 (mixed_precision.decorate) — v5e MXU path.
  * Every leg reports tflops + MFU (VERDICT r3 item 2): transformer legs
    use the analytic matmul-flop model (XLA's cost analysis cannot see
    inside the Pallas attention custom-calls); vision/CTR legs use the
    compiled executable's own cost analysis (Executor.flops).
  * Every leg records per-round throughput samples so chip-contention
    claims are evidenced in the artifact (VERDICT r3 item 7).
  * MLM head computes logits on the MASKED positions only via mask_pos
    gather (the reference BERT pretraining contract); the flop model
    scales the head term by P/(B*S) accordingly.
  * Causal GPT attention counts s/2 useful key positions per token (the
    standard MFU convention; the tiled kernel skips the dead tiles, so
    hardware work tracks the same ratio).
  * Pre-staged device batches, pipelined steps, device-side fetches; the
    final loss materialization is the step barrier (see round-2 notes).
  * Shared tunneled chip: BERT/GPT best-of-2, vision/CTR best-of-3
    (20-step windows) — small-batch configs swing up to 3x under
    contention. YOLOv3 runs b=16 from round 4 (the b=8 leg swung 3x,
    VERDICT r3 weak item 10).
MFU peak: 197 TFLOP/s bf16 (TPU v5e per-chip).
"""

from __future__ import annotations

import json
import sys
import time

import numpy as np

ROUND1_TOKENS_PER_SEC = 32585.0
ROUND2_RESNET_IMG_S = 1631.0
# round-3 recorded "~270-350 img/s" at b=8 (BASELINE.md r3); 300 is the
# midpoint — the denominator for the stabler b=16 leg introduced in r4
ROUND3_YOLO_IMG_S = 300.0
ROUND3_GPT2048_TOK_S = 50787.0
# r5 Mask R-CNN: AMP bf16 + dynamic loss scaling, 4x1-image unroll
# (BASELINE.md r5 table) — denominator for the r6 batched leg
ROUND5_MASK_RCNN_IMG_S = 20.99
# r5 DeepFM: per-slot gather path, b=4096 criteo shape (BENCH_r05 deepfm
# leg) — denominator for the r11 fused-embedding leg (acceptance >= 5x)
ROUND5_DEEPFM_EX_S = 266671.4


def _amp(opt):
    from paddle_tpu.contrib import mixed_precision as mp

    return mp.decorate(
        opt,
        amp_lists=mp.AutoMixedPrecisionLists(
            custom_white_list={"softmax", "layer_norm"}
        ),
        use_dynamic_loss_scaling=False,
        init_loss_scaling=1.0,
        dest_dtype="bfloat16",
    )


def _timed_loop(exe, prog, scope, batches, loss, n_steps, rounds):
    """Best-of-N pipelined timing; returns (best_dt, [all dts], loss)."""
    dts, final_loss = [], None
    for _ in range(rounds):
        fetched = []
        t0 = time.perf_counter()
        for i in range(n_steps):
            (lv,) = exe.run(
                prog, feed=batches[i % len(batches)], fetch_list=[loss],
                scope=scope, return_numpy=False,
            )
            fetched.append(lv)
        final_loss = float(np.asarray(fetched[-1]).reshape(-1)[0])
        dts.append(time.perf_counter() - t0)
    assert np.isfinite(final_loss), "loss went non-finite during benchmark"
    return min(dts), dts, final_loss


def _mfu_fields(per_step_flops, best_dt, n_steps, on_accel):
    # the SAME configurable peak the live perf.mfu gauge divides by
    # (PADDLE_TPU_PEAK_TFLOPS, default v5e bf16), so offline and live MFU
    # agree by construction
    from paddle_tpu.analysis.cost import peak_flops

    achieved = per_step_flops * n_steps / best_dt
    return {
        "tflops": round(achieved / 1e12, 1),
        "mfu_vs_v5e_bf16_peak": (
            round(achieved / peak_flops(), 3) if on_accel else None
        ),
        # the denominator actually used: when PADDLE_TPU_PEAK_TFLOPS
        # overrides the v5e default the key above keeps its historical
        # name but this field keeps the artifact honest
        "mfu_peak_tflops": round(peak_flops() / 1e12, 1),
    }


def _samples(unit_count, dts):
    return [round(unit_count / dt, 1) for dt in dts]


def _estimated_step_flops(prog, feed, legacy=None, legacy_name=None,
                          xla_flops=None):
    """Per-step FLOPs from the IR cost model (`Program.estimate`), plus a
    one-time cross-check block against the retired hand-coded closed form
    (r1-r6 bench methodology) and/or XLA's own cost_analysis. >20%
    divergence from the legacy formula is loud on stderr — that formula
    anchored every per-round MFU comparison, so a silent drift would
    rewrite history."""
    est = prog.estimate(
        feed_shapes={k: tuple(np.asarray(v).shape) for k, v in feed.items()}
    )
    fields = {"estimated_step_tflops": round(est.total_flops / 1e12, 6)}
    if legacy:
        div = abs(est.total_flops - legacy) / legacy
        fields["legacy_formula_tflops"] = round(legacy / 1e12, 6)
        fields["divergence_vs_legacy"] = round(div, 3)
        if div > 0.20:
            print(
                f"WARNING: cost-model step FLOPs diverge "
                f"{div:.0%} from the retired {legacy_name or 'closed-form'} "
                f"formula ({est.total_flops / 1e12:.4f} vs "
                f"{legacy / 1e12:.4f} TFLOP/step)",
                file=sys.stderr,
            )
    if xla_flops:
        fields["xla_step_tflops"] = round(xla_flops / 1e12, 6)
        fields["divergence_vs_xla"] = round(
            abs(est.total_flops - xla_flops) / xla_flops, 3
        )
    return est.total_flops, fields


def _perf_gauge_fields(est_step_flops, best_dt, n_steps, on_accel):
    """Live perf.* gauges after a timed loop: the executor-side MFU must
    agree with the offline per-leg number (acceptance: within 2 points).
    Both sides of the delta use the SAME cost-model numerator
    (est_step_flops), so the delta measures only timing skew (gauge's
    mean steady-state window vs offline best-of-N) — never
    estimate-vs-XLA divergence, which flops_model reports separately.
    The executor drops stale perf gauges on every compile-carrying run,
    so the gauge read here is this leg's own."""
    from paddle_tpu import observability as obs
    from paddle_tpu.analysis.cost import peak_flops

    gauges = obs.snapshot()["gauges"]
    mfu = gauges.get("perf.mfu")
    out = {"perf_mfu_gauge": None if mfu is None else round(mfu, 4)}
    if mfu is not None and on_accel:
        offline = est_step_flops * n_steps / best_dt / peak_flops()
        out["perf_mfu_gauge_delta"] = round(mfu - offline, 4)
    return out


def bench_bert(on_accel):
    import jax.numpy as jnp

    import paddle_tpu as fluid
    from paddle_tpu import layers
    from paddle_tpu.framework.scope import Scope
    from paddle_tpu.models import BertConfig, bert_pretrain
    from paddle_tpu.optimizer import Adam

    b, s = (32, 512) if on_accel else (4, 64)
    cfg = BertConfig.base() if on_accel else BertConfig.tiny()
    P = max(1, int(0.15 * b * s))  # max_predictions budget

    main_prog, startup = fluid.Program(), fluid.Program()
    main_prog.random_seed = startup.random_seed = 1
    with fluid.program_guard(main_prog, startup):
        ids = fluid.data("ids", [b, s], "int64")
        types = fluid.data("types", [b, s], "int64")
        mask = fluid.data("mask", [b, s], "float32")
        mask_pos = fluid.data("mask_pos", [P], "int64")
        labels = fluid.data("labels", [P], "int64")
        loss = bert_pretrain(ids, types, mask, labels, cfg,
                             mask_pos=mask_pos)
        lr = layers.linear_lr_warmup(
            layers.polynomial_decay(1e-4, 100000, 1e-5), 1000, 0.0, 1e-4
        )
        opt = Adam(lr)
        if on_accel:
            opt = _amp(opt)
        opt.minimize(loss, startup)

    scope = Scope()
    exe = fluid.Executor()
    exe.run(startup, scope=scope)

    rng = np.random.RandomState(0)
    batches = []
    for _ in range(4):
        pos = rng.choice(b * s, P, replace=False).astype("int32")
        batches.append({
            "ids": rng.randint(0, cfg.vocab_size, (b, s)).astype("int32"),
            "types": rng.randint(0, cfg.type_vocab_size, (b, s)).astype("int32"),
            "mask": np.ones((b, s), "float32"),
            "mask_pos": pos,
            "labels": rng.randint(0, cfg.vocab_size, P).astype("int32"),
        })
    batches = [{k: jnp.asarray(v) for k, v in bt.items()} for bt in batches]

    for i in range(3):
        (wv,) = exe.run(main_prog, feed=batches[i % 4], fetch_list=[loss],
                        scope=scope, return_numpy=False)
    np.asarray(wv)

    n_steps = 20 if on_accel else 5
    dt, dts, final_loss = _timed_loop(
        exe, main_prog, scope, batches, loss, n_steps, 2 if on_accel else 1
    )
    tokens_per_sec = n_steps * b * s / dt

    h, L, V = cfg.hidden_size, cfg.num_layers, cfg.vocab_size
    # retired r1-r6 closed form, kept as the cross-check: fwd matmul
    # flops/token L*(qkv 6h^2 + attn-out 2h^2 + ffn 16h^2 + attention
    # 4sh) + MLM head 2hV * (P/B*s); training ~= 3x fwd
    legacy = 3 * (
        L * (24 * h * h + 4 * s * h) + 2 * h * V * P / (b * s)
    ) * b * s
    step_flops, flops_model = _estimated_step_flops(
        main_prog, batches[0], legacy=legacy, legacy_name="transformer"
    )
    mfu = _mfu_fields(step_flops, dt, n_steps, on_accel)
    return {
        "metric": ("bert_base_mlm_train_tokens_per_sec" if on_accel
                   else "bert_tiny_mlm_train_tokens_per_sec_cpu"),
        "value": round(tokens_per_sec, 1),
        "unit": "tokens/s",
        "vs_baseline": (round(tokens_per_sec / ROUND1_TOKENS_PER_SEC, 3)
                        if on_accel else 1.0),
        "config": {"batch": b, "seq": s, "amp": bool(on_accel),
                   "mask_pos": P},
        "samples": _samples(n_steps * b * s, dts),
        **mfu,
        "flops_model": flops_model,
        **_perf_gauge_fields(step_flops, dt, n_steps, on_accel),
        "final_loss": round(final_loss, 4),
    }


def bench_resnet(on_accel):
    import jax.numpy as jnp

    import paddle_tpu as fluid
    from paddle_tpu.framework.scope import Scope
    from paddle_tpu.models.resnet import resnet_train_net
    from paddle_tpu.optimizer import Momentum

    # b=128 from round 5: the canonical TPU batch amortizes BN-stat and
    # layout overheads (r5 study: b=64 15-20%, b=128 23%, b=256 23.5% MFU;
    # BASELINE.md ResNet batch-scaling table)
    b, hw, depth = (128, 224, 50) if on_accel else (4, 32, 18)
    main_prog, startup = fluid.Program(), fluid.Program()
    main_prog.random_seed = startup.random_seed = 1
    with fluid.program_guard(main_prog, startup):
        image = fluid.data("image", [b, 3, hw, hw])
        label = fluid.data("label", [b, 1], "int64")
        loss, _acc = resnet_train_net(image, label, depth=depth)
        opt = Momentum(0.1, 0.9)
        if on_accel:
            opt = _amp(opt)
        opt.minimize(loss, startup)
    scope = Scope()
    exe = fluid.Executor()
    exe.run(startup, scope=scope)
    rng = np.random.RandomState(0)
    batches = [
        {"image": jnp.asarray(rng.rand(b, 3, hw, hw).astype("float32")),
         "label": jnp.asarray(
             rng.randint(0, 1000, (b, 1)).astype("int32"))}
        for _ in range(2)
    ]
    for i in range(3):
        (wv,) = exe.run(main_prog, feed=batches[i % 2], fetch_list=[loss],
                        scope=scope, return_numpy=False)
    np.asarray(wv)
    step_flops = exe.flops(main_prog, feed=batches[0], fetch_list=[loss],
                           scope=scope)
    # the shared tunneled chip makes vision wall-clocks swing 30%+
    # between rounds; best-of-3 tightens the floor
    n_steps = 20 if on_accel else 3
    dt, dts, final_loss = _timed_loop(
        exe, main_prog, scope, batches, loss, n_steps, 3 if on_accel else 1
    )
    img_s = n_steps * b / dt
    est_flops, flops_model = _estimated_step_flops(
        main_prog, batches[0], xla_flops=step_flops
    )
    mfu = _mfu_fields(step_flops, dt, n_steps, on_accel)
    return {
        "metric": "resnet50_train_images_per_sec" if on_accel
        else "resnet18_tiny_train_images_per_sec_cpu",
        "value": round(img_s, 1),
        "unit": "img/s",
        "vs_baseline": (round(img_s / ROUND2_RESNET_IMG_S, 3)
                        if on_accel else 1.0),
        "config": {"batch": b, "size": hw, "depth": depth,
                   "amp": bool(on_accel)},
        "samples": _samples(n_steps * b, dts),
        **mfu,
        "flops_model": flops_model,
        **_perf_gauge_fields(est_flops, dt, n_steps, on_accel),
        "final_loss": round(final_loss, 4),
    }


def bench_yolov3(on_accel):
    import jax.numpy as jnp

    import paddle_tpu as fluid
    from paddle_tpu.framework.scope import Scope
    from paddle_tpu.models import yolov3
    from paddle_tpu.optimizer import Momentum

    if on_accel:
        # b=64 from round 5: the r5 limiter analysis (BASELINE.md) showed
        # the leg carries a fixed ~20ms/step latency floor (tunnel +
        # shared-chip interleave); b=64 amortizes it (b=16 measured 3-5%
        # MFU, b=64 10-24% depending on contention)
        b, hw = 64, 224
        cfg = yolov3.YoloConfig(class_num=80, scale=0.5)
    else:
        b, hw = 2, 64
        cfg = yolov3.YoloConfig.tiny()
    n_gt = 10
    main_prog, startup = fluid.Program(), fluid.Program()
    main_prog.random_seed = startup.random_seed = 1
    with fluid.program_guard(main_prog, startup):
        image = fluid.data("image", [b, 3, hw, hw])
        gt_box = fluid.data("gt_box", [b, n_gt, 4])
        gt_label = fluid.data("gt_label", [b, n_gt], "int32")
        loss = yolov3.yolov3_train(image, gt_box, gt_label, cfg)
        opt = Momentum(0.01, 0.9)
        if on_accel:
            opt = _amp(opt)
        opt.minimize(loss, startup)
    scope = Scope()
    exe = fluid.Executor()
    exe.run(startup, scope=scope)
    rng = np.random.RandomState(0)
    boxes = rng.rand(b, n_gt, 4).astype("float32") * 0.5
    boxes[..., 2:] += 0.2  # w, h
    batches = [{
        "image": jnp.asarray(rng.rand(b, 3, hw, hw).astype("float32")),
        "gt_box": jnp.asarray(boxes),
        "gt_label": jnp.asarray(rng.randint(
            0, cfg.class_num, (b, n_gt)).astype("int32")),
    }]
    for _ in range(3):
        (wv,) = exe.run(main_prog, feed=batches[0], fetch_list=[loss],
                        scope=scope, return_numpy=False)
    np.asarray(wv)
    step_flops = exe.flops(main_prog, feed=batches[0], fetch_list=[loss],
                           scope=scope)
    n_steps = 20 if on_accel else 3
    dt, dts, final_loss = _timed_loop(
        exe, main_prog, scope, batches, loss, n_steps, 3 if on_accel else 1
    )
    img_s = n_steps * b / dt
    est_flops, flops_model = _estimated_step_flops(
        main_prog, batches[0], xla_flops=step_flops
    )
    mfu = _mfu_fields(step_flops, dt, n_steps, on_accel)
    return {
        "metric": "yolov3_half_train_images_per_sec" if on_accel
        else "yolov3_tiny_train_images_per_sec_cpu",
        "value": round(img_s, 1),
        "unit": "img/s",
        "vs_baseline": (round(img_s / ROUND3_YOLO_IMG_S, 3)
                        if on_accel else 1.0),
        "baseline_note": "r3 b=8 best-of-3 midpoint (270-350 swing); "
                         "b=16 from r4",
        "config": {"batch": b, "size": hw, "scale": cfg.scale,
                   "amp": bool(on_accel)},
        "samples": _samples(n_steps * b, dts),
        **mfu,
        "flops_model": flops_model,
        **_perf_gauge_fields(est_flops, dt, n_steps, on_accel),
        "final_loss": round(final_loss, 4),
    }


def bench_gpt_longctx(on_accel, seq_len=2048, batch=4):
    """GPT-small at S>=2048 — past the whole-row kernel's 1024 cap, so the
    KV-tiled flash kernel (kernels/flash_tiled.py) carries the attention;
    causal dead tiles are skipped in-kernel (r4)."""
    import jax.numpy as jnp

    import paddle_tpu as fluid
    from paddle_tpu.framework.scope import Scope
    from paddle_tpu.models import GPTConfig, gpt_lm_loss
    from paddle_tpu.optimizer import Adam

    if on_accel:
        b, s = batch, seq_len
        cfg = GPTConfig(vocab_size=32000, hidden_size=768, num_layers=12,
                        num_heads=12, intermediate_size=3072,
                        max_position=seq_len)
    else:
        b, s = 2, 64
        cfg = GPTConfig.tiny()
    main_prog, startup = fluid.Program(), fluid.Program()
    main_prog.random_seed = startup.random_seed = 1
    with fluid.program_guard(main_prog, startup):
        ids = fluid.data("ids", [b, s], "int64")
        loss = gpt_lm_loss(ids, cfg)
        opt = Adam(1e-4)
        if on_accel:
            opt = _amp(opt)
        opt.minimize(loss, startup)
    scope = Scope()
    exe = fluid.Executor()
    exe.run(startup, scope=scope)
    rng = np.random.RandomState(0)
    batches = [
        {"ids": jnp.asarray(
            rng.randint(0, cfg.vocab_size, (b, s)).astype("int32"))}
        for _ in range(2)
    ]
    for i in range(3):
        (wv,) = exe.run(main_prog, feed=batches[i % 2], fetch_list=[loss],
                        scope=scope, return_numpy=False)
    np.asarray(wv)
    n_steps = 10 if on_accel else 3
    dt, dts, final_loss = _timed_loop(
        exe, main_prog, scope, batches, loss, n_steps, 2 if on_accel else 1
    )
    tok_s = n_steps * b * s / dt
    h, L, V = cfg.hidden_size, cfg.num_layers, cfg.vocab_size
    # retired closed form (cross-check): causal attention counts s/2
    # useful key positions per token (standard MFU convention; the
    # kernel's dead-tile skip makes hardware work track it)
    legacy = 3 * (L * (24 * h * h + 4 * (s // 2) * h) + 2 * h * V) * b * s
    step_flops, flops_model = _estimated_step_flops(
        main_prog, batches[0], legacy=legacy, legacy_name="causal GPT"
    )
    mfu = _mfu_fields(step_flops, dt, n_steps, on_accel)
    vs = (round(tok_s / ROUND3_GPT2048_TOK_S, 3)
          if (on_accel and seq_len == 2048) else None)
    return {
        "metric": (f"gpt_small_s{s}_train_tokens_per_sec" if on_accel
                   else "gpt_tiny_train_tokens_per_sec_cpu"),
        "value": round(tok_s, 1),
        "unit": "tokens/s",
        "vs_baseline": vs if on_accel else 1.0,
        "config": {"batch": b, "seq": s, "amp": bool(on_accel),
                   "attention": "flash_tiled (S beyond whole-row cap)"
                   if on_accel else "whole-row"},
        "samples": _samples(n_steps * b * s, dts),
        **mfu,
        "flops_model": flops_model,
        **_perf_gauge_fields(step_flops, dt, n_steps, on_accel),
        "final_loss": round(final_loss, 4),
    }


def bench_deepfm(on_accel):
    """CTR path: DeepFM (Criteo shape) examples/sec on single chip —
    embedding-gather + small-matmul bound, so MFU is expected to be tiny;
    the number exists so sparse-path regressions are visible (VERDICT r3
    weak item 9)."""
    import jax.numpy as jnp

    import paddle_tpu as fluid
    from paddle_tpu.framework.scope import Scope
    from paddle_tpu.models.deepfm import DeepFMConfig, deepfm
    from paddle_tpu.optimizer import Adam

    cfg = DeepFMConfig.criteo() if on_accel else DeepFMConfig(
        vocab_size=1000, num_fields=6, embed_dim=8, mlp_sizes=(16,),
        dense_dim=4,
    )
    b = 4096 if on_accel else 64
    main_prog, startup = fluid.Program(), fluid.Program()
    main_prog.random_seed = startup.random_seed = 1
    with fluid.program_guard(main_prog, startup):
        feat = fluid.data("feat", [b, cfg.num_fields], "int64")
        dense = fluid.data("dense", [b, cfg.dense_dim], "float32")
        label = fluid.data("label", [b, 1], "float32")
        loss, _pred = deepfm(feat, label, cfg, dense_input=dense)
        Adam(1e-3).minimize(loss, startup)
    scope = Scope()
    exe = fluid.Executor()
    exe.run(startup, scope=scope)
    rng = np.random.RandomState(0)
    batches = [{
        "feat": jnp.asarray(rng.randint(
            0, cfg.vocab_size, (b, cfg.num_fields)).astype("int32")),
        "dense": jnp.asarray(rng.rand(b, cfg.dense_dim).astype("float32")),
        "label": jnp.asarray(
            (rng.rand(b, 1) < 0.3).astype("float32")),
    } for _ in range(2)]
    for i in range(3):
        (wv,) = exe.run(main_prog, feed=batches[i % 2], fetch_list=[loss],
                        scope=scope, return_numpy=False)
    np.asarray(wv)
    step_flops = exe.flops(main_prog, feed=batches[0], fetch_list=[loss],
                           scope=scope)
    n_steps = 20 if on_accel else 3
    dt, dts, final_loss = _timed_loop(
        exe, main_prog, scope, batches, loss, n_steps, 3 if on_accel else 1
    )
    ex_s = n_steps * b / dt
    est_flops, flops_model = _estimated_step_flops(
        main_prog, batches[0], xla_flops=step_flops
    )
    mfu = _mfu_fields(step_flops, dt, n_steps, on_accel)
    return {
        "metric": "deepfm_criteo_train_examples_per_sec" if on_accel
        else "deepfm_tiny_train_examples_per_sec_cpu",
        "value": round(ex_s, 1),
        "unit": "examples/s",
        "vs_baseline": None if on_accel else 1.0,
        "baseline_note": "new leg in r4",
        "config": {"batch": b, "fields": cfg.num_fields,
                   "dense": cfg.dense_dim, "vocab": cfg.vocab_size,
                   "mlp": list(cfg.mlp_sizes)},
        "samples": _samples(n_steps * b, dts),
        **mfu,
        "flops_model": flops_model,
        **_perf_gauge_fields(est_flops, dt, n_steps, on_accel),
        "final_loss": round(final_loss, 4),
    }


def bench_deepfm_fused(on_accel):
    """CTR path through the PR-11 embedding engine: the per-slot reference
    layout (2F gather dispatch sites) coalesced into ONE fused_lookup_table
    per table width, batch-dedup on, async prefetch staging the next
    batch's rows. Self-gating structural proxies on the CPU leg (one fused
    gather for all slots, dedup active, prefetch overlap recorded); the
    accel leg reports examples/s against the r5 per-slot denominator
    (acceptance: >= 5x)."""
    import jax.numpy as jnp

    import paddle_tpu as fluid
    from paddle_tpu import observability as _obs
    from paddle_tpu.embedding import EmbeddingEngine, Prefetcher, fuse_lookups
    from paddle_tpu.framework.scope import Scope
    from paddle_tpu.models.deepfm import DeepFMConfig, deepfm
    from paddle_tpu.optimizer import Adam

    cfg = DeepFMConfig.criteo() if on_accel else DeepFMConfig(
        vocab_size=4096, num_fields=8, embed_dim=8, mlp_sizes=(16,),
        dense_dim=4,
    )
    b = 4096 if on_accel else 64
    rng = np.random.RandomState(0)

    def make_batches(k):
        out = []
        for _ in range(k):
            # power-law ids: the skew that makes the hot tier and dedup
            # meaningful (criteo id frequency is heavy-tailed)
            idv = (cfg.vocab_size * rng.power(0.35, (b, cfg.num_fields)))
            out.append({
                "feat": jnp.asarray(idv.astype("int64")),
                "dense": jnp.asarray(
                    rng.rand(b, cfg.dense_dim).astype("float32")
                ),
                "label": jnp.asarray(
                    (rng.rand(b, 1) < 0.3).astype("float32")
                ),
            })
        return out

    def build(fused):
        main_prog, startup = fluid.Program(), fluid.Program()
        main_prog.random_seed = startup.random_seed = 1
        scope = Scope()
        with fluid.program_guard(main_prog, startup):
            feat = fluid.data("feat", [b, cfg.num_fields], "int64")
            dense = fluid.data("dense", [b, cfg.dense_dim], "float32")
            label = fluid.data("label", [b, 1], "float32")
            loss, _pred = deepfm(feat, label, cfg, dense_input=dense,
                                 per_slot=True)
            if fused:
                fuse_lookups(main_prog)
            Adam(1e-3).minimize(loss, startup)
        exe = fluid.Executor()
        exe.run(startup, scope=scope)
        return main_prog, scope, exe, loss

    def lookup_sites(prog):
        singles = sum(1 for op in prog.global_block.ops
                      if op.type == "distributed_lookup_table")
        fused = sum(1 for op in prog.global_block.ops
                    if op.type == "fused_lookup_table")
        return singles, fused

    batches = make_batches(4)
    n_steps = 20 if on_accel else 6
    rounds = 3 if on_accel else 1

    # per-slot unfused baseline (the r5 shape, measured in-run on CPU so
    # the structural comparison is like-for-like on this host)
    base_prog, base_scope, base_exe, base_loss = build(fused=False)
    base_singles, _ = lookup_sites(base_prog)
    for i in range(2):
        base_exe.run(base_prog, feed=batches[i % 4], fetch_list=[base_loss],
                     scope=base_scope)
    base_dt, _, _ = _timed_loop(
        base_exe, base_prog, base_scope, batches, base_loss, n_steps, rounds
    )
    base_ex_s = n_steps * b / base_dt

    # fused leg
    main_prog, scope, exe, loss = build(fused=True)
    singles_left, fused_sites = lookup_sites(main_prog)
    for i in range(3):
        exe.run(main_prog, feed=batches[i % 4], fetch_list=[loss],
                scope=scope)
    dt, dts, final_loss = _timed_loop(
        exe, main_prog, scope, batches, loss, n_steps, rounds
    )
    ex_s = n_steps * b / dt
    est_flops, flops_model = _estimated_step_flops(main_prog, batches[0])
    mfu = _mfu_fields(est_flops, dt, n_steps, on_accel)

    # dedup ratio on the actual batches (host-side truth)
    ratios = [
        len(np.unique(np.asarray(f["feat"]))) / np.asarray(f["feat"]).size
        for f in batches
    ]

    # short cached+prefetched segment: the hot tier holds half the vocab,
    # the prefetcher stages cold rows behind compute — structural proxy
    # that the engine composes (hit-rate + overlap metrics land)
    cache_prog, cache_startup = fluid.Program(), fluid.Program()
    cache_prog.random_seed = cache_startup.random_seed = 1
    cache_scope = Scope()
    with fluid.program_guard(cache_prog, cache_startup):
        feat = fluid.data("feat", [b, cfg.num_fields], "int64")
        dense = fluid.data("dense", [b, cfg.dense_dim], "float32")
        label = fluid.data("label", [b, 1], "float32")
        closs, _ = deepfm(feat, label, cfg, dense_input=dense,
                          per_slot=True)
        fuse_lookups(cache_prog)
        engine = EmbeddingEngine(
            cache_prog, cache_startup,
            hot_rows=max(b * cfg.num_fields, cfg.vocab_size // 2),
        )
        Adam(1e-3).minimize(closs, cache_startup)
    cache_exe = fluid.Executor()
    cache_exe.run(cache_startup, scope=cache_scope)
    engine.attach(cache_scope)
    feed_stream = [
        {k: np.asarray(v) for k, v in batches[i % 4].items()}
        for i in range(8 if not on_accel else 16)
    ]
    for f in Prefetcher(engine, feed_stream, cache_scope):
        cache_exe.run(cache_prog, feed=f, fetch_list=[closs],
                      scope=cache_scope)
    gauges = _obs.get_gauges()
    hists = _obs.get_histograms()
    hit_rate = next(
        (v for k, v in gauges.items()
         if k.startswith("embedding.hot_hit_rate.")), None
    )
    overlap = hists.get("embedding.prefetch_overlap", {})
    overlap_mean = (
        overlap["sum"] / overlap["count"] if overlap.get("count") else None
    )

    gates = {
        "one_fused_gather_per_width": fused_sites == 2 and singles_left <= 1,
        "lookup_sites_before": base_singles,
        "lookup_sites_after": fused_sites + singles_left,
        "dedup_active": all(r < 1.0 for r in ratios),
        "dedup_unique_ratio": round(float(np.mean(ratios)), 4),
        "prefetch_overlap_recorded": bool(overlap.get("count")),
        "prefetch_overlap_mean": (
            round(overlap_mean, 3) if overlap_mean is not None else None
        ),
        "hot_hit_rate": round(hit_rate, 3) if hit_rate is not None else None,
    }
    structural_ok = (
        gates["one_fused_gather_per_width"]
        and gates["dedup_active"]
        and gates["prefetch_overlap_recorded"]
    )
    if not structural_ok:
        raise RuntimeError(f"deepfm_fused structural gates failed: {gates}")
    return {
        "metric": "deepfm_fused_criteo_train_examples_per_sec" if on_accel
        else "deepfm_fused_tiny_train_examples_per_sec_cpu",
        "value": round(ex_s, 1),
        "unit": "examples/s",
        # r5 denominator: 266,671 ex/s (BENCH_r05 deepfm leg, per-slot
        # gather path on the tunneled v5e) — acceptance >= 5x on accel
        "vs_baseline": (
            round(ex_s / ROUND5_DEEPFM_EX_S, 3) if on_accel else None
        ),
        "vs_per_slot_in_run": round(ex_s / base_ex_s, 3),
        "per_slot_examples_per_sec": round(base_ex_s, 1),
        "config": {"batch": b, "fields": cfg.num_fields,
                   "vocab": cfg.vocab_size, "mlp": list(cfg.mlp_sizes),
                   "layout": "per_slot->fused", "dedup": True},
        "samples": _samples(n_steps * b, dts),
        **mfu,
        "flops_model": flops_model,
        "gates": gates,
        "final_loss": round(final_loss, 4),
    }


def bench_mask_rcnn_legacy(on_accel):
    """LEGACY Mask R-CNN leg (r5 configuration, kept for like-for-like
    comparison under PADDLE_TPU_BATCHED_DETECTION=0): AMP bf16 + dynamic
    loss scaling, FOUR one-image graphs unrolled into one program. The r5
    BASELINE.md limiter analysis measured ~50-58 ms/image of device-busy
    small-op bookkeeping in this unroll — the batched leg below is the
    re-architecture that deletes it."""
    import jax.numpy as jnp

    import paddle_tpu as fluid
    from paddle_tpu import layers
    from paddle_tpu.framework.scope import Scope
    from paddle_tpu.models import mask_rcnn
    from paddle_tpu.optimizer import Momentum

    if on_accel:
        size, n_gt, n_img = 256, 8, 4
        cfg = mask_rcnn.MaskRCNNConfig(
            class_num=81, scale=0.5, rpn_pre_nms=512, rpn_post_nms=128,
            batch_size_per_im=64, depth=50,
        )
    else:
        size, n_gt, n_img = 64, 2, 1
        cfg = mask_rcnn.MaskRCNNConfig.tiny()
    main_prog, startup = fluid.Program(), fluid.Program()
    main_prog.random_seed = startup.random_seed = 1
    with fluid.program_guard(main_prog, startup):
        per_losses = []
        for i in range(n_img):
            image = fluid.data(f"image{i}", [1, 3, size, size])
            gt_boxes = fluid.data(f"gt_boxes{i}", [n_gt, 4])
            gt_classes = fluid.data(f"gt_classes{i}", [n_gt],
                                    dtype="int32")
            is_crowd = fluid.data(f"is_crowd{i}", [n_gt], dtype="int32")
            gt_segms = fluid.data(f"gt_segms{i}", [n_gt, size, size])
            im_info = fluid.data(f"im_info{i}", [1, 3])
            losses = mask_rcnn.mask_rcnn_train(
                image, gt_boxes, gt_classes, is_crowd, gt_segms, im_info,
                cfg,
            )
            per_losses.append(losses[0])
        loss = per_losses[0]
        for l in per_losses[1:]:
            loss = layers.elementwise_add(loss, l)
        if n_img > 1:
            loss = layers.scale(loss, scale=1.0 / n_img)
        opt = Momentum(0.002, 0.9)
        if on_accel:
            from paddle_tpu.contrib import mixed_precision as mp

            opt = mp.decorate(
                opt,
                amp_lists=mp.AutoMixedPrecisionLists(
                    custom_white_list={"softmax", "layer_norm"}),
                use_dynamic_loss_scaling=True,
                init_loss_scaling=2.0 ** 12,
                dest_dtype="bfloat16",
            )
        opt.minimize(loss, startup)
    scope = Scope()
    exe = fluid.Executor()
    exe.run(startup, scope=scope)
    rng = np.random.RandomState(0)
    feed = {}
    for i in range(n_img):
        boxes = rng.rand(n_gt, 4).astype("float32") * (size / 2)
        boxes[:, 2:] = boxes[:, :2] + 8 + boxes[:, 2:] / 2
        feed.update({
            f"image{i}": jnp.asarray(
                rng.rand(1, 3, size, size).astype("float32")),
            f"gt_boxes{i}": jnp.asarray(boxes),
            f"gt_classes{i}": jnp.asarray(
                rng.randint(1, cfg.class_num, n_gt).astype("int32")),
            f"is_crowd{i}": jnp.asarray(np.zeros(n_gt, "int32")),
            f"gt_segms{i}": jnp.asarray(
                (rng.rand(n_gt, size, size) > 0.5).astype("float32")),
            f"im_info{i}": jnp.asarray(
                np.array([[size, size, 1.0]], "float32")),
        })
    for _ in range(3):
        (wv,) = exe.run(main_prog, feed=feed, fetch_list=[loss],
                        scope=scope, return_numpy=False)
    np.asarray(wv)
    step_flops = exe.flops(main_prog, feed=feed, fetch_list=[loss],
                           scope=scope)
    n_steps = 20 if on_accel else 3
    dt, dts, final_loss = _timed_loop(
        exe, main_prog, scope, [feed], loss, n_steps, 3 if on_accel else 1
    )
    img_s = n_steps * n_img / dt
    return {
        "metric": "mask_rcnn_half_train_images_per_sec" if on_accel
        else "mask_rcnn_tiny_train_images_per_sec_cpu",
        "value": round(img_s, 2),
        "unit": "img/s",
        "vs_baseline": None if on_accel else 1.0,
        "baseline_note": "r5: AMP bf16 + dynamic loss scaling, 4-image "
                         "unroll (r4 was fp32 b=1: 20.8 img/s; "
                         "like-for-like fp32-b=1 measured 13.5 under r5 "
                         "chip conditions)",
        "config": {"images_per_step": n_img, "size": size,
                   "scale": cfg.scale, "depth": cfg.depth,
                   "amp": bool(on_accel), "dynamic_loss_scaling": True,
                   "batched_detection_ops": False},
        "samples": _samples(n_steps * n_img, dts),
        **_mfu_fields(step_flops, dt, n_steps, on_accel),
        "final_loss": round(final_loss, 4),
    }


def bench_mask_rcnn(on_accel):
    """Mask R-CNN train step, r6 cross-image batched detection ops: ONE
    [B, ...] program feeds B images through batched roi_align /
    generate_proposals / NMS / target-assign / label ops (fixed per-image
    RoI caps + validity masks) — the re-architecture BASELINE.md r5 named
    as the only path past the ~50-58 ms/image bookkeeping floor of the
    per-image unroll. images_per_step=8 on accel (vs the r5 4x unroll);
    PADDLE_TPU_BATCHED_DETECTION=0 selects the legacy r5 leg for
    like-for-like comparison. The "unroll_proxy" fields evidence the
    elimination on CPU-only CI where MFU cannot be measured: 1 program
    for B images, and the batched op count vs what the unroll would cost.
    """
    import jax.numpy as jnp

    import paddle_tpu as fluid
    from paddle_tpu.framework.scope import Scope
    from paddle_tpu.models import mask_rcnn
    from paddle_tpu.ops.detection_stats import record_roi_stats
    from paddle_tpu.optimizer import Momentum

    if not mask_rcnn.batched_detection_enabled():
        return bench_mask_rcnn_legacy(on_accel)

    if on_accel:
        size, n_gt, B = 256, 8, 8
        cfg = mask_rcnn.MaskRCNNConfig(
            class_num=81, scale=0.5, rpn_pre_nms=512, rpn_post_nms=128,
            batch_size_per_im=64, depth=50,
        )
    else:
        size, n_gt, B = 64, 2, 2
        cfg = mask_rcnn.MaskRCNNConfig.tiny()
    main_prog, startup = fluid.Program(), fluid.Program()
    main_prog.random_seed = startup.random_seed = 1
    with fluid.program_guard(main_prog, startup):
        images = fluid.data("images", [B, 3, size, size])
        gt_boxes = fluid.data("gt_boxes", [B, n_gt, 4])
        gt_classes = fluid.data("gt_classes", [B, n_gt], dtype="int32")
        is_crowd = fluid.data("is_crowd", [B, n_gt], dtype="int32")
        gt_segms = fluid.data("gt_segms", [B, n_gt, size, size])
        im_info = fluid.data("im_info", [B, 3])
        losses, aux = mask_rcnn.mask_rcnn_train_batched(
            images, gt_boxes, gt_classes, is_crowd, gt_segms, im_info, cfg,
        )
        loss = losses[0]
        batched_fwd_ops = len(main_prog.global_block.ops)
        opt = Momentum(0.002, 0.9)
        if on_accel:
            from paddle_tpu.contrib import mixed_precision as mp

            opt = mp.decorate(
                opt,
                amp_lists=mp.AutoMixedPrecisionLists(
                    custom_white_list={"softmax", "layer_norm"}),
                use_dynamic_loss_scaling=True,
                init_loss_scaling=2.0 ** 12,
                dest_dtype="bfloat16",
            )
        opt.minimize(loss, startup)
    batched_op_count = len(main_prog.global_block.ops)

    # unroll-eliminated proxy: what ONE legacy per-image graph costs in
    # FORWARD ops (build only, never run; no optimizer on either side of
    # the comparison) -> the unroll would be B x that
    legacy_prog, legacy_startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(legacy_prog, legacy_startup):
        li = fluid.data("image", [1, 3, size, size])
        lb = fluid.data("gt_boxes", [n_gt, 4])
        lc = fluid.data("gt_classes", [n_gt], dtype="int32")
        lcr = fluid.data("is_crowd", [n_gt], dtype="int32")
        ls = fluid.data("gt_segms", [n_gt, size, size])
        lii = fluid.data("im_info", [1, 3])
        mask_rcnn.mask_rcnn_train(li, lb, lc, lcr, ls, lii, cfg)
    legacy_ops_per_image = len(legacy_prog.global_block.ops)

    scope = Scope()
    exe = fluid.Executor()
    exe.run(startup, scope=scope)
    rng = np.random.RandomState(0)
    boxes = rng.rand(B, n_gt, 4).astype("float32") * (size / 2)
    boxes[..., 2:] = boxes[..., :2] + 8 + boxes[..., 2:] / 2
    feed = {
        "images": jnp.asarray(
            rng.rand(B, 3, size, size).astype("float32")),
        "gt_boxes": jnp.asarray(boxes),
        "gt_classes": jnp.asarray(
            rng.randint(1, cfg.class_num, (B, n_gt)).astype("int32")),
        "is_crowd": jnp.asarray(np.zeros((B, n_gt), "int32")),
        "gt_segms": jnp.asarray(
            (rng.rand(B, n_gt, size, size) > 0.5).astype("float32")),
        "im_info": jnp.asarray(
            np.tile([[size, size, 1.0]], (B, 1)).astype("float32")),
    }
    # padding stats fetch once, then warm the EXACT [loss] fetch set the
    # timed loop uses (executables are cached per fetch set; a cold set
    # would put trace+compile inside the timed region)
    wv, rois_num = exe.run(main_prog, feed=feed,
                           fetch_list=[loss, aux["rois_num"]],
                           scope=scope, return_numpy=False)
    padding_waste = record_roi_stats(
        np.asarray(rois_num), cfg.batch_size_per_im
    )
    for _ in range(3):
        (wv,) = exe.run(main_prog, feed=feed, fetch_list=[loss],
                        scope=scope, return_numpy=False)
    np.asarray(wv)
    step_flops = exe.flops(main_prog, feed=feed, fetch_list=[loss],
                           scope=scope)
    n_steps = 20 if on_accel else 3
    dt, dts, final_loss = _timed_loop(
        exe, main_prog, scope, [feed], loss, n_steps, 3 if on_accel else 1
    )
    img_s = n_steps * B / dt
    est_flops, flops_model = _estimated_step_flops(
        main_prog, feed, xla_flops=step_flops
    )
    mfu = _mfu_fields(step_flops, dt, n_steps, on_accel)
    return {
        "metric": "mask_rcnn_half_train_images_per_sec" if on_accel
        else "mask_rcnn_tiny_train_images_per_sec_cpu",
        "value": round(img_s, 2),
        "unit": "img/s",
        "vs_baseline": (round(img_s / ROUND5_MASK_RCNN_IMG_S, 3)
                        if on_accel else 1.0),
        "baseline_note": "r5 denominator 20.99 img/s = AMP bf16+DLS "
                         "4x1-image unroll at 256^2 half-width (r4 fp32 "
                         "b=1: 20.8); PADDLE_TPU_BATCHED_DETECTION=0 "
                         "re-runs that legacy leg like-for-like",
        "config": {"images_per_step": B, "size": size,
                   "scale": cfg.scale, "depth": cfg.depth,
                   "roi_cap_per_image": cfg.batch_size_per_im,
                   "amp": bool(on_accel),
                   "dynamic_loss_scaling": bool(on_accel),
                   "batched_detection_ops": True},
        "unroll_proxy": {
            "programs_per_step": 1,
            "images_per_program": B,
            "batched_op_count": batched_op_count,
            "batched_fwd_ops": batched_fwd_ops,
            "legacy_fwd_ops_per_image": legacy_ops_per_image,
            "legacy_fwd_ops_if_unrolled": legacy_ops_per_image * B,
        },
        "padding_waste": round(padding_waste, 3),
        "samples": _samples(n_steps * B, dts),
        **mfu,
        "flops_model": flops_model,
        **_perf_gauge_fields(est_flops, dt, n_steps, on_accel),
        "final_loss": round(final_loss, 4),
    }


def _run_bench_child(script):
    """Run a tools/ bench script in its own (virtual-mesh-pinned) child
    process and parse the ONE JSON line it prints as its result."""
    import os
    import subprocess

    proc = subprocess.run(
        [sys.executable,
         os.path.join(os.path.dirname(os.path.abspath(__file__)),
                      "tools", script)],
        capture_output=True, text=True, timeout=1200,
    )
    line = (proc.stdout or "").strip().splitlines()
    if proc.returncode != 0 or not line:
        raise RuntimeError(
            f"{script} failed (rc={proc.returncode}): "
            f"{proc.stderr[-500:]}"
        )
    return json.loads(line[-1])


def bench_dp_sharding(on_accel):
    """ZeRO weight-update sharding + quantized collectives on the dp=8
    virtual mesh (tools/bench_dp_sharding.py in a pinned CPU child
    process — a payload/memory leg, not a throughput leg): collective
    wire bytes vs the allreduce baseline, optimizer-state bytes/rank,
    and loss parity. Gates: >=40% int8 payload reduction, state/rank
    ~1/8, fp32 parity."""
    m = _run_bench_child("bench_dp_sharding.py")
    return {
        **m,
        "metric": "dp_sharding_payload_reduction",
        "value": m["int8_payload_reduction"],
        "unit": "fraction_of_allreduce_wire_bytes_saved",
    }


def bench_dp_overlap(on_accel):
    """Communication/compute overlap on the dp=8 virtual mesh
    (tools/bench_overlap.py in a pinned CPU child): bucketed grad
    collectives + prefetched all-gathers vs PR 9's serialized ZeRO — the
    r9 schedule is the denominator, PR 13's wait-fraction attribution the
    measurement. Self-gating: overlapped step <= serialized, fp32 bitwise
    parity, int8 within the r9 tolerance, wait fraction drops."""
    m = _run_bench_child("bench_overlap.py")
    return {
        **m,
        "metric": "dp_overlap_speedup",
        "value": m["overlap_speedup"],
        "unit": "serialized_step_over_overlapped_step",
        "baseline_note": "serialized ZeRO (r9 schedule) on the same "
                         "model/mesh is the denominator",
    }


def main():
    import jax

    on_accel = jax.devices()[0].platform != "cpu"
    primary = bench_bert(on_accel)
    extras = {}
    legs = [
        ("resnet50", lambda: bench_resnet(on_accel)),
        ("yolov3", lambda: bench_yolov3(on_accel)),
        ("gpt_longctx", lambda: bench_gpt_longctx(on_accel, 2048, 4)),
        ("deepfm", lambda: bench_deepfm(on_accel)),
        ("deepfm_fused", lambda: bench_deepfm_fused(on_accel)),
        ("mask_rcnn", lambda: bench_mask_rcnn(on_accel)),
        ("dp_sharding", lambda: bench_dp_sharding(on_accel)),
        ("dp_overlap", lambda: bench_dp_overlap(on_accel)),
    ]
    if on_accel:
        legs += [
            ("gpt_s4096", lambda: bench_gpt_longctx(on_accel, 4096, 2)),
            ("gpt_s8192", lambda: bench_gpt_longctx(on_accel, 8192, 1)),
        ]
    for name, fn in legs:
        try:
            extras[name] = fn()
        except Exception as e:  # a vision bench failing must not hide BERT
            extras[name] = {"error": f"{type(e).__name__}: {e}"}
    primary["extra_metrics"] = extras
    print(json.dumps(primary))
    # LAST line: compact all-legs summary. The driver records the TAIL of
    # stdout; r4's full JSON was truncated mid-line and lost the headline
    # legs entirely (VERDICT r4 weak #7). This line is small enough to
    # always survive whole and parses to every leg.
    def _leg_brief(m):
        if "error" in m:
            return {"error": m["error"][:120]}
        out = {"value": m.get("value"), "unit": m.get("unit")}
        mfu = m.get("mfu_vs_v5e_bf16_peak")
        if mfu is not None:
            out["mfu"] = mfu
        if m.get("samples"):
            out["samples"] = m["samples"]
        return out

    compact = {
        "metric": primary["metric"],
        "value": primary["value"],
        "unit": primary["unit"],
        "vs_baseline": primary.get("vs_baseline"),
        "mfu": primary.get("mfu_vs_v5e_bf16_peak"),
        "legs": {
            "bert": _leg_brief(primary),
            **{k: _leg_brief(v) for k, v in extras.items()},
        },
    }
    print(json.dumps(compact))


if __name__ == "__main__":
    sys.exit(main())
