"""Distributed tests on the 8-device virtual CPU mesh (conftest.py), the
analog of the reference's localhost multi-process dist tests
(test_dist_base.py:506): run the same model data-parallel and single-device
and assert the losses match.
"""

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import layers
from paddle_tpu.fleet import DistributedStrategy, Fleet, UserDefinedRoleMaker
from paddle_tpu.parallel import GradAllReduce, make_mesh


def _build_model():
    img = fluid.data("img", [-1, 8], "float32")
    label = fluid.data("label", [-1, 1], "float32")
    hidden = layers.fc(img, size=16, act="relu")
    pred = layers.fc(hidden, size=1)
    loss = layers.reduce_mean(layers.square_error_cost(pred, label))
    return loss


def _train(loss_builder, optimizer_factory, n_steps, batch, use_fleet):
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 42
    with fluid.program_guard(main, startup):
        loss = loss_builder()
        opt = optimizer_factory()
        if use_fleet:
            f = Fleet().init(UserDefinedRoleMaker())
            strategy = DistributedStrategy()
            dist_opt = f.distributed_optimizer(opt, strategy)
            dist_opt.minimize(loss, startup)
        else:
            opt.minimize(loss, startup)
    exe = fluid.Executor()
    exe.run(startup, scope=(scope := fluid.framework.scope.Scope()))
    rng = np.random.RandomState(0)
    losses = []
    for _ in range(n_steps):
        x = rng.randn(batch, 8).astype("float32")
        y = (x.sum(axis=1, keepdims=True) > 0).astype("float32")
        (lv,) = exe.run(
            main, feed={"img": x, "label": y}, fetch_list=[loss], scope=scope
        )
        losses.append(float(lv))
    return losses


def test_fleet_dp_matches_single_device():
    from paddle_tpu.optimizer import SGD

    single = _train(_build_model, lambda: SGD(0.1), 5, 16, use_fleet=False)
    dist = _train(_build_model, lambda: SGD(0.1), 5, 16, use_fleet=True)
    # data-parallel mean-of-shard-means == global mean when shards are equal
    np.testing.assert_allclose(single, dist, rtol=1e-4, atol=1e-5)
    assert dist[-1] < dist[0]  # actually learning


def test_make_mesh_shapes():
    m = make_mesh({"dp": 2, "mp": -1})
    assert m.shape["dp"] == 2 and m.shape["mp"] == 4
    with pytest.raises(ValueError):
        make_mesh({"dp": 3})


def test_grad_allreduce_transpile_inserts_ops():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        loss = _build_model()
        from paddle_tpu.optimizer import SGD

        opt = SGD(0.1)
        pg = opt.backward(loss)
        GradAllReduce(nranks=8).transpile(main, pg)
        opt.apply_gradients(pg)
    types = [op.type for op in main.global_block.ops]
    assert types.count("c_allreduce_sum") == len(pg)
    # every allreduce sits before the sgd update ops
    assert max(i for i, t in enumerate(types) if t == "c_allreduce_sum") < min(
        i for i, t in enumerate(types) if t == "sgd"
    )


def test_spmd_collective_allreduce_on_mesh():
    """A raw c_allreduce over the dp axis must sum across all 8 shards
    (reference test_collective_base.py check_with_place analog)."""
    from paddle_tpu.parallel import shard_program

    main = fluid.Program()
    with fluid.program_guard(main, fluid.Program()):
        x = fluid.data("x", [8, 4], "float32")
        blk = main.global_block
        out = blk.create_var(name="out", shape=(8, 4), dtype="float32")
        blk.append_op(
            "c_allreduce_sum",
            inputs={"X": ["x"]},
            outputs={"Out": ["out"]},
            attrs={"axis_name": "dp"},
        )
    mesh = make_mesh({"dp": 8})
    shard_program(main, mesh, {"x": ("dp",), "out": ("dp",)})
    exe = fluid.Executor()
    data = np.arange(32, dtype="float32").reshape(8, 4)
    (res,) = exe.run(main, feed={"x": data}, fetch_list=["out"])
    expect = np.tile(data.reshape(8, 1, 4).sum(axis=0), (8, 1))
    np.testing.assert_allclose(res, expect)


# -- round 3: TP/SPMD equivalence beyond toy shapes (VERDICT r2 weak #8) --


def test_bert_tp_matches_replicated_at_real_width():
    """BERT-tiny-but-real-width (h=256, 2 layers, s=64) under 4-way tensor
    parallelism (gspmd) matches the replicated run's loss trajectory."""
    from paddle_tpu.framework import unique_name
    from paddle_tpu.models import BertConfig, bert_pretrain
    from paddle_tpu.models.bert import bert_tp_shardings
    from paddle_tpu.parallel import shard_program
    from paddle_tpu.parallel.mesh import make_mesh

    b, s = 4, 64
    cfg = BertConfig(
        vocab_size=512, hidden_size=256, num_layers=2, num_heads=4,
        intermediate_size=1024, max_position=128,
    )
    rng = np.random.RandomState(0)
    feed = {
        "ids": rng.randint(0, cfg.vocab_size, (b, s)).astype("int64"),
        "types": rng.randint(0, 2, (b, s)).astype("int64"),
        "mask": np.ones((b, s), "float32"),
        "labels": rng.randint(0, cfg.vocab_size, (b, s)).astype("int64"),
    }

    results = {}
    for mode in ("replicated", "tp"):
        main, startup = fluid.Program(), fluid.Program()
        main.random_seed = startup.random_seed = 7
        scope = fluid.framework.scope.Scope()
        with fluid.program_guard(main, startup), \
                fluid.scope_guard(scope), unique_name.guard():
            ids = fluid.data("ids", [b, s], "int64")
            types = fluid.data("types", [b, s], "int64")
            mask = fluid.data("mask", [b, s], "float32")
            labels = fluid.data("labels", [b, s], "int64")
            loss = bert_pretrain(ids, types, mask, labels, cfg,
                                 is_test=True)  # no dropout: exact compare
            fluid.optimizer.SGD(0.1).minimize(loss)
            if mode == "tp":
                import jax

                shard_program(
                    main, make_mesh({"mp": 4}, jax.devices()[:4]),
                    shardings=bert_tp_shardings(cfg), mode="gspmd",
                )
            exe = fluid.Executor()
            exe.run(startup, scope=scope)
            vals = []
            for _ in range(3):
                (lv,) = exe.run(main, feed=feed, fetch_list=[loss],
                                scope=scope)
                vals.append(float(np.asarray(lv).reshape(-1)[0]))
        results[mode] = vals
    np.testing.assert_allclose(
        results["tp"], results["replicated"], rtol=2e-4
    )
