"""AMP, recompute, and io round-trip tests."""

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import layers
from paddle_tpu.contrib.mixed_precision import decorate
from paddle_tpu.incubate import RecomputeOptimizer
from paddle_tpu.optimizer import Adam, SGD


def _mlp(img, label, hidden=32):
    h1 = layers.fc(img, size=hidden, act="relu")
    h2 = layers.fc(h1, size=hidden, act="relu")
    pred = layers.fc(h2, size=10)
    loss = layers.reduce_mean(
        layers.softmax_with_cross_entropy(pred, label)
    )
    return loss, (h1, h2)


def _feed(rng, bs=8):
    return {
        "img": rng.randn(bs, 16).astype("float32"),
        "label": rng.randint(0, 10, (bs, 1)).astype("int64"),
    }


def _build(opt_factory, wrap=None):
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 5
    with fluid.program_guard(main, startup):
        img = fluid.data("img", [-1, 16], "float32")
        label = fluid.data("label", [-1, 1], "int64")
        loss, hs = _mlp(img, label)
        opt = opt_factory()
        if wrap:
            opt = wrap(opt, hs)
        opt.minimize(loss, startup)
    return main, startup, loss


def _train(main, startup, loss, steps=4):
    exe = fluid.Executor()
    scope = fluid.framework.scope.Scope()
    exe.run(startup, scope=scope)
    rng = np.random.RandomState(0)
    f = _feed(rng)
    out = []
    for _ in range(steps):
        (lv,) = exe.run(main, feed=f, fetch_list=[loss], scope=scope)
        out.append(float(np.asarray(lv).reshape(-1)[0]))
    return out


def test_amp_bf16_trains_close_to_fp32():
    ref = _train(*_build(lambda: SGD(0.1)))
    amp = _train(
        *_build(
            lambda: SGD(0.1),
            wrap=lambda o, hs: decorate(o, use_dynamic_loss_scaling=False,
                                        init_loss_scaling=1.0),
        )
    )
    assert amp[-1] < amp[0]
    np.testing.assert_allclose(ref, amp, rtol=0.1, atol=0.05)  # bf16 tolerance


def test_amp_program_has_casts():
    main, _, _ = _build(
        lambda: SGD(0.1),
        wrap=lambda o, hs: decorate(o, use_dynamic_loss_scaling=False),
    )
    types = [op.type for op in main.global_block.ops]
    assert "cast" in types


def test_amp_dynamic_loss_scaling_fp16_style():
    main, startup, loss = _build(
        lambda: SGD(0.05),
        wrap=lambda o, hs: decorate(
            o, init_loss_scaling=2.0**10, use_dynamic_loss_scaling=True,
            incr_every_n_steps=2, dest_dtype="float32",
        ),
    )
    vals = _train(main, startup, loss, steps=6)
    assert vals[-1] < vals[0] and np.isfinite(vals).all()


def test_recompute_matches_plain_backward():
    ref = _train(*_build(lambda: SGD(0.1)))

    def wrap(o, hs):
        r = RecomputeOptimizer(o)
        r._set_checkpoints(list(hs))
        return r

    rec = _train(*_build(lambda: SGD(0.1), wrap=wrap))
    np.testing.assert_allclose(ref, rec, rtol=1e-4, atol=1e-5)


def test_recompute_folds_segments():
    main, _, _ = _build(
        lambda: SGD(0.1),
        wrap=lambda o, hs: (
            lambda r: (r._set_checkpoints(list(hs)), r)[1]
        )(RecomputeOptimizer(o)),
    )
    types = [op.type for op in main.global_block.ops]
    assert "recompute_segment" in types


def test_save_load_roundtrip(tmp_path):
    main, startup, loss = _build(lambda: Adam(1e-2))
    exe = fluid.Executor()
    with fluid.scope_guard(fluid.global_scope()):
        exe.run(startup)
        rng = np.random.RandomState(0)
        f = _feed(rng)
        exe.run(main, feed=f, fetch_list=[loss])
        path = str(tmp_path / "model")
        fluid.io.save(main, path)
        (before,) = exe.run(main, feed=f, fetch_list=[loss])
        # clobber params, reload, expect same loss
        for p in main.all_parameters():
            fluid.global_scope().set_var(
                p.name, np.zeros([int(s) for s in p.shape], "float32")
            )
        fluid.io.load(main, path)
        (after,) = exe.run(main, feed=f, fetch_list=[loss])
    np.testing.assert_allclose(
        np.asarray(before), np.asarray(after), rtol=1e-5
    )


def test_save_load_inference_model(tmp_path):
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 5
    with fluid.program_guard(main, startup):
        img = fluid.data("img", [-1, 16], "float32")
        label = fluid.data("label", [-1, 1], "int64")
        loss, _ = _mlp(img, label)  # forward-only: params must not mutate
    exe = fluid.Executor()
    with fluid.scope_guard(fluid.global_scope()):
        exe.run(startup)
        rng = np.random.RandomState(0)
        f = _feed(rng)
        (ref,) = exe.run(main, feed=f, fetch_list=[loss])
        d = str(tmp_path / "infer")
        fluid.io.save_inference_model(d, ["img", "label"], [loss], exe, main)
        prog, feeds, fetches = fluid.io.load_inference_model(d, exe)
        (out,) = exe.run(prog, feed=f, fetch_list=fetches)
    types = [op.type for op in prog.global_block.ops]
    assert "__vjp__" not in types
    np.testing.assert_allclose(np.asarray(ref), np.asarray(out), rtol=1e-5)


def test_fleet_checkpoint_rotation_and_resume(tmp_path):
    """save_check_point rotates numbered dirs + TrainStatus; load resumes
    params and epoch (reference incubate/fleet/collective :155-240)."""
    import os

    from paddle_tpu.fleet import collective as fc

    x = fluid.data("x", [-1, 4])
    y = fluid.layers.fc(x, 2, param_attr=fluid.ParamAttr(name="ck_w"))
    loss = fluid.layers.mean(y)
    fluid.optimizer.SGD(0.1).minimize(loss)
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    scope = fluid.framework.scope.global_scope()

    from paddle_tpu.fleet.role_maker import UserDefinedRoleMaker

    fleet = fc.Fleet()
    fleet.init(UserDefinedRoleMaker())

    path = str(tmp_path / "ckpts")
    feed = {"x": np.ones((2, 4), np.float32)}
    saved_params = []
    for epoch in range(5):
        exe.run(feed=feed, fetch_list=[loss])
        saved_params.append(np.asarray(scope.find_var("ck_w")).copy())
        no = fleet.save_check_point(
            exe, path, fc.TrainStatus(epoch), max_checkpoint_num=3
        )
        assert no == epoch
    dirs = sorted(os.listdir(path))
    assert dirs == [
        "__paddle_checkpoint__2", "__paddle_checkpoint__3",
        "__paddle_checkpoint__4",
    ]

    # clobber params, resume from latest
    scope.set_var("ck_w", np.zeros_like(saved_params[-1]))
    status = fleet.load_check_point(exe, path)
    assert status.next() == 5
    np.testing.assert_allclose(
        np.asarray(scope.find_var("ck_w")), saved_params[-1]
    )
    # resume a specific earlier number
    status = fleet.load_check_point(exe, path, checkpoint_no=2)
    assert status.next() == 3
    np.testing.assert_allclose(
        np.asarray(scope.find_var("ck_w")), saved_params[2]
    )
    # cold start: empty dir -> TrainStatus(-1)
    assert fleet.load_check_point(exe, str(tmp_path / "none")).next() == 0


def test_hadoop_fs_checkpoint_roundtrip(tmp_path):
    """HadoopFS drives save/load_check_point through a fake `hadoop`
    binary backed by a local dir (reference pattern: fs.cc shells out;
    incubate/fleet/utils/hdfs.py tests used mocks the same way)."""
    import os
    import stat

    store = tmp_path / "hdfs_store"
    store.mkdir()
    fake = tmp_path / "bin" / "hadoop"
    fake.parent.mkdir()
    # translate `hadoop fs -cmd args...` to local filesystem operations
    fake.write_text(f"""#!/usr/bin/env python3
import os, shutil, sys
root = {str(store)!r}

def loc(p):
    return os.path.join(root, p.lstrip("/"))

args = sys.argv[2:]  # drop 'fs'
cmd, rest = args[0], args[1:]
if cmd == "-ls":
    d = loc(rest[0])
    if not os.path.isdir(d):
        sys.exit(1)
    for n in sorted(os.listdir(d)):
        kind = "d" if os.path.isdir(os.path.join(d, n)) else "-"
        print(f"{{kind}}rwxr-xr-x - u g 0 d t {{rest[0].rstrip('/')}}/{{n}}")
elif cmd == "-test":
    p = loc(rest[1])
    ok = os.path.isdir(p) if rest[0] == "-d" else os.path.exists(p)
    sys.exit(0 if ok else 1)
elif cmd == "-cat":
    p = loc(rest[0])
    if not os.path.isfile(p):
        print(f"cat: `{{rest[0]}}': No such file or directory",
              file=sys.stderr)
        sys.exit(1)
    sys.stdout.buffer.write(open(p, "rb").read())
elif cmd == "-mkdir":
    os.makedirs(loc(rest[-1]), exist_ok=True)
elif cmd == "-rm":
    p = loc(rest[-1])
    shutil.rmtree(p, ignore_errors=True) if os.path.isdir(p) else (
        os.path.exists(p) and os.remove(p))
elif cmd == "-mv":
    shutil.move(loc(rest[0]), loc(rest[1]))
elif cmd == "-put":
    src, dst = rest[-2], loc(rest[-1])
    shutil.copytree(src, dst, dirs_exist_ok=True)
elif cmd == "-get":
    src = loc(rest[0].replace("/*", ""))
    if os.path.isfile(src):
        shutil.copy2(src, rest[1])
    else:
        shutil.copytree(src, rest[1], dirs_exist_ok=True)
else:
    sys.exit(2)
""")
    fake.chmod(fake.stat().st_mode | stat.S_IEXEC)

    from paddle_tpu.fleet import collective as fc
    from paddle_tpu.fleet.fs_wrapper import HadoopFS
    from paddle_tpu.fleet.role_maker import UserDefinedRoleMaker

    x = fluid.data("x", [-1, 4])
    y = fluid.layers.fc(x, 2, param_attr=fluid.ParamAttr(name="hw"))
    loss = fluid.layers.mean(y)
    fluid.optimizer.SGD(0.1).minimize(loss)
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    scope = fluid.framework.scope.global_scope()

    fleet = fc.Fleet()
    fleet.init(UserDefinedRoleMaker())
    fs = HadoopFS(hadoop_bin=str(fake))
    exe.run(feed={"x": np.ones((2, 4), np.float32)}, fetch_list=[loss])
    saved = np.asarray(scope.find_var("hw")).copy()
    no = fleet.save_check_point(exe, "/ckpts", fc.TrainStatus(4), fs=fs)
    assert no == 0
    assert (store / "ckpts" / "__paddle_checkpoint__0").is_dir()

    scope.set_var("hw", np.zeros_like(saved))
    status = fleet.load_check_point(exe, "/ckpts", fs=fs)
    assert status.next() == 5
    np.testing.assert_allclose(np.asarray(scope.find_var("hw")), saved)


def test_amp_gray_rule_leaves_soft_labels_fp32():
    """ADVICE r4: the gray-op downcast must not quantize label slots —
    a soft-label fp32 Label is data, not a master param on the activation
    stream. The rewrite casts Logits to bf16 but leaves Label untouched."""
    from paddle_tpu.contrib.mixed_precision import fp16_utils

    main = fluid.Program()
    with fluid.program_guard(main, fluid.Program()):
        img = fluid.data("img", [-1, 16], "float32")
        soft = fluid.data("soft", [-1, 10], "float32")
        pred = layers.fc(img, size=10)
        loss = layers.reduce_mean(
            layers.softmax_with_cross_entropy(pred, soft, soft_label=True)
        )
    fp16_utils.rewrite_program(main)
    for op in main.global_block.ops:
        if op.type == "softmax_with_cross_entropy":
            # Label input must still be the raw fp32 feed, not a cast
            (lbl,) = op.inputs["Label"]
            assert lbl == "soft", lbl
            v = main.global_block._find_var_recursive(lbl)
            assert str(v.dtype) == "float32"
            break
    else:
        pytest.fail("softmax_with_cross_entropy not found")
