"""Worker for the LocalSGD multi-process test: each rank trains
INDEPENDENTLY (no per-step grad allreduce), then runs the LocalSGD
averaging program; writes pre/post parameter values per rank
(reference transpiler/collective.py:270 LocalSGD semantics)."""

import json
import os
import sys

import numpy as np

import paddle_tpu as fluid
from paddle_tpu import layers
from paddle_tpu.fleet.role_maker import PaddleCloudRoleMaker
from paddle_tpu.parallel.mesh import make_mesh
from paddle_tpu.parallel.transpiler import LocalSGD


def main():
    out_dir = sys.argv[1]
    role = PaddleCloudRoleMaker()
    role.generate_role()  # brings up jax.distributed
    rank, nranks = role.worker_index(), role.worker_num()

    main_prog, startup = fluid.Program(), fluid.Program()
    main_prog.random_seed = startup.random_seed = 5
    with fluid.program_guard(main_prog, startup):
        x = fluid.data("x", [8, 4])
        y = fluid.data("y", [8, 1])
        pred = layers.fc(x, 1, param_attr=fluid.ParamAttr(name="w"),
                         bias_attr=False)
        loss = layers.mean(layers.square_error_cost(pred, y))
        fluid.optimizer.SGD(0.1).minimize(loss)

    exe = fluid.Executor()
    exe.run(startup)
    scope = fluid.framework.scope.global_scope()

    # rank-dependent data -> params diverge across workers
    rng = np.random.RandomState(100 + rank)
    feed = {"x": rng.randn(8, 4).astype(np.float32),
            "y": rng.randn(8, 1).astype(np.float32)}
    for _ in range(3):
        exe.run(main_prog, feed=feed, fetch_list=[loss])
    pre = np.asarray(scope.find_var("w")).copy()

    # periodic averaging step over the global device mesh: the divisor is
    # the AXIS SIZE (every device holds a model copy — a process's local
    # devices hold replicas, so psum counts each rank local_count times)
    import jax

    n_dev = len(jax.devices())
    avg = LocalSGD(n_dev).build_average_program(main_prog)
    from paddle_tpu.parallel.spmd import shard_program

    shard_program(avg, make_mesh({"dp": n_dev}, jax.devices()))
    exe.run(avg, scope=scope)
    post = np.asarray(scope.find_var("w"))

    with open(os.path.join(out_dir, f"localsgd_{rank}.json"), "w") as f:
        json.dump({"pre": pre.tolist(), "post": post.tolist()}, f)


if __name__ == "__main__":
    main()
