"""Exact-resume audit worker: deterministic 2-rank training with full
TrainStatus-v2 checkpoints, a consumed-example log, and an optional
self-SIGKILL mid-epoch.

Each rank trains the same tiny regression on ITS
DistributedBatchSampler shard (ranks are independent, the established
chaos-worker pattern — a killed peer cannot wedge the others), feeds every
persistable as a per-rank `local_vars` shard (the no-collective analog of
weight-update-sharded state: nothing here is replicated), and checkpoints
every CKPT_EVERY steps with `TrainStatus.capture` (global step, program
RNG state, DataLoader cursor).

On attempt 0 with ``kill_rank`` >= 0, that rank SIGKILLs itself at
KILL_STEP — mid-epoch, off the checkpoint cadence — so the launcher's
``--elastic`` path restarts it and the restart resumes from its newest
COMPLETE checkpoint: restore RNG + cursor, truncate the consumed log to
the checkpoint's step, fast-skip to the cursor, replay. The audit
(tools/resume_audit.py) diffs final weights and the consumed log bitwise
against an uninterrupted control run.

PADDLE_TPU_RESUME_ASYNC=1 (tools/resume_audit.py --async): checkpoints
go through fleet.AsyncCheckpointer (delta chains, full_every=2) instead
of the synchronous save, and on attempt 0 the kill rank arms a ``hang``
fault on the ``checkpoint.publish`` seam after its first committed save
— so the SIGKILL lands while an async publish is IN FLIGHT and the
restart must resume from the newest *committed* checkpoint (the wedged
publish left only a ``*.tmp`` dir behind).

argv: out_dir [kill_rank]   (kill_rank defaults to -1 = never kill)
"""

import json
import os
import signal
import sys

import numpy as np

EPOCHS = 3
N = 48          # dataset size -> 6 batches per rank per epoch at nranks=2
BS = 4
CKPT_EVERY = 5  # steps; deliberately off the 6-step epoch length
KILL_STEP = 11  # mid epoch 1, one step past the step-10 checkpoint


def main(out_dir, kill_rank=-1):
    # PADDLE_TPU_RESUME_SHARDED=1 (tools/resume_audit.py --sharded):
    # train with Momentum + the ZeRO weight-update transpile over a
    # per-process dp=2 virtual mesh, so every checkpointed local_vars
    # shard carries genuinely dp-sharded optimizer state — the
    # exact-resume machinery must restore it bitwise. The device count
    # must be forced BEFORE jax initializes.
    sharded = os.environ.get("PADDLE_TPU_RESUME_SHARDED") == "1"
    if sharded:
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=2"
            ).strip()

    import paddle_tpu as fluid
    from paddle_tpu import layers, observability
    from paddle_tpu.dataloader.dataset import Dataset
    from paddle_tpu.fleet import collective as fc
    from paddle_tpu.fleet.role_maker import UserDefinedRoleMaker

    rank = int(os.environ.get("PADDLE_TRAINER_ID", "0"))
    nranks = int(os.environ.get("PADDLE_TRAINERS_NUM", "1"))
    attempt = int(os.environ.get("PADDLE_RESTART_ATTEMPT", "0"))

    W = np.linspace(-1.0, 1.0, 4).reshape(4, 1).astype(np.float32)

    class DS(Dataset):
        def __len__(self):
            return N

        def __getitem__(self, i):
            rs = np.random.RandomState(1000 + i)  # per-example deterministic
            xa = rs.randn(4).astype(np.float32)
            return np.float32(i), xa, (xa @ W).astype(np.float32)

    x = fluid.data("x", [-1, 4])
    y = fluid.data("y", [-1, 1])
    pred = layers.fc(x, 1)
    loss = layers.mean(layers.square_error_cost(pred, y))
    opt = (fluid.optimizer.Momentum(0.05, 0.9) if sharded
           else fluid.optimizer.SGD(0.05))
    _, pg = opt.minimize(loss)
    main_prog = fluid.default_main_program()
    if sharded:
        import jax

        from paddle_tpu.parallel import make_mesh, shard_program
        from paddle_tpu.parallel.transpiler import ShardedWeightUpdate

        ShardedWeightUpdate(2).transpile(
            main_prog, fluid.default_startup_program(), pg
        )
        blk = main_prog.global_block
        blk.append_op("scale", {"X": [loss.name]}, {"Out": [loss.name]},
                      {"scale": 0.5, "bias": 0.0})
        blk.append_op("c_allreduce_sum", {"X": [loss.name]},
                      {"Out": [loss.name]}, {"axis_name": "dp"})
        shard_program(
            main_prog, make_mesh({"dp": 2}, jax.devices()[:2]),
            {"x": ("dp",), "y": ("dp",)},
        )
    main_prog.random_seed = fluid.default_startup_program().random_seed = 7
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())

    fleet = fc.Fleet()
    fleet.init(UserDefinedRoleMaker(current_id=rank, worker_num=nranks))
    ckpt_dir = os.path.join(out_dir, "ckpts")
    log_path = os.path.join(out_dir, f"consumed_rank{rank}.log")

    ds = DS()
    sampler = fluid.dataloader.DistributedBatchSampler(
        ds, BS, nranks=nranks, rank=rank, shuffle=True, seed=13
    )
    loader = fluid.DataLoader(ds, batch_sampler=sampler,
                              use_buffer_reader=False)
    # every persistable is per-rank state here (independent ranks = fully
    # weight-update-sharded); in a replicated job this list would name only
    # the genuinely non-replicated vars
    local_vars = [
        v.name for v in main_prog.list_vars()
        if getattr(v, "persistable", False) and not getattr(v, "is_data", False)
    ]

    async_mode = os.environ.get("PADDLE_TPU_RESUME_ASYNC") == "1"

    status = fleet.load_check_point(exe, ckpt_dir)
    step = int(status.global_step)
    if step > 0:
        status.restore(program=main_prog, loader=loader)
        start_epoch = int(status.cursor.get("epoch", status.next()))
        # drop log entries the resumed timeline will replay: a consumed
        # line is authoritative only up to the checkpoint's step
        lines = [
            ln for ln in open(log_path).read().splitlines()
            if ln and int(ln.split()[0]) <= step
        ]
        with open(log_path, "w") as f:
            f.writelines(ln + "\n" for ln in lines)
    else:
        start_epoch = 0
        open(log_path, "w").close()

    saver = None
    if async_mode:
        saver = fc.AsyncCheckpointer(
            fleet, ckpt_dir, executor=exe, main_program=main_prog,
            local_vars=local_vars, remain_all_checkpoint=True,
            delta=True, full_every=2,
        )

    logf = open(log_path, "a")
    for epoch in range(start_epoch, EPOCHS):
        sampler.set_epoch(epoch)
        for idxb, xb, yb in loader:
            step += 1
            exe.run(feed={"x": xb, "y": yb}, fetch_list=[loss])
            idxs = ",".join(
                str(int(i)) for i in np.asarray(idxb).reshape(-1)
            )
            logf.write(f"{step} {epoch} {idxs}\n")
            logf.flush()
            if rank == kill_rank and attempt == 0 and step == KILL_STEP:
                os.kill(os.getpid(), signal.SIGKILL)
            if step % CKPT_EVERY == 0:
                st = fc.TrainStatus.capture(
                    epoch_no=epoch - 1, global_step=step,
                    program=main_prog, loader=loader,
                )
                if saver is not None:
                    saver.save(st)
                    if (step == CKPT_EVERY and rank == kill_rank
                            and attempt == 0):
                        # make one checkpoint durably committed, then wedge
                        # the NEXT publish mid-flight: the step-11 SIGKILL
                        # lands while the step-10 publish is hung — the
                        # "killed mid-async-publish" shape the audit proves
                        saver.wait()
                        from paddle_tpu.resilience import faults

                        faults.inject(
                            "checkpoint.publish", "hang", 1.0, 0, 1
                        )
                else:
                    fleet.save_check_point(
                        exe, ckpt_dir, st, local_vars=local_vars,
                        remain_all_checkpoint=True,
                    )
    logf.close()
    if saver is not None:
        saver.close()

    scope = fluid.framework.scope.global_scope()
    arrays = {
        name: np.asarray(scope.find_var(name))
        for name in local_vars
        if scope.find_var(name) is not None
    }
    np.savez(os.path.join(out_dir, f"final_rank{rank}.npz"), **arrays)
    observability.dump(
        os.path.join(out_dir, f"obs_rank{rank}_attempt{attempt}.json")
    )
    with open(os.path.join(out_dir, f"done_rank{rank}.json"), "w") as f:
        json.dump({"attempt": attempt, "steps": step}, f)


if __name__ == "__main__":
    main(sys.argv[1], int(sys.argv[2]) if len(sys.argv) > 2 else -1)
