"""Execution sweep over the round-3 functional wrappers (layers/
functional_ext.py, layers/ssd.py): every wrapper builds into a program and
runs through the Executor — import parity (tests/test_namespaces.py) says
the names exist; this says they work."""

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import layers
from paddle_tpu.framework import unique_name


@pytest.fixture(autouse=True)
def fresh():
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 7
    scope = fluid.framework.scope.Scope()
    with fluid.program_guard(main, startup), fluid.scope_guard(scope), \
            unique_name.guard():
        yield


def _run(fetches, feed):
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    return [np.asarray(v) for v in exe.run(feed=feed, fetch_list=fetches)]


def test_activation_variants_execute():
    x = fluid.data("x", [4, 8])
    outs = [
        layers.prelu(x, mode="channel"),
        layers.hard_shrink(x), layers.softshrink(x),
        layers.tanh_shrink(x), layers.thresholded_relu(x),
        layers.soft_relu(x), layers.brelu(x), layers.stanh(x),
        layers.erf(x),
    ]
    feed = {"x": np.random.RandomState(0).randn(4, 8).astype(np.float32)}
    for v in _run(outs, feed):
        assert v.shape == (4, 8) and np.all(np.isfinite(v))


def test_norm_wrappers_execute():
    x = fluid.data("x", [2, 4, 8, 8])
    outs = [
        layers.group_norm(x, groups=2),
        layers.instance_norm(x),
        layers.data_norm(layers.reshape(x, [2, 256])),
        layers.spectral_norm(
            fluid.layers.helper.LayerHelper("w").create_parameter(
                None, [4, 6], "float32") if False else _mk_weight(),
            dim=0, power_iters=2),
    ]
    feed = {"x": np.random.RandomState(0).rand(2, 4, 8, 8).astype(np.float32)}
    for v in _run(outs, feed):
        assert np.all(np.isfinite(v))


def _mk_weight():
    from paddle_tpu.layers.helper import LayerHelper
    from paddle_tpu.initializer import Xavier

    return LayerHelper("sn").create_parameter(
        None, [4, 6], "float32", default_initializer=Xavier())


def test_conv3d_and_pool3d_wrappers():
    x = fluid.data("x", [1, 2, 4, 8, 8])
    c = layers.conv3d(x, 4, 3, padding=1, act="relu")
    p = layers.pool3d(c, pool_size=2, pool_stride=2)
    d = layers.conv3d_transpose(p, 2, 2, stride=2)
    a = layers.adaptive_pool3d(x, 2, pool_type="avg")
    feed = {"x": np.random.RandomState(0).rand(1, 2, 4, 8, 8).astype(
        np.float32)}
    outs = _run([c, p, d, a], feed)
    assert outs[0].shape == (1, 4, 4, 8, 8)
    assert outs[1].shape == (1, 4, 2, 4, 4)
    assert outs[2].shape == (1, 2, 4, 8, 8)
    assert outs[3].shape == (1, 2, 2, 2, 2)


def test_vision_wrappers_execute():
    x = fluid.data("x", [1, 4, 8, 8])
    outs = [
        layers.pixel_shuffle(x, 2),
        layers.space_to_depth(x, 2),
        layers.shuffle_channel(x, 2),
        layers.lrn(x),
        layers.interpolate(x, out_shape=[16, 16]),
        layers.image_resize_short(x, 12),
        layers.unfold(x, 3, paddings=1),
        layers.pad2d(x, (1, 1, 1, 1)),
    ]
    feed = {"x": np.random.RandomState(0).rand(1, 4, 8, 8).astype(
        np.float32)}
    for v in _run(outs, feed):
        assert np.all(np.isfinite(v))


def test_loss_wrappers_execute():
    x = fluid.data("x", [8, 4])
    y = fluid.data("y", [8, 4])
    lab = fluid.data("lab", [8, 1], "int64")
    outs = [
        layers.mse_loss(x, y),
        layers.l2_normalize(x),
        layers.dice_loss(layers.sigmoid(x), layers.cast(y, "int64")),
        layers.kldiv_loss(layers.log_softmax(x), layers.softmax(y)),
        layers.huber_loss(x, y, delta=1.0),
        layers.log_loss(layers.sigmoid(x), layers.sigmoid(y)),
        layers.smooth_l1(x, y),
        layers.npair_loss(x, y, lab),
        layers.center_loss(x, lab, num_classes=4, alpha=0.1),
        layers.teacher_student_sigmoid_loss(
            layers.reshape(x, [32, 1]), layers.reshape(y, [32, 1])),
    ]
    rng = np.random.RandomState(0)
    feed = {"x": rng.rand(8, 4).astype(np.float32),
            "y": rng.rand(8, 4).astype(np.float32),
            "lab": rng.randint(0, 4, (8, 1)).astype(np.int64)}
    for v in _run(outs, feed):
        assert np.all(np.isfinite(v))


def test_sampled_heads_execute():
    x = fluid.data("x", [8, 16])
    lab = fluid.data("lab", [8, 1], "int64")
    logits = fluid.data("logits", [8, 32])
    outs = [
        layers.nce(x, lab, num_total_classes=32, num_neg_samples=4),
        layers.hsigmoid(x, lab, num_classes=16),
        layers.sampled_softmax_with_cross_entropy(logits, lab, 8),
    ]
    rng = np.random.RandomState(0)
    feed = {"x": rng.rand(8, 16).astype(np.float32),
            "lab": rng.randint(0, 16, (8, 1)).astype(np.int64),
            "logits": rng.rand(8, 32).astype(np.float32)}
    for v in _run(outs, feed):
        assert np.all(np.isfinite(v))


def test_rnn_units_and_rowconv_execute():
    x = fluid.data("x", [4, 6, 8])
    xt = fluid.data("xt", [4, 8])
    h = fluid.data("h", [4, 8])
    c = fluid.data("c", [4, 8])
    proj, out = layers.dynamic_lstmp(x, size=32, proj_size=8)
    hid, cell = layers.lstm_unit(xt, h, c)
    rc = layers.row_conv(x, future_context_size=2)
    rng = np.random.RandomState(0)
    feed = {"x": rng.rand(4, 6, 8).astype(np.float32),
            "xt": rng.rand(4, 8).astype(np.float32),
            "h": rng.rand(4, 8).astype(np.float32),
            "c": rng.rand(4, 8).astype(np.float32)}
    outs = _run([proj, hid, cell, rc], feed)
    assert outs[0].shape == (4, 6, 8)
    assert outs[1].shape == (4, 8)
    assert outs[3].shape == (4, 6, 8)


def test_ssd_multi_box_head_and_loss_train():
    """SSD composite: multi_box_head over two feature maps + ssd_loss
    trains with finite decreasing loss."""
    img = fluid.data("img", [1, 3, 32, 32])
    gt_box = fluid.data("gt_box", [3, 4])
    gt_label = fluid.data("gt_label", [3, 1], "int64")
    f1 = layers.conv2d(img, 8, 3, stride=4, padding=1, act="relu")
    f2 = layers.conv2d(f1, 8, 3, stride=2, padding=1, act="relu")
    locs, confs, boxes, variances = layers.multi_box_head(
        [f1, f2], img, base_size=32, num_classes=4,
        aspect_ratios=[[1.0], [1.0, 2.0]],
    )
    loss = layers.ssd_loss(locs, confs, gt_box, gt_label, boxes, variances)
    fluid.optimizer.Adam(5e-3).minimize(loss)
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    rng = np.random.RandomState(0)
    feed = {
        "img": rng.rand(1, 3, 32, 32).astype(np.float32),
        "gt_box": np.array([[0.1, 0.1, 0.4, 0.4],
                            [0.5, 0.5, 0.9, 0.9],
                            [0.2, 0.6, 0.5, 0.95]], np.float32),
        "gt_label": np.array([[1], [2], [3]], np.int64),
    }
    losses = [
        float(np.asarray(exe.run(feed=feed, fetch_list=[loss])[0])
              .reshape(-1)[0])
        for _ in range(25)
    ]
    assert all(np.isfinite(v) for v in losses), losses[:3]
    assert losses[-1] < losses[0], (losses[0], losses[-1])


def test_misc_wrappers_execute():
    x = fluid.data("x", [4, 8])
    ids = fluid.data("ids", [4, 1], "int64")
    outs = [
        layers.hash(ids, hash_size=100, num_hash=2),
        layers.similarity_focus(
            layers.reshape(x, [1, 2, 4, 4]), axis=1, indexes=[0]),
        layers.maxout(layers.reshape(x, [1, 4, 2, 4]), groups=2),
        layers.label_smooth(
            layers.cast(layers.one_hot(ids, 8), "float32")),
        layers.linear(x, _mk_linear_w()),
        layers.pad(x, [1, 1, 2, 2]),
        layers.fsp_matrix(
            layers.reshape(x, [1, 4, 4, 2]),
            layers.reshape(x, [1, 4, 4, 2])),
    ]
    rng = np.random.RandomState(0)
    feed = {"x": rng.rand(4, 8).astype(np.float32),
            "ids": rng.randint(0, 8, (4, 1)).astype(np.int64)}
    for v in _run(outs, feed):
        assert np.all(np.isfinite(v))


def _mk_linear_w():
    from paddle_tpu.layers.helper import LayerHelper
    from paddle_tpu.initializer import Xavier

    return LayerHelper("lin").create_parameter(
        None, [8, 4], "float32", default_initializer=Xavier())
