"""Preemption-drain worker: trains with a TrainGuard wired to a Fleet
checkpoint dir, touches a ``ready`` marker once the loop is underway, and
then keeps stepping until a SIGTERM arrives. The guard drains — finishes
the in-flight step, writes a final ``save_check_point`` (CRC manifest and
all), and exits with the distinguished PREEMPTION_EXIT_CODE (75).

argv[1] = work dir (checkpoints land in {dir}/ckpts, marker at
{dir}/ready). Used by tests/test_health_guard.py and the ci.sh chaos
smoke.
"""

import os
import sys
import time

import numpy as np


def main(work_dir):
    import paddle_tpu as fluid
    from paddle_tpu import layers
    from paddle_tpu.fleet import collective as fc
    from paddle_tpu.fleet.role_maker import UserDefinedRoleMaker
    from paddle_tpu.resilience import TrainGuard

    rng = np.random.RandomState(11)
    W = rng.randn(4, 1).astype(np.float32)

    x = fluid.data("x", [-1, 4])
    y = fluid.data("y", [-1, 1])
    pred = layers.fc(x, 1)
    loss = layers.mean(layers.square_error_cost(pred, y))
    fluid.optimizer.SGD(0.05).minimize(loss)
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())

    fleet = fc.Fleet()
    fleet.init(UserDefinedRoleMaker())
    ckpt_dir = os.path.join(work_dir, "ckpts")
    marker = os.path.join(work_dir, "ready")

    with TrainGuard(
        exe, fleet=fleet, checkpoint_dir=ckpt_dir,
        train_status=fc.TrainStatus(0),
    ) as g:
        for step in range(100000):
            xa = rng.randn(8, 4).astype(np.float32)
            g.step(feed={"x": xa, "y": xa @ W}, fetch_list=[loss])
            if step == 0:
                open(marker, "w").close()
            time.sleep(0.05)  # leave a window for the SIGTERM to land
    # unreachable under preemption: g.step raises SystemExit(75) after the
    # final checkpoint; reaching here means the test never sent SIGTERM
    sys.exit(9)


if __name__ == "__main__":
    main(sys.argv[1])
