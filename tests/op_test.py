"""Declarative op-test harness.

Reference parity: python/paddle/fluid/tests/unittests/op_test.py:170 — a test
declares op_type / inputs / attrs / expected outputs as numpy; check_output
builds a one-op program and compares; check_grad compares analytic gradients
(append_backward over the op) against a central-difference numeric Jacobian
(reference: tests/unittests/gradient_checker.py).
"""

from __future__ import annotations

import numpy as np

import paddle_tpu as fluid
from paddle_tpu.framework import unique_name
from paddle_tpu.framework.registry import infer_shapes
from paddle_tpu.framework.scope import Scope, scope_guard


class OpTest:
    op_type: str = ""

    def setup(self):
        """Subclasses set self.inputs / self.outputs / self.attrs here."""
        raise NotImplementedError

    # ------------------------------------------------------------------
    def _build(self):
        self.attrs = getattr(self, "attrs", {})
        main, startup = fluid.Program(), fluid.Program()
        scope = Scope()
        ctx = (fluid.program_guard(main, startup), scope_guard(scope),
               unique_name.guard())
        for c in ctx:
            c.__enter__()
        self._ctx = ctx

        blk = main.global_block
        feed = {}
        in_names = {}
        self._in_vars = {}
        for slot, val in self.inputs.items():
            vals = val if isinstance(val, list) else [val]
            names = []
            for i, v in enumerate(vals):
                v = np.asarray(v)
                name = f"{slot.lower()}_{i}"
                var = blk.create_var(
                    name=name, shape=v.shape, dtype=v.dtype, is_data=True,
                    stop_gradient=not np.issubdtype(v.dtype, np.floating),
                )
                feed[name] = v
                names.append(name)
                self._in_vars[(slot, i)] = var
            in_names[slot] = names

        out_specs = infer_shapes(self.op_type, blk, in_names, self.attrs)
        out_names = {}
        self._out_vars = {}
        for slot, specs in out_specs.items():
            names = []
            for i, (shape, dtype) in enumerate(specs):
                name = f"out_{slot.lower()}_{i}"
                var = blk.create_var(name=name, shape=shape, dtype=dtype)
                names.append(name)
                self._out_vars[(slot, i)] = var
            out_names[slot] = names
        blk.append_op(self.op_type, in_names, out_names, self.attrs)
        return main, startup, feed

    def _teardown(self):
        for c in reversed(self._ctx):
            c.__exit__(None, None, None)

    # ------------------------------------------------------------------
    def check_output(self, atol=1e-5, rtol=1e-5):
        self.setup()
        main, startup, feed = self._build()
        try:
            exe = fluid.Executor()
            exe.run(startup)
            for slot, expect in self.outputs.items():
                expects = expect if isinstance(expect, list) else [expect]
                for i, e in enumerate(expects):
                    if e is None:
                        continue
                    (got,) = exe.run(
                        main, feed=feed, fetch_list=[self._out_vars[(slot, i)]]
                    )
                    np.testing.assert_allclose(
                        got.astype(np.float64)
                        if got.dtype != np.bool_
                        else got,
                        np.asarray(e),
                        atol=atol,
                        rtol=rtol,
                        err_msg=f"{self.op_type} output {slot}[{i}]",
                    )
        finally:
            self._teardown()

    # ------------------------------------------------------------------
    def check_grad(
        self, inputs_to_check, output_slot=None, delta=1e-3, rtol=1e-2,
        atol=1e-4,
    ):
        """Compare analytic grad of mean(output) vs numeric central diff."""
        self.setup()
        main, startup, feed = self._build()
        try:
            if output_slot is None:
                output_slot = sorted(self._out_vars)[0][0]
            out_var = self._out_vars[(output_slot, 0)]
            loss = fluid.layers.mean(
                fluid.layers.cast(out_var, "float32")
                if out_var.dtype != "float32"
                else out_var
            )
            check_vars = [
                self._in_vars[(slot, 0)] for slot in inputs_to_check
            ]
            grads = fluid.gradients(loss, check_vars)
            exe = fluid.Executor()
            exe.run(startup)
            analytic = exe.run(main, feed=feed, fetch_list=grads)

            def scalar(feed_override):
                (o,) = exe.run(main, feed=feed_override, fetch_list=[loss])
                return float(np.asarray(o).reshape(-1)[0])

            for slot, g in zip(inputs_to_check, analytic):
                base = np.asarray(feed[f"{slot.lower()}_0"], dtype=np.float64)
                num = np.zeros_like(base)
                flat = base.reshape(-1)
                for j in range(flat.size):
                    for sgn in (+1, -1):
                        pert = flat.copy()
                        pert[j] += sgn * delta
                        f2 = dict(feed)
                        f2[f"{slot.lower()}_0"] = pert.reshape(base.shape).astype(
                            feed[f"{slot.lower()}_0"].dtype
                        )
                        num.reshape(-1)[j] += sgn * scalar(f2)
                num /= 2 * delta
                np.testing.assert_allclose(
                    np.asarray(g), num, rtol=rtol, atol=atol,
                    err_msg=f"{self.op_type} grad wrt {slot}",
                )
        finally:
            self._teardown()
