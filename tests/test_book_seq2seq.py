"""End-to-end seq2seq "book test" (reference
tests/book/test_machine_translation.py): GRU encoder-decoder trained with
teacher forcing on a toy copy task, then beam-search decoding reproduces
the sequences. Exercises embedding + GRU + attention-free decoding +
beam_search/beam_search_decode together.
"""

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import layers
from paddle_tpu.framework import unique_name

V, T, H, B = 12, 5, 64, 32
BOS, EOS = 0, 1


@pytest.fixture(autouse=True)
def fresh_programs():
    main, startup = fluid.Program(), fluid.Program()
    # seed 0 = fluid's "nondeterministic" mode (per-instance nonce), which
    # makes the convergence/beam-decode assertions stochastic — pin them
    main.random_seed = startup.random_seed = 2024
    scope = fluid.framework.scope.Scope()
    with fluid.program_guard(main, startup), fluid.scope_guard(scope), \
            unique_name.guard():
        yield main, startup, scope


def _embed(ids, name):
    return layers.embedding(
        ids, size=[V, H],
        param_attr=fluid.ParamAttr(name=name),
    )


def _decoder_logits(dec_in_emb, enc_last):
    dec_out, _ = layers.gru(
        dec_in_emb, H, init_h=enc_last,
        param_attr=fluid.ParamAttr(name="dec_wih"),
    )
    b, t = dec_out.shape[0], dec_out.shape[1]
    flat = layers.reshape(dec_out, [b * t, H])
    logits = layers.fc(
        flat, V,
        param_attr=fluid.ParamAttr(name="proj_w"),
        bias_attr=fluid.ParamAttr(name="proj_b"),
    )
    return layers.reshape(logits, [b, t, V])


def _batch(rng, n):
    """Toy task: target = source (copy), source tokens in [2, V)."""
    src = rng.randint(2, V, (n, T)).astype(np.int64)
    dec_in = np.concatenate(
        [np.full((n, 1), BOS, np.int64), src[:, :-1]], axis=1
    )
    return src, dec_in, src  # (src, decoder input, labels)


def test_seq2seq_trains_and_beam_decodes():
    src = fluid.data("src", [B, T], "int64")
    dec_in = fluid.data("dec_in", [B, T], "int64")
    label = fluid.data("label", [B, T], "int64")

    _, enc_last = layers.gru(
        _embed(src, "src_emb"), H,
        param_attr=fluid.ParamAttr(name="enc_wih"),
    )
    logits = _decoder_logits(_embed(dec_in, "tgt_emb"), enc_last)
    loss = layers.mean(
        layers.softmax_with_cross_entropy(
            layers.reshape(logits, [B * T, V]),
            layers.reshape(label, [B * T, 1]),
        )
    )
    fluid.optimizer.Adam(0.02).minimize(loss)

    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    rng = np.random.RandomState(0)
    losses = []
    # 900 steps (was 700): at 700 the copy task sat on a knife edge where
    # float-rounding-level changes in the CE emitter (r4 lse-form, ~1e-6)
    # flipped one of the ten decode trials; the extra steps make the
    # decode margin robust to benign numeric drift
    for step in range(900):
        s, d, l = _batch(rng, B)
        (lv,) = exe.run(
            feed={"src": s, "dec_in": d, "label": l}, fetch_list=[loss]
        )
        losses.append(float(np.asarray(lv).reshape(-1)[0]))
    assert losses[-1] < 0.2, (losses[0], losses[-1])

    # ---- greedy/beam decoding program reusing the trained weights ----
    K = 3
    infer = fluid.Program()
    with fluid.program_guard(infer, fluid.Program()):
        src_i = fluid.data("src", [1, T], "int64")
        _, h = layers.gru(
            _embed(src_i, "src_emb"), H,
            param_attr=fluid.ParamAttr(name="enc_wih"),
        )
        # beam state: [1, K] frontier; decoder state per beam [K, H]
        pre_ids = fluid.data("pre0", [1, K], "int64")
        pre_sc = fluid.data("sc0", [1, K])
        state = layers.expand(h, [K, 1])  # same encoder state per beam
        ids_v, sc_v = pre_ids, pre_sc
        step_ids, step_par = [], []
        for t in range(T):
            emb_t = layers.reshape(
                _embed(layers.reshape(ids_v, [K, 1]), "tgt_emb"), [K, 1, H]
            )
            out_t, state_next = layers.gru(
                emb_t, H, init_h=state,
                param_attr=fluid.ParamAttr(name="dec_wih"),
            )
            logits_t = layers.fc(
                layers.reshape(out_t, [K, H]), V,
                param_attr=fluid.ParamAttr(name="proj_w"),
                bias_attr=fluid.ParamAttr(name="proj_b"),
            )
            logp = layers.reshape(
                layers.log_softmax(logits_t), [1, K, V]
            )
            ids_v, sc_v, par_v = layers.beam_search(
                ids_v, sc_v, None, logp, beam_size=K, end_id=EOS,
                is_accumulated=False,  # logp is per-step log-probs
                return_parent_idx=True, first_step=(t == 0),
            )
            # reorder decoder states to follow the selected parents
            state_next = layers.reshape(state_next, [K, H])
            state = layers.gather(state_next, layers.reshape(par_v, [K]))
            step_ids.append(ids_v)
            step_par.append(par_v)
        sentences = layers.beam_search_decode(
            layers.stack(step_ids, axis=0),
            layers.stack(step_par, axis=0), end_id=EOS,
        )

    correct = 0
    trials = 10
    init_sc = np.full((1, K), -1e9, np.float32)
    init_sc[0, 0] = 0.0
    for _ in range(trials):
        s, _, _ = _batch(rng, 1)
        (seqs,) = exe.run(
            infer,
            feed={"src": s,
                  "pre0": np.full((1, K), BOS, np.int64),
                  "sc0": init_sc},
            fetch_list=[sentences],
        )
        if np.array_equal(np.asarray(seqs)[0, 0], s[0]):
            correct += 1
    assert correct >= 8, f"beam decode reproduced {correct}/{trials}"
