"""C inference API: build libpaddle_tpu_capi.so, compile a real C driver
against paddle_tpu_capi.h, run it in a subprocess against a saved model,
and compare its output with the Python predictor (reference
inference/capi tests pattern)."""

import os
import shutil
import subprocess
import sys

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import layers
from paddle_tpu.framework import unique_name
from paddle_tpu.inference_capi import build_capi, header_path

pytestmark = pytest.mark.skipif(
    shutil.which("gcc") is None or shutil.which("g++") is None,
    reason="no C/C++ toolchain",
)

_DRIVER = r"""
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include "paddle_tpu_capi.h"

int main(int argc, char** argv) {
  PD_AnalysisConfig* cfg = PD_NewAnalysisConfig();
  PD_SetModel(cfg, argv[1], NULL, NULL);
  PD_Predictor* pred = PD_NewPredictor(cfg);
  if (!pred) {
    fprintf(stderr, "predictor: %s\n", PD_GetLastError());
    return 2;
  }
  printf("inputs=%d outputs=%d in0=%s out0=%s\n", PD_GetInputNum(pred),
         PD_GetOutputNum(pred), PD_GetInputName(pred, 0),
         PD_GetOutputName(pred, 0));

  float data[4 * 8];
  for (int i = 0; i < 32; i++) data[i] = (float)i / 31.0f - 0.5f;
  int64_t shape[2] = {4, 8};
  PD_TensorC in = {PD_GetInputName(pred, 0), PD_FLOAT32, shape, 2, data,
                   sizeof(data)};
  PD_TensorC* outs = NULL;
  int n_out = 0;
  if (!PD_PredictorRun(pred, &in, 1, &outs, &n_out)) {
    fprintf(stderr, "run: %s\n", PD_GetLastError());
    return 3;
  }
  printf("n_out=%d rank=%d dtype=%d bytes=%zu\n", n_out, outs[0].rank,
         outs[0].dtype, outs[0].byte_size);
  const float* y = (const float*)outs[0].data;
  size_t n = outs[0].byte_size / sizeof(float);
  for (size_t i = 0; i < n; i++) printf("%.6f\n", y[i]);
  PD_FreeOutputs(outs, n_out);

  /* zero-copy run: output data points into predictor-owned buffers */
  PD_TensorC* zouts = NULL;
  int zn = 0;
  if (!PD_ZeroCopyRun(pred, &in, 1, &zouts, &zn)) {
    fprintf(stderr, "zrun: %s\n", PD_GetLastError());
    return 4;
  }
  printf("zero_copy n=%d\n", zn);
  {
    const float* zy = (const float*)zouts[0].data;
    size_t zn_el = zouts[0].byte_size / sizeof(float);
    for (size_t i = 0; i < zn_el; i++) printf("%.6f\n", zy[i]);
  }
  PD_FreeZeroCopyOutputs(zouts, zn);
  PD_DeletePredictor(pred);
  PD_DeleteAnalysisConfig(cfg);
  return 0;
}
"""


def test_c_api_end_to_end(tmp_path):
    # ---- save a small model + compute the Python-side reference ----
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 4
    scope = fluid.framework.scope.Scope()
    feed = (np.arange(32, dtype=np.float32) / 31.0 - 0.5).reshape(4, 8)
    with fluid.program_guard(main, startup), fluid.scope_guard(scope), \
            unique_name.guard():
        x = fluid.data("x", [4, 8])
        y = layers.fc(x, 5, act="tanh")
        exe = fluid.Executor()
        exe.run(startup, scope=scope)
        model_dir = str(tmp_path / "model")
        fluid.io.save_inference_model(model_dir, ["x"], [y], exe,
                                      main_program=main)
        (ref,) = exe.run(main, feed={"x": feed}, fetch_list=[y], scope=scope)
    ref = np.asarray(ref)

    # ---- build the shared library and the C driver ----
    lib = build_capi()
    driver_c = tmp_path / "driver.c"
    driver_c.write_text(_DRIVER)
    driver = tmp_path / "driver"
    subprocess.run(
        ["gcc", str(driver_c), "-o", str(driver),
         f"-I{os.path.dirname(header_path())}", str(lib),
         f"-Wl,-rpath,{os.path.dirname(lib)}"],
        check=True, capture_output=True, text=True,
    )

    # ---- run the C program; the embedded interpreter must see our repo
    # and run jax on CPU (no conftest inside the C process) ----
    env = dict(os.environ)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    # strip any TPU-plugin site dir: the embedded interpreter must run jax
    # on CPU so the comparison against the (CPU) pytest reference is exact
    keep = [
        p for p in env.get("PYTHONPATH", "").split(os.pathsep)
        if p and "axon" not in p
    ]
    env["PYTHONPATH"] = os.pathsep.join([repo, *keep])
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run(
        [str(driver), model_dir], capture_output=True, text=True, env=env,
        timeout=300,
    )
    assert proc.returncode == 0, proc.stderr + proc.stdout
    lines = proc.stdout.strip().splitlines()
    assert lines[0].startswith("inputs=1 outputs=1 in0=x")
    meta = lines[1]
    assert "n_out=1" in meta and "rank=2" in meta and "dtype=0" in meta
    zc = lines.index("zero_copy n=1")
    got = np.array([float(v) for v in lines[2:zc]], np.float32).reshape(4, 5)
    np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-6)
    # zero-copy outputs read in place from predictor-owned buffers
    zgot = np.array([float(v) for v in lines[zc + 1:]],
                    np.float32).reshape(4, 5)
    np.testing.assert_allclose(zgot, ref, rtol=1e-5, atol=1e-6)
