"""GPT decoder-only LM: causality (future tokens cannot influence past
positions, fused and dense paths), fused==dense equivalence, and
next-token training on a deterministic sequence."""

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import layers
from paddle_tpu.framework import unique_name
from paddle_tpu.models import GPTConfig, gpt_decoder, gpt_lm_loss


@pytest.fixture(autouse=True)
def fresh_programs():
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 77
    scope = fluid.framework.scope.Scope()
    with fluid.program_guard(main, startup), fluid.scope_guard(scope), \
            unique_name.guard():
        yield main, startup, scope


def _cfg(fused):
    cfg = GPTConfig.tiny()
    cfg.use_fused_attention = fused
    cfg.hidden_dropout = 0.0
    cfg.attention_dropout = 0.0
    return cfg


@pytest.mark.parametrize("fused", [True, False])
def test_causality(fused):
    """Changing tokens after position t must not change hidden states at
    positions <= t."""
    B, S = 2, 16
    ids = fluid.data("ids", [B, S], "int64")
    hidden = gpt_decoder(ids, _cfg(fused), is_test=True)
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    rng = np.random.RandomState(0)
    a = rng.randint(0, 512, (B, S)).astype("int64")
    b = a.copy()
    b[:, 10:] = rng.randint(0, 512, (B, S - 10))
    (ha,) = exe.run(feed={"ids": a}, fetch_list=[hidden])
    (hb,) = exe.run(feed={"ids": b}, fetch_list=[hidden])
    np.testing.assert_allclose(
        np.asarray(ha)[:, :10], np.asarray(hb)[:, :10], rtol=1e-5, atol=1e-5
    )
    assert not np.allclose(np.asarray(ha)[:, 10:], np.asarray(hb)[:, 10:])


def test_fused_matches_dense():
    B, S = 2, 16
    rng = np.random.RandomState(1)
    ids_np = rng.randint(0, 512, (B, S)).astype("int64")
    outs = {}
    for fused in (True, False):
        main, startup = fluid.Program(), fluid.Program()
        main.random_seed = startup.random_seed = 9
        scope = fluid.framework.scope.Scope()
        with fluid.program_guard(main, startup), \
                fluid.scope_guard(scope), unique_name.guard():
            ids = fluid.data("ids", [B, S], "int64")
            hidden = gpt_decoder(ids, _cfg(fused), is_test=True)
            exe = fluid.Executor()
            exe.run(startup, scope=scope)
            (h,) = exe.run(
                main, feed={"ids": ids_np}, fetch_list=[hidden], scope=scope
            )
            outs[fused] = np.asarray(h)
    np.testing.assert_allclose(outs[True], outs[False], rtol=1e-4, atol=1e-4)


def test_gpt_trains_on_cyclic_sequence():
    """Next-token prediction on w[t+1] = (w[t]*5 + 1) % V — fully
    deterministic, so the LM loss should collapse."""
    B, S, V = 8, 32, 512
    cfg = _cfg(True)
    ids = fluid.data("ids", [B, S], "int64")
    loss = gpt_lm_loss(ids, cfg)
    fluid.optimizer.Adam(1e-3).minimize(loss)
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    rng = np.random.RandomState(2)
    seq = np.zeros((B, S), np.int64)
    seq[:, 0] = rng.randint(0, V, B)
    for t in range(1, S):
        seq[:, t] = (seq[:, t - 1] * 5 + 1) % V
    vals = []
    for _ in range(60):
        (lv,) = exe.run(feed={"ids": seq}, fetch_list=[loss])
        vals.append(float(np.asarray(lv).reshape(-1)[0]))
    assert vals[-1] < 0.25 * vals[0], (vals[0], vals[-1])
