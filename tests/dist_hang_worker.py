"""Launcher hang-chaos worker: each rank trains a tiny regression
independently (no cross-rank collectives, so a killed peer cannot wedge
the others). On its FIRST attempt, rank 1 arms a ``hang`` fault at the
``guard.step`` seam after a few healthy steps — the beats stop, the
launcher's ``--heartbeat_timeout`` watcher kills it, and the ``--elastic``
path restarts it; the restart (PADDLE_RESTART_ATTEMPT=1) runs clean.

Writes ``hang_losses_{rank}.json`` into argv[1] on successful completion.
Used by tests/test_health_guard.py (slow) and the ci.sh chaos smoke.
"""

import json
import os
import sys

# bound the injected hang: long enough to be "stuck" for any sane
# --heartbeat_timeout, short enough that a broken watchdog fails the test
# instead of wedging CI
os.environ.setdefault("PADDLE_TPU_FAULT_HANG_SECONDS", "120")

import numpy as np


def main(out_dir):
    import paddle_tpu as fluid
    from paddle_tpu import layers
    from paddle_tpu.resilience import TrainGuard, faults

    rank = int(os.environ.get("PADDLE_TRAINER_ID", "0"))
    attempt = int(os.environ.get("PADDLE_RESTART_ATTEMPT", "0"))
    rng = np.random.RandomState(7 + rank)
    W = rng.randn(4, 1).astype(np.float32)

    x = fluid.data("x", [-1, 4])
    y = fluid.data("y", [-1, 1])
    pred = layers.fc(x, 1)
    loss = layers.mean(layers.square_error_cost(pred, y))
    fluid.optimizer.SGD(0.05).minimize(loss)
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())

    losses = []
    # heartbeat auto-configured from the launcher's PADDLE_HEARTBEAT_DIR
    with TrainGuard(exe) as g:
        for step in range(20):
            if rank == 1 and attempt == 0 and step == 3:
                faults.inject("guard.step", "hang", 1.0, 0, 1)
            xa = rng.randn(8, 4).astype(np.float32)
            out = g.step(feed={"x": xa, "y": xa @ W}, fetch_list=[loss])
            losses.append(float(np.asarray(out[0]).reshape(-1)[0]))
    with open(os.path.join(out_dir, f"hang_losses_{rank}.json"), "w") as f:
        json.dump({"attempt": attempt, "losses": losses}, f)


if __name__ == "__main__":
    main(sys.argv[1])
