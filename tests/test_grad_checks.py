"""Per-op numeric gradient checks across the differentiable op surface.

VERDICT item 10: analytic grads (append_backward's generic __vjp__) vs
central-difference Jacobians, the reference's OpTest.check_grad bar
(tests/unittests/op_test.py:170 + gradient_checker.py). Table-driven sweep;
inputs are chosen away from kinks (|x| >= 0.1 for relu/abs-like ops) so the
numeric difference is well-conditioned.
"""

import numpy as np
import pytest

from op_test import OpTest

RNG = np.random.RandomState(7)


def smooth(*shape):
    """Values bounded away from zero (kink-free for piecewise ops)."""
    x = RNG.uniform(0.2, 1.0, size=shape) * RNG.choice([-1, 1], size=shape)
    return x.astype(np.float32)


def positive(*shape):
    return RNG.uniform(0.3, 1.2, size=shape).astype(np.float32)


# (name, op_type, inputs, attrs, inputs_to_check, tolerances)
CASES = [
    ("elementwise_add", "elementwise_add",
     {"X": smooth(3, 4), "Y": smooth(3, 4)}, {}, ["X", "Y"], {}),
    ("elementwise_sub", "elementwise_sub",
     {"X": smooth(3, 4), "Y": smooth(3, 4)}, {}, ["X", "Y"], {}),
    ("elementwise_mul", "elementwise_mul",
     {"X": smooth(3, 4), "Y": smooth(3, 4)}, {}, ["X", "Y"], {}),
    ("elementwise_div", "elementwise_div",
     {"X": smooth(3, 4), "Y": positive(3, 4)}, {}, ["X", "Y"], {}),
    ("elementwise_max", "elementwise_max",
     {"X": smooth(3, 4), "Y": smooth(3, 4) + 5.0}, {}, ["X"], {}),
    ("elementwise_pow", "elementwise_pow",
     {"X": positive(3, 4), "Y": positive(3, 4)}, {}, ["X", "Y"], {}),
    ("matmul", "matmul",
     {"X": smooth(3, 4), "Y": smooth(4, 5)}, {}, ["X", "Y"], {}),
    ("matmul_transpose", "matmul",
     {"X": smooth(4, 3), "Y": smooth(5, 4)},
     {"transpose_X": True, "transpose_Y": True}, ["X", "Y"], {}),
    ("mul", "mul", {"X": smooth(3, 4), "Y": smooth(4, 2)}, {}, ["X", "Y"], {}),
    ("bmm", "bmm",
     {"X": smooth(2, 3, 4), "Y": smooth(2, 4, 3)}, {}, ["X", "Y"], {}),
    ("softmax", "softmax", {"X": smooth(3, 5)}, {"axis": -1}, ["X"], {}),
    ("log_softmax", "log_softmax", {"X": smooth(3, 5)}, {}, ["X"], {}),
    ("sigmoid", "sigmoid", {"X": smooth(3, 4)}, {}, ["X"], {}),
    ("tanh", "tanh", {"X": smooth(3, 4)}, {}, ["X"], {}),
    ("exp", "exp", {"X": smooth(3, 4)}, {}, ["X"], {}),
    ("log", "log", {"X": positive(3, 4)}, {}, ["X"], {}),
    ("sqrt", "sqrt", {"X": positive(3, 4)}, {}, ["X"], {}),
    ("rsqrt", "rsqrt", {"X": positive(3, 4)}, {}, ["X"], {}),
    ("square", "square", {"X": smooth(3, 4)}, {}, ["X"], {}),
    ("gelu", "gelu", {"X": smooth(3, 4)}, {}, ["X"], {}),
    ("relu", "relu", {"X": smooth(3, 4)}, {}, ["X"], {}),
    ("leaky_relu", "leaky_relu",
     {"X": smooth(3, 4)}, {"alpha": 0.1}, ["X"], {}),
    ("silu", "silu", {"X": smooth(3, 4)}, {}, ["X"], {}),
    ("softplus", "softplus", {"X": smooth(3, 4)}, {}, ["X"], {}),
    ("reduce_sum", "reduce_sum",
     {"X": smooth(3, 4)}, {"dim": [1], "keep_dim": False, "reduce_all": False},
     ["X"], {}),
    ("reduce_mean", "reduce_mean",
     {"X": smooth(3, 4)}, {"dim": [0], "keep_dim": True, "reduce_all": False},
     ["X"], {}),
    ("reduce_max", "reduce_max",
     {"X": smooth(3, 4)}, {"dim": [1], "keep_dim": False, "reduce_all": False},
     ["X"], {}),
    ("reduce_prod", "reduce_prod",
     {"X": positive(2, 3)}, {"dim": [1], "keep_dim": False, "reduce_all": False},
     ["X"], {}),
    ("layer_norm", "layer_norm",
     {"X": smooth(3, 8), "Scale": positive(8), "Bias": smooth(8)},
     {"begin_norm_axis": 1, "epsilon": 1e-5}, ["X", "Scale", "Bias"],
     {"rtol": 3e-2, "atol": 3e-4}),
    ("instance_norm", "instance_norm",
     {"X": smooth(2, 3, 4, 4), "Scale": positive(3), "Bias": smooth(3)},
     {"epsilon": 1e-5}, ["X"], {"rtol": 3e-2, "atol": 3e-4}),
    ("conv2d", "conv2d",
     {"Input": smooth(2, 3, 6, 6), "Filter": smooth(4, 3, 3, 3)},
     {"strides": [1, 1], "paddings": [1, 1], "dilations": [1, 1], "groups": 1},
     ["Input", "Filter"], {"rtol": 2e-2, "atol": 3e-4}),
    ("pool2d_avg", "pool2d",
     {"X": smooth(2, 3, 6, 6)},
     {"pooling_type": "avg", "ksize": [2, 2], "strides": [2, 2],
      "paddings": [0, 0]}, ["X"], {}),
    ("transpose2", "transpose2",
     {"X": smooth(3, 4, 5)}, {"axis": [2, 0, 1]}, ["X"], {}),
    ("reshape2", "reshape2",
     {"X": smooth(3, 4)}, {"shape": [2, 6]}, ["X"], {}),
    ("concat", "concat",
     {"X": [smooth(3, 2), smooth(3, 3)]}, {"axis": 1}, ["X"], {}),
    ("slice", "slice",
     {"Input": smooth(4, 5)},
     {"axes": [0, 1], "starts": [1, 0], "ends": [3, 4]}, ["Input"], {}),
    ("gather", "gather",
     {"X": smooth(5, 3), "Index": np.asarray([0, 2, 2], np.int32)}, {},
     ["X"], {}),
    ("scale", "scale",
     {"X": smooth(3, 4)}, {"scale": 2.5, "bias": 0.5}, ["X"], {}),
    ("cumsum", "cumsum",
     {"X": smooth(3, 4)}, {"axis": 1, "reverse": False, "exclusive": False},
     ["X"], {}),
    ("stack", "stack",
     {"X": [smooth(3, 2), smooth(3, 2)]}, {"axis": 0}, ["X"], {}),
    ("squeeze2", "squeeze2",
     {"X": smooth(3, 1, 4)}, {"axes": [1]}, ["X"], {}),
    ("unsqueeze2", "unsqueeze2",
     {"X": smooth(3, 4)}, {"axes": [1]}, ["X"], {}),
    ("pad", "pad",
     {"X": smooth(3, 4)}, {"paddings": [1, 1, 0, 2], "pad_value": 0.0},
     ["X"], {}),
    ("softmax_with_cross_entropy", "softmax_with_cross_entropy",
     {"Logits": smooth(4, 6), "Label": RNG.randint(0, 6, (4, 1)).astype(np.int64)},
     {}, ["Logits"], {"output_slot": "Loss"}),
    ("cross_entropy", "cross_entropy",
     {"X": (positive(4, 5) / positive(4, 5).sum(1, keepdims=True)),
      "Label": RNG.randint(0, 5, (4, 1)).astype(np.int64)}, {}, ["X"],
     {"output_slot": "Y"}),
    ("sigmoid_xent", "sigmoid_cross_entropy_with_logits",
     {"X": smooth(4, 3), "Label": RNG.rand(4, 3).astype(np.float32)}, {},
     ["X"], {}),
    ("huber_loss", "huber_loss",
     {"X": smooth(4, 1), "Y": smooth(4, 1)}, {"delta": 1.0}, ["X"],
     {"output_slot": "Out"}),
    ("lookup_table_v2", "lookup_table_v2",
     {"Ids": np.asarray([0, 2, 1], np.int64), "W": smooth(4, 3)}, {},
     ["W"], {}),
    ("distributed_lookup_table", "distributed_lookup_table",
     {"Ids": np.asarray([0, 2, 1], np.int64), "W": smooth(4, 3)}, {},
     ["W"], {}),
    ("group_norm", "group_norm",
     {"X": smooth(2, 4, 3, 3), "Scale": positive(4), "Bias": smooth(4)},
     {"groups": 2, "epsilon": 1e-5}, ["X"], {"rtol": 3e-2, "atol": 3e-4}),
    ("clip", "clip",
     {"X": smooth(3, 4) * 0.4}, {"min": -0.9, "max": 0.9}, ["X"], {}),
    ("dot", "dot", {"X": smooth(5), "Y": smooth(5)}, {}, ["X", "Y"], {}),
    # --- round-3 op-surface additions ---
    ("prelu", "prelu",
     {"X": smooth(2, 3, 4), "Alpha": positive(1)}, {"mode": "all"},
     ["X", "Alpha"], {}),
    ("row_conv", "row_conv",
     {"X": smooth(2, 5, 3), "Filter": smooth(2, 3)}, {},
     ["X", "Filter"], {}),
    ("conv_shift", "conv_shift",
     {"X": smooth(2, 8), "Y": smooth(2, 3)}, {}, ["X", "Y"], {}),
    ("unfold", "unfold",
     {"X": smooth(1, 2, 4, 4)},
     {"kernel_sizes": [2, 2], "strides": [1, 1],
      "paddings": [0, 0, 0, 0], "dilations": [1, 1]},
     ["X"], {"output_slot": "Y"}),
    ("partial_sum", "partial_sum",
     {"X": smooth(2, 5)}, {"start_index": 1, "length": 2}, ["X"], {}),
    ("frobenius_norm", "frobenius_norm",
     {"X": positive(3, 4)}, {"reduce_all": True}, ["X"], {}),
    ("fsp", "fsp",
     {"X": smooth(2, 3, 4, 4), "Y": smooth(2, 2, 4, 4)}, {},
     ["X", "Y"], {}),
    ("batch_fc", "batch_fc",
     {"Input": smooth(2, 3, 4), "W": smooth(2, 4, 5), "Bias": smooth(2, 1, 5)},
     {}, ["Input", "W", "Bias"], {}),
    ("warpctc", "warpctc",
     {"Logits": smooth(2, 5, 4), "Label": np.asarray([[1, 2], [2, 3]], np.int64),
      "LogitsLength": np.asarray([5, 5], np.int64),
      "LabelLength": np.asarray([2, 2], np.int64)},
     {"blank": 0}, ["Logits"], {"output_slot": "Loss", "rtol": 3e-2}),
    ("teacher_student_sigmoid_loss", "teacher_student_sigmoid_loss",
     {"X": smooth(4, 1), "Label": positive(4, 1) * 0.5}, {},
     ["X"], {"output_slot": "Y"}),
    ("spectral_norm", "spectral_norm",
     {"Weight": smooth(3, 4), "U": smooth(3), "V": smooth(4)},
     {"dim": 0, "power_iters": 0, "eps": 1e-12},
     ["Weight"], {"rtol": 3e-2, "atol": 3e-4}),
    ("spp_avg", "spp",
     {"X": smooth(1, 2, 5, 5)},
     {"pyramid_height": 2, "pooling_type": "avg"}, ["X"], {}),
    ("scatter_nd_add", "scatter_nd_add",
     {"X": smooth(3, 3), "Index": np.asarray([[0, 0], [1, 2]], np.int64),
      "Updates": smooth(2)}, {}, ["X", "Updates"], {}),
]


@pytest.mark.parametrize(
    "case", CASES, ids=[c[0] for c in CASES]
)
def test_op_grad(case):
    name, op_type, inputs, attrs, to_check, opts = case

    class T(OpTest):
        def setup(self):
            self.op_type = op_type
            self.inputs = inputs
            self.outputs = {}
            self.attrs = attrs

    t = T()
    t.op_type = op_type
    kwargs = dict(delta=1e-3, rtol=1e-2, atol=1e-4)
    kwargs.update(opts)  # check_grad accepts output_slot as a plain kwarg
    t.check_grad(to_check, **kwargs)
