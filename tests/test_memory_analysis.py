"""Memory & liveness analysis family (analysis/memory.py).

Covers the static peak-HBM planner's live-interval accounting (buffer
reuse, liveness kills, feed pinning, sharded/pipeline/hot-tier byte
math), the donation verifier's broken fixtures (use-after-donate,
missed-donation, recompute-no-savings, oom-risk), the strict-mode
budget-gated compile, the ``Program.estimate`` integration, and the
serving warmup budget check. The estimate-vs-XLA calibration over the
zoo is the slow tail (``-m slow``; CI runs it in its own stage).
"""

import os
import warnings

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import layers
from paddle_tpu.analysis import (
    MISSED_DONATION,
    OOM_RISK,
    RECOMPUTE_NO_SAVINGS,
    USE_AFTER_DONATE,
    Severity,
    hbm_budget,
    plan_memory,
    set_verify_mode,
    verify_program,
)
from paddle_tpu.errors import PreconditionNotMetError, ProgramVerifyError
from paddle_tpu.framework import unique_name


@pytest.fixture(autouse=True)
def fresh():
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 7
    scope = fluid.framework.scope.Scope()
    with fluid.program_guard(main, startup), fluid.scope_guard(scope), \
            unique_name.guard():
        yield main, startup, scope
    set_verify_mode(None)
    os.environ.pop("PADDLE_TPU_HBM_BYTES", None)


def _cats(findings):
    return {f.category for f in findings}


F32 = 4  # bytes


# ---------------------------------------------------------------------------
# budget knob
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("raw,expect", [
    ("1024", 1024.0),
    ("2k", 2 * 2 ** 10),
    ("1.5m", 1.5 * 2 ** 20),
    ("16G", 16 * 2 ** 30),
    ("2T", 2 * 2 ** 40),
    ("junk", None),
    ("", None),
    ("-5", None),
    ("0", None),
])
def test_hbm_budget_parsing(monkeypatch, raw, expect):
    monkeypatch.setenv("PADDLE_TPU_HBM_BYTES", raw)
    assert hbm_budget() == expect


def test_hbm_budget_unset(monkeypatch):
    monkeypatch.delenv("PADDLE_TPU_HBM_BYTES", raising=False)
    assert hbm_budget() is None


# ---------------------------------------------------------------------------
# live-interval goldens
# ---------------------------------------------------------------------------


def test_elementwise_chain_reuses_buffers(fresh):
    """XLA writes an elementwise output over its dying input: a relu
    chain holds ONE activation buffer, not one per op."""
    main, _, _ = fresh
    x = fluid.data("x", [256, 1024])  # 1 MiB
    h = x
    for _ in range(4):
        h = layers.relu(h)
    mt = plan_memory(main, fetch_names=(h.name,))
    assert mt.transient_peak_bytes == 256 * 1024 * F32


def test_matmul_holds_inputs_and_output(fresh):
    """No reuse across a matmul: both operands stay live under the
    output (the MXU reads them while writing)."""
    main, _, _ = fresh
    x = fluid.data("x", [64, 64])
    a = layers.relu(x)          # 16 KiB transient
    b = layers.relu(x)          # 16 KiB transient
    y = layers.matmul(a, b)     # 16 KiB transient
    mt = plan_memory(main, fetch_names=(y.name,))
    assert mt.transient_peak_bytes == 3 * 64 * 64 * F32


def test_liveness_frees_dead_temps(fresh):
    """A temp dies at its last read; a deep matmul chain peaks at two
    live activations, not the whole chain."""
    main, _, _ = fresh
    x = fluid.data("x", [64, 64])
    h = x
    for _ in range(5):
        h = layers.matmul(h, h)
    mt = plan_memory(main, fetch_names=(h.name,))
    assert mt.transient_peak_bytes == 2 * 64 * 64 * F32


def test_resident_counts_each_referenced_persistable_once(fresh):
    main, _, _ = fresh
    x = fluid.data("x", [8, 32])
    h = layers.fc(x, 16)            # w [32,16] + b [16]
    h = layers.fc(h, 16)            # w [16,16] + b [16]
    mt = plan_memory(main, fetch_names=(h.name,))
    expect = (32 * 16 + 16 + 16 * 16 + 16) * F32
    assert mt.resident_bytes == expect
    assert sum(b for _, b in mt.residents) == expect


def test_unreferenced_persistable_costs_nothing(fresh):
    main, _, _ = fresh
    x = fluid.data("x", [8, 8])
    y = layers.relu(x)
    main.global_block.create_var(
        name="orphan_table", shape=[1024, 1024], dtype="float32",
        persistable=True,
    )
    mt = plan_memory(main, fetch_names=(y.name,))
    assert mt.resident_bytes == 0.0


def test_feed_shapes_pin_batch_dim(fresh):
    main, _, _ = fresh
    x = fluid.data("x", [-1, 8])
    y = layers.relu(x)
    pinned = plan_memory(main, fetch_names=(y.name,),
                         feed_shapes={"x": (32, 8)})
    assert pinned.feed_bytes == 32 * 8 * F32
    hinted = plan_memory(main, fetch_names=(y.name,))
    assert hinted.feed_bytes == 1 * 8 * F32  # batch hint 1
    assert any("pinned" in a for a in hinted.assumptions)


def test_watermark_names_the_source_line(fresh):
    main, _, _ = fresh
    x = fluid.data("x", [64, 64])
    y = layers.matmul(layers.relu(x), layers.relu(x))
    mt = plan_memory(main, fetch_names=(y.name,))
    assert mt.watermark is not None
    assert "test_memory_analysis.py" in (mt.watermark["loc"] or "")
    assert mt.watermark["live_bytes"] == mt.peak_bytes
    assert len(mt.timeline) > 0


def test_fetches_stay_live_to_the_end(fresh):
    """A fetched temp cannot be freed at its last in-graph read: the
    host still reads it after the step."""
    main, _, _ = fresh
    x = fluid.data("x", [64, 64])
    a = layers.relu(x)
    b = layers.relu(a)
    c = layers.relu(b)
    fetched = plan_memory(main, fetch_names=(a.name, c.name))
    unfetched = plan_memory(main, fetch_names=(c.name,))
    assert fetched.transient_peak_bytes > unfetched.transient_peak_bytes


# ---------------------------------------------------------------------------
# donation verifier
# ---------------------------------------------------------------------------


def _kv_donation_program(main, read_after=True):
    rows = fluid.data("rows", [1, 4, 8])
    pos = fluid.data("pos", [1], dtype="int32")
    blk = main.global_block
    blk.create_var(name="cache", shape=[16, 4, 8], dtype="float32",
                   persistable=True)
    blk.create_var(name="cache_new", shape=[16, 4, 8], dtype="float32",
                   persistable=True)
    blk.append_op(
        "kv_cache_write",
        {"Cache": ["cache"], "X": [rows.name], "Pos": [pos.name]},
        {"Out": ["cache_new"]},
    )
    blk.create_var(name="reader", shape=[16, 4, 8], dtype="float32")
    src = "cache" if read_after else "cache_new"
    blk.append_op("scale", {"X": [src]}, {"Out": ["reader"]},
                  {"scale": 2.0})
    return ("rows", "pos"), ("reader",)


def test_use_after_donate_detected(fresh):
    main, _, _ = fresh
    feeds, fetches = _kv_donation_program(main, read_after=True)
    mt = plan_memory(main, feed_names=feeds, fetch_names=fetches)
    bad = [f for f in mt.findings if f.category == USE_AFTER_DONATE]
    assert len(bad) == 1
    f = bad[0]
    assert f.severity == Severity.ERROR
    assert "cache" in f.names
    assert "kv_cache_write" in f.message
    # the family is wired into the verifier proper
    report = verify_program(main, feeds, fetches)
    assert USE_AFTER_DONATE in _cats(report.findings)
    assert not report.ok


def test_reading_the_donated_output_is_clean(fresh):
    main, _, _ = fresh
    feeds, fetches = _kv_donation_program(main, read_after=False)
    mt = plan_memory(main, feed_names=feeds, fetch_names=fetches)
    assert USE_AFTER_DONATE not in _cats(mt.findings)


def test_same_name_cache_write_is_clean(fresh):
    """The zoo idiom — Out under the SAME name as Cache — is the
    executor's write-back donation, not a hazard."""
    main, _, _ = fresh
    rows = fluid.data("rows", [1, 4, 8])
    pos = fluid.data("pos", [1], dtype="int32")
    blk = main.global_block
    blk.create_var(name="cache", shape=[16, 4, 8], dtype="float32",
                   persistable=True)
    blk.append_op(
        "kv_cache_write",
        {"Cache": ["cache"], "X": [rows.name], "Pos": [pos.name]},
        {"Out": ["cache"]},
    )
    blk.create_var(name="reader", shape=[16, 4, 8], dtype="float32")
    blk.append_op("scale", {"X": ["cache"]}, {"Out": ["reader"]},
                  {"scale": 2.0})
    mt = plan_memory(main, feed_names=("rows", "pos"),
                     fetch_names=("reader",))
    assert USE_AFTER_DONATE not in _cats(mt.findings)


def test_rewritten_donated_name_is_a_fresh_buffer(fresh):
    """Writing the donated name again rebinds it to a live buffer; a
    read after the rewrite is fine."""
    main, _, _ = fresh
    feeds, _ = _kv_donation_program(main, read_after=False)
    blk = main.global_block
    blk.append_op(
        "fill_constant", {}, {"Out": ["cache"]},
        {"shape": [16, 4, 8], "dtype": "float32", "value": 0.0},
    )
    blk.create_var(name="reader2", shape=[16, 4, 8], dtype="float32")
    blk.append_op("scale", {"X": ["cache"]}, {"Out": ["reader2"]},
                  {"scale": 1.0})
    mt = plan_memory(main, feed_names=feeds, fetch_names=("reader2",))
    assert USE_AFTER_DONATE not in _cats(mt.findings)


def test_missed_donation_detected(fresh):
    main, _, _ = fresh
    blk = main.global_block
    blk.create_var(name="table", shape=[256, 256], dtype="float32",
                   persistable=True)  # 256 KiB: over the noise floor
    blk.create_var(name="table_scaled", shape=[256, 256], dtype="float32")
    blk.append_op("scale", {"X": ["table"]}, {"Out": ["table_scaled"]},
                  {"scale": 0.99})
    mt = plan_memory(main, feed_names=(), fetch_names=("table_scaled",))
    hits = [f for f in mt.findings if f.category == MISSED_DONATION]
    assert len(hits) == 1
    assert hits[0].severity == Severity.INFO
    assert set(hits[0].names) == {"table", "table_scaled"}


def test_small_buffers_skip_missed_donation(fresh):
    main, _, _ = fresh
    blk = main.global_block
    blk.create_var(name="lr", shape=[4, 4], dtype="float32",
                   persistable=True)
    blk.create_var(name="lr2", shape=[4, 4], dtype="float32")
    blk.append_op("scale", {"X": ["lr"]}, {"Out": ["lr2"]}, {"scale": 0.5})
    mt = plan_memory(main, feed_names=(), fetch_names=("lr2",))
    assert MISSED_DONATION not in _cats(mt.findings)


def test_optimizer_write_back_is_not_a_missed_donation(fresh):
    """sgd writes ParamOut under the Param name — the in-place update
    the executor already aliases."""
    main, _, _ = fresh
    x = fluid.data("x", [64, 64])
    loss = layers.mean(layers.fc(x, 64))
    fluid.optimizer.SGD(0.1).minimize(loss)
    mt = plan_memory(main, fetch_names=(loss.name,))
    assert MISSED_DONATION not in _cats(mt.findings)
    assert USE_AFTER_DONATE not in _cats(mt.findings)


# ---------------------------------------------------------------------------
# recompute
# ---------------------------------------------------------------------------


def test_recompute_without_backward_saves_nothing(fresh):
    from paddle_tpu.incubate.recompute import apply_recompute

    main, _, _ = fresh
    x = fluid.data("x", [8, 32])
    h = layers.relu(layers.fc(x, 32))
    out = layers.fc(h, 32)
    apply_recompute(main, [h.name])
    mt = plan_memory(main, fetch_names=(out.name,))
    hits = [f for f in mt.findings
            if f.category == RECOMPUTE_NO_SAVINGS]
    assert hits and hits[0].severity == Severity.INFO
    assert "forward-only" in hits[0].message


def test_recompute_with_backward_is_clean_and_charges_rematerialize(fresh):
    from paddle_tpu.incubate.recompute import apply_recompute

    main, _, _ = fresh
    x = fluid.data("x", [8, 32])
    h = layers.relu(layers.fc(x, 32))
    loss = layers.mean(layers.fc(h, 32))
    apply_recompute(main, [h.name])
    fluid.optimizer.SGD(0.1).minimize(loss)
    mt = plan_memory(main, fetch_names=(loss.name,))
    assert RECOMPUTE_NO_SAVINGS not in _cats(mt.findings)


# ---------------------------------------------------------------------------
# oom-risk + the budget-gated compile
# ---------------------------------------------------------------------------


def _mlp_program(main):
    x = fluid.data("x", [64, 256])
    h = layers.relu(layers.fc(x, 256))
    return ("x",), (layers.fc(h, 256).name,)


def test_oom_risk_fires_over_budget(fresh):
    main, _, _ = fresh
    feeds, fetches = _mlp_program(main)
    mt = plan_memory(main, feed_names=feeds, fetch_names=fetches,
                     budget=1024.0)
    hits = [f for f in mt.findings if f.category == OOM_RISK]
    assert len(hits) == 1
    f = hits[0]
    assert f.severity == Severity.WARNING
    assert "PADDLE_TPU_HBM_BYTES" in f.message
    assert f.loc and "test_memory_analysis.py" in f.loc  # watermark op
    assert mt.budget_bytes == 1024.0


def test_oom_risk_quiet_under_budget(fresh):
    main, _, _ = fresh
    feeds, fetches = _mlp_program(main)
    mt = plan_memory(main, feed_names=feeds, fetch_names=fetches,
                     budget=float(2 ** 30))
    assert OOM_RISK not in _cats(mt.findings)


def test_env_budget_reaches_the_verifier(fresh, monkeypatch):
    main, _, _ = fresh
    feeds, fetches = _mlp_program(main)
    monkeypatch.setenv("PADDLE_TPU_HBM_BYTES", "1k")
    report = verify_program(main, feeds, fetches)
    assert OOM_RISK in _cats(report.findings)
    # WARNING normally; an error only under strict escalation
    assert report.ok
    assert any(f.category == OOM_RISK for f in report.strict_errors())


def test_strict_mode_refuses_over_budget_compile(fresh, monkeypatch):
    """The acceptance gate: strict + tiny budget refuses the compile
    with a typed finding naming the watermark op's source line."""
    main, _, _ = fresh
    feeds, fetches = _mlp_program(main)
    monkeypatch.setenv("PADDLE_TPU_HBM_BYTES", "1k")
    set_verify_mode("strict")
    exe = fluid.Executor()
    with pytest.raises(ProgramVerifyError) as ei:
        exe.run(main, feed={"x": np.ones((64, 256), "float32")},
                fetch_list=[fetches[0]])
    msg = str(ei.value)
    assert "oom-risk" in msg
    assert "test_memory_analysis.py" in msg


def test_warn_mode_warns_and_still_runs(fresh, monkeypatch):
    main, startup, _ = fresh
    feeds, fetches = _mlp_program(main)
    monkeypatch.setenv("PADDLE_TPU_HBM_BYTES", "1k")
    set_verify_mode("warn")
    exe = fluid.Executor()
    exe.run(startup)
    with warnings.catch_warnings(record=True) as got:
        warnings.simplefilter("always")
        out, = exe.run(main, feed={"x": np.ones((64, 256), "float32")},
                       fetch_list=[fetches[0]])
    assert out.shape == (64, 256)
    assert any("oom-risk" in str(w.message) for w in got)


# ---------------------------------------------------------------------------
# sharding / pipeline / hot-tier byte math
# ---------------------------------------------------------------------------


def test_sharded_persistables_divide_by_axis_size(fresh):
    from paddle_tpu.parallel import make_mesh

    main, _, _ = fresh
    x = fluid.data("x", [8, 64])
    y = layers.fc(x, 64)  # w [64,64], b [64]
    base = plan_memory(main, fetch_names=(y.name,)).resident_bytes
    w = main.global_block.all_parameters()[0]
    main._mesh = make_mesh({"dp": 8})  # conftest's 8 virtual devices
    main._sharding = {w.name: (("dp",), None)}
    mt = plan_memory(main, fetch_names=(y.name,))
    # w drops to an eighth; the bias is unsharded
    assert mt.resident_bytes == base - (64 * 64 * F32) * 7 / 8


def test_pipeline_stage_peaks_reported(fresh):
    from paddle_tpu.parallel.pipeline import slice_program_into_stages

    main, _, _ = fresh
    x = fluid.data("x", [8, 64])
    with fluid.device_guard("pipeline:0"):
        h = layers.fc(x, 64)
    with fluid.device_guard("pipeline:1"):
        loss = layers.mean(layers.fc(h, 64))
    main._pipeline = {"num_microbatches": 2, "axis_name": "pp"}
    slice_program_into_stages(main, loss)
    mt = plan_memory(main, feed_names=("x",), fetch_names=(loss.name,))
    assert set(mt.stage_peaks) == {0, 1}
    assert all(v > 0 for v in mt.stage_peaks.values())


def test_hot_tier_shrink_drops_resident(fresh):
    """EmbeddingEngine rewrites cached tables' declared shapes to the
    hot-row count; the planner sees the shrunk table with no special
    case."""
    from paddle_tpu.embedding import EmbeddingEngine

    main, startup, _ = fresh
    ids = fluid.data("ids", [8, 1], "int64")
    emb = layers.sparse_embedding(ids, size=[4096, 16])
    loss = layers.mean(emb)
    table = main.global_block.all_parameters()[0]
    before = plan_memory(main, fetch_names=(loss.name,))
    assert dict(before.residents)[table.name] == 4096 * 16 * F32
    EmbeddingEngine(main, startup, hot_rows={table.name: 64})
    fluid.optimizer.SGD(0.1).minimize(loss)
    after = plan_memory(main, fetch_names=(loss.name,))
    assert dict(after.residents)[table.name] == 64 * 16 * F32


# ---------------------------------------------------------------------------
# estimate() integration + serving warmup budget
# ---------------------------------------------------------------------------


def test_estimate_carries_the_memory_plan(fresh):
    main, _, _ = fresh
    feeds, fetches = _mlp_program(main)
    est = main.estimate(feed_shapes={"x": (64, 256)})
    mt = plan_memory(main, feed_names=feeds, fetch_names=(),
                     feed_shapes={"x": (64, 256)}, budget=None)
    assert est.peak_bytes == mt.peak_bytes
    assert est.resident_bytes == mt.resident_bytes
    assert "static memory:" in est.format()
    d = est.to_dict()
    assert d["peak_bytes"] == mt.peak_bytes
    assert d["memory"]["watermark"] is not None


def test_executor_publishes_peak_gauges(fresh):
    from paddle_tpu import observability as obs

    main, _, _ = fresh
    x = fluid.data("x", [4, 8])
    y = layers.relu(x)
    exe = fluid.Executor()
    exe.run(main, feed={"x": np.ones((4, 8), "float32")},
            fetch_list=[y])
    snap = obs.snapshot()
    assert snap["gauges"].get("perf.peak_bytes_est", 0) > 0
    assert "perf.resident_bytes_est" in snap["gauges"]


def _frozen_classifier(main, startup, scope):
    from paddle_tpu.serving import freeze_program

    x = fluid.data("x", [-1, 16])
    prob = layers.softmax(layers.fc(layers.fc(x, 32, act="relu"), 4))
    exe = fluid.Executor()
    exe.run(startup, scope=scope)
    return exe, freeze_program(main, [prob], feed_names=("x",))


def test_serving_warmup_respects_hbm_budget(fresh, monkeypatch):
    from paddle_tpu.serving import Server
    from paddle_tpu.serving.router import EndpointConfig

    main, startup, scope = fresh
    exe, frozen = _frozen_classifier(main, startup, scope)
    server = Server()
    ep = server.add_endpoint(
        "clf", None, EndpointConfig(buckets=(1, 4), max_wait_ms=1),
        frozen=frozen, executor=exe, scope=scope,
    )
    try:
        plan = ep.plan_memory()
        assert plan["planned_peak_bytes"] > plan["resident_bytes"] > 0
        assert plan["per_bucket_dynamic_bytes"][4] > \
            plan["per_bucket_dynamic_bytes"][1]
        monkeypatch.setenv("PADDLE_TPU_HBM_BYTES", "1k")
        with pytest.raises(PreconditionNotMetError, match="HBM budget"):
            server.warmup()
        monkeypatch.setenv("PADDLE_TPU_HBM_BYTES", "1g")
        assert server.warmup() >= 1  # fits: warmup actually compiles
    finally:
        for e in server.endpoints().values():
            e.drain(timeout=10)


# ---------------------------------------------------------------------------
# the zoo: clean bill + estimate-vs-XLA calibration (slow tail)
# ---------------------------------------------------------------------------


def test_small_zoo_models_are_memory_clean(fresh):
    from paddle_tpu.models import build_model

    for name in ("deepfm", "gpt"):
        bm = build_model(name)
        mt = plan_memory(bm.main, feed_names=bm.feed_names or None,
                         fetch_names=bm.fetch_names)
        assert not mt.findings, (name, [f.format() for f in mt.findings])
        assert mt.peak_bytes > mt.resident_bytes > 0


@pytest.mark.slow
def test_zoo_estimate_vs_xla_memory(fresh):
    """Static peak within 25% of XLA memory_analysis (arg+out+temp-alias)
    on all but <=2 of the XLA-checkable zoo models."""
    from paddle_tpu.framework.scope import Scope
    from paddle_tpu.models import MODEL_BUILDERS, build_model

    divergent, checked = [], 0
    for name in MODEL_BUILDERS:
        bm = build_model(name)
        mt = plan_memory(bm.main, feed_names=bm.feed_names or None,
                         fetch_names=bm.fetch_names)
        assert not mt.findings, (  # clean bill across the whole zoo
            name, [f.format() for f in mt.findings])
        if getattr(bm.main, "_mesh", None) is not None:
            continue  # shard_map wants the whole virtual pod
        est = bm.main.estimate()
        exe = fluid.Executor()
        scope = Scope()
        exe.run(bm.startup, scope=scope)
        feed = {}
        blk = bm.main.global_block
        for fn in bm.feed_names:
            v = blk._find_var_recursive(fn)
            shape = [d if d not in (-1, None) else 4 for d in v.shape]
            feed[fn] = np.zeros(shape, np.dtype(v.dtype or "float32"))
        ma = exe.memory_analysis(bm.main, feed=feed,
                                 fetch_list=list(bm.fetch_names),
                                 scope=scope)
        if ma is None:
            continue  # backend without memory_analysis: counted, not failed
        checked += 1
        div = abs(est.peak_bytes - ma["peak_bytes"]) / ma["peak_bytes"]
        if div > 0.25:
            divergent.append((name, round(div, 3)))
    assert checked >= 5, f"only {checked} models were XLA-checkable"
    assert len(divergent) <= 2, divergent
