"""2.0-preview namespace import parity (VERDICT r2 item 9): enumerate the
REFERENCE's __all__ lists for python/paddle/tensor/ and
python/paddle/nn/functional/ and assert our namespaces expose them.
LoD-plumbing names whose capability lives in the padded+lengths design are
the explicit skip list (each with its replacement)."""

import glob
import os
import re

import numpy as np
import pytest

REF = "/root/reference/python/paddle"

# LoD-era names with no padded-dense analog: capability -> replacement
LOD_SKIPS = {
    "im2sequence": "padded [B,T,...] frames (layers/sequence_lod.py)",
    "lod_append": "padded+lengths design",
    "lod_reset": "padded+lengths design",
    "reorder_lod_tensor_by_rank": "padded+lengths design",
    "sequence_enumerate": "padded windows via unfold",
    "sequence_reshape": "reshape on the dense frame",
    "sequence_scatter": "scatter on the dense frame",
    "sequence_slice": "slice on the dense frame",
    # non-function constants the reference re-exported into functional
    "EXPLICIT": "string attr", "NCHW": "string attr", "SAME": "string attr",
    "VALID": "string attr", "float32": "dtype string",
    "padding": "attr name", "bilinear": "resample mode string",
    "nearest": "resample mode string", "trilinear": "resample mode string",
    "bicubic": "resample mode string",
    # LoD helpers in tensor/
    "create_lod_tensor": "dense arrays",
    "create_random_int_lodtensor": "dense arrays",
    # typo'd reference export (random.py __all__ lists 'gaussin')
    "gaussin": "reference typo for gaussian (tensor.random)",
    "elementwise_equal": "equal",
}


def _all_names(paths):
    names = set()
    for p in paths:
        txt = open(p).read()
        if "__all__" not in txt:
            continue
        seg = txt.split("__all__", 1)[1]
        # stop at the first statement after the (possibly concatenated)
        # __all__ lists so code identifiers don't leak in
        m = re.search(r"\n(def |class |from |import |[A-Za-z_]+ =)", seg)
        if m:
            seg = seg[:m.start()]
        names.update(re.findall(r"['\"]([A-Za-z0-9_]+)['\"]", seg))
    return names


def test_nn_functional_import_parity():
    import paddle_tpu.nn.functional as F

    ref = _all_names(glob.glob(os.path.join(REF, "nn", "functional", "*.py")))
    missing = sorted(
        n for n in ref if n not in LOD_SKIPS and not hasattr(F, n)
    )
    assert not missing, missing


def test_tensor_import_parity():
    import paddle_tpu.tensor as T

    files = [os.path.join(REF, "tensor", f) for f in
             ("creation.py", "linalg.py", "logic.py", "manipulation.py",
              "math.py", "random.py", "search.py", "stat.py",
              "attribute.py")]
    ref = _all_names([f for f in files if os.path.exists(f)])
    missing = sorted(
        n for n in ref if n not in LOD_SKIPS and not hasattr(T, n)
    )
    assert not missing, missing


def test_namespace_functions_execute():
    """A sample of namespace functions actually build + run (not just
    import): tensor math aliases and a functional activation."""
    import paddle_tpu as fluid
    import paddle_tpu.tensor as T
    import paddle_tpu.nn.functional as F
    from paddle_tpu.framework import unique_name

    main, startup = fluid.Program(), fluid.Program()
    scope = fluid.framework.scope.Scope()
    with fluid.program_guard(main, startup), fluid.scope_guard(scope), \
            unique_name.guard():
        x = fluid.data("x", [2, 3])
        y = T.add(x, T.multiply(x, x))
        z = T.std(y)
        k = T.kron(x, x)
        a = F.relu(y)
        m = F.mse_loss(a, y)
        tri = T.tril(x)
        exe = fluid.Executor()
        exe.run(startup)
        xv = np.array([[1., 2., 3.], [4., 5., 6.]], np.float32)
        outs = exe.run(feed={"x": xv}, fetch_list=[y, z, k, m, tri])
        np.testing.assert_allclose(np.asarray(outs[0]), xv + xv * xv,
                                   rtol=1e-6)
        assert np.asarray(outs[2]).shape == (4, 9)
        assert np.isfinite(float(np.asarray(outs[1]).reshape(-1)[0]))
