"""Detection-suite op tests (VERDICT r2 item 3): the 15 Mask R-CNN /
RetinaNet / SSD assignment ops added in round 3, exercised through their
emitters with numeric checks against the reference kernels'
semantics (per-op files under paddle/fluid/operators/detection/, cited in
ops/detection_ext.py)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu  # noqa: F401
from paddle_tpu.framework.registry import EmitContext, get_op_def


class _FakeOp:
    def __init__(self, type, attrs):
        self.type, self.attrs, self.uid = type, attrs, 7

    def attr(self, k, d=None):
        return self.attrs.get(k, d)


def test_detection_ext_suite():
    ctx = EmitContext()
    ctx.key_for = lambda uid, t: jax.random.key(uid)

    def run(t, attrs, ins):
        return get_op_def(t).emit(ctx, _FakeOp(t, attrs), ins)

    rng = np.random.RandomState(0)


    # --- rpn_target_assign ---
    anchors = []
    for y in range(4):
        for x in range(4):
            anchors.append([x*16, y*16, x*16+31, y*16+31])
    anchors = jnp.asarray(np.array(anchors, np.float32))
    gt = jnp.asarray(np.array([[0, 0, 31, 31], [32, 32, 63, 63]], np.float32))
    o = run("rpn_target_assign", {"rpn_batch_size_per_im": 8, "rpn_positive_overlap": 0.7,
            "rpn_negative_overlap": 0.3, "rpn_fg_fraction": 0.5},
            {"Anchor": [anchors], "GtBoxes": [gt], "IsCrowd": [jnp.zeros(2, jnp.int32)], "ImInfo": [jnp.asarray([[64., 64., 1.]])]})
    loc = np.asarray(o["LocationIndex"][0])
    assert loc.shape == (4,)
    assert (loc >= 0).sum() >= 2, loc  # the two exact-match anchors are fg
    lbl = np.asarray(o["TargetLabel"][0]).ravel()
    assert set(lbl.tolist()) <= {-1, 0, 1}
    tb = np.asarray(o["TargetBBox"][0])
    # exact matches -> zero deltas for fg rows
    fg_rows = tb[(loc >= 0)]
    assert np.allclose(fg_rows, 0.0, atol=1e-5), fg_rows

    # --- retinanet_target_assign ---
    o = run("retinanet_target_assign", {"positive_overlap": 0.5, "negative_overlap": 0.4},
            {"Anchor": [anchors], "GtBoxes": [gt], "GtLabels": [jnp.asarray([[3],[5]], jnp.int32)],
             "IsCrowd": [jnp.zeros(2, jnp.int32)], "ImInfo": [jnp.asarray([[64., 64., 1.]])]})
    assert int(np.asarray(o["ForegroundNumber"][0])) >= 2

    # --- generate_proposal_labels ---
    rois = jnp.asarray(np.array([[0,0,30,30],[31,31,62,62],[5,5,20,20],[40,0,60,20]], np.float32))
    o = run("generate_proposal_labels", {"batch_size_per_im": 6, "fg_fraction": 0.5,
            "fg_thresh": 0.5, "bg_thresh_hi": 0.5, "bg_thresh_lo": 0.0, "class_nums": 4},
            {"RpnRois": [rois], "GtClasses": [jnp.asarray([1, 2], jnp.int32)],
             "IsCrowd": [jnp.zeros(2, jnp.int32)], "GtBoxes": [gt],
             "ImInfo": [jnp.asarray([[64., 64., 1.]])], "RpnRoisNum": [None]})
    assert o["Rois"][0].shape == (6, 4)
    lbls = np.asarray(o["LabelsInt32"][0]).ravel()
    assert (lbls > 0).sum() >= 2, lbls  # the two gt-appended rois are fg
    assert o["BboxTargets"][0].shape == (6, 16)

    # --- generate_mask_labels ---
    segms = np.zeros((2, 64, 64), np.float32)
    segms[0, 0:32, 0:32] = 1
    segms[1, 32:64, 32:64] = 1
    o = run("generate_mask_labels", {"resolution": 4, "num_classes": 4},
            {"ImInfo": [jnp.asarray([[64., 64., 1.]])], "GtClasses": [jnp.asarray([1, 2], jnp.int32)],
             "IsCrowd": [jnp.zeros(2, jnp.int32)], "GtSegms": [jnp.asarray(segms)],
             "Rois": [o["Rois"][0]], "LabelsInt32": [o["LabelsInt32"][0]]})
    assert o["MaskInt32"][0].shape == (6, 4*16)

    # --- distribute + collect fpn proposals ---
    frois = jnp.asarray(np.array([[0,0,15,15],[0,0,63,63],[0,0,223,223],[0,0,500,500]], np.float32))
    o = run("distribute_fpn_proposals", {"min_level": 2, "max_level": 5, "refer_level": 4, "refer_scale": 224},
            {"FpnRois": [frois], "RoisNum": [None]})
    assert len(o["MultiFpnRois"]) == 4
    nums = [int(np.asarray(n)) for n in o["MultiLevelRoIsNum"]]
    assert sum(nums) == 4, nums
    restore = np.asarray(o["RestoreIndex"][0]).ravel()
    # restore[i] = roi i's row in the padded level-major concat: gathering
    # the concat at restore must reproduce the input rois (the contract
    # _fpn_roi_extract depends on)
    concat = np.concatenate(
        [np.asarray(r) for r in o["MultiFpnRois"]], axis=0
    )
    assert np.allclose(concat[restore], np.asarray(frois)), (restore, concat)

    scores = [jnp.asarray(rng.rand(4).astype(np.float32)) for _ in range(4)]
    o2 = run("collect_fpn_proposals", {"post_nms_topN": 3},
             {"MultiLevelRois": o["MultiFpnRois"], "MultiLevelScores": scores,
              "MultiLevelRoIsNum": o["MultiLevelRoIsNum"]})
    assert o2["FpnRois"][0].shape == (3, 4)
    assert int(np.asarray(o2["RoisNum"][0])) == 3

    # --- bipartite_match ---
    dist = jnp.asarray(np.array([[0.9, 0.1], [0.2, 0.8], [0.3, 0.3]], np.float32))
    o = run("bipartite_match", {"match_type": "bipartite"}, {"DistMat": [dist]})
    mi = np.asarray(o["ColToRowMatchIndices"][0])[0]
    assert list(mi) == [0, 1], mi

    o = run("bipartite_match", {"match_type": "per_prediction", "dist_threshold": 0.25},
            {"DistMat": [dist.T]})  # 2 rows, 3 cols
    mi = np.asarray(o["ColToRowMatchIndices"][0])[0]
    assert mi[0] == 0 and mi[1] == 1 and mi[2] >= 0, mi  # col2 matched via threshold

    # --- target_assign ---
    xta = jnp.asarray(rng.randn(1, 3, 4).astype(np.float32))
    match = jnp.asarray(np.array([[0, -1, 2]], np.int32))
    o = run("target_assign", {"mismatch_value": 0}, {"X": [xta], "MatchIndices": [match], "NegIndices": [None]})
    out = np.asarray(o["Out"][0])
    assert np.allclose(out[0, 0], np.asarray(xta)[0, 0])
    assert np.allclose(out[0, 1], 0.0)
    w = np.asarray(o["OutWeight"][0]).ravel()
    assert list(w) == [1.0, 0.0, 1.0]

    # --- mine_hard_examples ---
    cls_loss = jnp.asarray(np.array([[0.1, 0.9, 0.5, 0.7]], np.float32))
    match = jnp.asarray(np.array([[0, -1, -1, -1]], np.int32))
    o = run("mine_hard_examples", {"neg_pos_ratio": 2.0, "mining_type": "max_negative"},
            {"ClsLoss": [cls_loss], "LocLoss": [None], "MatchIndices": [match], "MatchDist": [None]})
    sel = np.asarray(o["NegIndices"][0])[0]
    assert sel.sum() == 2 and sel[1] == 1 and sel[3] == 1, sel  # two hardest negs

    # --- box_decoder_and_assign ---
    prior = jnp.asarray(np.array([[0, 0, 31, 31]], np.float32))
    deltas = jnp.zeros((1, 8))
    score = jnp.asarray(np.array([[0.1, 0.9]], np.float32))
    o = run("box_decoder_and_assign", {}, {"PriorBox": [prior], "PriorBoxVar": [jnp.ones(4)],
            "TargetBox": [deltas], "BoxScore": [score]})
    assert np.allclose(np.asarray(o["OutputAssignBox"][0]), np.asarray(prior), atol=1e-4)

    # --- retinanet_detection_output ---
    o = run("retinanet_detection_output", {"score_threshold": 0.05, "nms_top_k": 10, "keep_top_k": 5, "nms_threshold": 0.3},
            {"BBoxes": [jnp.zeros((8, 4))], "Scores": [jnp.asarray(rng.rand(8, 3).astype(np.float32))],
             "Anchors": [anchors[:8]], "ImInfo": [jnp.asarray([[64., 64., 1.]])]})
    assert o["Out"][0].shape == (5, 6)

    # --- locality_aware_nms ---
    bxs = jnp.asarray(np.array([[0,0,10,10],[1,1,11,11],[40,40,50,50]], np.float32))
    scs = jnp.asarray(np.array([[[0.9, 0.8, 0.7]]], np.float32))
    o = run("locality_aware_nms", {"nms_threshold": 0.3, "score_threshold": 0.1, "keep_top_k": 4},
            {"BBoxes": [bxs], "Scores": [scs]})
    out = np.asarray(o["Out"][0])
    live = out[out[:, 0] >= 0]
    assert len(live) == 2, out  # two clusters

    # --- multiclass_nms2 ---
    bx = jnp.asarray(np.array([[[0,0,10,10],[40,40,50,50]]], np.float32))
    sc = jnp.asarray(np.array([[[0.9, 0.8]]], np.float32))
    o = run("multiclass_nms2", {"score_threshold": 0.1, "nms_top_k": 4, "keep_top_k": 4, "nms_threshold": 0.3, "background_label": -1},
            {"BBoxes": [bx], "Scores": [sc], "RoisNum": [None]})
    out2 = np.asarray(o["Out"][0])[0]
    idx2 = np.asarray(o["Index"][0]).ravel()
    # Index maps kept rows back to INPUT boxes: out row == bx[Index[row]]
    for r in range(out2.shape[0]):
        if out2[r, 0] >= 0:
            assert np.allclose(out2[r, 2:6], np.asarray(bx)[0, idx2[r]]), r

    # --- polygon_box_transform ---
    xin = jnp.zeros((1, 4, 2, 3))
    o = run("polygon_box_transform", {}, {"Input": [xin]})
    out = np.asarray(o["Output"][0])
    assert out[0, 0, 0, 2] == 8.0 and out[0, 1, 1, 0] == 4.0

    # --- roi_perspective_transform ---
    img = jnp.asarray(rng.rand(1, 2, 16, 16).astype(np.float32))
    quad = jnp.asarray(np.array([[2, 2, 10, 2, 10, 10, 2, 10]], np.float32))
    o = run("roi_perspective_transform", {"transformed_height": 4, "transformed_width": 4, "spatial_scale": 1.0},
            {"X": [img], "ROIs": [quad]})
    assert o["Out"][0].shape == (1, 2, 4, 4)
    # axis-aligned square -> matches bilinear crop corners approximately
    assert np.all(np.asarray(o["Mask"][0]) == 1)


