"""Native C++ training demo: build the embedded-CPython trainer, save a
training bundle, run the binary in a subprocess, and assert the loss it
prints decreases (reference train/demo/demo_trainer.cc end-to-end)."""

import os
import shutil
import subprocess

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import layers
from paddle_tpu.framework import unique_name
from paddle_tpu.train_demo import build_demo, save_train_bundle

pytestmark = pytest.mark.skipif(
    shutil.which("g++") is None, reason="no C++ toolchain"
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_cpp_train_demo(tmp_path):
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 5
    with fluid.program_guard(main, startup), unique_name.guard():
        x = fluid.data("x", [16, 4])
        y = fluid.data("y", [16, 1])
        pred = layers.fc(x, 1)
        loss = layers.mean(layers.square_error_cost(pred, y))
        fluid.optimizer.SGD(0.05).minimize(loss, startup)
    rng = np.random.RandomState(0)
    xv = rng.randn(16, 4).astype(np.float32)
    yv = (xv @ np.arange(4, dtype=np.float32).reshape(4, 1))
    bundle = str(tmp_path / "bundle.pkl")
    save_train_bundle(bundle, main, startup, {"x": xv, "y": yv}, loss.name)

    binary = build_demo()
    env = dict(os.environ)
    keep = [p for p in env.get("PYTHONPATH", "").split(os.pathsep)
            if p and "axon" not in p]
    env["PYTHONPATH"] = os.pathsep.join([REPO, *keep])
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run([binary, bundle, "8"], capture_output=True,
                          text=True, env=env, timeout=300)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    lines = [l for l in proc.stdout.splitlines() if l.startswith("step ")]
    assert len(lines) == 8
    losses = [float(l.split()[-1]) for l in lines]
    assert losses[-1] < losses[0]
    assert "done" in proc.stdout
