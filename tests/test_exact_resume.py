"""Exact-resume checkpoints: TrainStatus v2 full-state capture/restore,
the resumable data-pipeline cursor, per-rank shards + commit-record
coherence, and the rotate-after-verify publish discipline.

The end-to-end kill/resume equivalence proof lives in
tools/resume_audit.py (run by the ci.sh chaos stage and by the slow test
at the bottom); these tests pin each layer in isolation.
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import errors, layers, observability
from paddle_tpu.dataloader import (
    BatchSampler,
    DistributedBatchSampler,
    RandomSampler,
)
from paddle_tpu.dataloader.dataset import Dataset
from paddle_tpu.fleet import collective as fc
from paddle_tpu.fleet.role_maker import UserDefinedRoleMaker
from paddle_tpu.framework import unique_name
from paddle_tpu.resilience import TrainGuard, faults

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.clear()
    yield
    faults.clear()


@pytest.fixture
def fresh_programs():
    main, startup = fluid.Program(), fluid.Program()
    scope = fluid.framework.scope.Scope()
    with fluid.program_guard(main, startup), fluid.scope_guard(scope), \
            unique_name.guard():
        yield main


def _build_model():
    x = fluid.data("x", [-1, 4])
    y = layers.fc(x, 2, param_attr=fluid.ParamAttr(name="er_w"))
    loss = layers.mean(y)
    fluid.optimizer.SGD(0.1).minimize(loss)
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    return exe, loss


def _fleet(rank=0, nranks=1):
    f = fc.Fleet()
    f.init(UserDefinedRoleMaker(current_id=rank, worker_num=nranks))
    return f


class _Idx(Dataset):
    def __init__(self, n=24):
        self.n = n

    def __len__(self):
        return self.n

    def __getitem__(self, i):
        return np.asarray([i], dtype=np.float32)


# -- TrainStatus v2 ----------------------------------------------------------
def test_train_status_v2_dict_round_trip():
    st = fc.TrainStatus(
        2, global_step=17, rng={"random_seed": 7, "rng_step": 17,
                                "rng_nonce": 5},
        amp={"loss_scaling": 1024.0, "good_steps": 3, "bad_steps": 0},
        guard={"steps": 17, "bad_steps": 1, "bad_streak": 0, "rollbacks": 1},
        cursor={"epoch": 2, "batches_consumed": 5},
    )
    d = st.to_dict()
    assert d["version"] == fc.TRAIN_STATUS_VERSION == 2
    back = fc.TrainStatus.from_dict(json.loads(json.dumps(d)))
    assert back == st and back.global_step == 17
    assert back.rng == st.rng and back.amp == st.amp
    assert back.guard == st.guard and back.cursor == st.cursor


def test_train_status_v1_payload_loads_with_defaults():
    st = fc.TrainStatus.from_dict({"epoch_no": 3})
    assert st.next() == 4
    assert st.global_step == 0 and not st.rng and not st.cursor


def test_train_status_future_version_refused():
    with pytest.raises(errors.CheckpointCorruptionError, match="version"):
        fc.TrainStatus.from_dict({"version": 99, "epoch_no": 0})


def test_program_rng_state_round_trip(fresh_programs):
    main = fresh_programs
    main.random_seed = 11
    main._rng_step = 42
    state = main.rng_state()
    other = fluid.Program()
    other.set_rng_state(state)
    assert other.random_seed == 11 and other._rng_step == 42
    assert other._rng_nonce == main._rng_nonce


def test_guard_state_round_trip():
    exe = fluid.Executor()
    g = TrainGuard(exe)
    g.steps, g.bad_steps, g.bad_streak, g.rollbacks = 9, 2, 1, 1
    g2 = TrainGuard(exe)
    g2.load_state_dict(g.state_dict())
    assert (g2.steps, g2.bad_steps, g2.bad_streak, g2.rollbacks) == (9, 2, 1, 1)


def test_amp_state_round_trip(fresh_programs):
    from paddle_tpu.contrib.mixed_precision import decorate

    x = fluid.data("x", [-1, 4])
    y = layers.fc(x, 2)
    loss = layers.mean(y)
    opt = decorate(fluid.optimizer.SGD(0.1), init_loss_scaling=2.0 ** 10,
                   dest_dtype="float16")
    opt.minimize(loss)
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    st = opt.state_dict()
    assert st == {"loss_scaling": 2.0 ** 10, "good_steps": 0, "bad_steps": 0}
    opt.load_state_dict(
        {"loss_scaling": 256.0, "good_steps": 5, "bad_steps": 1}
    )
    assert opt.state_dict() == {
        "loss_scaling": 256.0, "good_steps": 5, "bad_steps": 1,
    }
    # empty state (v1 checkpoint) is a no-op, not a reset-to-garbage
    opt.load_state_dict({})
    assert opt.state_dict()["loss_scaling"] == 256.0


# -- deterministic RandomSampler ---------------------------------------------
def test_random_sampler_unseeded_is_instance_seeded_not_global():
    ds = _Idx(16)
    np.random.seed(0)
    a1 = list(RandomSampler(ds))
    np.random.seed(0)  # identical global numpy state...
    s = RandomSampler(ds)
    b1 = list(s)
    # ...yet instances draw their own OS-entropy seed: no global coupling
    # (ranks forking with different global state shuffle from their OWN
    # seed, and two samplers in one process are decorrelated)
    assert sorted(a1) == sorted(b1) == list(range(16))
    # standalone unseeded keeps the classic semantics: every epoch
    # reshuffles — but deterministically given the instance seed, so a
    # restored cursor can replay any one of them
    b2 = list(s)
    assert b2 != b1 and sorted(b2) == sorted(b1)
    s.set_epoch(0)
    assert list(s) == b1  # pinning the epoch replays its permutation


def test_random_sampler_legacy_randomstate_cursor_refused():
    s = RandomSampler(_Idx(16), generator=np.random.RandomState(3))
    state = s.state_dict()
    assert state["seed"] is None  # the stream position is not capturable
    import paddle_tpu.errors as errs

    with pytest.raises(errs.ResumeMismatchError, match="caller-managed"):
        RandomSampler(_Idx(16)).load_state_dict(state)


def test_random_sampler_epoch_reshuffles_deterministically():
    s = RandomSampler(_Idx(32), generator=5)
    e0 = list(s)
    s.set_epoch(1)
    e1 = list(s)
    s.set_epoch(0)
    assert list(s) == e0 and e0 != e1
    # a fresh process restoring the cursor replays the same permutations
    s2 = RandomSampler(_Idx(32))
    s2.load_state_dict({"seed": 5, "epoch": 1})
    assert list(s2) == e1


# -- cursor: BatchSampler / DistributedBatchSampler / DataLoader -------------
def test_batch_sampler_cursor_skips_consumed_prefix():
    bs = BatchSampler(dataset=_Idx(20), shuffle=True, batch_size=3)
    full = list(bs)
    bs2 = BatchSampler(dataset=_Idx(20), shuffle=True, batch_size=3)
    bs2.load_state_dict(
        {"epoch": 0, "batches_consumed": 4,
         "sampler": bs.sampler.state_dict()}
    )
    assert list(bs2) == full[4:]


def test_batch_sampler_auto_epoch_bump_reshuffles():
    bs = BatchSampler(dataset=_Idx(20), shuffle=True, batch_size=5)
    e0, e1 = list(bs), list(bs)
    assert e0 != e1  # per-epoch reshuffle survives the deterministic seeding
    assert sorted(sum(e0, [])) == sorted(sum(e1, [])) == list(range(20))


def test_distributed_batch_sampler_cursor_fast_skip():
    ds = _Idx(48)
    s = DistributedBatchSampler(ds, 4, nranks=2, rank=1, shuffle=True,
                                seed=13)
    s.set_epoch(2)
    full = list(s)
    s2 = DistributedBatchSampler(ds, 4, nranks=2, rank=1, shuffle=True,
                                 seed=13)
    s2.load_state_dict({"epoch": 2, "batches_consumed": 3})
    assert list(s2) == full[3:]
    # the armed skip is one-shot: the next epoch is complete again
    assert list(s2) == full


def test_distributed_sampler_cursor_restores_seed_refuses_resize():
    ds = _Idx(48)
    src = DistributedBatchSampler(ds, 4, nranks=2, rank=0, shuffle=True,
                                  seed=13)
    state = src.state_dict()
    # a restart that constructed the sampler with a different seed still
    # replays the dead run's permutation: the cursor carries the seed
    other = DistributedBatchSampler(ds, 4, nranks=2, rank=0, shuffle=True,
                                    seed=99)
    other.load_state_dict(state)
    assert other.seed == 13
    # an elastically resized world cannot fast-skip (different sharding):
    # typed refusal, not a silently wrong prefix
    resized = DistributedBatchSampler(ds, 4, nranks=4, rank=0, shuffle=True,
                                      seed=13)
    with pytest.raises(errors.ResumeMismatchError, match="nranks"):
        resized.load_state_dict(state)


def test_dataloader_cursor_resume_matches_uninterrupted(fresh_programs):
    def make():
        return fluid.DataLoader(_Idx(18), batch_size=4,
                                use_buffer_reader=False, shuffle=True)

    loader = make()
    seen, state = [], None
    it = iter(loader)
    for k in range(2):
        seen.append(np.asarray(next(it)).copy())
    state = loader.state_dict()
    assert state["batches_consumed"] == 2
    rest_expected = [np.asarray(b) for b in it]

    resumed = make()
    resumed.load_state_dict(state)
    rest = [np.asarray(b) for b in resumed]
    assert len(rest) == len(rest_expected)
    for a, b in zip(rest, rest_expected):
        np.testing.assert_array_equal(a, b)


def test_dataloader_cursor_multiworker(fresh_programs):
    loader = fluid.DataLoader(_Idx(30), batch_size=3, num_workers=2,
                              use_buffer_reader=False)
    it = iter(loader)
    first = np.asarray(next(it))
    np.testing.assert_array_equal(first.ravel(), [0, 1, 2])
    loader2 = fluid.DataLoader(_Idx(30), batch_size=3, num_workers=2,
                               use_buffer_reader=False)
    loader2.load_state_dict(loader.state_dict())
    nxt = np.asarray(next(iter(loader2)))
    np.testing.assert_array_equal(nxt.ravel(), [3, 4, 5])


def test_dataloader_iterable_dataset_has_no_cursor():
    from paddle_tpu.dataloader.dataset import IterableDataset

    class Stream(IterableDataset):
        def __iter__(self):
            return iter([np.zeros(1, np.float32)])

    loader = fluid.DataLoader(Stream(), batch_size=1)
    with pytest.raises(TypeError, match="cursor"):
        loader.state_dict()


# -- checkpoint layout: commit record + rank shard ---------------------------
def test_save_writes_commit_and_rank_shard(tmp_path, fresh_programs):
    exe, _ = _build_model()
    fleet = _fleet()
    path = str(tmp_path / "ckpts")
    st = fc.TrainStatus(1, global_step=12, rng={"rng_step": 12})
    assert fleet.save_check_point(exe, path, st) == 0
    ckpt = os.path.join(path, "__paddle_checkpoint__0")
    commit = json.load(open(os.path.join(ckpt, "commit.json")))
    assert commit == {
        "version": 2, "checkpoint_no": 0, "epoch_no": 1, "global_step": 12,
        "nranks": 1,
    }
    shard_commit = json.load(
        open(os.path.join(ckpt, "rank_0", "commit.json"))
    )
    assert shard_commit["rank"] == 0 and shard_commit["checkpoint_no"] == 0
    back = fleet.load_check_point(exe, path)
    assert back.global_step == 12 and back.rng["rng_step"] == 12
    assert back.checkpoint_no == 0


def test_local_vars_land_in_rank_shard_and_overlay_on_load(
    tmp_path, fresh_programs
):
    exe, loss = _build_model()
    scope = fluid.framework.scope.global_scope()
    fleet = _fleet()
    path = str(tmp_path / "ckpts")
    want = np.asarray(scope.find_var("er_w")).copy()
    fleet.save_check_point(
        exe, path, fc.TrainStatus(0, global_step=1), local_vars=["er_w"]
    )
    shard = os.path.join(path, "__paddle_checkpoint__0", "rank_0")
    assert os.path.exists(os.path.join(shard, "__params__.npz"))
    scope.set_var("er_w", np.zeros_like(want))
    fleet.load_check_point(exe, path)
    np.testing.assert_array_equal(np.asarray(scope.find_var("er_w")), want)


def test_rank_shard_commit_mismatch_raises(tmp_path, fresh_programs):
    exe, _ = _build_model()
    fleet = _fleet()
    path = str(tmp_path / "ckpts")
    fleet.save_check_point(exe, path, fc.TrainStatus(0, global_step=5))
    # tamper: the rank shard claims a different global step than the
    # checkpoint's commit record — the silent-divergence shape
    shard_commit = os.path.join(
        path, "__paddle_checkpoint__0", "rank_0", "commit.json"
    )
    c = json.load(open(shard_commit))
    c["global_step"] = 999
    json.dump(c, open(shard_commit, "w"))
    c0 = observability.snapshot()["counters"].get(
        "resilience.resume_mismatches", 0
    )
    with pytest.raises(errors.ResumeMismatchError, match="global_step"):
        fleet.load_check_point(exe, path)
    c1 = observability.snapshot()["counters"].get(
        "resilience.resume_mismatches", 0
    )
    assert c1 - c0 == 1


def test_incomplete_checkpoint_skipped_for_older_complete(
    tmp_path, fresh_programs
):
    import shutil

    exe, _ = _build_model()
    fleet = _fleet()
    path = str(tmp_path / "ckpts")
    fleet.save_check_point(exe, path, fc.TrainStatus(0, global_step=5))
    fleet.save_check_point(exe, path, fc.TrainStatus(1, global_step=10))
    # simulate "save died between the replicated publish and the shard
    # upload" on the NEWEST checkpoint: promise 2 ranks, deliver 1
    ckpt1 = os.path.join(path, "__paddle_checkpoint__1")
    commit = json.load(open(os.path.join(ckpt1, "commit.json")))
    commit["nranks"] = 2
    json.dump(commit, open(os.path.join(ckpt1, "commit.json"), "w"))
    status = fleet.load_check_point(exe, path)
    assert status.global_step == 5  # fell back to the complete one
    c = observability.snapshot()["counters"]
    assert c.get("resilience.checkpoint_incomplete", 0) >= 1
    # an explicit request for the incomplete checkpoint must raise, not
    # silently fall back
    with pytest.raises(errors.ResumeMismatchError, match="missing rank"):
        fleet.load_check_point(exe, path, checkpoint_no=1)
    # once every shard is gone the checkpoint is just incoherent for
    # everyone: no complete candidate -> typed error, not silent cold start
    shutil.rmtree(os.path.join(ckpt1, "rank_0"))
    ckpt0 = os.path.join(path, "__paddle_checkpoint__0")
    c0 = json.load(open(os.path.join(ckpt0, "commit.json")))
    c0["nranks"] = 2
    json.dump(c0, open(os.path.join(ckpt0, "commit.json"), "w"))
    with pytest.raises(errors.ResumeMismatchError):
        fleet.load_check_point(exe, path)


def test_rank_with_no_shard_anywhere_cold_starts(tmp_path, fresh_programs):
    """Startup race: the first worker published a per-rank checkpoint
    before this rank attached its first shard. The rank has no state in
    ANY checkpoint — that is a cold start, not a resume error."""
    exe, _ = _build_model()
    path = str(tmp_path / "ckpts")
    _fleet(0, 2).save_check_point(exe, path, fc.TrainStatus(0, global_step=5),
                                  per_rank=True)
    c0 = observability.snapshot()["counters"].get(
        "resilience.resume_cold_starts", 0
    )
    status = _fleet(1, 2).load_check_point(exe, path)
    assert status == fc.TrainStatus(-1) and status.global_step == 0
    c1 = observability.snapshot()["counters"].get(
        "resilience.resume_cold_starts", 0
    )
    assert c1 - c0 == 1
    # but a rank that HAS a shard somewhere still refuses incoherence:
    # rank 0's shard exists in the (incomplete) checkpoint, so rank 0
    # must not silently cold-start over its own history
    with pytest.raises(errors.ResumeMismatchError):
        _fleet(0, 2).load_check_point(exe, path)


def test_second_rank_attaches_shard_and_loads_its_own_cursor(
    tmp_path, fresh_programs
):
    exe, _ = _build_model()
    path = str(tmp_path / "ckpts")
    st0 = fc.TrainStatus(0, global_step=5,
                         cursor={"epoch": 0, "batches_consumed": 5})
    st1 = fc.TrainStatus(0, global_step=5,
                         cursor={"epoch": 0, "batches_consumed": 7})
    rank0, rank1 = _fleet(0, 2), _fleet(1, 2)
    assert rank0.save_check_point(exe, path, st0, per_rank=True) == 0
    # rank 1 finds the matching publish and attaches its shard
    assert rank1.save_check_point(
        exe, path, st1, per_rank=True, shard_wait_timeout=5
    ) == 0
    ckpt = os.path.join(path, "__paddle_checkpoint__0")
    assert os.path.isdir(os.path.join(ckpt, "rank_1"))
    # each rank resumes with ITS cursor
    back0 = rank0.load_check_point(exe, path)
    back1 = rank1.load_check_point(exe, path)
    assert back0.cursor["batches_consumed"] == 5
    assert back1.cursor["batches_consumed"] == 7


def test_second_rank_times_out_without_matching_publish(
    tmp_path, fresh_programs
):
    exe, _ = _build_model()
    path = str(tmp_path / "ckpts")
    _fleet(0, 2).save_check_point(
        exe, path, fc.TrainStatus(0, global_step=5), per_rank=True
    )
    with pytest.raises(errors.ExecutionTimeoutError, match="step=42"):
        _fleet(1, 2).save_check_point(
            exe, path, fc.TrainStatus(0, global_step=42),
            per_rank=True, shard_wait_timeout=0.3,
        )


def test_non_first_worker_save_is_noop_without_per_rank(
    tmp_path, fresh_programs
):
    """The classic contract: without per_rank (or local_vars) a non-first
    worker's save returns None IMMEDIATELY — no blocking wait — and the
    first worker's commit promises only its own shard, so the checkpoint
    is complete for loaders."""
    exe, _ = _build_model()
    path = str(tmp_path / "ckpts")
    assert _fleet(1, 2).save_check_point(
        exe, path, fc.TrainStatus(0)
    ) is None
    assert not os.path.exists(path)  # it wrote nothing, waited for nothing
    _fleet(0, 2).save_check_point(exe, path, fc.TrainStatus(0))
    ckpt = os.path.join(path, "__paddle_checkpoint__0")
    assert json.load(open(os.path.join(ckpt, "commit.json")))["nranks"] == 1
    # complete as promised: a non-first rank load works (replicated status)
    assert _fleet(1, 2).load_check_point(exe, path).next() == 1


def test_corrupt_commit_record_falls_back_not_bricks(
    tmp_path, fresh_programs
):
    exe, _ = _build_model()
    fleet = _fleet()
    path = str(tmp_path / "ckpts")
    fleet.save_check_point(exe, path, fc.TrainStatus(0, global_step=5))
    fleet.save_check_point(exe, path, fc.TrainStatus(1, global_step=10))
    with open(os.path.join(path, "__paddle_checkpoint__1",
                           "commit.json"), "w") as f:
        f.write("{torn")  # bit-rot / torn write on the newest commit
    status = fleet.load_check_point(exe, path)
    assert status.global_step == 5  # fell back instead of raising
    # an explicit request for the garbled one DOES surface the corruption
    with pytest.raises(errors.CheckpointCorruptionError, match="commit"):
        fleet.load_check_point(exe, path, checkpoint_no=1)


def test_per_rank_rotation_spares_newest_complete_checkpoint(
    tmp_path, fresh_programs
):
    """per_rank publishes are complete only after every peer attaches its
    shard; rotation must not delete the last COMPLETE checkpoint while the
    survivors are still waiting for peers."""
    exe, _ = _build_model()
    path = str(tmp_path / "ckpts")
    rank0, rank1 = _fleet(0, 2), _fleet(1, 2)
    st = fc.TrainStatus(0, global_step=5)
    rank0.save_check_point(exe, path, st, per_rank=True,
                           max_checkpoint_num=1)
    rank1.save_check_point(exe, path, st, per_rank=True,
                           shard_wait_timeout=5)
    # checkpoint 0 is now complete; rank 0 publishes 1 and 2 but the peer
    # never attaches (it died): with max_checkpoint_num=1 naive rotation
    # would delete 0 (and then 1), leaving only incomplete checkpoints
    rank0.save_check_point(exe, path, fc.TrainStatus(1, global_step=10),
                           per_rank=True, max_checkpoint_num=1)
    rank0.save_check_point(exe, path, fc.TrainStatus(2, global_step=15),
                           per_rank=True, max_checkpoint_num=1)
    dirs = sorted(os.listdir(path))
    assert "__paddle_checkpoint__0" in dirs, dirs  # the complete one lives
    status = rank1.load_check_point(exe, path)
    assert status.global_step == 5  # and it is what a resume lands on


def test_batch_size_mismatch_refused():
    ds = _Idx(48)
    src = DistributedBatchSampler(ds, 4, nranks=2, rank=0)
    state = src.state_dict()
    with pytest.raises(errors.ResumeMismatchError, match="batch_size"):
        DistributedBatchSampler(ds, 8, nranks=2, rank=0).load_state_dict(
            state
        )
    bs = BatchSampler(dataset=ds, batch_size=4)
    with pytest.raises(errors.ResumeMismatchError, match="batch_size"):
        BatchSampler(dataset=ds, batch_size=6).load_state_dict(
            bs.state_dict()
        )


def test_dataset_size_change_refused():
    """A grown/shrunk dataset reshuffles into a different permutation —
    the consumed prefix no longer matches, so fast-skip must refuse."""
    state = DistributedBatchSampler(_Idx(48), 4, nranks=2, rank=0,
                                    shuffle=True).state_dict()
    grown = DistributedBatchSampler(_Idx(60), 4, nranks=2, rank=0,
                                    shuffle=True)
    with pytest.raises(errors.ResumeMismatchError, match="48 samples"):
        grown.load_state_dict(state)
    state = BatchSampler(dataset=_Idx(20), shuffle=True,
                         batch_size=4).state_dict()
    with pytest.raises(errors.ResumeMismatchError, match="20 samples"):
        BatchSampler(dataset=_Idx(24), shuffle=True,
                     batch_size=4).load_state_dict(state)


# -- rotate-after-verify + corrupt-target loads ------------------------------
def test_publish_verify_failure_keeps_old_checkpoints(
    tmp_path, fresh_programs
):
    from paddle_tpu.fleet.fs_wrapper import LocalFS

    exe, _ = _build_model()
    fleet = _fleet()
    path = str(tmp_path / "ckpts")
    for epoch in range(3):
        fleet.save_check_point(exe, path, fc.TrainStatus(epoch),
                               max_checkpoint_num=2)
    kept = sorted(os.listdir(path))
    assert kept == ["__paddle_checkpoint__1", "__paddle_checkpoint__2"]

    class TearOnPublish(LocalFS):
        def mv(self, src, dst):
            super().mv(src, dst)
            if dst.endswith("__paddle_checkpoint__3"):
                # the publish "succeeds" but the landed payload is torn
                npz = os.path.join(dst, "__params__.npz")
                blob = open(npz, "rb").read()
                open(npz, "wb").write(blob[: len(blob) // 2])

    with pytest.raises(errors.CheckpointCorruptionError):
        fleet.save_check_point(
            exe, path, fc.TrainStatus(3), fs=TearOnPublish(),
            max_checkpoint_num=2,
        )
    # the bad publish must NOT have rotated the older checkpoints away
    assert "__paddle_checkpoint__1" in os.listdir(path)
    assert "__paddle_checkpoint__2" in os.listdir(path)
    status = fleet.load_check_point(exe, path)  # falls back past the torn one
    assert status.next() == 3
    c = observability.snapshot()["counters"]
    assert c.get("resilience.checkpoint_publish_verify_failures", 0) >= 1


def test_corrupt_explicit_checkpoint_no_fallback_counter_exactly_once(
    tmp_path, fresh_programs
):
    exe, _ = _build_model()
    fleet = _fleet()
    path = str(tmp_path / "ckpts")
    for epoch in range(2):
        fleet.save_check_point(exe, path, fc.TrainStatus(epoch))
    npz = os.path.join(path, "__paddle_checkpoint__1", "__params__.npz")
    blob = open(npz, "rb").read()
    open(npz, "wb").write(blob[: len(blob) // 2])
    c0 = observability.snapshot()["counters"].get(
        "resilience.checkpoint_corrupt", 0
    )
    with pytest.raises(errors.CheckpointCorruptionError):
        fleet.load_check_point(exe, path, checkpoint_no=1)
    c1 = observability.snapshot()["counters"].get(
        "resilience.checkpoint_corrupt", 0
    )
    assert c1 - c0 == 1  # exactly once: no fallback was attempted
    # checkpoint 0 is untouched and still loads when asked for
    assert fleet.load_check_point(exe, path, checkpoint_no=0).next() == 1


# -- fs.mkdir / fs.list_dirs fault seams -------------------------------------
@pytest.mark.parametrize("site", ["fs.mkdir", "fs.list_dirs"])
def test_save_heals_transient_prepare_faults(site, tmp_path, fresh_programs):
    exe, _ = _build_model()
    fleet = _fleet()
    faults.inject(site, "io", prob=1.0, max_fires=1)
    c0 = observability.snapshot()["counters"].get(
        "resilience.retries.checkpoint.prepare", 0
    )
    path = str(tmp_path / "ckpts")
    assert fleet.save_check_point(exe, path, fc.TrainStatus(0)) == 0
    c1 = observability.snapshot()["counters"].get(
        "resilience.retries.checkpoint.prepare", 0
    )
    assert c1 - c0 >= 1
    assert fleet.load_check_point(exe, path).next() == 1


# -- v1 compatibility --------------------------------------------------------
def test_v1_epoch_only_checkpoint_still_loads(tmp_path, fresh_programs):
    exe, _ = _build_model()
    fleet = _fleet()
    path = str(tmp_path / "ckpts")
    ckpt = os.path.join(path, "__paddle_checkpoint__0")
    fluid.io.save_persistables(exe, ckpt)
    with open(os.path.join(ckpt, "train_status.json"), "w") as f:
        json.dump({"epoch_no": 2}, f)  # the PR-2/3 on-disk format
    status = fleet.load_check_point(exe, path)
    assert status.next() == 3
    assert status.global_step == 0 and not status.cursor and not status.rng


# -- the full kill/resume equivalence audit (slow) ---------------------------
@pytest.mark.slow
def test_resume_audit_end_to_end(tmp_path):
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "resume_audit.py"),
         "--out", str(tmp_path / "audit")],
        env=env, capture_output=True, text=True, timeout=600,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "resume audit OK" in proc.stdout
