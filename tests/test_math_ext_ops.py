"""Extended op surface (ops/math_ext.py) against numpy references."""

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu.framework import unique_name
from paddle_tpu.framework.registry import get_op_def
from paddle_tpu.framework.registry import OpView
from paddle_tpu.framework.registry import EmitContext

import jax.numpy as jnp


@pytest.fixture(autouse=True)
def fresh_programs():
    main, startup = fluid.Program(), fluid.Program()
    scope = fluid.framework.scope.Scope()
    with fluid.program_guard(main, startup), fluid.scope_guard(scope), \
            unique_name.guard():
        yield


def run_op(op_type, ins, attrs=None, outs=("Out",)):
    """Drive an emitter directly (the micro harness pattern of
    tests/op_test.py)."""
    ctx = EmitContext(is_test=True)
    op = OpView(op_type, attrs or {})
    got = get_op_def(op_type).emit(
        ctx, op, {k: [jnp.asarray(x) for x in v] for k, v in ins.items()}
    )
    return [np.asarray(got[o][0]) for o in outs]


RNG = np.random.RandomState(0)


def test_linalg_family():
    a = RNG.randn(4, 5).astype("f4")
    b = RNG.randn(5, 3).astype("f4")
    inp = RNG.randn(4, 3).astype("f4")
    (out,) = run_op("addmm", {"Input": [inp], "X": [a], "Y": [b]},
                    {"Alpha": 2.0, "Beta": 0.5})
    np.testing.assert_allclose(out, 0.5 * inp + 2.0 * (a @ b), rtol=1e-5)

    m = RNG.randn(4, 4).astype("f4")
    spd = m @ m.T + 4 * np.eye(4, dtype="f4")
    (c,) = run_op("cholesky", {"X": [spd]})
    np.testing.assert_allclose(c @ c.T, spd, rtol=1e-4, atol=1e-4)
    (inv,) = run_op("inverse", {"Input": [spd]}, outs=("Output",))
    np.testing.assert_allclose(inv @ spd, np.eye(4), atol=1e-4)

    x = RNG.randn(2, 3).astype("f4")
    y = RNG.randn(3, 2).astype("f4")
    (k,) = run_op("kron", {"X": [x], "Y": [y]})
    np.testing.assert_allclose(k, np.kron(x, y), rtol=1e-6)

    v = RNG.randn(6, 3).astype("f4")
    w = RNG.randn(6, 3).astype("f4")
    (cr,) = run_op("cross", {"X": [v], "Y": [w]})
    np.testing.assert_allclose(cr, np.cross(v, w), rtol=1e-5)

    sq = RNG.randn(5, 5).astype("f4")
    (tr,) = run_op("trace", {"Input": [sq]})
    np.testing.assert_allclose(tr, np.trace(sq), rtol=1e-5)

    d = RNG.randn(4).astype("f4")
    (de,) = run_op("diag_embed", {"Input": [d]}, {"offset": 1})
    np.testing.assert_allclose(de, np.diag(d, k=1), rtol=1e-6)

    (e,) = run_op("eye", {}, {"num_rows": 3, "num_columns": 5,
                              "dtype": "float32"})
    np.testing.assert_array_equal(e, np.eye(3, 5))


def test_elementwise_and_indexing():
    x = RNG.randn(4, 6).astype("f4")
    (oh,) = run_op("one_hot", {"X": [np.array([[1], [3]], "i8")]},
                   {"depth": 5})
    np.testing.assert_array_equal(oh, np.eye(5, dtype="f4")[[1, 3]])

    (f,) = run_op("flatten", {"X": [RNG.randn(2, 3, 4).astype("f4")]},
                  {"axis": 1})
    assert f.shape == (2, 12)

    idx = np.array([2, 0], "i4")
    (sel,) = run_op("index_select", {"X": [x], "Index": [idx]}, {"dim": 0})
    np.testing.assert_allclose(sel, x[[2, 0]])

    samp_idx = RNG.randint(0, 6, (4, 3)).astype("i4")
    (samp,) = run_op("index_sample", {"X": [x], "Index": [samp_idx]})
    np.testing.assert_allclose(samp, np.take_along_axis(x, samp_idx, 1))

    (sh,) = run_op("shard_index", {"X": [np.array([[1], [7], [15]], "i8")]},
                   {"index_num": 20, "nshards": 2, "shard_id": 0,
                    "ignore_value": -1})
    np.testing.assert_array_equal(sh, [[1], [7], [-1]])

    xs = [RNG.randn(3, 4).astype("f4") for _ in range(3)]
    ids = np.array([[2], [0], [1]], "i4")
    (mx,) = run_op("multiplex", {"X": xs, "Ids": [ids]})
    ref = np.stack([xs[2][0], xs[0][1], xs[1][2]])
    np.testing.assert_allclose(mx, ref)

    (hist,) = run_op("histogram", {"X": [np.array([0.1, 0.5, 0.9, 0.55],
                                                  "f4")]},
                     {"bins": 2, "min": 0.0, "max": 1.0})
    np.testing.assert_array_equal(hist, [1, 3])


def test_norms_similarity_losses():
    x = RNG.randn(4, 8).astype("f4")
    y = RNG.randn(4, 8).astype("f4")
    (cs,) = run_op("cos_sim", {"X": [x], "Y": [y]})
    ref = (x * y).sum(-1, keepdims=True) / (
        np.linalg.norm(x, axis=-1, keepdims=True)
        * np.linalg.norm(y, axis=-1, keepdims=True)
    )
    np.testing.assert_allclose(cs, ref, rtol=1e-5)

    (pn,) = run_op("p_norm", {"X": [x]}, {"porder": 3.0, "axis": 1})
    np.testing.assert_allclose(
        pn, (np.abs(x) ** 3).sum(1) ** (1 / 3), rtol=1e-5
    )
    (nrm, nval) = run_op("norm", {"X": [x]}, {"axis": 1}, ("Out", "Norm"))
    np.testing.assert_allclose(np.linalg.norm(nrm, axis=1), 1.0, rtol=1e-5)

    (dst,) = run_op("dist", {"X": [x], "Y": [y]}, {"p": 2.0})
    np.testing.assert_allclose(dst, np.linalg.norm((x - y).ravel()),
                               rtol=1e-5)

    p = 1 / (1 + np.exp(-x))
    lab = (RNG.rand(4, 8) > 0.5).astype("f4")
    (bce,) = run_op("bce_loss", {"X": [p], "Label": [lab]})
    ref = -(lab * np.log(p) + (1 - lab) * np.log(1 - p))
    np.testing.assert_allclose(bce, ref, rtol=1e-4)

    logp = np.log(np.abs(RNG.rand(5, 7).astype("f4")) + 0.1)
    labels = RNG.randint(0, 7, (5,)).astype("i8")
    (nll, tw) = run_op(
        "nll_loss", {"X": [logp], "Label": [labels]},
        {"reduction": "mean"}, ("Out", "Total_weight"),
    )
    np.testing.assert_allclose(
        nll, -logp[np.arange(5), labels].mean(), rtol=1e-5
    )

    scores = RNG.randn(4, 6).astype("f4")
    blab = RNG.randint(0, 6, (4, 1)).astype("i8")
    (bpr,) = run_op("bpr_loss", {"X": [scores], "Label": [blab]},
                    outs=("Y",))
    assert bpr.shape == (4, 1) and np.isfinite(bpr).all()


def test_vision_family():
    x = RNG.randn(2, 8, 3, 3).astype("f4")
    (ps,) = run_op("pixel_shuffle", {"X": [x]}, {"upscale_factor": 2})
    assert ps.shape == (2, 2, 6, 6)
    # block (0,0) of channel 0 comes from channels 0..3 at pixel (0,0)
    np.testing.assert_allclose(
        ps[0, 0, :2, :2].ravel(), x[0, :4, 0, 0], rtol=1e-6
    )

    (mo,) = run_op("maxout", {"X": [RNG.randn(2, 6, 4, 4).astype("f4")]},
                   {"groups": 3})
    assert mo.shape == (2, 2, 4, 4)

    xm = RNG.randn(1, 1, 4, 4).astype("f4")
    (out, mask) = run_op(
        "max_pool2d_with_index", {"X": [xm]},
        {"ksize": [2, 2], "strides": [2, 2]}, ("Out", "Mask"),
    )
    np.testing.assert_allclose(out[0, 0, 0, 0], xm[0, 0, :2, :2].max())
    flat_idx = int(mask[0, 0, 0, 0])
    np.testing.assert_allclose(
        xm[0, 0].ravel()[flat_idx], out[0, 0, 0, 0]
    )

    ac_x = RNG.randn(2, 3, 4, 4).astype("f4")
    sc, bi = RNG.randn(3).astype("f4"), RNG.randn(3).astype("f4")
    (ac,) = run_op("affine_channel", {"X": [ac_x], "Scale": [sc],
                                      "Bias": [bi]})
    np.testing.assert_allclose(
        ac, ac_x * sc[None, :, None, None] + bi[None, :, None, None],
        rtol=1e-5,
    )

    # identity grid reproduces the input (align_corners=True)
    gx, gy = np.meshgrid(np.linspace(-1, 1, 4), np.linspace(-1, 1, 4))
    grid = np.stack([gx, gy], -1)[None].astype("f4")
    gs_x = RNG.randn(1, 2, 4, 4).astype("f4")
    (gs,) = run_op("grid_sampler", {"X": [gs_x], "Grid": [grid]},
                   outs=("Output",))
    np.testing.assert_allclose(gs, gs_x, rtol=1e-4, atol=1e-5)


def test_gather_tree():
    # two steps, one batch, beam 2: chain endpoints back to their roots
    ids = np.array([[[1, 2]], [[3, 4]]], "i8")  # [T=2, B=1, K=2]
    parents = np.array([[[0, 0]], [[1, 0]]], "i8")
    (out,) = run_op("gather_tree", {"Ids": [ids], "Parents": [parents]})
    # beam 0 at t=1 has parent 1 -> its t=0 token is ids[0,0,1]=2
    np.testing.assert_array_equal(out, [[[2, 1]], [[3, 4]]])


def test_label_smooth_and_lrn():
    oh = np.eye(4, dtype="f4")[[0, 2]]
    (ls,) = run_op("label_smooth", {"X": [oh]}, {"epsilon": 0.1})
    np.testing.assert_allclose(ls, 0.9 * oh + 0.1 / 4, rtol=1e-5)

    x = RNG.randn(1, 5, 3, 3).astype("f4")
    (lr, mid) = run_op("lrn", {"X": [x]},
                       {"n": 3, "alpha": 0.1, "beta": 0.5, "k": 1.0},
                       ("Out", "MidOut"))
    # channel 0: window = channels {0, 1}
    ref_mid = 1.0 + 0.1 * (x[0, 0] ** 2 + x[0, 1] ** 2)
    np.testing.assert_allclose(mid[0, 0], ref_mid, rtol=1e-5)
    np.testing.assert_allclose(lr[0, 0], x[0, 0] / np.sqrt(ref_mid),
                               rtol=1e-5)


def test_batch2_rnn_cells_and_conv3d():
    # gru_unit vs manual
    B, D = 3, 5
    x = RNG.randn(B, 3 * D).astype("f4")
    hp = RNG.randn(B, D).astype("f4")
    w = RNG.randn(D, 3 * D).astype("f4")
    (gate, rh, h) = run_op(
        "gru_unit", {"Input": [x], "HiddenPrev": [hp], "Weight": [w]},
        outs=("Gate", "ResetHiddenPrev", "Hidden"),
    )
    sig = lambda v: 1 / (1 + np.exp(-v))  # noqa: E731
    uh = hp @ w[:, :2 * D]
    u = sig(x[:, :D] + uh[:, :D])
    r = sig(x[:, D:2 * D] + uh[:, D:])
    c = np.tanh(x[:, 2 * D:] + (r * hp) @ w[:, 2 * D:])
    np.testing.assert_allclose(h, u * c + (1 - u) * hp, rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(rh, r * hp, rtol=1e-4, atol=1e-5)

    # lstm_unit vs manual
    x4 = RNG.randn(B, 4 * D).astype("f4")
    cp = RNG.randn(B, D).astype("f4")
    (c_out, h_out) = run_op(
        "lstm_unit", {"X": [x4], "C_prev": [cp]}, {"forget_bias": 1.0},
        ("C", "H"),
    )
    i, f = sig(x4[:, :D]), sig(x4[:, D:2 * D] + 1.0)
    g, o = np.tanh(x4[:, 2 * D:3 * D]), sig(x4[:, 3 * D:])
    cr = f * cp + i * g
    np.testing.assert_allclose(c_out, cr, rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(h_out, o * np.tanh(cr), rtol=1e-4, atol=1e-5)

    # conv3d: 1x1x1 kernel equals a channel mix
    xv = RNG.randn(1, 2, 3, 4, 4).astype("f4")
    wv = RNG.randn(3, 2, 1, 1, 1).astype("f4")
    (out,) = run_op("conv3d", {"Input": [xv], "Filter": [wv]},
                    outs=("Output",))
    ref = np.einsum("ncdhw,kc->nkdhw", xv, wv[:, :, 0, 0, 0])
    np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-5)


def test_batch2_misc():
    # bilinear tensor product
    x = RNG.randn(2, 3).astype("f4")
    y = RNG.randn(2, 4).astype("f4")
    w = RNG.randn(5, 3, 4).astype("f4")
    (out,) = run_op("bilinear_tensor_product",
                    {"X": [x], "Y": [y], "Weight": [w]})
    np.testing.assert_allclose(out, np.einsum("bi,kij,bj->bk", x, w, y),
                               rtol=1e-4)

    # pad_constant_like
    big = np.zeros((3, 5), "f4")
    small = RNG.randn(2, 3).astype("f4")
    (p,) = run_op("pad_constant_like", {"X": [big], "Y": [small]},
                  {"pad_value": 7.0})
    assert p.shape == (3, 5) and p[2, 4] == 7.0
    np.testing.assert_allclose(p[:2, :3], small)

    # mean_iou: perfect prediction -> 1.0
    lab = RNG.randint(0, 3, (10,)).astype("i4")
    (miou, wrong, correct) = run_op(
        "mean_iou", {"Predictions": [lab], "Labels": [lab]},
        {"num_classes": 3}, ("OutMeanIou", "OutWrong", "OutCorrect"),
    )
    np.testing.assert_allclose(miou, 1.0)
    assert (wrong == 0).all()

    # space_to_depth / shuffle_channel round shapes
    xs = RNG.randn(1, 2, 4, 4).astype("f4")
    (sd,) = run_op("space_to_depth", {"X": [xs]}, {"blocksize": 2})
    assert sd.shape == (1, 8, 2, 2)
    (sc,) = run_op("shuffle_channel", {"X": [RNG.randn(1, 6, 2, 2)
                                             .astype("f4")]}, {"group": 3})
    assert sc.shape == (1, 6, 2, 2)

    # temporal_shift: static channels unchanged
    xt = RNG.randn(4, 8, 2, 2).astype("f4")  # N=2, T=2
    (ts,) = run_op("temporal_shift", {"X": [xt]},
                   {"seg_num": 2, "shift_ratio": 0.25})
    np.testing.assert_allclose(ts[:, 4:], xt[:, 4:])  # last half static
    # fwd-shifted channels: t=0 gets zeros
    assert (ts.reshape(2, 2, 8, 2, 2)[:, 0, :2] == 0).all()

    # add_position_encoding: beta=0 is identity
    xa = RNG.randn(2, 5, 8).astype("f4")
    (ap,) = run_op("add_position_encoding", {"X": [xa]},
                   {"alpha": 1.0, "beta": 0.0})
    np.testing.assert_allclose(ap, xa)

    (sl2,) = run_op("squared_l2_norm", {"X": [x]})
    np.testing.assert_allclose(sl2, (x ** 2).sum(), rtol=1e-5)

    # cvm log-adjusts the first two columns
    xc = np.abs(RNG.randn(3, 5)).astype("f4")
    (cv,) = run_op("cvm", {"X": [xc], "CVM": [xc[:, :2]]},
                   {"use_cvm": True}, ("Y",))
    np.testing.assert_allclose(cv[:, 0], np.log(xc[:, 0] + 1), rtol=1e-5)
    np.testing.assert_allclose(cv[:, 2:], xc[:, 2:])
