"""Telemetry-plane chaos worker (driven by ci.sh).

Steps a tiny train loop with the journal publisher + flight recorder
live via the ``PADDLE_TPU_TELEMETRY_DIR`` one-env-var opt-in (the
Executor constructor wires the plane up — this script never imports a
publisher to *start* one).

argv: OUT_DIR STEPS. STEPS > 0 finishes cleanly: the plane is frozen
(final publish) and the live registry snapshot dumped to
``OUT_DIR/telemetry_stats.json`` so the driver can prove the offline
journal replay lands exactly on it. STEPS == 0 loops until the driver
SIGKILLs the process — its journal and periodic flight bundle are all
that survive.
"""

import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import paddle_tpu as fluid  # noqa: E402
from paddle_tpu import layers, observability as obs  # noqa: E402
from paddle_tpu.observability import recorder, timeline  # noqa: E402

out, steps = sys.argv[1], int(sys.argv[2])

x = fluid.data("x", [-1, 4])
y = fluid.data("y", [-1, 1])
pred = layers.fc(x, 1)
loss = layers.mean(layers.square_error_cost(pred, y))
fluid.optimizer.SGD(0.05).minimize(loss)
exe = fluid.Executor()  # <- ensure_publisher(): the plane starts HERE
exe.run(fluid.default_startup_program())
assert timeline.current_publisher() is not None, "publisher did not start"
assert recorder.get_recorder() is not None, "flight recorder did not start"

rng = np.random.RandomState(0)
i = 0
while steps == 0 or i < steps:
    t0 = time.perf_counter()
    xa = rng.randn(8, 4).astype(np.float32)
    with obs.span("train.step", step=i):
        exe.run(feed={"x": xa, "y": xa @ np.ones((4, 1), np.float32)},
                fetch_list=[loss])
    obs.add("guard.steps")
    obs.observe("executor.step_latency", time.perf_counter() - t0)
    # the doomed rank serves slow requests so the fleet p99 carries its
    # signature; the clean rank serves fast ones
    obs.observe("serving.request_latency", 0.2 if steps == 0 else 0.002)
    obs.add("serving.requests_served")
    obs.add("serving.goodput")
    i += 1
    if steps == 0:
        # slow enough that the driver's kill lands well before this
        # rank's step counter could catch the clean rank's
        time.sleep(0.1)

# clean finish: stop the recorder FIRST (its dump would bump counters),
# then the publisher (stop() takes a final publish), then snapshot the
# now-frozen registry — offline replay must reproduce this file's
# counters/gauges/histograms/tables bitwise
recorder.get_recorder().stop()
timeline.current_publisher().stop()
obs.dump(out + "/telemetry_stats.json")
