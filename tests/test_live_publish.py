"""PR-18 live model publish plane: versioned delta bundles with a
commit-record visibility barrier, all-or-nothing subscriber applies
(torn-read fence), per-consumer delta-row cursors, canaried rollout with
automatic rollback, staleness gauges, and the brownout freeze rung.

Everything here is in-process (real Programs/Scopes, fake watcher, fault
seams instead of SIGKILL); the real multi-process leg — a worker shot
mid-apply respawning bitwise onto the last committed version — is
bench_serving.py's ``--mix live_update`` and ci.sh's live-publish chaos
stage."""

import importlib.util
import json
import os
import time

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import layers
from paddle_tpu import observability as obs
from paddle_tpu.errors import CheckpointCorruptionError
from paddle_tpu.fleet import publish as pub_mod
from paddle_tpu.fleet.publish import (
    PAYLOAD_NAME,
    ModelPublisher,
    ModelSubscriber,
    block_version,
    committed_versions,
    latest_version,
    load_version,
    read_blocked,
    resolve_chain,
    version_dir,
)
from paddle_tpu.framework.scope import Scope, scope_guard
from paddle_tpu.resilience import faults
from paddle_tpu.resilience.health import Heartbeat
from paddle_tpu.serving import freeze_program
from paddle_tpu.serving.brownout import DEFAULT_LADDER, BrownoutController
from paddle_tpu.serving.replica import ReplicaSet
from paddle_tpu.serving.rollout import RolloutController, SubscribedRunner
from paddle_tpu.serving.router import FrozenRunner

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_tool(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(_ROOT, "tools", f"{name}.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(autouse=True)
def fresh_metrics():
    obs.reset()
    obs.set_enabled(True)
    faults.clear()
    yield
    faults.clear()
    obs.reset()
    obs.set_enabled(None)


def _counter(name):
    return obs.get_counters().get(name, 0)


# ---------------------------------------------------------------------------
# fixture: a tiny trainable classifier + its frozen serving graph
# ---------------------------------------------------------------------------


class _Trainer:
    def __init__(self, seed=7):
        self.scope = Scope()
        self.main, self.startup = fluid.Program(), fluid.Program()
        self.main.random_seed = self.startup.random_seed = seed
        with fluid.program_guard(self.main, self.startup):
            x = fluid.data("x", [-1, 8])
            lab = fluid.data("lab", [-1, 1], "int64")
            h = layers.fc(x, 16, act="relu")
            logits = layers.fc(h, 4)
            self.prob = layers.softmax(logits)
            self.loss = layers.mean(
                layers.softmax_with_cross_entropy(logits, lab)
            )
            fluid.optimizer.Adam(1e-2).minimize(self.loss, self.startup)
        self.exe = fluid.Executor()
        self._rng = np.random.RandomState(seed)
        with scope_guard(self.scope):
            self.exe.run(self.startup, scope=self.scope)
        self.frozen = freeze_program(
            self.main, [self.prob], feed_names=("x",)
        )

    def step(self, n=2):
        with scope_guard(self.scope):
            for _ in range(n):
                self.exe.run(
                    self.main,
                    feed={
                        "x": self._rng.randn(4, 8).astype(np.float32),
                        "lab": self._rng.randint(
                            0, 4, (4, 1)
                        ).astype(np.int64),
                    },
                    fetch_list=[self.loss], scope=self.scope,
                )

    def serving_scope(self):
        """A cold replica scope: startup-initialized, same topology —
        what a fresh worker holds before its catch-up poll."""
        scope = Scope()
        with scope_guard(scope):
            self.exe.run(self.startup, scope=scope)
        return scope


@pytest.fixture()
def trainer():
    return _Trainer()


def _dense(arrays):
    """The dense persistables of a folded bundle (drop embedding
    host-store keys; row pairs never survive a fold)."""
    return {
        n: a for n, a in arrays.items() if "::host::" not in n
    }


def _assert_scope_matches(scope, arrays):
    for name, arr in _dense(arrays).items():
        live = scope.find_var(name)
        assert live is not None, name
        np.testing.assert_array_equal(np.asarray(live), np.asarray(arr))


# ---------------------------------------------------------------------------
# publisher: commit record = visibility barrier
# ---------------------------------------------------------------------------


def test_commit_seam_crash_is_invisible_and_number_reclaimed(
    trainer, tmp_path
):
    p = ModelPublisher(str(tmp_path), main_program=trainer.main,
                       scope=trainer.scope)
    faults.inject("publish.commit", "io", 1.0, 0, 1)
    with pytest.raises(Exception):
        p.publish(step=1)
    # payload may have landed; without its commit record the version
    # does not exist to any reader
    assert committed_versions(str(tmp_path)) == []
    assert latest_version(str(tmp_path)) is None
    # the seam healed (max_fires=1): the same version number is
    # reclaimed, not burned
    assert p.publish(step=1) == 1
    assert committed_versions(str(tmp_path)) == [1]


def test_failed_publish_advances_no_cursors(trainer, tmp_path):
    p = ModelPublisher(str(tmp_path), main_program=trainer.main,
                       scope=trainer.scope)
    assert p.publish(step=1) == 1
    trainer.step()
    faults.inject("publish.commit", "io", 1.0, 0, 1)
    with pytest.raises(Exception):
        p.publish(step=2)
    # the retried delta still carries everything trained since v1
    assert p.publish(step=2) == 2
    folded = load_version(str(tmp_path), 2)
    _assert_scope_matches(trainer.scope, folded)


def test_delta_chain_folds_bitwise_and_retires_safely(trainer, tmp_path):
    p = ModelPublisher(str(tmp_path), main_program=trainer.main,
                       scope=trainer.scope, full_every=4, max_versions=2)
    for s in range(6):
        trainer.step()
        p.publish(step=s)
    committed = committed_versions(str(tmp_path))
    # retention keeps the window plus every base a kept delta chains
    # through — all committed versions must still fold
    for v in committed:
        chain = resolve_chain(str(tmp_path), v)
        assert chain[-1] == v
    _assert_scope_matches(
        trainer.scope, load_version(str(tmp_path), committed[-1])
    )
    assert _counter("publish.versions") == 6
    assert obs.get_gauges()["publish.version"] == float(committed[-1])


# ---------------------------------------------------------------------------
# subscriber: epoch fence — all-or-nothing applies
# ---------------------------------------------------------------------------


def test_subscriber_incremental_applies_bitwise(trainer, tmp_path):
    p = ModelPublisher(str(tmp_path), main_program=trainer.main,
                       scope=trainer.scope, full_every=3)
    sub = ModelSubscriber(str(tmp_path), main_program=trainer.main,
                          scope=trainer.serving_scope())
    for s in range(5):
        trainer.step()
        v = p.publish(step=s)
        assert sub.poll() == v
        assert sub.version == v
        # the delta-applied scope is bitwise the cold fold of v — the
        # acceptance bar for a replica that never restarts
        _assert_scope_matches(sub._scope, load_version(str(tmp_path), v))
    assert _counter("publish.applies") == 5
    assert obs.get_gauges()["serving.model_version"] == float(sub.version)


def test_torn_payload_never_applies(trainer, tmp_path):
    p = ModelPublisher(str(tmp_path), main_program=trainer.main,
                       scope=trainer.scope)
    p.publish(step=1)
    sub = ModelSubscriber(str(tmp_path), main_program=trainer.main,
                          scope=trainer.serving_scope())
    assert sub.poll() == 1
    v1 = load_version(str(tmp_path), 1)
    trainer.step()
    v2 = p.publish(step=2)
    # poison the committed payload: flip bytes mid-file (a torn write a
    # crashed publisher could leave if commit.json were not the barrier)
    payload = os.path.join(version_dir(str(tmp_path), v2), PAYLOAD_NAME)
    size = os.path.getsize(payload)
    with open(payload, "r+b") as f:
        f.seek(size // 2)
        f.write(b"\xff" * min(64, size - size // 2))
    with pytest.raises(CheckpointCorruptionError):
        sub.poll()
    # the fence held: nothing was mutated, the version never moved
    assert sub.version == 1
    _assert_scope_matches(sub._scope, v1)
    assert obs.get_gauges()["serving.model_version"] == 1.0


def test_apply_fault_restores_pre_apply_state(trainer, tmp_path):
    p = ModelPublisher(str(tmp_path), main_program=trainer.main,
                       scope=trainer.scope)
    p.publish(step=1)
    sub = ModelSubscriber(str(tmp_path), main_program=trainer.main,
                          scope=trainer.serving_scope())
    sub.poll()
    v1 = load_version(str(tmp_path), 1)
    trainer.step()
    p.publish(step=2)
    faults.inject("publish.apply", "io", 1.0, 0, 1)
    with pytest.raises(Exception):
        sub.poll()
    # mid-apply failure: the snapshot restored, the version gauge never
    # flipped — no batch can ever observe a half-applied bundle
    assert sub.version == 1
    _assert_scope_matches(sub._scope, v1)
    assert _counter("publish.apply_failures") == 1
    # the seam healed: the next poll applies v2 fully
    assert sub.poll() == 2
    _assert_scope_matches(sub._scope, load_version(str(tmp_path), 2))


def test_respawn_after_killed_apply_matches_cold_load(trainer, tmp_path):
    """A worker SIGKILLed mid-apply respawns, catch-up-polls, and must be
    bitwise a cold load of the last committed version (the in-process
    equivalent: a fenced-off failed apply, then a FRESH scope + fresh
    subscriber — the respawned worker's exact path)."""
    p = ModelPublisher(str(tmp_path), main_program=trainer.main,
                       scope=trainer.scope)
    p.publish(step=1)
    sub = ModelSubscriber(str(tmp_path), main_program=trainer.main,
                          scope=trainer.serving_scope())
    sub.poll()
    trainer.step()
    v2 = p.publish(step=2)
    faults.inject("publish.apply", "io", 1.0, 0, 1)
    with pytest.raises(Exception):
        sub.poll()
    faults.clear()
    # the respawn: cold scope, new subscriber, catch-up before serving
    respawn = ModelSubscriber(str(tmp_path), main_program=trainer.main,
                              scope=trainer.serving_scope())
    assert respawn.poll() == v2
    _assert_scope_matches(
        respawn._scope, load_version(str(tmp_path), v2)
    )


def test_blocked_version_downgrades_via_full_refold(trainer, tmp_path):
    p = ModelPublisher(str(tmp_path), main_program=trainer.main,
                       scope=trainer.scope)
    p.publish(step=1)
    sub = ModelSubscriber(str(tmp_path), main_program=trainer.main,
                          scope=trainer.serving_scope())
    sub.poll()
    trainer.step()
    v2 = p.publish(step=2)
    sub.poll()
    assert sub.version == v2
    block_version(str(tmp_path), v2)
    assert read_blocked(str(tmp_path)) == {v2}
    assert latest_version(str(tmp_path)) == 1
    # rollback is data: the next poll targets the older version and
    # re-folds its chain — bitwise the cold start on v1
    assert sub.poll() == 1
    _assert_scope_matches(sub._scope, load_version(str(tmp_path), 1))
    assert _counter("publish.versions_blocked") == 1


def test_staleness_grows_between_applies_and_snaps_down(
    trainer, tmp_path
):
    p = ModelPublisher(str(tmp_path), main_program=trainer.main,
                       scope=trainer.scope)
    p.publish(step=1)
    sub = ModelSubscriber(str(tmp_path), main_program=trainer.main,
                          scope=trainer.serving_scope())
    sub.poll()
    t0 = time.time()
    s0 = sub.staleness_s(now=t0)
    assert s0 is not None and s0 >= 0.0
    # monotonic between applies...
    assert sub.staleness_s(now=t0 + 5.0) == pytest.approx(s0 + 5.0)
    assert sub.staleness_s(now=t0 + 9.0) > sub.staleness_s(now=t0 + 5.0)
    assert "serving.model_staleness_seconds" in obs.get_gauges()
    # ...and snaps down when a fresher bundle applies
    trainer.step()
    p.publish(step=2)
    sub.poll()
    assert sub.staleness_s(now=time.time() + 5.0) < s0 + 5.0


def test_apply_stamps_heartbeat_with_model_version(trainer, tmp_path):
    hb_dir = tmp_path / "hb"
    hb = Heartbeat(str(hb_dir), rank=0)
    hb.beat()
    p = ModelPublisher(str(tmp_path / "pub"),
                       main_program=trainer.main, scope=trainer.scope)
    p.publish(step=1)
    sub = ModelSubscriber(str(tmp_path / "pub"),
                          main_program=trainer.main,
                          scope=trainer.serving_scope(), heartbeat=hb)
    sub.poll()
    with open(hb.path) as f:
        payload = json.load(f)
    # a fleet reader can tell which model version this worker serves
    # from its beat file alone
    assert payload["model_version"] == 1
    # sticky: every later beat carries it
    hb.beat()
    with open(hb.path) as f:
        assert json.load(f)["model_version"] == 1


# ---------------------------------------------------------------------------
# per-consumer delta-row cursors (embedding engine)
# ---------------------------------------------------------------------------


def _build_engine_model(seed=3):
    from paddle_tpu.embedding import EmbeddingEngine
    from paddle_tpu.framework import unique_name
    from paddle_tpu.models.deepfm import DeepFMConfig, deepfm

    cfg = DeepFMConfig(vocab_size=64, num_fields=4, embed_dim=4,
                       mlp_sizes=(8,))
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = seed
    scope = Scope()
    with fluid.program_guard(main, startup), fluid.scope_guard(scope), \
            unique_name.guard():
        ids = fluid.data("feat_ids", [8, cfg.num_fields], "int64")
        label = fluid.data("label", [8, 1], "float32")
        loss, _pred = deepfm(ids, label, cfg)
        engine = EmbeddingEngine(main, startup, hot_rows=32)
        fluid.optimizer.SGD(0.1).minimize(loss)
        exe = fluid.Executor()
        exe.run(startup, scope=scope)
        engine.attach(scope)
    rng = np.random.RandomState(seed)

    def step(n=1):
        for _ in range(n):
            feed = {
                "feat_ids": (64 * rng.power(0.4, (8, cfg.num_fields))
                             ).astype(np.int64),
                "label": rng.rand(8, 1).astype(np.float32),
            }
            ff = engine.prepare_feed(feed, scope)
            exe.run(main, feed=ff, fetch_list=[loss], scope=scope)

    return main, scope, engine, step


def test_consumer_cursors_are_independent():
    main, scope, engine, step = _build_engine_model()
    step(2)
    group = engine.groups[0]
    # first "publish" payload: oracle(None) with no committed cursor =
    # no base = full; commit its marks
    oracles = engine.delta_row_oracles(consumer="pub")
    marks = {}
    for key, oracle in oracles.items():
        rows, mark = oracle(None)
        assert rows is None  # no base yet: store in full
        marks[key] = mark
    engine.commit_row_marks("pub", marks)
    pub_mark = group.consumer_mark("pub")
    assert pub_mark is not None
    # rows dirtied AFTER pub's payload...
    step(1)
    engine.flush(scope)
    # ...get consumed by a CHECKPOINT landing in between, committing its
    # OWN cursor — which must not touch pub's
    ck_oracles = engine.delta_row_oracles(consumer="ckpt")
    ck_marks = {}
    for key, oracle in ck_oracles.items():
        _rows, mark = oracle(None)
        ck_marks[key] = mark
    engine.commit_row_marks("ckpt", ck_marks)
    assert group.consumer_mark("pub") == pub_mark
    assert group.consumer_mark("ckpt") > pub_mark
    # a RESTARTED publisher (in-process marks gone: oracle(None)) falls
    # back to pub's committed cursor and still sees every row dirtied
    # since ITS last payload — the checkpoint swallowed nothing
    dirty = group.dirty_rows_since(pub_mark)
    assert dirty.size > 0
    for key, oracle in engine.delta_row_oracles(consumer="pub").items():
        rows, _mark = oracle(None)
        assert rows is not None
        np.testing.assert_array_equal(rows, dirty)
    # marks never regress: a stale late commit cannot re-expose rows
    group.commit_consumer_mark("ckpt", pub_mark)
    assert group.consumer_mark("ckpt") == ck_marks[
        max(ck_marks, key=lambda k: ck_marks[k])
    ]


def test_checkpoint_between_publishes_drops_no_rows(tmp_path):
    main, scope, engine, step = _build_engine_model()
    step(2)
    p = ModelPublisher(str(tmp_path), main_program=main, scope=scope,
                       engine=engine, full_every=8)
    p.publish(step=1)
    step(1)
    # a checkpoint consumes the delta-row oracles between two publishes
    # (the AsyncCheckpointer shape: its own consumer, its own commit)
    ck_marks = {}
    for key, oracle in engine.delta_row_oracles(
        consumer="checkpoint"
    ).items():
        _rows, mark = oracle(None)
        ck_marks[key] = mark
    engine.commit_row_marks("checkpoint", ck_marks)
    step(1)
    v = p.publish(step=2)
    # the invariant the cursors exist for: the folded publish chain
    # reproduces the trainer's host stores bitwise — every row dirtied
    # since v1 made it into v2 even though a checkpoint consumed the
    # oracles in between
    engine.flush(scope)
    folded = load_version(str(tmp_path), v)
    for g in engine.groups:
        for vname, store in g.host.items():
            key = f"{g.name}::host::{vname}"
            assert key in folded, key
            np.testing.assert_array_equal(folded[key], store)


# ---------------------------------------------------------------------------
# rollout: canary gating, staged rollout, automatic rollback
# ---------------------------------------------------------------------------


class _FakeWatcher:
    def __init__(self):
        self.findings = []
        self.breaching = False

    def poll(self):
        out, self.findings = self.findings, []
        return out


def _rollout_rig(trainer, tmp_path, n=2, **kwargs):
    p = ModelPublisher(str(tmp_path), main_program=trainer.main,
                       scope=trainer.scope)
    runners = {}
    for i in range(n):
        scope = trainer.serving_scope()
        sub = ModelSubscriber(str(tmp_path), main_program=trainer.main,
                              scope=scope, name=f"r{i}")
        runners[f"r{i}"] = SubscribedRunner(
            FrozenRunner(trainer.frozen, scope=scope), sub
        )
    rs = ReplicaSet(runners)
    watcher = _FakeWatcher()
    ctl = RolloutController(rs, str(tmp_path), watcher=watcher,
                            canary_soak_ticks=1, post_soak_ticks=4,
                            breach_ticks=2, **kwargs)
    return p, rs, watcher, ctl, runners


def test_canary_pass_promotes_fleet_wide(trainer, tmp_path):
    p, rs, _watcher, ctl, runners = _rollout_rig(trainer, tmp_path)
    v1 = p.publish(step=1)
    assert ctl.poll() == "canary"       # canary (r0) applied v1
    assert runners["r0"].version == v1
    assert runners["r1"].version is None
    assert ctl.poll() == "post"         # soak passed: staged rollout
    assert runners["r1"].version == v1
    assert ctl.version == v1
    assert _counter("publish.canary_passes") == 1
    assert _counter("publish.rollouts") == 1
    assert obs.get_gauges()["serving.model_version"] == float(v1)
    # replicas are bitwise the cold fold of the promoted version
    for r in runners.values():
        _assert_scope_matches(
            r.subscriber._scope, load_version(str(tmp_path), v1)
        )


def test_canary_fail_rolls_back_one_replica_and_blocks(
    trainer, tmp_path
):
    p, rs, watcher, ctl, runners = _rollout_rig(trainer, tmp_path)
    v1 = p.publish(step=1)
    ctl.poll(), ctl.poll(), ctl.poll()  # v1 rolled out + post soak
    while ctl.state != "idle":
        ctl.poll()
    trainer.step()
    v2 = p.publish(step=2)
    assert ctl.poll() == "canary"
    assert runners["r0"].version == v2
    # the canary soaks badly: a watcher p99 breach finding
    watcher.findings = [{"kind": "slo_breach", "severity": "error"}]
    assert ctl.poll() == "idle"
    # one-replica blast radius: the canary re-folded to last-good, the
    # follower never moved, the bad version is blocked for everyone
    assert runners["r0"].version == v1
    assert runners["r1"].version == v1
    assert read_blocked(str(tmp_path)) == {v2}
    assert _counter("publish.canary_fails") == 1
    assert _counter("publish.rollbacks") == 1
    _assert_scope_matches(
        runners["r0"].subscriber._scope, load_version(str(tmp_path), v1)
    )
    # blocked stays blocked: the controller does not retry the version
    assert ctl.poll() == "idle"
    assert runners["r0"].version == v1


def test_post_rollout_breach_rolls_back_fleet(trainer, tmp_path):
    p, rs, watcher, ctl, runners = _rollout_rig(trainer, tmp_path)
    v1 = p.publish(step=1)
    ctl.poll(), ctl.poll()
    while ctl.state != "idle":
        ctl.poll()
    trainer.step()
    v2 = p.publish(step=2)
    ctl.poll()                           # canary v2
    assert ctl.poll() == "post"          # fleet-wide on v2
    assert ctl.version == v2
    # sustained post-rollout breach (breach_ticks=2 consecutive polls)
    watcher.breaching = True
    assert ctl.poll() == "post"          # streak 1: not yet
    assert ctl.poll() == "idle"          # streak 2: automatic rollback
    watcher.breaching = False
    assert ctl.version == v1
    assert read_blocked(str(tmp_path)) == {v2}
    assert _counter("publish.rollbacks") == 1
    for r in runners.values():
        assert r.version == v1
        _assert_scope_matches(
            r.subscriber._scope, load_version(str(tmp_path), v1)
        )
    # a single transient breach tick must NOT roll back
    trainer.step()
    v3 = p.publish(step=3)
    ctl.poll(), ctl.poll()
    assert ctl.version == v3
    watcher.breaching = True
    ctl.poll()
    watcher.breaching = False
    assert ctl.poll() == "post"
    assert ctl.version == v3


def test_nonfinite_probe_fails_canary(trainer, tmp_path):
    probe = {"x": np.zeros((2, 8), np.float32)}
    p, rs, watcher, ctl, runners = _rollout_rig(
        trainer, tmp_path, probe_feed=probe
    )
    v1 = p.publish(step=1)
    ctl.poll(), ctl.poll()
    while ctl.state != "idle":
        ctl.poll()
    # poison the trainer: a bias full of NaN rides the next publish
    name = [
        n for n in trainer.scope.local_var_names() if "fc" in n
    ][0]
    trainer.scope.set_var(
        name, np.full_like(np.asarray(trainer.scope.find_var(name)),
                           np.nan)
    )
    v2 = p.publish(step=2)
    ctl.poll()                           # canary applies v2
    assert ctl.poll() == "idle"          # probe sees NaN: rollback
    assert _counter("publish.nonfinite_probes") >= 1
    assert _counter("publish.canary_fails") == 1
    assert read_blocked(str(tmp_path)) == {v2}
    assert all(r.version == v1 for r in runners.values())


def test_freeze_blocks_rollouts_and_brownout_rung_drives_it(
    trainer, tmp_path
):
    p, rs, _watcher, ctl, runners = _rollout_rig(trainer, tmp_path)
    v1 = p.publish(step=1)
    ctl.freeze()
    assert ctl.poll() == "idle"
    assert runners["r0"].version is None  # nothing moved while frozen
    assert _counter("publish.freezes") == 1
    ctl.unfreeze()
    assert ctl.poll() == "canary"
    assert runners["r0"].version == v1

    # the ladder's top rung freezes publishes; recovery unfreezes
    class _NoEndpoints:
        def endpoints(self):
            return {}

    bc = BrownoutController(_NoEndpoints(), slo_p99_s=0.1,
                            escalate_after=1, recover_after=1,
                            publish_control=ctl)
    assert "freeze_publishes" in DEFAULT_LADDER[-1]
    for _ in range(len(DEFAULT_LADDER) - 1):
        bc.observe(p99=5.0)
    assert bc.level == len(DEFAULT_LADDER) - 1
    assert ctl.frozen
    bc.observe(p99=0.01)
    assert not ctl.frozen


def test_restore_replica_rewarm_replays_warm_buckets(trainer):
    calls = []

    class _Counting:
        feed_names = ("x",)
        fetch_names = ("out",)

        def __init__(self, name):
            self.name = name

        def sample_spec(self, name):
            return ((8,), "float32")

        def run(self, feed):
            calls.append(self.name)
            return [np.zeros((len(feed["x"]), 1), np.float32)]

    rs = ReplicaSet({"a": _Counting("a"), "b": _Counting("b")})
    rs.warmup_run({"x": np.zeros((2, 8), np.float32)})
    rs.warmup_run({"x": np.zeros((4, 8), np.float32)})
    calls.clear()
    rs.drain_replica("a")
    rs.restore_replica("a", rewarm=True)
    # only the restored replica re-ran, once per warmed bucket size
    assert calls == ["a", "a"]
    assert _counter("serving.replica_rewarms") == 1
    # without rewarm the restore is knob-only
    rs.drain_replica("b")
    calls.clear()
    rs.restore_replica("b")
    assert calls == []


# ---------------------------------------------------------------------------
# fleet_report: publish-version skew across journal shards
# ---------------------------------------------------------------------------


def test_fleet_report_renders_publish_version_skew(tmp_path):
    now = time.time()
    for rank, version in ((0, 7.0), (1, 6.0)):
        with open(
            tmp_path / f"telemetry_rank{rank}.jsonl", "a"
        ) as f:
            f.write(json.dumps({
                "kind": "base", "rank": rank, "pid": 100 + rank,
                "seq": 1, "t": now - 1.0,
                "counters": {"publish.applies": 1},
                "gauges": {"serving.model_version": version,
                           "serving.model_staleness_seconds": 2.5},
            }) + "\n")
    fleet_report = _load_tool("fleet_report")
    report = fleet_report.build_report(str(tmp_path), now=now)
    by_rank = {s["rank"]: s for s in report["shards"]}
    assert by_rank[0]["model_version"] == 7
    assert by_rank[1]["model_version"] == 6
    skew = report["fleet"]["publish_skew"]
    assert skew["max_version"] == 7
    assert skew["min_version"] == 6
    assert skew["lagging_ranks"] == [1]
    assert "publish skew" in fleet_report.render(report)
