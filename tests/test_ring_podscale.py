"""Pod-scale SP evidence (VERDICT r4 weak #4 / next #6): the ring loop is
a lax.scan, so the compiled program contains ONE ppermute pair and the
HLO/compile time stay flat as the mesh grows — n=64 must look like n=8.

Each measurement runs in a subprocess because the virtual-CPU device count
is fixed at backend init (the conftest pins this process to 8)."""

import json
import os
import subprocess
import sys


_PROBE = r"""
import json, time
import numpy as np
import jax, jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from paddle_tpu.parallel.ring_attention import ring_attention

n = len(jax.devices())
mesh = Mesh(np.array(jax.devices()), ("sp",))
b, h, s_local, d = 1, 8, 16, 16
s = s_local * n

def local(q, k, v):
    out = ring_attention(q, k, v, "sp", n, causal=True)
    return out

def f(q, k, v):
    fn = jax.shard_map(local, mesh=mesh,
                       in_specs=(P(None, None, "sp"),) * 3,
                       out_specs=P(None, None, "sp"),
                       check_vma=False)
    return fn(q, k, v)

q = jnp.zeros((b, h, s, d), jnp.float32)
t0 = time.perf_counter()
lowered = jax.jit(f).lower(q, q, q)
hlo = lowered.as_text()
t1 = time.perf_counter()
compiled = lowered.compile()
t2 = time.perf_counter()
print(json.dumps({
    "n": n,
    "trace_s": round(t1 - t0, 3),
    "compile_s": round(t2 - t1, 3),
    "hlo_chars": len(hlo),
    "permutes": hlo.count("collective_permute"),
}))
"""


def _probe(n_devices):
    env = dict(os.environ)
    import re

    base = re.sub(r"--xla_force_host_platform_device_count=\d+", "",
                  env.get("XLA_FLAGS", ""))
    env["XLA_FLAGS"] = (
        base + f" --xla_force_host_platform_device_count={n_devices}"
    ).strip()
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("PALLAS_AXON_POOL_IPS", None)
    proc = subprocess.run(
        [sys.executable, "-c", _PROBE], env=env, capture_output=True,
        text=True, timeout=600,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    )
    assert proc.returncode == 0, proc.stderr
    return json.loads(proc.stdout.strip().splitlines()[-1])


def test_ring_compile_flat_from_8_to_64_devices():
    r8 = _probe(8)
    r64 = _probe(64)
    # the scan keeps the program size mesh-independent: same number of
    # collective-permutes (2: one k, one v inside the scan body) and flat
    # HLO size; an unrolled ring would grow both 8x
    assert r8["permutes"] == r64["permutes"], (r8, r64)
    assert r8["permutes"] <= 4, r8
    assert r64["hlo_chars"] <= 1.5 * r8["hlo_chars"], (r8, r64)
    # tracing is mesh-size independent; XLA backend compile may grow a
    # little with the device count but must stay far from linear
    assert r64["trace_s"] <= max(3.0 * r8["trace_s"], r8["trace_s"] + 2.0), (
        r8, r64)
    print(f"podscale: n=8 {r8} / n=64 {r64}")
