"""Pallas LayerNorm kernels (interpret mode) vs the jnp oracle, plus the
dedicated layer_norm_grad op against numeric/vjp references."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as fluid
from paddle_tpu import layers
from paddle_tpu.framework import unique_name
from paddle_tpu.kernels.layer_norm import (
    layer_norm_bwd,
    layer_norm_fwd,
    reference_fwd,
)

R, N = 64, 256


@pytest.fixture(autouse=True)
def fresh_programs():
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 21
    scope = fluid.framework.scope.Scope()
    with fluid.program_guard(main, startup), fluid.scope_guard(scope), \
            unique_name.guard():
        yield main, startup, scope


def _data(seed=0):
    rng = np.random.RandomState(seed)
    x = jnp.asarray(rng.randn(R, N).astype("float32") * 2 + 0.5)
    scale = jnp.asarray(rng.rand(N).astype("float32") + 0.5)
    bias = jnp.asarray(rng.randn(N).astype("float32"))
    return x, scale, bias


def test_fwd_kernel_matches_reference():
    x, scale, bias = _data()
    y_k, m_k, v_k = layer_norm_fwd(x, scale, bias, 1e-5, interpret=True)
    y_r, m_r, v_r = reference_fwd(x, scale, bias, 1e-5)
    np.testing.assert_allclose(np.asarray(y_k), np.asarray(y_r), rtol=1e-5,
                               atol=1e-5)
    np.testing.assert_allclose(np.asarray(m_k), np.asarray(m_r), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(v_k), np.asarray(v_r), rtol=1e-5,
                               atol=1e-5)


def test_bwd_kernel_matches_vjp_of_reference():
    x, scale, bias = _data(1)
    w = jnp.asarray(np.random.RandomState(2).randn(R, N).astype("float32"))

    def f(x_, s_, b_):
        y, _, _ = reference_fwd(x_, s_, b_, 1e-5)
        return jnp.sum(y * w)

    gx, gs, gb = jax.grad(f, (0, 1, 2))(x, scale, bias)
    dx, ds, db = layer_norm_bwd(x, scale, w, 1e-5, interpret=True)
    np.testing.assert_allclose(np.asarray(dx), np.asarray(gx), rtol=2e-4,
                               atol=2e-4)
    np.testing.assert_allclose(np.asarray(ds), np.asarray(gs), rtol=2e-4,
                               atol=2e-4)
    np.testing.assert_allclose(np.asarray(db), np.asarray(gb), rtol=2e-4,
                               atol=2e-4)


def test_layer_norm_grad_op_matches_generic_vjp():
    """The dedicated layer_norm_grad op (CPU jnp path) reproduces the
    gradients the generic __vjp__ path used to produce. The dedicated op
    only exists under the Pallas-LN flag (default path keeps the generic
    vjp, which XLA CSEs and fuses better)."""
    fluid.set_flags({"FLAGS_paddle_tpu_pallas_layer_norm": True})
    try:
        _run_grad_op_check()
    finally:
        fluid.set_flags({"FLAGS_paddle_tpu_pallas_layer_norm": False})


def _run_grad_op_check():
    rng = np.random.RandomState(3)
    xn = rng.randn(4, 8, 32).astype("float32")
    x = fluid.data("x", [4, 8, 32])
    x.stop_gradient = False
    y = layers.layer_norm(
        x, begin_norm_axis=2,
        param_attr=fluid.ParamAttr(name="ln_s"),
        bias_attr=fluid.ParamAttr(name="ln_b"),
    )
    loss = layers.reduce_sum(layers.square(y))
    grads = fluid.framework.backward.gradients([loss], [x])
    main = fluid.default_main_program()
    assert any(op.type == "layer_norm_grad" for op in main.global_block.ops)
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    (gx,) = exe.run(feed={"x": xn}, fetch_list=[grads[0]])

    # numeric check on a few coordinates
    def loss_np(xv):
        m = xv.mean(-1, keepdims=True)
        v = xv.var(-1, keepdims=True)
        yv = (xv - m) / np.sqrt(v + 1e-5)  # scale=1 bias=0 at init
        return float((yv ** 2).sum())

    eps = 1e-3
    for idx in [(0, 0, 5), (2, 3, 17), (3, 7, 31)]:
        xp = xn.copy(); xp[idx] += eps
        xm = xn.copy(); xm[idx] -= eps
        fd = (loss_np(xp) - loss_np(xm)) / (2 * eps)
        got = float(np.asarray(gx)[idx])
        np.testing.assert_allclose(got, fd, rtol=5e-2, atol=5e-3)


def test_layer_norm_training_converges_with_grad_op():
    """End-to-end: LN params actually learn through the dedicated grad."""
    rng = np.random.RandomState(4)
    xn = rng.randn(16, 64).astype("float32")
    target = rng.randn(64).astype("float32")
    x = fluid.data("x", [16, 64])
    t = fluid.data("t", [1, 64])
    y = layers.layer_norm(
        x, begin_norm_axis=1,
        param_attr=fluid.ParamAttr(name="s2"),
        bias_attr=fluid.ParamAttr(name="b2"),
    )
    loss = layers.reduce_mean(layers.square(y - t))
    fluid.optimizer.Adam(0.1).minimize(loss)
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    feed = {"x": xn, "t": target.reshape(1, 64)}
    vals = [
        float(np.asarray(exe.run(feed=feed, fetch_list=[loss])[0]))
        for _ in range(30)
    ]
    assert vals[-1] < vals[0] * 0.5, (vals[0], vals[-1])
