"""Async tiered checkpointing: the snapshot/publish pipeline
(fleet.AsyncCheckpointer), bounded-queue coalescing, delta chains +
row-oracle tiering + compression, the TrainGuard rollback/drain
lifecycle, and the heartbeat-during-publish liveness contract.

The end-to-end SIGKILL-mid-async-publish proof lives in
tools/resume_audit.py --async (run by the ci.sh chaos stage and by the
slow test at the bottom); these tests pin each layer in isolation.
"""

import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import errors, layers, observability
from paddle_tpu.fleet import collective as fc
from paddle_tpu.fleet.role_maker import UserDefinedRoleMaker
from paddle_tpu.framework import unique_name
from paddle_tpu.framework.scope import global_scope
from paddle_tpu.resilience import StepWatchdog, TrainGuard, faults

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)

HANG_ENV = "PADDLE_TPU_FAULT_HANG_SECONDS"


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.clear()
    old = os.environ.pop(HANG_ENV, None)
    yield
    faults.clear()
    if old is None:
        os.environ.pop(HANG_ENV, None)
    else:
        os.environ[HANG_ENV] = old


@pytest.fixture
def fresh_programs():
    main, startup = fluid.Program(), fluid.Program()
    scope = fluid.framework.scope.Scope()
    with fluid.program_guard(main, startup), fluid.scope_guard(scope), \
            unique_name.guard():
        yield main


def _build_model():
    x = fluid.data("x", [-1, 4])
    y = fluid.data("y", [-1, 1])
    pred = layers.fc(x, 1, param_attr=fluid.ParamAttr(name="ac_w"))
    loss = layers.mean(fluid.layers.square_error_cost(pred, y))
    fluid.optimizer.SGD(0.05).minimize(loss)
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    return exe, loss


def _fleet(rank=0, nranks=1):
    f = fc.Fleet()
    f.init(UserDefinedRoleMaker(current_id=rank, worker_num=nranks))
    return f


def _persistable_state():
    scope = global_scope()
    return {
        v.name: np.asarray(scope.find_var(v.name)).copy()
        for v in fluid.default_main_program().list_vars()
        if v.persistable and scope.find_var(v.name) is not None
    }


def _step(exe, loss, rng):
    xa = rng.randn(8, 4).astype(np.float32)
    exe.run(feed={"x": xa, "y": xa @ np.ones((4, 1), np.float32)},
            fetch_list=[loss])


def _counter(name):
    return observability.snapshot()["counters"].get(name, 0)


# -- the basic pipeline ------------------------------------------------------
def test_async_save_commits_bitwise_snapshot(tmp_path, fresh_programs):
    exe, loss = _build_model()
    fleet = _fleet()
    rng = np.random.RandomState(0)
    path = str(tmp_path / "ck")
    with fc.AsyncCheckpointer(fleet, path, executor=exe) as saver:
        _step(exe, loss, rng)
        want = _persistable_state()
        handle = saver.save(fc.TrainStatus(0, global_step=1))
        # the snapshot is immutable: training past the save must not
        # change what lands on disk
        _step(exe, loss, rng)
        assert handle.result(timeout=30) == 0
    status = fleet.load_check_point(exe, path)
    assert status.global_step == 1
    for name, arr in want.items():
        got = np.asarray(global_scope().find_var(name))
        assert got.tobytes() == arr.tobytes(), name
    h = observability.snapshot()["histograms"]
    assert h["checkpoint.snapshot_latency"]["count"] >= 1
    assert h["checkpoint.publish_latency"]["count"] >= 1
    assert h["checkpoint.save_bandwidth"]["count"] >= 1


def test_save_returns_before_slow_publish(tmp_path, fresh_programs):
    exe, loss = _build_model()
    fleet = _fleet()
    path = str(tmp_path / "ck")
    os.environ[HANG_ENV] = "1.5"
    saver = fc.AsyncCheckpointer(fleet, path, executor=exe)
    try:
        _step(exe, loss, np.random.RandomState(0))
        faults.inject("checkpoint.publish", "hang", 1.0, 0, 1)
        t0 = time.perf_counter()
        handle = saver.save(fc.TrainStatus(0, global_step=1))
        stall = time.perf_counter() - t0
        assert stall < 1.0, (
            f"save() blocked {stall:.2f}s — the publish hang leaked onto "
            "the step loop"
        )
        assert handle.result(timeout=30) == 0
    finally:
        saver.close()


def test_coalesce_keeps_newest_and_resolves_superseded(
    tmp_path, fresh_programs
):
    exe, loss = _build_model()
    fleet = _fleet()
    rng = np.random.RandomState(0)
    path = str(tmp_path / "ck")
    os.environ[HANG_ENV] = "0.4"
    saver = fc.AsyncCheckpointer(fleet, path, executor=exe,
                                 remain_all_checkpoint=True)
    try:
        # first publish is slowed: the three saves behind it land while
        # it is in flight, so the queue must coalesce them to one
        faults.inject("checkpoint.publish", "hang", 1.0, 0, 1)
        handles, states = [], []
        for i in range(4):
            _step(exe, loss, rng)
            states.append(_persistable_state())
            handles.append(saver.save(fc.TrainStatus(i, global_step=i + 1)))
        final = handles[0].result(timeout=30)
        # every handle resolves (superseded ones through their successor)
        results = [h.result(timeout=30) for h in handles]
        assert results[-1] == max(results)
        saver.wait(timeout=30)
    finally:
        saver.close()
    assert _counter("checkpoint.coalesced") >= 1
    # the NEWEST state is what the last commit carries
    status = fleet.load_check_point(exe, path)
    assert status.global_step == 4
    for name, arr in states[-1].items():
        got = np.asarray(global_scope().find_var(name))
        assert got.tobytes() == arr.tobytes(), name
    assert final is not None


def test_block_policy_publishes_every_save(tmp_path, fresh_programs):
    exe, loss = _build_model()
    fleet = _fleet()
    rng = np.random.RandomState(0)
    path = str(tmp_path / "ck")
    with fc.AsyncCheckpointer(fleet, path, executor=exe,
                              queue_policy="block",
                              remain_all_checkpoint=True) as saver:
        for i in range(3):
            _step(exe, loss, rng)
            saver.save(fc.TrainStatus(i, global_step=i + 1))
        saver.wait(timeout=30)
    dirs = [d for d in os.listdir(path) if d.startswith("__paddle_")]
    assert len(dirs) == 3, dirs


def test_publish_failure_surfaces_and_transient_heals(
    tmp_path, fresh_programs
):
    exe, loss = _build_model()
    fleet = _fleet()
    path = str(tmp_path / "ck")
    _step(exe, loss, np.random.RandomState(0))
    # one injected fault heals through the checkpoint.save retry policy
    faults.inject("checkpoint.publish", "io", 1.0, 0, 1)
    r0 = _counter("resilience.retries.checkpoint.save")
    with fc.AsyncCheckpointer(fleet, path, executor=exe) as saver:
        assert saver.save(fc.TrainStatus(0)).result(timeout=30) == 0
    assert _counter("resilience.retries.checkpoint.save") - r0 >= 1
    # a persistent fault exhausts the retries and must surface loudly
    faults.inject("checkpoint.publish", "io", 1.0, 0, 50)
    saver = fc.AsyncCheckpointer(fleet, str(tmp_path / "ck2"), executor=exe)
    handle = saver.save(fc.TrainStatus(0))
    with pytest.raises(errors.ExternalError):
        handle.result(timeout=30)
    with pytest.raises(errors.ExternalError):
        saver.wait(timeout=30)
    faults.clear()
    with pytest.raises(errors.ExternalError):
        saver.save(fc.TrainStatus(1))  # dead saver refuses new work
    assert _counter("checkpoint.publish_failures") >= 1


def test_snapshot_fault_seam_retries(tmp_path, fresh_programs):
    exe, loss = _build_model()
    fleet = _fleet()
    path = str(tmp_path / "ck")
    faults.inject("checkpoint.snapshot", "io", 1.0, 0, 1)
    r0 = _counter("resilience.retries.checkpoint.snapshot")
    with fc.AsyncCheckpointer(fleet, path, executor=exe) as saver:
        assert saver.save(fc.TrainStatus(0)).result(timeout=30) == 0
    assert _counter("resilience.retries.checkpoint.snapshot") - r0 >= 1


# -- tiered saves: delta chains, row oracles, compression --------------------
def test_delta_chain_roundtrip_and_forced_full(tmp_path, fresh_programs):
    exe, loss = _build_model()
    fleet = _fleet()
    rng = np.random.RandomState(0)
    path = str(tmp_path / "ck")
    with fc.AsyncCheckpointer(fleet, path, executor=exe, delta=True,
                              full_every=2, queue_policy="block",
                              remain_all_checkpoint=True) as saver:
        for i in range(4):
            _step(exe, loss, rng)
            saver.save(fc.TrainStatus(i, global_step=i + 1)).result(30)
        want = _persistable_state()
    kinds = {
        int(d.rsplit("__", 1)[-1]): os.path.exists(
            os.path.join(path, d, "delta.json")
        )
        for d in os.listdir(path) if d.startswith("__paddle_")
    }
    # 0 full, 1-2 delta chain, 3 forced full (chain never exceeds K=2)
    assert kinds == {0: False, 1: True, 2: True, 3: False}, kinds
    status = fleet.load_check_point(exe, path)
    assert status.global_step == 4
    for name, arr in want.items():
        got = np.asarray(global_scope().find_var(name))
        assert got.tobytes() == arr.tobytes(), name
    # an explicitly requested mid-chain delta reconstructs too
    assert fleet.load_check_point(exe, path, checkpoint_no=2).global_step == 3
    assert _counter("checkpoint.delta_saves") >= 2
    assert _counter("resilience.checkpoint_chain_loads") >= 1


def test_delta_broken_chain_falls_back(tmp_path, fresh_programs):
    import shutil

    exe, loss = _build_model()
    fleet = _fleet()
    rng = np.random.RandomState(0)
    path = str(tmp_path / "ck")
    with fc.AsyncCheckpointer(fleet, path, executor=exe, delta=True,
                              full_every=1, queue_policy="block",
                              remain_all_checkpoint=True) as saver:
        for i in range(4):  # 0 full, 1 delta, 2 full, 3 delta
            _step(exe, loss, rng)
            saver.save(fc.TrainStatus(i, global_step=i + 1)).result(30)
    # rot the newest delta's base away: candidate 3's chain is broken,
    # candidate 1's chain (0 -> 1) still loads
    shutil.rmtree(os.path.join(path, "__paddle_checkpoint__2"))
    b0 = _counter("resilience.checkpoint_chain_broken")
    status = fleet.load_check_point(exe, path)
    assert status.global_step == 2, status
    assert _counter("resilience.checkpoint_chain_broken") - b0 >= 1
    # an explicitly requested broken delta refuses instead of falling back
    with pytest.raises(
        (errors.ResumeMismatchError, errors.CheckpointCorruptionError)
    ):
        fleet.load_check_point(exe, path, checkpoint_no=3)


def test_rotation_spares_delta_chain_bases(tmp_path, fresh_programs):
    exe, loss = _build_model()
    fleet = _fleet()
    rng = np.random.RandomState(0)
    path = str(tmp_path / "ck")
    with fc.AsyncCheckpointer(fleet, path, executor=exe, delta=True,
                              full_every=3, queue_policy="block",
                              max_checkpoint_num=2) as saver:
        for i in range(4):  # 0 full, 1-3 deltas based (transitively) on 0
            _step(exe, loss, rng)
            saver.save(fc.TrainStatus(i, global_step=i + 1)).result(30)
        want = _persistable_state()
    present = sorted(
        int(d.rsplit("__", 1)[-1])
        for d in os.listdir(path) if d.startswith("__paddle_")
    )
    # rotation wanted to keep only {2, 3}, but their chain needs 0 and 1
    assert present == [0, 1, 2, 3], present
    status = fleet.load_check_point(exe, path)
    assert status.global_step == 4
    for name, arr in want.items():
        got = np.asarray(global_scope().find_var(name))
        assert got.tobytes() == arr.tobytes(), name


def test_row_oracle_delta_and_aux_roundtrip(tmp_path, fresh_programs):
    exe, loss = _build_model()
    fleet = _fleet()
    rng = np.random.RandomState(0)
    path = str(tmp_path / "ck")
    table = rng.randn(4096, 16).astype(np.float32)
    tick, dirty = [0], [np.array([], np.int64)]

    def oracle(last):
        mark = tick[0]
        if last is None:
            return None, mark
        return dirty[0], mark

    with fc.AsyncCheckpointer(
        fleet, path, executor=exe, delta=True, full_every=4,
        queue_policy="block", remain_all_checkpoint=True,
        row_oracles={"tab": oracle},
    ) as saver:
        tables = []
        for i in range(3):
            _step(exe, loss, rng)
            if i:
                rows = rng.choice(4096, 7, replace=False)
                table[rows] += 1.0
                dirty[0] = np.sort(rows.astype(np.int64))
                tick[0] += 1
            saver.save(fc.TrainStatus(i, global_step=i + 1),
                       aux={"tab": table}).result(30)
            dirty[0] = np.array([], np.int64)
            tables.append(table.copy())
    # the delta aux payloads carry only the dirty rows, not 4096x16
    full_aux = os.path.getsize(
        os.path.join(path, "__paddle_checkpoint__0", "__aux__.npz")
    )
    delta_aux = os.path.getsize(
        os.path.join(path, "__paddle_checkpoint__2", "__aux__.npz")
    )
    assert delta_aux < full_aux / 10, (full_aux, delta_aux)
    status = fleet.load_check_point(exe, path, load_aux=True)
    assert status.aux["tab"].tobytes() == tables[-1].tobytes()
    mid = fleet.load_check_point(exe, path, checkpoint_no=1, load_aux=True)
    assert mid.aux["tab"].tobytes() == tables[1].tobytes()


def test_compressed_payload_roundtrip_and_smaller(tmp_path, fresh_programs):
    exe, loss = _build_model()
    # a compressible ballast persistable (zeros) dominates the payload
    main = fluid.default_main_program()
    main.global_block.create_parameter("ac_ballast", [2048, 32], "float32")
    global_scope().set_var("ac_ballast", np.zeros((2048, 32), np.float32))
    fleet = _fleet()
    _step(exe, loss, np.random.RandomState(0))
    want = _persistable_state()
    plain, packed = str(tmp_path / "plain"), str(tmp_path / "packed")
    with fc.AsyncCheckpointer(fleet, plain, executor=exe) as saver:
        saver.save(fc.TrainStatus(0)).result(30)
    with fc.AsyncCheckpointer(fleet, packed, executor=exe,
                              compress=True) as saver:
        saver.save(fc.TrainStatus(0)).result(30)
    p0 = os.path.getsize(
        os.path.join(plain, "__paddle_checkpoint__0", "__params__.npz")
    )
    p1 = os.path.getsize(
        os.path.join(packed, "__paddle_checkpoint__0", "__params__.npz")
    )
    assert p1 < p0 / 2, (p0, p1)
    fleet.load_check_point(exe, packed)
    for name, arr in want.items():
        got = np.asarray(global_scope().find_var(name))
        assert got.tobytes() == arr.tobytes(), name


# -- lifecycle: rollback race + drain ----------------------------------------
def test_rollback_cancels_pending_awaits_inflight(tmp_path, fresh_programs):
    exe, loss = _build_model()
    fleet = _fleet()
    rng = np.random.RandomState(0)
    path = str(tmp_path / "ck")
    os.environ[HANG_ENV] = "0.6"
    saver = fc.AsyncCheckpointer(fleet, path, executor=exe,
                                 remain_all_checkpoint=True)
    try:
        _step(exe, loss, rng)
        saver.save(fc.TrainStatus(0, global_step=1)).result(30)
        # in-flight publish is slowed; a second snapshot queues behind it
        faults.inject("checkpoint.publish", "hang", 1.0, 0, 1)
        _step(exe, loss, rng)
        inflight_state = _persistable_state()
        inflight = saver.save(fc.TrainStatus(1, global_step=2))
        _step(exe, loss, rng)
        pending = saver.save(fc.TrainStatus(2, global_step=3))
        with TrainGuard(exe, checkpointer=saver, max_bad_steps=1,
                        snapshot=False) as g:
            bad = np.full((8, 4), np.nan, np.float32)
            out = g.step(feed={"x": bad, "y": np.ones((8, 1), np.float32)},
                         fetch_list=[loss])
        assert out is None and g.rollbacks == 1
        # the queued snapshot was cancelled, the in-flight one committed
        assert pending.cancelled
        with pytest.raises(errors.UnavailableError):
            pending.result(timeout=1)
        assert inflight.result(timeout=30) is not None
        # rollback restored the newest COMMITTED state (the in-flight
        # publish that quiesce awaited), not the cancelled one
        assert g.train_status.global_step == 2
        for name, arr in inflight_state.items():
            got = np.asarray(global_scope().find_var(name))
            assert got.tobytes() == arr.tobytes(), name
    finally:
        saver.close()
    assert _counter("checkpoint.cancelled") >= 1


def test_drain_awaits_async_final_checkpoint(tmp_path, fresh_programs):
    exe, loss = _build_model()
    fleet = _fleet()
    path = str(tmp_path / "ck")
    os.environ[HANG_ENV] = "0.5"
    saver = fc.AsyncCheckpointer(fleet, path, executor=exe,
                                 remain_all_checkpoint=True)
    try:
        faults.inject("checkpoint.publish", "hang", 1.0, 0, 1)
        with TrainGuard(exe, checkpointer=saver, exit_on_preempt=False,
                        train_status=fc.TrainStatus(3, global_step=7)) as g:
            _step(exe, loss, np.random.RandomState(0))
            g.draining = True  # what the SIGTERM handler sets
            assert g.step(feed={"x": np.ones((8, 4), np.float32),
                                "y": np.ones((8, 1), np.float32)},
                          fetch_list=[loss]) is None
        assert g.preempted
        # by the time the drain returned, the final checkpoint is
        # COMMITTED despite the slowed publish — never half-published
        status = fleet.load_check_point(exe, path)
        assert status.global_step == 7
    finally:
        saver.close()


# -- heartbeat during publish (satellite regression) -------------------------
def test_slow_sync_publish_starves_watchdog_without_heartbeat(
    tmp_path, fresh_programs
):
    exe, _ = _build_model()
    fleet = _fleet()
    os.environ[HANG_ENV] = "1.2"
    faults.inject("fs.upload", "hang", 1.0, 0, 1)
    with StepWatchdog(timeout=0.4, poll_interval=0.05) as wd:
        fleet.save_check_point(exe, str(tmp_path / "ck"), fc.TrainStatus(0))
    assert wd.stalls >= 1  # the failure mode the heartbeat fixes


def test_slow_sync_publish_with_heartbeat_never_reads_as_hang(
    tmp_path, fresh_programs
):
    exe, _ = _build_model()
    fleet = _fleet()
    os.environ[HANG_ENV] = "2.0"
    faults.inject("fs.upload", "hang", 1.0, 0, 1)
    with StepWatchdog(timeout=0.8, poll_interval=0.05) as wd:
        fleet.save_check_point(exe, str(tmp_path / "ck"), fc.TrainStatus(0),
                               heartbeat=wd.touch)
    assert wd.stalls == 0


def test_slow_async_publish_with_heartbeat_never_reads_as_hang(
    tmp_path, fresh_programs
):
    exe, loss = _build_model()
    fleet = _fleet()
    os.environ[HANG_ENV] = "2.0"
    faults.inject("fs.upload", "hang", 1.0, 0, 1)
    with StepWatchdog(timeout=0.8, poll_interval=0.05) as wd:
        with fc.AsyncCheckpointer(fleet, str(tmp_path / "ck"), executor=exe,
                                  heartbeat=wd.touch) as saver:
            saver.save(fc.TrainStatus(0)).result(timeout=30)
    assert wd.stalls == 0
    assert _counter("resilience.faults_injected.fs.upload") >= 1


def test_heartbeat_touch_is_thread_safe_and_keeps_step(tmp_path):
    from paddle_tpu.resilience.health import Heartbeat, read_beat

    hb = Heartbeat(str(tmp_path / "hb"), rank=0)
    hb.beat()
    t0 = read_beat(hb.path)
    time.sleep(0.01)
    hb.touch()
    t1 = read_beat(hb.path)
    assert t1["step"] == t0["step"] == 1
    assert t1["time"] > t0["time"]


# -- the full kill/resume-mid-async-publish audit (slow) ---------------------
@pytest.mark.slow
def test_async_resume_audit_end_to_end(tmp_path):
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "resume_audit.py"),
         "--async", "--out", str(tmp_path / "audit")],
        env=env, capture_output=True, text=True, timeout=600,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "resume audit OK" in proc.stdout
