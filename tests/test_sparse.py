"""Sparse / PS path: row-sharded embedding tables over the "ps" axis.

VERDICT item 6 done-bar: a CTR model with an embedding bigger than one
device's share trains on the virtual mesh. Modeled on the reference's
dist_fleet_ctr / test_dist_ctr suites (which compared distributed vs local
losses for a sparse model).
"""

import numpy as np
import pytest

import jax

import paddle_tpu as fluid
from paddle_tpu import layers
from paddle_tpu.framework import unique_name
from paddle_tpu.models import DeepFMConfig, deepfm
from paddle_tpu.parallel import shard_program, shard_sparse_tables
from paddle_tpu.parallel.mesh import make_mesh


@pytest.fixture(autouse=True)
def fresh_programs():
    main, startup = fluid.Program(), fluid.Program()
    scope = fluid.framework.scope.Scope()
    with fluid.program_guard(main, startup), fluid.scope_guard(scope), \
            unique_name.guard():
        yield main, startup, scope


def _lookup_program(vocab, dim, b):
    ids = fluid.data("ids", [b], "int64")
    out = layers.sparse_embedding(
        ids, [vocab, dim], param_attr=fluid.ParamAttr(name="table"),
        pad_to_multiple=8,
    )
    return ids, out


def test_sharded_lookup_matches_local():
    """distributed_lookup_table over ps=8 returns the same rows as the
    unsharded gather."""
    vocab, dim, b = 64, 4, 16
    rng = np.random.RandomState(0)
    idv = rng.randint(0, vocab, b).astype(np.int64)

    outs = {}
    for mode in ("local", "sharded"):
        main, startup = fluid.Program(), fluid.Program()
        main.random_seed = startup.random_seed = 3
        scope = fluid.framework.scope.Scope()
        with fluid.program_guard(main, startup), fluid.scope_guard(scope), \
                unique_name.guard():
            _, out = _lookup_program(vocab, dim, b)
            if mode == "sharded":
                shard_sparse_tables(main)
                shard_program(main, make_mesh({"ps": 8}))
            exe = fluid.Executor()
            exe.run(startup)
            (v,) = exe.run(feed={"ids": idv}, fetch_list=[out])
            outs[mode] = np.asarray(v)
    np.testing.assert_allclose(outs["local"], outs["sharded"], rtol=1e-6)


def test_sharded_lookup_grads_match_local():
    """Backward through the psum-gather scatter-adds into the owning shard
    with the same magnitude as the local gather."""
    vocab, dim, b = 32, 4, 8
    rng = np.random.RandomState(0)
    idv = rng.randint(0, vocab, b).astype(np.int64)

    grads = {}
    for mode in ("local", "sharded"):
        main, startup = fluid.Program(), fluid.Program()
        main.random_seed = startup.random_seed = 3
        scope = fluid.framework.scope.Scope()
        with fluid.program_guard(main, startup), fluid.scope_guard(scope), \
                unique_name.guard():
            _, out = _lookup_program(vocab, dim, b)
            loss = layers.reduce_sum(layers.square(out))
            fluid.optimizer.SGD(0.0).minimize(loss)  # lr 0: params frozen
            if mode == "sharded":
                shard_sparse_tables(main)
                shard_program(main, make_mesh({"ps": 8}))
            exe = fluid.Executor()
            exe.run(startup)
            (g,) = exe.run(feed={"ids": idv}, fetch_list=["table@GRAD"])
            grads[mode] = np.asarray(g)
    np.testing.assert_allclose(grads["local"], grads["sharded"], rtol=1e-5)


def test_table_state_is_actually_sharded():
    """Each device holds only vocab/8 rows of the table and its Adam
    moments (the huge-embedding property)."""
    vocab, dim, b = 80, 8, 4
    ids, out = _lookup_program(vocab, dim, b)
    loss = layers.reduce_sum(out)
    fluid.optimizer.Adam(0.01).minimize(loss)
    shard_sparse_tables(fluid.default_main_program())
    shard_program(fluid.default_main_program(), make_mesh({"ps": 8}))
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    exe.run(feed={"ids": np.arange(b).astype(np.int64)}, fetch_list=[loss])
    scope = fluid.framework.scope.global_scope()
    table = scope.find_var("table")
    shards = table.addressable_shards
    assert len(shards) == 8
    assert shards[0].data.shape[0] == table.shape[0] // 8
    # Adam moment accumulators sharded the same way
    m1 = scope.find_var(
        [n for n in fluid.default_main_program().global_block.vars
         if n.startswith("table_moment1")][0]
    )
    assert m1.addressable_shards[0].data.shape[0] == table.shape[0] // 8


def test_deepfm_trains_on_virtual_mesh():
    """DeepFM with sharded tables learns a separable CTR toy problem."""
    cfg = DeepFMConfig(vocab_size=4096, num_fields=6, embed_dim=8,
                       mlp_sizes=(32,))
    b = 32
    ids = fluid.data("feat_ids", [b, cfg.num_fields], "int64")
    label = fluid.data("label", [b, 1], "float32")
    loss, predict = deepfm(ids, label, cfg)
    fluid.optimizer.Adam(0.01).minimize(loss)
    shard_sparse_tables(fluid.default_main_program())
    shard_program(fluid.default_main_program(), make_mesh({"ps": 8}))

    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())

    rng = np.random.RandomState(0)
    # clickiness is determined by whether field-0's id is even
    def batch():
        idv = rng.randint(0, cfg.vocab_size, (b, cfg.num_fields))
        lab = (idv[:, :1] % 2 == 0).astype(np.float32)
        return {"feat_ids": idv.astype(np.int64), "label": lab}

    losses = []
    feeds = [batch() for _ in range(8)]
    for epoch in range(30):
        for f in feeds:
            (lv,) = exe.run(feed=f, fetch_list=[loss])
            losses.append(float(np.asarray(lv).reshape(-1)[0]))
    assert losses[-1] < losses[0] * 0.5, (losses[0], losses[-1])


def test_fleet_ps_mode_api():
    """Fleet PS facade: init -> distributed_optimizer -> minimize shards the
    tables and trains (reference test_dist_fleet_base shape)."""
    from paddle_tpu.fleet.parameter_server import StrategyFactory, fleet

    cfg = DeepFMConfig(vocab_size=1024, num_fields=4, embed_dim=4,
                       mlp_sizes=(16,))
    b = 16
    ids = fluid.data("feat_ids", [b, cfg.num_fields], "int64")
    label = fluid.data("label", [b, 1], "float32")
    loss, _ = deepfm(ids, label, cfg)
    fleet.init()
    opt = fleet.distributed_optimizer(
        fluid.optimizer.Adam(0.02), StrategyFactory.create_sync_strategy()
    )
    opt.minimize(loss)
    assert fleet.worker_num() == 8
    assert fleet.sparse_table_names() == ["deepfm_w1", "deepfm_emb"]

    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    rng = np.random.RandomState(1)
    idv = rng.randint(0, cfg.vocab_size, (b, cfg.num_fields))
    feed = {"feat_ids": idv.astype(np.int64),
            "label": (idv[:, :1] % 2 == 0).astype(np.float32)}
    losses = []
    for _ in range(40):
        (lv,) = exe.run(feed=feed, fetch_list=[loss])
        losses.append(float(np.asarray(lv).reshape(-1)[0]))
    assert losses[-1] < losses[0] * 0.5
