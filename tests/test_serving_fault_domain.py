"""Serving fault domain tests (r15): deadline propagation, priority load
shedding + brownout ladder, circuit-broken replica failover, and the
pro-rated Server.drain budget.

Everything here runs on executor-free stub runners (the queue/batcher/
breaker machinery without XLA in the loop) so the suite stays fast; the
end-to-end frozen-graph legs live in bench_serving.py's ``overload`` and
``failover`` mixes, gated by ci.sh's serving-chaos stage."""

import os
import time

import numpy as np
import pytest

from paddle_tpu import errors, observability
from paddle_tpu.errors import (
    DeadlineExceededError,
    InvalidArgumentError,
    PreconditionNotMetError,
    RequestShedError,
)
from paddle_tpu.resilience import faults
from paddle_tpu.serving import Server
from paddle_tpu.serving.brownout import DEFAULT_LADDER, BrownoutController
from paddle_tpu.serving.replica import ReplicaSet
from paddle_tpu.serving.router import (
    BACKGROUND,
    BATCH,
    INTERACTIVE,
    Endpoint,
    EndpointConfig,
)


class _StubRunner:
    """Executor-free runner: doubles its input; optional per-batch delay
    and forced failure. Records the first feed column of every batch so
    tests can assert WHAT was dispatched, not just how much."""

    feed_names = ("x",)

    def __init__(self, delay=0.0, name="stub"):
        self.delay = delay
        self.name = name
        self.fail_with = None
        self.batches = []  # list of row-0 values per dispatched batch

    def sample_spec(self, name):
        return (2,), "float32"

    def run(self, feed):
        if self.fail_with is not None:
            raise self.fail_with
        if self.delay:
            time.sleep(self.delay)
        self.batches.append([float(row[0]) for row in feed["x"]])
        return [feed["x"] * 2.0]


def _feed(v=0.0):
    """One SAMPLE (no batch axis) — the Endpoint.submit shape."""
    return {"x": np.full(2, v, np.float32)}


def _bfeed(v=0.0, n=1):
    """One BATCH (batch-leading) — the shape runners/ReplicaSet.run see."""
    return {"x": np.full((n, 2), v, np.float32)}


def _counter(name):
    return observability.get_counters().get(name, 0)


# ---------------------------------------------------------------------------
# deadline propagation
# ---------------------------------------------------------------------------


def test_expired_request_resolves_typed_and_never_dispatches():
    runner = _StubRunner(delay=0.15)
    ep = Endpoint("exp", runner, EndpointConfig(buckets=(1,),
                                                max_wait_ms=0.0))
    c0 = _counter("serving.expired")
    blocker = ep.submit(_feed(1.0))  # occupies the runner
    doomed = ep.submit(_feed(2.0), deadline_ms=30)
    with pytest.raises(DeadlineExceededError):
        doomed.result(timeout=5)
    blocker.result(timeout=5)
    assert ep.drain(timeout=5)
    assert _counter("serving.expired") == c0 + 1
    assert _counter("serving.expired.exp") == 1
    # the expired request never padded a bucket or burned a dispatch
    assert [1.0] in runner.batches and all(
        2.0 not in b for b in runner.batches
    ), runner.batches


def test_expired_requests_never_pad_the_surviving_batch():
    """Bucket formation after an expiry wave carries ONLY live work."""
    runner = _StubRunner(delay=0.12)
    ep = Endpoint("pad", runner,
                  EndpointConfig(buckets=(4,), max_wait_ms=1.0))
    blocker = ep.submit(_feed(9.0))
    time.sleep(0.03)  # the blocker dispatches ALONE and occupies the runner
    doomed = [ep.submit(_feed(1.0), deadline_ms=25) for _ in range(2)]
    live = [ep.submit(_feed(5.0)) for _ in range(2)]
    time.sleep(0.05)  # both deadlines pass while the blocker runs
    for f in doomed:
        with pytest.raises(DeadlineExceededError):
            f.result(timeout=5)
    for f in live:
        np.testing.assert_array_equal(
            f.result(timeout=5)[0], np.full(2, 10.0)
        )
    blocker.result(timeout=5)
    ep.drain(timeout=5)
    # the survivors' batch is zero-PADDED to the bucket, never padded
    # with expired requests' rows
    assert [5.0, 5.0, 0.0, 0.0] in runner.batches, runner.batches
    assert all(1.0 not in b for b in runner.batches), runner.batches


def test_batch_former_wait_clamped_to_tightest_deadline():
    """A lonely request with an 80ms budget must not sit out the full
    5s max_wait waiting for bucket-8 co-batching."""
    runner = _StubRunner()
    ep = Endpoint("clamp", runner,
                  EndpointConfig(buckets=(8,), max_wait_ms=5000.0))
    t0 = time.perf_counter()
    fut = ep.submit(_feed(3.0), deadline_ms=80)
    out = fut.result(timeout=3)[0]
    waited = time.perf_counter() - t0
    ep.drain(timeout=5)
    np.testing.assert_array_equal(out, np.full(2, 6.0))
    assert waited < 0.5, f"dispatch waited {waited:.3f}s past the deadline"
    assert _counter("serving.goodput.clamp") >= 1


def test_goodput_vs_late_split():
    """A dispatch that outlives the deadline still resolves with its
    result, but counts as late, not goodput."""
    runner = _StubRunner(delay=0.08)
    ep = Endpoint("good", runner,
                  EndpointConfig(buckets=(1,), max_wait_ms=0.0))
    late = ep.submit(_feed(1.0), deadline_ms=20)  # expires mid-dispatch
    ok = ep.submit(_feed(2.0))
    late.result(timeout=5), ok.result(timeout=5)
    ep.drain(timeout=5)
    assert _counter("serving.late_completions.good") == 1
    assert _counter("serving.goodput.good") == 1


def test_submit_validation():
    ep = Endpoint("val", _StubRunner(), EndpointConfig(buckets=(1,)))
    try:
        with pytest.raises(InvalidArgumentError):
            ep.submit(_feed(), deadline_ms=0)
        with pytest.raises(InvalidArgumentError):
            ep.submit(_feed(), deadline_ms=-5)
        with pytest.raises(InvalidArgumentError):
            ep.submit(_feed(), priority=-1)
    finally:
        ep.drain(timeout=5)


# ---------------------------------------------------------------------------
# priority classes + shedding
# ---------------------------------------------------------------------------


def test_queue_pressure_sheds_lowest_class_first():
    runner = _StubRunner(delay=0.1)
    ep = Endpoint("shed", runner,
                  EndpointConfig(buckets=(1,), max_wait_ms=0.0,
                                 max_queue=2))
    blocker = ep.submit(_feed(9.0))
    time.sleep(0.02)  # scheduler takes the blocker; queue now empty
    bg_old = ep.submit(_feed(1.0), priority=BACKGROUND)
    bg_young = ep.submit(_feed(2.0), priority=BACKGROUND)
    hi = ep.submit(_feed(3.0), priority=INTERACTIVE)  # evicts bg_young
    with pytest.raises(RequestShedError):
        bg_young.result(timeout=5)
    np.testing.assert_array_equal(
        hi.result(timeout=10)[0], np.full(2, 6.0)
    )
    bg_old.result(timeout=10)
    blocker.result(timeout=5)
    ep.drain(timeout=10)
    assert _counter("serving.shed.shed") == 1
    assert _counter("serving.shed_class.background") == 1


def test_queue_full_same_class_still_rejects():
    runner = _StubRunner(delay=0.1)
    ep = Endpoint("rej", runner,
                  EndpointConfig(buckets=(1,), max_wait_ms=0.0,
                                 max_queue=1))
    blocker = ep.submit(_feed())
    time.sleep(0.02)
    filler = ep.submit(_feed(), priority=BATCH)
    c0 = _counter("serving.rejected")
    with pytest.raises(PreconditionNotMetError) as ei:
        ep.submit(_feed(), priority=BATCH)  # nothing lower-class queued
    assert not isinstance(ei.value, RequestShedError)
    assert _counter("serving.rejected") == c0 + 1
    blocker.result(timeout=5), filler.result(timeout=5)
    ep.drain(timeout=5)


def test_batches_form_in_priority_order():
    """An interactive arrival jumps ahead of earlier-queued background
    work at batch formation (FIFO within a class)."""
    runner = _StubRunner(delay=0.08)
    ep = Endpoint("prio", runner,
                  EndpointConfig(buckets=(2,), max_wait_ms=0.0))
    blocker = ep.submit(_feed(9.0))
    time.sleep(0.02)
    bg = [ep.submit(_feed(float(i)), priority=BACKGROUND)
          for i in (1, 2, 3)]
    hi = ep.submit(_feed(7.0), priority=INTERACTIVE)
    for f in bg + [hi, blocker]:
        f.result(timeout=10)
    ep.drain(timeout=10)
    # first post-blocker batch: the interactive request leads, then the
    # OLDEST background; the remaining background pair follows
    assert runner.batches[1] == [7.0, 1.0], runner.batches
    assert runner.batches[2] == [2.0, 3.0], runner.batches


# ---------------------------------------------------------------------------
# brownout ladder
# ---------------------------------------------------------------------------


def test_brownout_ladder_escalates_and_rearms():
    ep = Endpoint("bo", _StubRunner(),
                  EndpointConfig(buckets=(1, 2, 4, 8), max_wait_ms=40.0))
    server_like = {"bo": ep}

    class _S:
        def endpoints(self):
            return server_like

    ctl = BrownoutController(_S(), slo_p99_s=0.1, escalate_after=2,
                             recover_after=3)
    try:
        assert ctl.level == 0
        ctl.observe(p99=0.5)
        assert ctl.level == 0, "one breach observation must not escalate"
        ctl.observe(p99=0.5)
        assert ctl.level == 1 and ep._wait_scale == 0.5
        for _ in range(2):
            ctl.observe(p99=0.5)
        assert ctl.level == 2 and ep._shed_priority == BACKGROUND
        with pytest.raises(RequestShedError):
            ep.submit(_feed(), priority=BACKGROUND)
        assert _counter("serving.shed_class.background") >= 1
        # batch class still admitted at rung 2, shed at rung 3
        ep.submit(_feed(1.0), priority=BATCH).result(timeout=5)
        for _ in range(2):
            ctl.observe(p99=0.5)
        assert ctl.level == 3 and ep._shed_priority == BATCH
        with pytest.raises(RequestShedError):
            ep.submit(_feed(), priority=BATCH)
        assert ep._bucket_cap is None, (
            "capacity-reducing bucket cap must come AFTER shedding"
        )
        # rung 4 — the last-ditch bucket cap
        for _ in range(2):
            ctl.observe(p99=0.5)
        assert ctl.level == 4
        assert ep._wait_scale == 0.25
        assert ep._bucket_cap == 2  # lower half of (1, 2, 4, 8)
        assert ep._effective_buckets() == (1, 2)
        # interactive still admitted at the top rung
        ep.submit(_feed(1.0), priority=INTERACTIVE).result(timeout=5)
        # recovery walks the ladder back down with hysteresis
        for _ in range(2):
            ctl.observe(p99=0.01)
        assert ctl.level == 4, "recovery must be sustained, not one tick"
        for _ in range(16):
            ctl.observe(p99=0.01)
        assert ctl.level == 0
        assert ep._wait_scale == 1.0 and ep._bucket_cap is None
        ep.submit(_feed(2.0), priority=BACKGROUND).result(timeout=5)
        g = observability.get_gauges()
        assert g.get("serving.brownout_level") == 0.0
        assert g.get("serving.brownout_level.bo") == 0.0
        assert _counter("serving.brownout_escalations") == 4
        assert _counter("serving.brownout_recoveries") == 4
    finally:
        ep.drain(timeout=5)


def test_watcher_slo_breach_drives_brownout_both_directions():
    """The satellite contract: a REAL Watcher over the latency histogram
    latches slo_breach -> the controller escalates; recovery re-arms the
    watcher AND walks the controller back down."""
    from paddle_tpu.observability.watch import Watcher

    metric = "serving.request_latency.bo2"
    ep = Endpoint("bo2", _StubRunner(),
                  EndpointConfig(buckets=(1, 2), max_wait_ms=5.0))

    class _S:
        def endpoints(self):
            return {"bo2": ep}

    watcher = Watcher(latency_metric=metric, slo_p99_s=0.05)
    ctl = BrownoutController(_S(), slo_p99_s=0.05, watcher=watcher,
                             escalate_after=1, recover_after=2)
    try:
        # breach window: p99 ~ 0.25s >> 50ms SLO
        for _ in range(40):
            observability.observe(metric, 0.2)
        ctl.poll()
        assert watcher.breaching
        assert _counter("watch.findings.slo_breach") >= 1
        assert ctl.level >= 1
        level_after_breach = ctl.level
        # recovery windows: p99 ~ 1ms; the watcher re-arms its latch and
        # the gauge it maintains drives the controller back to 0
        for _ in range(8):
            for _ in range(40):
                observability.observe(metric, 0.001)
            ctl.poll()
        assert not watcher.breaching
        assert ctl.level == 0 < level_after_breach
        # a SECOND excursion latches a fresh finding (re-armed)
        for _ in range(40):
            observability.observe(metric, 0.2)
        ctl.poll()
        assert watcher.breaching and ctl.level >= 1
        assert _counter("watch.findings.slo_breach") >= 2
    finally:
        ep.drain(timeout=5)


def test_default_ladder_shape():
    assert DEFAULT_LADDER[0] == {"wait_scale": 1.0, "bucket_frac": 1.0,
                                 "shed_priority": None}
    # shedding (demand reduction) strictly precedes the bucket cap
    # (capacity reduction): the first capped rung must already shed
    first_capped = next(
        r for r in DEFAULT_LADDER if r["bucket_frac"] < 1.0
    )
    assert first_capped["shed_priority"] is not None
    assert DEFAULT_LADDER[2]["shed_priority"] == BACKGROUND
    assert DEFAULT_LADDER[-1]["shed_priority"] == BATCH
    with pytest.raises(InvalidArgumentError):
        BrownoutController(object(), ladder=(DEFAULT_LADDER[0],))


# ---------------------------------------------------------------------------
# replica failover
# ---------------------------------------------------------------------------


def test_breaker_opens_after_threshold_and_fails_over():
    a, b = _StubRunner(name="a"), _StubRunner(name="b")
    rs = ReplicaSet({"a": a, "b": b}, breaker_threshold=2, cooldown_s=60)
    ep = Endpoint("fo", rs, EndpointConfig(buckets=(2,), max_wait_ms=2.0))
    ep.submit(_feed(0.0)).result(timeout=5)
    a.fail_with = errors.UnavailableError("replica died")
    c0 = _counter("serving.requeued")
    futs = [ep.submit(_feed(float(i))) for i in range(6)]
    for f in futs:
        f.result(timeout=10)  # every request resolves despite the kill
    ep.drain(timeout=10)
    assert rs.states()["a"] == "open"
    g = observability.get_gauges()
    assert g.get("serving.breaker_state.a") == 1.0
    assert g.get("serving.breaker_state.b") == 0.0
    assert _counter("serving.requeued") > c0
    assert _counter("serving.breaker_opened.a") == 1


def test_half_open_probe_closes_breaker_on_recovery():
    clock = [0.0]
    a, b = _StubRunner(name="a"), _StubRunner(name="b")
    rs = ReplicaSet({"a": a, "b": b}, breaker_threshold=1, cooldown_s=5.0,
                    clock=lambda: clock[0])
    a.fail_with = errors.UnavailableError("down")
    rs.run(_bfeed(1.0), request_ids=[1])  # fails over a->b, opens a
    assert rs.states()["a"] == "open"
    rs.run(_bfeed(2.0), request_ids=[2])  # a still cooling: b serves
    assert rs.states()["a"] == "open"
    clock[0] += 6.0
    a.fail_with = None  # replica healed
    rs.run(_bfeed(3.0), request_ids=[3])  # the half-open probe
    assert rs.states()["a"] == "closed"
    assert observability.get_gauges().get("serving.breaker_state.a") == 0.0
    assert _counter("serving.breaker_closed.a") == 1


def test_half_open_probe_failure_reopens():
    clock = [0.0]
    a, b = _StubRunner(name="a"), _StubRunner(name="b")
    rs = ReplicaSet({"a": a, "b": b}, breaker_threshold=1, cooldown_s=5.0,
                    clock=lambda: clock[0])
    a.fail_with = errors.UnavailableError("down")
    rs.run(_bfeed(1.0), request_ids=[1])
    clock[0] += 6.0
    rs.run(_bfeed(2.0), request_ids=[2])  # probe fails -> re-open + reroute
    assert rs.states()["a"] == "open"
    assert observability.get_gauges().get("serving.breaker_state.a") == 1.0
    clock[0] += 3.0  # cooldown restarts at the failed probe
    rs.run(_bfeed(3.0), request_ids=[3])
    assert rs.states()["a"] == "open", "cooldown must restart on re-open"


def test_failover_is_exactly_once_per_request_id():
    a, b = _StubRunner(name="a"), _StubRunner(name="b")
    rs = ReplicaSet({"a": a, "b": b}, breaker_threshold=99, cooldown_s=0.0)
    a.fail_with = errors.UnavailableError("down")
    rs.run(_bfeed(1.0, n=2), request_ids=[11, 12])  # a->b, re-route spent
    assert [1.0, 1.0] in b.batches
    # ids 12/13 fail on a again: the failure must surface TYPED instead
    # of re-routing a second time (12 already spent its one re-route)
    with pytest.raises(errors.UnavailableError):
        rs.run(_bfeed(2.0, n=2), request_ids=[12, 13])
    assert [2.0, 2.0] not in b.batches, (
        "a second re-route executed the batch again"
    )


def test_both_replicas_down_surfaces_typed_error():
    a, b = _StubRunner(name="a"), _StubRunner(name="b")
    rs = ReplicaSet({"a": a, "b": b}, breaker_threshold=1, cooldown_s=60)
    a.fail_with = errors.UnavailableError("a down")
    b.fail_with = errors.UnavailableError("b down")
    with pytest.raises(errors.UnavailableError):
        rs.run(_bfeed(), request_ids=[1])
    assert rs.states() == {"a": "open", "b": "open"}
    # and with every breaker open, the next call refuses immediately
    with pytest.raises(errors.UnavailableError):
        rs.run(_bfeed(), request_ids=[2])


def test_replica_set_validates_feed_names():
    class _Other(_StubRunner):
        feed_names = ("y",)

    with pytest.raises(InvalidArgumentError):
        ReplicaSet({"a": _StubRunner(), "b": _Other()})
    with pytest.raises(InvalidArgumentError):
        ReplicaSet({})
    with pytest.raises(InvalidArgumentError):
        ReplicaSet({"a": _StubRunner()}, breaker_threshold=0)


def test_heartbeat_informed_health(tmp_path):
    from paddle_tpu.resilience.health import Heartbeat

    hb_dir = str(tmp_path)
    hb_a = Heartbeat(hb_dir, rank=0)
    hb_b = Heartbeat(hb_dir, rank=1)
    hb_a.beat(), hb_b.beat()
    a, b = _StubRunner(name="a"), _StubRunner(name="b")
    rs = ReplicaSet(
        {"a": a, "b": b},
        heartbeats={"a": hb_a.path, "b": hb_b.path},
        heartbeat_timeout=0.2,
    )
    rs.run(_bfeed(1.0), request_ids=[1])
    time.sleep(0.3)
    hb_b.touch()  # only b stays fresh; a's beat goes stale
    for i in range(4):
        rs.run(_bfeed(float(i)), request_ids=[10 + i])
    assert not any(
        batch for batch in a.batches[1:]
    ), "stale-beat replica kept receiving dispatches"
    assert len(b.batches) >= 3


def test_replica_drain_keeps_set_live():
    a, b = _StubRunner(name="a"), _StubRunner(name="b")
    rs = ReplicaSet({"a": a, "b": b})
    ep = Endpoint("pd", rs, EndpointConfig(buckets=(1,), max_wait_ms=0.0))
    ep.submit(_feed(1.0)).result(timeout=5)
    assert rs.drain_replica("a") is True
    assert rs.states()["a"] == "draining"
    for i in range(3):
        ep.submit(_feed(float(i))).result(timeout=5)
    assert len(b.batches) >= 3, "set did not stay live on the survivor"
    assert len(a.batches) == 1
    rs.restore_replica("a")
    assert rs.states()["a"] == "closed"
    ep.submit(_feed(5.0)).result(timeout=5)
    ep.drain(timeout=5)
    assert _counter("serving.replica_drains") == 1


def test_warmup_warms_every_replica():
    a, b = _StubRunner(name="a"), _StubRunner(name="b")
    rs = ReplicaSet({"a": a, "b": b})
    ep = Endpoint("warm", rs,
                  EndpointConfig(buckets=(1, 2, 4), max_wait_ms=1.0))
    ep.warmup()
    ep.drain(timeout=5)
    assert len(a.batches) == 3 and len(b.batches) == 3, (
        "a cold standby pays its compiles during failover"
    )


# ---------------------------------------------------------------------------
# the serving.dispatch fault seam
# ---------------------------------------------------------------------------


def test_dispatch_fault_fails_plain_endpoint_batch_typed():
    runner = _StubRunner()
    ep = Endpoint("seam", runner,
                  EndpointConfig(buckets=(1,), max_wait_ms=0.0))
    faults.inject("serving.dispatch", "io", prob=1.0, seed=0, max_fires=1)
    try:
        f1 = ep.submit(_feed(1.0))
        with pytest.raises(errors.ExternalError):
            f1.result(timeout=5)
        ep.submit(_feed(2.0)).result(timeout=5)  # seam healed
    finally:
        faults.clear("serving.dispatch")
        ep.drain(timeout=5)
    assert _counter("resilience.faults_injected.serving.dispatch") == 1
    assert _counter("serving.request_errors") >= 1


def test_dispatch_fault_heals_through_failover():
    a, b = _StubRunner(name="a"), _StubRunner(name="b")
    rs = ReplicaSet({"a": a, "b": b}, breaker_threshold=3, cooldown_s=60)
    ep = Endpoint("heal", rs, EndpointConfig(buckets=(1,),
                                             max_wait_ms=0.0))
    faults.inject("serving.dispatch", "io", prob=1.0, seed=0, max_fires=1)
    try:
        out = ep.submit(_feed(3.0)).result(timeout=5)[0]
        np.testing.assert_array_equal(out, np.full(2, 6.0))
    finally:
        faults.clear("serving.dispatch")
        ep.drain(timeout=5)
    assert _counter("serving.requeued") >= 1


def test_per_replica_seam_targets_one_replica():
    a, b = _StubRunner(name="a"), _StubRunner(name="b")
    rs = ReplicaSet({"a": a, "b": b}, breaker_threshold=1, cooldown_s=60)
    faults.inject("serving.dispatch.a", "unavailable", prob=1.0, seed=0)
    try:
        for i in range(4):
            rs.run(_bfeed(float(i)), request_ids=[i])
        assert rs.states()["a"] == "open"
        assert len(b.batches) == 4
    finally:
        faults.clear("serving.dispatch.a")


def test_dispatch_hang_bounded_by_attempt_timeout():
    """A hung replica dispatch surfaces as a typed timeout after
    attempt_timeout and the batch fails over — the scheduler thread is
    never wedged for the hang duration."""
    a, b = _StubRunner(name="a"), _StubRunner(name="b")
    rs = ReplicaSet({"a": a, "b": b}, breaker_threshold=1, cooldown_s=60,
                    attempt_timeout=0.3)
    ep = Endpoint("hang", rs, EndpointConfig(buckets=(1,),
                                             max_wait_ms=0.0))
    os.environ[faults.HANG_SECONDS_ENV] = "5"
    faults.inject("serving.dispatch.a", "hang", prob=1.0, seed=0,
                  max_fires=1)
    try:
        t0 = time.perf_counter()
        out = ep.submit(_feed(4.0)).result(timeout=10)[0]
        dt = time.perf_counter() - t0
        np.testing.assert_array_equal(out, np.full(2, 8.0))
        assert dt < 3.0, f"hang was not bounded ({dt:.1f}s)"
        assert rs.states()["a"] == "open"
    finally:
        os.environ.pop(faults.HANG_SECONDS_ENV, None)
        faults.clear("serving.dispatch.a")
        ep.drain(timeout=10)


# ---------------------------------------------------------------------------
# drain semantics
# ---------------------------------------------------------------------------


def test_drain_resolves_expired_requests_instead_of_hanging():
    """The satellite contract: SIGTERM drain with expired-deadline
    requests still queued — they must resolve with the typed error and
    the drain must complete."""
    from paddle_tpu.serving import install_preemption_handler

    runner = _StubRunner(delay=0.1)
    server = Server()
    server.add_endpoint(
        "dr", runner, EndpointConfig(buckets=(4,), max_wait_ms=1.0)
    )
    import signal

    old = install_preemption_handler(server, exit_on_drain=False)
    try:
        blocker = server.submit("dr", _feed(9.0))
        time.sleep(0.02)
        doomed = [server.submit("dr", _feed(1.0), deadline_ms=20)
                  for _ in range(3)]
        live = [server.submit("dr", _feed(2.0)) for _ in range(2)]
        time.sleep(0.05)  # deadlines pass while the blocker dispatch runs
        os.kill(os.getpid(), signal.SIGTERM)
        assert server.wait_drained(timeout=30), "drain hung on dead work"
        for f in doomed:
            with pytest.raises(DeadlineExceededError):
                f.result(timeout=5)
        for f in live:
            f.result(timeout=5)
        blocker.result(timeout=5)
        assert _counter("serving.expired.dr") == 3
        assert _counter("serving.drained") == 1
    finally:
        signal.signal(signal.SIGTERM, old)


def test_server_drain_prorates_timeout_across_endpoints():
    """The r8 bug: drain(t) handed every endpoint the FULL t, so N wedged
    endpoints drained in N*t. The budget must bound the whole drain."""
    server = Server()
    for i in range(3):
        server.add_endpoint(
            f"slow{i}", _StubRunner(delay=1.0),
            EndpointConfig(buckets=(1,), max_wait_ms=0.0),
        )
        server.submit(f"slow{i}", _feed())
    time.sleep(0.05)  # every scheduler enters its 1s dispatch
    t0 = time.monotonic()
    ok = server.drain(timeout=0.5)
    took = time.monotonic() - t0
    assert took < 1.2, (
        f"drain(0.5) took {took:.2f}s — budget not pro-rated"
    )
    assert ok is False  # truthful: the dispatches outlived the budget
    server.drain(timeout=10)  # now let them finish for clean teardown


def test_server_submit_passes_deadline_and_priority_through():
    runner = _StubRunner(delay=0.1)
    server = Server()
    server.add_endpoint("pass", runner,
                        EndpointConfig(buckets=(1,), max_wait_ms=0.0))
    blocker = server.submit("pass", _feed(0.0))
    fut = server.submit("pass", _feed(1.0), deadline_ms=25,
                        priority=BACKGROUND)
    with pytest.raises(DeadlineExceededError):
        fut.result(timeout=5)
    blocker.result(timeout=5)
    server.drain(timeout=5)
    assert _counter("serving.expired_class.background") == 1
