"""Op unit tests against numpy references (reference pattern:
tests/unittests/test_*_op.py files using OpTest)."""

import numpy as np
import pytest

from op_test import OpTest


class TestElementwiseAdd(OpTest):
    op_type = "elementwise_add"

    def setup(self):
        x = np.random.rand(3, 4).astype(np.float32)
        y = np.random.rand(3, 4).astype(np.float32)
        self.inputs = {"X": x, "Y": y}
        self.outputs = {"Out": x + y}

    def test(self):
        self.check_output()
        self.check_grad(["X", "Y"])


class TestElementwiseAddBroadcast(OpTest):
    op_type = "elementwise_add"

    def setup(self):
        x = np.random.rand(2, 3, 4).astype(np.float32)
        y = np.random.rand(3).astype(np.float32)
        self.inputs = {"X": x, "Y": y}
        self.attrs = {"axis": 1}
        self.outputs = {"Out": x + y.reshape(1, 3, 1)}

    def test(self):
        self.check_output()
        self.check_grad(["X", "Y"])


class TestMatmul(OpTest):
    op_type = "matmul"

    def setup(self):
        x = np.random.rand(4, 5).astype(np.float32)
        y = np.random.rand(5, 3).astype(np.float32)
        self.inputs = {"X": x, "Y": y}
        self.outputs = {"Out": x @ y}

    def test(self):
        self.check_output()
        self.check_grad(["X", "Y"])


class TestMatmulTranspose(OpTest):
    op_type = "matmul"

    def setup(self):
        x = np.random.rand(5, 4).astype(np.float32)
        y = np.random.rand(3, 5).astype(np.float32)
        self.inputs = {"X": x, "Y": y}
        self.attrs = {"transpose_X": True, "transpose_Y": True}
        self.outputs = {"Out": x.T @ y.T}

    def test(self):
        self.check_output()


class TestSoftmax(OpTest):
    op_type = "softmax"

    def setup(self):
        x = np.random.rand(3, 7).astype(np.float32)
        e = np.exp(x - x.max(-1, keepdims=True))
        self.inputs = {"X": x}
        self.outputs = {"Out": e / e.sum(-1, keepdims=True)}

    def test(self):
        self.check_output()
        self.check_grad(["X"])


class TestRelu(OpTest):
    op_type = "relu"

    def setup(self):
        x = np.random.randn(4, 5).astype(np.float32)
        x[np.abs(x) < 0.05] = 0.1  # keep away from the kink for numeric grad
        self.inputs = {"X": x}
        self.outputs = {"Out": np.maximum(x, 0)}

    def test(self):
        self.check_output()
        self.check_grad(["X"])


class TestLayerNorm(OpTest):
    op_type = "layer_norm"

    def setup(self):
        x = np.random.rand(2, 6).astype(np.float32)
        scale = np.random.rand(6).astype(np.float32)
        bias = np.random.rand(6).astype(np.float32)
        mu = x.mean(-1, keepdims=True)
        var = x.var(-1, keepdims=True)
        y = (x - mu) / np.sqrt(var + 1e-5) * scale + bias
        self.inputs = {"X": x, "Scale": scale, "Bias": bias}
        self.attrs = {"begin_norm_axis": 1, "epsilon": 1e-5}
        self.outputs = {"Y": y}

    def test(self):
        self.check_output(atol=1e-4)
        self.check_grad(["X", "Scale", "Bias"], output_slot="Y")


class TestConv2d(OpTest):
    op_type = "conv2d"

    def setup(self):
        x = np.random.rand(2, 3, 5, 5).astype(np.float32)
        w = np.random.rand(4, 3, 3, 3).astype(np.float32)
        # naive conv reference
        out = np.zeros((2, 4, 3, 3), np.float32)
        for n in range(2):
            for o in range(4):
                for i in range(3):
                    for j in range(3):
                        patch = x[n, :, i : i + 3, j : j + 3]
                        out[n, o, i, j] = (patch * w[o]).sum()
        self.inputs = {"Input": x, "Filter": w}
        self.attrs = {"strides": [1, 1], "paddings": [0, 0]}
        self.outputs = {"Output": out}

    def test(self):
        self.check_output(atol=1e-4)


class TestConv2dGrad(OpTest):
    op_type = "conv2d"

    def setup(self):
        self.inputs = {
            "Input": np.random.rand(1, 2, 4, 4).astype(np.float32),
            "Filter": np.random.rand(2, 2, 3, 3).astype(np.float32),
        }
        self.attrs = {"strides": [1, 1], "paddings": [1, 1]}
        self.outputs = {}

    def test(self):
        self.check_grad(["Input", "Filter"], output_slot="Output")


class TestPool2dMax(OpTest):
    op_type = "pool2d"

    def setup(self):
        x = np.random.rand(2, 3, 4, 4).astype(np.float32)
        out = x.reshape(2, 3, 2, 2, 2, 2).max(axis=(3, 5))
        self.inputs = {"X": x}
        self.attrs = {
            "ksize": [2, 2], "strides": [2, 2], "paddings": [0, 0],
            "pooling_type": "max",
        }
        self.outputs = {"Out": out}

    def test(self):
        self.check_output()


class TestPool2dAvg(OpTest):
    op_type = "pool2d"

    def setup(self):
        x = np.random.rand(2, 3, 4, 4).astype(np.float32)
        out = x.reshape(2, 3, 2, 2, 2, 2).mean(axis=(3, 5))
        self.inputs = {"X": x}
        self.attrs = {
            "ksize": [2, 2], "strides": [2, 2], "paddings": [0, 0],
            "pooling_type": "avg",
        }
        self.outputs = {"Out": out}

    def test(self):
        self.check_output()
        self.check_grad(["X"])


class TestReduceSum(OpTest):
    op_type = "reduce_sum"

    def setup(self):
        x = np.random.rand(3, 4, 5).astype(np.float32)
        self.inputs = {"X": x}
        self.attrs = {"dim": [1], "keep_dim": False}
        self.outputs = {"Out": x.sum(1)}

    def test(self):
        self.check_output()
        self.check_grad(["X"])


class TestReshape(OpTest):
    op_type = "reshape2"

    def setup(self):
        x = np.random.rand(2, 6).astype(np.float32)
        self.inputs = {"X": x}
        self.attrs = {"shape": [3, 4]}
        self.outputs = {"Out": x.reshape(3, 4)}

    def test(self):
        self.check_output()
        self.check_grad(["X"])


class TestTranspose(OpTest):
    op_type = "transpose2"

    def setup(self):
        x = np.random.rand(2, 3, 4).astype(np.float32)
        self.inputs = {"X": x}
        self.attrs = {"axis": [2, 0, 1]}
        self.outputs = {"Out": x.transpose(2, 0, 1)}

    def test(self):
        self.check_output()
        self.check_grad(["X"])


class TestConcat(OpTest):
    op_type = "concat"

    def setup(self):
        xs = [np.random.rand(2, 3).astype(np.float32) for _ in range(3)]
        self.inputs = {"X": xs}
        self.attrs = {"axis": 1}
        self.outputs = {"Out": np.concatenate(xs, 1)}

    def test(self):
        self.check_output()


class TestSplit(OpTest):
    op_type = "split"

    def setup(self):
        x = np.random.rand(4, 6).astype(np.float32)
        self.inputs = {"X": x}
        self.attrs = {"num": 3, "axis": 1, "sections": []}
        self.outputs = {"Out": [x[:, 0:2], x[:, 2:4], x[:, 4:6]]}

    def test(self):
        self.check_output()


class TestLookupTable(OpTest):
    op_type = "lookup_table_v2"

    def setup(self):
        w = np.random.rand(10, 4).astype(np.float32)
        ids = np.array([[1, 2], [3, 9]], np.int64)
        self.inputs = {"W": w, "Ids": ids}
        self.outputs = {"Out": w[ids]}

    def test(self):
        self.check_output()
        self.check_grad(["W"])


class TestCrossEntropy(OpTest):
    op_type = "cross_entropy"

    def setup(self):
        x = np.random.rand(3, 5).astype(np.float32)
        x /= x.sum(-1, keepdims=True)
        label = np.array([[0], [2], [4]], np.int64)
        self.inputs = {"X": x, "Label": label}
        self.outputs = {
            "Y": -np.log(x[np.arange(3), label[:, 0]] + 1e-9)[:, None]
        }

    def test(self):
        self.check_output()


class TestSoftmaxWithCrossEntropy(OpTest):
    op_type = "softmax_with_cross_entropy"

    def setup(self):
        logits = np.random.rand(4, 6).astype(np.float32)
        label = np.array([[0], [5], [2], [1]], np.int64)
        e = np.exp(logits - logits.max(-1, keepdims=True))
        sm = e / e.sum(-1, keepdims=True)
        loss = -np.log(sm[np.arange(4), label[:, 0]])[:, None]
        self.inputs = {"Logits": logits, "Label": label}
        self.outputs = {"Softmax": sm, "Loss": loss}

    def test(self):
        self.check_output(atol=1e-5)
        self.check_grad(["Logits"], output_slot="Loss")


class TestBatchNormInference(OpTest):
    op_type = "batch_norm"

    def setup(self):
        x = np.random.rand(2, 3, 2, 2).astype(np.float32)
        scale = np.random.rand(3).astype(np.float32)
        bias = np.random.rand(3).astype(np.float32)
        mean = np.random.rand(3).astype(np.float32)
        var = np.random.rand(3).astype(np.float32) + 0.5
        y = (
            (x - mean.reshape(1, 3, 1, 1))
            / np.sqrt(var.reshape(1, 3, 1, 1) + 1e-5)
        ) * scale.reshape(1, 3, 1, 1) + bias.reshape(1, 3, 1, 1)
        self.inputs = {
            "X": x, "Scale": scale, "Bias": bias, "Mean": mean, "Variance": var,
        }
        self.attrs = {"is_test": True, "epsilon": 1e-5}
        self.outputs = {"Y": y}

    def test(self):
        self.check_output(atol=1e-4)


class TestScale(OpTest):
    op_type = "scale"

    def setup(self):
        x = np.random.rand(3, 4).astype(np.float32)
        self.inputs = {"X": x}
        self.attrs = {"scale": 2.5, "bias": 1.0}
        self.outputs = {"Out": 2.5 * x + 1.0}

    def test(self):
        self.check_output()
        self.check_grad(["X"])


class TestCast(OpTest):
    op_type = "cast"

    def setup(self):
        x = np.random.rand(3, 4).astype(np.float32)
        self.inputs = {"X": x}
        self.attrs = {"out_dtype": "int32"}
        self.outputs = {"Out": x.astype(np.int32)}

    def test(self):
        self.check_output()


class TestGather(OpTest):
    op_type = "gather"

    def setup(self):
        x = np.random.rand(5, 3).astype(np.float32)
        idx = np.array([0, 2, 4], np.int64)
        self.inputs = {"X": x, "Index": idx}
        self.outputs = {"Out": x[idx]}

    def test(self):
        self.check_output()
        self.check_grad(["X"])


class TestTopK(OpTest):
    op_type = "top_k"

    def setup(self):
        x = np.array([[1.0, 3.0, 2.0], [5.0, 4.0, 6.0]], np.float32)
        self.inputs = {"X": x}
        self.attrs = {"k": 2}
        self.outputs = {
            "Out": np.array([[3.0, 2.0], [6.0, 5.0]], np.float32),
            "Indices": np.array([[1, 2], [2, 0]], np.int64),
        }

    def test(self):
        self.check_output()


class TestSigmoidCE(OpTest):
    op_type = "sigmoid_cross_entropy_with_logits"

    def setup(self):
        x = np.random.randn(3, 4).astype(np.float32)
        label = np.random.randint(0, 2, (3, 4)).astype(np.float32)
        loss = np.maximum(x, 0) - x * label + np.log1p(np.exp(-np.abs(x)))
        self.inputs = {"X": x, "Label": label}
        self.outputs = {"Out": loss}

    def test(self):
        self.check_output()
        self.check_grad(["X"])


class TestDropoutTestMode(OpTest):
    op_type = "dropout"

    def setup(self):
        x = np.random.rand(4, 4).astype(np.float32)
        self.inputs = {"X": x}
        self.attrs = {
            "dropout_prob": 0.35, "is_test": True,
            "dropout_implementation": "downgrade_in_infer",
        }
        self.outputs = {"Out": x * 0.65}

    def test(self):
        self.check_output()


class TestClip(OpTest):
    op_type = "clip"

    def setup(self):
        x = np.random.randn(3, 4).astype(np.float32)
        self.inputs = {"X": x}
        self.attrs = {"min": -0.5, "max": 0.5}
        self.outputs = {"Out": np.clip(x, -0.5, 0.5)}

    def test(self):
        self.check_output()


class TestMul(OpTest):
    op_type = "mul"

    def setup(self):
        x = np.random.rand(2, 3, 4).astype(np.float32)
        y = np.random.rand(12, 5).astype(np.float32)
        self.inputs = {"X": x, "Y": y}
        self.attrs = {"x_num_col_dims": 1, "y_num_col_dims": 1}
        self.outputs = {"Out": (x.reshape(2, 12) @ y).reshape(2, 5)}

    def test(self):
        self.check_output()
        self.check_grad(["X", "Y"])


class TestSum(OpTest):
    op_type = "sum"

    def setup(self):
        xs = [np.random.rand(3, 4).astype(np.float32) for _ in range(3)]
        self.inputs = {"X": xs}
        self.outputs = {"Out": sum(xs)}

    def test(self):
        self.check_output()
