"""Process replica fleet (r17): the worker wire protocol (framing, torn
reads, oversized refusal, chaos seams), the extracted Supervisor loop,
worker-death-mid-batch surfacing typed, the least-inflight client plumbing,
the FleetAutoscaler / brownout capacity rung, the Watcher's dead_process
finding, and fleet_report's --stale-after twin.

Everything here runs against fake sockets, fake procs, and hand-written
journal records so the suite stays fast; the real 4-process fleet — spawn,
SIGKILL, exactly-once failover, scale-out-before-shed, zero orphans — is
bench_serving.py's ``--fleet --fleet-kill`` leg, gated by ci.sh's
fleet-chaos stage."""

import importlib.util
import json
import os
import socket
import threading
import time

import numpy as np
import pytest

from paddle_tpu import observability as obs
from paddle_tpu.errors import (
    ExecutionTimeoutError,
    InvalidArgumentError,
    UnavailableError,
)
from paddle_tpu.observability import watch
from paddle_tpu.resilience import faults
from paddle_tpu.resilience.supervisor import Supervisor
from paddle_tpu.serving import fleet as fleet_mod
from paddle_tpu.serving.brownout import BrownoutController
from paddle_tpu.serving.router import Endpoint, EndpointConfig
from paddle_tpu.serving.worker import (
    TransportError,
    bind_serving_socket,
    recv_msg,
    send_msg,
)

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_tool(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(_ROOT, "tools", f"{name}.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(autouse=True)
def fresh_metrics():
    obs.reset()
    obs.set_enabled(True)
    faults.clear()
    yield
    faults.clear()
    obs.reset()
    obs.set_enabled(None)


def _counter(name):
    return obs.get_counters().get(name, 0)


# ---------------------------------------------------------------------------
# wire framing: send_msg / recv_msg
# ---------------------------------------------------------------------------


def test_framing_round_trips_numpy_payloads():
    a, b = socket.socketpair()
    try:
        msg = {
            "kind": "run", "id": "w0:1",
            "feed": {"x": np.arange(6, dtype=np.float32).reshape(2, 3)},
        }
        send_msg(a, msg)
        send_msg(a, {"kind": "ping", "id": "w0:2"})
        got = recv_msg(b)
        assert got["kind"] == "run" and got["id"] == "w0:1"
        np.testing.assert_array_equal(
            got["feed"]["x"], msg["feed"]["x"]
        )
        assert recv_msg(b)["id"] == "w0:2"  # back-to-back frames stay aligned
    finally:
        a.close(), b.close()


def test_clean_eof_at_frame_boundary_is_none_not_error():
    a, b = socket.socketpair()
    send_msg(a, {"kind": "ping", "id": "x"})
    a.close()
    try:
        assert recv_msg(b)["kind"] == "ping"
        assert recv_msg(b) is None  # peer closed BETWEEN frames: clean
    finally:
        b.close()


def test_torn_frame_raises_typed_not_hangs():
    a, b = socket.socketpair()
    # half a header, then death — the SIGKILL-mid-write shape
    a.sendall(b"\x00\x00\x00")
    a.close()
    try:
        with pytest.raises(TransportError, match="mid-frame"):
            recv_msg(b)
    finally:
        b.close()


def test_oversized_send_refused_before_any_bytes_leave():
    a, b = socket.socketpair()
    try:
        with pytest.raises(TransportError, match="refusing to send"):
            send_msg(a, {"blob": b"x" * 4096}, max_frame=64)
        # nothing was written: the stream is still usable for a good frame
        send_msg(a, {"kind": "ping", "id": "ok"})
        assert recv_msg(b)["id"] == "ok"
    finally:
        a.close(), b.close()


def test_oversized_length_prefix_refused_on_recv():
    a, b = socket.socketpair()
    try:
        send_msg(a, {"blob": b"y" * 4096})
        with pytest.raises(TransportError, match="refusing"):
            recv_msg(b, max_frame=64)
    finally:
        a.close(), b.close()


def test_transport_chaos_seams_fire_on_both_ends():
    a, b = socket.socketpair()
    try:
        faults.inject("serving.transport.send", "unavailable", prob=1.0,
                      max_fires=1)
        with pytest.raises(UnavailableError):
            send_msg(a, {"kind": "ping", "id": "1"})
        send_msg(a, {"kind": "ping", "id": "2"})  # healed after max_fires
        faults.inject("serving.transport.recv", "unavailable", prob=1.0,
                      max_fires=1)
        with pytest.raises(UnavailableError):
            recv_msg(b)
        assert recv_msg(b)["id"] == "2"
        assert _counter(
            "resilience.faults_injected.serving.transport.send") == 1
        assert _counter(
            "resilience.faults_injected.serving.transport.recv") == 1
    finally:
        faults.clear()
        a.close(), b.close()


def test_double_spawn_port_collision_falls_back_to_ephemeral():
    srv1, port1 = bind_serving_socket("127.0.0.1", 0)
    try:
        # second spawn asks for the SAME explicit port: must come up
        # anyway on a fresh one and report the real port
        srv2, port2 = bind_serving_socket("127.0.0.1", port1)
        try:
            assert port2 != port1 and port2 > 0
            assert _counter("serving.worker.port_fallbacks") == 1
        finally:
            srv2.close()
    finally:
        srv1.close()


# ---------------------------------------------------------------------------
# Supervisor: the extracted launcher loop on fake procs
# ---------------------------------------------------------------------------


class _FakeProc:
    """Popen-shaped: the test scripts its exit via .rc."""

    _ids = iter(range(10_000, 99_999))

    def __init__(self):
        self.pid = next(self._ids)
        self.rc = None
        self.signals = []

    def poll(self):
        return self.rc

    def wait(self, timeout=None):
        return self.rc

    def send_signal(self, sig):
        self.signals.append(sig)

    def kill(self):
        self.signals.append("KILL")
        self.rc = -9


class _Clock:
    def __init__(self, t=100.0):
        self.t = t

    def __call__(self):
        return self.t


def _mk_sup(clock, **kw):
    spawned = []

    def spawn(key, attempt):
        proc = _FakeProc()
        proc._paddle_spawned = clock.t
        spawned.append((key, attempt, proc))
        return proc

    kw.setdefault("backoff_base", 0.5)
    kw.setdefault("backoff_cap", 0.5)  # cap==base: delay is deterministic
    sup = Supervisor(spawn, clock=clock, wall=clock, **kw)
    return sup, spawned


def test_supervisor_routes_death_through_backoff_then_respawns():
    clock = _Clock()
    sup, spawned = _mk_sup(clock, max_restarts=3)
    p0 = sup.add("w0")
    assert sup.poll() == []  # healthy tick: no events
    p0.rc = -9  # SIGKILL
    (ev,) = sup.poll()
    assert ev["kind"] == "restart_scheduled"
    assert ev["attempt"] == 1 and 0 < ev["delay"] <= 0.5
    assert sup.poll() == []  # still inside the backoff window
    clock.t += 1.0
    (ev,) = sup.poll()
    assert ev["kind"] == "respawned" and ev["key"] == "w0"
    assert ev["proc"] is not p0 and sup.state("w0") == "running"
    assert spawned[-1] == ("w0", 1, ev["proc"])  # attempt number travels


def test_supervisor_same_tick_deaths_get_independent_deadlines():
    clock = _Clock()
    sup, _ = _mk_sup(clock, max_restarts=3)
    pa, pb = sup.add("a"), sup.add("b")
    pa.rc = pb.rc = 1
    events = sup.poll()
    assert [e["kind"] for e in events] == ["restart_scheduled"] * 2
    clock.t += 1.0
    assert sorted(e["key"] for e in sup.poll()
                  if e["kind"] == "respawned") == ["a", "b"]


def test_supervisor_clean_exit_ends_supervision():
    clock = _Clock()
    sup, _ = _mk_sup(clock, clean_exit=lambda rc, hung: rc in (0, 75))
    p = sup.add("w0")
    p.rc = 75  # the preemption contract's drain exit
    (ev,) = sup.poll()
    assert ev["kind"] == "exit_clean" and ev["rc"] == 75
    assert sup.state("w0") == "done" and not sup.some_active()


def test_supervisor_restart_budget_exhaustion_is_fatal():
    clock = _Clock()
    sup, _ = _mk_sup(clock, max_restarts=1)
    sup.add("w0").rc = 1
    assert sup.poll()[0]["kind"] == "restart_scheduled"
    clock.t += 1.0
    (ev,) = sup.poll()
    assert ev["kind"] == "respawned"
    ev["proc"].rc = 1  # second death: budget (1) already spent
    (ev,) = sup.poll()
    assert ev["kind"] == "fatal" and ev["restarts"] == 1
    assert sup.state("w0") == "failed"
    assert sup.poll() == []  # left dead, never polled again


def test_supervisor_stale_heartbeat_kills_hung_child():
    clock = _Clock()
    import signal as _signal

    sup, _ = _mk_sup(
        clock, max_restarts=1,
        staleness=lambda proc, now: getattr(proc, "stale", 0.0),
        stale_after=5.0,
    )
    p = sup.add("w0")
    p.stale = 99.0
    (ev,) = sup.poll()
    assert ev["kind"] == "hung" and p.signals == [_signal.SIGTERM]
    assert sup.poll() == []  # hung emitted once; grace running
    p._paddle_kill_at = 0.0  # grace expired
    sup.poll()
    assert "KILL" in p.signals and p._paddle_hung
    # the kill routes through the SAME restart path as any crash
    (ev,) = sup.poll()
    assert ev["kind"] == "restart_scheduled" and ev["hung"]


def test_supervisor_forget_is_a_silent_scale_in():
    clock = _Clock()
    sup, _ = _mk_sup(clock)
    p = sup.add("w0")
    assert sup.forget("w0") is p
    p.rc = 1  # dies AFTER the forget: no events, no respawn
    assert sup.poll() == [] and sup.keys() == []


# ---------------------------------------------------------------------------
# _WorkerClient: typed failure surfacing over a scripted worker
# ---------------------------------------------------------------------------


def _scripted_worker(script):
    """One-connection fake worker: `script(conn)` plays the server side.
    Returns the ready dict a _WorkerClient binds to."""
    srv, port = bind_serving_socket("127.0.0.1", 0)

    def serve():
        try:
            conn, _ = srv.accept()
            with conn:
                script(conn)
        except OSError:
            pass
        finally:
            srv.close()

    threading.Thread(target=serve, daemon=True).start()
    return {
        "pid": os.getpid(), "host": "127.0.0.1", "port": port,
        "attempt": 0, "feed_names": ["x"], "fetch_names": ["y"],
        "sample_specs": {"x": [[2], "float32"]},
    }


def test_worker_death_mid_batch_is_typed_not_a_hang():
    def die_mid_reply(conn):
        recv_msg(conn)  # take the batch, then die without replying

    ready = _scripted_worker(die_mid_reply)
    client = fleet_mod._WorkerClient("w0", ready, io_timeout=5.0)
    t0 = time.perf_counter()
    with pytest.raises(TransportError, match="closed the connection"):
        client.run({"x": np.zeros((1, 2), np.float32)})
    assert time.perf_counter() - t0 < 5.0  # typed promptly, no hang
    client.close()


def test_stale_replies_discarded_by_id_stream_stays_usable():
    def straggler_then_answer(conn):
        msg = recv_msg(conn)
        # a reply from an attempt the watchdog already abandoned...
        send_msg(conn, {"kind": "result", "id": "w0:ancient", "outs": []})
        # ...then the reply this call is actually waiting on
        send_msg(conn, {"kind": "pong", "id": msg["id"], "pid": 1,
                        "batches": 0})

    ready = _scripted_worker(straggler_then_answer)
    client = fleet_mod._WorkerClient("w0", ready, io_timeout=5.0)
    assert client.call("ping")["kind"] == "pong"
    assert _counter("serving.fleet.stale_replies") == 1
    client.close()


def test_remote_error_rehydrates_by_taxonomy_name():
    def reply_error(conn):
        msg = recv_msg(conn)
        send_msg(conn, {"kind": "error", "id": msg["id"],
                        "etype": "InvalidArgumentError",
                        "msg": "bad feed shape"})

    ready = _scripted_worker(reply_error)
    client = fleet_mod._WorkerClient("w0", ready, io_timeout=5.0)
    with pytest.raises(InvalidArgumentError, match="bad feed shape"):
        client.run({"x": np.zeros((1, 2), np.float32)})
    client.close()


def test_reply_timeout_is_typed_and_burns_the_connection():
    def never_reply(conn):
        recv_msg(conn)
        time.sleep(3.0)

    ready = _scripted_worker(never_reply)
    client = fleet_mod._WorkerClient("w0", ready, io_timeout=0.2)
    with pytest.raises(ExecutionTimeoutError):
        client.call("ping")
    # a timed-out read may sit mid-frame: the socket must be gone
    assert client._sock is None
    client.close()


def test_respawn_with_different_contract_is_rejected():
    ready = {
        "pid": 1, "host": "127.0.0.1", "port": 1, "attempt": 0,
        "feed_names": ["x"], "fetch_names": ["y"],
        "sample_specs": {"x": [[2], "float32"]},
    }
    client = fleet_mod._WorkerClient.__new__(fleet_mod._WorkerClient)
    client.name = "w0"
    client.inflight = 0
    client._io_timeout = None
    client._connect_timeout = 1.0
    client._lock = threading.Lock()
    client._sock = None
    client._bind(ready, first=True)
    with pytest.raises(InvalidArgumentError, match="different"):
        client.rebind(dict(ready, feed_names=["x", "mask"]))


# ---------------------------------------------------------------------------
# FleetAutoscaler + the brownout capacity rung
# ---------------------------------------------------------------------------


class _FakeFleet:
    def __init__(self, can_grow=True, can_shrink=True):
        self.can_grow, self.can_shrink = can_grow, can_shrink
        self.outs = 0
        self.ins = 0

    def try_scale_out(self):
        if self.can_grow:
            self.outs += 1
            return True
        return False

    def scale_in(self):
        if self.can_shrink:
            self.ins += 1
            return True
        return False


def test_autoscaler_scales_out_on_sustained_breach_with_cooldown():
    from paddle_tpu.serving.fleet import FleetAutoscaler

    clock = _Clock()
    fleet = _FakeFleet()
    asc = FleetAutoscaler(fleet, breach_after=2, idle_after=3,
                          cooldown_s=10.0, clock=clock)
    assert asc.observe(True, idle=False) is None  # streak 1 < 2
    assert asc.observe(True, idle=False) == "scale_out"
    clock.t += 1.0  # inside cooldown: a fresh streak must NOT act
    assert asc.observe(True, idle=False) is None
    assert asc.observe(True, idle=False) is None
    clock.t += 10.0  # cooldown over; streak is already >= breach_after
    assert asc.observe(True, idle=False) == "scale_out"
    assert fleet.outs == 2


def test_autoscaler_at_max_returns_none_and_keeps_trying():
    from paddle_tpu.serving.fleet import FleetAutoscaler

    clock = _Clock()
    fleet = _FakeFleet(can_grow=False)
    asc = FleetAutoscaler(fleet, breach_after=1, cooldown_s=0.0,
                          clock=clock)
    assert asc.observe(True, idle=False) is None  # full: falls through
    assert asc.observe(True, idle=False) is None  # and keeps retrying
    fleet.can_grow = True  # a worker drained meanwhile
    assert asc.observe(True, idle=False) == "scale_out"


def test_autoscaler_scales_in_after_sustained_idle():
    from paddle_tpu.serving.fleet import FleetAutoscaler

    clock = _Clock()
    fleet = _FakeFleet()
    asc = FleetAutoscaler(fleet, breach_after=2, idle_after=2,
                          cooldown_s=0.0, clock=clock)
    assert asc.observe(False, idle=True) is None
    assert asc.observe(False, idle=True) == "scale_in"
    assert fleet.ins == 1
    # a breach tick resets the idle streak even when idle= was passed
    assert asc.observe(True, idle=True) is None
    assert asc.observe(False, idle=True) is None  # streak restarted at 1


class _NoEndpoints:
    def endpoints(self):
        return {}


def test_brownout_scale_out_absorbs_the_breach_tick():
    from paddle_tpu.serving.fleet import FleetAutoscaler

    clock = _Clock()
    asc = FleetAutoscaler(_FakeFleet(), breach_after=1, cooldown_s=0.0,
                          clock=clock)
    ctl = BrownoutController(_NoEndpoints(), slo_p99_s=0.1,
                             escalate_after=2, autoscaler=asc)
    # every breach tick is absorbed by a scale-out: the ladder never moves
    for _ in range(6):
        assert ctl.observe(p99=0.5) == 0
    assert asc.fleet.outs == 6
    assert _counter("serving.brownout_scale_outs") == 6


def test_brownout_escalates_only_once_the_fleet_is_full():
    from paddle_tpu.serving.fleet import FleetAutoscaler

    clock = _Clock()
    fleet = _FakeFleet(can_grow=False)  # at max_replicas from the start
    asc = FleetAutoscaler(fleet, breach_after=1, cooldown_s=0.0,
                          clock=clock)
    ctl = BrownoutController(_NoEndpoints(), slo_p99_s=0.1,
                             escalate_after=2, autoscaler=asc)
    assert ctl.observe(p99=0.5) == 0  # breach 1 of 2
    assert ctl.observe(p99=0.5) == 1  # capacity exhausted: degrade
    assert fleet.outs == 0
    assert _counter("serving.brownout_escalations") == 1


# ---------------------------------------------------------------------------
# Watcher dead_process finding + fleet_report --stale-after
# ---------------------------------------------------------------------------


def _write_record(path, seq, t, counters=None, kind="base"):
    rec = {"kind": kind, "rank": 0, "pid": 4242, "seq": seq, "t": t,
           "counters": counters or {"telemetry.publishes": seq}}
    with open(path, "a") as f:
        f.write(json.dumps(rec) + "\n")


def test_watcher_dead_process_latches_and_rearms_on_respawn(tmp_path):
    shard = tmp_path / "telemetry_rank0.jsonl"
    _write_record(str(shard), seq=1, t=time.time() - 60.0)
    w = watch.Watcher(journal_dir=str(tmp_path), dead_process_timeout=3.0)
    (finding,) = w.poll()
    assert finding["kind"] == "dead_process"
    assert finding["severity"] == "error"
    assert finding["detail"]["pid"] == 4242
    assert finding["detail"]["stale_s"] > 3.0
    assert obs.get_gauges()["watch.dead_processes"] == 1.0
    assert w.poll() == []  # latched: one finding per death
    # the respawn writes fresh records: the latch re-arms...
    _write_record(str(shard), seq=2, t=time.time())
    assert w.poll() == []
    assert obs.get_gauges()["watch.dead_processes"] == 0.0
    # ...so a SECOND death of the same shard raises a second finding
    _write_record(str(shard), seq=3, t=time.time() - 60.0)
    (finding,) = w.poll()
    assert finding["kind"] == "dead_process"
    assert _counter("watch.findings.dead_process") == 2


def test_watcher_dead_process_off_by_default(tmp_path):
    shard = tmp_path / "telemetry_rank0.jsonl"
    _write_record(str(shard), seq=1, t=time.time() - 60.0)
    w = watch.Watcher(journal_dir=str(tmp_path))
    assert all(f["kind"] != "dead_process" for f in w.poll())


def test_fleet_report_flags_stale_shards_as_dead(tmp_path):
    now = time.time()
    live = tmp_path / "telemetry_rank0.jsonl"
    dead = tmp_path / "telemetry_rank1.jsonl"
    _write_record(str(live), seq=1, t=now - 1.0)
    with open(str(dead), "a") as f:
        f.write(json.dumps({
            "kind": "base", "rank": 1, "pid": 777, "seq": 1,
            "t": now - 30.0, "counters": {"serving.goodput": 5},
        }) + "\n")
    fleet_report = _load_tool("fleet_report")
    report = fleet_report.build_report(
        str(tmp_path), stale_after=5.0, now=now
    )
    by_rank = {s["rank"]: s for s in report["shards"]}
    assert by_rank[1]["dead"] and not by_rank[0]["dead"]
    deads = report["fleet"]["dead_processes"]
    assert [d["pid"] for d in deads] == [777]
    assert deads[0]["stale_s"] == pytest.approx(30.0, abs=1.0)
    assert "DEAD: rank 1" in fleet_report.render(report)
    # without --stale-after nothing is judged (no false positives)
    report = fleet_report.build_report(str(tmp_path))
    assert report["fleet"]["dead_processes"] == []


# ---------------------------------------------------------------------------
# Endpoint dispatch pool: max_concurrency actually overlaps batches
# ---------------------------------------------------------------------------


class _ConcurrentRunner:
    """Tracks how many batches run at once; sleeps so overlap is forced."""

    feed_names = ("x",)
    max_concurrency = 4

    def __init__(self, delay=0.05):
        self.delay = delay
        self.active = 0
        self.peak = 0
        self._lock = threading.Lock()

    def sample_spec(self, name):
        return (2,), "float32"

    def run(self, feed):
        with self._lock:
            self.active += 1
            self.peak = max(self.peak, self.active)
        time.sleep(self.delay)
        with self._lock:
            self.active -= 1
        return [feed["x"] * 2.0]


def test_endpoint_dispatch_pool_overlaps_batches():
    runner = _ConcurrentRunner()
    ep = Endpoint("pool", runner,
                  EndpointConfig(buckets=(1,), max_wait_ms=0.0))
    futs = [
        ep.submit({"x": np.full(2, float(i), np.float32)})
        for i in range(8)
    ]
    outs = [f.result(timeout=10)[0] for f in futs]
    assert ep.drain(timeout=10)
    for i, out in enumerate(outs):
        np.testing.assert_array_equal(out, np.full(2, 2.0 * i))
    # serialized dispatch would hold peak at 1; the pool must overlap
    assert runner.peak >= 2


def test_endpoint_single_runner_stays_serialized():
    runner = _ConcurrentRunner()
    runner.max_concurrency = 1
    ep = Endpoint("ser", runner,
                  EndpointConfig(buckets=(1,), max_wait_ms=0.0))
    futs = [
        ep.submit({"x": np.zeros(2, np.float32)}) for _ in range(4)
    ]
    for f in futs:
        f.result(timeout=10)
    assert ep.drain(timeout=10)
    assert runner.peak == 1
