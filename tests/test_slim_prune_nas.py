"""slim pruning + NAS (reference contrib/slim/prune, contrib/slim/nas,
contrib/slim/searcher): structured channel pruning rewrites the Program and
the model keeps working; SA search finds good tokens; controller
server/agent round-trips over TCP."""

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import layers
from paddle_tpu.contrib.slim.analysis import flops
from paddle_tpu.contrib.slim.nas import (
    ControllerServer,
    LightNAS,
    SAController,
    SearchAgent,
    SearchSpace,
)
from paddle_tpu.contrib.slim.prune import (
    SensitivePruneStrategy,
    StructurePruner,
    UniformPruneStrategy,
    get_ratios_by_sensitivity,
    prune_program,
    sensitivity,
)
from paddle_tpu.framework import unique_name

B = 8


@pytest.fixture(autouse=True)
def fresh_programs():
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 11
    scope = fluid.framework.scope.Scope()
    with fluid.program_guard(main, startup), fluid.scope_guard(scope), \
            unique_name.guard():
        yield main, startup, scope


def _cnn(img, label):
    """conv-bn-relu -> depthwise -> conv-relu -> fc chain covering every
    supported propagation case."""
    c1 = layers.conv2d(
        img, 16, 3, padding=1, act=None,
        param_attr=fluid.ParamAttr(name="c1_w"),
        bias_attr=fluid.ParamAttr(name="c1_b"),
    )
    c1 = layers.batch_norm(
        c1,
        act="relu",
        param_attr=fluid.ParamAttr(name="bn1_s"),
        bias_attr=fluid.ParamAttr(name="bn1_b"),
    )
    c1 = layers.pool2d(c1, 2, "max", 2)
    c2 = layers.conv2d(
        c1, 12, 3, padding=1, act="relu",
        param_attr=fluid.ParamAttr(name="c2_w"), bias_attr=False,
    )
    logits = layers.fc(
        c2, 10, num_flatten_dims=1,
        param_attr=fluid.ParamAttr(name="fc_w"),
        bias_attr=fluid.ParamAttr(name="fc_b"),
    )
    loss = layers.mean(
        layers.softmax_with_cross_entropy(logits, label)
    )
    return logits, loss


def _feed(rng):
    return {
        "img": rng.randn(B, 3, 8, 8).astype("float32"),
        "label": rng.randint(0, 10, (B, 1)).astype("int64"),
    }


def test_structure_pruner_idx_and_tensor():
    p = StructurePruner()
    w = np.arange(24, dtype=np.float32).reshape(4, 6)
    idx = p.cal_pruned_idx("w", w, 0.5, axis=0)
    assert list(idx) == [0, 1]  # lowest l1 rows
    pruned = p.prune_tensor(w, idx, 0)
    assert pruned.shape == (2, 6)
    lazy = p.prune_tensor(w, idx, 0, lazy=True)
    assert lazy.shape == (4, 6) and lazy[:2].sum() == 0


def test_prune_program_end_to_end():
    img = fluid.data("img", [B, 3, 8, 8])
    label = fluid.data("label", [B, 1], "int64")
    logits, loss = _cnn(img, label)
    fluid.optimizer.Momentum(0.05, 0.9).minimize(loss)
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    rng = np.random.RandomState(0)
    for _ in range(5):
        exe.run(feed=_feed(rng), fetch_list=[loss])

    main = fluid.default_main_program()
    scope = fluid.framework.scope.global_scope()
    f0 = flops(main)
    prune_program(main, scope, {"c1_w": 0.5, "c2_w": 0.5})
    f1 = flops(main)
    assert f1 < 0.65 * f0, (f0, f1)
    # shapes really shrank, bn + bias + downstream conv/fc followed
    assert scope.find_var("c1_w").shape == (8, 3, 3, 3)
    assert scope.find_var("c1_b").shape == (8,)
    assert scope.find_var("bn1_s").shape == (8,)
    assert scope.find_var("c2_w").shape == (6, 8, 3, 3)
    assert scope.find_var("fc_w").shape[0] == 6 * 4 * 4
    # training still runs on the pruned program (fresh trace via _bump)
    vals = [
        float(np.asarray(exe.run(feed=_feed(rng), fetch_list=[loss])[0]))
        for _ in range(3)
    ]
    assert all(np.isfinite(vals))


def test_sensitivity_and_auto_ratio():
    img = fluid.data("img", [B, 3, 8, 8])
    label = fluid.data("label", [B, 1], "int64")
    logits, loss = _cnn(img, label)
    test_prog = fluid.default_main_program().clone(for_test=True)
    fluid.optimizer.Momentum(0.05, 0.9).minimize(loss)
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    rng = np.random.RandomState(1)
    fixed = _feed(rng)
    for _ in range(30):
        exe.run(feed=fixed, fetch_list=[loss])

    def eval_func(prog, scope):
        (lv,) = exe.run(prog, feed=fixed, fetch_list=[loss.name], scope=scope)
        return -float(np.asarray(lv).reshape(-1)[0])  # higher = better

    scope = fluid.framework.scope.global_scope()
    sens = sensitivity(
        fluid.default_main_program(), scope, eval_func,
        ["c1_w", "c2_w"], ratios=(0.25, 0.75),
    )
    assert set(sens) == {"c1_w", "c2_w"}
    # zeroing MORE channels cannot hurt less (monotone in ratio)
    for t in sens.values():
        assert t[0.75] >= t[0.25] - 1e-6
    ratios = get_ratios_by_sensitivity(sens, target_loss=1e9)
    assert ratios == {"c1_w": 0.75, "c2_w": 0.75}
    UniformPruneStrategy(
        target_ratio=0.25, pruned_params=["c1_w"]
    ).apply(fluid.default_main_program(), scope)
    assert scope.find_var("c1_w").shape[0] == 12


def test_sa_controller_minimizes_toy_objective():
    rt = [8] * 6
    ctl = SAController(rt, init_temperature=1.0, reduce_rate=0.7, seed=0)
    ctl.reset(rt, [0] * 6)
    target = [5, 2, 7, 1, 3, 6]
    for _ in range(300):
        t = ctl.next_tokens()
        reward = -sum(abs(a - b) for a, b in zip(t, target))
        ctl.update(t, reward)
    assert ctl.best_reward >= -4, (ctl.best_tokens, ctl.best_reward)


def test_controller_server_agent_roundtrip():
    rt = [4, 4]
    ctl = SAController(rt, seed=3)
    ctl.reset(rt, [0, 0])
    server = ControllerServer(ctl).start()
    try:
        agent = SearchAgent(server.address)
        for _ in range(20):
            t = agent.next_tokens()
            assert all(0 <= x < 4 for x in t)
            agent.update(t, float(sum(t)))
        best = agent.best()
        assert best["reward"] == 6.0 and best["tokens"] == [3, 3]
    finally:
        server.close()


def test_light_nas_searches_mlp_width():
    """End-to-end: search hidden width; reward favors width 3 (accuracy
    proxy) under a latency cap that penalizes the largest width."""

    widths = [4, 16, 64, 256]

    class MLPSpace(SearchSpace):
        def init_tokens(self):
            return [0]

        def range_table(self):
            return [len(widths)]

        def create_net(self, tokens):
            main, startup = fluid.Program(), fluid.Program()
            main.random_seed = startup.random_seed = 5
            with fluid.program_guard(main, startup), unique_name.guard():
                x = fluid.data("x", [16, 8])
                y = fluid.data("y", [16, 1], "int64")
                h = layers.fc(x, widths[tokens[0]], act="relu")
                logits = layers.fc(h, 4)
                loss = layers.mean(
                    layers.softmax_with_cross_entropy(logits, y)
                )
            return startup, main, main, loss, loss

    space = MLPSpace()
    rng = np.random.RandomState(2)
    xs = rng.randn(16, 8).astype("float32")
    ys = (xs[:, :1] > 0).astype("int64")

    def eval_candidate(tokens):
        startup, main, _, loss, _ = space.create_net(tokens)
        scope = fluid.framework.scope.Scope()
        exe = fluid.Executor()
        with fluid.scope_guard(scope):
            with fluid.program_guard(main, startup):
                fluid.optimizer.Adam(0.05).minimize(loss)
            exe.run(startup, scope=scope)
            for _ in range(15):
                (lv,) = exe.run(
                    main, feed={"x": xs, "y": ys}, fetch_list=[loss],
                    scope=scope,
                )
        metric = -float(np.asarray(lv).reshape(-1)[0])
        return metric, space.get_model_latency(main)

    nas = LightNAS(space, max_latency=40_000, latency_weight=10.0)
    best_tokens, best_reward = nas.search(eval_candidate, steps=8)
    assert best_tokens is not None and np.isfinite(best_reward)
    # the 256-wide net busts the latency cap; search must not pick it
    assert best_tokens[0] != 3
