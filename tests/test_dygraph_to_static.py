"""@declarative AST conversion of plain-Python control flow
(dygraph/ast_transform.py; reference dygraph_to_static/ transformer stack:
program_translator.py:252, ifelse_transformer.py, loop_transformer.py,
break_continue_transformer.py, logical_transformer.py).

A branchy dygraph function with TENSOR conditions must convert unmodified
and match eager output; python conditions must run unchanged; in static
mode the same source builds cond/while ops."""

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import layers as L
from paddle_tpu.dygraph import declarative, to_variable
from paddle_tpu import dygraph


@declarative
def _branchy(x):
    s = L.reduce_sum(x)
    if s > 0:
        y = x * 2.0
        z = y + 1.0
    else:
        y = x - 3.0
        z = y * y
    return z


def _branchy_eager(x):
    if float(np.asarray(x.value).sum()) > 0:
        y = x * 2.0
        z = y + 1.0
    else:
        y = x - 3.0
        z = y * y
    return z


def test_tensor_if_matches_eager_both_outcomes():
    with dygraph.guard():
        for xv in [np.ones((2, 2), "float32"), -np.ones((2, 2), "float32")]:
            x = to_variable(xv)
            np.testing.assert_allclose(
                np.asarray(_branchy(x).value),
                np.asarray(_branchy_eager(x).value),
                rtol=1e-6,
            )


def test_tensor_while_with_break():
    @declarative
    def loopy(x, n):
        i = 0
        acc = x * 0.0
        while i < n:  # tensor condition
            acc = acc + x
            i = i + 1
            if i >= 3:
                break
        return acc

    with dygraph.guard():
        x = to_variable(np.ones((2,), "float32"))
        n = to_variable(np.asarray(5, "int32"))
        np.testing.assert_allclose(
            np.asarray(loopy(x, n).value), 3.0 * np.ones(2), rtol=1e-6
        )
        # break never reached when the loop ends first
        n2 = to_variable(np.asarray(2, "int32"))
        np.testing.assert_allclose(
            np.asarray(loopy(x, n2).value), 2.0 * np.ones(2), rtol=1e-6
        )


def test_tensor_while_with_continue():
    @declarative
    def skippy(x, n):
        i = 0
        acc = x * 0.0
        while i < n:
            i = i + 1
            if i % 2 == 0:
                continue
            acc = acc + x
        return acc

    def ref(k, n):
        acc = 0.0
        i = 0
        while i < n:
            i += 1
            if i % 2 == 0:
                continue
            acc += 1.0
        return acc

    with dygraph.guard():
        x = to_variable(np.ones((2,), "float32"))
        n = to_variable(np.asarray(5, "int32"))
        np.testing.assert_allclose(
            np.asarray(skippy(x, n).value), ref(1, 5) * np.ones(2),
            rtol=1e-6,
        )


def test_for_over_tensor_range():
    @declarative
    def forloop(x, n):
        acc = x * 0.0
        for _ in range(n):  # tensor trip count
            acc = acc + x
        return acc

    with dygraph.guard():
        x = to_variable(np.ones((2,), "float32"))
        n = to_variable(np.asarray(5, "int32"))
        np.testing.assert_allclose(
            np.asarray(forloop(x, n).value), 5.0 * np.ones(2), rtol=1e-6
        )


def test_python_control_flow_unchanged():
    """Python conditions (and ifs containing `return`) keep exact python
    semantics — the conversion must not perturb the functional subset."""

    @declarative
    def fn(x, flag):
        if flag:  # python bool
            return x * 2.0
        acc = x
        for i in range(3):  # python range
            acc = acc + x
        return acc

    with dygraph.guard():
        x = to_variable(np.ones((2,), "float32"))
        np.testing.assert_allclose(
            np.asarray(fn(x, True).value), 2.0 * np.ones(2)
        )
        np.testing.assert_allclose(
            np.asarray(fn(x, False).value), 4.0 * np.ones(2)
        )


def test_static_if_builds_cond_op():
    @declarative
    def model(x):
        s = L.reduce_sum(x)
        if s > 0:
            out = x * 2.0
        else:
            out = x - 3.0
        return out

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.data("x", [2, 2], "float32")
        out = model(x)
    assert "cond" in [op.type for op in main.global_block.ops]
    exe = fluid.Executor()
    for xv, expect in [
        (np.ones((2, 2), "float32"), 2.0),
        (-np.ones((2, 2), "float32"), -4.0),
    ]:
        (res,) = exe.run(main, feed={"x": xv}, fetch_list=[out])
        np.testing.assert_allclose(
            np.asarray(res), expect * np.ones((2, 2)), rtol=1e-6
        )


def test_static_while_builds_while_op():
    @declarative
    def model(x, n):
        i = L.fill_constant([1], "int32", 0)
        acc = x * 0.0
        while i < n:
            acc = acc + x
            i = i + 1
        return acc

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.data("x", [2], "float32")
        n = fluid.data("n", [1], "int32")
        out = model(x, n)
    assert "while" in [op.type for op in main.global_block.ops]
    exe = fluid.Executor()
    (res,) = exe.run(
        main,
        feed={"x": np.ones(2, "float32"), "n": np.array([4], "int32")},
        fetch_list=[out],
    )
    np.testing.assert_allclose(np.asarray(res), 4.0 * np.ones(2), rtol=1e-6)


def test_book_fit_a_line_with_python_if_in_body():
    """Book-test shape (fit-a-line, test_fit_a_line.py) whose model body
    branches in plain Python on a TENSOR statistic, run under @declarative
    in static mode: converts to a cond op and still converges."""
    from paddle_tpu.param_attr import ParamAttr

    @declarative
    def net(x):
        pred = L.fc(x, size=1,
                    param_attr=ParamAttr(name="fal_w"),
                    bias_attr=ParamAttr(name="fal_b"))
        # keep predictions bounded: a python `if` over a tensor statistic
        m = L.reduce_mean(pred)
        if m > 100.0:
            out = pred * 0.5
        else:
            out = pred
        return out

    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 7
    with fluid.program_guard(main, startup):
        x = fluid.data("x", [-1, 13], "float32")
        y = fluid.data("y", [-1, 1], "float32")
        pred = net(x)
        loss = L.reduce_mean(L.square(pred - y))
        fluid.optimizer.SGD(0.01).minimize(loss, startup)
    assert "cond" in [op.type for op in main.global_block.ops]

    exe = fluid.Executor()
    scope = fluid.framework.scope.Scope()
    exe.run(startup, scope=scope)
    rng = np.random.RandomState(0)
    w = rng.randn(13, 1).astype("float32")
    xs = rng.randn(64, 13).astype("float32")
    ys = xs @ w
    losses = []
    for _ in range(30):
        (lv,) = exe.run(main, feed={"x": xs, "y": ys}, fetch_list=[loss],
                        scope=scope)
        losses.append(float(np.asarray(lv).reshape(-1)[0]))
    assert losses[-1] < 0.5 * losses[0], losses[:3] + losses[-3:]


def test_declarative_training_through_converted_if():
    """Eager training: grads flow through a converted tensor-if (lax.cond
    is differentiable) via the declarative boundary vjp."""
    from paddle_tpu.dygraph import Linear
    from paddle_tpu.optimizer import SGD

    @declarative
    def fwd(layer, x):
        h = layer(x)
        s = L.reduce_sum(h)
        if s > 0:
            out = h * 2.0
        else:
            out = h * 0.5
        return L.reduce_mean(L.square(out))

    with dygraph.guard():
        lin = Linear(4, 4)
        opt = SGD(0.05, parameter_list=lin.parameters())
        x = to_variable(np.random.RandomState(0).randn(8, 4).astype("f4"))
        vals = []
        for _ in range(5):
            loss = fwd(lin, x)
            loss.backward()
            opt.minimize(loss)
            lin.clear_gradients()
            vals.append(float(np.asarray(loss.value)))
        assert vals[-1] < vals[0], vals  # grads flowed through lax.cond


def test_static_for_over_tensor_range():
    """Static mode: for over a tensor trip count lowers to a while op
    (python loop carries auto-lift to fill_constant Variables)."""

    @declarative
    def model(x, n):
        acc = x * 0.0
        for _ in range(n):
            acc = acc + x
        return acc

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.data("x", [2], "float32")
        n = fluid.data("n", [1], "int32")
        out = model(x, n)
    assert "while" in [op.type for op in main.global_block.ops]
    exe = fluid.Executor()
    (res,) = exe.run(
        main,
        feed={"x": np.ones(2, "float32"), "n": np.array([3], "int32")},
        fetch_list=[out],
    )
    np.testing.assert_allclose(np.asarray(res), 3.0 * np.ones(2), rtol=1e-6)


def test_helper_defined_after_decoration():
    """Converted functions resolve module globals LIVE — a helper defined
    (or rebound) after decoration must be visible at call time."""
    import types

    mod = types.ModuleType("dy2st_live_mod")
    src = (
        "from paddle_tpu.dygraph import declarative\n"
        "@declarative\n"
        "def f(x):\n"
        "    return helper(x)\n"
        "def helper(x):\n"
        "    return x * 3.0\n"
    )
    # emulate module definition order: decorator runs before helper exists
    exec(src, mod.__dict__)
    with dygraph.guard():
        x = to_variable(np.ones((2,), "float32"))
        np.testing.assert_allclose(
            np.asarray(mod.f(x).value), 3.0 * np.ones(2), rtol=1e-6
        )


def test_varbase_eq_contract():
    with dygraph.guard():
        v = to_variable(np.ones((2,), "float32"))
        assert (v == None) is False  # noqa: E711 — python fallback equality
        assert (v != None) is True  # noqa: E711
        assert v in [None, v]  # membership via identity fallback
        eq = v == 1.0
        np.testing.assert_array_equal(
            np.asarray(eq.value), np.array([True, True])
        )


def test_declarative_on_bound_method():
    """r5 regression: declarative(layer.forward) on a BOUND method must
    keep its `self` through AST conversion (the converted function is
    re-bound; the r5 bench tool caught conversion dropping it)."""
    from paddle_tpu import dygraph
    from paddle_tpu.dygraph import declarative, to_variable
    from paddle_tpu.dygraph.nn import Linear

    class Net(dygraph.Layer):
        def __init__(self):
            super().__init__()
            self.fc = Linear(4, 4)

        def forward(self, x):
            y = self.fc(x)
            if L.reduce_sum(y) > 1e6:  # tensor condition: AST-converted
                y = y * 0.0
            return L.reduce_sum(y)

    with dygraph.guard():
        net = Net()
        traced = declarative(net.forward)
        x = to_variable(np.ones((2, 4), "float32"))
        out_eager = float(np.asarray(net(x).value))
        out_traced = float(np.asarray(traced(x).value))
        np.testing.assert_allclose(out_traced, out_eager, rtol=1e-6)
        # the bound Layer's parameters must be traced INPUTS, not baked
        # constants: grads flow to them, and a weight update is visible
        # on the next traced call (review r5 finding)
        loss = traced(x)
        loss.backward()
        w = net.fc.weight
        assert w.gradient() is not None, "no grad reached the bound self"
        net.clear_gradients()
        w.set_value(np.asarray(w.value) * 2.0)
        out_after = float(np.asarray(traced(x).value))
        np.testing.assert_allclose(out_after, 2.0 * out_traced, rtol=1e-5)
