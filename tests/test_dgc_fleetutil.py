"""DGC momentum optimizer + FleetUtil metric aggregation (the two
remaining COVERAGE gaps: reference DGCMomentumOptimizer optimizer.py:1071
and incubate fleet_util)."""

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import layers
from paddle_tpu.framework import unique_name


@pytest.fixture(autouse=True)
def fresh():
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 5
    scope = fluid.framework.scope.Scope()
    with fluid.program_guard(main, startup), fluid.scope_guard(scope), \
            unique_name.guard():
        yield main, startup, scope


def _fit_a_line(opt):
    x = fluid.data("x", [16, 4])
    y = fluid.data("y", [16, 1])
    loss = layers.mean(layers.square_error_cost(layers.fc(x, 1), y))
    opt.minimize(loss)
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    rng = np.random.RandomState(0)
    xv = rng.randn(16, 4).astype(np.float32)
    yv = (xv @ np.arange(4, dtype=np.float32).reshape(4, 1)).astype(
        np.float32)
    return exe, loss, {"x": xv, "y": yv}


def test_dgc_momentum_converges_single_process():
    exe, loss, feed = _fit_a_line(
        fluid.optimizer.DGCMomentum(0.05, momentum=0.9,
                                    rampup_begin_step=5,
                                    sparsity=[0.5])
    )
    losses = [
        float(np.asarray(exe.run(feed=feed, fetch_list=[loss])[0])
              .reshape(-1)[0])
        for _ in range(60)
    ]
    assert losses[-1] < losses[0] * 0.3, (losses[0], losses[-1])


def test_dgc_sent_ratio_and_error_feedback():
    """After rampup, only (1 - sparsity) of coordinates travel per step;
    error feedback keeps the rest in V so nothing is lost long-run."""
    exe, loss, feed = _fit_a_line(
        fluid.optimizer.DGCMomentum(0.05, momentum=0.9,
                                    rampup_begin_step=2,
                                    sparsity=[0.75])
    )
    blk = fluid.default_main_program().global_block
    ratio_vars = [n for n in blk.vars if n.endswith("@DGC_RATIO")]
    assert ratio_vars
    # step 1: warmup (dense, ratio 1); step 3: compressed
    r1 = exe.run(feed=feed, fetch_list=[ratio_vars[0]])[0]
    exe.run(feed=feed, fetch_list=[loss])
    r3 = exe.run(feed=feed, fetch_list=[ratio_vars[0]])[0]
    assert float(np.asarray(r1).reshape(-1)[0]) == 1.0
    assert float(np.asarray(r3).reshape(-1)[0]) == 0.25  # 1 of 4 weights


def test_dgc_matches_sgd_at_zero_sparsity():
    """sparsity=0 selects EVERY coordinate each step, so momentum-factor
    masking clears the velocity every step (Lin et al. 2017 §3.2) — DGC
    degenerates to exact plain SGD."""
    results = {}
    for kind in ("sgd", "dgc"):
        main, startup = fluid.Program(), fluid.Program()
        main.random_seed = startup.random_seed = 5
        scope = fluid.framework.scope.Scope()
        with fluid.program_guard(main, startup), fluid.scope_guard(scope), \
                unique_name.guard():
            opt = (fluid.optimizer.SGD(0.05)
                   if kind == "sgd"
                   else fluid.optimizer.DGCMomentum(
                       0.05, momentum=0.9, rampup_begin_step=0,
                       sparsity=[0.0]))
            exe, loss, feed = _fit_a_line(opt)
            results[kind] = [
                float(np.asarray(exe.run(feed=feed, fetch_list=[loss])[0])
                      .reshape(-1)[0])
                for _ in range(5)
            ]
    np.testing.assert_allclose(results["dgc"], results["sgd"], rtol=1e-4)


def test_fleet_util_global_auc_matches_sklearn():
    from paddle_tpu.fleet.util import FleetUtil

    rng = np.random.RandomState(0)
    scores = rng.rand(2000)
    labels = (rng.rand(2000) < scores).astype(np.int64)  # correlated
    bins = 512
    pos = np.zeros(bins)
    neg = np.zeros(bins)
    idx = np.minimum((scores * bins).astype(int), bins - 1)
    for i, l in zip(idx, labels):
        (pos if l else neg)[i] += 1

    fu = FleetUtil()  # single process: reduction is identity
    auc = fu.calc_global_auc(pos, neg)
    try:
        from sklearn.metrics import roc_auc_score

        ref = roc_auc_score(labels, scores)
    except ImportError:
        from scipy import stats as _st

        ref = 1 - _st.mannwhitneyu(
            scores[labels == 0], scores[labels == 1],
            alternative="greater").statistic / (
                (labels == 0).sum() * (labels == 1).sum())
    assert abs(auc - ref) < 5e-3, (auc, ref)


def test_fleet_util_metrics_dict():
    from paddle_tpu.fleet.util import FleetUtil

    fu = FleetUtil()
    out = fu.get_global_metrics({"loss": 1.5, "count": 32})
    assert out == {"count": 32.0, "loss": 1.5}


def test_dgc_sparse_exchange_on_mesh():
    """Under a dp mesh the emitter all_gathers (values, indices) pairs in
    shard_map; the training still converges with 8-way sharded batches."""
    from paddle_tpu.parallel import make_mesh, shard_program

    x = fluid.data("x", [16, 4])
    y = fluid.data("y", [16, 1])
    loss = layers.mean(layers.square_error_cost(layers.fc(x, 1), y))
    fluid.optimizer.DGCMomentum(
        0.05, momentum=0.9, rampup_begin_step=3, sparsity=[0.5],
        num_trainers=8,
    ).minimize(loss)
    shard_program(
        fluid.default_main_program(), make_mesh({"dp": 8}),
        {"x": ("dp",), "y": ("dp",)},
    )
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    rng = np.random.RandomState(0)
    xv = rng.randn(16, 4).astype(np.float32)
    yv = (xv @ np.arange(4, dtype=np.float32).reshape(4, 1)).astype(
        np.float32)
    losses = [
        float(np.asarray(exe.run(feed={"x": xv, "y": yv},
                                 fetch_list=[loss])[0]).reshape(-1)[0])
        for _ in range(60)
    ]
    assert all(np.isfinite(v) for v in losses)
    assert losses[-1] < losses[0] * 0.3, (losses[0], losses[-1])
