"""Test config: force an 8-device virtual CPU mesh so multi-chip sharding
paths run without TPU hardware (mirrors the reference's strategy of testing
distributed modes on localhost, test_dist_base.py:506).

Note: the axon sitecustomize imports jax at interpreter startup, so env vars
alone are too late — jax.config.update is required to switch platforms.
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
