"""Test config: force an 8-device virtual CPU mesh so multi-chip sharding
paths run without TPU hardware (mirrors the reference's strategy of testing
distributed modes on localhost, test_dist_base.py:506).

Note: the axon sitecustomize imports jax at interpreter startup, so env vars
alone are too late — jax.config.update is required to switch platforms.
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import copy  # noqa: E402

import pytest  # noqa: E402


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: long-running test (tier-1 verify runs -m 'not slow')"
    )


@pytest.fixture(autouse=True)
def _isolate_global_state():
    """Snapshot/restore every piece of process-global framework state so a
    test that mutates flags, the active mesh, the current scope, or the
    default programs cannot leak into later tests (order-dependent failures,
    e.g. the round-2 test_compiled_program_data_parallel_runs flake)."""
    from paddle_tpu import flags as _flags
    from paddle_tpu.framework import program as _prog
    from paddle_tpu.framework import scope as _scope
    from paddle_tpu.framework import unique_name as _un
    from paddle_tpu.observability import metrics as _met
    from paddle_tpu.observability import spans as _spans
    from paddle_tpu.parallel import mesh as _mesh
    from paddle_tpu.resilience import faults as _faults

    saved_metrics = copy.deepcopy(
        (_met._counters, _met._gauges, _met._histograms, _met._tables)
    )
    saved_enabled = _met._enabled
    saved_spans = list(_spans._spans)
    saved_flags = copy.deepcopy(_flags._FLAGS)
    saved_mesh = _mesh._current_mesh
    saved_scope = _scope._current_scope
    saved_main = _prog._main_program
    saved_startup = _prog._startup_program
    saved_device = _prog._current_device
    saved_gen = _un._generator
    saved_faults = (dict(_faults._registry), _faults._env_loaded)
    try:
        yield
    finally:
        for store, saved in zip(
            (_met._counters, _met._gauges, _met._histograms, _met._tables),
            saved_metrics,
        ):
            store.clear()
            store.update(saved)
        _met._enabled = saved_enabled
        _spans._spans.clear()
        _spans._spans.extend(saved_spans)
        _flags._FLAGS.clear()
        _flags._FLAGS.update(saved_flags)
        _mesh._current_mesh = saved_mesh
        _scope._current_scope = saved_scope
        _prog._main_program = saved_main
        _prog._startup_program = saved_startup
        _prog._current_device = saved_device
        _un._generator = saved_gen
        _faults._registry.clear()
        _faults._registry.update(saved_faults[0])
        _faults._env_loaded = saved_faults[1]
