"""Static program verifier (paddle_tpu/analysis): one deliberately broken
Program per finding category, the executor wiring (strict / warn / off),
the did-you-mean lookup diagnostics, and a clean bill over every bundled
model."""

import warnings

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import layers
from paddle_tpu.analysis import (
    COLLECTIVE_BRANCH_DIVERGENCE,
    COLLECTIVE_DIVERGENCE,
    DEAD_OP,
    DTYPE_DESYNC,
    MISSING_FEED,
    REDEFINITION,
    SHAPE_DESYNC,
    UNDECLARED_WRITE,
    UNKNOWN_MESH_AXIS,
    UNKNOWN_OP,
    UNREACHABLE_VAR,
    USE_BEFORE_DEF,
    Severity,
    set_verify_mode,
    verify_mode,
    verify_program,
)
from paddle_tpu.errors import (
    NotFoundError,
    ProgramVerifyError,
    ProgramVerifyWarning,
)
from paddle_tpu.framework import unique_name
from paddle_tpu.parallel import make_mesh, shard_program
from paddle_tpu.parallel.pipeline import slice_program_into_stages


@pytest.fixture(autouse=True)
def fresh():
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 7
    scope = fluid.framework.scope.Scope()
    with fluid.program_guard(main, startup), fluid.scope_guard(scope), \
            unique_name.guard():
        yield main, startup, scope
    set_verify_mode(None)  # never leak a mode override across tests


def _cats(report):
    return {f.category for f in report.findings}


# ---------------------------------------------------------------------------
# one broken program per category
# ---------------------------------------------------------------------------


def test_use_before_def_detected(fresh):
    main, _, _ = fresh
    blk = main.global_block
    fluid.data("x", [4, 4])
    blk.create_var(name="ghost", shape=[4, 4], dtype="float32")
    blk.create_var(name="out", shape=[4, 4], dtype="float32")
    blk.append_op("relu", {"X": ["ghost"]}, {"Out": ["out"]})
    rep = verify_program(main, ("x",), ("out",))
    (f,) = rep.by_category(USE_BEFORE_DEF)
    assert f.severity == Severity.ERROR
    assert "ghost" in f.names and f.op_type == "relu"
    assert not rep.ok


def test_use_before_def_names_late_producer(fresh):
    main, _, _ = fresh
    blk = main.global_block
    fluid.data("x", [4, 4])
    blk.create_var(name="late", shape=[4, 4], dtype="float32")
    blk.create_var(name="out", shape=[4, 4], dtype="float32")
    blk.append_op("relu", {"X": ["late"]}, {"Out": ["out"]})
    blk.append_op("relu", {"X": ["x"]}, {"Out": ["late"]})
    rep = verify_program(main, ("x",), ("out",))
    (f,) = rep.by_category(USE_BEFORE_DEF)
    assert "produced later" in f.message


def test_feeds_and_persistables_are_not_use_before_def(fresh):
    main, startup, _ = fresh
    x = fluid.data("x", [4, 4])
    y = layers.fc(x, 3)
    loss = layers.mean(y)
    fluid.optimizer.SGD(0.1).minimize(loss, startup)
    rep = verify_program(main, ("x",), (loss.name,))
    assert not rep.by_category(USE_BEFORE_DEF)
    assert rep.ok


def test_shadowing_redefinition_detected(fresh):
    main, _, _ = fresh
    blk = main.global_block
    blk.create_var(name="v", shape=[2, 2], dtype="float32")
    with pytest.warns(ProgramVerifyWarning, match="silently redefined"):
        blk.create_var(name="v", shape=[3, 3], dtype="float32")
    rep = verify_program(main)
    (f,) = rep.by_category(REDEFINITION)
    assert f.severity == Severity.WARNING
    assert "shape" in f.message
    # escalated under strict: counts as an error there, not in warn mode
    assert f in rep.strict_errors() and f not in rep.errors


def test_same_spec_redefinition_is_info_and_silent(fresh):
    main, _, _ = fresh
    blk = main.global_block
    blk.create_var(name="v", shape=[2, 2], dtype="float32")
    with warnings.catch_warnings():
        warnings.simplefilter("error", ProgramVerifyWarning)
        blk.create_var(name="v", shape=[2, 2], dtype="float32")  # no warn
    rep = verify_program(main)
    (f,) = rep.by_category(REDEFINITION)
    assert f.severity == Severity.INFO
    assert not rep.strict_errors()


def test_parameter_redefined_as_var_warns(fresh):
    main, _, _ = fresh
    blk = main.global_block
    blk.create_parameter("w", [2, 2], "float32")
    with pytest.warns(ProgramVerifyWarning, match="class Parameter"):
        blk.create_var(name="w", shape=[2, 2], dtype="float32",
                       persistable=True)


def test_shape_desync_detected(fresh):
    main, _, _ = fresh
    blk = main.global_block
    fluid.data("x", [4, 4])
    blk.create_var(name="out", shape=[9, 9], dtype="float32")
    blk.append_op("relu", {"X": ["x"]}, {"Out": ["out"]})
    rep = verify_program(main, ("x",), ("out",))
    (f,) = rep.by_category(SHAPE_DESYNC)
    assert f.severity == Severity.ERROR
    assert "(9, 9)" in f.message and "(4, 4)" in f.message


def test_batch_dim_is_shape_wildcard(fresh):
    main, _, _ = fresh
    blk = main.global_block
    fluid.data("x", [-1, 4])
    blk.create_var(name="out", shape=[-1, 4], dtype="float32")
    blk.append_op("relu", {"X": ["x"]}, {"Out": ["out"]})
    rep = verify_program(main, ("x",), ("out",))
    assert not rep.by_category(SHAPE_DESYNC)


def test_dtype_desync_detected(fresh):
    main, _, _ = fresh
    blk = main.global_block
    fluid.data("x", [4, 4])
    blk.create_var(name="out", shape=[4, 4], dtype="int64")
    blk.append_op("relu", {"X": ["x"]}, {"Out": ["out"]})
    rep = verify_program(main, ("x",), ("out",))
    (f,) = rep.by_category(DTYPE_DESYNC)
    assert f.severity == Severity.ERROR
    assert "int64" in f.message and "float32" in f.message


def test_dead_op_detected(fresh):
    main, _, _ = fresh
    blk = main.global_block
    x = fluid.data("x", [4, 4])
    live = layers.scale(x, scale=2.0)
    dead = layers.scale(x, scale=3.0)  # never fetched, feeds nothing
    rep = verify_program(main, ("x",), (live.name,))
    dead_findings = rep.by_category(DEAD_OP)
    assert len(dead_findings) == 1
    assert dead.name in dead_findings[0].names
    assert dead_findings[0].severity == Severity.INFO
    assert rep.ok  # INFO never fails a build


def test_unreachable_var_detected(fresh):
    main, _, _ = fresh
    blk = main.global_block
    blk.create_var(name="orphan", shape=[2], dtype="float32")
    rep = verify_program(main)
    (f,) = rep.by_category(UNREACHABLE_VAR)
    assert "orphan" in f.names


def test_unknown_op_detected(fresh):
    main, _, _ = fresh
    blk = main.global_block
    fluid.data("x", [4, 4])
    blk.create_var(name="out", shape=[4, 4], dtype="float32")
    blk.append_op("definitely_not_an_op", {"X": ["x"]}, {"Out": ["out"]})
    rep = verify_program(main, ("x",), ("out",))
    (f,) = rep.by_category(UNKNOWN_OP)
    assert f.severity == Severity.ERROR
    assert f.op_type == "definitely_not_an_op"


def test_undeclared_write_detected(fresh):
    main, _, _ = fresh
    blk = main.global_block
    fluid.data("x", [4, 4])
    blk.append_op("relu", {"X": ["x"]}, {"Out": ["nowhere_declared"]})
    rep = verify_program(main, ("x",), ())
    (f,) = rep.by_category(UNDECLARED_WRITE)
    assert "nowhere_declared" in f.names


def test_missing_feed_detected(fresh):
    main, _, _ = fresh
    blk = main.global_block
    fluid.data("x", [4, 4])
    fluid.data("y", [4, 4])
    blk.create_var(name="out", shape=[4, 4], dtype="float32")
    blk.append_op("elementwise_add", {"X": ["x"], "Y": ["y"]},
                  {"Out": ["out"]})
    rep = verify_program(main, ("x",), ("out",))  # y not fed
    (f,) = rep.by_category(MISSING_FEED)
    assert "y" in f.names and f.severity == Severity.ERROR


# ---------------------------------------------------------------------------
# collective schedule
# ---------------------------------------------------------------------------


def _pipeline_program(poison_stage=None):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.data("x", [8, 4])
        with fluid.device_guard("pipeline:0"):
            h = layers.fc(x, 4)
        with fluid.device_guard("pipeline:1"):
            loss = layers.mean(layers.fc(h, 4))
        main._pipeline = {"num_microbatches": 2, "axis_name": "pp"}
        _, pipe_op = slice_program_into_stages(main, loss)
    if poison_stage is not None:
        stage = main.blocks[pipe_op.attr("stage_blocks")[poison_stage]]
        stage.append_op(
            "c_allreduce_sum", {"X": [h.name]}, {"Out": [h.name]},
            {"axis_name": "dp"},
        )
    mesh = make_mesh({"dp": 4, "pp": 2})
    shard_program(main, mesh, {"x": ("dp",)})
    return main, loss


def test_mismatched_collective_order_detected(fresh):
    main, loss = _pipeline_program(poison_stage=0)
    rep = verify_program(main, ("x",), (loss.name,))
    (f,) = rep.by_category(COLLECTIVE_DIVERGENCE)
    assert f.severity == Severity.ERROR
    # the finding names the op, the axis, and the user source line
    assert f.op_type == "c_allreduce_sum"
    assert "dp" in f.names
    assert f.loc and "test_program_analysis.py" in f.loc


def test_uniform_collective_schedule_is_clean(fresh):
    main, loss = _pipeline_program(poison_stage=None)
    rep = verify_program(main, ("x",), (loss.name,))
    assert not rep.by_category(COLLECTIVE_DIVERGENCE)
    assert rep.ok


def test_unknown_mesh_axis_detected(fresh):
    main, _, _ = fresh
    blk = main.global_block
    x = fluid.data("x", [8, 4])
    blk.create_var(name="red", shape=[8, 4], dtype="float32")
    blk.append_op("c_allreduce_sum", {"X": ["x"]}, {"Out": ["red"]},
                  {"axis_name": "dpp"})  # typo'd axis
    shard_program(main, make_mesh({"dp": 8}))
    rep = verify_program(main, ("x",), ("red",))
    (f,) = rep.by_category(UNKNOWN_MESH_AXIS)
    assert f.severity == Severity.WARNING
    assert "dpp" in f.names


def test_collective_in_divergent_cond_branches_flagged(fresh):
    main, _, _ = fresh
    blk = main.global_block
    x = fluid.data("x", [8, 4])
    cond_v = fluid.data("c", [1], "bool")
    tb = main.create_block()
    main.rollback()
    tb.append_op("c_allreduce_sum", {"X": ["x"]}, {"Out": ["x"]},
                 {"axis_name": "dp"})
    fb = main.create_block()
    main.rollback()
    blk.create_var(name="out", shape=[8, 4], dtype="float32")
    blk.append_op(
        "cond", {"Cond": ["c"], "TrueIn": ["x"], "FalseIn": ["x"]},
        {"Out": ["out"]},
        {"true_block": tb.idx, "false_block": fb.idx,
         "true_out_names": ["x"], "false_out_names": ["x"]},
    )
    shard_program(main, make_mesh({"dp": 8}))
    rep = verify_program(main, ("x", "c"), ("out",))
    (f,) = rep.by_category(COLLECTIVE_BRANCH_DIVERGENCE)
    assert f.op_type == "cond"


def test_collective_hidden_in_recompute_segment_detected(fresh):
    """recompute_segment folds ops into a `sub_ops` attr, not a sub-block;
    a collective rematerialized inside one stage's segment must still
    count toward that rank's stream."""
    main, loss = _pipeline_program(poison_stage=None)
    pipe_op = main.global_block.ops[0]
    stage0 = main.blocks[pipe_op.attr("stage_blocks")[0]]
    h = pipe_op.attr("boundary_names")[0]
    stage0.append_op(
        "recompute_segment", {"X": [h]}, {"Out": [h]},
        {"sub_ops": [("c_allreduce_sum", {"X": [h]}, {"Out": [h]},
                      {"axis_name": "dp"})],
         "in_names": [h], "out_names": [h]},
    )
    main._bump()
    rep = verify_program(main, ("x",), (loss.name,))
    (f,) = rep.by_category(COLLECTIVE_DIVERGENCE)
    assert f.op_type == "c_allreduce_sum"


def test_meshless_program_skips_collective_analysis(fresh):
    main, _, _ = fresh
    blk = main.global_block
    x = fluid.data("x", [8, 4])
    blk.create_var(name="red", shape=[8, 4], dtype="float32")
    blk.append_op("c_allreduce_sum", {"X": ["x"]}, {"Out": ["red"]},
                  {"axis_name": "nonexistent"})
    rep = verify_program(main, ("x",), ("red",))
    assert not rep.by_category(UNKNOWN_MESH_AXIS)


# ---------------------------------------------------------------------------
# sharded-weight-update collective kinds (zero_reduce_scatter /
# zero_all_gather, quantized variants, c_allreduce_any): one broken
# fixture per kind — a stage-divergent site of each must be an ERROR
# ---------------------------------------------------------------------------


def _poison_pipeline_with(op_type, attrs, out_shape):
    """A 2-stage pipeline whose stage-0 block gains one `op_type` site the
    other stage never issues — the canonical rank-divergence fixture."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.data("x", [8, 4])
        with fluid.device_guard("pipeline:0"):
            h = layers.fc(x, 4)
        with fluid.device_guard("pipeline:1"):
            loss = layers.mean(layers.fc(h, 4))
        main._pipeline = {"num_microbatches": 2, "axis_name": "pp"}
        _, pipe_op = slice_program_into_stages(main, loss)
    stage = main.blocks[pipe_op.attr("stage_blocks")[0]]
    stage.create_var(name="zout", shape=out_shape, dtype="float32")
    stage.append_op(op_type, {"X": [h.name]}, {"Out": ["zout"]}, attrs)
    shard_program(main, make_mesh({"dp": 4, "pp": 2}), {"x": ("dp",)})
    return main, loss


@pytest.mark.parametrize("op_type,attrs,out_shape,kind", [
    ("zero_reduce_scatter",
     {"axis_name": "dp", "pad_len": 32, "quant": "none"}, [32],
     "zero_reduce_scatter"),
    ("zero_all_gather",
     {"axis_name": "dp", "pad_len": 32, "shape": [8, 4], "quant": "none"},
     [8, 4], "zero_all_gather"),
    ("zero_reduce_scatter",
     {"axis_name": "dp", "pad_len": 1024, "quant": "int8",
      "quant_block": 256}, [1024],
     "zero_reduce_scatter:int8"),
    ("zero_all_gather",
     {"axis_name": "dp", "pad_len": 1024, "shape": [8, 4], "quant": "int8",
      "quant_block": 256}, [8, 4],
     "zero_all_gather:int8"),
    ("c_allreduce_any", {"axis_name": "dp"}, [8, 4], "c_allreduce_any"),
])
def test_divergent_sharded_update_site_detected(fresh, op_type, attrs,
                                                out_shape, kind):
    main, loss = _poison_pipeline_with(op_type, attrs, out_shape)
    rep = verify_program(main, ("x",), (loss.name,),
                         families=("collectives",))
    findings = rep.by_category(COLLECTIVE_DIVERGENCE)
    assert findings, f"{kind}: stage-divergent site not flagged"
    f = findings[0]
    assert f.severity == Severity.ERROR
    assert f.op_type == op_type
    assert kind in f.message


def test_quantized_wire_format_is_part_of_the_site_kind(fresh):
    """An int8-quantized reduce-scatter on one cond branch against a
    full-precision one on the other is a payload mismatch, not a match:
    the branch-divergence lint must see two DIFFERENT kinds."""
    main, _, _ = fresh
    blk = main.global_block
    fluid.data("x", [8, 4])
    cond_v = fluid.data("c", [1], "bool")
    branches = []
    for quant in ("none", "int8"):
        b = main.create_block()
        main.rollback()
        b.create_var(name=f"zs_{quant}", shape=[1024], dtype="float32")
        b.append_op(
            "zero_reduce_scatter", {"X": ["x"]}, {"Out": [f"zs_{quant}"]},
            {"axis_name": "dp", "pad_len": 1024, "quant": quant,
             "quant_block": 256},
        )
        branches.append(b)
    blk.create_var(name="out", shape=[8, 4], dtype="float32")
    blk.append_op(
        "cond", {"Cond": [cond_v.name], "TrueIn": ["x"], "FalseIn": ["x"]},
        {"Out": ["out"]},
        {"true_block": branches[0].idx, "false_block": branches[1].idx,
         "true_out_names": ["x"], "false_out_names": ["x"]},
    )
    shard_program(main, make_mesh({"dp": 8}))
    rep = verify_program(main, ("x", "c"), ("out",),
                         families=("collectives",))
    (f,) = rep.by_category(COLLECTIVE_BRANCH_DIVERGENCE)
    assert "zero_reduce_scatter:int8" in f.message
    assert "zero_reduce_scatter@dp" in f.message


def test_sharded_weight_update_program_is_error_clean(fresh):
    """The real ShardedWeightUpdate transpile (AMP + int8) must come out of
    the full verifier with zero ERROR findings — the lint understands the
    new collective pattern end to end."""
    from paddle_tpu.contrib import mixed_precision as mp
    from paddle_tpu.parallel.transpiler import ShardedWeightUpdate

    main, startup, _ = fresh
    x = fluid.data("x", [8, 16])
    y = fluid.data("y", [8, 1])
    loss = layers.mean(layers.square_error_cost(
        layers.fc(layers.fc(x, 32, act="relu"), 1), y
    ))
    opt = mp.decorate(fluid.optimizer.Adam(0.01), dest_dtype="bfloat16")
    _, pg = opt.minimize(loss, startup)
    ShardedWeightUpdate(2, quant="int8").transpile(main, startup, pg)
    import jax

    shard_program(main, make_mesh({"dp": 2}, jax.devices()[:2]),
                  {"x": ("dp",), "y": ("dp",)})
    rep = verify_program(main, ("x", "y"), (loss.name,))
    errors = [f for f in rep.findings if f.severity == Severity.ERROR]
    assert not errors, [f.format() for f in errors]


# ---------------------------------------------------------------------------
# embedding-engine lookup kinds (PR 11): the fused/partitioned/quantized
# lookups are collective-bearing sites — one broken fixture per new kind
# ---------------------------------------------------------------------------


def _poison_pipeline_with_lookup(op_type, attrs):
    """A 2-stage pipeline whose stage-0 block gains one lookup site over
    the bound "ps" axis that the other stage never issues."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.data("x", [8, 4])
        with fluid.device_guard("pipeline:0"):
            h = layers.fc(x, 4)
        with fluid.device_guard("pipeline:1"):
            loss = layers.mean(layers.fc(h, 4))
        main._pipeline = {"num_microbatches": 2, "axis_name": "pp"}
        _, pipe_op = slice_program_into_stages(main, loss)
    stage = main.blocks[pipe_op.attr("stage_blocks")[0]]
    stage.create_var(name="lk_ids", shape=[8], dtype="int64")
    stage.create_var(name="lk_w", shape=[32, 4], dtype="float32")
    stage.create_var(name="lk_out", shape=[8, 4], dtype="float32")
    stage.append_op(
        op_type, {"Ids": ["lk_ids"], "W": ["lk_w"]}, {"Out": ["lk_out"]},
        attrs,
    )
    shard_program(main, make_mesh({"ps": 4, "pp": 2}), {"x": ("ps",)})
    return main, loss


@pytest.mark.parametrize("op_type,attrs,kind", [
    ("fused_lookup_table", {"axis_name": "ps"}, "fused_lookup_table"),
    ("fused_lookup_table",
     {"axis_name": "ps", "quant": "int8", "quant_block": 256},
     "fused_lookup_table:int8"),
    ("fused_lookup_table", {"axis_name": "ps", "partition": "col"},
     "fused_lookup_table:col"),
    ("distributed_lookup_table",
     {"axis_name": "ps", "quant": "int8", "quant_block": 256},
     "distributed_lookup_table:int8"),
    ("distributed_lookup_table", {"axis_name": "ps", "partition": "col"},
     "distributed_lookup_table:col"),
])
def test_divergent_lookup_site_detected(fresh, op_type, attrs, kind):
    main, loss = _poison_pipeline_with_lookup(op_type, attrs)
    rep = verify_program(main, ("x",), (loss.name,),
                         families=("collectives",))
    findings = rep.by_category(COLLECTIVE_DIVERGENCE)
    assert findings, f"{kind}: stage-divergent lookup site not flagged"
    f = findings[0]
    assert f.severity == Severity.ERROR
    assert f.op_type == op_type
    assert kind in f.message


def test_lookup_quant_wire_format_is_part_of_the_site_kind(fresh):
    """An int8 grad-exchange lookup on one cond branch against an fp32 one
    on the other is a different collective sequence — the branch lint must
    see two DIFFERENT kinds (exactly the zero_reduce_scatter contract)."""
    main, _, _ = fresh
    blk = main.global_block
    fluid.data("ids", [8], "int64")
    cond_v = fluid.data("c", [1], "bool")
    blk.create_var(name="w", shape=[32, 4], dtype="float32",
                   persistable=True)
    branches = []
    for quant in ("none", "int8"):
        b = main.create_block()
        main.rollback()
        b.create_var(name=f"lk_{quant}", shape=[8, 4], dtype="float32")
        b.append_op(
            "fused_lookup_table", {"Ids": ["ids"], "W": ["w"]},
            {"Out": [f"lk_{quant}"]},
            {"axis_name": "ps", "quant": quant, "quant_block": 256},
        )
        branches.append(b)
    blk.create_var(name="out", shape=[8, 4], dtype="float32")
    blk.append_op(
        "cond",
        {"Cond": [cond_v.name], "TrueIn": ["ids"], "FalseIn": ["ids"]},
        {"Out": ["out"]},
        {"true_block": branches[0].idx, "false_block": branches[1].idx,
         "true_out_names": ["ids"], "false_out_names": ["ids"]},
    )
    shard_program(main, make_mesh({"ps": 8}))
    rep = verify_program(main, ("ids", "c"), ("out",),
                         families=("collectives",))
    (f,) = rep.by_category(COLLECTIVE_BRANCH_DIVERGENCE)
    assert "fused_lookup_table:int8" in f.message
    assert "fused_lookup_table@ps" in f.message


def test_fused_deepfm_zoo_model_is_error_clean(fresh):
    """The real fused + ps-sharded DeepFM (zoo: deepfm_fused) must come out
    of the FULL verifier with zero ERROR findings."""
    from paddle_tpu.models.zoo import build_model

    bm = build_model("deepfm_fused")
    rep = verify_program(bm.main, bm.feed_names, bm.fetch_names)
    errors = [f for f in rep.findings if f.severity == Severity.ERROR]
    assert not errors, [f.format() for f in errors]


# ---------------------------------------------------------------------------
# executor wiring: strict rejects, warn warns, off is silent
# ---------------------------------------------------------------------------


def test_strict_mode_rejects_divergent_program_before_trace(fresh):
    main, loss = _pipeline_program(poison_stage=0)
    set_verify_mode("strict")
    exe = fluid.Executor()
    with pytest.raises(ProgramVerifyError) as ei:
        exe.run(main, feed={"x": np.ones((8, 4), "float32")},
                fetch_list=[loss])
    err = ei.value
    assert err.findings, "typed error must carry the structured findings"
    msgs = str(err)
    assert "collective" in msgs and "dp" in msgs
    assert "test_program_analysis.py" in msgs  # user source line


def test_strict_mode_rejects_use_before_def_at_run(fresh):
    main, _, _ = fresh
    blk = main.global_block
    fluid.data("x", [4, 4])
    blk.create_var(name="ghost", shape=[4, 4], dtype="float32")
    blk.create_var(name="out", shape=[4, 4], dtype="float32")
    blk.append_op("relu", {"X": ["ghost"]}, {"Out": ["out"]})
    set_verify_mode("strict")
    exe = fluid.Executor()
    with pytest.raises(ProgramVerifyError):
        exe.run(main, feed={"x": np.ones((4, 4), "float32")},
                fetch_list=["out"])


def test_strict_mode_rejects_shape_desync_at_run(fresh):
    """strict is the mode that replays shape inference at compile time."""
    main, _, _ = fresh
    blk = main.global_block
    fluid.data("x", [4, 4])
    blk.create_var(name="out", shape=[9, 9], dtype="float32")  # desynced
    blk.append_op("relu", {"X": ["x"]}, {"Out": ["out"]})
    set_verify_mode("strict")
    exe = fluid.Executor()
    with pytest.raises(ProgramVerifyError, match="shape-desync"):
        exe.run(main, feed={"x": np.ones((4, 4), "float32")},
                fetch_list=["out"])


def test_warn_mode_warns_and_still_runs(fresh):
    main, _, _ = fresh
    blk = main.global_block
    x = fluid.data("x", [4, 4])
    out = layers.scale(x, scale=2.0)
    # undeclared write: a WARNING from the structural family, which warn
    # mode runs at compile time — the program still executes
    blk.append_op("relu", {"X": [out.name]}, {"Out": ["undeclared_sink"]})
    set_verify_mode("warn")
    exe = fluid.Executor()
    with pytest.warns(ProgramVerifyWarning, match="undeclared-write"):
        (got,) = exe.run(main, feed={"x": np.ones((4, 4), "float32")},
                         fetch_list=[out])
    np.testing.assert_allclose(got, 2.0 * np.ones((4, 4)))


def test_off_mode_skips_verification(fresh):
    main, _, _ = fresh
    blk = main.global_block
    fluid.data("x", [4, 4])
    blk.create_var(name="out", shape=[9, 9], dtype="float32")
    blk.append_op("relu", {"X": ["x"]}, {"Out": ["out"]})
    set_verify_mode("off")
    exe = fluid.Executor()
    with warnings.catch_warnings():
        warnings.simplefilter("error", ProgramVerifyWarning)
        exe.run(main, feed={"x": np.ones((4, 4), "float32")},
                fetch_list=["out"])


def test_verify_mode_env_parsing(fresh, monkeypatch):
    set_verify_mode(None)
    for raw, want in (
        ("strict", "strict"), ("warn", "warn"), ("0", "off"),
        ("off", "off"), ("", "off"), ("garbage", "warn"),
    ):
        monkeypatch.setenv("PADDLE_TPU_VERIFY", raw)
        assert verify_mode() == want
    monkeypatch.delenv("PADDLE_TPU_VERIFY")
    assert verify_mode() == "warn"
    with pytest.raises(ValueError):
        set_verify_mode("not-a-mode")


def test_verify_cached_per_program_version(fresh):
    from paddle_tpu import observability as obs

    main, startup, _ = fresh
    x = fluid.data("x", [-1, 4])
    y = layers.scale(x, scale=2.0)
    set_verify_mode("warn")
    exe = fluid.Executor()
    before = obs.snapshot()["counters"].get("analysis.programs_verified", 0)
    exe.run(main, feed={"x": np.ones((4, 4), "float32")}, fetch_list=[y])
    # same program version, new feed shape -> recompile, but NO re-verify
    exe.run(main, feed={"x": np.ones((8, 4), "float32")}, fetch_list=[y])
    after = obs.snapshot()["counters"].get("analysis.programs_verified", 0)
    assert after == before + 1


def test_verify_cache_keeps_multiple_fetch_sets(fresh):
    """Alternating fetch sets each verify ONCE: the cache is a bounded
    dict keyed per (version, feeds, fetches), not a single entry a
    different key evicts on every flip."""
    from paddle_tpu import observability as obs

    main, startup, _ = fresh
    x = fluid.data("x", [-1, 4])
    a = layers.scale(x, scale=2.0)
    b = layers.scale(x, scale=3.0)
    set_verify_mode("warn")
    exe = fluid.Executor()
    feed = {"x": np.ones((4, 4), "float32")}
    before = obs.snapshot()["counters"].get("analysis.programs_verified", 0)
    for _ in range(3):  # a<->b thrash: 2 verifies total, not 6
        exe.run(main, feed=feed, fetch_list=[a])
        exe.run(main, feed=feed, fetch_list=[b])
    after = obs.snapshot()["counters"].get("analysis.programs_verified", 0)
    assert after == before + 2


def test_verify_cache_is_bounded(fresh):
    from paddle_tpu.analysis.verify import (
        _VERIFY_CACHE_CAPACITY,
        check_before_compile,
    )

    main, _, _ = fresh
    x = fluid.data("x", [-1, 4])
    y = layers.scale(x, scale=2.0)
    set_verify_mode("warn")
    for i in range(_VERIFY_CACHE_CAPACITY + 5):
        check_before_compile(main, ("x",), (y.name, f"alias_{i}"))
    assert len(main.__dict__["_verify_cache"]) <= _VERIFY_CACHE_CAPACITY


def test_render_caps_per_severity_with_elision_tail(fresh):
    from paddle_tpu.analysis.findings import Finding, Report

    report = Report()
    for i in range(30):
        report.add(Finding(Severity.WARNING, REDEFINITION, f"w{i}"))
    for i in range(3):
        report.add(Finding(Severity.ERROR, USE_BEFORE_DEF, f"e{i}"))
    text = report.render(max_per_severity=25)
    assert text.count("ERROR[") == 3  # under the cap: all shown
    assert text.count("WARNING[") == 25
    assert "+5 more WARNING finding(s) (redefinition x5)" in text
    assert len(report.warnings) == 30  # the full list survives on the report
    everything = report.render(max_per_severity=None)
    assert everything.count("WARNING[") == 30
    assert "more" not in everything


def test_observability_counters_and_latency(fresh):
    from paddle_tpu import observability as obs

    main, _, _ = fresh
    blk = main.global_block
    fluid.data("x", [4, 4])
    blk.create_var(name="out", shape=[9, 9], dtype="float32")
    blk.append_op("relu", {"X": ["x"]}, {"Out": ["out"]})
    obs.reset()
    verify_program(main, ("x",), ("out",))
    snap = obs.snapshot()
    assert snap["counters"]["analysis.programs_verified"] == 1
    assert snap["counters"]["analysis.findings.error"] >= 1
    assert snap["histograms"]["analysis.verify_latency"]["count"] == 1


# ---------------------------------------------------------------------------
# did-you-mean lookup diagnostics
# ---------------------------------------------------------------------------


def test_var_lookup_suggests_nearest_name(fresh):
    main, _, _ = fresh
    fluid.data("learning_rate", [1])
    main.global_block.create_parameter("fc_weight", [4, 4], "float32")
    with pytest.raises(NotFoundError) as ei:
        main.global_block.var("fc_wieght")
    msg = str(ei.value)
    assert "did you mean" in msg and "'fc_weight'" in msg
    assert "feeds: [learning_rate]" in msg
    assert "persistables: [fc_weight]" in msg


def test_var_lookup_without_close_match_names_sets(fresh):
    main, _, _ = fresh
    fluid.data("x", [1])
    with pytest.raises(NotFoundError) as ei:
        main.global_block.var("zzzzqqqq")
    msg = str(ei.value)
    assert "did you mean" not in msg
    assert "declares 1 vars" in msg


# ---------------------------------------------------------------------------
# clean bill over every bundled model
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("model", [
    "resnet", "bert", "gpt", "deepfm", "bert_3d",
    pytest.param("yolov3", marks=pytest.mark.slow),
    pytest.param("mask_rcnn", marks=pytest.mark.slow),
])
def test_bundled_model_clean_bill(fresh, model):
    from paddle_tpu.models import build_model

    bm = build_model(model)
    rep = verify_program(bm.main, bm.feed_names, bm.fetch_names)
    assert not rep.strict_errors(), rep.render(Severity.WARNING)
    startup_rep = verify_program(bm.startup)
    assert not startup_rep.strict_errors(), startup_rep.render(
        Severity.WARNING
    )
