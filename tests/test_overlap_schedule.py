"""Communication/compute overlap (ROADMAP item 4): bucketed grad
collectives + prefetched all-gathers.

The schedule transforms are pure reorderings/regroupings, so every fp32
leg here asserts BITWISE parity against the serialized per-grad schedule
(dp=2 and dp=8 in-process submeshes), int8 against the per-grad int8 path
(bitwise too: member pads are block-aligned, so the quant blocks and
scales are identical). The lint leg proves a rank-divergent bucketing is
a build-time ERROR, and the cost-model leg pins the overlap-aware
scheduled estimate's op goldens.
"""

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import layers, observability
from paddle_tpu.framework import unique_name
from paddle_tpu.framework.scope import Scope
from paddle_tpu.parallel import make_mesh, shard_program
from paddle_tpu.parallel.transpiler import (
    GradAllReduce,
    ShardedWeightUpdate,
    plan_grad_buckets,
)

B, D, H, STEPS = 8, 16, 32, 4


@pytest.fixture(autouse=True)
def fresh_programs():
    main, startup = fluid.Program(), fluid.Program()
    scope = fluid.framework.scope.Scope()
    with fluid.program_guard(main, startup), fluid.scope_guard(scope), \
            unique_name.guard():
        yield


def _feed(i):
    rng = np.random.RandomState(100 + i)
    return {
        "x": rng.randn(B, D).astype(np.float32),
        "y": rng.randn(B, 1).astype(np.float32),
    }


def _train(mode, nranks=2, steps=STEPS, quant=None, bucket=None,
           prefetch=False, depth=2, return_numpy=False):
    """Train the reference MLP under `mode` ("allreduce" | "sharded") on
    a dp=`nranks` in-process submesh with the requested overlap knobs;
    returns (losses, main program)."""
    import jax

    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 7
    scope = Scope()
    with fluid.program_guard(main, startup), fluid.scope_guard(scope), \
            unique_name.guard():
        x = fluid.data("x", [B, D])
        y = fluid.data("y", [B, 1])
        h = x
        for _ in range(depth):
            h = layers.fc(h, H, act="relu")
        pred = layers.fc(h, 1)
        loss = layers.mean(layers.square_error_cost(pred, y))
        _, pg = fluid.optimizer.Adam(0.01).minimize(loss, startup)
        blk = main.global_block
        if mode == "allreduce":
            GradAllReduce(nranks, bucket_bytes=bucket).transpile(main, pg)
        else:
            ShardedWeightUpdate(
                nranks, quant=quant, bucket_bytes=bucket, prefetch=prefetch,
            ).transpile(main, startup, pg)
        blk.append_op("scale", {"X": [loss.name]}, {"Out": [loss.name]},
                      {"scale": 1.0 / nranks, "bias": 0.0})
        blk.append_op("c_allreduce_sum", {"X": [loss.name]},
                      {"Out": [loss.name]}, {"axis_name": "dp"})
        shard_program(
            main, make_mesh({"dp": nranks}, jax.devices()[:nranks]),
            {"x": ("dp",), "y": ("dp",)},
        )
        exe = fluid.Executor()
        exe.run(startup, scope=scope)
        losses = []
        for i in range(steps):
            (lv,) = exe.run(main, feed=_feed(i), fetch_list=[loss],
                            scope=scope, return_numpy=return_numpy)
            losses.append(np.asarray(lv).reshape(-1)[0].copy())
    return np.array(losses), main


# ---------------------------------------------------------------------------
# bucket planning goldens
# ---------------------------------------------------------------------------


class _FakeBlock:
    """Minimal producer stream for plan_grad_buckets: op i produces
    grad gi."""

    def __init__(self, names):
        class _Op:
            def __init__(self, name):
                self._n = name
                self.type = "relu"

            def output_names(self):
                return [self._n]

        self.ops = [_Op(n) for n in names]


def test_bucket_plan_straddle_golden():
    """A grad that would push a non-empty bucket past the target CLOSES
    it and opens the next — straddling grads move whole, never split; an
    oversize grad gets a bucket of its own."""
    blk = _FakeBlock(["g0", "g1", "g2", "g3"])
    entries = [
        {"name": "g0", "numel": 10, "nbytes": 40, "group": "float32"},
        {"name": "g1", "numel": 10, "nbytes": 40, "group": "float32"},
        {"name": "g2", "numel": 10, "nbytes": 40, "group": "float32"},  # straddles
        {"name": "g3", "numel": 100, "nbytes": 400, "group": "float32"},  # oversize
    ]
    buckets = plan_grad_buckets(blk, entries, bucket_bytes=100)
    got = [[e["name"] for e in b["members"]] for b in buckets]
    assert got == [["g0", "g1"], ["g2"], ["g3"]], got
    # each bucket fires just after its LAST member's producer
    assert [b["pos"] for b in buckets] == [2, 3, 4]


def test_bucket_plan_orders_by_production_and_groups_dtype():
    """Grads bucket in backward-production (reverse-topological) order
    regardless of entry order, and dtypes never share a bucket (members
    concatenate into one exchange buffer)."""
    blk = _FakeBlock(["g0", "g1", "g2"])
    entries = [  # handed over in reversed order on purpose
        {"name": "g2", "numel": 1, "nbytes": 4, "group": "float32"},
        {"name": "g1", "numel": 1, "nbytes": 2, "group": "bfloat16"},
        {"name": "g0", "numel": 1, "nbytes": 4, "group": "float32"},
    ]
    buckets = plan_grad_buckets(blk, entries, bucket_bytes=1 << 20)
    by_group = {b["group"]: [e["name"] for e in b["members"]]
                for b in buckets}
    assert by_group["float32"] == ["g0", "g2"]  # production order
    assert by_group["bfloat16"] == ["g1"]
    with pytest.raises(ValueError, match="positive"):
        plan_grad_buckets(blk, entries, bucket_bytes=0)


def test_bucketed_firing_order_is_reverse_topological():
    """In the transpiled program the bucket collectives appear in
    backward-production order (last forward layer's grads fire first) and
    each sits at its last member's producer — NOT at the program tail."""
    import re

    _, main = _train("sharded", bucket=600, prefetch=False, depth=3)
    block = main.global_block
    bucket_idx = [i for i, op in enumerate(block.ops)
                  if op.type == "zero_bucket_reduce_scatter"]
    assert len(bucket_idx) > 1
    assert bucket_idx == sorted(bucket_idx)

    def layer_of(name):  # fc_w_3@GRAD -> 3
        return int(re.search(r"_(\d+)@", name).group(1))

    # reverse-topological: the FIRST bucket carries the LAST fc layer's
    # grads (produced earliest in the backward), the last bucket the
    # first layer's
    first_members = block.ops[bucket_idx[0]].inputs["X"]
    last_members = block.ops[bucket_idx[-1]].inputs["X"]
    assert max(layer_of(n) for n in first_members) > max(
        layer_of(n) for n in last_members
    )
    # the first bucket fires while backward compute REMAINS — grad
    # producers (vjp ops) still follow it, so its wire can hide
    later_types = [op.type for op in block.ops[bucket_idx[0] + 1:]]
    assert "__vjp__" in later_types, (
        "first bucket must fire while backward compute remains"
    )
    # membership is disjoint and covers all dense grads
    all_members = [n for i in bucket_idx for n in block.ops[i].inputs["X"]]
    assert len(all_members) == len(set(all_members))
    assert set(last_members).isdisjoint(first_members)


# ---------------------------------------------------------------------------
# bitwise parity: overlapped vs serialized
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("nranks", [2, 8])
def test_bucketed_allreduce_bitwise_matches_per_grad(nranks):
    """Satellite bugfix leg: the non-ZeRO dp path routed through the
    bucketing machinery is BITWISE the per-grad c_allreduce_sum schedule
    (elementwise sums are unchanged by concatenation), at dp=2 and dp=8."""
    la, main_a = _train("allreduce", nranks=nranks)
    lb, main_b = _train("allreduce", nranks=nranks, bucket=1 << 20)
    np.testing.assert_array_equal(la, lb)
    types_a = [op.type for op in main_a.global_block.ops]
    types_b = [op.type for op in main_b.global_block.ops]
    # per-grad: one allreduce per grad (+ the loss mean); bucketed: ONE
    # bucket collective, only the loss allreduce left per-tensor
    assert types_b.count("c_bucket_allreduce_sum") == 1
    assert types_b.count("c_allreduce_sum") == 1
    assert types_a.count("c_allreduce_sum") > 2


def test_overlapped_zero_bitwise_matches_serialized():
    """Tentpole parity: bucketed reduce-scatters + prefetched all-gathers
    reproduce the serialized ZeRO loss trajectory BITWISE in fp32."""
    l0, m0 = _train("sharded")
    l1, m1 = _train("sharded", bucket=1 << 20, prefetch=True)
    l2, m2 = _train("sharded", prefetch=True)  # per-grad + prefetch only
    np.testing.assert_array_equal(l0, l1)
    np.testing.assert_array_equal(l0, l2)
    assert not getattr(m0, "_overlap_schedule", False)
    assert getattr(m1, "_overlap_schedule", False)
    assert getattr(m2, "_overlap_schedule", False)
    # prefetch interleaved the updates + all-gathers into the backward:
    # the first all-gather sits before the last grad producer (per-grad
    # reduce-scatters fire at each grad's true production point, so the
    # hoisted update/gather pair rides right behind it)
    types = [op.type for op in m2.global_block.ops]
    first_gather = types.index("zero_all_gather")
    last_vjp = max(i for i, t in enumerate(types) if t == "__vjp__")
    assert first_gather < last_vjp


def test_overlapped_zero_int8_matches_per_grad_int8():
    """int8 leg: member pads are aligned to nranks*quant_block, so the
    bucketed exchange quantizes the SAME blocks with the SAME scales as
    the per-grad path — bitwise, not just tolerance."""
    q0, _ = _train("sharded", quant="int8")
    q1, _ = _train("sharded", quant="int8", bucket=1 << 20, prefetch=True)
    np.testing.assert_array_equal(q0, q1)
    # and the int8 trajectory stays within the PR-9 tolerance of fp32
    f0, _ = _train("allreduce")
    np.testing.assert_allclose(f0, q0, rtol=5e-2, atol=5e-2)


def test_multi_bucket_zero_bitwise():
    """Several small buckets (grads straddling bucket boundaries in a
    real program) still reproduce the serialized trajectory bitwise."""
    l0, _ = _train("sharded", depth=3)
    l1, main = _train("sharded", bucket=600, prefetch=True, depth=3)
    np.testing.assert_array_equal(l0, l1)
    n_buckets = sum(1 for op in main.global_block.ops
                    if op.type == "zero_bucket_reduce_scatter")
    assert n_buckets > 1


def test_fleet_bucket_knob_and_refusal():
    """DistributedStrategy.collective_bucket_mb=0 restores the per-grad
    schedule; a negative bucket size refuses loudly."""
    from paddle_tpu.fleet import collective as fc
    from paddle_tpu.fleet.role_maker import UserDefinedRoleMaker

    def minimize(bucket_mb):
        main, startup = fluid.Program(), fluid.Program()
        scope = Scope()
        with fluid.program_guard(main, startup), fluid.scope_guard(scope), \
                unique_name.guard():
            x = fluid.data("x", [B, D])
            y = fluid.data("y", [B, 1])
            loss = layers.mean(
                layers.square_error_cost(layers.fc(x, 1), y)
            )
            fleet = fc.Fleet()
            fleet.init(UserDefinedRoleMaker())
            strategy = fc.DistributedStrategy()
            strategy.collective_bucket_mb = bucket_mb
            opt = fleet.distributed_optimizer(
                fluid.optimizer.SGD(0.1), strategy
            )
            opt.minimize(loss)
        return main

    per_grad = minimize(0)
    types = [op.type for op in per_grad.global_block.ops]
    assert "c_bucket_allreduce_sum" not in types
    assert types.count("c_allreduce_sum") >= 2  # per-grad + loss mean
    bucketed = minimize(25.0)
    assert any(op.type == "c_bucket_allreduce_sum"
               for op in bucketed.global_block.ops)
    with pytest.raises(ValueError, match="bucket"):
        minimize(-1.0)


# ---------------------------------------------------------------------------
# rank-divergent bucketing is a build-time ERROR
# ---------------------------------------------------------------------------


def _divergent_bucket_program():
    """Pipeline stages that bucket the same exchange differently — the
    wire-layout mismatch the lint must reject at build time."""
    from paddle_tpu.parallel.pipeline import slice_program_into_stages

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup), unique_name.guard():
        x = fluid.data("x", [8, 4])
        with fluid.device_guard("pipeline:0"):
            h = layers.fc(x, 4)
        with fluid.device_guard("pipeline:1"):
            loss = layers.mean(layers.fc(h, 4))
        main._pipeline = {"num_microbatches": 2, "axis_name": "pp"}
        _, pipe_op = slice_program_into_stages(main, loss)
    for si, pads in ((0, [256, 256]), (1, [512])):
        stage = main.blocks[pipe_op.attr("stage_blocks")[si]]
        gname = f"divg{si}"
        stage.create_var(name=gname, shape=[4, 4], dtype="float32")
        stage.append_op("fill_constant", {}, {"Out": [gname]},
                        {"shape": [4, 4], "dtype": "float32", "value": 0.0})
        outs = []
        for j, p in enumerate(pads):
            on = f"divs{si}_{j}"
            stage.create_var(name=on, shape=[p], dtype="float32")
            outs.append(on)
        stage.append_op(
            "zero_bucket_reduce_scatter",
            {"X": [gname] * len(pads)}, {"Out": outs},
            {"axis_name": "dp", "pad_lens": pads, "quant": "none"},
        )
    shard_program(main, make_mesh({"dp": 4, "pp": 2}), {"x": ("dp",)})
    return main


def test_rank_divergent_bucketing_is_build_time_error():
    from paddle_tpu.analysis.collectives import analyze_collectives
    from paddle_tpu.analysis.findings import Severity

    findings = analyze_collectives(_divergent_bucket_program())
    errs = [f for f in findings if f.severity == Severity.ERROR]
    assert errs, "rank-divergent bucket membership must ERROR"
    assert any("zero_bucket_reduce_scatter[256,256]" in f.format()
               or "zero_bucket_reduce_scatter[512]" in f.format()
               for f in errs)


def test_quantized_bucket_kind_is_distinct():
    """fp32-vs-int8 bucket wire formats are DISTINCT site kinds, exactly
    like the per-grad zero collectives (PR 9)."""
    from paddle_tpu.analysis.collectives import collective_axis
    from paddle_tpu.framework.registry import OpView

    fp = OpView("zero_bucket_reduce_scatter",
                {"axis_name": "dp", "pad_lens": [256], "quant": "none"})
    q = OpView("zero_bucket_reduce_scatter",
               {"axis_name": "dp", "pad_lens": [256], "quant": "int8"})
    _, kfp = collective_axis(fp)
    _, kq = collective_axis(q)
    assert kfp == "zero_bucket_reduce_scatter[256]"
    assert kq == "zero_bucket_reduce_scatter[256]:int8"
    ar = OpView("c_bucket_allreduce_sum",
                {"axis_name": "dp", "bucket_numels": [10, 20]})
    _, kar = collective_axis(ar)
    assert kar == "c_bucket_allreduce_sum[10,20]"


# ---------------------------------------------------------------------------
# overlap-aware cost model
# ---------------------------------------------------------------------------


def test_bucket_collective_op_cost_goldens():
    """Closed forms: a bucket moves exactly its members' summed (padded,
    possibly quantized) ring bytes."""
    from paddle_tpu.analysis.cost import _quant_elem_bytes, op_cost
    from paddle_tpu.framework.registry import OpView

    n = 8
    pads = [2048, 4096]
    rs = OpView("zero_bucket_reduce_scatter",
                {"axis_name": "dp", "pad_lens": pads, "quant": "none"})
    grads = [((2000,), 4), ((4000,), 4)]
    flops, wire = op_cost(rs, {"X": grads}, {}, axis_sizes={"dp": n})
    assert wire == pytest.approx(sum(pads) * 4 * (n - 1) / n)
    assert flops == pytest.approx(sum(pads))
    q = OpView("zero_bucket_reduce_scatter",
               {"axis_name": "dp", "pad_lens": pads, "quant": "int8",
                "quant_block": 256})
    _, qwire = op_cost(q, {"X": grads}, {}, axis_sizes={"dp": n})
    assert qwire == pytest.approx(
        sum(pads) * _quant_elem_bytes("int8", 256, 4) * (n - 1) / n
    )
    assert qwire < 0.4 * wire
    ar = OpView("c_bucket_allreduce_sum", {"axis_name": "dp"})
    flops, arwire = op_cost(ar, {"X": grads}, {}, axis_sizes={"dp": n})
    assert arwire == pytest.approx(6000 * 4 * 2 * (n - 1) / n)
    assert flops == pytest.approx(6000)
    # unbound axis: identity degrade
    assert op_cost(ar, {"X": grads}, {}, axis_sizes={}) == (0.0, 0.0)


def test_scheduled_latency_simulation_golden():
    """The two-resource sim: a collective overlaps following compute
    until something READS its output; a serialized consumer chain
    degrades to the sum."""
    from paddle_tpu.analysis.cost import _scheduled_latency

    # compute 10, wire 6 issued, compute 10 (independent), read -> step:
    # wire runs [10, 16] while compute runs [10, 20] -> 20, then consumer 1
    entries = [
        (10.0, False, ("a",), ("b",)),
        (6.0, True, ("b",), ("c",)),
        (10.0, False, ("a",), ("d",)),
        (1.0, False, ("c",), ("e",)),  # waits for the wire (already done)
    ]
    assert _scheduled_latency(entries) == pytest.approx(21.0)
    # wire longer than the remaining compute: the tail is exposed
    entries = [
        (10.0, False, ("a",), ("b",)),
        (30.0, True, ("b",), ("c",)),
        (10.0, False, ("a",), ("d",)),
        (1.0, False, ("c",), ("e",)),
    ]
    assert _scheduled_latency(entries) == pytest.approx(41.0)
    # immediate consumer = fully serialized
    entries = [
        (10.0, False, ("a",), ("b",)),
        (6.0, True, ("b",), ("c",)),
        (1.0, False, ("c",), ("e",)),
    ]
    assert _scheduled_latency(entries) == pytest.approx(17.0)


def test_program_estimate_overlap_aware():
    """Program.estimate() on an overlap-transpiled program: scheduled
    step <= serialized sum, exposed wire <= total wire, overlap metrics
    in to_dict, and the serialized build keeps the PR-13 semantics."""
    _, m_serial = _train("sharded")
    _, m_over = _train("sharded", bucket=1 << 20, prefetch=True)
    feeds = {"x": (B, D), "y": (B, 1)}
    est_s = m_serial.estimate(feed_shapes=feeds)
    est_o = m_over.estimate(feed_shapes=feeds)
    assert est_s.scheduled_latency is None
    assert est_s.step_latency == est_s.total_latency
    assert est_s.wire_exposed_latency == pytest.approx(est_s.wire_latency)
    assert est_s.overlap_ratio == 0.0
    assert est_o.scheduled_latency is not None
    assert est_o.step_latency <= est_o.total_latency
    assert 0.0 < est_o.wire_exposed_latency <= est_o.wire_latency
    assert 0.0 <= est_o.overlap_ratio <= 1.0
    d = est_o.to_dict()
    for key in ("scheduled_latency", "wire_latency",
                "wire_exposed_latency", "overlap_ratio"):
        assert key in d
    assert any("overlap schedule" in a for a in d["assumptions"])


def test_executor_publishes_overlap_attribution():
    """The live attribution split on an overlapped dp=8 run: wait
    fractions sum to ~1, the est wire term is nonzero, and the
    collective.overlap_ratio gauge + est_wire_hidden_seconds land."""
    observability.reset()
    _train("sharded", nranks=8, bucket=1 << 20, prefetch=True, steps=3,
           return_numpy=True)
    snap = observability.snapshot()
    gauges = snap["gauges"]
    attr = snap["tables"].get("perf.step_attribution")
    assert attr is not None
    assert attr["est_wire_seconds"] > 0
    assert attr["est_wire_total_seconds"] >= attr["est_wire_seconds"]
    assert attr["est_wire_hidden_seconds"] >= 0
    assert 0.0 <= attr["est_overlap_ratio"] <= 1.0
    assert "collective.overlap_ratio" in gauges
    total = (gauges["perf.wait_fraction.collective"]
             + gauges["perf.wait_fraction.host"]
             + gauges["perf.wait_fraction.compute"])
    assert total == pytest.approx(1.0, abs=1e-6)
    counters = snap["counters"]
    assert counters.get("collective.buckets", 0) > 0
    assert counters.get("collective.bucket_bytes", 0) > 0
