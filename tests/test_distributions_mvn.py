"""MultivariateNormalDiag parity (VERDICT r2 item 10): sample moments,
entropy, log_prob and the KL pair matrix against scipy closed forms
(reference fluid/layers/distributions.py:383)."""

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu.framework import unique_name
from paddle_tpu.layers.distributions import MultivariateNormalDiag, Normal


@pytest.fixture(autouse=True)
def fresh():
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 9
    scope = fluid.framework.scope.Scope()
    with fluid.program_guard(main, startup), fluid.scope_guard(scope), \
            unique_name.guard():
        yield


def _run(fetches, feed=None):
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    return [np.asarray(v) for v in exe.run(feed=feed or {},
                                           fetch_list=list(fetches))]


def test_mvn_entropy_and_logprob_match_scipy():
    from scipy.stats import multivariate_normal

    loc = [0.5, -1.0, 2.0]
    sig = [0.8, 1.2, 2.0]
    mvn = MultivariateNormalDiag(loc, np.diag(sig).tolist())
    x = [0.0, 0.0, 1.0]
    ent, lp = _run([mvn.entropy(), mvn.log_prob(
        fluid.layers.assign_value(x))])
    ref = multivariate_normal(mean=loc, cov=np.diag(np.square(sig)))
    assert abs(float(ent.reshape(-1)[0]) - ref.entropy()) < 1e-4
    assert abs(float(lp.reshape(-1)[0]) - ref.logpdf(x)) < 1e-4


def test_mvn_kl_matches_closed_form():
    loc1, sig1 = [0.0, 0.0], [1.0, 2.0]
    loc2, sig2 = [1.0, -1.0], [2.0, 1.0]
    a = MultivariateNormalDiag(loc1, np.diag(sig1).tolist())
    b = MultivariateNormalDiag(loc2, np.diag(sig2).tolist())
    (kl,) = _run([a.kl_divergence(b)])
    v1, v2 = np.square(sig1), np.square(sig2)
    diff = np.array(loc2) - np.array(loc1)
    ref = 0.5 * (np.sum(v1 / v2) + np.sum(diff ** 2 / v2) - 2
                 + np.sum(np.log(v2)) - np.sum(np.log(v1)))
    assert abs(float(kl.reshape(-1)[0]) - ref) < 1e-5
    # KL(p||p) == 0
    (kl0,) = _run([a.kl_divergence(
        MultivariateNormalDiag(loc1, np.diag(sig1).tolist()))])
    assert abs(float(kl0.reshape(-1)[0])) < 1e-6


def test_mvn_sample_moments():
    loc, sig = [1.0, -2.0], [0.5, 1.5]
    mvn = MultivariateNormalDiag(loc, np.diag(sig).tolist())
    (s,) = _run([mvn.sample([4096], seed=7)])
    assert s.shape == (4096, 2)
    np.testing.assert_allclose(s.mean(0), loc, atol=0.1)
    np.testing.assert_allclose(s.std(0), sig, atol=0.1)


def test_kl_pair_matrix_normal_vs_mvn():
    """kl_divergence is defined across the class pairs the reference
    supports (Normal-Normal, MVN-MVN); cross-class raises cleanly."""
    n1, n2 = Normal(0.0, 1.0), Normal(1.0, 2.0)
    (kl,) = _run([n1.kl_divergence(n2)])
    ref = np.log(2.0) + (1.0 + 1.0) / (2 * 4.0) - 0.5
    assert abs(float(kl.reshape(-1)[0]) - ref) < 1e-5
