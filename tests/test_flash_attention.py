"""Pallas flash-attention kernel: interpret-mode kernel vs jnp reference vs
composed dense ops, forward and backward.

The Mosaic interpreter runs the actual kernel logic on CPU (dropout>0
training is excluded there: the interpreter's prng_random_bits is a zero
stub — that leg runs on real TPU via the verify flow instead).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as fluid
from paddle_tpu import layers
from paddle_tpu.framework import unique_name
from paddle_tpu.kernels.flash_attention import _reference, fused_attention

B, H, S, D = 2, 3, 128, 16


@pytest.fixture(autouse=True)
def fresh_programs():
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 99
    scope = fluid.framework.scope.Scope()
    with fluid.program_guard(main, startup), fluid.scope_guard(scope), \
            unique_name.guard():
        yield main, startup, scope


def _qkv(dtype=np.float32, seed=0):
    rng = np.random.RandomState(seed)
    mk = lambda: rng.randn(B, H, S, D).astype(dtype) * 0.5  # noqa: E731
    return mk(), mk(), mk()


def _bias():
    # mask out the last quarter of keys for batch 1
    bias = np.zeros((B, S), np.float32)
    bias[1, 3 * S // 4:] = -1e4
    return bias


@pytest.mark.parametrize("causal", [False, True])
def test_kernel_matches_reference_forward(causal):
    q, k, v = _qkv()
    bias = _bias()
    out_k = fused_attention(
        jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), jnp.asarray(bias),
        causal=causal, interpret=True,
    )
    out_r = _reference(
        jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), jnp.asarray(bias),
        jax.random.key(0), scale=1.0 / np.sqrt(D), rate=0.0, is_test=True,
        upscale=False, causal=causal,
    )
    np.testing.assert_allclose(
        np.asarray(out_k), np.asarray(out_r), rtol=1e-5, atol=1e-5
    )


def test_kernel_infer_dropout_scaling():
    """is_test with downgrade_in_infer scales probs by (1-p) — fluid
    dropout_op.cc semantics."""
    q, k, v = _qkv()
    out_p = fused_attention(
        jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
        dropout_rate=0.25, is_test=True, interpret=True,
    )
    out_base = fused_attention(
        jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), interpret=True,
    )
    np.testing.assert_allclose(
        np.asarray(out_p), 0.75 * np.asarray(out_base), rtol=1e-5, atol=1e-6
    )


def test_kernel_backward_matches_reference_grads():
    q, k, v = _qkv()
    bias = _bias()

    def via_kernel(q_, k_, v_, b_):
        return jnp.sum(
            fused_attention(q_, k_, v_, b_, interpret=True)
            * jnp.cos(jnp.arange(D, dtype=jnp.float32))
        )

    def via_ref(q_, k_, v_, b_):
        return jnp.sum(
            _reference(
                q_, k_, v_, b_, jax.random.key(0),
                scale=1.0 / np.sqrt(D), rate=0.0, is_test=True,
                upscale=False, causal=False,
            )
            * jnp.cos(jnp.arange(D, dtype=jnp.float32))
        )

    args = tuple(jnp.asarray(a) for a in (q, k, v, bias))
    gk = jax.grad(via_kernel, argnums=(0, 1, 2, 3))(*args)
    gr = jax.grad(via_ref, argnums=(0, 1, 2, 3))(*args)
    for a, b, name in zip(gk, gr, "qkv b"):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-5,
            err_msg=f"grad mismatch for {name}",
        )


def _dense_attention_program(q, k, v, bias2d, dropout, is_test):
    scores = layers.matmul(q, k, transpose_y=True, alpha=1.0 / np.sqrt(D))
    scores = scores + layers.reshape(bias2d, [B, 1, 1, S])
    probs = layers.softmax(scores, axis=-1)
    probs = layers.dropout(probs, dropout_prob=dropout, is_test=is_test)
    return layers.matmul(probs, v)


def test_fused_op_matches_composed_ops_in_program():
    qn, kn, vn = _qkv()
    bias = _bias()
    q = fluid.data("q", [B, H, S, D])
    k = fluid.data("k", [B, H, S, D])
    v = fluid.data("v", [B, H, S, D])
    bi = fluid.data("bi", [B, S])
    fused = layers.fused_multihead_attention(
        q, k, v, key_bias=bi, scale=1.0 / np.sqrt(D), is_test=True
    )
    dense = _dense_attention_program(q, k, v, bi, 0.0, True)
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    f, d = exe.run(
        feed={"q": qn, "k": kn, "v": vn, "bi": bias},
        fetch_list=[fused, dense],
    )
    np.testing.assert_allclose(np.asarray(f), np.asarray(d), rtol=1e-5,
                               atol=1e-5)


def test_fused_op_trains_with_dropout():
    """Training-mode dropout through the op (CPU reference path): loss is
    finite, grads flow to q/k/v, and two steps draw different masks."""
    qn, kn, vn = _qkv()
    q = fluid.data("q", [B, H, S, D])
    q.stop_gradient = False
    k = fluid.data("k", [B, H, S, D])
    v = fluid.data("v", [B, H, S, D])
    out = layers.fused_multihead_attention(
        q, k, v, dropout_prob=0.3, is_test=False
    )
    loss = layers.reduce_mean(out)
    grads = fluid.framework.backward.gradients([loss], [q])
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    feed = {"q": qn, "k": kn, "v": vn}
    (l1, g1) = exe.run(feed=feed, fetch_list=[loss, grads[0]])
    (l2, _) = exe.run(feed=feed, fetch_list=[loss, grads[0]])
    assert np.isfinite(np.asarray(l1)).all()
    assert np.abs(np.asarray(g1)).sum() > 0
    # per-step RNG: same feed, different step -> different dropout mask
    assert not np.allclose(np.asarray(l1), np.asarray(l2))


def test_bert_fused_matches_dense_path():
    from paddle_tpu.models import BertConfig, bert_pretrain

    losses = {}
    for fused in (True, False):
        main, startup = fluid.Program(), fluid.Program()
        main.random_seed = startup.random_seed = 7
        scope = fluid.framework.scope.Scope()
        with fluid.program_guard(main, startup), \
                fluid.scope_guard(scope), unique_name.guard():
            cfg = BertConfig.tiny()
            cfg.use_fused_attention = fused
            cfg.attention_dropout = 0.0  # masks would differ across paths
            cfg.hidden_dropout = 0.0
            b, s = 2, 64
            ids = fluid.data("ids", [b, s], "int64")
            types = fluid.data("types", [b, s], "int64")
            mask = fluid.data("mask", [b, s], "float32")
            labels = fluid.data("labels", [b, s], "int64")
            loss = bert_pretrain(ids, types, mask, labels, cfg)
            fluid.optimizer.Adam(1e-3).minimize(loss)
            exe = fluid.Executor()
            exe.run(startup, scope=scope)
            rng = np.random.RandomState(3)
            feed = {
                "ids": rng.randint(0, cfg.vocab_size, (b, s)).astype("int64"),
                "types": rng.randint(0, 2, (b, s)).astype("int64"),
                "mask": np.ones((b, s), np.float32),
                "labels": rng.randint(0, cfg.vocab_size, (b, s)).astype(
                    "int64"
                ),
            }
            vals = []
            for _ in range(3):
                (lv,) = exe.run(
                    main, feed=feed, fetch_list=[loss], scope=scope
                )
                vals.append(float(np.asarray(lv).reshape(-1)[0]))
            losses[fused] = vals
    np.testing.assert_allclose(losses[True], losses[False], rtol=2e-4)


def _pack_qkv(q, k, v, h, d):
    """[B,H,S,D] x3 -> packed [B,S,3HD] (head-major within each section)."""
    def flat(t):
        return np.transpose(t, (0, 2, 1, 3)).reshape(B, S, h * d)
    return np.concatenate([flat(q), flat(k), flat(v)], axis=-1)


# packed kernel wants full 128-lane groups: H2*D2 == 128, H2 % (128//D2) == 0
H2, D2 = 8, 16


def _qkv_packed(seed=0):
    rng = np.random.RandomState(seed)
    mk = lambda: rng.randn(B, H2, S, D2).astype(np.float32) * 0.5  # noqa: E731
    return mk(), mk(), mk()


def test_packed_qkv_kernel_matches_reference():
    from paddle_tpu.kernels.flash_attention import fused_attention_qkv

    q, k, v = _qkv_packed()
    bias = _bias()
    qkv = _pack_qkv(q, k, v, H2, D2)
    out_k = fused_attention_qkv(
        jnp.asarray(qkv), H2, jnp.asarray(bias), interpret=True
    )
    ref4 = _reference(
        jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), jnp.asarray(bias),
        jax.random.key(0), scale=1.0 / np.sqrt(D2), rate=0.0, is_test=True,
        upscale=False, causal=False,
    )
    ref = np.transpose(np.asarray(ref4), (0, 2, 1, 3)).reshape(B, S, H2 * D2)
    np.testing.assert_allclose(np.asarray(out_k), ref, rtol=1e-5, atol=1e-5)


def test_packed_qkv_grads_match_reference():
    from paddle_tpu.kernels.flash_attention import (
        _reference_qkv,
        fused_attention_qkv,
    )

    q, k, v = _qkv_packed()
    bias = _bias()
    qkv = jnp.asarray(_pack_qkv(q, k, v, H2, D2))
    bj = jnp.asarray(bias)
    w = jnp.cos(jnp.arange(H2 * D2, dtype=jnp.float32))

    f_k = lambda a, b2: jnp.sum(  # noqa: E731
        fused_attention_qkv(a, H2, b2, interpret=True) * w
    )
    f_r = lambda a, b2: jnp.sum(  # noqa: E731
        _reference_qkv(
            a, b2, jax.random.key(0), H2, scale=1.0 / np.sqrt(D2), rate=0.0,
            is_test=True, upscale=False, causal=False,
        ) * w
    )
    gk = jax.grad(f_k, (0, 1))(qkv, bj)
    gr = jax.grad(f_r, (0, 1))(qkv, bj)
    for a, b2, name in zip(gk, gr, ("qkv", "bias")):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b2), rtol=2e-4, atol=2e-5,
            err_msg=f"grad mismatch for {name}",
        )


def test_bert_packed_fused_matches_dense():
    """BERT via fused_qkv_attention (CPU reference path) == dense path."""
    from paddle_tpu.models import BertConfig, bert_pretrain

    losses = {}
    for fused in (True, False):
        main, startup = fluid.Program(), fluid.Program()
        main.random_seed = startup.random_seed = 31
        scope = fluid.framework.scope.Scope()
        with fluid.program_guard(main, startup), \
                fluid.scope_guard(scope), unique_name.guard():
            cfg = BertConfig.tiny()
            cfg.use_fused_attention = fused
            cfg.attention_dropout = 0.0
            cfg.hidden_dropout = 0.0
            b, s = 2, 64
            ids = fluid.data("ids", [b, s], "int64")
            types = fluid.data("types", [b, s], "int64")
            mask = fluid.data("mask", [b, s], "float32")
            labels = fluid.data("labels", [b, s], "int64")
            loss = bert_pretrain(ids, types, mask, labels, cfg)
            fluid.optimizer.Adam(1e-3).minimize(loss)
            exe = fluid.Executor()
            exe.run(startup, scope=scope)
            rng = np.random.RandomState(13)
            feed = {
                "ids": rng.randint(0, cfg.vocab_size, (b, s)).astype("int64"),
                "types": rng.randint(0, 2, (b, s)).astype("int64"),
                "mask": np.ones((b, s), np.float32),
                "labels": rng.randint(0, cfg.vocab_size, (b, s)).astype(
                    "int64"
                ),
            }
            vals = []
            for _ in range(3):
                (lv,) = exe.run(
                    main, feed=feed, fetch_list=[loss], scope=scope
                )
                vals.append(float(np.asarray(lv).reshape(-1)[0]))
            losses[fused] = vals
    np.testing.assert_allclose(losses[True], losses[False], rtol=2e-4)
