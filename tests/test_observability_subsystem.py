"""Observability subsystem: histogram/timer primitives, span -> Chrome-trace
round-trip, exporters, the PADDLE_TPU_MONITOR=0 kill-switch, and the
instrumented executor / dataloader / collective hot paths.

Reference role: platform/monitor.h StatRegistry + tools/timeline.py, grown
into the histogram/span/export layer (ISSUE 1)."""

import json
import os
import subprocess
import sys
import threading

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import layers, observability as obs
from paddle_tpu.framework import unique_name

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def fresh_metrics():
    obs.reset()
    obs.set_enabled(True)
    yield
    obs.reset()
    obs.set_enabled(None)  # back to the environment's setting


@pytest.fixture
def fresh_programs():
    main, startup = fluid.Program(), fluid.Program()
    scope = fluid.framework.scope.Scope()
    with fluid.program_guard(main, startup), fluid.scope_guard(scope), \
            unique_name.guard():
        yield main, startup, scope


# -- primitives --------------------------------------------------------------


def test_histogram_bucket_edges():
    """Edges are inclusive (value <= le) and snapshot buckets cumulative."""
    for v in (0.5, 1.0, 1.5, 4.0, 9.0):
        obs.observe("h.edges", v, buckets=(1.0, 2.0, 4.0))
    h = obs.snapshot()["histograms"]["h.edges"]
    assert h["count"] == 5
    assert h["sum"] == pytest.approx(16.0)
    assert h["min"] == 0.5 and h["max"] == 9.0
    assert h["buckets"] == [[1.0, 2], [2.0, 3], [4.0, 4], ["+Inf", 5]]


def test_timed_context_and_decorator():
    with obs.timed("t.ctx"):
        pass

    @obs.timed("t.fn")
    def f(a, b):
        return a + b

    assert f(2, 3) == 5
    assert f(4, 5) == 9
    hists = obs.snapshot()["histograms"]
    assert hists["t.ctx"]["count"] == 1
    assert hists["t.fn"]["count"] == 2
    assert hists["t.fn"]["sum"] >= 0.0


def test_timed_records_on_exception():
    with pytest.raises(ValueError):
        with obs.timed("t.err"):
            raise ValueError("boom")
    assert obs.snapshot()["histograms"]["t.err"]["count"] == 1


def test_monitor_facade_back_compat():
    from paddle_tpu import monitor

    monitor.add("compat.counter", 2)
    monitor.add("compat.counter")
    monitor.set_float("compat.gauge", 1.5)
    assert monitor.get_int_stats()["compat.counter"] == 3
    assert monitor.get_float_stats()["compat.gauge"] == 1.5
    monitor.reset()
    assert monitor.get_int_stats() == {}


def test_thread_safety_concurrent_add_observe_snapshot():
    """Exact totals under 8 writer threads racing snapshot readers."""
    n_threads, n_iter = 8, 500
    stop = threading.Event()

    def writer():
        for _ in range(n_iter):
            obs.add("ts.counter")
            obs.observe("ts.hist", 1.0, buckets=(0.5, 2.0))

    def reader():
        while not stop.is_set():
            obs.snapshot()

    threads = [threading.Thread(target=writer) for _ in range(n_threads)]
    r = threading.Thread(target=reader)
    r.start()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    stop.set()
    r.join()
    snap = obs.snapshot()
    assert snap["counters"]["ts.counter"] == n_threads * n_iter
    h = snap["histograms"]["ts.hist"]
    assert h["count"] == n_threads * n_iter
    assert h["buckets"][-1][1] == h["count"]


def test_thread_safety_concurrent_reset_does_not_corrupt():
    """add/reset races must never raise or leave negative/garbage state."""
    def writer():
        for _ in range(300):
            obs.add("tr.counter")
            obs.observe("tr.hist", 0.1)

    def resetter():
        for _ in range(50):
            obs.reset()

    threads = [threading.Thread(target=writer) for _ in range(4)]
    threads.append(threading.Thread(target=resetter))
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    snap = obs.snapshot()
    assert 0 <= snap["counters"].get("tr.counter", 0) <= 1200
    h = snap["histograms"].get("tr.hist")
    if h is not None:
        assert h["buckets"][-1][1] == h["count"]


# -- spans -------------------------------------------------------------------


def test_span_chrome_trace_round_trip():
    with obs.span("outer", step=1):
        with obs.span("inner"):
            pass
    data = json.loads(obs.chrome_trace())
    events = data["traceEvents"]
    metas = [e for e in events if e["ph"] == "M"]
    regions = [e for e in events if e["ph"] == "X"]
    assert metas and regions
    names = {e["name"] for e in regions}
    assert {"outer", "inner"} <= names
    outer = next(e for e in regions if e["name"] == "outer")
    inner = next(e for e in regions if e["name"] == "inner")
    assert outer["args"] == {"step": 1}
    assert outer["dur"] >= inner["dur"]
    assert {"ts", "dur", "pid", "tid", "cat"} <= set(outer)


def test_span_decorator_and_ring_buffer_bound():
    @obs.span("decorated")
    def f():
        return 7

    assert f() == 7
    assert any(s["name"] == "decorated" for s in obs.get_spans())
    from paddle_tpu.observability import spans as spans_mod

    assert spans_mod._spans.maxlen is not None  # bounded ring, never grows


def test_save_chrome_trace(tmp_path):
    with obs.span("persisted"):
        pass
    path = obs.save_chrome_trace(str(tmp_path / "trace.json"))
    data = json.loads(open(path).read())
    assert any(e["name"] == "persisted" for e in data["traceEvents"])


# -- exporters ---------------------------------------------------------------


def test_prometheus_text_exposition():
    obs.add("prom.counter", 3)
    obs.set_gauge("prom.gauge", 2.5)
    obs.observe("prom.lat", 0.3, buckets=(0.25, 1.0))
    text = obs.prometheus_text()
    assert "# TYPE prom_counter counter" in text
    assert "prom_counter 3" in text
    assert "# TYPE prom_gauge gauge" in text
    assert 'prom_lat_bucket{le="1.0"} 1' in text
    assert 'prom_lat_bucket{le="+Inf"} 1' in text
    assert "prom_lat_count 1" in text


def test_dump_and_stats_report_cli(tmp_path):
    obs.add("cli.counter")
    obs.observe("cli.hist", 0.5)
    path = obs.dump(str(tmp_path / "snap.json"))
    snap = json.loads(open(path).read())
    assert snap["counters"]["cli.counter"] == 1
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "stats_report.py"),
         path, "--require", "cli."],
        capture_output=True, text=True,
    )
    assert r.returncode == 0, r.stderr
    assert "cli.counter" in r.stdout
    r2 = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "stats_report.py"),
         path, "--require", "absent."],
        capture_output=True, text=True,
    )
    assert r2.returncode == 2


# -- kill-switch -------------------------------------------------------------


def test_kill_switch_in_process():
    obs.set_enabled(False)
    obs.add("dead.counter")
    obs.set_gauge("dead.gauge", 1.0)
    obs.observe("dead.hist", 1.0)
    with obs.timed("dead.timer"):
        pass
    with obs.span("dead.span"):
        pass
    snap = obs.snapshot()
    assert snap["counters"] == {}
    assert snap["gauges"] == {}
    assert snap["histograms"] == {}
    assert snap["span_count"] == 0


@pytest.mark.slow
def test_kill_switch_env_subprocess():
    """PADDLE_TPU_MONITOR=0 at process start: every hook is a no-op even
    across an instrumented executor run."""
    script = (
        "import numpy as np\n"
        "import paddle_tpu as fluid\n"
        "from paddle_tpu import layers, observability as obs\n"
        "main, startup = fluid.Program(), fluid.Program()\n"
        "with fluid.program_guard(main, startup):\n"
        "    x = fluid.data('x', [2, 2])\n"
        "    y = layers.scale(x, scale=2.0)\n"
        "exe = fluid.Executor()\n"
        "exe.run(startup)\n"
        "exe.run(main, feed={'x': np.zeros((2, 2), 'float32')},"
        " fetch_list=[y])\n"
        "import json; print(json.dumps(obs.snapshot()))\n"
    )
    env = dict(os.environ, PADDLE_TPU_MONITOR="0", JAX_PLATFORMS="cpu")
    r = subprocess.run(
        [sys.executable, "-c", script], capture_output=True, text=True,
        env=env, cwd=REPO,
    )
    assert r.returncode == 0, r.stderr
    snap = json.loads(r.stdout.strip().splitlines()[-1])
    assert snap["counters"] == {} and snap["histograms"] == {}


# -- instrumented hot paths --------------------------------------------------


def test_executor_step_and_cache_metrics(fresh_programs):
    main, startup, scope = fresh_programs
    x = fluid.data("x", [2, 2])
    y = layers.scale(x, scale=3.0)
    exe = fluid.Executor()
    exe.run(startup, scope=scope)
    for _ in range(3):
        exe.run(main, feed={"x": np.zeros((2, 2), "float32")},
                fetch_list=[y], scope=scope)
    snap = obs.snapshot()
    c = snap["counters"]
    assert c["executor.run_steps"] == 4
    assert c["executor.compile_count"] == 2  # startup + one main step
    assert c["executor.cache_misses"] == 2
    assert c["executor.cache_hits"] == 2  # steps 2 and 3
    assert snap["histograms"]["executor.step_latency"]["count"] == 4
    assert snap["histograms"]["executor.compile_time"]["count"] == 2
    # hit rate derivable from ONE snapshot (ISSUE satellite)
    assert c["executor.cache_hits"] + c["executor.cache_misses"] \
        == c["executor.run_steps"]
    # step spans landed in the ring buffer
    names = [s["name"] for s in obs.get_spans()]
    assert names.count("executor.step") == 4
    assert names.count("executor.compile") == 2


def test_executor_cache_eviction_counter(fresh_programs):
    main, startup, scope = fresh_programs
    x = fluid.data("x", [2, 2])
    y = layers.scale(x, scale=2.0)
    exe = fluid.Executor()
    exe.CACHE_CAPACITY = 1
    exe.run(startup, scope=scope)
    exe.run(main, feed={"x": np.zeros((2, 2), "float32")},
            fetch_list=[y], scope=scope)
    # startup's executable was evicted to make room for the main step
    assert obs.snapshot()["counters"]["executor.cache_evictions"] >= 1


def test_dataloader_metrics():
    from paddle_tpu.dataloader import Dataset

    class _Sq(Dataset):
        def __getitem__(self, i):
            return np.asarray([i], dtype=np.float32)

        def __len__(self):
            return 12

    n = sum(1 for _ in fluid.DataLoader(
        _Sq(), batch_size=3, use_buffer_reader=False))
    assert n == 4
    snap = obs.snapshot()
    assert snap["counters"]["dataloader.batches"] == 4
    assert snap["histograms"]["dataloader.batch_wait"]["count"] == 4

    obs.reset()
    n = sum(1 for _ in fluid.DataLoader(
        _Sq(), batch_size=3, num_workers=2, use_buffer_reader=False))
    assert n == 4
    snap = obs.snapshot()
    assert snap["counters"]["dataloader.batches"] == 4
    assert snap["histograms"]["dataloader.batch_wait"]["count"] == 4
    assert "dataloader.queue_depth" in snap["gauges"]


def test_collective_counters_on_mesh(fresh_programs):
    from paddle_tpu.parallel import make_mesh, shard_program

    main, startup, scope = fresh_programs
    fluid.data("x", [8, 4], "float32")
    blk = main.global_block
    blk.create_var(name="out", shape=(8, 4), dtype="float32")
    blk.append_op(
        "c_allreduce_sum",
        inputs={"X": ["x"]},
        outputs={"Out": ["out"]},
        attrs={"axis_name": "dp"},
    )
    mesh = make_mesh({"dp": 8})
    shard_program(main, mesh, {"x": ("dp",), "out": ("dp",)})
    exe = fluid.Executor()
    data = np.arange(32, dtype="float32").reshape(8, 4)
    exe.run(main, feed={"x": data}, fetch_list=["out"], scope=scope)
    c = obs.snapshot()["counters"]
    assert c["collective.c_allreduce_sum"] >= 1
    # per-shard payload: [1, 4] float32 = 16 bytes per traced emission
    assert c["collective.c_allreduce_sum.bytes"] >= 16
    assert c["collective.shard_map_dispatches"] >= 1
    assert obs.snapshot()["gauges"]["collective.mesh_devices"] == 8


def test_one_step_train_snapshot_end_to_end(fresh_programs, tmp_path):
    """Acceptance: one fleet training step + a dataloader pull, then
    dump() -> snapshot holds an executor.* histogram, a dataloader.*
    metric, and a collective.* counter."""
    from paddle_tpu.dataloader import Dataset
    from paddle_tpu.fleet.collective import DistributedStrategy, fleet

    main, startup, scope = fresh_programs
    x = fluid.data("x", [8, 4])
    y = layers.fc(x, 1)
    loss = layers.reduce_mean(y)
    fleet.init()
    strategy = DistributedStrategy()
    strategy.mesh_axes = {"dp": 8}
    opt = fleet.distributed_optimizer(fluid.optimizer.SGD(0.1), strategy)
    opt.minimize(loss)
    exe = fluid.Executor()
    exe.run(startup, scope=scope)

    class _Ds(Dataset):
        def __getitem__(self, i):
            return np.ones((4,), dtype=np.float32)

        def __len__(self):
            return 8

    for batch in fluid.DataLoader(_Ds(), batch_size=8,
                                  use_buffer_reader=False):
        exe.run(main, feed={"x": np.stack(batch)}, fetch_list=[loss],
                scope=scope)
    snap = json.loads(open(obs.dump(str(tmp_path / "snap.json"))).read())
    assert any(k.startswith("executor.") for k in snap["histograms"])
    assert any(k.startswith("dataloader.") for k in snap["counters"])
    assert any(k.startswith("collective.") for k in snap["counters"])
    assert snap["counters"]["collective.grad_allreduce_tensors"] >= 1
    assert snap["gauges"]["collective.dp_degree"] == 8


# -- profiler satellites -----------------------------------------------------


def test_profiler_op_kind_digits_and_ids():
    from paddle_tpu.profiler import _op_kind

    assert _op_kind("fusion.2") == "fusion"
    assert _op_kind("all-reduce.1") == "all-reduce"
    assert _op_kind("%convolution.37") == "convolution"
    # names starting with a digit must not fall into 24-char truncation
    assert _op_kind("2d_transpose.4") == "2d_transpose"
    assert _op_kind("log1p.3") == "log1p"


def test_stop_profiler_resets_active_dir_on_error(monkeypatch):
    import jax

    import paddle_tpu.profiler as prof

    monkeypatch.setattr(prof, "_active_dir", "/tmp/phantom_prof")

    def boom():
        raise RuntimeError("runtime stop failure")

    monkeypatch.setattr(jax.profiler, "stop_trace", boom)
    with pytest.raises(RuntimeError, match="runtime stop failure"):
        prof.stop_profiler()
    assert prof._active_dir is None  # no phantom active session left behind
