"""YOLOv3: the yolov3_loss op against an independent numpy port of the
reference semantics (detection/yolov3_loss_op.h), and the full model
(darknet53 + FPN heads) training and decoding end to end."""

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import layers
from paddle_tpu.framework import unique_name
from paddle_tpu.models import YoloConfig, yolov3_infer, yolov3_train


@pytest.fixture(autouse=True)
def fresh_programs():
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 17
    scope = fluid.framework.scope.Scope()
    with fluid.program_guard(main, startup), fluid.scope_guard(scope), \
            unique_name.guard():
        yield main, startup, scope


def _sce(x, z):
    return max(x, 0.0) - x * z + np.log1p(np.exp(-abs(x)))


def _np_yolov3_loss(x, gt_box, gt_label, anchors, anchor_mask, class_num,
                    ignore_thresh, downsample, use_label_smooth=True):
    """Literal numpy port of the reference loops (yolov3_loss_op.h:256+),
    gt_score == 1."""
    N, _, H, W = x.shape
    M, A = len(anchor_mask), len(anchors) // 2
    B = gt_box.shape[1]
    input_size = downsample * H
    xr = x.reshape(N, M, 5 + class_num, H, W).astype(np.float64)
    sig = lambda v: 1.0 / (1.0 + np.exp(-v))  # noqa: E731
    if use_label_smooth:
        sm = min(1.0 / class_num, 1.0 / 40)
        pos_l, neg_l = 1.0 - sm, sm
    else:
        pos_l, neg_l = 1.0, 0.0

    def iou(b1, b2):
        ow = min(b1[0] + b1[2] / 2, b2[0] + b2[2] / 2) - max(
            b1[0] - b1[2] / 2, b2[0] - b2[2] / 2)
        oh = min(b1[1] + b1[3] / 2, b2[1] + b2[3] / 2) - max(
            b1[1] - b1[3] / 2, b2[1] - b2[3] / 2)
        inter = 0.0 if ow < 0 or oh < 0 else ow * oh
        return inter / (b1[2] * b1[3] + b2[2] * b2[3] - inter)

    loss = np.zeros(N)
    obj = np.zeros((N, M, H, W))
    for n in range(N):
        valid = [gt_box[n, t, 2] > 1e-6 and gt_box[n, t, 3] > 1e-6
                 for t in range(B)]
        for j in range(M):
            for k in range(H):
                for l in range(W):  # noqa: E741
                    pred = (
                        (l + sig(xr[n, j, 0, k, l])) / H,
                        (k + sig(xr[n, j, 1, k, l])) / H,
                        np.exp(xr[n, j, 2, k, l])
                        * anchors[2 * anchor_mask[j]] / input_size,
                        np.exp(xr[n, j, 3, k, l])
                        * anchors[2 * anchor_mask[j] + 1] / input_size,
                    )
                    best = 0.0
                    for t in range(B):
                        if valid[t]:
                            best = max(best, iou(pred, gt_box[n, t]))
                    if best > ignore_thresh:
                        obj[n, j, k, l] = -1
        for t in range(B):
            if not valid[t]:
                continue
            gx, gy, gw, gh = gt_box[n, t]
            gi, gj = int(gx * W), int(gy * H)
            best_iou, best_n = 0.0, 0
            for a in range(A):
                cand = (0, 0, anchors[2 * a] / input_size,
                        anchors[2 * a + 1] / input_size)
                v = iou(cand, (0, 0, gw, gh))
                if v > best_iou:
                    best_iou, best_n = v, a
            if best_n not in anchor_mask:
                continue
            m = anchor_mask.index(best_n)
            tx, ty = gx * H - gi, gy * H - gj
            tw = np.log(gw * input_size / anchors[2 * best_n])
            th = np.log(gh * input_size / anchors[2 * best_n + 1])
            sc = 2.0 - gw * gh
            loss[n] += _sce(xr[n, m, 0, gj, gi], tx) * sc
            loss[n] += _sce(xr[n, m, 1, gj, gi], ty) * sc
            loss[n] += abs(xr[n, m, 2, gj, gi] - tw) * sc
            loss[n] += abs(xr[n, m, 3, gj, gi] - th) * sc
            obj[n, m, gj, gi] = 1.0
            for c in range(class_num):
                lab = pos_l if c == gt_label[n, t] else neg_l
                loss[n] += _sce(xr[n, m, 5 + c, gj, gi], lab)
        for j in range(M):
            for k in range(H):
                for l in range(W):  # noqa: E741
                    o = obj[n, j, k, l]
                    if o > 1e-5:
                        loss[n] += _sce(xr[n, j, 4, k, l], 1.0) * o
                    elif o > -0.5:
                        loss[n] += _sce(xr[n, j, 4, k, l], 0.0)
    return loss, obj


def test_yolov3_loss_matches_reference_port():
    rng = np.random.RandomState(0)
    N, H, W, C, B = 2, 8, 8, 4, 5
    anchors = [10, 14, 23, 27, 37, 58, 81, 82]
    anchor_mask = [1, 2]
    M = len(anchor_mask)
    x = rng.randn(N, M * (5 + C), H, W).astype("float32") * 0.5
    gt = rng.uniform(0.1, 0.9, (N, B, 4)).astype("float32")
    gt[:, :, 2:] = rng.uniform(0.05, 0.5, (N, B, 2))
    gt[0, 3:, 2:] = 0.0  # invalid boxes
    labels = rng.randint(0, C, (N, B)).astype("int64")

    ref_loss, ref_obj = _np_yolov3_loss(
        x, gt, labels, anchors, anchor_mask, C, 0.5, 16
    )

    xv = fluid.data("x", [N, M * (5 + C), H, W])
    gv = fluid.data("gt", [N, B, 4])
    lv = fluid.data("lab", [N, B], "int64")
    loss = layers.yolov3_loss(
        xv, gv, lv, anchors=anchors, anchor_mask=anchor_mask, class_num=C,
        ignore_thresh=0.5, downsample_ratio=16,
    )
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    (got,) = exe.run(
        feed={"x": x, "gt": gt, "lab": labels}, fetch_list=[loss]
    )
    np.testing.assert_allclose(np.asarray(got), ref_loss, rtol=2e-5,
                               atol=2e-4)


@pytest.mark.slow  # ~55s on the CI CPU (tier-1 runtime brushes its 870s
# budget); the loss-port oracle + infer decode tests keep tier-1 coverage,
# and ci.sh's unfiltered pytest still runs this end-to-end convergence
def test_yolov3_trains_on_toy_boxes():
    cfg = YoloConfig.tiny(class_num=3)
    N, S, B = 2, 64, 4
    img = fluid.data("img", [N, 3, S, S])
    gt = fluid.data("gt", [N, B, 4])
    lab = fluid.data("lab", [N, B], "int64")
    loss = yolov3_train(img, gt, lab, cfg)
    fluid.optimizer.Adam(1e-3).minimize(loss)
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    rng = np.random.RandomState(1)
    feed = {
        "img": rng.randn(N, 3, S, S).astype("float32"),
        "gt": np.tile(
            np.array([[0.5, 0.5, 0.3, 0.4]], np.float32), (N, B, 1)
        ),
        "lab": np.ones((N, B), np.int64),
    }
    losses = []
    for _ in range(12):
        (v,) = exe.run(feed=feed, fetch_list=[loss])
        losses.append(float(np.asarray(v).reshape(-1)[0]))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0] * 0.9, (losses[0], losses[-1])


@pytest.mark.slow  # ~24s on the CI CPU; ci.sh's unfiltered pytest runs it
def test_yolov3_infer_decodes_boxes():
    cfg = YoloConfig.tiny(class_num=3)
    N, S = 1, 64
    img = fluid.data("img", [N, 3, S, S])
    size = fluid.data("size", [N, 2], "int32")
    out, num = yolov3_infer(img, size, cfg, keep_top_k=20)
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    rng = np.random.RandomState(2)
    o, n = exe.run(
        feed={
            "img": rng.randn(N, 3, S, S).astype("float32"),
            "size": np.array([[S, S]], np.int32),
        },
        fetch_list=[out, num],
    )
    o = np.asarray(o)
    assert o.shape == (N, 20, 6)
    assert int(np.asarray(n)[0]) >= 0
