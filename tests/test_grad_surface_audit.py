"""The gradient-audit invariant (VERDICT r4 next #5): every registered
emitter must be numerically swept, flagged non-differentiable, covered by
a named dedicated test, or exempt with a recorded reason — and the
curated lists may not go stale. Mirrors the reference's check_grad
whitelist discipline (op_test.py:170, white_list/op_accuracy_white_list.py)."""

import os
import sys

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "tools"))


def test_every_emitter_is_accounted_for():
    import check_grad_surface as cgs

    buckets, problems = cgs.classify()
    assert not problems, problems
    total = sum(len(v) for v in buckets.values())
    # the sweep should carry the bulk of the surface; guard against the
    # sweep silently shrinking (cases deleted without reclassification)
    assert len(buckets["swept"]) >= 190, len(buckets["swept"])
    assert total >= 390, total
