"""Sequence ops (padded+lengths), slim QAT, dygraph_to_static, timeline.

Reference suites: test_sequence_pool.py / test_sequence_softmax_op.py /
test_sequence_reverse.py (LoD-based — here padded+mask semantics are
checked against per-row numpy loops), slim quantization tests,
test_dygraph_to_static basics, timeline tool test.
"""

import json

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import layers
from paddle_tpu.framework import unique_name


@pytest.fixture(autouse=True)
def fresh_programs():
    main, startup = fluid.Program(), fluid.Program()
    scope = fluid.framework.scope.Scope()
    with fluid.program_guard(main, startup), fluid.scope_guard(scope), \
            unique_name.guard():
        yield main, startup, scope


def _run(fetch, feed):
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    return [np.asarray(v) for v in exe.run(feed=feed, fetch_list=fetch)]


def test_sequence_ops_match_numpy():
    B, T, D = 3, 5, 2
    rng = np.random.RandomState(0)
    xv = rng.randn(B, T, D).astype(np.float32)
    lens = np.asarray([5, 3, 1], np.int64)

    x = fluid.data("x", [B, T, D])
    L = fluid.data("lens", [B], "int64")
    fetches = [
        layers.sequence_pool(x, "sum", L),
        layers.sequence_pool(x, "average", L),
        layers.sequence_pool(x, "max", L),
        layers.sequence_last_step(x, L),
        layers.sequence_first_step(x),
        layers.sequence_reverse(x, L),
        layers.sequence_mask(L, T),
    ]
    outs = _run(fetches, {"x": xv, "lens": lens})

    want_sum = np.stack([xv[b, :lens[b]].sum(0) for b in range(B)])
    want_avg = np.stack([xv[b, :lens[b]].mean(0) for b in range(B)])
    want_max = np.stack([xv[b, :lens[b]].max(0) for b in range(B)])
    want_last = np.stack([xv[b, lens[b] - 1] for b in range(B)])
    want_rev = xv.copy()
    for b in range(B):
        want_rev[b, :lens[b]] = xv[b, :lens[b]][::-1]
    np.testing.assert_allclose(outs[0], want_sum, rtol=1e-5)
    np.testing.assert_allclose(outs[1], want_avg, rtol=1e-5)
    np.testing.assert_allclose(outs[2], want_max, rtol=1e-5)
    np.testing.assert_allclose(outs[3], want_last, rtol=1e-5)
    np.testing.assert_allclose(outs[4], xv[:, 0], rtol=1e-5)
    np.testing.assert_allclose(outs[5], want_rev, rtol=1e-5)
    want_mask = (np.arange(T)[None, :] < lens[:, None]).astype(np.float32)
    np.testing.assert_allclose(outs[6], want_mask)


def test_sequence_softmax_masks_padding():
    B, T = 2, 4
    x = fluid.data("x", [B, T])
    L = fluid.data("lens", [B], "int64")
    sm = layers.sequence_softmax(x, L)
    xv = np.zeros((B, T), np.float32)
    (out,) = _run([sm], {"x": xv, "lens": np.asarray([2, 4], np.int64)})
    np.testing.assert_allclose(out[0, :2], [0.5, 0.5], rtol=1e-5)
    np.testing.assert_allclose(out[0, 2:], 0.0, atol=1e-6)
    np.testing.assert_allclose(out[1], 0.25, rtol=1e-5)


# -- slim QAT ---------------------------------------------------------------


def test_qat_inserts_fake_quant_and_trains():
    from paddle_tpu.contrib.slim.quantization import quant_aware

    x = fluid.data("x", [16, 8])
    y = fluid.data("y", [16, 1])
    h = layers.fc(x, 16, act="relu")
    pred = layers.fc(h, 1)
    loss = layers.mean(layers.square_error_cost(pred, y))
    main = fluid.default_main_program()
    n_ops_before = len(main.global_block.ops)
    quant_aware(main)
    q_ops = [
        op.type for op in main.global_block.ops if "fake" in op.type
    ]
    assert len(q_ops) >= 4  # 2 matmuls x (input + weight)
    assert any("channel_wise" in t for t in q_ops)  # weights channel-wise
    fluid.optimizer.Adam(0.01).minimize(loss)
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    rng = np.random.RandomState(0)
    feed = {"x": rng.randn(16, 8).astype(np.float32)}
    feed["y"] = (feed["x"] @ rng.randn(8, 1)).astype(np.float32)
    losses = [
        float(np.asarray(exe.run(feed=feed, fetch_list=[loss])[0])
              .reshape(-1)[0])
        for _ in range(60)
    ]
    assert losses[-1] < losses[0] * 0.3  # straight-through grads train


def test_fake_quant_levels():
    """Quantized values land on the int8 grid of the abs-max scale."""
    x = fluid.data("x", [1, 6])
    blk = fluid.default_main_program().global_block
    q = blk.create_var(name="q", shape=[1, 6], dtype="float32")
    s = blk.create_var(name="s", shape=[1], dtype="float32")
    blk.append_op(
        "fake_quantize_dequantize_abs_max",
        {"X": ["x"]}, {"Out": ["q"], "OutScale": ["s"]}, {"bit_length": 8},
    )
    xv = np.asarray([[1.0, -0.5, 0.25, 0.1, -1.0, 0.77]], np.float32)
    qv, sv = _run(["q", "s"], {"x": xv})
    scale = float(sv[0])
    levels = np.round(xv / scale * 127)
    np.testing.assert_allclose(qv, levels * scale / 127, rtol=1e-5)


def test_post_training_quantization_scales():
    from paddle_tpu.contrib.slim.quantization import PostTrainingQuantization

    x = fluid.data("x", [4, 3])
    h = layers.scale(x, scale=2.0)
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    ptq = PostTrainingQuantization(
        exe, fluid.default_main_program(), ["x"], [h]
    )
    feeds = [
        {"x": np.full((4, 3), v, np.float32)} for v in (0.5, -3.0, 1.0)
    ]
    scales = ptq.quantize(feeds, [h.name])
    assert scales[h.name] == pytest.approx(6.0)


def test_ptq_algo_family_semantics():
    """r5 (VERDICT #7): KL picks a clip point far below abs-max when the
    distribution has a few huge outliers; hist takes the requested
    percentile; avg means the per-batch maxima; min_max records both ends."""
    from paddle_tpu.contrib.slim.quantization import PostTrainingQuantization

    x = fluid.data("x", [1000])
    h = layers.scale(x, scale=1.0)
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    rng = np.random.RandomState(0)
    body = rng.uniform(-1.0, 1.0, 1000).astype(np.float32)
    body[:3] = [100.0, -80.0, 90.0]  # outliers
    feeds = [{"x": body}, {"x": (body * 0.5).astype(np.float32)}]

    def ptq(algo, **kw):
        p = PostTrainingQuantization(
            exe, fluid.default_main_program(), ["x"], [h], algo=algo, **kw
        )
        return p.quantize(feeds, [h.name])[h.name]

    assert ptq("abs_max") == pytest.approx(100.0)
    assert ptq("avg") == pytest.approx((100.0 + 50.0) / 2)
    lo, hi = ptq("min_max")
    assert lo == pytest.approx(-80.0) and hi == pytest.approx(100.0)
    # 99th percentile of the pooled |x| sits inside the uniform body
    assert 0.5 < ptq("hist", hist_percent=0.99) < 2.0
    # KL clips below abs-max but only within the reference's search band
    # (candidate clip points span the top 30% of histogram bins, so the
    # reachable floor is 0.7*max — post_training_quantization.py:560)
    kl = ptq("KL")
    assert 69.0 < kl < 100.0, kl


def test_ptq_apply_quantizes_inference_program():
    """r5: the calibrate -> apply flow (reference save_quantized_model):
    fixed-scale quant-dequant ops bake into the inference program; the
    quantized program tracks the float one within int8 error and is NOT
    bit-identical (quantization really happened)."""
    from paddle_tpu.contrib.slim.quantization import (
        PostTrainingQuantization,
    )

    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 11
    with fluid.program_guard(main, startup):
        x = fluid.data("x", [4, 6])
        h = layers.fc(x, size=8, act="relu")
        out = layers.fc(h, size=3)
    scope = fluid.framework.scope.Scope()
    exe = fluid.Executor()
    exe.run(startup, scope=scope)
    rng = np.random.RandomState(2)
    feeds = [{"x": rng.randn(4, 6).astype(np.float32)} for _ in range(4)]

    ptq = PostTrainingQuantization(exe, main, ["x"], [out], scope=scope)
    # calibrate the activation inputs of the two fc (mul) ops: x and h
    scales = ptq.quantize(iter(feeds), [x.name, h.name])
    (ref,) = exe.run(main, feed=feeds[0], fetch_list=[out], scope=scope)

    n = ptq.apply(main, scales)
    assert n >= 3  # 2 activations + >=1 weight
    qops = [o.type for o in main.global_block.ops]
    assert "fake_quantize_dequantize_moving_average_abs_max" in qops
    assert "fake_channel_wise_quantize_dequantize_abs_max" in qops
    (got,) = exe.run(main, feed=feeds[0], fetch_list=[out], scope=scope)
    assert not np.array_equal(got, ref), "quantization was a no-op"
    np.testing.assert_allclose(got, ref, rtol=0.1, atol=0.1)


def test_out_scale_for_training_pass():
    """r5 (VERDICT #7): observers record output ranges DURING training
    (reference OutScaleForTrainingPass); scales() returns the moving
    average of per-step abs-max for every observed float output."""
    from paddle_tpu.contrib.slim.quantization import OutScaleForTrainingPass

    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 5
    with fluid.program_guard(main, startup):
        x = fluid.data("x", [4, 3])
        y = fluid.data("y", [4, 1])
        h = layers.fc(x, size=8, act="relu")
        pred = layers.fc(h, size=1)
        loss = layers.reduce_mean(layers.square(pred - y))
        passo = OutScaleForTrainingPass(moving_rate=0.9)
        n = passo.apply(main, startup)
        assert n >= 2  # at least the two fc (mul) outputs + relu
        fluid.optimizer.SGD(0.1).minimize(loss, startup)
    scope = fluid.framework.scope.Scope()
    exe = fluid.Executor()
    exe.run(startup, scope=scope)
    rng = np.random.RandomState(1)
    xv = rng.randn(4, 3).astype(np.float32)
    yv = rng.randn(4, 1).astype(np.float32)
    for _ in range(5):
        exe.run(main, feed={"x": xv, "y": yv}, fetch_list=[loss],
                scope=scope)
    scales = passo.scales(main, scope)
    assert len(scales) == n
    relu_scales = [v for k, v in scales.items()]
    assert all(np.isfinite(v) and v >= 0.0 for v in relu_scales)
    assert any(v > 0.0 for v in relu_scales)
    # the observer is a passthrough: training still converges with it
    lvals = [float(np.asarray(exe.run(main, feed={"x": xv, "y": yv},
                                      fetch_list=[loss], scope=scope)[0]
                              ).reshape(-1)[0]) for _ in range(30)]
    assert lvals[-1] < lvals[0]


# -- dygraph_to_static ------------------------------------------------------


def test_declarative_caches_and_matches_eager():
    dg = fluid.dygraph
    calls = {"n": 0}

    @dg.declarative
    def f(a, b):
        calls["n"] += 1
        return layers.reduce_sum(layers.elementwise_mul(a, b))

    with dg.guard():
        a = dg.to_variable(np.ones((2, 3), np.float32) * 2)
        b = dg.to_variable(np.ones((2, 3), np.float32) * 3)
        r1 = f(a, b)
        r2 = f(a, b)  # cached: python body must not re-run
        assert float(np.asarray(r1.value)) == 36.0
        assert float(np.asarray(r2.value)) == 36.0
    assert calls["n"] == 1
    # static mode: plain layer-building call
    x = fluid.data("x", [2, 2])
    out = f(x, x)
    assert hasattr(out, "name")  # a graph Variable, not a VarBase


def test_declarative_rejects_python_branch_on_tensor():
    dg = fluid.dygraph

    @dg.declarative
    def g(a):
        if float(np.asarray(a.value).sum()) > 0:  # concretizes a tracer
            return a
        return a

    with dg.guard():
        a = dg.to_variable(np.ones((2,), np.float32))
        with pytest.raises(RuntimeError, match="layers.cond"):
            g(a)


# -- timeline ---------------------------------------------------------------


def test_timeline_chrome_trace(tmp_path):
    import paddle_tpu.profiler as prof
    from paddle_tpu.tools.timeline import Timeline

    x = fluid.data("x", [16, 16])
    y = layers.matmul(x, x)
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    feed = {"x": np.ones((16, 16), np.float32)}
    exe.run(feed=feed, fetch_list=[y])
    d = prof.start_profiler(log_dir=str(tmp_path / "prof"))
    exe.run(feed=feed, fetch_list=[y])
    prof.stop_profiler()
    out = Timeline(d).save(str(tmp_path / "trace.json"))
    trace = json.load(open(out))
    assert "traceEvents" in trace and len(trace["traceEvents"]) > 0
    kinds = {e["ph"] for e in trace["traceEvents"]}
    assert "X" in kinds and "M" in kinds


def test_sequence_pool_empty_rows_emit_pad_value():
    x = fluid.data("x", [2, 3, 2])
    L = fluid.data("lens", [2], "int64")
    mx = layers.sequence_pool(x, "max", L, pad_value=-7.0)
    sm = layers.sequence_pool(x, "sum", L, pad_value=-7.0)
    xv = np.ones((2, 3, 2), np.float32)
    outs = _run([mx, sm], {"x": xv, "lens": np.asarray([0, 2], np.int64)})
    np.testing.assert_allclose(outs[0][0], -7.0)
    np.testing.assert_allclose(outs[0][1], 1.0)
    np.testing.assert_allclose(outs[1][0], -7.0)
    np.testing.assert_allclose(outs[1][1], 2.0)


def test_declarative_trains_layer():
    """loss.backward() through a @declarative forward reaches parameters
    (the reference to_static supports training)."""
    dg = fluid.dygraph

    @dg.declarative
    def forward(net, a):
        return layers.reduce_mean(
            layers.elementwise_mul(net(a), net(a))
        )

    with dg.guard():
        net = dg.Linear(4, 4, bias_attr=False)
        opt = fluid.optimizer.SGD(
            learning_rate=0.1, parameter_list=net.parameters()
        )
        xv = dg.to_variable(np.ones((2, 4), np.float32))
        losses = []
        for _ in range(20):
            loss = forward(net, xv)
            loss.backward()
            opt.minimize(loss)
            net.clear_gradients()
            losses.append(float(np.asarray(loss.value).reshape(-1)[0]))
    assert losses[-1] < losses[0] * 0.5, (losses[0], losses[-1])


def test_declarative_trains_multi_param_layer():
    """Regression: >=2 grad-requiring params through the boundary vjp
    (weight + bias) — the tape contract returns a 1-tuple of grads."""
    dg = fluid.dygraph

    @dg.declarative
    def forward(net, a):
        return layers.reduce_mean(layers.square(net(a)))

    with dg.guard():
        net = dg.Linear(4, 3)  # weight AND bias
        opt = fluid.optimizer.SGD(
            learning_rate=0.2, parameter_list=net.parameters()
        )
        rng = np.random.RandomState(0)
        xv = dg.to_variable(rng.randn(8, 4).astype(np.float32))
        losses = []
        for _ in range(30):
            loss = forward(net, xv)
            loss.backward()
            opt.minimize(loss)
            net.clear_gradients()
            losses.append(float(np.asarray(loss.value).reshape(-1)[0]))
    assert losses[-1] < losses[0] * 0.2, (losses[0], losses[-1])


def test_declarative_distinguishes_layer_instances():
    dg = fluid.dygraph

    @dg.declarative
    def f(net, a):
        return layers.reduce_sum(net(a))

    with dg.guard():
        n1 = dg.Linear(3, 1, bias_attr=False)
        n2 = dg.Linear(3, 1, bias_attr=False)
        x = dg.to_variable(np.ones((2, 3), np.float32))
        r1 = float(np.asarray(f(n1, x).value).reshape(-1)[0])
        r2 = float(np.asarray(f(n2, x).value).reshape(-1)[0])
        w1 = np.asarray(n1.weight.value).sum() * 2
        w2 = np.asarray(n2.weight.value).sum() * 2
        assert r1 == pytest.approx(w1, rel=1e-5)
        assert r2 == pytest.approx(w2, rel=1e-5)


def test_distillation_merge_and_soft_label():
    """Student learns to match a fixed teacher through the merged program
    (reference slim distillation flow: merge -> soft_label_loss -> train)."""
    from paddle_tpu.contrib.slim.distillation import merge, soft_label_loss

    scope = fluid.framework.scope.global_scope()

    # teacher: a fixed random linear projection (trained stand-in)
    teacher = fluid.Program()
    t_start = fluid.Program()
    with fluid.program_guard(teacher, t_start):
        tx = fluid.data("x", [16, 8])
        t_logits = layers.fc(
            tx, 4, param_attr=fluid.ParamAttr(name="t_w"),
            bias_attr=fluid.ParamAttr(name="t_b"),
        )
    exe = fluid.Executor()
    exe.run(t_start)

    # student program with its own tower
    s_logits = layers.fc(
        fluid.data("x", [16, 8]), 4,
        param_attr=fluid.ParamAttr(name="s_w"),
        bias_attr=fluid.ParamAttr(name="s_b"),
    )
    main = fluid.default_main_program()
    merge(teacher, main, {"x": "x"}, scope=scope)
    assert main.global_block.has_var("teacher_" + t_logits.name)
    loss = soft_label_loss("teacher_" + t_logits.name, s_logits.name)
    fluid.optimizer.Adam(0.05).minimize(loss)
    # teacher params must stay frozen
    tw_before = np.asarray(scope.find_var("teacher_t_w")).copy()

    exe.run(fluid.default_startup_program())
    scope.set_var("teacher_t_w", tw_before)  # startup may re-init; restore
    rng = np.random.RandomState(0)
    losses = []
    for _ in range(120):
        (lv,) = exe.run(
            feed={"x": rng.randn(16, 8).astype(np.float32)},
            fetch_list=[loss],
        )
        losses.append(float(np.asarray(lv).reshape(-1)[0]))
    assert losses[-1] < losses[0] * 0.8
    np.testing.assert_allclose(
        np.asarray(scope.find_var("teacher_t_w")), tw_before
    )
    # student mimics teacher: logits close on fresh data
    xv = rng.randn(16, 8).astype(np.float32)
    sw = np.asarray(scope.find_var("s_w"))
    sb = np.asarray(scope.find_var("s_b"))
    tw = np.asarray(scope.find_var("teacher_t_w"))
    tb = np.asarray(scope.find_var("teacher_t_b"))
    s_out = xv @ sw + sb
    t_out = xv @ tw + tb
    # compare softmax distributions (soft-label target)
    def softmax(z):
        e = np.exp(z - z.max(-1, keepdims=True))
        return e / e.sum(-1, keepdims=True)
    assert np.abs(softmax(s_out) - softmax(t_out)).max() < 0.2
