"""LR schedulers (static in-graph + dygraph) and meta-optimizers
(EMA / ModelAverage / Lookahead).

Modeled on the reference's test_learning_rate_scheduler.py, which runs the
program N steps and compares the fetched LR against a python formula
(python/paddle/fluid/tests/unittests/test_learning_rate_scheduler.py).
"""

import math

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu.framework import unique_name
from paddle_tpu import layers


@pytest.fixture(autouse=True)
def fresh_programs():
    main, startup = fluid.Program(), fluid.Program()
    scope = fluid.framework.scope.Scope()
    with fluid.program_guard(main, startup), fluid.scope_guard(scope), \
            unique_name.guard():
        yield main, startup, scope


def _run_schedule(lr_var, steps):
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    out = []
    for _ in range(steps):
        (v,) = exe.run(fetch_list=[lr_var])
        out.append(float(np.asarray(v).reshape(-1)[0]))
    return out


def test_noam_decay():
    lr = layers.noam_decay(d_model=64, warmup_steps=4, learning_rate=2.0)
    got = _run_schedule(lr, 8)
    for n, v in enumerate(got, start=1):
        want = 2.0 * 64 ** -0.5 * min(n ** -0.5, n * 4 ** -1.5)
        assert abs(v - want) < 1e-6, (n, v, want)


def test_exponential_decay_and_staircase():
    lr = layers.exponential_decay(0.1, decay_steps=3, decay_rate=0.5, staircase=True)
    got = _run_schedule(lr, 7)
    for n, v in enumerate(got):  # first run observes step 0 (= begin)
        want = 0.1 * 0.5 ** math.floor(n / 3)
        assert abs(v - want) < 1e-7


def test_natural_exp_and_inverse_time():
    lr = layers.natural_exp_decay(0.1, 5, 0.7)
    got = _run_schedule(lr, 5)
    for n, v in enumerate(got):
        assert abs(v - 0.1 * math.exp(-0.7 * n / 5)) < 1e-7


def test_polynomial_decay_cycle():
    lr = layers.polynomial_decay(1.0, decay_steps=4, end_learning_rate=0.1,
                                 power=2.0, cycle=True)
    got = _run_schedule(lr, 9)
    for n, v in enumerate(got):
        ratio = max(math.ceil(n / 4), 1)
        steps = 4 * ratio
        want = (1.0 - 0.1) * (1 - n / steps) ** 2 + 0.1
        assert abs(v - want) < 1e-6, (n, v, want)


def test_piecewise_decay():
    lr = layers.piecewise_decay([2, 5], [1.0, 0.5, 0.1])
    got = _run_schedule(lr, 7)
    want = [1.0, 1.0, 0.5, 0.5, 0.5, 0.1, 0.1]
    np.testing.assert_allclose(got, want, rtol=1e-6)


def test_cosine_decay():
    lr = layers.cosine_decay(0.5, step_each_epoch=2, epochs=4)
    got = _run_schedule(lr, 8)
    for n, v in enumerate(got):
        epoch = math.floor(n / 2)
        want = 0.5 * 0.5 * (math.cos(epoch * math.pi / 4) + 1)
        assert abs(v - want) < 1e-6


def test_linear_warmup_over_decay():
    base = layers.exponential_decay(0.1, 10, 0.5)
    lr = layers.linear_lr_warmup(base, warmup_steps=3, start_lr=0.0, end_lr=0.1)
    got = _run_schedule(lr, 6)
    for n, v in enumerate(got):
        if n < 3:
            want = 0.0 + (0.1 - 0.0) * n / 3
        else:
            want = 0.1 * 0.5 ** (n / 10)
        assert abs(v - want) < 1e-6, (n, v, want)


def test_scheduler_drives_optimizer():
    """SGD step size must follow the schedule (lr var feeds the update op)."""
    x = fluid.data("x", [-1, 2])
    w = fluid.layers.fc(x, 1, bias_attr=False)
    loss = fluid.layers.mean(w)
    lr = layers.piecewise_decay([2], [0.5, 0.0])
    opt = fluid.optimizer.SGD(learning_rate=lr)
    opt.minimize(loss)
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    feed = {"x": np.ones((4, 2), dtype=np.float32)}
    scope = fluid.framework.scope.global_scope()
    pname = fluid.default_main_program().all_parameters()[0].name
    before = np.asarray(scope.find_var(pname)).copy()
    exe.run(feed=feed, fetch_list=[loss])  # lr = 0.5 -> param moves
    after1 = np.asarray(scope.find_var(pname)).copy()
    assert np.abs(after1 - before).max() > 1e-6
    exe.run(feed=feed, fetch_list=[loss])
    exe.run(feed=feed, fetch_list=[loss])  # step 3: lr = 0 -> param frozen
    after2 = np.asarray(scope.find_var(pname)).copy()
    exe.run(feed=feed, fetch_list=[loss])
    after3 = np.asarray(scope.find_var(pname))
    np.testing.assert_allclose(after2, after3)


# -- dygraph schedulers ----------------------------------------------------


def test_dygraph_schedulers_match_static_formulas():
    dg = fluid.dygraph
    s = dg.NoamDecay(64, 4, begin=1)
    vals = [s() for _ in range(5)]
    for n, v in enumerate(vals, start=1):
        assert abs(v - 64 ** -0.5 * min(n ** -0.5, n * 4 ** -1.5)) < 1e-9

    pw = dg.PiecewiseDecay([2, 5], [1.0, 0.5, 0.1])
    got = [pw() for _ in range(7)]
    assert got == [1.0, 1.0, 0.5, 0.5, 0.5, 0.1, 0.1]

    pl = dg.ReduceLROnPlateau(1.0, patience=0, decay_rate=0.5)
    pl.step(1.0)
    assert pl() == 1.0
    pl.step(1.0)  # not better -> patience 0 exceeded -> decay
    assert pl() == 0.5


# -- meta-optimizers -------------------------------------------------------


def _train_sgd_steps(nsteps, lr=0.1, build_extra=None):
    x = fluid.data("x", [-1, 2])
    y = fluid.layers.fc(x, 1, bias_attr=False)
    loss = fluid.layers.mean(y)
    opt = fluid.optimizer.SGD(learning_rate=lr)
    opt.minimize(loss)
    extra = build_extra() if build_extra else None
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    scope = fluid.framework.scope.global_scope()
    pname = fluid.default_main_program().all_parameters()[0].name
    feed = {"x": np.ones((2, 2), dtype=np.float32)}
    history = []
    for _ in range(nsteps):
        exe.run(feed=feed, fetch_list=[loss])
        history.append(np.asarray(scope.find_var(pname)).copy())
    return exe, scope, pname, history, extra


def test_ema_matches_numpy():
    decay = 0.9

    def build():
        ema = fluid.optimizer.ExponentialMovingAverage(decay)
        ema.update()
        return ema

    exe, scope, pname, history, ema = _train_sgd_steps(4, build_extra=build)
    want = np.zeros_like(history[0])
    for p in history:
        want = decay * want + (1 - decay) * p
    debias = 1 - decay ** len(history)
    with ema.apply(exe):
        got = np.asarray(scope.find_var(pname))
        np.testing.assert_allclose(got, want / debias, rtol=1e-5)
    # restored after context exit
    np.testing.assert_allclose(np.asarray(scope.find_var(pname)), history[-1])


def test_model_average_matches_numpy():
    def build():
        return fluid.optimizer.ModelAverage(0.15, max_average_window=100)

    exe, scope, pname, history, ma = _train_sgd_steps(5, build_extra=build)
    want = np.mean(history, axis=0)
    with ma.apply(exe):
        np.testing.assert_allclose(
            np.asarray(scope.find_var(pname)), want, rtol=1e-5
        )
    np.testing.assert_allclose(np.asarray(scope.find_var(pname)), history[-1])


def test_lookahead_matches_numpy():
    k, alpha, lr = 2, 0.5, 0.1
    x = fluid.data("x", [-1, 2])
    y = fluid.layers.fc(x, 1, bias_attr=False)
    loss = fluid.layers.mean(y)
    inner = fluid.optimizer.SGD(learning_rate=lr)
    opt = fluid.optimizer.LookaheadOptimizer(inner, alpha=alpha, k=k)
    opt.minimize(loss)
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    scope = fluid.framework.scope.global_scope()
    pname = fluid.default_main_program().all_parameters()[0].name
    feed = {"x": np.ones((2, 2), dtype=np.float32)}

    fast = np.asarray(scope.find_var(pname)).copy()
    slow = fast.copy()
    # derive the true grad once from the first step instead of hand-computing
    exe.run(feed=feed, fetch_list=[loss])
    after1 = np.asarray(scope.find_var(pname))
    g = (fast - after1) / lr  # step 1 is not a sync step (counter=1, 1%2!=0)
    fast_np = fast - lr * g
    for step in range(2, 5):
        fast_np = fast_np - lr * g
        if step % k == 0:
            slow = alpha * fast_np + (1 - alpha) * slow
            fast_np = slow
        exe.run(feed=feed, fetch_list=[loss])
    got = np.asarray(scope.find_var(pname))
    np.testing.assert_allclose(got, fast_np, rtol=1e-5, atol=1e-6)


def test_model_average_window_shift_keeps_history():
    """After cnt_cur hits max_average_window the tier shifts instead of
    dropping history: apply() right after a restart still averages over at
    least one full window (review finding vs the reference's sum_1/2/3)."""

    def build():
        return fluid.optimizer.ModelAverage(
            0.15, min_average_window=2, max_average_window=3
        )

    exe, scope, pname, history, ma = _train_sgd_steps(4, build_extra=build)
    # steps 1..3 fill the current tier; step 4 shifts it and restarts:
    # average must cover all 4 samples (3 old + 1 current), not just 1
    want = np.mean(history, axis=0)
    with ma.apply(exe):
        np.testing.assert_allclose(
            np.asarray(scope.find_var(pname)), want, rtol=1e-5
        )


def test_unseeded_programs_are_decorrelated():
    """random_seed=0 means nondeterministic (fluid semantics): two unseeded
    programs must draw different dropout masks."""
    outs = []
    for _ in range(2):
        main, startup = fluid.Program(), fluid.Program()
        scope = fluid.framework.scope.Scope()
        with fluid.program_guard(main, startup), fluid.scope_guard(scope), \
                unique_name.guard():
            x = fluid.data("x", [-1, 64])
            y = fluid.layers.dropout(x, dropout_prob=0.5)
            exe = fluid.Executor()
            exe.run(startup)
            (v,) = exe.run(
                feed={"x": np.ones((4, 64), dtype=np.float32)}, fetch_list=[y]
            )
            outs.append(np.asarray(v))
    assert not np.array_equal(outs[0], outs[1])


def test_eager_schedule_advances_once_per_minimize():
    """A schedule callable must be evaluated once per minimize, not once per
    parameter (multi-param model would burn the schedule N_params too fast)."""
    dg = fluid.dygraph
    with dg.guard():
        layer = dg.Linear(4, 3)  # weight + bias = 2 params
        sched = dg.PiecewiseDecay([2, 5], [1.0, 0.5, 0.1])
        opt = fluid.optimizer.SGD(
            learning_rate=sched, parameter_list=layer.parameters()
        )
        x = dg.to_variable(np.ones((2, 4), dtype=np.float32))
        loss = fluid.layers.reduce_mean(layer(x))
        loss.backward()
        opt.minimize(loss)
        assert sched.step_num == 1, sched.step_num
