"""Training health guard: heartbeat liveness, step watchdog, TrainGuard
numeric-anomaly skip/rollback, preemption drain, and the launcher's
hung-rank + preemption exit-code contracts.

In-process pieces (watchdog, guard policy, AMP feedback) run against real
programs on the CPU mesh; the launcher contracts run against fake procs
(same-tick death bookkeeping) and real subprocesses (exit codes, and — as
slow tests — the full hang-kill-restart and SIGTERM-drain loops that the
ci.sh chaos smoke also exercises).
"""

import json
import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import errors, layers, observability
from paddle_tpu.fleet import collective as fc
from paddle_tpu.fleet.role_maker import UserDefinedRoleMaker
from paddle_tpu.framework import unique_name
from paddle_tpu.framework.scope import Scope
from paddle_tpu.resilience import (
    PREEMPTION_EXIT_CODE,
    Heartbeat,
    StepWatchdog,
    TrainGuard,
    faults,
    heartbeat_path,
    read_beat,
)

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)


@pytest.fixture(autouse=True)
def _fresh():
    faults.clear()
    main, startup = fluid.Program(), fluid.Program()
    scope = Scope()
    with fluid.program_guard(main, startup), fluid.scope_guard(scope), \
            unique_name.guard():
        yield
    faults.clear()


def _counter(name):
    return observability.snapshot()["counters"].get(name, 0)


# -- heartbeat ---------------------------------------------------------------
def test_heartbeat_writes_monotonic_beats(tmp_path):
    hb = Heartbeat(str(tmp_path), rank=3)
    p1 = hb.beat()
    p2 = hb.beat()
    assert (p1["step"], p2["step"]) == (1, 2)
    on_disk = read_beat(heartbeat_path(str(tmp_path), 3))
    assert on_disk["rank"] == 3 and on_disk["step"] == 2
    assert on_disk["time"] == pytest.approx(time.time(), abs=30)
    hb.beat(step=41)  # resume-from-checkpoint override
    assert read_beat(hb.path)["step"] == 41


def test_heartbeat_env_autoconfig(tmp_path, monkeypatch):
    monkeypatch.setenv("PADDLE_HEARTBEAT_DIR", str(tmp_path))
    monkeypatch.setenv("PADDLE_TRAINER_ID", "2")
    hb = Heartbeat()
    hb.beat()
    assert read_beat(heartbeat_path(str(tmp_path), 2))["step"] == 1


def test_read_beat_tolerates_missing_and_torn(tmp_path):
    assert read_beat(str(tmp_path / "nope")) is None
    torn = tmp_path / "hb_rank0"
    torn.write_text('{"rank": 0, "st')  # torn mid-publish
    assert read_beat(str(torn)) is None


# -- watchdog ----------------------------------------------------------------
def test_watchdog_fires_on_stall_and_stays_quiet_when_beating():
    stalls = []
    wd = StepWatchdog(timeout=0.5, poll_interval=0.02,
                      on_stall=stalls.append, name="t")
    with wd:
        for _ in range(15):  # slow-but-beating loop: never a stall
            time.sleep(0.03)
            wd.touch()
        assert stalls == []
        time.sleep(1.2)  # stalled: fires exactly once until re-armed
        assert len(stalls) == 1 and stalls[0] > 0.5
        wd.touch()
        time.sleep(1.2)
        assert len(stalls) == 2
    assert wd.stalls == 2
    assert _counter("resilience.hangs") >= 2
    assert _counter("resilience.hangs.t") >= 2


# -- fault kinds -------------------------------------------------------------
def test_hang_fault_sleeps_at_seam(monkeypatch):
    monkeypatch.setenv(faults.HANG_SECONDS_ENV, "0.3")
    faults.inject("some.site", "hang", 1.0, 0, 1)
    t0 = time.monotonic()
    faults.fault_point("some.site")  # sleeps, does not raise
    assert time.monotonic() - t0 >= 0.25
    faults.fault_point("some.site")  # max_fires=1: healed
    assert time.monotonic() - t0 < 1.0


def test_nonfinite_corrupt_point_poisons_floats_only():
    faults.inject("guard.step", "nonfinite", 1.0, 0, 1)
    feed = {"x": np.ones((2, 2), np.float32), "i": np.arange(3)}
    out = faults.corrupt_point("guard.step", feed)
    assert np.isnan(out["x"]).all()
    np.testing.assert_array_equal(out["i"], np.arange(3))  # ints untouched
    clean = {"x": np.ones(2, np.float32)}
    assert faults.corrupt_point("guard.step", clean) is clean  # healed


def test_nonfinite_at_raise_seam_degrades_to_typed_error():
    faults.inject("io.save", "nonfinite", 1.0)
    with pytest.raises(errors.NonFiniteError):
        faults.fault_point("io.save")


def test_parse_spec_accepts_new_kinds():
    assert faults.parse_spec("a.b:hang:1.0:7").kind == "hang"
    assert faults.parse_spec("a.b:nonfinite").kind == "nonfinite"


# -- executor check_nan_inf typing -------------------------------------------
def test_check_nan_inf_raises_typed_nonfinite_error():
    x = fluid.data("x", [2, 2])
    y = layers.log(x)
    fluid.set_flags({"FLAGS_check_nan_inf": True})
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    with pytest.raises(errors.NonFiniteError) as ei:
        exe.run(feed={"x": np.full((2, 2), -1.0, np.float32)},
                fetch_list=[y])
    assert ei.value.op_type == "log"
    assert y.name in ei.value.outputs
    assert "log" in str(ei.value) and y.name in str(ei.value)
    # still catchable as the pre-taxonomy type
    assert isinstance(ei.value, errors.PreconditionNotMetError)


# -- TrainGuard --------------------------------------------------------------
def _regression(lr=0.05, amp=None):
    rng = np.random.RandomState(3)
    W = rng.randn(4, 1).astype(np.float32)
    x = fluid.data("x", [-1, 4])
    y = fluid.data("y", [-1, 1])
    pred = layers.fc(x, 1)
    loss = layers.mean(layers.square_error_cost(pred, y))
    opt = fluid.optimizer.SGD(lr)
    if amp is not None:
        opt = amp(opt)
    opt.minimize(loss)
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())

    def feed(step, b=8):
        r = np.random.RandomState(100 + step)
        xa = r.randn(b, 4).astype(np.float32)
        return {"x": xa, "y": xa @ W}

    return exe, loss, feed, opt


def _params(scope=None):
    from paddle_tpu.framework.scope import global_scope

    scope = scope or global_scope()
    prog = fluid.default_main_program()
    return {
        v.name: np.asarray(scope.find_var(v.name)).copy()
        for v in prog.list_vars()
        if getattr(v, "persistable", False)
        and scope.find_var(v.name) is not None
    }


def test_guard_skips_nonfinite_step_and_converges():
    exe, loss, feed, _ = _regression()
    with TrainGuard(exe) as g:
        losses = []
        for step in range(12):
            if step == 4:
                before = _params()
                faults.inject("guard.step", "nonfinite", 1.0, 0, 1)
            out = g.step(feed=feed(step), fetch_list=[loss])
            if step == 4:
                # the poisoned step was skipped: no fetches, ZERO weight
                # updates (restored state is bit-identical)
                assert out is None
                after = _params()
                for name, val in before.items():
                    np.testing.assert_array_equal(val, after[name])
            else:
                assert out is not None
                losses.append(float(out[0].reshape(-1)[0]))
    assert g.bad_steps == 1 and g.steps == 12
    assert _counter("resilience.bad_steps") == 1
    assert losses[-1] < losses[0]  # still converged around the skip


def test_guard_returns_device_arrays_when_asked():
    exe, loss, feed, _ = _regression()
    with TrainGuard(exe) as g:
        out = g.step(feed=feed(0), fetch_list=[loss], return_numpy=False)
    assert not isinstance(out[0], np.ndarray)


def test_guard_feeds_amp_loss_scale_decay():
    from paddle_tpu.contrib.mixed_precision import decorate

    amp_box = {}

    def amp(opt):
        amp_box["opt"] = decorate(
            opt, init_loss_scaling=1024.0, decr_every_n_nan_or_inf=1,
            decr_ratio=0.5,
        )
        return amp_box["opt"]

    exe, loss, feed, _ = _regression(amp=amp)
    amp_opt = amp_box["opt"]
    scale_name = amp_opt.get_loss_scaling().name
    from paddle_tpu.framework.scope import global_scope

    with TrainGuard(exe, amp=amp_opt) as g:
        g.step(feed=feed(0), fetch_list=[loss])
        assert float(
            np.asarray(global_scope().find_var(scale_name)).reshape(-1)[0]
        ) == 1024.0
        faults.inject("guard.step", "nonfinite", 1.0, 0, 1)
        assert g.step(feed=feed(1), fetch_list=[loss]) is None
        # skip restored the pre-step state, then note_step decayed it
        assert float(
            np.asarray(global_scope().find_var(scale_name)).reshape(-1)[0]
        ) == 512.0


def test_guard_rolls_back_then_raises_diverged(tmp_path):
    exe, loss, feed, _ = _regression()
    fleet = fc.Fleet()
    fleet.init(UserDefinedRoleMaker())
    ckpt = str(tmp_path / "ckpts")
    with TrainGuard(
        exe, fleet=fleet, checkpoint_dir=ckpt,
        max_bad_steps=2, max_rollbacks=1,
    ) as g:
        for step in range(3):
            g.step(feed=feed(step), fetch_list=[loss])
        fleet.save_check_point(exe, ckpt, fc.TrainStatus(0))
        good = _params()
        faults.inject("guard.step", "nonfinite", 1.0)  # every step bad now
        assert g.step(feed=feed(3), fetch_list=[loss]) is None
        assert g.rollbacks == 0
        assert g.step(feed=feed(4), fetch_list=[loss]) is None  # K=2 -> roll
        assert g.rollbacks == 1
        assert _counter("resilience.rollbacks") == 1
        after = _params()
        for name, val in good.items():
            np.testing.assert_array_equal(val, after[name])
        assert g.train_status == fc.TrainStatus(0)
        g.step(feed=feed(5), fetch_list=[loss])
        with pytest.raises(errors.TrainingDivergedError, match="budget"):
            g.step(feed=feed(6), fetch_list=[loss])
    assert g.bad_steps == 4


def test_guard_rolls_back_to_pre_epoch_checkpoint(tmp_path):
    """A preemption-drain checkpoint saved before the first epoch finishes
    carries TrainStatus(-1) — it must still count as a valid rollback
    target, not as 'nothing to load'."""
    exe, loss, feed, _ = _regression()
    fleet = fc.Fleet()
    fleet.init(UserDefinedRoleMaker())
    ckpt = str(tmp_path / "ckpts")
    assert not fleet.has_check_point(ckpt)
    with TrainGuard(
        exe, fleet=fleet, checkpoint_dir=ckpt, max_bad_steps=2,
    ) as g:
        g.step(feed=feed(0), fetch_list=[loss])
        fleet.save_check_point(exe, ckpt, fc.TrainStatus(-1))
        assert fleet.has_check_point(ckpt)
        good = _params()
        faults.inject("guard.step", "nonfinite", 1.0)
        g.step(feed=feed(1), fetch_list=[loss])
        g.step(feed=feed(2), fetch_list=[loss])  # K=2 -> rollback, no raise
        assert g.rollbacks == 1
        after = _params()
        for name, val in good.items():
            np.testing.assert_array_equal(val, after[name])


def test_guard_diverges_without_rollback_config():
    exe, loss, feed, _ = _regression()
    faults.inject("guard.step", "nonfinite", 1.0)
    with TrainGuard(exe, max_bad_steps=2) as g:
        assert g.step(feed=feed(0), fetch_list=[loss]) is None
        with pytest.raises(errors.TrainingDivergedError, match="no fleet"):
            g.step(feed=feed(1), fetch_list=[loss])


def test_guard_beats_heartbeat_each_step(tmp_path):
    exe, loss, feed, _ = _regression()
    hb = Heartbeat(str(tmp_path), rank=0)
    with TrainGuard(exe, heartbeat=hb) as g:
        for step in range(3):
            g.step(feed=feed(step), fetch_list=[loss])
    assert read_beat(hb.path)["step"] == 3
    assert _counter("resilience.heartbeats") == 3


def test_guard_sigterm_drains_to_final_checkpoint(tmp_path):
    exe, loss, feed, _ = _regression()
    fleet = fc.Fleet()
    fleet.init(UserDefinedRoleMaker())
    ckpt = str(tmp_path / "ckpts")
    with TrainGuard(
        exe, fleet=fleet, checkpoint_dir=ckpt, exit_on_preempt=False,
        train_status=fc.TrainStatus(7),
    ) as g:
        assert signal.getsignal(signal.SIGTERM) == g._on_sigterm
        g.step(feed=feed(0), fetch_list=[loss])
        signal.raise_signal(signal.SIGTERM)  # delivered in-process
        assert g.draining
        assert g.step(feed=feed(1), fetch_list=[loss]) is None  # drained
    assert g.preempted
    assert _counter("resilience.preemptions") == 1
    # the final checkpoint is valid (CRC-verified on load) and carries the
    # drain-time train status
    status = fleet.load_check_point(exe, ckpt)
    assert status == fc.TrainStatus(7)
    # handler restored on exit
    assert signal.getsignal(signal.SIGTERM) != g._on_sigterm


def test_guard_sigterm_exit_code_is_distinguished():
    exe, loss, feed, _ = _regression()
    with pytest.raises(SystemExit) as ei:
        with TrainGuard(exe) as g:
            g.step(feed=feed(0), fetch_list=[loss])
            signal.raise_signal(signal.SIGTERM)
            g.step(feed=feed(1), fetch_list=[loss])
    assert ei.value.code == PREEMPTION_EXIT_CODE


def test_guard_drain_at_loop_end_still_finalizes():
    """SIGTERM landing after the last step: __exit__ honors the contract."""
    exe, loss, feed, _ = _regression()
    with pytest.raises(SystemExit) as ei:
        with TrainGuard(exe) as g:
            g.step(feed=feed(0), fetch_list=[loss])
            signal.raise_signal(signal.SIGTERM)
    assert ei.value.code == PREEMPTION_EXIT_CODE
    assert g.preempted


# -- AMP note_step unit ------------------------------------------------------
def test_amp_note_step_automaton():
    from paddle_tpu.contrib.mixed_precision import decorate
    from paddle_tpu.framework.scope import global_scope

    rng = np.random.RandomState(0)
    x = fluid.data("x", [4, 4])
    loss = layers.mean(layers.fc(x, 1))
    opt = decorate(
        fluid.optimizer.SGD(0.1), init_loss_scaling=8.0,
        incr_every_n_steps=2, decr_every_n_nan_or_inf=2,
        incr_ratio=2.0, decr_ratio=0.5,
    )
    opt.minimize(loss)
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())

    def scale():
        return float(np.asarray(
            global_scope().find_var(opt.get_loss_scaling().name)
        ).reshape(-1)[0])

    opt.note_step(False)
    assert scale() == 8.0  # 1 bad < decr_every
    opt.note_step(False)
    assert scale() == 4.0  # 2 consecutive bad -> decay
    opt.note_step(True)
    opt.note_step(False)  # good resets the bad streak
    opt.note_step(False)
    assert scale() == 2.0
    opt.note_step(True)
    opt.note_step(True)  # 2 consecutive good -> grow
    assert scale() == 4.0


def test_amp_note_step_noop_before_minimize():
    from paddle_tpu.contrib.mixed_precision import decorate

    opt = decorate(fluid.optimizer.SGD(0.1))
    assert opt.note_step(False) is None  # state not built yet: no crash


# -- TrainStatus -------------------------------------------------------------
def test_train_status_ne_consistent_with_eq():
    a, b, c = fc.TrainStatus(1), fc.TrainStatus(1), fc.TrainStatus(2)
    assert a == b and not (a != b)
    assert a != c and not (a == c)
    assert a != object() and not (a == object())
    assert "epoch_no=1" in repr(a)


# -- launcher: same-tick deaths + interleaved restarts -----------------------
class _FakeProc:
    """poll() plays back a script of return codes (None = alive)."""

    _pid = 1000

    def __init__(self, rank, script):
        _FakeProc._pid += 1
        self.pid = _FakeProc._pid
        self._paddle_rank = rank
        self._paddle_log = None
        self._paddle_spawned = time.time()
        self._script = list(script)
        self._rc = None

    def poll(self):
        if self._rc is None and self._script:
            self._rc = self._script.pop(0)
        return self._rc

    def wait(self, timeout=None):
        return self.poll()

    def send_signal(self, sig):
        pass

    def kill(self):
        self._rc = -9


def test_watch_two_ranks_dying_same_tick_get_independent_restarts(capsys):
    from paddle_tpu.distributed import launch

    spawned = []

    def fake_spawn(args, endpoints, rank, attempt=0):
        spawned.append((rank, attempt))
        return _FakeProc(rank, [0])  # restarted children exit clean

    args = launch.parse_args([
        "--elastic", "--max_restarts", "2", "--restart_backoff", "0.01",
        "x.py",
    ])
    procs = [
        _FakeProc(0, [None, None, None, None, 0]),
        _FakeProc(1, [1]),   # dies on the first tick...
        _FakeProc(2, [7]),   # ...same tick as rank 2
    ]
    old_spawn = launch.spawn_trainer
    launch.spawn_trainer = fake_spawn
    try:
        rc = launch.watch_local_trainers(procs, args, ["e0", "e1", "e2"])
    finally:
        launch.spawn_trainer = old_spawn
    assert rc == 0
    # both ranks were scheduled + respawned with their own attempt counter
    assert sorted(spawned) == [(1, 1), (2, 1)]
    err = capsys.readouterr().err
    assert "rank 1 died (rc=1); restart 1/2" in err
    assert "rank 2 died (rc=7); restart 1/2" in err


def test_watch_interleaved_restarts_survive_bookkeeping(capsys):
    from paddle_tpu.distributed import launch

    spawned = []

    def fake_spawn(args, endpoints, rank, attempt=0):
        spawned.append((rank, attempt))
        if rank == 1 and attempt == 1:
            return _FakeProc(rank, [3])  # rank 1's first restart dies too
        return _FakeProc(rank, [None, 0])

    args = launch.parse_args([
        "--elastic", "--max_restarts", "2", "--restart_backoff", "0.01",
        "x.py",
    ])
    procs = [
        _FakeProc(0, [None] * 12 + [0]),
        _FakeProc(1, [1]),
        _FakeProc(2, [2]),
    ]
    old_spawn = launch.spawn_trainer
    launch.spawn_trainer = fake_spawn
    try:
        rc = launch.watch_local_trainers(procs, args, ["e0", "e1", "e2"])
    finally:
        launch.spawn_trainer = old_spawn
    assert rc == 0
    # rank 1 restarted twice (second restart after the first's death
    # interleaved with rank 2's pending restart), rank 2 once
    assert sorted(spawned) == [(1, 1), (1, 2), (2, 1)]
    assert "restart 2/2" in capsys.readouterr().err


def test_watch_aborts_when_hung_rank_exits_preemption_code():
    """rc==PREEMPTION_EXIT_CODE is clean ONLY when the launcher did not
    have to kill the child as hung."""
    from paddle_tpu.distributed import launch

    p = _FakeProc(1, [PREEMPTION_EXIT_CODE])
    p._paddle_hung = True
    args = launch.parse_args(["x.py"])
    with pytest.raises(RuntimeError, match="hung"):
        launch.watch_local_trainers(
            [_FakeProc(0, [None, 0]), p], args, ["e0", "e1"]
        )


def test_launcher_treats_preemption_exit_as_clean(tmp_path):
    """A child exiting PREEMPTION_EXIT_CODE does not abort the pod and
    burns no restart budget (subprocess-level contract; the child script
    is jax-free so this is fast)."""
    script = tmp_path / "preempted.py"
    script.write_text(
        "import os, sys\n"
        "sys.exit(%d if os.environ['PADDLE_TRAINER_ID'] == '1' else 0)\n"
        % PREEMPTION_EXIT_CODE
    )
    proc = subprocess.run(
        [
            sys.executable, "-m", "paddle_tpu.distributed.launch",
            "--nproc_per_node", "2", str(script),
        ],
        cwd=REPO, capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 0, proc.stderr
    assert "restart" not in proc.stderr and "aborted" not in proc.stderr


# -- end-to-end chaos (also run by ci.sh) ------------------------------------
@pytest.mark.slow
def test_launcher_kills_and_restarts_hung_rank(tmp_path):
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.run(
        [
            sys.executable, "-m", "paddle_tpu.distributed.launch",
            "--nproc_per_node", "2", "--simulate_cpu", "--elastic",
            "--max_restarts", "2", "--restart_backoff", "0.1",
            "--heartbeat_dir", str(tmp_path / "hb"),
            "--heartbeat_timeout", "20",
            os.path.join(HERE, "dist_hang_worker.py"), str(tmp_path),
        ],
        env=env, cwd=REPO, capture_output=True, text=True, timeout=540,
    )
    assert proc.returncode == 0, f"stdout:{proc.stdout}\nstderr:{proc.stderr}"
    assert "hung" in proc.stderr and "restart 1/2" in proc.stderr
    r1 = json.load(open(tmp_path / "hang_losses_1.json"))
    assert r1["attempt"] == 1  # the file was written by the restart
    assert r1["losses"][-1] < r1["losses"][0]
    r0 = json.load(open(tmp_path / "hang_losses_0.json"))
    assert r0["attempt"] == 0  # rank 0 was never disturbed


@pytest.mark.slow
def test_sigterm_produces_final_checkpoint_and_exit_code(tmp_path):
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.Popen(
        [
            sys.executable,
            os.path.join(HERE, "dist_preempt_worker.py"), str(tmp_path),
        ],
        env=env, cwd=REPO, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
        text=True,
    )
    marker = tmp_path / "ready"
    deadline = time.monotonic() + 120
    while not marker.exists():
        assert proc.poll() is None, proc.communicate()[1]
        assert time.monotonic() < deadline, "worker never became ready"
        time.sleep(0.1)
    proc.send_signal(signal.SIGTERM)
    out, err = proc.communicate(timeout=120)
    assert proc.returncode == PREEMPTION_EXIT_CODE, f"{out}\n{err}"
    # the drain checkpoint verifies (CRC manifest) and loads
    fleet = fc.Fleet()
    fleet.init(UserDefinedRoleMaker())
    status = fleet.load_check_point(
        fluid.Executor(), str(tmp_path / "ckpts")
    )
    assert status == fc.TrainStatus(0)
