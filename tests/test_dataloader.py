"""DataLoader / reader subsystem tests.

Modeled on the reference's test_dataloader_* / test_generator_loader suites
(python/paddle/fluid/tests/unittests/test_dataloader_dataset.py,
test_generator_dataloader.py): samplers, collation, multi-worker ordering,
from_generator feeding a real train loop.
"""

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu.dataloader import (
    BatchSampler,
    ChainDataset,
    ConcatDataset,
    Dataset,
    DistributedBatchSampler,
    IterableDataset,
    RandomSampler,
    Subset,
    TensorDataset,
    default_collate_fn,
    random_split,
)
from paddle_tpu.framework import unique_name


@pytest.fixture(autouse=True)
def fresh_programs():
    main, startup = fluid.Program(), fluid.Program()
    scope = fluid.framework.scope.Scope()
    with fluid.program_guard(main, startup), fluid.scope_guard(scope), \
            unique_name.guard():
        yield main, startup, scope


class _Square(Dataset):
    def __init__(self, n):
        self.n = n

    def __getitem__(self, i):
        return np.asarray([i, i * i], dtype=np.float32)

    def __len__(self):
        return self.n


class _Stream(IterableDataset):
    def __init__(self, n):
        self.n = n

    def __iter__(self):
        for i in range(self.n):
            yield np.asarray([i], dtype=np.float32)


def test_batch_sampler_shapes_and_drop_last():
    bs = BatchSampler(dataset=_Square(10), batch_size=3)
    batches = list(bs)
    assert [len(b) for b in batches] == [3, 3, 3, 1]
    assert len(bs) == 4
    bs = BatchSampler(dataset=_Square(10), batch_size=3, drop_last=True)
    assert len(list(bs)) == 3 == len(bs)


def test_random_sampler_seeded_permutation():
    s = RandomSampler(_Square(8), generator=0)
    a, b = list(s), list(s)
    assert sorted(a) == list(range(8)) and a == b  # seeded -> reproducible


def test_dataloader_map_style_order_and_collate():
    dl = fluid.DataLoader(_Square(7), batch_size=3, use_buffer_reader=False)
    out = list(dl)
    assert len(out) == 3
    np.testing.assert_allclose(out[0], [[0, 0], [1, 1], [2, 4]])
    np.testing.assert_allclose(out[2], [[6, 36]])


def test_dataloader_multiworker_preserves_order():
    dl = fluid.DataLoader(
        _Square(50), batch_size=4, num_workers=3, use_buffer_reader=False
    )
    flat = np.concatenate([np.asarray(b)[:, 0] for b in dl])
    np.testing.assert_allclose(flat, np.arange(50))


def test_dataloader_multiworker_propagates_errors():
    class Bad(Dataset):
        def __len__(self):
            return 10

        def __getitem__(self, i):
            if i == 7:
                raise ValueError("boom at 7")
            return np.zeros(1, np.float32)

    dl = fluid.DataLoader(Bad(), batch_size=2, num_workers=2,
                          use_buffer_reader=False)
    with pytest.raises(ValueError, match="boom at 7"):
        list(dl)


def test_iterable_dataset_stream():
    dl = fluid.DataLoader(_Stream(5), batch_size=2, use_buffer_reader=False)
    out = list(dl)
    assert [len(b) for b in out] == [2, 2, 1]
    with pytest.raises(ValueError):
        iter(fluid.DataLoader(_Stream(5), batch_size=2, num_workers=2))


def test_tensor_concat_subset_split_chain():
    td = TensorDataset([np.arange(6), np.arange(6) * 10])
    assert td[2] == (2, 20) and len(td) == 6
    cd = ConcatDataset([_Square(3), _Square(2)])
    assert len(cd) == 5
    np.testing.assert_allclose(cd[3], [0, 0])
    sub = Subset(_Square(10), [9, 1])
    np.testing.assert_allclose(sub[0], [9, 81])
    a, b = random_split(_Square(10), [7, 3], seed=0)
    assert len(a) == 7 and len(b) == 3
    assert sorted(a.indices + b.indices) == list(range(10))
    ch = list(ChainDataset([_Stream(2), _Stream(3)]))
    assert len(ch) == 5


def test_distributed_batch_sampler_disjoint_covering():
    ds = _Square(10)
    seen = []
    for rank in range(3):
        s = DistributedBatchSampler(ds, batch_size=2, nranks=3, rank=rank)
        for batch in s:
            seen.extend(batch)
    # padded coverage: every index appears; ranks get equal share (12 total)
    assert set(seen) == set(range(10)) and len(seen) == 12


def test_collate_nested_structures():
    batch = [
        {"a": np.ones(2, np.float32), "b": (1, np.zeros(3))},
        {"a": np.zeros(2, np.float32), "b": (2, np.ones(3))},
    ]
    out = default_collate_fn(batch)
    assert out["a"].shape == (2, 2)
    np.testing.assert_allclose(out["b"][0], [1, 2])
    assert out["b"][1].shape == (2, 3)


def test_from_generator_trains_fit_a_line():
    """GeneratorLoader feeds a real training loop (reference
    test_generator_dataloader.py shape)."""
    x = fluid.data("x", [-1, 4])
    y = fluid.data("y", [-1, 1])
    pred = fluid.layers.fc(x, 1)
    loss = fluid.layers.mean(fluid.layers.square_error_cost(pred, y))
    fluid.optimizer.SGD(0.05).minimize(loss)

    loader = fluid.DataLoader.from_generator(feed_list=[x, y], capacity=8)
    w_true = np.arange(4, dtype=np.float32).reshape(4, 1)
    rng = np.random.RandomState(0)

    def sample_gen():
        for _ in range(64):
            xv = rng.randn(4).astype(np.float32)
            yield xv, np.asarray([xv @ w_true.ravel()], dtype=np.float32)

    loader.set_sample_generator(sample_gen, batch_size=16)

    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    losses = []
    for _ in range(8):  # epochs over the generator
        for feed in loader():
            (lv,) = exe.run(feed=feed, fetch_list=[loss])
            losses.append(float(np.asarray(lv).reshape(-1)[0]))
    assert losses[-1] < losses[0] * 0.5


def test_from_generator_batch_generator_and_names():
    x = fluid.data("xx", [-1, 2])
    loader = fluid.DataLoader.from_generator(feed_list=[x])

    def batches():
        for i in range(3):
            yield [np.full((2, 2), i, np.float32)]

    loader.set_batch_generator(batches)
    got = list(loader())
    assert list(got[0].keys()) == ["xx"]
    np.testing.assert_allclose(got[2]["xx"], np.full((2, 2), 2))


def test_dataloader_device_staging_feeds_executor():
    """use_buffer_reader=True yields device arrays the Executor accepts."""
    x = fluid.data("x", [-1, 2])
    out = fluid.layers.reduce_sum(x)
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    dl = fluid.DataLoader(
        TensorDataset([np.ones((6, 2), np.float32)]), batch_size=3,
        feed_list=[x],
    )
    total = 0.0
    for feed in dl:
        (v,) = exe.run(feed=feed, fetch_list=[out])
        total += float(np.asarray(v).reshape(-1)[0])
    assert total == 12.0


def test_feed_list_single_column_batches():
    """A dataset whose samples are single arrays must feed the whole batch,
    not row 0 (regression: zip over the ndarray iterated rows)."""
    x = fluid.data("x", [-1, 2])
    dl = fluid.DataLoader(
        _Square(6), batch_size=3, feed_list=[x], use_buffer_reader=False
    )
    feeds = list(dl)
    assert feeds[0]["x"].shape == (3, 2)
    np.testing.assert_allclose(feeds[1]["x"], [[3, 9], [4, 16], [5, 25]])


def test_generator_loader_early_break_releases_producer():
    import threading

    x = fluid.data("x", [-1, 1])
    loader = fluid.DataLoader.from_generator(feed_list=[x], capacity=2)

    def batches():
        for i in range(1000):
            yield [np.full((1, 1), i, np.float32)]

    loader.set_batch_generator(batches)
    before = threading.active_count()
    for _ in range(5):
        for feed in loader():
            break  # abandon immediately
    import time

    time.sleep(0.5)  # give producers time to observe the stop event
    assert threading.active_count() <= before + 1


def test_generator_loader_start_next_reset_protocol():
    x = fluid.data("x", [-1, 1])
    loader = fluid.DataLoader.from_generator(feed_list=[x])
    with pytest.raises(RuntimeError, match="start"):
        loader.next()
    loader.set_batch_generator(
        lambda: iter([[np.ones((1, 1), np.float32)]])
    )
    loader.start()
    got = loader.next()
    np.testing.assert_allclose(got["x"], [[1.0]])
    with pytest.raises(StopIteration):
        loader.next()
    loader.reset()
    with pytest.raises(RuntimeError, match="start"):
        loader.next()
