"""Mask R-CNN assembly test (VERDICT r2 item 3): tiny-config train step
with finite losses that decrease, plus the inference decode path.
Mirrors tests/test_yolov3.py's shape: one synthetic image, dense gt
contract (boxes + classes + per-gt bitmap masks)."""

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import layers
from paddle_tpu.framework import unique_name
from paddle_tpu.models import mask_rcnn


@pytest.fixture(autouse=True)
def fresh():
    main, startup = fluid.Program(), fluid.Program()
    scope = fluid.framework.scope.Scope()
    with fluid.program_guard(main, startup), fluid.scope_guard(scope), \
            unique_name.guard():
        yield main, startup


def _feed(rng, size=64, n_gt=2):
    img = rng.rand(1, 3, size, size).astype(np.float32)
    gt_boxes = np.array([[4, 4, 30, 30], [34, 34, 60, 60]], np.float32)
    gt_classes = np.array([1, 2], np.int32)
    is_crowd = np.zeros(2, np.int32)
    segms = np.zeros((2, size, size), np.float32)
    segms[0, 4:31, 4:31] = 1
    segms[1, 34:61, 34:61] = 1
    im_info = np.array([[size, size, 1.0]], np.float32)
    return {"image": img, "gt_boxes": gt_boxes, "gt_classes": gt_classes,
            "is_crowd": is_crowd, "gt_segms": segms, "im_info": im_info}


@pytest.mark.slow  # ~58s on the CI CPU: the single heaviest tier-1 test;
# ci.sh's unfiltered pytest still runs it (tier-1 runs -m 'not slow')
def test_mask_rcnn_train_step_converges(fresh):
    cfg = mask_rcnn.MaskRCNNConfig.tiny()
    image = fluid.data("image", [1, 3, 64, 64])
    gt_boxes = fluid.data("gt_boxes", [2, 4])
    gt_classes = fluid.data("gt_classes", [2], dtype="int32")
    is_crowd = fluid.data("is_crowd", [2], dtype="int32")
    gt_segms = fluid.data("gt_segms", [2, 64, 64])
    im_info = fluid.data("im_info", [1, 3])

    losses = mask_rcnn.mask_rcnn_train(
        image, gt_boxes, gt_classes, is_crowd, gt_segms, im_info, cfg
    )
    total = losses[0]
    fluid.optimizer.SGD(0.01).minimize(total)

    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    rng = np.random.RandomState(0)
    feed = _feed(rng)
    vals = []
    for _ in range(12):
        out = exe.run(feed=feed, fetch_list=list(losses))
        vals.append([float(np.asarray(v).reshape(-1)[0]) for v in out])
    totals = [v[0] for v in vals]
    assert all(np.isfinite(v) for row in vals for v in row), vals[0]
    # the per-step RNG re-samples the fg/bg minibatch (reference
    # use_random=True), so compare a trailing average, not single steps
    assert np.mean(totals[-3:]) < totals[0], totals


@pytest.mark.slow  # ~25s on the CI CPU; ci.sh's unfiltered pytest runs it
def test_mask_rcnn_infer_shapes(fresh):
    cfg = mask_rcnn.MaskRCNNConfig.tiny()
    image = fluid.data("image", [1, 3, 64, 64])
    im_info = fluid.data("im_info", [1, 3])
    out, mlogits = mask_rcnn.mask_rcnn_infer(image, im_info, cfg)
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    rng = np.random.RandomState(0)
    dets, masks = exe.run(
        feed={"image": rng.rand(1, 3, 64, 64).astype(np.float32),
              "im_info": np.array([[64, 64, 1.0]], np.float32)},
        fetch_list=[out, mlogits],
    )
    dets = np.asarray(dets)
    masks = np.asarray(masks)
    assert dets.ndim >= 2 and dets.shape[-1] == 6
    assert masks.shape[1] == cfg.class_num
