"""Per-op cost attribution (paddle_tpu/analysis/cost.py): closed-form
goldens for the core op families, `Program.estimate()` against XLA's own
cost_analysis, the executor's live perf.* telemetry, and the
tools/perf_report.py multi-rank timeline merge."""

import importlib.util
import json
import os
import types

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import layers, observability
from paddle_tpu.analysis import estimate_program, family_of, op_cost
from paddle_tpu.analysis.cost import (
    DEFAULT_PEAK_GBPS,
    DEFAULT_PEAK_TFLOPS,
    peak_flops,
)
from paddle_tpu.errors import CostAnalysisUnavailableWarning
from paddle_tpu.framework import unique_name
from paddle_tpu.framework.registry import OpView
from paddle_tpu.framework.scope import Scope

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_tool(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(_ROOT, "tools", f"{name}.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(autouse=True)
def fresh():
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 7
    scope = Scope()
    with fluid.program_guard(main, startup), fluid.scope_guard(scope), \
            unique_name.guard():
        yield main, startup, scope


def _f32(shape):
    return (tuple(shape), 4)


# ---------------------------------------------------------------------------
# per-op goldens (op_cost on synthetic specs)
# ---------------------------------------------------------------------------


class TestOpGoldens:
    def test_matmul(self):
        op = OpView("mul", {"x_num_col_dims": 1})
        flops, nbytes = op_cost(
            op,
            {"X": [_f32((8, 16))], "Y": [_f32((16, 32))]},
            {"Out": [_f32((8, 32))]},
        )
        assert flops == 2 * 8 * 32 * 16
        assert nbytes == (8 * 16 + 16 * 32 + 8 * 32) * 4

    def test_matmul_transpose_x(self):
        # x [K, M] with transpose_X: contraction length is x's second-last
        op = OpView("matmul", {"transpose_X": True})
        flops, _ = op_cost(
            op,
            {"X": [_f32((16, 8))], "Y": [_f32((16, 32))]},
            {"Out": [_f32((8, 32))]},
        )
        assert flops == 2 * 8 * 32 * 16

    def test_conv_no_padding(self):
        # 8x8 VALID 3x3: every output tap lands on real input
        op = OpView("conv2d", {"paddings": [0, 0], "strides": [1, 1]})
        flops, _ = op_cost(
            op,
            {"Input": [_f32((2, 3, 8, 8))], "Filter": [_f32((4, 3, 3, 3))]},
            {"Output": [_f32((2, 4, 6, 6))]},
        )
        assert flops == 2 * (2 * 4 * 6 * 6) * (3 * 3 * 3)

    def test_conv_padding_discounts_dead_taps(self):
        # SAME 3x3 on 4x4: border taps land in padding and must not count
        full = 2 * (2 * 4 * 4 * 4) * (3 * 3 * 3)
        op = OpView("conv2d", {"paddings": [1, 1], "strides": [1, 1]})
        flops, _ = op_cost(
            op,
            {"Input": [_f32((2, 3, 4, 4))], "Filter": [_f32((4, 3, 3, 3))]},
            {"Output": [_f32((2, 4, 4, 4))]},
        )
        assert 0 < flops < full
        # separable taps: per dim 3*4 - 2 dead columns = 10 of 12
        assert flops == pytest.approx(full * (10 / 12) ** 2)

    def test_attention_fwd_and_grad(self):
        qkv = {"QKV": [_f32((2, 16, 3 * 32))]}
        fwd, _ = op_cost(OpView("fused_qkv_attention", {}), qkv, {})
        assert fwd == 4.0 * 2 * 16 * 16 * 32
        causal, _ = op_cost(
            OpView("fused_qkv_attention", {"causal": True}), qkv, {}
        )
        assert causal == fwd / 2
        bwd, _ = op_cost(OpView("fused_qkv_attention_grad", {}), qkv, {})
        assert bwd == 2.5 * fwd

    def test_elementwise_weights(self):
        flops, _ = op_cost(OpView("relu", {}), {}, {"Out": [_f32((4, 4))]})
        assert flops == 16
        flops, _ = op_cost(OpView("gelu", {}), {}, {"Out": [_f32((4, 4))]})
        assert flops == 8 * 16

    def test_data_movement_zero_flops(self):
        flops, nbytes = op_cost(
            OpView("reshape2", {}),
            {"X": [_f32((4, 4))]}, {"Out": [_f32((16,))]},
        )
        assert flops == 0.0
        assert nbytes == 2 * 16 * 4

    def test_reduce_is_one_pass_over_input(self):
        flops, _ = op_cost(
            OpView("reduce_sum", {}),
            {"X": [_f32((8, 32))]}, {"Out": [_f32((8,))]},
        )
        assert flops == 8 * 32

    def test_optimizer_per_param_weight(self):
        flops, _ = op_cost(
            OpView("adam", {}), {"Param": [_f32((100,))]}, {}
        )
        assert flops == 12.0 * 100

    def test_collective_ring_payload(self):
        specs = {"X": [_f32((1024,))]}
        op = OpView("c_allreduce_sum", {"axis_name": "dp"})
        flops, wire = op_cost(op, specs, {}, axis_sizes={"dp": 4})
        assert wire == pytest.approx(1024 * 4 * 2 * 3 / 4)
        assert flops == 1024
        # unbound axis degrades to identity: no wire traffic, no flops
        assert op_cost(op, specs, {}, axis_sizes={}) == (0.0, 0.0)
        _, ag = op_cost(
            OpView("c_allgather", {"axis_name": "dp"}), specs, {},
            axis_sizes={"dp": 4},
        )
        assert ag == pytest.approx(1024 * 4 * 3 / 4)

    def test_gather_moves_rows_not_the_table(self):
        # lookup over a 1M x 8 table: only the gathered rows (~output
        # sized) count as bytes moved, never the whole table
        table = _f32((1_000_000, 8))
        ids = ((64, 1), 8)  # int64 ids
        out = _f32((64, 8))
        flops, nbytes = op_cost(
            OpView("lookup_table_v2", {}),
            {"W": [table], "Ids": [ids]}, {"Out": [out]},
        )
        assert flops == 0.0
        assert nbytes == (
            64 * 8  # ids
            + 2 * 64 * 8 * 4  # rows read from the table + output written
        )
        # non-table data movement is unchanged
        _, plain = op_cost(
            OpView("concat", {}), {"X": [_f32((4, 4))]},
            {"Out": [_f32((4, 4))]},
        )
        assert plain == 2 * 16 * 4

    def test_zero_sharded_collective_wire_bytes(self):
        """Sharded weight-update collectives: wire bytes follow the PADDED
        flat payload at the quantized element size, with reduce-scatter
        and all-gather each moving (n-1)/n of it."""
        n, pad, block = 4, 4096, 256
        grad = _f32((60, 64))  # 3840 elements, padded to 4096
        rs = OpView("zero_reduce_scatter", {
            "axis_name": "dp", "pad_len": pad, "quant": "none",
            "quant_block": block, "scale": 0.25,
        })
        flops, wire = op_cost(rs, {"X": [grad]}, {}, axis_sizes={"dp": n})
        assert wire == pytest.approx(pad * 4 * (n - 1) / n)
        assert flops == pad  # n contributions summed per element
        # int8 blocks: 1 byte/elem + 4-byte fp32 scale per block
        rs_q = OpView("zero_reduce_scatter", {
            "axis_name": "dp", "pad_len": pad, "quant": "int8",
            "quant_block": block,
        })
        _, wire_q = op_cost(rs_q, {"X": [grad]}, {}, axis_sizes={"dp": n})
        assert wire_q == pytest.approx(
            pad * (1 + 4 / block) * (n - 1) / n
        )
        assert wire_q < 0.3 * wire  # the >=40% payload-reduction headline
        ag = OpView("zero_all_gather", {
            "axis_name": "dp", "pad_len": pad, "quant": "none",
            "shape": [60, 64],
        })
        shard = _f32((pad,))
        ag_flops, ag_wire = op_cost(
            ag, {"X": [shard]}, {}, axis_sizes={"dp": n}
        )
        assert ag_flops == 0.0
        assert ag_wire == pytest.approx(pad * 4 * (n - 1) / n)
        # unbound axis: identity degrade, no wire traffic
        assert op_cost(rs, {"X": [grad]}, {}, axis_sizes={}) == (0.0, 0.0)
        # found-inf any-reduce is a [1]-element allreduce
        anyop = OpView("c_allreduce_any", {"axis_name": "dp"})
        _, any_wire = op_cost(
            anyop, {"X": [((1,), 1)]}, {}, axis_sizes={"dp": n}
        )
        assert any_wire == pytest.approx(1 * 2 * (n - 1) / n)

    def test_zero_collectives_in_program_estimate(self, fresh):
        """A ShardedWeightUpdate-transpiled program's estimate carries the
        new collective sites with quantized wire bytes smaller than the
        fp32 build's."""
        import jax

        from paddle_tpu.parallel import make_mesh, shard_program
        from paddle_tpu.parallel.transpiler import ShardedWeightUpdate

        def build(quant):
            main, startup = fluid.Program(), fluid.Program()
            scope = Scope()
            with fluid.program_guard(main, startup), \
                    fluid.scope_guard(scope), unique_name.guard():
                # a 512x64 weight: big enough that int8 padding overhead
                # cannot mask the 4x element shrink
                x = fluid.data("x", [8, 512])
                loss = layers.mean(layers.square(layers.fc(x, 64)))
                _, pg = fluid.optimizer.Adam(0.01).minimize(loss, startup)
                ShardedWeightUpdate(2, quant=quant).transpile(
                    main, startup, pg
                )
                shard_program(
                    main, make_mesh({"dp": 2}, jax.devices()[:2]),
                    {"x": ("dp",)},
                )
            return main.estimate(feed_shapes={"x": (8, 512)})

        est_fp = build(None)
        est_q = build("int8")
        kinds_fp = {e.op_type for e in est_fp.ops}
        assert {"zero_reduce_scatter", "zero_all_gather"} <= kinds_fp

        def coll_bytes(est):
            return sum(
                e.bytes for e in est.ops
                if e.op_type in ("zero_reduce_scatter", "zero_all_gather")
            )

        assert coll_bytes(est_q) < 0.6 * coll_bytes(est_fp)

    def test_family_of(self):
        assert family_of("matmul") == "matmul"
        assert family_of("conv2d") == "conv"
        assert family_of("ring_attention") == "attention"
        assert family_of("layer_norm") == "normalization"
        assert family_of("lookup_table_v2") == "embedding"
        assert family_of("adam") == "optimizer"
        assert family_of("c_allreduce_sum") == "collective"
        assert family_of("reshape2") == "data_movement"
        assert family_of("relu") == "elementwise"

    def test_recorded_grad_family_strips_suffix(self):
        """_record resolves the family from the FORWARD op type for every
        synthesized *_grad entry — incl. bases like ring_attention whose
        _grad form is not itself a registered attention op."""
        from paddle_tpu.analysis.cost import CostTable, _Estimator

        table = CostTable(peak_flops=1e12, peak_bandwidth=1e11)
        est = _Estimator.__new__(_Estimator)
        est.table = table
        for t, fam in (("ring_attention_grad", "attention"),
                       ("conv2d_grad", "conv"),
                       ("layer_norm_grad", "normalization")):
            est._record(None, t, 1.0, 1.0, 1, 0, 0, loc="")
            assert table.ops[-1].family == fam, t

    def test_fused_lookup_unique_row_gather_bytes(self):
        """fused_lookup_table forward: ids + outputs + the UNIQUE-row
        gather — bounded by min(total ids, total table rows), never the
        whole table, never one row per occurrence."""
        v, d, b = 1_000_000, 8, 64
        tables = [_f32((v, d))] * 4
        ids = [((b, 1), 8)] * 4  # 4 slots of int64 [64, 1] ids
        outs = [_f32((b, d))] * 4
        op = OpView("fused_lookup_table", {"axis_name": "ps"})
        flops, nbytes = op_cost(
            op, {"Ids": ids, "W": tables}, {"Out": outs}
        )
        assert flops == 0.0
        total_ids = 4 * b
        assert nbytes == (
            total_ids * 8          # ids read
            + total_ids * d * 4    # outputs written
            + total_ids * d * 4    # unique-row gather (<= total ids rows)
        )
        # a table smaller than the batch bounds the gather by its rows
        tiny = [_f32((16, d))]
        _, small = op_cost(
            OpView("fused_lookup_table", {}),
            {"Ids": [((b,), 8)], "W": tiny}, {"Out": [_f32((b, d))]},
        )
        assert small == b * 8 + b * d * 4 + 16 * d * 4
        # dedup=False: the legacy per-occurrence gather (output-sized)
        _, nodedup = op_cost(
            OpView("fused_lookup_table", {"dedup": False}),
            {"Ids": [((b,), 8)], "W": tiny}, {"Out": [_f32((b, d))]},
        )
        assert nodedup == b * 8 + 2 * b * d * 4

    def test_fused_lookup_sharded_exchange_wire(self):
        """Row partition adds the psum row-assembly wire; the backward
        segment-sum (via __vjp__) adds the grad exchange at the quantized
        element size when int8 is opted in."""
        from paddle_tpu.analysis.cost import _lookup_grad_cost

        v, d, b, n = 4096, 16, 32, 8
        ins = {"Ids": [((b,), 8)], "W": [_f32((v, d))]}
        outs = {"Out": [_f32((b, d))]}
        base_op = OpView("fused_lookup_table", {"axis_name": "ps"})
        _, local = op_cost(base_op, ins, outs, axis_sizes={})
        _, sharded = op_cost(base_op, ins, outs, axis_sizes={"ps": n})
        assert sharded - local == pytest.approx(
            b * d * 4 * 2 * (n - 1) / n
        )
        # backward: fp32 grad exchange vs int8 block-quantized wire
        g_flops, g_fp32 = _lookup_grad_cost(
            base_op, ins, outs, {"ps": n}
        )
        assert g_flops >= b * d  # segment-sum adds + shard accumulation
        q_op = OpView("fused_lookup_table", {
            "axis_name": "ps", "quant": "int8", "quant_block": 256,
        })
        _, g_int8 = _lookup_grad_cost(q_op, ins, outs, {"ps": n})
        fixed = 2 * b * d * 4 + b * d * 4  # segment-sum local traffic
        assert (g_int8 - fixed) < 0.3 * (g_fp32 - fixed)
        # col partition: all-gather forward, no quantized grad exchange
        col_op = OpView("fused_lookup_table", {
            "axis_name": "ps", "partition": "col",
        })
        _, col = op_cost(col_op, ins, outs, axis_sizes={"ps": n})
        assert col - local == pytest.approx(b * d * 4 * (n - 1) / n)

    def test_fused_lookup_family_is_embedding(self):
        assert family_of("fused_lookup_table") == "embedding"
        assert family_of("distributed_lookup_table") == "embedding"


# ---------------------------------------------------------------------------
# Program.estimate()
# ---------------------------------------------------------------------------


def _fc_train(main, startup):
    with fluid.program_guard(main, startup):
        x = fluid.data("x", [-1, 16])
        h = layers.fc(x, 32, act="relu")
        loss = layers.mean(h)
        fluid.optimizer.SGD(0.1).minimize(loss, startup)
    return loss


class TestProgramEstimate:
    def test_feed_shapes_pin_batch(self, fresh):
        main, startup, _ = fresh
        _fc_train(main, startup)
        est8 = main.estimate(feed_shapes={"x": (8, 16)})
        est16 = main.estimate(feed_shapes={"x": (16, 16)})
        assert est16.total_flops > est8.total_flops
        # every -1 pin is recorded, never silent
        assert any("batch hint 8" in a for a in est8.assumptions)
        # no feed: batch hint falls back to 1
        assert any("batch hint 1" in a for a in main.estimate().assumptions)

    def test_cond_branch_costed_and_pins_surfaced(self, fresh):
        main, startup, _ = fresh
        with fluid.program_guard(main, startup):
            x = fluid.data("x", [-1, 16])
            p = fluid.data("p", [1], "float32")
            pred = layers.greater_than(
                p, layers.fill_constant([1], "float32", 0.0)
            )
            layers.cond(pred, lambda: layers.fc(x, 32),
                        lambda: layers.fc(x, 32))
        est = main.estimate()
        # the charged branch's ops land in the table...
        assert any(e.op_type == "mul" for e in est.ops)
        # ...and -1 pins made INSIDE the branch are still recorded
        assert any("pinned" in a for a in est.assumptions)

    def test_grad_ops_attributed_to_forward_family(self, fresh):
        main, startup, _ = fresh
        _fc_train(main, startup)
        est = main.estimate(feed_shapes={"x": (8, 16)})
        types = {e.op_type for e in est.ops}
        assert {"mul", "mul_grad", "relu_grad", "sgd"} <= types
        grad = next(e for e in est.ops if e.op_type == "mul_grad")
        fwd = next(e for e in est.ops if e.op_type == "mul")
        # first-layer mul: x is a feed, so only dW is wanted — one
        # forward-sized contraction, not two
        assert grad.flops == fwd.flops
        assert grad.family == "matmul"
        fams = est.by_family()
        assert fams["matmul"]["flops"] == pytest.approx(2 * fwd.flops)

    def test_table_views_and_serialization(self, fresh):
        main, startup, _ = fresh
        _fc_train(main, startup)
        est = main.estimate(feed_shapes={"x": (8, 16)})
        top = est.top(3)
        assert len(top) == 3
        assert top[0].latency == max(e.latency for e in est.ops)
        d = est.to_dict(top=5)
        assert d["total_flops"] == est.total_flops
        assert len(d["ops"]) == 5
        json.dumps(d)  # must be a plain-JSON artifact (set_table contract)
        text = est.format(top=2)
        assert "by family" in text and "top 2 op sites" in text
        assert est.mfu_at(1.0) == pytest.approx(
            est.total_flops / est.peak_flops
        )
        assert est.mfu_at(0.0) == 0.0

    def test_peak_env_overrides(self, fresh, monkeypatch):
        main, startup, _ = fresh
        _fc_train(main, startup)
        monkeypatch.setenv("PADDLE_TPU_PEAK_TFLOPS", "100")
        monkeypatch.setenv("PADDLE_TPU_PEAK_GBPS", "500")
        est = main.estimate(feed_shapes={"x": (8, 16)})
        assert est.peak_flops == 100e12
        assert est.peak_bandwidth == 500e9
        monkeypatch.setenv("PADDLE_TPU_PEAK_TFLOPS", "not-a-number")
        assert peak_flops() == DEFAULT_PEAK_TFLOPS * 1e12
        # explicit args beat the env
        est = main.estimate(feed_shapes={"x": (8, 16)}, peak_tflops=1.0,
                            peak_gbps=DEFAULT_PEAK_GBPS)
        assert est.peak_flops == 1e12

    def test_bounded_while_counts_static_trips(self, fresh):
        main, startup, _ = fresh
        with fluid.program_guard(main, startup):
            x = fluid.data("x", [4, 8])
            i = layers.fill_constant([1], "int32", 0)
            n = layers.fill_constant([1], "int32", 5)
            acc = layers.fill_constant([4, 8], "float32", 0.0)
            cond = layers.less_than(i, n)
            w = layers.While(cond, max_iters=5)
            with w.block():
                layers.assign(layers.elementwise_add(acc, x), acc)
                layers.increment(i)
                layers.assign(layers.less_than(i, n), cond)
        est = main.estimate()
        adds = [e for e in est.ops if e.op_type == "elementwise_add"]
        # the body's add is charged once per static trip (max_iters),
        # not once total, and no trip-count assumption is emitted
        assert adds and all(e.count == 5 for e in adds)
        assert not any("counted once" in a for a in est.assumptions)

    def test_estimate_matches_xla_on_small_program(self, fresh):
        main, startup, scope = fresh
        loss = _fc_train(main, startup)
        exe = fluid.Executor()
        exe.run(startup, scope=scope)
        feed = {"x": np.ones((8, 16), "float32")}
        exe.run(main, feed=feed, fetch_list=[loss.name], scope=scope)
        xla = exe.flops(
            main, feed=feed, fetch_list=[loss.name], scope=scope
        )
        est = main.estimate(feed_shapes={"x": (8, 16)})
        assert xla > 0
        assert abs(est.total_flops - xla) / xla < 0.25


@pytest.mark.slow
@pytest.mark.parametrize(
    "name",
    sorted(__import__("paddle_tpu.models",
                      fromlist=["MODEL_BUILDERS"]).MODEL_BUILDERS),
)
def test_zoo_estimate_vs_xla(name):
    """`Program.estimate()` within 25% of XLA cost_analysis for every
    bundled model (meshed models are estimate-only: their shard_map
    executable wants the whole virtual pod). Mirrors the ci.sh
    perf_report stage so a regression fails in pytest too."""
    from paddle_tpu.models import build_model

    perf_report = _load_tool("perf_report")

    bm = build_model(name)
    feed = perf_report._synthetic_feed(bm)
    est = bm.main.estimate(
        feed_shapes={k: v.shape for k, v in feed.items()}
    )
    assert est.total_flops > 0
    assert est.ops
    if getattr(bm.main, "_mesh", None) is not None:
        return
    exe = fluid.Executor()
    scope = Scope()
    exe.run(bm.startup, scope=scope)
    xla = exe.flops(
        bm.main, feed=feed, fetch_list=list(bm.fetch_names), scope=scope
    )
    if not xla:
        pytest.skip("XLA cost_analysis reported no FLOP data")
    assert abs(est.total_flops - xla) / xla <= 0.25


# ---------------------------------------------------------------------------
# live perf.* telemetry
# ---------------------------------------------------------------------------


class TestPerfTelemetry:
    def test_executor_publishes_perf_metrics(self, fresh):
        main, startup, scope = fresh
        loss = _fc_train(main, startup)
        exe = fluid.Executor()
        exe.run(startup, scope=scope)
        observability.reset()  # drop the startup program's own estimate
        feed = {"x": np.ones((8, 16), "float32")}
        for _ in range(3):
            exe.run(main, feed=feed, fetch_list=[loss.name], scope=scope)
        snap = observability.snapshot()
        est = main.estimate(feed_shapes={"x": (8, 16)})
        # counters tick every run, compile-carrying or not
        assert snap["counters"]["perf.step_flops"] == 3 * int(
            est.total_flops
        )
        assert snap["counters"]["perf.step_bytes"] == 3 * int(
            est.total_bytes
        )
        gauges = snap["gauges"]
        # the MFU gauge is exactly est-flops over the steady-state mean
        # step, against the configured peak
        assert gauges["perf.mfu"] == pytest.approx(
            est.total_flops / gauges["perf.step_seconds"] / est.peak_flops
        )
        for fam in est.by_family():
            assert f"perf.family_time.{fam}" in gauges
        table = snap["tables"]["perf.cost_table"]
        assert table["total_flops"] == pytest.approx(est.total_flops)
        assert table["ops"]

    def test_mfu_gauge_excludes_compile_runs(self, fresh):
        main, startup, scope = fresh
        loss = _fc_train(main, startup)
        exe = fluid.Executor()
        exe.run(startup, scope=scope)
        feed = {"x": np.ones((8, 16), "float32")}
        observability.reset()
        exe.run(main, feed=feed, fetch_list=[loss.name], scope=scope)
        snap = observability.snapshot()
        # first run carries the compile: counters tick, no MFU yet
        assert "perf.step_flops" in snap["counters"]
        assert "perf.mfu" not in snap["gauges"]
        exe.run(main, feed=feed, fetch_list=[loss.name], scope=scope)
        assert "perf.mfu" in observability.snapshot()["gauges"]

    def test_tables_reset_and_snapshot_backcompat(self):
        observability.reset()
        assert "tables" not in observability.snapshot()  # nothing published
        observability.set_table("perf.cost_table", {"total_flops": 1.0})
        assert observability.get_tables() == {
            "perf.cost_table": {"total_flops": 1.0}
        }
        observability.reset()
        assert observability.get_tables() == {}

    def test_cost_analysis_unavailable_is_loud(self, fresh):
        main, startup, scope = fresh
        loss = _fc_train(main, startup)
        exe = fluid.Executor()
        exe.run(startup, scope=scope)
        feed = {"x": np.ones((8, 16), "float32")}
        exe.run(main, feed=feed, fetch_list=[loss.name], scope=scope)
        # the cache holds startup's executable too; main's was used last
        compiled = list(exe._cache.values())[-1]

        class _NoCost:
            def compile(self):
                return self

            def cost_analysis(self):
                return None

        compiled.fn = types.SimpleNamespace(
            lower=lambda *a, **k: _NoCost()
        )
        observability.reset()
        with pytest.warns(CostAnalysisUnavailableWarning):
            val = exe.flops(
                main, feed=feed, fetch_list=[loss.name], scope=scope
            )
        assert val == 0.0
        snap = observability.snapshot()
        assert snap["counters"]["perf.cost_analysis_unavailable"] == 1


# ---------------------------------------------------------------------------
# multi-rank timeline merge (tools/perf_report.py)
# ---------------------------------------------------------------------------


def _rank_trace(steps):
    """Synthetic chrome trace: one executor.step X event per (ts, dur)."""
    events = [{
        "name": "thread_name", "ph": "M", "tid": 0, "pid": 0,
        "args": {"name": "thread-0"},
    }]
    for ts, dur in steps:
        events.append({
            "name": "executor.step", "ph": "X", "cat": "host",
            "ts": ts, "dur": dur, "tid": 0, "pid": 0, "args": {},
        })
    return {"traceEvents": events}


class TestTimelineMerge:
    def test_two_rank_merge_skew_and_straggler(self, tmp_path):
        perf_report = _load_tool("perf_report")
        # rank 0 ends steps at 1500/3500 us; rank 1 at 1700/3900:
        # skews 200 and 400 -> mean 300, max 400, straggler rank 1
        p0 = tmp_path / "trace_rank0.json"
        p1 = tmp_path / "trace_rank1.json"
        p0.write_text(json.dumps(_rank_trace([(1000, 500), (3000, 500)])))
        p1.write_text(json.dumps(_rank_trace([(1100, 600), (3200, 700)])))
        trace, stats = perf_report.merge_traces([str(p0), str(p1)])
        assert {e.get("pid") for e in trace["traceEvents"]} == {0, 1}
        steps = [
            e for e in trace["traceEvents"]
            if e.get("ph") == "X" and e["name"] == "executor.step"
        ]
        assert len(steps) == 4
        assert stats["ranks"] == [0, 1]
        assert stats["aligned_steps"] == 2
        assert stats["step_skew_us"]["mean"] == pytest.approx(300.0)
        assert stats["step_skew_us"]["max"] == pytest.approx(400.0)
        assert stats["straggler_gap_us"] == pytest.approx(300.0)
        assert stats["straggler_rank"] == 1
        assert stats["straggler_last_finishes"] == {1: 2}

    def test_straggler_gap_isolates_last_finisher(self, tmp_path):
        perf_report = _load_tool("perf_report")
        # ranks 0/1 finish 5 us apart; rank 2 trails by a full 1000 us:
        # skew = 1005 (first vs last) but the straggler GAP — the stall
        # rank 2 alone causes — is last vs second-to-last = 1000
        paths = []
        for r, steps in enumerate(
            [[(1000, 500)], [(1000, 505)], [(1000, 1505)]]
        ):
            p = tmp_path / f"trace_rank{r}.json"
            p.write_text(json.dumps(_rank_trace(steps)))
            paths.append(str(p))
        _, stats = perf_report.merge_traces(paths)
        assert stats["step_skew_us"]["mean"] == pytest.approx(1005.0)
        assert stats["straggler_gap_us"] == pytest.approx(1000.0)
        assert stats["straggler_rank"] == 2

    def test_count_mismatch_aligns_trailing_steps(self, tmp_path):
        perf_report = _load_tool("perf_report")
        # rank 0 kept 3 steps; rank 1's ring buffer dropped the oldest and
        # kept 2. Trailing alignment pairs r0's LAST two steps with r1's
        # (ends 1100/2100 vs 1150/2150 -> skew 50), where leading-index
        # pairing would compare unrelated steps (skew 1050); the mismatch
        # is still flagged.
        p0 = tmp_path / "trace_rank0.json"
        p1 = tmp_path / "trace_rank1.json"
        p0.write_text(json.dumps(
            _rank_trace([(0, 100), (1000, 100), (2000, 100)])
        ))
        p1.write_text(json.dumps(_rank_trace([(1000, 150), (2000, 150)])))
        _, stats = perf_report.merge_traces([str(p0), str(p1)])
        assert stats["count_mismatch"] is True
        assert stats["aligned_steps"] == 2
        assert stats["step_skew_us"]["mean"] == pytest.approx(50.0)
        assert stats["straggler_rank"] == 1

    def test_rank_from_filename_else_position(self, tmp_path):
        perf_report = _load_tool("perf_report")
        a = tmp_path / "leg_a.json"
        b = tmp_path / "rank3.json"
        a.write_text(json.dumps(_rank_trace([(0, 10)])))
        b.write_text(json.dumps(_rank_trace([(0, 20)])))
        trace, stats = perf_report.merge_traces([str(a), str(b)])
        # a has no rank in its name -> positional 0; b -> parsed 3
        assert stats["ranks"] == [0, 3]

    def test_heartbeats_fold_in_as_instants(self, tmp_path):
        perf_report = _load_tool("perf_report")
        p0 = tmp_path / "trace_rank0.json"
        p0.write_text(json.dumps(_rank_trace([(1000, 500)])))
        hb = tmp_path / "hb"
        hb.mkdir()
        (hb / "hb_rank0").write_text(
            json.dumps({"rank": 0, "step": 1, "time": 0.0015})
        )
        (hb / "hb_rank1.tmp.123").write_text("{torn")  # must be ignored
        trace, _ = perf_report.merge_traces(
            [str(p0)], heartbeat_dir=str(hb)
        )
        beats = [
            e for e in trace["traceEvents"] if e.get("cat") == "health"
        ]
        assert len(beats) == 1
        assert beats[0]["ph"] == "I" and beats[0]["pid"] == 0
        assert beats[0]["ts"] == pytest.approx(1500.0)

    def test_merged_trace_loads_like_chrome_trace(self, tmp_path):
        # end to end with REAL span exports: step a program on two fake
        # ranks, export, merge, and require a well-formed trace JSON
        perf_report = _load_tool("perf_report")
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = fluid.data("x", [4, 8])
            loss = layers.mean(layers.fc(x, 8))
        exe = fluid.Executor()
        exe.run(startup)
        paths = []
        for rank in (0, 1):
            observability.reset()
            for _ in range(2):
                exe.run(main, feed={"x": np.ones((4, 8), "float32")},
                        fetch_list=[loss.name])
            p = tmp_path / f"trace_rank{rank}.json"
            observability.spans.save_chrome_trace(str(p))
            paths.append(str(p))
        trace, stats = perf_report.merge_traces(paths)
        assert stats["aligned_steps"] == 2
        assert stats["steps_per_rank"] == {0: 2, 1: 2}
        out = tmp_path / "pod.json"
        out.write_text(json.dumps(trace))
        reloaded = json.loads(out.read_text())
        assert {e.get("pid") for e in reloaded["traceEvents"]} == {0, 1}


# ---------------------------------------------------------------------------
# stats_report rendering of the published cost table
# ---------------------------------------------------------------------------


def test_stats_report_top_ops_and_require(tmp_path, fresh):
    main, startup, scope = fresh
    loss = _fc_train(main, startup)
    exe = fluid.Executor()
    exe.run(startup, scope=scope)
    feed = {"x": np.ones((8, 16), "float32")}
    for _ in range(2):
        exe.run(main, feed=feed, fetch_list=[loss.name], scope=scope)
    snap_path = tmp_path / "snap.json"
    observability.dump(str(snap_path))
    stats_report = _load_tool("stats_report")
    out = stats_report.render(
        json.load(open(snap_path)), top_ops=3
    )
    assert "perf.cost_table" in out
    assert "top 3 op sites" in out
    # --require perf. is satisfied by the table name alone
    assert stats_report.main([str(snap_path), "--require", "perf."]) in (
        0, None,
    )
