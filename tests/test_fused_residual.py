"""fused_dropout_add_ln: kernel logic (Mosaic interpreter) vs jnp
reference, and end-to-end equivalence of the fused vs composed BERT
residual tail in the static graph (dropout=0 so the two formulations are
bit-comparable; dropout>0 mask streams differ by design)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as fluid
from paddle_tpu.framework.scope import Scope
from paddle_tpu.kernels import fused_residual as frk


def test_kernel_matches_reference_interpret():
    rng = np.random.RandomState(0)
    x = rng.randn(64, 256).astype(np.float32)
    y = rng.randn(64, 256).astype(np.float32)
    g = rng.rand(256).astype(np.float32) + 0.5
    c = rng.randn(256).astype(np.float32)
    seed = jnp.zeros(2, jnp.uint32)
    st = dict(rate=0.0, is_test=True, upscale=False, eps=1e-5)
    out = frk.fused_dropout_add_ln(
        jnp.asarray(x), jnp.asarray(y), jnp.asarray(g), jnp.asarray(c),
        seed, tuple(st.items()), True,
    )
    ref = frk.reference_fwd(
        jnp.asarray(x), jnp.asarray(y), jnp.asarray(g), jnp.asarray(c),
        None, **st,
    )
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=1e-5, atol=1e-5
    )


def test_kernel_test_mode_dropout_scaling_interpret():
    # downgrade_in_infer at is_test: y scaled by (1-p) before the add
    rng = np.random.RandomState(1)
    x = rng.randn(32, 128).astype(np.float32)
    y = rng.randn(32, 128).astype(np.float32)
    seed = jnp.zeros(2, jnp.uint32)
    st = dict(rate=0.4, is_test=True, upscale=False, eps=1e-5)
    out = frk.fused_dropout_add_ln(
        jnp.asarray(x), jnp.asarray(y), jnp.ones(128, jnp.float32),
        jnp.zeros(128, jnp.float32), seed, tuple(st.items()), True,
    )
    ref = frk.reference_fwd(
        jnp.asarray(x), jnp.asarray(y), None, None, None, **st
    )
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=1e-5, atol=1e-5
    )


def test_bwd_kernel_matches_reference_grads_interpret():
    rng = np.random.RandomState(2)
    x = jnp.asarray(rng.randn(32, 128).astype(np.float32))
    y = jnp.asarray(rng.randn(32, 128).astype(np.float32))
    g = jnp.asarray(rng.rand(128).astype(np.float32) + 0.5)
    c = jnp.asarray(rng.randn(128).astype(np.float32))
    do = jnp.asarray(rng.randn(32, 128).astype(np.float32))
    seed = jnp.zeros(2, jnp.uint32)
    dx, dy, dg, dc = frk.fused_dropout_add_ln_bwd(
        x, y, g, seed, do, 0.0, True, False, 1e-5, True
    )

    def f(x_, y_, g_, c_):
        return frk.reference_fwd(x_, y_, g_, c_, None, rate=0.0,
                                 is_test=True, upscale=False, eps=1e-5)

    _, vjp = jax.vjp(f, x, y, g, c)
    rdx, rdy, rdg, rdc = vjp(do)
    np.testing.assert_allclose(np.asarray(dx), np.asarray(rdx),
                               rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(np.asarray(dy), np.asarray(rdy),
                               rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(np.asarray(dg), np.asarray(rdg),
                               rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(np.asarray(dc), np.asarray(rdc),
                               rtol=1e-4, atol=1e-4)


@pytest.mark.parametrize("dropout", [0.0])
def test_bert_fused_vs_composed_residual(dropout):
    """Same tiny BERT trained 3 steps with the fused residual tail vs the
    composed ops: identical losses (shared seed, dropout=0)."""
    from paddle_tpu.models import BertConfig, bert_pretrain
    from paddle_tpu.optimizer import SGD

    losses = {}
    for fused in (True, False):
        cfg = BertConfig.tiny()
        cfg.hidden_dropout = dropout
        cfg.attention_dropout = 0.0
        cfg.use_fused_residual = fused
        b, s = 2, 64
        main, startup = fluid.Program(), fluid.Program()
        main.random_seed = startup.random_seed = 11
        with fluid.program_guard(main, startup):
            ids = fluid.data("ids", [b, s], "int64")
            types = fluid.data("types", [b, s], "int64")
            mask = fluid.data("mask", [b, s], "float32")
            labels = fluid.data("labels", [b, s], "int64")
            loss = bert_pretrain(ids, types, mask, labels, cfg)
            SGD(0.01).minimize(loss, startup)
        scope = Scope()
        exe = fluid.Executor()
        exe.run(startup, scope=scope)
        rng = np.random.RandomState(3)
        feed = {
            "ids": rng.randint(0, cfg.vocab_size, (b, s)).astype("int32"),
            "types": rng.randint(0, 2, (b, s)).astype("int32"),
            "mask": np.ones((b, s), "float32"),
            "labels": rng.randint(0, cfg.vocab_size, (b, s)).astype("int32"),
        }
        run = []
        for _ in range(3):
            (lv,) = exe.run(main, feed=feed, fetch_list=[loss], scope=scope)
            run.append(float(np.asarray(lv).reshape(-1)[0]))
        losses[fused] = run
        assert run[-1] < run[0], f"loss must drop (fused={fused}): {run}"
    np.testing.assert_allclose(losses[True], losses[False], rtol=2e-5)


def test_bert_fused_residual_train_mode_dropout_runs():
    """dropout>0 training through the fused op (reference path on CPU):
    finite decreasing loss and deterministic across rebuilds."""
    from paddle_tpu.models import BertConfig, bert_pretrain
    from paddle_tpu.optimizer import SGD

    def run_once():
        cfg = BertConfig.tiny()
        cfg.use_fused_residual = True
        b, s = 2, 64
        main, startup = fluid.Program(), fluid.Program()
        main.random_seed = startup.random_seed = 5
        with fluid.program_guard(main, startup):
            ids = fluid.data("ids", [b, s], "int64")
            types = fluid.data("types", [b, s], "int64")
            mask = fluid.data("mask", [b, s], "float32")
            labels = fluid.data("labels", [b, s], "int64")
            loss = bert_pretrain(ids, types, mask, labels, cfg)
            SGD(0.01).minimize(loss, startup)
        scope = Scope()
        exe = fluid.Executor()
        exe.run(startup, scope=scope)
        rng = np.random.RandomState(4)
        feed = {
            "ids": rng.randint(0, cfg.vocab_size, (b, s)).astype("int32"),
            "types": rng.randint(0, 2, (b, s)).astype("int32"),
            "mask": np.ones((b, s), "float32"),
            "labels": rng.randint(0, cfg.vocab_size, (b, s)).astype("int32"),
        }
        out = []
        for _ in range(4):
            (lv,) = exe.run(main, feed=feed, fetch_list=[loss], scope=scope)
            out.append(float(np.asarray(lv).reshape(-1)[0]))
        return out

    a = run_once()
    b = run_once()
    assert all(np.isfinite(a)) and a[-1] < a[0], a
    np.testing.assert_allclose(a, b, rtol=1e-6)


def test_infer_clone_consistency():
    """clone(for_test=True) flips the fused op to is_test semantics."""
    from paddle_tpu import layers

    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 9
    with fluid.program_guard(main, startup):
        x = fluid.data("x", [4, 128], "float32")
        y = fluid.data("y", [4, 128], "float32")
        out = layers.fused_dropout_add_ln(x, y, dropout_prob=0.5)
        loss = layers.reduce_mean(out)
    test_prog = main.clone(for_test=True)
    scope = Scope()
    exe = fluid.Executor()
    exe.run(startup, scope=scope)
    rng = np.random.RandomState(6)
    feed = {"x": rng.randn(4, 128).astype("float32"),
            "y": rng.randn(4, 128).astype("float32")}
    (a,) = exe.run(test_prog, feed=feed, fetch_list=[out], scope=scope)
    (b,) = exe.run(test_prog, feed=feed, fetch_list=[out], scope=scope)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
