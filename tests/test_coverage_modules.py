"""Coverage-widening modules: metrics, nets, DataFeeder, 2.0 namespaces,
dataset readers + decorators, distributions, CompiledProgram, inference
predictor.

Reference suites: test_metrics.py, test_nets.py, test_data_feeder.py,
test_dataset_*.py, test_distributions.py, test_compiled_program.py,
inference api tests.
"""

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import layers
from paddle_tpu.framework import unique_name


@pytest.fixture(autouse=True)
def fresh_programs():
    main, startup = fluid.Program(), fluid.Program()
    scope = fluid.framework.scope.Scope()
    with fluid.program_guard(main, startup), fluid.scope_guard(scope), \
            unique_name.guard():
        yield main, startup, scope


# -- metrics ---------------------------------------------------------------


def test_metrics_accuracy_precision_recall_auc():
    m = fluid.metrics.Accuracy()
    m.update(0.5, weight=10)
    m.update(1.0, weight=10)
    assert m.eval() == pytest.approx(0.75)

    p = fluid.metrics.Precision()
    r = fluid.metrics.Recall()
    preds = np.asarray([1, 1, 0, 1])
    labels = np.asarray([1, 0, 1, 1])
    p.update(preds, labels)
    r.update(preds, labels)
    assert p.eval() == pytest.approx(2 / 3)
    assert r.eval() == pytest.approx(2 / 3)

    auc = fluid.metrics.Auc()
    scores = np.asarray([0.1, 0.4, 0.35, 0.8])
    auc_labels = np.asarray([0, 0, 1, 1])
    auc.update(scores, auc_labels)
    # sklearn roc_auc_score for this case = 0.75
    assert auc.eval() == pytest.approx(0.75, abs=1e-3)

    comp = fluid.metrics.CompositeMetric()
    comp.add_metric(fluid.metrics.Precision())
    comp.add_metric(fluid.metrics.Recall())
    comp.update(preds, labels)
    assert comp.eval() == [pytest.approx(2 / 3), pytest.approx(2 / 3)]


# -- nets ------------------------------------------------------------------


def test_nets_build_and_run():
    img = fluid.data("img", [2, 3, 8, 8])
    conv_pool = fluid.nets.simple_img_conv_pool(
        img, num_filters=4, filter_size=3, pool_size=2, pool_stride=2,
        conv_padding=1, act="relu",
    )
    g = fluid.nets.glu(fluid.data("gx", [2, 6]), dim=-1)
    q = fluid.data("q", [2, 5, 8])
    att = fluid.nets.scaled_dot_product_attention(q, q, q, num_heads=2)
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    rng = np.random.RandomState(0)
    outs = exe.run(
        feed={
            "img": rng.randn(2, 3, 8, 8).astype(np.float32),
            "gx": rng.randn(2, 6).astype(np.float32),
            "q": rng.randn(2, 5, 8).astype(np.float32),
        },
        fetch_list=[conv_pool, g, att],
    )
    assert np.asarray(outs[0]).shape == (2, 4, 4, 4)
    assert np.asarray(outs[1]).shape == (2, 3)
    assert np.asarray(outs[2]).shape == (2, 5, 8)


# -- DataFeeder ------------------------------------------------------------


def test_data_feeder_casts_and_batches():
    x = fluid.data("x", [-1, 3], "float32")
    y = fluid.data("y", [-1, 1], "int64")
    feeder = fluid.DataFeeder(feed_list=[x, y])
    feed = feeder.feed([
        ([1, 2, 3], 0),
        ([4, 5, 6], 1),
    ])
    assert feed["x"].dtype == np.float32 and feed["x"].shape == (2, 3)
    assert feed["y"].dtype == np.int64 and feed["y"].shape == (2, 1)


# -- reader decorators + dataset ------------------------------------------


def test_reader_decorators():
    r = lambda: iter(range(10))
    assert list(fluid.reader.firstn(r, 3)()) == [0, 1, 2]
    assert len(list(fluid.batch(r, 4)())) == 3
    assert len(list(fluid.batch(r, 4, drop_last=True)())) == 2
    assert list(fluid.reader.chain(r, r)()) == list(range(10)) * 2
    assert sorted(fluid.reader.shuffle(r, 5)()) == list(range(10))
    doubled = fluid.reader.map_readers(lambda a: a * 2, r)
    assert list(doubled()) == [0, 2, 4, 6, 8, 10, 12, 14, 16, 18]
    buf = fluid.reader.buffered(r, 2)
    assert list(buf()) == list(range(10))
    cached = fluid.reader.cache(r)
    assert list(cached()) == list(cached())


def test_dataset_readers_shapes():
    tr = fluid.dataset.mnist.train()
    img, lab = next(iter(tr()))
    assert img.shape == (784,) and img.dtype == np.float32
    assert 0 <= lab < 10
    hx, hy = next(iter(fluid.dataset.uci_housing.train()()))
    assert hx.shape == (13,) and hy.shape == (1,)
    ci, cl = next(iter(fluid.dataset.cifar.train10()()))
    assert ci.shape == (3072,) and 0 <= cl < 10
    # batch-composable (the reader contract)
    b = next(iter(fluid.batch(tr, 16)()))
    assert len(b) == 16


def test_mnist_synthetic_is_learnable():
    """Softmax regression on the synthetic MNIST stream converges — keeps
    the book-test style convergence checks meaningful offline."""
    img = fluid.data("img", [-1, 784])
    label = fluid.data("label", [-1, 1], "int64")
    probs = layers.fc(img, 10, act=None)
    loss = layers.mean(
        layers.softmax_with_cross_entropy(probs, label)
    )
    fluid.optimizer.Adam(0.01).minimize(loss)
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    feeder = fluid.DataFeeder([img, label])
    losses = []
    for epoch in range(3):
        for b in fluid.batch(fluid.dataset.mnist.train(), 64, drop_last=True)():
            feed = feeder.feed([(s[0], np.asarray([s[1]])) for s in b])
            (lv,) = exe.run(feed=feed, fetch_list=[loss])
            losses.append(float(np.asarray(lv).reshape(-1)[0]))
    assert losses[-1] < losses[0] * 0.5


# -- distributions ---------------------------------------------------------


def test_distributions_normal_uniform_categorical():
    from paddle_tpu.layers.distributions import Categorical, Normal, Uniform

    n1 = Normal(0.0, 1.0)
    n2 = Normal(1.0, 2.0)
    ent = n1.entropy()
    kl = n1.kl_divergence(n2)
    lp = n1.log_prob(layers.fill_constant([1], "float32", 0.0))
    u = Uniform(0.0, 2.0)
    ulp = u.log_prob(layers.fill_constant([1], "float32", 1.0))
    logits = layers.assign_value([[1.0, 2.0, 0.5]])
    c = Categorical(logits)
    cent = c.entropy()
    s = n1.sample([1000], seed=7)
    smean = layers.reduce_mean(s)
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    ev, kv, lv, uv, cv, sm = (
        float(np.asarray(v).reshape(-1)[0])
        for v in exe.run(fetch_list=[ent, kl, lp, ulp, cent, smean])
    )
    import math

    assert ev == pytest.approx(0.5 * math.log(2 * math.pi) + 0.5, rel=1e-5)
    # KL(N(0,1) || N(1,2)) = log(2) + (1+1)/(2*4) - 0.5
    assert kv == pytest.approx(math.log(2) + 2 / 8 - 0.5, rel=1e-5)
    assert lv == pytest.approx(-0.5 * math.log(2 * math.pi), rel=1e-5)
    assert uv == pytest.approx(math.log(0.5), rel=1e-4)
    p = np.exp([1.0, 2.0, 0.5])
    p /= p.sum()
    assert cv == pytest.approx(-(p * np.log(p)).sum(), rel=1e-4)
    assert abs(sm) < 0.15  # sample mean near loc


# -- CompiledProgram -------------------------------------------------------


def test_compiled_program_data_parallel_runs():
    # unseeded programs draw a per-instance RNG nonce (fluid random_seed=0
    # semantics) — the round-2 "order-dependent" flake was an unlucky init
    # landing near the optimum so 10 SGD steps oscillated; seed for a
    # deterministic trajectory
    fluid.default_main_program().random_seed = 1234
    fluid.default_startup_program().random_seed = 1234
    x = fluid.data("x", [8, 4])
    y = fluid.data("y", [8, 1])
    loss = layers.mean(layers.square_error_cost(layers.fc(x, 1), y))
    fluid.optimizer.SGD(0.1).minimize(loss)
    compiled = fluid.CompiledProgram(
        fluid.default_main_program()
    ).with_data_parallel(loss_name=loss.name)
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    rng = np.random.RandomState(0)
    feed = {"x": rng.randn(8, 4).astype(np.float32),
            "y": rng.randn(8, 1).astype(np.float32)}
    losses = [
        float(np.asarray(exe.run(compiled, feed=feed, fetch_list=[loss])[0])
              .reshape(-1)[0])
        for _ in range(10)
    ]
    assert losses[-1] < losses[0]


# -- inference predictor ---------------------------------------------------


def test_predictor_roundtrip(tmp_path):
    x = fluid.data("x", [-1, 4])
    out = layers.fc(x, 2, act="relu")
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    fluid.io.save_inference_model(str(tmp_path / "model"), ["x"], [out], exe)

    config = fluid.inference.AnalysisConfig(str(tmp_path / "model"))
    pred = fluid.inference.create_paddle_predictor(config)
    assert pred.get_input_names() == ["x"]
    xv = np.ones((3, 4), np.float32)
    outs = pred.run([fluid.inference.PaddleTensor(xv, name="x")])
    ref = exe.run(feed={"x": xv}, fetch_list=[out])
    np.testing.assert_allclose(
        outs[0].as_ndarray(), np.asarray(ref[0]), rtol=1e-6
    )


# -- 2.0 namespaces --------------------------------------------------------


def test_v2_namespaces():
    assert fluid.nn.Linear is fluid.dygraph.nn.Linear
    assert fluid.nn.functional.relu is layers.relu
    x = fluid.data("nx", [2, 3])
    s = fluid.tensor.sum(x)
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    (v,) = exe.run(feed={"nx": np.ones((2, 3), np.float32)}, fetch_list=[s])
    assert float(np.asarray(v).reshape(-1)[0]) == 6.0


def test_reader_error_propagation_and_alignment():
    def bad():
        yield 1
        raise IOError("reader broke")

    with pytest.raises(IOError, match="reader broke"):
        list(fluid.reader.buffered(bad, 2)())
    with pytest.raises(IOError, match="reader broke"):
        list(fluid.reader.xmap_readers(lambda s: s, bad, 2, 4)())

    def bad_map(s):
        if s == 3:
            raise ValueError("mapper broke")
        return s * 2

    r = lambda: iter(range(6))
    with pytest.raises(ValueError, match="mapper broke"):
        list(fluid.reader.xmap_readers(bad_map, r, 2, 4)())
    ordered = list(
        fluid.reader.xmap_readers(lambda s: s * 2, r, 3, 4, order=True)()
    )
    assert ordered == [0, 2, 4, 6, 8, 10]

    r3 = lambda: iter(range(3))
    r2 = lambda: iter(range(2))
    with pytest.raises(ValueError, match="different lengths"):
        list(fluid.reader.compose(r3, r2)())
    with pytest.raises(ValueError, match="different lengths"):
        list(fluid.reader.compose(r2, r3)())


def test_data_feeder_rejects_bad_shapes():
    x = fluid.data("fx", [-1, 3])
    feeder = fluid.DataFeeder([x])
    with pytest.raises(ValueError, match="declares"):
        feeder.feed([([1, 2, 3, 4],)])


def test_declarative_recaches_on_static_args():
    dg = fluid.dygraph

    @dg.declarative
    def f(a, scale):
        return layers.reduce_sum(a) * scale

    with dg.guard():
        a = dg.to_variable(np.ones((2,), np.float32))
        r2 = f(a, 2.0)
        r3 = f(a, 3.0)
        assert float(np.asarray(r2.value).reshape(-1)[0]) == 4.0
        assert float(np.asarray(r3.value).reshape(-1)[0]) == 6.0


def test_predictor_shape_and_error_handling(tmp_path):
    """Predictor beyond the happy path (VERDICT r2 weak #7): batch-size
    flexibility through the -1 dim, wrong-rank feeds raise, missing feeds
    raise, named outputs round-trip."""
    from paddle_tpu.inference import (
        AnalysisConfig, PaddleTensor, create_paddle_predictor,
    )

    x = fluid.data("x", [-1, 4])
    out = layers.fc(x, 2, act="relu")
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    fluid.io.save_inference_model(str(tmp_path / "m"), ["x"], [out], exe)

    pred = create_paddle_predictor(AnalysisConfig(str(tmp_path / "m")))
    assert pred.get_input_names() == ["x"]
    rng = np.random.RandomState(0)
    # two different batch sizes through the same predictor
    for b in (1, 7):
        (res,) = pred.run([PaddleTensor(rng.randn(b, 4).astype("float32"))])
        assert res.as_ndarray().shape == (b, 2)
        assert res.name == pred.get_output_names()[0]
    # wrong rank surfaces as an error, not silence
    with pytest.raises(Exception):
        outs = pred.run([PaddleTensor(rng.randn(4).astype("float32"))])
        np.asarray(outs[0].as_ndarray())
    # missing feed
    with pytest.raises(Exception):
        outs = pred.run([])
        np.asarray(outs[0].as_ndarray())
