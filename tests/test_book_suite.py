"""End-to-end "book" tests mirroring the reference's tests/book suite
(test_recognize_digits.py, notest_understand_sentiment.py,
test_recommender_system.py, test_word2vec.py): small full models trained
for a few steps with convergence thresholds, built only on the public API.
Synthetic data is constructed learnable (fixed mappings), so memorization
drives the loss down the same way the reference's real datasets do."""

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import layers, nets
from paddle_tpu.framework import unique_name


@pytest.fixture(autouse=True)
def fresh_programs():
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 42
    scope = fluid.framework.scope.Scope()
    with fluid.program_guard(main, startup), fluid.scope_guard(scope), \
            unique_name.guard():
        yield main, startup, scope


def _train(loss, feeds, steps, lr=0.01, opt=None, extra_fetch=()):
    (opt or fluid.optimizer.Adam(lr)).minimize(loss)
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    vals = []
    for _ in range(steps):
        out = exe.run(feed=feeds, fetch_list=[loss, *extra_fetch])
        vals.append(float(np.asarray(out[0]).reshape(-1)[0]))
    return vals, out


def test_recognize_digits_conv():
    """reference tests/book/test_recognize_digits.py (conv variant): LeNet
    via nets.simple_img_conv_pool on a fixed batch."""
    rng = np.random.RandomState(0)
    img = fluid.data("img", [32, 1, 28, 28])
    label = fluid.data("label", [32, 1], "int64")
    conv1 = nets.simple_img_conv_pool(
        img, filter_size=5, num_filters=8, pool_size=2, pool_stride=2,
        act="relu",
    )
    conv2 = nets.simple_img_conv_pool(
        conv1, filter_size=5, num_filters=16, pool_size=2, pool_stride=2,
        act="relu",
    )
    logits = layers.fc(conv2, 10, num_flatten_dims=1)
    loss = layers.mean(layers.softmax_with_cross_entropy(logits, label))
    acc = layers.accuracy(layers.softmax(logits), label)
    feeds = {
        "img": rng.randn(32, 1, 28, 28).astype("float32"),
        "label": rng.randint(0, 10, (32, 1)).astype("int64"),
    }
    vals, out = _train(loss, feeds, 40, lr=2e-3, extra_fetch=[acc])
    assert vals[-1] < vals[0] * 0.5, (vals[0], vals[-1])
    assert float(np.asarray(out[1]).reshape(-1)[0]) > 0.7  # memorized


def test_understand_sentiment_lstm():
    """reference tests/book/notest_understand_sentiment.py (stacked LSTM):
    label = parity of the first token — linearly separable through the
    recurrence."""
    rng = np.random.RandomState(1)
    B, T, V, H = 16, 12, 50, 32
    words = fluid.data("words", [B, T], "int64")
    label = fluid.data("label", [B, 1], "int64")
    emb = layers.embedding(words, size=[V, H])
    out, last_h, last_c = layers.lstm(emb, H)
    feat = layers.reduce_max(out, dim=1)
    logits = layers.fc(feat, 2)
    loss = layers.mean(layers.softmax_with_cross_entropy(logits, label))
    acc = layers.accuracy(layers.softmax(logits), label)
    w = rng.randint(0, V, (B, T)).astype("int64")
    feeds = {"words": w, "label": (w[:, :1] % 2).astype("int64")}
    vals, out = _train(loss, feeds, 60, lr=5e-3, extra_fetch=[acc])
    assert vals[-1] < vals[0] * 0.5
    assert float(np.asarray(out[1]).reshape(-1)[0]) > 0.9


def test_recommender_system():
    """reference tests/book/test_recommender_system.py: user/item embedding
    towers, rating = fixed user-item table (learnable by memorization)."""
    rng = np.random.RandomState(2)
    NU, NI, B = 20, 30, 64
    table = rng.rand(NU, NI).astype("float32") * 4 + 1  # ratings 1..5
    uid = fluid.data("uid", [B, 1], "int64")
    iid = fluid.data("iid", [B, 1], "int64")
    rating = fluid.data("rating", [B, 1], "float32")
    u = layers.fc(layers.embedding(uid, size=[NU, 16]), 16, act="relu")
    i = layers.fc(layers.embedding(iid, size=[NI, 16]), 16, act="relu")
    both = layers.concat([layers.reshape(u, [B, 16]),
                          layers.reshape(i, [B, 16])], axis=1)
    pred = layers.fc(both, 1)
    loss = layers.mean(layers.square_error_cost(pred, rating))
    us = rng.randint(0, NU, (B, 1)).astype("int64")
    is_ = rng.randint(0, NI, (B, 1)).astype("int64")
    feeds = {
        "uid": us, "iid": is_,
        "rating": table[us[:, 0], is_[:, 0]].reshape(B, 1),
    }
    vals, _ = _train(loss, feeds, 80, lr=0.01)
    assert vals[-1] < 0.15 * vals[0], (vals[0], vals[-1])


def test_word2vec_cbow():
    """reference tests/book/test_word2vec.py: N-gram/CBOW — predict the
    middle word from context embeddings; corpus is a fixed cyclic pattern
    so the mapping is deterministic."""
    V, H, B, C = 40, 24, 64, 4
    rng = np.random.RandomState(3)
    ctx = fluid.data("ctx", [B, C], "int64")
    target = fluid.data("target", [B, 1], "int64")
    emb = layers.embedding(
        ctx, size=[V, H], param_attr=fluid.ParamAttr(name="shared_emb")
    )
    feat = layers.reduce_mean(emb, dim=1)
    logits = layers.fc(feat, V)
    loss = layers.mean(layers.softmax_with_cross_entropy(logits, target))
    # deterministic corpus: word w is always followed by (w*7+3) % V
    base = rng.randint(0, V, (B,)).astype("int64")
    seq = [base]
    for _ in range(C):
        seq.append((seq[-1] * 7 + 3) % V)
    seq = np.stack(seq, 1)  # [B, C+1]
    feeds = {"ctx": seq[:, :C], "target": seq[:, C:]}
    vals, _ = _train(loss, feeds, 200, lr=0.03)
    # from ln(V)=3.69 at init to ~0.97 (the fc head plateaus there on this
    # tiny fixed batch) — well below uniform, proving the CBOW mapping fits
    assert vals[-1] < 0.35 * vals[0], (vals[0], vals[-1])
