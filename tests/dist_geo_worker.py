"""Worker for the 2-process Geo-SGD PS test: each rank trains DeepFM on
rank-dependent data LOCALLY (tables updated in-graph), the
GeoCommunicator exchanges table deltas every `update_frequency` steps
over the global device mesh (reference geo_sgd_transpiler.py semantics:
periodic delta push, bounded divergence)."""

import json
import os
import sys

import numpy as np

import paddle_tpu as fluid
from paddle_tpu.fleet.communicator import GeoCommunicator
from paddle_tpu.fleet.role_maker import PaddleCloudRoleMaker
from paddle_tpu.models import DeepFMConfig, deepfm
from paddle_tpu.parallel.mesh import make_mesh


def main():
    out_dir = sys.argv[1]
    role = PaddleCloudRoleMaker()
    role.generate_role()  # brings up jax.distributed
    rank = role.worker_index()

    import jax

    cfg = DeepFMConfig(vocab_size=256, num_fields=4, embed_dim=4,
                       mlp_sizes=(8,))
    b = 8
    main_prog, startup = fluid.Program(), fluid.Program()
    main_prog.random_seed = startup.random_seed = 7
    with fluid.program_guard(main_prog, startup):
        ids = fluid.data("feat_ids", [b, cfg.num_fields], "int64")
        label = fluid.data("label", [b, 1], "float32")
        loss, _ = deepfm(ids, label, cfg)
        fluid.optimizer.SGD(0.1).minimize(loss)

    exe = fluid.Executor()
    exe.run(startup)
    scope = fluid.framework.scope.global_scope()
    mesh = make_mesh({"dp": len(jax.devices())}, jax.devices())
    comm = GeoCommunicator(["deepfm_w1", "deepfm_emb"], scope, exe,
                           update_frequency=5, mesh=mesh)

    rng = np.random.RandomState(100 + rank)  # divergent local data
    feeds = []
    for _ in range(3):
        idv = rng.randint(0, cfg.vocab_size, (b, cfg.num_fields))
        lab = (idv[:, :1] % 2 == 0).astype(np.float32)
        feeds.append({"feat_ids": idv.astype(np.int64), "label": lab})
    losses = []
    for step in range(15):
        (lv,) = exe.run(
            main_prog, feed=feeds[step % 3], fetch_list=[loss],
        )
        losses.append(float(np.asarray(lv).reshape(-1)[0]))
        comm.maybe_sync()

    emb = np.asarray(scope.find_var("deepfm_emb"))
    with open(os.path.join(out_dir, f"geo_{rank}.json"), "w") as f:
        json.dump({
            "losses": losses,
            "emb_sum": float(emb.sum()),
            "emb_absmax": float(np.abs(emb).max()),
        }, f)


if __name__ == "__main__":
    main()
