"""Native MultiSlot parser + fluid Dataset + train_from_dataset.

Reference: C++ data_feed parsing tests + test_dataset.py (QueueDataset/
InMemoryDataset driving train_from_dataset over MultiSlot files).
"""

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import layers, native
from paddle_tpu.framework import unique_name


@pytest.fixture(autouse=True)
def fresh_programs():
    main, startup = fluid.Program(), fluid.Program()
    scope = fluid.framework.scope.Scope()
    with fluid.program_guard(main, startup), fluid.scope_guard(scope), \
            unique_name.guard():
        yield main, startup, scope


def test_native_lib_builds():
    assert native.native_available(), "g++ build of the native lib failed"


def test_parse_multislot_native_matches_python():
    text = "2 11 12 1 0.5\n1 13 2 1.5 -2.25\n3 1 2 3 1 9\n"
    v_n, o_n = native.parse_multislot(text, 2)
    v_p, o_p = native._parse_multislot_py(text.encode(), 2)
    np.testing.assert_allclose(v_n, v_p)
    np.testing.assert_array_equal(o_n, o_p)
    np.testing.assert_allclose(
        v_n, [11, 12, 0.5, 13, 1.5, -2.25, 1, 2, 3, 9]
    )
    np.testing.assert_array_equal(o_n, [0, 2, 3, 4, 6, 9, 10])


def test_parse_multislot_malformed():
    with pytest.raises(ValueError, match="malformed"):
        native.parse_multislot("2 11\n", 2)  # declares 2 values, has 1+EOL
    with pytest.raises(ValueError, match="malformed"):
        native._parse_multislot_py(b"2 11\n", 2)


def test_pack_padded_variants():
    vals = np.asarray([1.5, 2.5, 3.5], np.float32)
    offs = np.asarray([0, 1, 1, 3], np.int64)
    out, lens = native.pack_padded(vals, offs, 2, pad_value=-1.0)
    np.testing.assert_allclose(out, [[1.5, -1], [-1, -1], [2.5, 3.5]])
    np.testing.assert_array_equal(lens, [1, 0, 2])
    big = np.asarray([2**40, 7], np.int64)
    out_i, _ = native.pack_padded(
        big, np.asarray([0, 2], np.int64), 3, dtype=np.int64
    )
    assert out_i[0, 0] == 2**40  # exact (why the i64 variant exists)


def test_train_from_dataset(tmp_path):
    """QueueDataset over MultiSlot files drives a CTR-style train loop
    (closes the reference train_from_dataset path)."""
    rng = np.random.RandomState(0)
    files = []
    for fi in range(2):
        lines = []
        for _ in range(64):
            ids = rng.randint(0, 100, 3)
            label = int(ids[0] % 2)
            lines.append(
                "3 " + " ".join(map(str, ids)) + f" 1 {label}"
            )
        f = tmp_path / f"part-{fi}.txt"
        f.write_text("\n".join(lines) + "\n")
        files.append(str(f))

    ids = fluid.data("ids", [-1, 3], "int64")
    label = fluid.data("label", [-1, 1], "float32")
    emb = layers.embedding(ids, size=[100, 8])
    logit = layers.fc(layers.reshape(emb, [-1, 24]), 1)
    loss = layers.mean(
        layers.sigmoid_cross_entropy_with_logits(logit, label)
    )
    fluid.optimizer.Adam(0.02).minimize(loss)

    dataset = fluid.DatasetFactory().create_dataset("QueueDataset")
    dataset.set_batch_size(32)
    dataset.set_use_var([ids, label])
    dataset.set_filelist(files)

    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    first = last = None
    for epoch in range(25):
        exe.train_from_dataset(
            fluid.default_main_program(), dataset, fetch_list=[loss]
        )
        (lv,) = exe.run(
            feed=next(iter(dataset.batches())), fetch_list=[loss]
        )
        lv = float(np.asarray(lv).reshape(-1)[0])
        first = first if first is not None else lv
        last = lv
    assert last < first * 0.7, (first, last)


def test_inmemory_dataset_shuffle_and_shard(tmp_path):
    f = tmp_path / "d.txt"
    f.write_text("".join(f"1 {i} 1 {i * 10}\n" for i in range(10)))
    x = fluid.data("xa", [-1, 1], "int64")
    y = fluid.data("ya", [-1, 1], "float32")
    ds = fluid.DatasetFactory().create_dataset("InMemoryDataset")
    ds.set_batch_size(4)
    ds.set_use_var([x, y])
    ds.set_filelist([str(f)])
    ds.load_into_memory()
    ds.local_shuffle(seed=0)
    rows = [b for b in ds.batches()]
    got = np.concatenate([b["xa"].reshape(-1) for b in rows])
    assert sorted(got.tolist()) == list(range(10))

    # global shuffle shards disjointly across 2 fake workers
    class W:
        def __init__(self, r):
            self.r = r

        def worker_index(self):
            return self.r

        def worker_num(self):
            return 2

    seen = []
    for r in range(2):
        ds2 = fluid.DatasetFactory().create_dataset("InMemoryDataset")
        ds2.set_batch_size(4)
        ds2.set_use_var([x, y])
        ds2.set_filelist([str(f)])
        ds2.load_into_memory()
        ds2.global_shuffle(W(r), seed=3)
        for b in ds2.batches():
            seen.extend(b["xa"].reshape(-1).tolist())
    assert sorted(seen) == list(range(10))


def test_parser_preserves_large_ids():
    """ids above 2^24 survive the parse->pack pipeline exactly (parsed as
    double, packed as int64)."""
    big = 16777217  # 2^24 + 1: not representable in float32
    v, o = native.parse_multislot(f"1 {big} 1 1\n", 2)
    assert v.dtype == np.float64
    out, _ = native.pack_padded(v[:1], np.asarray([0, 1], np.int64), 1,
                                dtype=np.int64)
    assert out[0, 0] == big


def test_infer_from_dataset_rejects_train_programs(tmp_path):
    f = tmp_path / "d.txt"
    f.write_text("1 1 1 1.0\n")
    x = fluid.data("ix", [-1, 1], "int64")
    y = fluid.data("iy", [-1, 1], "float32")
    loss = layers.mean(layers.fc(layers.cast(x, "float32"), 1) + y)
    fluid.optimizer.SGD(0.1).minimize(loss)
    ds = fluid.DatasetFactory().create_dataset("QueueDataset")
    ds.set_batch_size(1)
    ds.set_use_var([x, y])
    ds.set_filelist([str(f)])
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    with pytest.raises(ValueError, match="update ops"):
        exe.infer_from_dataset(fluid.default_main_program(), ds)


def test_parser_rejects_cross_line_records():
    """A short line must NOT pull tokens from the next line (newline is a
    hard record boundary, unlike bare strtod whitespace skipping)."""
    with pytest.raises(ValueError, match="malformed"):
        native.parse_multislot("2 11\n1 5\n", 1)
    v, o = native.parse_multislot("2 11 12\n1 5\n", 1)
    np.testing.assert_allclose(v, [11, 12, 5])


def test_dataset_rejects_width_mismatch(tmp_path):
    f = tmp_path / "d.txt"
    f.write_text("2 1 2 1 1.0\n")  # slot 0 has 2 values
    x = fluid.data("wx", [-1, 3], "int64")  # but declares width 3
    y = fluid.data("wy", [-1, 1], "float32")
    ds = fluid.DatasetFactory().create_dataset("QueueDataset")
    ds.set_batch_size(1)
    ds.set_use_var([x, y])
    ds.set_filelist([str(f)])
    with pytest.raises(ValueError, match="declares 3"):
        list(ds.batches())


def test_data_generator_roundtrip_train(tmp_path):
    """r5 (VERDICT #9): the user-facing MultiSlot writer
    (incubate/data_generator.py, reference incubate/data_generator)
    round-trips through the native parser into train_from_dataset."""
    from paddle_tpu.incubate.data_generator import (
        MultiSlotDataGenerator,
        MultiSlotStringDataGenerator,
    )

    rng = np.random.RandomState(3)

    class CTRData(MultiSlotDataGenerator):
        def generate_sample(self, line):
            def local_iter():
                ids = [int(v) for v in rng.randint(0, 100, 3)]
                yield [("ids", ids), ("label", [float(ids[0] % 2)])]

            return local_iter

    gen = CTRData()
    path = tmp_path / "gen-part-0.txt"
    # 64 raw "lines" -> 64 samples
    n = gen.write_to_file(range(64), str(path))
    assert n == 64
    assert gen._proto_info == [("ids", "uint64"), ("label", "float")]

    # the written text parses through the NATIVE parser byte-for-byte
    v, o = native.parse_multislot(path.read_text(), 2)
    assert len(o) == 64 * 2 + 1
    assert np.all(np.diff(o) >= 1)

    ids = fluid.data("ids", [-1, 3], "int64")
    label = fluid.data("label", [-1, 1], "float32")
    emb = layers.embedding(ids, size=[100, 8])
    logit = layers.fc(layers.reshape(emb, [-1, 24]), 1)
    loss = layers.mean(
        layers.sigmoid_cross_entropy_with_logits(logit, label)
    )
    fluid.optimizer.Adam(0.02).minimize(loss)
    dataset = fluid.DatasetFactory().create_dataset("QueueDataset")
    dataset.set_batch_size(32)
    dataset.set_use_var([ids, label])
    dataset.set_filelist([str(path)])
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    first = last = None
    for _ in range(20):
        exe.train_from_dataset(
            fluid.default_main_program(), dataset, fetch_list=[loss]
        )
        (lv,) = exe.run(
            feed=next(iter(dataset.batches())), fetch_list=[loss]
        )
        lv = float(np.asarray(lv).reshape(-1)[0])
        first = first if first is not None else lv
        last = lv
    assert last < first * 0.9, (first, last)

    # string variant + stdin/stdout pipe protocol parity
    import io

    class SData(MultiSlotStringDataGenerator):
        def generate_sample(self, line):
            def local_iter():
                toks = line.split()
                yield [("w", toks[:-1]), ("y", [toks[-1]])]

            return local_iter

    out = io.StringIO()
    SData().run_from_stdin(stdin=["1 2 3 0\n", "4 5 6 1\n"], out=out)
    assert out.getvalue() == "3 1 2 3 1 0\n3 4 5 6 1 1\n"
    v2, o2 = native.parse_multislot(out.getvalue(), 2)
    np.testing.assert_allclose(v2, [1, 2, 3, 0, 4, 5, 6, 1])
