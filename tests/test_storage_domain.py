"""PR-19 storage fault domain: the ENOSPC-safe durable-write contract
(preflight, typed mapping, temp unlink), the StorageMonitor pressure
ladder with hysteresis, cross-plane RetentionManager GC, the
StoragePressureController degradation rungs, the flight-dump ring, and
the stale-tmp sweepers.

Everything here is in-process and deterministic: disk pressure comes
from byte-BUDGETED roots (free = budget − bytes used), never from
filling a real volume, and ENOSPC comes from the seeded ``fs.write``
chaos seam inside ``io._atomic_write`` — the injected error is a RAW
``OSError(errno.ENOSPC)``, so these tests exercise the production
mapping to ``StorageExhaustedError``, not a shortcut. The multi-process
leg (2-rank train+publish under ENOSPC bursts) is ci.sh's storage-chaos
stage."""

import importlib.util
import json
import os
import random
import time

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import errors, io, layers
from paddle_tpu import observability as obs
from paddle_tpu.fleet import collective as fc
from paddle_tpu.fleet import publish as pub
from paddle_tpu.fleet.role_maker import UserDefinedRoleMaker
from paddle_tpu.framework import unique_name
from paddle_tpu.framework.scope import Scope, global_scope, scope_guard
from paddle_tpu.observability.recorder import FlightRecorder
from paddle_tpu.observability.timeline import TelemetryPublisher
from paddle_tpu.observability.watch import Watcher
from paddle_tpu.resilience import faults, storage
from paddle_tpu.resilience.health import Heartbeat

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def fresh_state():
    obs.reset()
    obs.set_enabled(True)
    faults.clear()
    storage.uninstall()
    yield
    faults.clear()
    storage.uninstall()
    obs.reset()
    obs.set_enabled(None)


def _counter(name):
    return obs.get_counters().get(name, 0)


def _tmp_residue(root):
    out = []
    for dirpath, _dirs, files in os.walk(root):
        out += [os.path.join(dirpath, f) for f in files if ".tmp." in f]
    return out


def _arm_nth_write(n, kind="enospc"):
    """Arm ``fs.write`` so the Nth draw — and only it — fires: search a
    (seed, prob) pair where the first N-1 seeded draws miss and the Nth
    hits, then cap with max_fires=1. Deterministic by construction."""
    for seed in range(20000):
        rng = random.Random(seed)
        draws = [rng.random() for _ in range(n)]
        lo = draws[n - 1]
        hi = min(draws[: n - 1], default=1.0)
        if lo < hi:
            return faults.inject(
                "fs.write", kind, (lo + hi) / 2.0, seed, 1
            )
    raise AssertionError(f"no seed places fire #{n}")


def _count_atomic_writes(fn, monkeypatch):
    """Run `fn` with io._atomic_write wrapped by a counter; returns the
    number of atomic writes it performed."""
    calls = [0]
    orig = io._atomic_write

    def counting(path, write_fn, estimated_size=None):
        calls[0] += 1
        return orig(path, write_fn, estimated_size=estimated_size)

    monkeypatch.setattr(io, "_atomic_write", counting)
    try:
        fn()
    finally:
        monkeypatch.setattr(io, "_atomic_write", orig)
    return calls[0]


# ---------------------------------------------------------------------------
# the ENOSPC-safe write contract (io.py)
# ---------------------------------------------------------------------------


def test_enospc_maps_to_typed_error_and_unlinks_tmp(tmp_path):
    faults.inject("fs.write", "enospc", 1.0, 0, 1)
    with pytest.raises(errors.StorageExhaustedError) as ei:
        io._atomic_write(str(tmp_path / "x.bin"), lambda f: f.write(b"hi"))
    assert ei.value.code == errors.ErrorCode.RESOURCE_EXHAUSTED
    assert ei.value.retryable is False
    assert _tmp_residue(str(tmp_path)) == []
    assert not (tmp_path / "x.bin").exists()
    assert _counter("storage.enospc_errors") == 1
    # the burst is over: the very next write succeeds in place
    io._atomic_write(str(tmp_path / "x.bin"), lambda f: f.write(b"hi"))
    assert (tmp_path / "x.bin").read_bytes() == b"hi"


def test_plain_io_failure_still_unlinks_tmp(tmp_path):
    faults.inject("fs.write", "io", 1.0, 0, 1)
    with pytest.raises(OSError):
        io._atomic_write(str(tmp_path / "y.bin"), lambda f: f.write(b"z"))
    assert _tmp_residue(str(tmp_path)) == []


def test_preflight_rejects_oversized_write_on_budget_root(tmp_path):
    storage.StorageMonitor(probe=False).add_root(
        "t", str(tmp_path), budget_bytes=1024
    ).install()
    with pytest.raises(errors.StorageExhaustedError):
        io.save_arrays(
            str(tmp_path / "big"), {"w": np.zeros(1 << 16, np.float32)}
        )
    assert _counter("storage.preflight_rejects") >= 1
    assert _tmp_residue(str(tmp_path)) == []


def test_preflight_env_kill_switch(tmp_path, monkeypatch):
    storage.StorageMonitor(probe=False).add_root(
        "t", str(tmp_path), budget_bytes=16
    ).install()
    monkeypatch.setenv(io.PREFLIGHT_ENV, "0")
    # preflight off: the write itself goes through (the real volume has
    # the room; only the synthetic budget disagreed)
    io._atomic_write(str(tmp_path / "z.bin"), lambda f: f.write(b"ok"),
                     estimated_size=1 << 20)
    assert (tmp_path / "z.bin").read_bytes() == b"ok"


def test_sweep_stale_tmp_prefix_and_recursive(tmp_path):
    sub = tmp_path / "sub"
    sub.mkdir()
    (tmp_path / "hb_rank0.tmp.aa").write_bytes(b"x" * 10)
    (tmp_path / "hb_rank1.tmp.bb").write_bytes(b"y" * 20)
    (tmp_path / "keep.json").write_bytes(b"{}")
    (sub / "shard.bin.tmp.cc").write_bytes(b"z" * 30)
    freed = io.sweep_stale_tmp(str(tmp_path), prefix="hb_rank0")
    assert freed == 10
    assert (tmp_path / "hb_rank1.tmp.bb").exists()
    freed = io.sweep_stale_tmp(str(tmp_path), recursive=True)
    assert freed == 50
    assert _tmp_residue(str(tmp_path)) == []
    assert (tmp_path / "keep.json").exists()
    assert _counter("storage.stale_tmp_swept") == 3


def test_startup_sweeps_heartbeat_and_publish_roots(tmp_path):
    hb_dir = tmp_path / "hb"
    pub_dir = tmp_path / "pub"
    hb_dir.mkdir()
    pub_dir.mkdir()
    (hb_dir / "hb_rank0.tmp.dead").write_bytes(b"x")
    (hb_dir / "hb_rank1.tmp.live").write_bytes(b"x")  # a sibling's: keep
    (pub_dir / "blocked.json.tmp.dead").write_bytes(b"x")
    Heartbeat(str(hb_dir), rank=0)
    assert not (hb_dir / "hb_rank0.tmp.dead").exists()
    assert (hb_dir / "hb_rank1.tmp.live").exists()
    pub.ModelPublisher(str(pub_dir), main_program=fluid.Program(),
                       scope=Scope())
    assert _tmp_residue(str(pub_dir)) == []


# ---------------------------------------------------------------------------
# StorageMonitor: budgets, hysteresis, gauges
# ---------------------------------------------------------------------------


def test_monitor_budget_mode_and_hysteresis(tmp_path):
    m = storage.StorageMonitor(soft_bytes=1000, hard_bytes=500,
                               critical_bytes=100, rearm=1.5, probe=False)
    m.add_root("checkpoint", str(tmp_path / "ck"), budget_bytes=2000)
    assert m.poll()["level"] == storage.OK
    junk = tmp_path / "ck" / "junk"
    junk.write_bytes(b"x" * 1100)      # free 900 < soft
    info = m.poll()
    assert info["level"] == storage.SOFT
    assert info["events"] == [("checkpoint", storage.OK, storage.SOFT)]
    # hysteresis: back above the SOFT line but NOT by the re-arm margin
    # (need free >= 1000 * 1.5) — the latch holds
    junk.write_bytes(b"x" * 990)       # free 1010
    assert m.poll()["level"] == storage.SOFT
    junk.write_bytes(b"x" * 400)       # free 1600 >= 1500: re-arms
    info = m.poll()
    assert info["level"] == storage.OK
    assert info["events"] == [("checkpoint", storage.SOFT, storage.OK)]
    # escalation is immediate, straight past intermediate rungs
    junk.write_bytes(b"x" * 1950)      # free 50 < critical
    assert m.poll()["level"] == storage.CRITICAL
    assert _counter("storage.escalations") == 2
    assert _counter("storage.recoveries") == 1
    gauges = obs.get_gauges()
    assert gauges["storage.free_bytes.checkpoint"] == 50.0
    assert gauges["storage.pressure"] == float(storage.CRITICAL)


def test_monitor_write_latency_probe_sees_slow_seam(tmp_path, monkeypatch):
    monkeypatch.setenv(faults.SLOW_SECONDS_ENV, "0.05")
    faults.inject("fs.write", "slow", 1.0, 0, 1)
    m = storage.StorageMonitor(probe=True)
    m.add_root("telemetry", str(tmp_path / "tl"))
    m.poll()
    assert obs.get_gauges()["storage.write_latency.telemetry"] >= 0.05
    # the probe target never lingers
    assert os.listdir(str(tmp_path / "tl")) == []


def test_require_writable_refuses_at_critical(tmp_path):
    # no monitor installed: a no-op
    storage.require_writable("checkpoint")
    m = storage.StorageMonitor(soft_bytes=300, hard_bytes=200,
                               critical_bytes=100, probe=False)
    m.add_root("checkpoint", str(tmp_path / "ck"), budget_bytes=1000)
    m.install()
    m.poll()
    storage.require_writable("checkpoint")
    (tmp_path / "ck" / "junk").write_bytes(b"x" * 950)
    m.poll()
    with pytest.raises(errors.StorageExhaustedError):
        storage.require_writable("checkpoint")
    assert _counter("storage.writes_refused.checkpoint") == 1
    # an unregistered plane falls back to the overall level
    with pytest.raises(errors.StorageExhaustedError):
        storage.require_writable("publish")


# ---------------------------------------------------------------------------
# crash consistency under disk-full: checkpoint plane
# ---------------------------------------------------------------------------


@pytest.fixture
def fresh_programs():
    main, startup = fluid.Program(), fluid.Program()
    scope = Scope()
    with fluid.program_guard(main, startup), scope_guard(scope), \
            unique_name.guard():
        yield main


def _build_model():
    x = fluid.data("x", [-1, 4])
    y = fluid.data("y", [-1, 1])
    pred = layers.fc(x, 1, param_attr=fluid.ParamAttr(name="sd_w"))
    loss = layers.mean(fluid.layers.square_error_cost(pred, y))
    fluid.optimizer.SGD(0.05).minimize(loss)
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    return exe, loss


def _fleet():
    f = fc.Fleet()
    f.init(UserDefinedRoleMaker(current_id=0, worker_num=1))
    return f


def _persistable_state():
    scope = global_scope()
    return {
        v.name: np.asarray(scope.find_var(v.name)).copy()
        for v in fluid.default_main_program().list_vars()
        if v.persistable and scope.find_var(v.name) is not None
    }


def _step(exe, loss, rng):
    xa = rng.randn(8, 4).astype(np.float32)
    exe.run(feed={"x": xa, "y": xa @ np.ones((4, 1), np.float32)},
            fetch_list=[loss])


@pytest.mark.parametrize("fire_at", ["first", "last"])
def test_checkpoint_enospc_previous_checkpoint_survives_bitwise(
    tmp_path, fresh_programs, monkeypatch, fire_at
):
    """ENOSPC mid-manifest (first/last atomic write of the save): the
    save fails TYPED without retries, the previously committed
    checkpoint resumes bitwise, and no torn dir or ``*.tmp.*`` residue
    survives anywhere under the checkpoint root."""
    exe, loss = _build_model()
    fleet = _fleet()
    rng = np.random.RandomState(3)
    path = str(tmp_path / "ck")
    _step(exe, loss, rng)
    want = _persistable_state()
    status = fc.TrainStatus(0, global_step=1)
    assert fleet.save_check_point(exe, path, status) == 0
    # measure the save's atomic-write count on a throwaway root (same
    # graph, same payload shape), so the "last" variant can target the
    # final manifest write deterministically
    n_writes = _count_atomic_writes(
        lambda: fleet.save_check_point(
            exe, str(tmp_path / "probe"), status
        ),
        monkeypatch,
    )
    assert n_writes >= 1
    _step(exe, loss, rng)  # diverge the live state past the checkpoint
    _arm_nth_write(1 if fire_at == "first" else n_writes)
    with pytest.raises(errors.StorageExhaustedError):
        fleet.save_check_point(
            exe, path, fc.TrainStatus(0, global_step=2)
        )
    # exactly one fire: the typed error must NOT have been retried into
    # accidental success (retryable=False is the contract)
    assert _counter("resilience.faults_injected.fs.write") == 1
    # the failed save left nothing: no new number, no tmp residue
    assert sorted(os.listdir(path)) == ["__paddle_checkpoint__0"]
    assert _tmp_residue(path) == []
    # and checkpoint 0 resumes bitwise
    got = fleet.load_check_point(exe, path)
    assert got.global_step == 1
    for name, arr in want.items():
        live = np.asarray(global_scope().find_var(name))
        assert live.tobytes() == arr.tobytes(), name


def test_save_check_point_bytes_budget_rotation(tmp_path, fresh_programs):
    exe, loss = _build_model()
    fleet = _fleet()
    rng = np.random.RandomState(5)
    path = str(tmp_path / "ck")
    status = fc.TrainStatus(0)
    fleet.save_check_point(exe, path, status, max_checkpoint_num=10)
    one = fc._dir_bytes(os.path.join(path, "__paddle_checkpoint__0"))
    for step in range(1, 4):
        _step(exe, loss, rng)
        fleet.save_check_point(
            exe, path, fc.TrainStatus(0, global_step=step),
            max_checkpoint_num=10,
            max_checkpoint_bytes=int(one * 2.5),
        )
    nos = sorted(os.listdir(path))
    # count budget allows 10, bytes budget only ~2.5 payloads
    assert len(nos) <= 3
    assert "__paddle_checkpoint__3" in nos  # newest always survives


def test_require_writable_gates_save_check_point(
    tmp_path, fresh_programs
):
    exe, _loss = _build_model()
    fleet = _fleet()
    ck = str(tmp_path / "ck")
    m = storage.StorageMonitor(soft_bytes=30, hard_bytes=20,
                               critical_bytes=10, probe=False)
    m.add_root("checkpoint", ck, budget_bytes=40).install()
    os.makedirs(ck, exist_ok=True)
    with open(os.path.join(ck, "junk"), "wb") as f:
        f.write(b"x" * 35)
    m.poll()
    with pytest.raises(errors.StorageExhaustedError):
        fleet.save_check_point(exe, ck, fc.TrainStatus(0))
    # the refusal happened before any FS work: only the junk file exists
    assert os.listdir(ck) == ["junk"]


# ---------------------------------------------------------------------------
# crash consistency under disk-full: publish plane
# ---------------------------------------------------------------------------


class _Trainer:
    def __init__(self, seed=7):
        self.scope = Scope()
        self.main, self.startup = fluid.Program(), fluid.Program()
        self.main.random_seed = self.startup.random_seed = seed
        with fluid.program_guard(self.main, self.startup), \
                unique_name.guard():
            x = fluid.data("x", [-1, 8])
            lab = fluid.data("lab", [-1, 1], "int64")
            h = layers.fc(x, 16, act="relu")
            logits = layers.fc(h, 4)
            self.loss = layers.mean(
                layers.softmax_with_cross_entropy(logits, lab)
            )
            fluid.optimizer.Adam(1e-2).minimize(self.loss, self.startup)
        self.exe = fluid.Executor()
        self._rng = np.random.RandomState(seed)
        with scope_guard(self.scope):
            self.exe.run(self.startup, scope=self.scope)

    def step(self, n=2):
        with scope_guard(self.scope):
            for _ in range(n):
                self.exe.run(
                    self.main,
                    feed={
                        "x": self._rng.randn(4, 8).astype(np.float32),
                        "lab": self._rng.randint(0, 4, (4, 1))
                        .astype(np.int64),
                    },
                    fetch_list=[self.loss], scope=self.scope,
                )


@pytest.mark.parametrize("fire_at", ["payload", "commit"])
def test_publish_enospc_previous_version_survives_bitwise(
    tmp_path, monkeypatch, fire_at
):
    """ENOSPC mid-payload-manifest and mid-``commit.json``: the publish
    raises typed, the failed version never exists to readers, the prior
    committed version still folds bitwise, and the publish root holds
    zero torn dirs and zero temp files."""
    tr = _Trainer()
    pdir = str(tmp_path / "pub")
    # full_every=1: every bundle is full, so the atomic-write count per
    # publish is stable and the commit write is targetable
    p = pub.ModelPublisher(pdir, main_program=tr.main, scope=tr.scope,
                           full_every=1)
    assert p.publish(step=1) == 1
    want = pub.load_version(pdir, 1)
    n_writes = _count_atomic_writes(lambda: p.publish(step=2), monkeypatch)
    assert n_writes >= 2  # at least payload (+manifest) and commit
    tr.step()
    _arm_nth_write(1 if fire_at == "payload" else n_writes)
    with pytest.raises(errors.StorageExhaustedError):
        p.publish(step=3)
    assert committed_versions_equal(pdir, [1, 2])
    # the prior committed version folds bitwise despite the failure
    got = pub.load_version(pdir, 2)
    for name in want:
        assert name in got
    assert _tmp_residue(pdir) == []
    # no uncommitted carcass dir either
    for entry in os.listdir(pdir):
        full = os.path.join(pdir, entry)
        if os.path.isdir(full):
            assert os.path.exists(
                os.path.join(full, pub.COMMIT_NAME)
            ), f"torn uncommitted dir {entry} survived"
    # and the plane heals: the next publish commits normally
    faults.clear()
    assert p.publish(step=4) == 3


def committed_versions_equal(pdir, want):
    return pub.committed_versions(pdir) == want


def test_publisher_freeze_skips_and_thaw_carries_everything(tmp_path):
    tr = _Trainer()
    pdir = str(tmp_path / "pub")
    p = pub.ModelPublisher(pdir, main_program=tr.main, scope=tr.scope)
    assert p.publish(step=1) == 1
    p.freeze(reason="disk_pressure")
    p.freeze(reason="disk_pressure")  # idempotent
    tr.step()
    assert p.publish(step=2) is None
    assert _counter("publish.skipped_frozen") == 1
    assert _counter("publish.freezes") == 1
    assert _counter("publish.freezes.disk_pressure") == 1
    assert pub.committed_versions(pdir) == [1]
    p.unfreeze()
    v = p.publish(step=3)
    assert v == 2
    # the frozen window's training is all in the thaw bundle: folding v2
    # matches the live scope bitwise
    folded = pub.load_version(pdir, 2)
    for name, arr in folded.items():
        live = tr.scope.find_var(name)
        if live is not None:
            assert np.asarray(live).tobytes() == np.asarray(arr).tobytes()


# ---------------------------------------------------------------------------
# RetentionManager: per-plane GC
# ---------------------------------------------------------------------------


def _fake_checkpoint(root, n, nbytes=4000, base=None):
    d = os.path.join(root, f"__paddle_checkpoint__{n}")
    os.makedirs(d, exist_ok=True)
    with open(os.path.join(d, "payload"), "wb") as f:
        f.write(b"x" * nbytes)
    with open(os.path.join(d, "commit.json"), "w") as f:
        json.dump({"checkpoint_no": n}, f)
    if base is not None:
        with open(os.path.join(d, "delta.json"), "w") as f:
            json.dump({"base_checkpoint_no": base}, f)


def test_gc_checkpoint_budget_spares_chain_ancestors(tmp_path):
    ck = str(tmp_path / "ck")
    for n, base in ((0, None), (1, None), (2, None), (3, 2)):
        _fake_checkpoint(ck, n, base=base)
    rm = storage.RetentionManager().add_checkpoint_plane(
        ck, budget_bytes=10000
    )
    freed = rm.collect()
    assert freed > 0
    left = sorted(os.listdir(ck))
    # 0 and 1 rotate; 2 survives the budget because delta 3 chains on it
    assert left == ["__paddle_checkpoint__2", "__paddle_checkpoint__3"]
    assert _counter("storage.gc_bytes_freed") == freed
    assert _counter("storage.gc_bytes_freed.checkpoint") == freed
    assert _counter("storage.gc_runs") == 1
    table = obs.get_tables()["storage.gc"]["actions"]
    assert table[-1]["plane"] == "checkpoint"
    assert table[-1]["freed"] == freed


def test_gc_publish_protects_live_subscriber_chain(tmp_path):
    pdir = str(tmp_path / "pub")
    os.makedirs(pdir)
    for v in range(1, 6):
        vdir = pub.version_dir(pdir, v)
        io.save_arrays(vdir, {"w": np.full(64, v, np.float32)},
                       filename=pub.PAYLOAD_NAME)
        commit = {"version": v, "kind": "full" if v in (1, 4) else "delta",
                  "base": None if v in (1, 4) else v - 1,
                  "created_at": 0.0}
        io._atomic_write(
            os.path.join(vdir, pub.COMMIT_NAME),
            lambda f, c=commit: f.write(json.dumps(c).encode()),
        )
    hb = tmp_path / "hb"
    hb.mkdir()
    # a live subscriber's beat stamps model_version 2 — its chain {1, 2}
    # must survive even though keep=1 only covers {4, 5}
    (hb / "hb_rank0").write_text(
        json.dumps({"rank": 0, "step": 9, "model_version": 2})
    )
    rm = storage.RetentionManager().add_publish_plane(
        pdir, keep=1, heartbeat_dir=str(hb)
    )
    freed = rm.collect()
    assert freed > 0
    assert pub.committed_versions(pdir) == [1, 2, 4, 5]
    # the spared chains still fold
    pub.load_version(pdir, 2)
    pub.load_version(pdir, 5)


def test_gc_telemetry_and_flight_planes(tmp_path, monkeypatch):
    tl = tmp_path / "tl"
    tl.mkdir()
    old = time.time() - 3600
    (tl / "telemetry_rank0.jsonl").write_bytes(b"live")
    (tl / "telemetry_rank1.jsonl.1").write_bytes(b"x" * 100)
    os.utime(tl / "telemetry_rank1.jsonl.1", (old, old))
    (tl / "telemetry_rank0.jsonl.1").write_bytes(b"fresh-rotated")
    # flight: black box + 4 trigger dumps, two of them aged
    (tl / "flight_rank0.json").write_bytes(b"blackbox")
    for i, age in enumerate((0, 0, 7200, 7200)):
        p = tl / f"flight_rank0.t{i}.json"
        p.write_bytes(b"y" * 10)
        if age:
            os.utime(p, (time.time() - age, time.time() - age))
    rm = (storage.RetentionManager()
          .add_telemetry_plane(str(tl), dead_after_s=300.0)
          .add_flight_plane(str(tl), keep=8, max_age_s=3600.0))
    freed = rm.collect()
    assert freed == 100 + 20
    names = set(os.listdir(tl))
    assert "telemetry_rank0.jsonl" in names          # live shard kept
    assert "telemetry_rank0.jsonl.1" in names        # fresh rotation kept
    assert "telemetry_rank1.jsonl.1" not in names    # dead writer's GC'd
    assert "flight_rank0.json" in names              # black box sacred
    assert "flight_rank0.t0.json" in names
    assert "flight_rank0.t2.json" not in names       # aged dumps GC'd
    # emergency mode sweeps rotated shards regardless of age
    rm.collect(emergency=True)
    assert "telemetry_rank0.jsonl.1" not in set(os.listdir(tl))


def test_gc_policy_failure_does_not_stop_other_planes(tmp_path):
    tl = tmp_path / "tl"
    tl.mkdir()
    old = time.time() - 3600
    (tl / "telemetry_rank9.jsonl.1").write_bytes(b"x" * 64)
    os.utime(tl / "telemetry_rank9.jsonl.1", (old, old))

    def broken(emergency=False):
        raise RuntimeError("boom")

    rm = (storage.RetentionManager()
          .add_plane("broken", broken)
          .add_telemetry_plane(str(tl)))
    assert rm.collect() == 64
    assert _counter("storage.gc_failures") == 1


# ---------------------------------------------------------------------------
# the degradation ladder
# ---------------------------------------------------------------------------


class _FakeCkpt:
    degraded = None

    def set_storage_degraded(self, active):
        self.degraded = active


class _FakePub:
    frozen = False
    reason = None

    def freeze(self, reason=None):
        self.frozen, self.reason = True, reason

    def unfreeze(self):
        self.frozen = False


class _FakeTl:
    max_bytes = 8 << 20
    paused = False

    def pause(self):
        self.paused = True

    def resume(self):
        self.paused = False


class _FakeRec:
    disk = True

    def suspend_disk(self):
        self.disk = False

    def resume_disk(self):
        self.disk = True


def test_pressure_ladder_rungs_and_recovery(tmp_path):
    ck = tmp_path / "ck"
    m = storage.StorageMonitor(soft_bytes=1000, hard_bytes=500,
                               critical_bytes=100, rearm=1.2, probe=False)
    m.add_root("checkpoint", str(ck), budget_bytes=2000).install()
    fck, fpb, ftl, frc = _FakeCkpt(), _FakePub(), _FakeTl(), _FakeRec()
    junk = ck / "junk"
    rm = storage.RetentionManager().add_plane(
        "junk",
        lambda e=False: (
            (junk.stat().st_size, junk.unlink())[0]
            if junk.exists() else 0
        ),
    )
    c = storage.StoragePressureController(
        m, retention=rm, checkpointer=fck, publish_control=fpb,
        telemetry=ftl, recorder=frc,
    )
    assert c.poll() == storage.OK
    junk.write_bytes(b"x" * 1200)               # free 800: SOFT
    assert c.poll() == storage.SOFT
    assert fck.degraded is True
    assert ftl.max_bytes == c.soft_journal_bytes
    assert not fpb.frozen and not ftl.paused and frc.disk
    junk.write_bytes(b"x" * 1600)               # free 400: HARD
    assert c.poll() == storage.HARD
    assert fpb.frozen and fpb.reason == "disk_pressure"
    assert ftl.paused and not frc.disk
    assert not junk.exists()                    # emergency GC ran
    assert _counter("storage.gc_runs") == 1
    # GC freed the space: the next poll re-arms all the way down
    assert c.poll() == storage.OK
    assert fck.degraded is False
    assert not fpb.frozen and not ftl.paused and frc.disk
    assert ftl.max_bytes == 8 << 20
    assert _counter("storage.escalations") == 2
    assert _counter("storage.recoveries") == 1


def test_ladder_critical_takes_one_flight_dump(tmp_path):
    from paddle_tpu.observability import recorder as rec_mod

    tl = str(tmp_path / "tl")
    recorder = FlightRecorder(directory=tl, rank=0).start()
    try:
        ck = tmp_path / "ck"
        m = storage.StorageMonitor(soft_bytes=1000, hard_bytes=500,
                                   critical_bytes=100, probe=False)
        m.add_root("checkpoint", str(ck), budget_bytes=2000).install()
        c = storage.StoragePressureController(m, recorder=recorder)
        (ck / "junk").write_bytes(b"x" * 1950)  # free 50: CRITICAL
        assert c.poll() == storage.CRITICAL
        assert c.poll() == storage.CRITICAL     # still only ONE dump
        dump = os.path.join(tl, "flight_rank0.disk_pressure.json")
        assert os.path.exists(dump)
        with open(dump) as f:
            bundle = json.load(f)
        assert bundle["trigger"] == "disk_pressure"
        assert bundle["detail"]["level"] == "critical"
        assert _counter("telemetry.flight_dumps.disk_pressure") == 1
    finally:
        recorder.stop()
        assert rec_mod.get_recorder() is None


def test_async_checkpointer_storage_degraded_forces_delta(
    tmp_path, fresh_programs
):
    exe, loss = _build_model()
    fleet = _fleet()
    rng = np.random.RandomState(11)
    path = str(tmp_path / "ck")
    with fc.AsyncCheckpointer(fleet, path, executor=exe, delta=True,
                              full_every=2) as saver:
        _step(exe, loss, rng)
        assert saver.save(fc.TrainStatus(0, global_step=1)).result(30) == 0
        saver.set_storage_degraded(True)
        # full_every=2 would force a full here; degraded defers to delta
        for step in (2, 3, 4):
            _step(exe, loss, rng)
            saver.save(fc.TrainStatus(0, global_step=step)).result(30)
        assert _counter("checkpoint.full_saves") == 1
        assert _counter("checkpoint.delta_saves") == 3
        assert _counter("checkpoint.storage_degraded") == 1
        saver.set_storage_degraded(False)
        _step(exe, loss, rng)
        saver.save(fc.TrainStatus(0, global_step=5)).result(30)
        # cadence resumed: well past full_every, this one is full
        assert _counter("checkpoint.full_saves") == 2
        assert _counter("checkpoint.storage_restored") == 1
    # the degraded chain still resumes
    status = fleet.load_check_point(exe, path)
    assert status.global_step == 5


# ---------------------------------------------------------------------------
# flight ring + watcher findings
# ---------------------------------------------------------------------------


def test_flight_trigger_dumps_are_a_bounded_ring(tmp_path, monkeypatch):
    monkeypatch.setenv("PADDLE_TPU_FLIGHT_KEEP", "3")
    tl = str(tmp_path / "tl")
    r = FlightRecorder(directory=tl, rank=0)
    r.start(register=False)
    try:
        now = time.time()
        for i in range(6):
            p = r.dump(f"t{i}")
            # backdate into the PAST with increasing offsets: the dump
            # being written always carries the newest real mtime, so the
            # in-dump prune never eats its own fresh file
            t = now - 1000 + i * 10
            os.utime(p, (t, t))
        names = sorted(
            f for f in os.listdir(tl)
            if f.startswith("flight_rank0.") and f != "flight_rank0.json"
        )
        assert names == [
            "flight_rank0.t3.json", "flight_rank0.t4.json",
            "flight_rank0.t5.json",
        ]
        assert os.path.exists(os.path.join(tl, "flight_rank0.json"))
        assert _counter("telemetry.flight_pruned") >= 3
    finally:
        r.stop()


def test_recorder_suspend_disk_keeps_sampling(tmp_path):
    tl = str(tmp_path / "tl")
    r = FlightRecorder(directory=tl, rank=0, interval=0.05)
    r.start(register=False)
    try:
        r.suspend_disk()
        time.sleep(0.15)
        blackbox = os.path.join(tl, "flight_rank0.json")
        mtime0 = (os.path.getmtime(blackbox)
                  if os.path.exists(blackbox) else None)
        obs.add("some.counter")
        time.sleep(0.15)
        if mtime0 is not None:
            assert os.path.getmtime(blackbox) == mtime0
        # an explicit dump still writes even while disk-suspended
        assert r.dump("manual") is not None
        r.resume_disk()
    finally:
        r.stop()


def test_watcher_emits_disk_pressure_findings(tmp_path):
    ck = tmp_path / "ck"
    m = storage.StorageMonitor(soft_bytes=1000, hard_bytes=500,
                               critical_bytes=100, probe=False)
    m.add_root("checkpoint", str(ck), budget_bytes=2000)
    w = Watcher(storage_monitor=m)
    assert w.poll() == []
    (ck / "junk").write_bytes(b"x" * 1700)     # free 300: HARD
    findings = w.poll()
    assert len(findings) == 1
    f = findings[0]
    assert f["kind"] == "disk_pressure"
    assert f["severity"] == "error"
    assert f["detail"]["root"] == "checkpoint"
    assert f["detail"]["level"] == "hard"
    assert f["detail"]["free_bytes"] == 300
    # the latch is the monitor's hysteresis: no repeat finding while held
    assert w.poll() == []
    assert _counter("watch.findings.disk_pressure") == 1


# ---------------------------------------------------------------------------
# the offline storage digest (tools/fleet_report.py)
# ---------------------------------------------------------------------------


def _load_tool(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(_ROOT, "tools", f"{name}.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_fleet_report_storage_digest(tmp_path):
    tl = str(tmp_path / "tl")
    p = TelemetryPublisher(directory=tl, rank=0, interval=3600.0)
    p.start(register=False)
    obs.set_gauge("storage.free_bytes.checkpoint", 5000.0)
    obs.set_gauge("storage.pressure", 0.0)
    p.publish()
    obs.set_gauge("storage.free_bytes.checkpoint", 300.0)
    obs.set_gauge("storage.pressure", 2.0)
    obs.add("storage.escalations")
    obs.add("storage.gc_bytes_freed", 4096)
    obs.set_table("storage.gc", {"actions": [
        {"plane": "checkpoint", "freed": 4096, "t": time.time(),
         "emergency": True},
    ]})
    p.publish()
    obs.set_gauge("storage.pressure", 0.0)
    obs.add("storage.recoveries")
    p.publish()
    p.stop()
    fleet_report = _load_tool("fleet_report")
    report = fleet_report.build_report(tl)
    sto = report["fleet"]["storage"]
    assert sto["gc_bytes_freed_total"] == 4096
    assert sto["escalations_total"] == 1
    assert sto["recoveries_total"] == 1
    rank0 = sto["per_rank"]["0"]
    assert rank0["free_bytes"] == {"checkpoint": 300}
    assert rank0["pressure"] == 0
    assert rank0["gc_actions"][-1]["plane"] == "checkpoint"
    # the pressure timeline replays every gauge move: 0 -> 2 -> 0
    curve = sto["pressure_timeline"]["0"]
    assert [lvl for _t, lvl in curve] == [0, 2, 0]
    # and the human rendering names the digest
    text = fleet_report.render(report)
    assert "storage:" in text
    assert "ok -> hard -> ok" in text
