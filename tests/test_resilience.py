"""Resilience subsystem: retry/backoff math, deterministic fault injection,
durable-checkpoint verification + fallback, hang-proof dataloader pool,
elastic launcher.

Clock-dependent retry behavior is tested against stubbed sleep/clock/rng so
the assertions are exact (no wall-clock flake); checkpoint corruption is
real torn bytes on disk, not mocks.
"""

import glob
import os
import subprocess
import sys
import time

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import errors, layers, observability, resilience
from paddle_tpu.dataloader.dataloader_iter import _WorkerPool
from paddle_tpu.framework import unique_name
from paddle_tpu.resilience import faults, retry
from paddle_tpu.resilience.retry import backoff_delay

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)


class _NoJitterRng:
    """rng stub whose uniform(0, cap) returns cap: the deterministic
    backoff envelope."""

    def uniform(self, a, b):
        return b


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.clear()
    yield
    faults.clear()


# -- retry/backoff math ------------------------------------------------------
def test_backoff_delay_exponential_and_capped():
    assert [backoff_delay(n, 0.1, 30.0) for n in (1, 2, 3, 4)] == [
        0.1, 0.2, 0.4, 0.8,
    ]
    assert backoff_delay(20, 0.1, 30.0) == 30.0  # cap
    # full jitter stays within [0, envelope]
    import random

    rng = random.Random(3)
    for n in range(1, 12):
        d = backoff_delay(n, 0.1, 30.0, rng)
        assert 0.0 <= d <= backoff_delay(n, 0.1, 30.0)


def test_retry_backoff_sequence_and_counters():
    slept, calls = [], []
    policy = resilience.retry(
        max_attempts=4, base_delay=0.1, max_delay=30.0,
        sleep=slept.append, rng=_NoJitterRng(), name="t",
    )

    def flaky():
        calls.append(1)
        if len(calls) < 4:
            raise OSError("transient")
        return "ok"

    c0 = observability.snapshot()["counters"]
    assert policy.call(flaky) == "ok"
    assert slept == [0.1, 0.2, 0.4]  # exponential, one per retry
    c1 = observability.snapshot()["counters"]
    assert c1.get("resilience.retries", 0) - c0.get("resilience.retries", 0) == 3
    assert c1.get("resilience.retries.t", 0) == 3


def test_retry_exhausts_attempts_and_gives_up():
    slept = []
    policy = resilience.retry(
        max_attempts=3, base_delay=0.1, sleep=slept.append,
        rng=_NoJitterRng(), name="g",
    )
    c0 = observability.snapshot()["counters"].get("resilience.giveups", 0)
    with pytest.raises(OSError):
        policy.call(lambda: (_ for _ in ()).throw(OSError("always")))
    assert len(slept) == 2  # attempts-1 sleeps, then the give-up
    c1 = observability.snapshot()["counters"]
    assert c1.get("resilience.giveups", 0) - c0 == 1
    assert c1.get("resilience.giveups.g", 0) >= 1


def test_retry_deadline_stops_before_sleeping_past_it():
    """Stubbed monotonic clock: the policy must refuse a retry whose
    backoff would land past the overall deadline."""
    now = [100.0]
    slept = []

    def sleep(s):
        slept.append(s)
        now[0] += s

    policy = resilience.retry(
        max_attempts=100, base_delay=1.0, max_delay=1.0, deadline=2.5,
        sleep=sleep, clock=lambda: now[0], rng=_NoJitterRng(),
    )
    with pytest.raises(OSError):
        policy.call(lambda: (_ for _ in ()).throw(OSError("x")))
    # t=0: fail, sleep 1 (ok, 1 <= 2.5); t=1: fail, sleep 1 (ok, 2 <= 2.5);
    # t=2: fail, next sleep would end at 3 > 2.5 -> give up
    assert slept == [1.0, 1.0]


def test_retry_non_retryable_raises_immediately():
    calls = []

    def bad():
        calls.append(1)
        raise ValueError("logic bug, not transient")

    c0 = observability.snapshot()["counters"].get("resilience.giveups", 0)
    with pytest.raises(ValueError):
        resilience.retry(max_attempts=10, sleep=lambda s: None).call(bad)
    assert len(calls) == 1
    # a first-try ordinary failure is not an abandoned retry budget
    assert observability.snapshot()["counters"].get(
        "resilience.giveups", 0
    ) == c0


def test_inject_wins_over_pending_env_config(monkeypatch):
    """A programmatic inject() before the first fault_point must not be
    clobbered by the lazy env load."""
    monkeypatch.setenv(faults.FAULT_ENV_VAR, "t.prec:io:1.0:0:5")
    faults._env_loaded = False  # simulate a fresh process, env unread
    faults.inject("t.prec", "unavailable", prob=1.0, max_fires=1)
    with pytest.raises(errors.UnavailableError):
        faults.fault_point("t.prec")
    faults.fault_point("t.prec")  # max_fires=1 honored, not env's 5


def test_retry_classifier_honors_retryable_attribute():
    # CheckpointCorruptionError IS an OSError but opts out via .retryable
    assert not resilience.default_retryable(
        errors.CheckpointCorruptionError("corrupt")
    )
    assert resilience.default_retryable(errors.UnavailableError("down"))
    assert resilience.default_retryable(ConnectionError("reset"))
    assert not resilience.default_retryable(ValueError("bug"))


def test_retry_attempt_iterator_shape():
    slept, tries = [], []
    for attempt in resilience.retry(
        max_attempts=3, base_delay=0.05, sleep=slept.append,
        rng=_NoJitterRng(),
    ):
        with attempt:
            tries.append(attempt.number)
            if attempt.number < 2:
                raise OSError("flaky")
    assert tries == [1, 2]
    assert slept == [0.05]


def test_retry_per_attempt_timeout():
    """A hung attempt is abandoned by the watchdog; once it drains during
    the backoff, the retry runs (and succeeds)."""
    c0 = observability.snapshot()["counters"].get("resilience.retries", 0)
    done = []

    def slow_then_fast():
        if not done:
            done.append(1)
            time.sleep(0.6)  # outlives the 0.2s watchdog, ends in backoff
            return "slow"
        return "fast"

    policy = resilience.retry(
        max_attempts=2, base_delay=1.0, attempt_timeout=0.2,
        rng=_NoJitterRng(),
    )
    assert policy.call(slow_then_fast) == "fast"
    c1 = observability.snapshot()["counters"].get("resilience.retries", 0)
    assert c1 - c0 == 1


def test_retry_timeout_refuses_concurrent_duplicate_attempt():
    """If the abandoned attempt is STILL running after the backoff, the
    policy gives up instead of running two copies of fn concurrently
    (torn-write hazard for non-reentrant operations)."""
    policy = resilience.retry(
        max_attempts=5, base_delay=0.05, max_delay=0.05,
        attempt_timeout=0.1, rng=_NoJitterRng(),
    )
    t0 = time.monotonic()
    with pytest.raises(errors.ExecutionTimeoutError):
        policy.call(lambda: time.sleep(3))
    assert time.monotonic() - t0 < 2.0  # gave up, did not wait out the hang


# -- fault injection ---------------------------------------------------------
def test_fault_injection_deterministic_by_seed():
    def pattern(seed):
        faults.clear()
        faults.inject("t.det", "io", prob=0.5, seed=seed)
        out = []
        for _ in range(64):
            try:
                faults.fault_point("t.det")
                out.append(0)
            except OSError:
                out.append(1)
        return out

    a, b, c = pattern(42), pattern(42), pattern(7)
    assert a == b  # same seed, same pattern
    assert a != c  # different seed, different pattern
    assert 0 < sum(a) < 64  # actually probabilistic


def test_fault_env_syntax_and_kinds():
    specs = faults.reload_env(
        "io.save:io:1.0:0:1,x.y:unavailable:0.5:9;z.w:timeout"
    )
    assert len(specs) == 3
    by_site = faults.specs()
    assert by_site["io.save"].max_fires == 1
    assert by_site["x.y"].prob == 0.5 and by_site["x.y"].seed == 9
    assert by_site["z.w"].kind == "timeout" and by_site["z.w"].prob == 1.0
    with pytest.raises(errors.ExecutionTimeoutError):
        faults.fault_point("z.w")
    with pytest.raises(errors.ExternalError):
        faults.fault_point("io.save")
    faults.fault_point("io.save")  # max_fires=1: second call clean
    with pytest.raises(ValueError):
        faults.parse_spec("siteonly")
    with pytest.raises(ValueError):
        faults.parse_spec("a.b:nosuchkind")


def test_fault_max_fires_heals():
    faults.inject("t.heal", "unavailable", prob=1.0, max_fires=2)
    fired = 0
    for _ in range(6):
        try:
            faults.fault_point("t.heal")
        except errors.UnavailableError:
            fired += 1
    assert fired == 2


def test_fault_seam_in_local_fs(tmp_path):
    from paddle_tpu.fleet.fs_wrapper import LocalFS

    src = tmp_path / "src"
    src.mkdir()
    (src / "f").write_text("x")
    faults.inject("fs.upload", "io", prob=1.0, max_fires=1)
    fs = LocalFS()
    with pytest.raises(errors.ExternalError):
        fs.upload(str(src), str(tmp_path / "dst"))
    fs.upload(str(src), str(tmp_path / "dst"))  # healed
    assert (tmp_path / "dst" / "f").read_text() == "x"


def test_fault_seam_in_collective_dispatch():
    """An armed collective.dispatch fault aborts program tracing with the
    typed error (a peer dropping out mid-compile)."""
    faults.inject("collective.dispatch", "unavailable", prob=1.0)
    main, startup = fluid.Program(), fluid.Program()
    scope = fluid.framework.scope.Scope()
    with fluid.program_guard(main, startup), fluid.scope_guard(scope), \
            unique_name.guard():
        x = fluid.data("x", [8, 4])
        y = layers.fc(x, 2)
        loss = layers.mean(y)
        from paddle_tpu.fleet import collective as fc
        from paddle_tpu.fleet.role_maker import UserDefinedRoleMaker

        fleet = fc.Fleet()
        fleet.init(UserDefinedRoleMaker())
        opt = fleet.distributed_optimizer(fluid.optimizer.SGD(0.1))
        opt.minimize(loss)
        exe = fluid.Executor()
        exe.run(startup)
        with pytest.raises(errors.UnavailableError):
            exe.run(
                main,
                feed={"x": np.ones((8, 4), np.float32)},
                fetch_list=[loss],
            )


# -- durable checkpoints -----------------------------------------------------
def _build_ckpt_model():
    x = fluid.data("x", [-1, 4])
    y = layers.fc(x, 2, param_attr=fluid.ParamAttr(name="rs_w"))
    loss = layers.mean(y)
    fluid.optimizer.SGD(0.1).minimize(loss)
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    return exe, loss


@pytest.fixture
def fresh_programs():
    main, startup = fluid.Program(), fluid.Program()
    scope = fluid.framework.scope.Scope()
    with fluid.program_guard(main, startup), fluid.scope_guard(scope), \
            unique_name.guard():
        yield main


def test_save_writes_manifest_and_load_verifies(tmp_path, fresh_programs):
    exe, loss = _build_ckpt_model()
    scope = fluid.framework.scope.global_scope()
    model = str(tmp_path / "m" / "model")
    fluid.io.save(fluid.default_main_program(), model)
    assert os.path.exists(model + ".manifest.json")
    w = np.asarray(scope.find_var("rs_w")).copy()
    scope.set_var("rs_w", np.zeros_like(w))
    fluid.io.load(fluid.default_main_program(), model)
    np.testing.assert_allclose(np.asarray(scope.find_var("rs_w")), w)

    # torn pdparams (truncate mid-file) -> typed error, scope untouched
    before = np.asarray(scope.find_var("rs_w")).copy()
    p = model + ".pdparams"
    blob = open(p, "rb").read()
    open(p, "wb").write(blob[: len(blob) // 2])
    with pytest.raises(errors.CheckpointCorruptionError):
        fluid.io.load(fluid.default_main_program(), model)
    np.testing.assert_allclose(np.asarray(scope.find_var("rs_w")), before)


def test_truncated_npz_detected_before_scope_mutation(tmp_path, fresh_programs):
    exe, _ = _build_ckpt_model()
    scope = fluid.framework.scope.global_scope()
    d = str(tmp_path / "vars")
    fluid.io.save_persistables(exe, d)
    assert os.path.exists(os.path.join(d, "manifest.json"))
    npz = os.path.join(d, "__params__.npz")
    blob = open(npz, "rb").read()
    open(npz, "wb").write(blob[: len(blob) // 2])
    before = np.asarray(scope.find_var("rs_w")).copy()
    with pytest.raises(errors.CheckpointCorruptionError):
        fluid.io.load_persistables(exe, d)
    np.testing.assert_allclose(np.asarray(scope.find_var("rs_w")), before)


def test_manifest_crc_mismatch_detected(tmp_path, fresh_programs):
    import json

    exe, _ = _build_ckpt_model()
    d = str(tmp_path / "vars")
    fluid.io.save_persistables(exe, d)
    mpath = os.path.join(d, "manifest.json")
    manifest = json.load(open(mpath))
    name = next(iter(manifest["arrays"]))
    manifest["arrays"][name]["crc32"] ^= 0xDEADBEEF
    json.dump(manifest, open(mpath, "w"))
    with pytest.raises(errors.CheckpointCorruptionError) as ei:
        fluid.io.load_persistables(exe, d)
    assert "crc32 mismatch" in str(ei.value)


def test_fleet_falls_back_to_newest_valid_checkpoint(tmp_path, fresh_programs):
    from paddle_tpu.fleet import collective as fc
    from paddle_tpu.fleet.role_maker import UserDefinedRoleMaker

    exe, loss = _build_ckpt_model()
    scope = fluid.framework.scope.global_scope()
    fleet = fc.Fleet()
    fleet.init(UserDefinedRoleMaker())
    path = str(tmp_path / "ckpts")
    ws = []
    for epoch in range(3):
        exe.run(
            feed={"x": np.ones((2, 4), np.float32)}, fetch_list=[loss]
        )
        ws.append(np.asarray(scope.find_var("rs_w")).copy())
        assert fleet.save_check_point(
            exe, path, fc.TrainStatus(epoch)
        ) == epoch

    # tear the NEWEST checkpoint's payload mid-array
    (npz,) = glob.glob(os.path.join(path, "__paddle_checkpoint__2", "*.npz"))
    blob = open(npz, "rb").read()
    open(npz, "wb").write(blob[: len(blob) // 2])

    c0 = observability.snapshot()["counters"]
    status = fleet.load_check_point(exe, path)
    assert status.next() == 2  # fell back to epoch 1's checkpoint
    np.testing.assert_allclose(np.asarray(scope.find_var("rs_w")), ws[1])
    c1 = observability.snapshot()["counters"]
    assert c1.get("resilience.checkpoint_fallbacks", 0) > c0.get(
        "resilience.checkpoint_fallbacks", 0
    )

    # an explicitly requested corrupt number must NOT fall back
    with pytest.raises(errors.CheckpointCorruptionError):
        fleet.load_check_point(exe, path, checkpoint_no=2)


def test_fleet_save_sweeps_stale_tmp_dirs(tmp_path, fresh_programs):
    from paddle_tpu.fleet import collective as fc
    from paddle_tpu.fleet.role_maker import UserDefinedRoleMaker

    exe, _ = _build_ckpt_model()
    fleet = fc.Fleet()
    fleet.init(UserDefinedRoleMaker())
    path = str(tmp_path / "ckpts")
    os.makedirs(os.path.join(path, "__paddle_checkpoint__7.tmp"))
    assert fleet.save_check_point(exe, path, fc.TrainStatus(0)) == 0
    assert not os.path.exists(
        os.path.join(path, "__paddle_checkpoint__7.tmp")
    )
    assert os.path.isdir(os.path.join(path, "__paddle_checkpoint__0"))


def test_fleet_save_retries_transient_fs_fault(tmp_path, fresh_programs):
    from paddle_tpu.fleet import collective as fc
    from paddle_tpu.fleet.role_maker import UserDefinedRoleMaker

    exe, _ = _build_ckpt_model()
    fleet = fc.Fleet()
    fleet.init(UserDefinedRoleMaker())
    faults.inject("fs.upload", "io", prob=1.0, max_fires=1)
    c0 = observability.snapshot()["counters"].get(
        "resilience.retries.checkpoint.save", 0
    )
    path = str(tmp_path / "ckpts")
    assert fleet.save_check_point(exe, path, fc.TrainStatus(0)) == 0
    status = fleet.load_check_point(exe, path)
    assert status.next() == 1
    c1 = observability.snapshot()["counters"].get(
        "resilience.retries.checkpoint.save", 0
    )
    assert c1 - c0 >= 1


def test_missing_pdparams_with_manifest_is_corruption(tmp_path, fresh_programs):
    """A published manifest whose payload vanished (torn publish) is typed
    corruption, same as the npz path — callers' fallback handling works."""
    _build_ckpt_model()
    model = str(tmp_path / "m" / "model")
    fluid.io.save(fluid.default_main_program(), model)
    os.remove(model + ".pdparams")
    with pytest.raises(errors.CheckpointCorruptionError, match="torn publish"):
        fluid.io.load(fluid.default_main_program(), model)


def test_fleet_publish_idempotent_when_mv_lands_but_reports_failure(
    tmp_path, fresh_programs
):
    """fs.mv applied remotely but reported failure (response lost): the
    retry must notice the checkpoint already exists instead of mv-ing the
    tmp dir INSIDE it."""
    from paddle_tpu.fleet import collective as fc
    from paddle_tpu.fleet.fs_wrapper import LocalFS
    from paddle_tpu.fleet.role_maker import UserDefinedRoleMaker

    class FlakyMvFS(LocalFS):
        def __init__(self):
            self.tripped = False

        def mv(self, src, dst):
            super().mv(src, dst)
            if not self.tripped:
                self.tripped = True
                raise errors.UnavailableError("rename applied, response lost")

    exe, _ = _build_ckpt_model()
    fleet = fc.Fleet()
    fleet.init(UserDefinedRoleMaker())
    path = str(tmp_path / "ckpts")
    assert fleet.save_check_point(
        exe, path, fc.TrainStatus(0), fs=FlakyMvFS()
    ) == 0
    inner = os.listdir(os.path.join(path, "__paddle_checkpoint__0"))
    assert not any(d.endswith(".tmp") for d in inner), inner
    status = fleet.load_check_point(exe, path)
    assert status.next() == 1


# -- hang-proof worker pool --------------------------------------------------
def test_worker_pool_get_after_close_raises():
    pool = _WorkerPool(lambda idxs: idxs, num_workers=2, capacity=4)
    pool.close()
    with pytest.raises(RuntimeError, match="closed"):
        pool.get(0)


@pytest.mark.filterwarnings(
    "ignore::pytest.PytestUnhandledThreadExceptionWarning"
)
def test_worker_pool_all_workers_dead_raises():
    pool = _WorkerPool(
        lambda idxs: idxs, num_workers=2, capacity=4,
        worker_init_fn=lambda wid: (_ for _ in ()).throw(SystemExit),
    )
    for t in pool._threads:
        t.join(5)
    pool.submit(0, [1])
    with pytest.raises(RuntimeError, match="workers are dead"):
        pool.get(0)
    pool.close()


@pytest.mark.filterwarnings(
    "ignore::pytest.PytestUnhandledThreadExceptionWarning"
)
def test_worker_pool_dead_worker_batch_resubmitted_once():
    c0 = observability.snapshot()["counters"].get(
        "resilience.worker_resubmits", 0
    )
    state = {"deaths": 0}

    def fetch(idxs):
        if state["deaths"] < 1:
            state["deaths"] += 1
            raise SystemExit  # kills this worker thread outright
        return sum(idxs)

    pool = _WorkerPool(fetch, num_workers=2, capacity=4)
    pool.submit(0, [1, 2, 3])
    assert pool.get(0) == 6  # resubmitted to the surviving worker
    c1 = observability.snapshot()["counters"].get(
        "resilience.worker_resubmits", 0
    )
    assert c1 - c0 == 1
    pool.close()


def test_worker_pool_get_timeout():
    pool = _WorkerPool(
        lambda idxs: time.sleep(30), num_workers=1, capacity=2
    )
    pool.submit(0, [1])
    t0 = time.monotonic()
    with pytest.raises(errors.ExecutionTimeoutError):
        pool.get(0, timeout=0.3)
    assert time.monotonic() - t0 < 5.0
    pool.close()


def test_worker_pool_ordinary_exception_still_surfaces():
    def fetch(idxs):
        raise ValueError("bad sample")

    pool = _WorkerPool(fetch, num_workers=2, capacity=4)
    pool.submit(0, [1])
    with pytest.raises(ValueError, match="bad sample"):
        pool.get(0)
    pool.close()


def test_dataloader_retries_injected_fetch_faults():
    from paddle_tpu.dataloader.dataset import Dataset

    class DS(Dataset):
        def __getitem__(self, i):
            return np.float32(i)

        def __len__(self):
            return 32

    faults.inject("dataloader.fetch", "io", prob=1.0, seed=0, max_fires=2)
    c0 = observability.snapshot()["counters"].get(
        "resilience.retries.dataloader.fetch", 0
    )
    loader = fluid.DataLoader(
        DS(), batch_size=4, num_workers=2, use_buffer_reader=False,
        return_list=True,
    )
    batches = [np.asarray(b) for b in loader]
    assert len(batches) == 8
    np.testing.assert_allclose(
        np.sort(np.concatenate(batches)), np.arange(32, dtype=np.float32)
    )
    c1 = observability.snapshot()["counters"].get(
        "resilience.retries.dataloader.fetch", 0
    )
    assert c1 - c0 == 2


# -- elastic launcher --------------------------------------------------------
def test_elastic_launcher_restarts_dead_child(tmp_path):
    """A non-rank-0 child that fails once is restarted (with the attempt
    number in its env) and the pod completes with rc 0."""
    script = tmp_path / "worker.py"
    script.write_text(
        "import os, sys\n"
        "rank = os.environ['PADDLE_TRAINER_ID']\n"
        "marker = os.path.join(%r, 'rank' + rank + '.failed')\n"
        "if rank != '0' and not os.path.exists(marker):\n"
        "    open(marker, 'w').close()\n"
        "    sys.exit(1)\n"
        "print('attempt', os.environ.get('PADDLE_RESTART_ATTEMPT'))\n"
        % str(tmp_path)
    )
    proc = subprocess.run(
        [
            sys.executable, "-m", "paddle_tpu.distributed.launch",
            "--nproc_per_node", "2", "--simulate_cpu", "--elastic",
            "--max_restarts", "2", "--restart_backoff", "0.05",
            "--log_dir", str(tmp_path / "logs"), str(script),
        ],
        cwd=REPO, capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 0, proc.stderr
    assert "restart 1/2" in proc.stderr
    log1 = (tmp_path / "logs" / "worker_1.log").read_text()
    assert "attempt 1" in log1


def test_elastic_launcher_exhausts_restart_budget(tmp_path):
    script = tmp_path / "always_fail.py"
    script.write_text(
        "import os, sys\n"
        "sys.exit(0 if os.environ['PADDLE_TRAINER_ID'] == '0' else 3)\n"
    )
    proc = subprocess.run(
        [
            sys.executable, "-m", "paddle_tpu.distributed.launch",
            "--nproc_per_node", "2", "--simulate_cpu", "--elastic",
            "--max_restarts", "1", "--restart_backoff", "0.05", str(script),
        ],
        cwd=REPO, capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode != 0
    assert "after 1 restart" in proc.stderr
