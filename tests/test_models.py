"""End-to-end model tests (the reference's tests/book/ strategy): build,
train a few steps, assert the loss drops."""

import numpy as np

import paddle_tpu as fluid
from paddle_tpu import layers
from paddle_tpu.models import BertConfig, bert_pretrain
from paddle_tpu.models.resnet import resnet_train_net
from paddle_tpu.optimizer import Adam, SGD


def _run_steps(main, startup, loss, feeder, n=3):
    exe = fluid.Executor()
    scope = fluid.framework.scope.Scope()
    exe.run(startup, scope=scope)
    vals = []
    for i in range(n):
        (lv,) = exe.run(main, feed=feeder(i), fetch_list=[loss], scope=scope)
        vals.append(float(np.asarray(lv).reshape(-1)[0]))
    return vals


def test_resnet18_trains():
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 7
    with fluid.program_guard(main, startup):
        img = fluid.data("image", [4, 3, 32, 32], "float32")
        label = fluid.data("label", [4, 1], "int64")
        loss, acc = resnet_train_net(img, label, depth=18, class_num=10)
        SGD(0.01).minimize(loss, startup)
    rng = np.random.RandomState(0)
    x = rng.randn(4, 3, 32, 32).astype("float32")
    y = rng.randint(0, 10, (4, 1)).astype("int64")
    vals = _run_steps(main, startup, loss, lambda i: {"image": x, "label": y}, n=4)
    assert vals[-1] < vals[0]
    assert np.isfinite(vals).all()


def test_resnet_space_to_depth_stem_trains():
    """r5: the TPU stem variant (s2d(2) + 4x4/s1 conv) trains; kept as an
    option even though it measured neutral on v5e (BASELINE.md negative
    result) — other TPU generations may differ."""
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 7
    with fluid.program_guard(main, startup):
        img = fluid.data("image", [4, 3, 32, 32], "float32")
        label = fluid.data("label", [4, 1], "int64")
        loss, acc = resnet_train_net(img, label, depth=18, class_num=10,
                                     space_to_depth_stem=True)
        SGD(0.01).minimize(loss, startup)
    rng = np.random.RandomState(0)
    x = rng.randn(4, 3, 32, 32).astype("float32")
    y = rng.randint(0, 10, (4, 1)).astype("int64")
    vals = _run_steps(main, startup, loss,
                      lambda i: {"image": x, "label": y}, n=4)
    assert vals[-1] < vals[0]
    assert np.isfinite(vals).all()


def test_bert_tiny_trains():
    cfg = BertConfig.tiny()
    b, s = 2, 16
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 7
    with fluid.program_guard(main, startup):
        ids = fluid.data("ids", [b, s], "int64")
        types = fluid.data("types", [b, s], "int64")
        mask = fluid.data("mask", [b, s], "float32")
        labels = fluid.data("labels", [b, s], "int64")
        loss = bert_pretrain(ids, types, mask, labels, cfg)
        Adam(1e-3).minimize(loss, startup)
    rng = np.random.RandomState(0)
    feed = {
        "ids": rng.randint(0, cfg.vocab_size, (b, s)).astype("int64"),
        "types": rng.randint(0, 2, (b, s)).astype("int64"),
        "mask": np.ones((b, s), "float32"),
        "labels": rng.randint(0, cfg.vocab_size, (b, s)).astype("int64"),
    }
    vals = _run_steps(main, startup, loss, lambda i: feed, n=4)
    assert vals[-1] < vals[0]
    assert np.isfinite(vals).all()


def test_bert_tiny_tensor_parallel_gspmd():
    """TP over mp axis via GSPMD annotations must match the replicated run."""
    from paddle_tpu.models.bert import bert_tp_shardings
    from paddle_tpu.parallel import make_mesh, shard_program

    cfg = BertConfig.tiny()
    cfg.hidden_dropout = cfg.attention_dropout = 0.0  # determinism across modes
    b, s = 2, 16

    def build():
        main, startup = fluid.Program(), fluid.Program()
        main.random_seed = startup.random_seed = 11
        with fluid.program_guard(main, startup):
            ids = fluid.data("ids", [b, s], "int64")
            types = fluid.data("types", [b, s], "int64")
            mask = fluid.data("mask", [b, s], "float32")
            labels = fluid.data("labels", [b, s], "int64")
            loss = bert_pretrain(ids, types, mask, labels, cfg)
        return main, startup, loss

    rng = np.random.RandomState(0)
    feed = {
        "ids": rng.randint(0, cfg.vocab_size, (b, s)).astype("int64"),
        "types": rng.randint(0, 2, (b, s)).astype("int64"),
        "mask": np.ones((b, s), "float32"),
        "labels": rng.randint(0, cfg.vocab_size, (b, s)).astype("int64"),
    }

    main1, startup1, loss1 = build()
    v1 = _run_steps(main1, startup1, loss1, lambda i: feed, n=1)

    main2, startup2, loss2 = build()
    mesh = make_mesh({"dp": 2, "mp": 4})
    shard_program(main2, mesh, bert_tp_shardings(cfg), mode="gspmd")
    v2 = _run_steps(main2, startup2, loss2, lambda i: feed, n=1)
    np.testing.assert_allclose(v1, v2, rtol=2e-4)
