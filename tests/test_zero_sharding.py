"""Cross-replica weight-update sharding (ZeRO, arXiv:2004.13336) +
block-quantized collectives (EQuARX, arXiv:2506.17615).

Fast legs run in-process on the 8-virtual-CPU-device mesh (dp=2 submesh,
where reduce-scatter and allreduce share one deterministic add order, so
fp32 parity is asserted BITWISE); the 2-process gloo golden equivalence —
the MULTICHIP dryrun path — is @slow and drives tests/dist_zero_worker.py
through the real launcher.
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import layers, observability
from paddle_tpu.framework import unique_name
from paddle_tpu.framework.scope import Scope
from paddle_tpu.parallel import make_mesh, shard_program
from paddle_tpu.parallel.transpiler import (
    _SHARD_SUFFIX,
    GradAllReduce,
    ShardedWeightUpdate,
)

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)

B, D, H, STEPS = 8, 16, 32, 5


@pytest.fixture(autouse=True)
def fresh_programs():
    main, startup = fluid.Program(), fluid.Program()
    scope = fluid.framework.scope.Scope()
    with fluid.program_guard(main, startup), fluid.scope_guard(scope), \
            unique_name.guard():
        yield


def _feed(i):
    rng = np.random.RandomState(100 + i)
    return {
        "x": rng.randn(B, D).astype(np.float32),
        "y": rng.randn(B, 1).astype(np.float32),
    }


def _train(mode, quant=None, optimizer=None, nranks=2, steps=STEPS,
           amp=False):
    """Train the reference MLP `steps` steps under `mode`
    ("allreduce" | "sharded") on a dp=`nranks` in-process submesh; returns
    (losses, trainable params, main program, scope)."""
    import jax

    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 7
    scope = Scope()
    with fluid.program_guard(main, startup), fluid.scope_guard(scope), \
            unique_name.guard():
        x = fluid.data("x", [B, D])
        y = fluid.data("y", [B, 1])
        h = layers.fc(x, H, act="relu")
        pred = layers.fc(h, 1)
        loss = layers.mean(layers.square_error_cost(pred, y))
        opt = optimizer() if optimizer else fluid.optimizer.Adam(0.01)
        if amp:
            from paddle_tpu.contrib import mixed_precision as mp

            opt = mp.decorate(
                opt, init_loss_scaling=2.0**4,
                use_dynamic_loss_scaling=True, incr_every_n_steps=3,
                dest_dtype="bfloat16",
            )
        _, pg = opt.minimize(loss, startup)
        blk = main.global_block
        if mode == "allreduce":
            GradAllReduce(nranks).transpile(main, pg)
        else:
            ShardedWeightUpdate(nranks, quant=quant).transpile(
                main, startup, pg
            )
        # global-mean loss, both modes (the fleet transpile does the same)
        blk.append_op("scale", {"X": [loss.name]}, {"Out": [loss.name]},
                      {"scale": 1.0 / nranks, "bias": 0.0})
        blk.append_op("c_allreduce_sum", {"X": [loss.name]},
                      {"Out": [loss.name]}, {"axis_name": "dp"})
        shard_program(
            main, make_mesh({"dp": nranks}, jax.devices()[:nranks]),
            {"x": ("dp",), "y": ("dp",)},
        )
        exe = fluid.Executor()
        exe.run(startup, scope=scope)
        losses = []
        for i in range(steps):
            (lv,) = exe.run(main, feed=_feed(i), fetch_list=[loss],
                            scope=scope, return_numpy=False)
            losses.append(np.asarray(lv).reshape(-1)[0].copy())
        params = {
            v.name: np.asarray(scope.find_var(v.name))
            for v in main.all_parameters()
            if getattr(v, "trainable", False)
        }
    return np.array(losses), params, main, scope


# ---------------------------------------------------------------------------
# equivalence
# ---------------------------------------------------------------------------


def test_sharded_update_bitwise_matches_allreduce():
    """dp=2 fp32: reduce-scatter + shard update + all-gather must be
    BITWISE loss- and weight-equivalent to the plain allreduce transpile
    (sum order is a single commutative add at n=2)."""
    la, pa, _, _ = _train("allreduce")
    ls, ps, main, scope = _train("sharded")
    np.testing.assert_array_equal(la, ls)
    assert sorted(pa) == sorted(ps)
    for name in pa:
        np.testing.assert_array_equal(pa[name], ps[name])
    # optimizer state is genuinely sharded: moment shards exist, the full
    # moments are gone from both the program and the scope
    shard_vars = list(main._zero_shard_vars)
    assert any("moment" in n for n in shard_vars)
    for n in shard_vars:
        assert scope.find_var(n) is not None
        full_name = n[: -len(_SHARD_SUFFIX)]
        if "moment" in full_name:
            assert not main.global_block.has_var(full_name)
            assert scope.find_var(full_name) is None


def test_sharded_update_int8_collectives_within_tolerance():
    la, _, _, _ = _train("allreduce")
    lq, _, main, _ = _train("sharded", quant="int8")
    assert main._zero_quant == "int8"
    assert np.all(np.isfinite(lq))
    np.testing.assert_allclose(la, lq, rtol=5e-2, atol=5e-2)


def test_amp_sharded_matches_allreduce_and_scale_stays_uniform():
    """bf16 AMP: the grad shards feed check_finite_and_unscale /
    update_loss_scaling, FoundInfinite is any-reduced across dp, and the
    whole trajectory (loss + dynamic loss scale automaton) matches the
    allreduce AMP run bitwise."""
    la, _, main_a, scope_a = _train(
        "allreduce", optimizer=lambda: fluid.optimizer.Momentum(0.01, 0.9),
        amp=True,
    )
    ls, _, main_s, scope_s = _train(
        "sharded", optimizer=lambda: fluid.optimizer.Momentum(0.01, 0.9),
        amp=True,
    )
    np.testing.assert_array_equal(la, ls)
    assert any(
        op.type == "c_allreduce_any" for op in main_s.global_block.ops
    )

    def _scale(main, scope):
        name = next(
            v.name for v in main.list_vars() if "loss_scaling" in v.name
        )
        return float(np.asarray(scope.find_var(name)).reshape(-1)[0])

    assert _scale(main_a, scope_a) == _scale(main_s, scope_s)


# ---------------------------------------------------------------------------
# state sizing + observability
# ---------------------------------------------------------------------------


def test_optimizer_state_bytes_per_rank_is_one_over_n():
    observability.reset()
    _train("sharded", nranks=2)
    g = observability.snapshot()["gauges"]
    per_rank = g["collective.zero_optimizer_state_bytes_per_rank"]
    full = g["collective.zero_optimizer_state_bytes_full"]
    assert full > 0
    # moments shard exactly 1/2; [1] beta pows stay replicated; padding
    # adds a little — 1/N within 25% covers both
    assert per_rank <= full / 2 * 1.25, (per_rank, full)
    assert g["collective.zero_master_shard_bytes_per_rank"] > 0


def test_payload_byte_counters_by_kind_and_precision():
    observability.reset()
    _train("sharded", steps=1)
    c_fp = dict(observability.snapshot()["counters"])
    observability.reset()
    _train("sharded", quant="int8", steps=1)
    c_q = dict(observability.snapshot()["counters"])
    assert c_fp["collective.reduce_scatter"] > 0
    assert c_fp["collective.all_gather"] > 0
    assert c_fp["collective.bytes.reduce_scatter_fp32"] > 0
    assert c_fp["collective.bytes.all_gather_fp32"] > 0
    assert c_q["collective.bytes.reduce_scatter_int8"] > 0
    assert c_q["collective.bytes.all_gather_int8"] > 0
    # the headline claim needs a non-padding-dominated tensor (this tiny
    # model pads every grad up to quant_block): check the wire-byte
    # accounting the emitters record, on a 16k-element payload
    import jax.numpy as jnp

    from paddle_tpu.ops.collective import _record_zero

    class _Op:
        def __init__(self, quant):
            self._q = quant

        def attr(self, name, default=None):
            return {"quant": self._q, "quant_block": 256}.get(name, default)

    n = 64 * 256
    observability.reset()
    for quant in ("none", "int8"):
        _record_zero(None, "reduce_scatter", _Op(quant), n, jnp.float32,
                     "dp", 2)
    c = observability.snapshot()["counters"]
    fp = c["collective.bytes.reduce_scatter_fp32"]
    q8 = c["collective.bytes.reduce_scatter_int8"]
    assert q8 < 0.6 * fp, (q8, fp)


# ---------------------------------------------------------------------------
# fleet strategy knob
# ---------------------------------------------------------------------------


def _fleet_minimize(shard, quant=None):
    from paddle_tpu.fleet import collective as fc
    from paddle_tpu.fleet.role_maker import UserDefinedRoleMaker

    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 7
    scope = Scope()
    with fluid.program_guard(main, startup), fluid.scope_guard(scope), \
            unique_name.guard():
        x = fluid.data("x", [B, D])
        y = fluid.data("y", [B, 1])
        pred = layers.fc(layers.fc(x, H, act="relu"), 1)
        loss = layers.mean(layers.square_error_cost(pred, y))
        fleet = fc.Fleet()
        fleet.init(UserDefinedRoleMaker())
        strategy = fc.DistributedStrategy()
        strategy.shard_weight_update = shard
        strategy.collective_quant = quant
        opt = fleet.distributed_optimizer(
            fluid.optimizer.Adam(0.01), strategy
        )
        opt.minimize(loss)
        exe = fluid.Executor()
        exe.run(startup, scope=scope)
        losses = []
        for i in range(3):
            (lv,) = exe.run(main, feed=_feed(i), fetch_list=[loss],
                            scope=scope, return_numpy=False)
            losses.append(float(np.asarray(lv).reshape(-1)[0]))
    return losses, main


def test_fleet_shard_weight_update_knob():
    """strategy.shard_weight_update routes minimize through the ZeRO
    transpile on the full dp=8 virtual mesh and tracks the allreduce
    strategy's losses (dp=8 changes the reduction tree, so tolerance)."""
    base, main_b = _fleet_minimize(shard=False)
    shard, main_s = _fleet_minimize(shard=True)
    zero_kinds = ("zero_reduce_scatter", "zero_bucket_reduce_scatter")
    assert not any(
        op.type in zero_kinds for op in main_b.global_block.ops
    )
    # the strategy's default collective_bucket_mb routes the sharded path
    # through BUCKETED reduce-scatters (PR 14's overlap schedule); the
    # per-grad kind comes back with collective_bucket_mb=0
    assert any(
        op.type == "zero_bucket_reduce_scatter"
        for op in main_s.global_block.ops
    )
    assert not any(
        op.type == "c_allreduce_sum" and "grad" in str(op.inputs).lower()
        for op in main_s.global_block.ops
    )
    np.testing.assert_allclose(base, shard, rtol=1e-4, atol=1e-5)


def test_fleet_sharding_refuses_grad_clip_and_lamb():
    from paddle_tpu.fleet import collective as fc
    from paddle_tpu.fleet.role_maker import UserDefinedRoleMaker

    x = fluid.data("x", [B, D])
    y = fluid.data("y", [B, 1])
    loss = layers.mean(layers.square_error_cost(layers.fc(x, 1), y))
    fleet = fc.Fleet()
    fleet.init(UserDefinedRoleMaker())
    strategy = fc.DistributedStrategy()
    strategy.shard_weight_update = True
    from paddle_tpu.clip import GradientClipByNorm

    opt = fleet.distributed_optimizer(
        fluid.optimizer.SGD(0.1, grad_clip=GradientClipByNorm(1.0)),
        strategy,
    )
    with pytest.raises(NotImplementedError, match="grad_clip"):
        opt.minimize(loss)

    opt2 = fleet.distributed_optimizer(fluid.optimizer.Lamb(0.01), strategy)
    with pytest.raises(NotImplementedError, match="lamb"):
        opt2.minimize(loss)


# ---------------------------------------------------------------------------
# checkpointing sharded optimizer state
# ---------------------------------------------------------------------------


def test_sharded_state_checkpoint_roundtrip(tmp_path):
    """save_check_point(local_vars=<shard vars>) persists each rank's
    optimizer-state shards through the PR-4 per-rank machinery; load
    restores them bitwise (single-process mesh: shards are addressable)."""
    from paddle_tpu.fleet import collective as fc
    from paddle_tpu.fleet.role_maker import UserDefinedRoleMaker

    _, _, main, scope = _train("sharded", steps=2)
    shard_vars = list(main._zero_shard_vars)
    with fluid.scope_guard(scope):
        fleet = fc.Fleet()
        fleet.init(UserDefinedRoleMaker())
        exe = fluid.Executor()
        fleet.save_check_point(
            exe, str(tmp_path), fc.TrainStatus(0), main_program=main,
            local_vars=shard_vars,
        )
        before = {n: np.asarray(scope.find_var(n)).copy()
                  for n in shard_vars}
        import jax.numpy as jnp

        for n in shard_vars:  # poison, then prove load restores
            scope.set_var(n, jnp.zeros_like(scope.find_var(n)))
        status = fleet.load_check_point(exe, str(tmp_path),
                                        main_program=main)
        assert status.epoch_no == 0
        for n in shard_vars:
            np.testing.assert_array_equal(
                before[n], np.asarray(scope.find_var(n))
            )


def test_warm_start_rederives_master_shards(tmp_path):
    """Loading weights saved from a NON-sharded layout into a sharded
    program must refresh the @ZERO_SHARD masters — otherwise the first
    all-gather would revert the loaded params to their startup values."""
    import jax.numpy as jnp

    _, _, main, scope = _train("sharded", steps=2)
    with fluid.scope_guard(scope):
        # a plain (non-sharded-layout) params-only save
        pnames = [v.name for v in main.all_parameters()
                  if getattr(v, "trainable", False)]
        import paddle_tpu.io as pio

        saved = {n: np.asarray(scope.find_var(n)) for n in pnames}
        # a replicated-era checkpoint also carries FULL moments: they must
        # convert into the moment shards and not strand in the scope
        moment_shard = next(n for n in main._zero_shard_vars
                            if "moment" in n)
        full_moment = moment_shard[: -len(_SHARD_SUFFIX)]
        moment_vals = np.arange(
            np.asarray(scope.find_var(moment_shard)).size, dtype=np.float32
        )
        saved[full_moment] = moment_vals
        os.makedirs(tmp_path / "plain", exist_ok=True)
        np.savez(tmp_path / "plain" / "__params__.npz", **saved)
        pio._write_manifest(
            str(tmp_path / "plain" / pio.MANIFEST_NAME),
            str(tmp_path / "plain" / "__params__.npz"), saved,
        )
        # poison both the params and their master shards, then load
        for n in pnames:
            scope.set_var(n, jnp.zeros_like(scope.find_var(n)))
        for n in main._zero_shard_vars:
            scope.set_var(n, jnp.zeros_like(scope.find_var(n)))
        pio.load_persistables(fluid.Executor(), str(tmp_path / "plain"),
                              main)
        for n in pnames:
            shard = np.asarray(scope.find_var(n + _SHARD_SUFFIX))
            flat = saved[n].reshape(-1)
            np.testing.assert_array_equal(shard[: flat.size], flat)
        # the full moment converted into its shard and was then dropped
        # (its program var no longer exists — keeping it would strand
        # 2x-params of host memory)
        np.testing.assert_array_equal(
            np.asarray(scope.find_var(moment_shard)), moment_vals
        )
        assert scope.find_var(full_moment) is None
        c = observability.snapshot()["counters"]
        assert c.get("collective.zero_shards_rederived", 0) > len(pnames)


def test_transpiler_refuses_unknown_update_op_and_clip():
    """Direct-transpile guards (not just the fleet wrapper): a param
    whose update op the pass does not understand, or a clipped gradient,
    must refuse loudly — silence would leave rank-local gradients."""
    from paddle_tpu.clip import GradientClipByNorm

    x = fluid.data("x", [B, D])
    y = fluid.data("y", [B, 1])
    loss = layers.mean(layers.square_error_cost(layers.fc(x, 1), y))
    main = fluid.default_main_program()
    startup = fluid.default_startup_program()
    _, pg = fluid.optimizer.SGD(
        0.1, grad_clip=GradientClipByNorm(1.0)
    ).minimize(loss)
    with pytest.raises(NotImplementedError, match="clip"):
        ShardedWeightUpdate(2).transpile(main, startup, pg)

    with pytest.raises(ValueError, match="quantization"):
        ShardedWeightUpdate(2, quant="fp8")

    # a params_grads entry with no update op in the block at all
    main2, startup2 = fluid.Program(), fluid.Program()
    with fluid.program_guard(main2, startup2), unique_name.guard():
        x2 = fluid.data("x", [B, D])
        loss2 = layers.mean(layers.fc(x2, 1))
        _, pg2 = fluid.optimizer.SGD(0.1).minimize(loss2)
        for op in list(main2.global_block.ops):
            if op.type == "sgd":
                main2.global_block.ops.remove(op)
        with pytest.raises(NotImplementedError, match="no supported"):
            ShardedWeightUpdate(2).transpile(main2, startup2, pg2)


def test_slice_overlay_restores_rank_slice():
    """The cross-process shard path: a persisted dim-0 slice keyed
    '<name>@@off<start>' overlays onto the startup-initialized full value
    (what a real pod's per-rank load does for non-addressable state)."""
    import jax.numpy as jnp

    from paddle_tpu.fleet.collective import _SLICE_MARK, _overlay_slice
    from paddle_tpu.framework.scope import global_scope

    scope = global_scope()
    scope.set_var("zstate", jnp.zeros([8], jnp.float32))
    ok = _overlay_slice(
        scope, f"zstate{_SLICE_MARK}4", np.arange(4, dtype=np.float32)
    )
    assert ok
    np.testing.assert_array_equal(
        np.asarray(scope.find_var("zstate")),
        np.array([0, 0, 0, 0, 0, 1, 2, 3], np.float32),
    )
    assert not _overlay_slice(
        scope, f"missing{_SLICE_MARK}0", np.zeros(2, np.float32)
    )


# ---------------------------------------------------------------------------
# 2-process gloo golden equivalence (the MULTICHIP dryrun path)
# ---------------------------------------------------------------------------


def _free_port_pair():
    import random
    import socket

    for _ in range(128):
        base = random.randint(20000, 60000)
        try:
            with socket.socket() as a, socket.socket() as b:
                a.bind(("127.0.0.1", base))
                b.bind(("127.0.0.1", base + 1))
            return base
        except OSError:
            continue
    raise RuntimeError("no free port pair found")


def _launch_zero(mode, out_dir):
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.run(
        [
            sys.executable, "-m", "paddle_tpu.distributed.launch",
            "--nproc_per_node=2", f"--started_port={_free_port_pair()}",
            "--simulate_cpu",
            os.path.join(HERE, "dist_zero_worker.py"), mode, str(out_dir),
        ],
        env=env, cwd=REPO, capture_output=True, text=True, timeout=540,
    )
    if proc.returncode != 0 and (
        "Multiprocess computations aren't implemented" in proc.stdout
        or "Multiprocess computations aren't implemented" in proc.stderr
    ):
        # this jaxlib build has no cross-process CPU collectives (the same
        # limitation the tests/test_dist_spmd.py suite trips here); the
        # in-process dp=2 bitwise tests above cover the math, this leg
        # covers the real gloo exchange where the backend supports it
        pytest.skip("jaxlib CPU backend lacks multiprocess collectives")
    assert proc.returncode == 0, f"stdout:{proc.stdout}\nstderr:{proc.stderr}"


@pytest.mark.slow
def test_two_process_sharded_matches_allreduce_bitwise(tmp_path):
    """Golden equivalence on the real 2-process gloo path: the sharded
    weight update must reproduce the plain-allreduce loss trajectory and
    final weights BITWISE in fp32, and within tolerance with int8
    collectives; the collective.* counters must show the int8 payload
    shrink."""
    for mode in ("baseline", "sharded", "sharded_int8"):
        d = tmp_path / mode
        d.mkdir()
        _launch_zero(mode, d)

    def _result(mode, rank=0):
        r = json.load(open(tmp_path / mode / f"result_{rank}.json"))
        params = np.load(tmp_path / mode / f"params_{rank}.npz")
        return r, params

    base, pb = _result("baseline")
    shard, ps = _result("sharded")
    quant, pq = _result("sharded_int8")
    # both ranks agree with themselves (replicated fetches)
    for mode in ("baseline", "sharded", "sharded_int8"):
        r0, _ = _result(mode, 0)
        r1, _ = _result(mode, 1)
        np.testing.assert_array_equal(r0["losses"], r1["losses"])
    # fp32 sharded == allreduce, bitwise
    np.testing.assert_array_equal(base["losses"], shard["losses"])
    for name in pb.files:
        assert pb[name].tobytes() == ps[name].tobytes(), name
    # int8: tolerance-bounded, still finite and training
    np.testing.assert_allclose(
        base["losses"], quant["losses"], rtol=5e-2, atol=5e-2
    )
    # counters: sharded run exchanged reduce-scatter/all-gather payloads;
    # the int8 run's wire bytes are measurably smaller
    cs = shard["counters"]
    cq = quant["counters"]
    assert cs["collective.bytes.reduce_scatter_fp32"] > 0
    assert cs["collective.bytes.all_gather_fp32"] > 0
    assert cq["collective.bytes.reduce_scatter_int8"] > 0
    q_wire = (cq["collective.bytes.reduce_scatter_int8"]
              + cq["collective.bytes.all_gather_int8"])
    f_wire = (cs["collective.bytes.reduce_scatter_fp32"]
              + cs["collective.bytes.all_gather_fp32"])
    assert q_wire < 0.6 * f_wire, (q_wire, f_wire)
    # optimizer state really lives 1/N per rank
    gq = shard["gauges"]
    assert gq["collective.zero_optimizer_state_bytes_per_rank"] <= (
        gq["collective.zero_optimizer_state_bytes_full"] / 2 * 1.25
    )
