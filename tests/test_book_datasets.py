"""Completes the 9/9 reference book-test matrix (VERDICT r2 item 8) and
exercises the round-3 canned datasets (imdb / conll05 / wmt16 / movielens /
flowers — reference python/paddle/dataset/).

Book analogs already elsewhere: fit_a_line (test_framework),
recognize_digits / understand_sentiment / recommender_system / word2vec
(test_book_suite), machine_translation (test_book_seq2seq),
label_semantic_roles (test_crf). Added here: image_classification
(tests/book/test_image_classification.py) and rnn_encoder_decoder
(tests/book/test_rnn_encoder_decoder.py)."""

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import layers
from paddle_tpu.dataset import conll05, flowers, imdb, movielens, wmt16
from paddle_tpu.framework import unique_name


@pytest.fixture(autouse=True)
def fresh_programs():
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 42
    scope = fluid.framework.scope.Scope()
    with fluid.program_guard(main, startup), fluid.scope_guard(scope), \
            unique_name.guard():
        yield main, startup, scope


# -- book: image_classification (VGG-ish conv stack on cifar samples) ------


def test_image_classification_book():
    from paddle_tpu.dataset import cifar

    b = 16
    samples = []
    for img, lab in cifar.train10()():
        samples.append((img, lab))
        if len(samples) >= b:
            break
    imgs = np.stack([s[0] for s in samples]).reshape(b, 3, 32, 32)
    labs = np.array([s[1] for s in samples], np.int64).reshape(b, 1)

    img = fluid.data("img", [b, 3, 32, 32])
    label = fluid.data("label", [b, 1], "int64")
    x = layers.conv2d(img, 16, 3, padding=1, act="relu")
    x = layers.pool2d(x, pool_size=2, pool_stride=2, pool_type="max")
    x = layers.conv2d(x, 32, 3, padding=1, act="relu")
    x = layers.pool2d(x, pool_size=2, pool_stride=2, pool_type="max")
    logits = layers.fc(x, 10, num_flatten_dims=1)
    loss = layers.mean(layers.softmax_with_cross_entropy(logits, label))
    fluid.optimizer.Adam(2e-3).minimize(loss)
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    feeds = {"img": imgs.astype(np.float32), "label": labs}
    vals = [
        float(np.asarray(exe.run(feed=feeds, fetch_list=[loss])[0])
              .reshape(-1)[0])
        for _ in range(30)
    ]
    assert vals[-1] < vals[0] * 0.5, (vals[0], vals[-1])


# -- book: rnn_encoder_decoder (plain GRU enc-dec, no attention/beam) ------


def test_rnn_encoder_decoder_book():
    src_vocab = trg_vocab = 32
    b, slen = 8, 6
    reader = wmt16.train(src_vocab, trg_vocab)
    src = fluid.data("src", [b, slen], "int64")
    trg_in = fluid.data("trg_in", [b, slen], "int64")
    trg_next = fluid.data("trg_next", [b, slen], "int64")

    emb_s = layers.embedding(src, size=[src_vocab, 16])
    emb_t = layers.embedding(trg_in, size=[trg_vocab, 16])
    # encoder GRU over the source; decoder GRU initialized from the
    # encoder's final state (the book model's plain enc-dec shape)
    enc_out, enc_last = layers.gru(emb_s, 16)
    dec_out, _ = layers.gru(emb_t, 16, init_h=enc_last)
    logits = layers.fc(dec_out, trg_vocab, num_flatten_dims=2)
    loss = layers.mean(
        layers.softmax_with_cross_entropy(
            layers.reshape(logits, [b * slen, trg_vocab]),
            layers.reshape(trg_next, [b * slen, 1]),
        )
    )
    fluid.optimizer.Adam(5e-3).minimize(loss)
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())

    def pad(seq, ln):
        a = np.full(ln, wmt16.EOS, np.int64)
        a[:min(len(seq), ln)] = seq[:ln]
        return a

    batch = []
    for s_ids, t_ids, t_next in reader():
        batch.append((pad(s_ids, slen), pad(t_ids, slen),
                      pad(t_next, slen)))
        if len(batch) >= b:
            break
    feeds = {
        "src": np.stack([x[0] for x in batch]),
        "trg_in": np.stack([x[1] for x in batch]),
        "trg_next": np.stack([x[2] for x in batch]),
    }
    vals = [
        float(np.asarray(exe.run(feed=feeds, fetch_list=[loss])[0])
              .reshape(-1)[0])
        for _ in range(40)
    ]
    assert vals[-1] < vals[0] * 0.6, (vals[0], vals[-1])


# -- dataset contract smoke tests ------------------------------------------


def test_imdb_reader_contract():
    wd = imdb.word_dict()
    assert "<unk>" in wd
    labels = set()
    for n, (ids, lab) in enumerate(imdb.train(wd)()):
        assert all(0 <= i < len(wd) for i in ids)
        labels.add(lab)
    assert labels == {0, 1} and n > 100


def test_conll05_reader_contract():
    wd, vd, ld = conll05.get_dict()
    emb = conll05.get_embedding()
    assert emb.shape[0] == len(wd)
    for sample in conll05.test()():
        assert len(sample) == 9
        ln = len(sample[0])
        assert all(len(s) == ln for s in sample)
        assert sample[8].max() < len(ld)
        break


def test_wmt16_reader_contract():
    for s_ids, t_ids, t_next in wmt16.train(50, 50)():
        assert s_ids[0] == wmt16.BOS and s_ids[-1] == wmt16.EOS
        assert t_ids[0] == wmt16.BOS and t_next[-1] == wmt16.EOS
        assert len(t_ids) == len(t_next)
        break
    d = wmt16.get_dict("en", 50)
    assert len(d) == 50


def test_movielens_reader_contract():
    for uid, gender, age, job, mid, cats, title, rating in \
            movielens.train()():
        assert 1 <= uid <= movielens.max_user_id()
        assert 1 <= mid <= movielens.max_movie_id()
        assert 1.0 <= rating <= 5.0
        assert all(c < len(movielens.movie_categories()) for c in cats)
        break


def test_flowers_reader_contract():
    for img, lab in flowers.train()():
        assert img.shape == (3 * 224 * 224,)
        assert 0 <= lab < flowers.N_CLASSES
        assert img.min() >= 0.0 and img.max() <= 1.0
        break
