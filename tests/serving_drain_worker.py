"""Serving preemption worker: serve load until SIGTERM, drain, exit 75.

Driven by tests/test_serving.py::test_drain_worker_exits_75 and the ci.sh
serving smoke: the parent SIGTERMs this process mid-load and asserts

* exit code == PREEMPTION_EXIT_CODE (75, the PR-3 preemption contract),
* every admitted request RESOLVED — served, or typed expired/shed for the
  deadline/priority slice of the load (result.json: dropped == 0; the
  r15 fault-domain drain contract: expired work resolves with
  ``DeadlineExceededError`` instead of hanging the drain),
* the ``serving.drained`` counter fired exactly once.

Usage: python tests/serving_drain_worker.py OUT_DIR
"""

import json
import os
import sys

import numpy as np

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import paddle_tpu as fluid  # noqa: E402
from paddle_tpu import layers, observability  # noqa: E402
from paddle_tpu.framework.scope import Scope, scope_guard  # noqa: E402
from paddle_tpu.resilience.health import PREEMPTION_EXIT_CODE  # noqa: E402
from paddle_tpu.serving import (  # noqa: E402
    Server,
    freeze_program,
    install_preemption_handler,
)
from paddle_tpu.serving.router import (  # noqa: E402
    EndpointConfig,
    ServerDrainingError,
)


def main():
    out_dir = sys.argv[1]
    scope = Scope()
    main_prog, startup = fluid.Program(), fluid.Program()
    main_prog.random_seed = startup.random_seed = 5
    with fluid.program_guard(main_prog, startup):
        x = fluid.data("x", [-1, 16])
        lab = fluid.data("lab", [-1, 1], "int64")
        logits = layers.fc(layers.fc(x, 32, act="relu"), 4)
        prob = layers.softmax(logits)
        loss = layers.mean(layers.softmax_with_cross_entropy(logits, lab))
        fluid.optimizer.Adam(1e-3).minimize(loss, startup)
    exe = fluid.Executor()
    with scope_guard(scope):
        exe.run(startup, scope=scope)
    frozen = freeze_program(main_prog, [prob], feed_names=("x",))

    server = Server()
    server.add_endpoint(
        "clf", None, EndpointConfig(buckets=(1, 2, 4, 8), max_wait_ms=5.0),
        frozen=frozen, executor=exe, scope=scope,
    )
    server.warmup()
    install_preemption_handler(server, exit_on_drain=False)

    # signal readiness only after warmup: the parent's SIGTERM must land
    # during steady-state load, not during compiles
    with open(os.path.join(out_dir, "ready"), "w") as f:
        f.write("1")

    from paddle_tpu.errors import (  # noqa: E402
        DeadlineExceededError,
        RequestShedError,
    )
    from paddle_tpu.serving import BACKGROUND  # noqa: E402

    rng = np.random.RandomState(0)
    futures = []
    i = 0
    while not server.draining:
        try:
            # every 4th request carries a tight deadline + background
            # class: under SIGTERM some of these are still queued and
            # already expired — the drain must RESOLVE them typed, not
            # hang on them
            kwargs = (
                {"deadline_ms": 2.0, "priority": BACKGROUND}
                if i % 4 == 0 else {}
            )
            futures.append(
                server.submit(
                    "clf", {"x": rng.randn(16).astype(np.float32)},
                    **kwargs,
                )
            )
            i += 1
        except ServerDrainingError:
            break
        except Exception:
            # queue-full shedding under the tight submit loop: back off
            import time as _time

            _time.sleep(0.005)
            continue
    if not server.wait_drained(timeout=60):
        print("drain never completed", file=sys.stderr)
        sys.exit(1)

    served = expired = shed = dropped = 0
    for f in futures:
        try:
            f.result(timeout=5)
            served += 1
        except DeadlineExceededError:
            expired += 1
        except RequestShedError:
            shed += 1
        except Exception:
            dropped += 1
    counters = observability.get_counters()
    with open(os.path.join(out_dir, "result.json"), "w") as f:
        json.dump({
            "admitted": len(futures),
            "served": served,
            "expired": expired,
            "shed": shed,
            "dropped": dropped,
            "drained_counter": counters.get("serving.drained", 0),
            "requests_served": counters.get("serving.requests_served", 0),
            "expired_counter": counters.get("serving.expired", 0),
        }, f)
    sys.exit(PREEMPTION_EXIT_CODE)


if __name__ == "__main__":
    main()
